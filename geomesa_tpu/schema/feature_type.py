"""Feature schema model.

Capability parity with the reference's SimpleFeatureType handling
(geomesa-utils/.../geotools/SimpleFeatureTypes.scala, SchemaBuilder.scala;
SURVEY.md §2.2): a schema is named, typed attributes plus user-data. The
spec-string format is kept compatible with GeoMesa's
(``name:Type:opt=val,*geom:Point:srid=4326;userdata=...``) so CLI/ingest
recipes and tutorials carry over.

Each attribute maps to a fixed-width columnar dtype for device residency:
geometry -> x/y float64 (+ normalized int32 on device), Date -> epoch-ms int64
(+ (bin, offset) on device), String -> dictionary int32 codes, numerics ->
their width. This replaces the reference's Kryo lazy row format — "lazy
attribute access" becomes "touch only the columns the query needs".
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import numpy as np

# Attribute type registry: spec name -> (canonical name, numpy dtype or tag)
_TYPES = {
    "string": "string",
    "integer": "int32",
    "int": "int32",
    "long": "int64",
    "float": "float32",
    "double": "float64",
    "boolean": "bool",
    "date": "date",
    "timestamp": "date",
    "uuid": "string",
    "bytes": "string",
    "json": "json",
    "point": "point",
    "linestring": "linestring",
    "polygon": "polygon",
    "multipoint": "multipoint",
    "multilinestring": "multilinestring",
    "multipolygon": "multipolygon",
    "geometry": "geometry",
    "geometrycollection": "geometry",
}

GEOM_TYPES = {
    "point", "linestring", "polygon", "multipoint", "multilinestring",
    "multipolygon", "geometry",
}

NUMERIC_TYPES = {"int32", "int64", "float32", "float64"}


@dataclass
class AttributeSpec:
    name: str
    type: str  # canonical: string | int32 | int64 | float32 | float64 | bool | date | <geom>
    default_geom: bool = False
    options: Dict[str, str] = field(default_factory=dict)

    @property
    def is_geom(self) -> bool:
        return self.type in GEOM_TYPES

    @property
    def is_point(self) -> bool:
        return self.type == "point"

    @property
    def indexed(self) -> bool:
        return self.options.get("index", "").lower() in ("true", "full", "join")

    def spec(self) -> str:
        names = {v: k for k, v in {
            "String": "string", "Integer": "int32", "Long": "int64",
            "Float": "float32", "Double": "float64", "Boolean": "bool",
            "Date": "date", "Json": "json",
            "Point": "point", "LineString": "linestring",
            "Polygon": "polygon", "MultiPoint": "multipoint",
            "MultiLineString": "multilinestring", "MultiPolygon": "multipolygon",
            "Geometry": "geometry",
        }.items()}
        star = "*" if self.default_geom else ""
        opts = "".join(f":{k}={v}" for k, v in self.options.items())
        return f"{star}{self.name}:{names[self.type]}{opts}"


@dataclass
class FeatureType:
    """Schema: name + ordered attributes + user data."""

    name: str
    attributes: List[AttributeSpec]
    user_data: Dict[str, str] = field(default_factory=dict)

    def __post_init__(self):
        self._by_name = {a.name: a for a in self.attributes}
        if len(self._by_name) != len(self.attributes):
            raise ValueError(f"duplicate attribute names in schema {self.name!r}")

    # -- accessors --------------------------------------------------------
    def attr(self, name: str) -> AttributeSpec:
        a = self._by_name.get(name)
        if a is None:
            raise KeyError(
                f"no attribute {name!r} in schema {self.name!r} "
                f"(has: {', '.join(self._by_name)})"
            )
        return a

    def has(self, name: str) -> bool:
        return name in self._by_name

    @property
    def geom_field(self) -> Optional[str]:
        for a in self.attributes:
            if a.default_geom:
                return a.name
        for a in self.attributes:
            if a.is_geom:
                return a.name
        return None

    @property
    def dtg_field(self) -> Optional[str]:
        explicit = self.user_data.get("geomesa.index.dtg")
        if explicit:
            return explicit
        for a in self.attributes:
            if a.type == "date":
                return a.name
        return None

    @property
    def time_period(self) -> str:
        return self.user_data.get("geomesa.z3.interval", "week")

    @property
    def shards(self) -> Optional[int]:
        v = self.user_data.get("geomesa.z.splits")
        return int(v) if v else None

    # -- spec string ------------------------------------------------------
    def spec(self) -> str:
        s = ",".join(a.spec() for a in self.attributes)
        if self.user_data:
            s += ";" + ",".join(f"{k}='{v}'" for k, v in self.user_data.items())
        return s

    @staticmethod
    def from_spec(name: str, spec: str) -> "FeatureType":
        """Parse ``field:Type[:opt=val]*,...[;userdata='v',...]``."""
        spec = spec.strip()
        user_data: Dict[str, str] = {}
        if ";" in spec:
            spec, ud = spec.split(";", 1)
            for kv in _split_top(ud, ","):
                if not kv.strip():
                    continue
                k, v = kv.split("=", 1)
                user_data[k.strip()] = v.strip().strip("'\"")
        attrs = []
        for part in _split_top(spec, ","):
            part = part.strip()
            if not part:
                continue
            default_geom = part.startswith("*")
            if default_geom:
                part = part[1:]
            pieces = part.split(":")
            if len(pieces) < 2:
                raise ValueError(f"invalid attribute spec: {part!r}")
            aname, atype = pieces[0].strip(), pieces[1].strip().lower()
            if atype not in _TYPES:
                raise ValueError(f"unknown attribute type {pieces[1]!r} for {aname!r}")
            options = {}
            for opt in pieces[2:]:
                if "=" in opt:
                    k, v = opt.split("=", 1)
                    options[k.strip()] = v.strip()
            attrs.append(AttributeSpec(aname, _TYPES[atype], default_geom, options))
        ft = FeatureType(name, attrs, user_data)
        if ft.geom_field is None and any(a.is_geom for a in attrs):
            raise ValueError("geometry attribute exists but none marked default (*)")
        return ft

    def describe(self) -> str:
        lines = [f"Feature type: {self.name}"]
        for a in self.attributes:
            flags = []
            if a.default_geom:
                flags.append("default geometry")
            if a.name == self.dtg_field:
                flags.append("default date")
            if a.indexed:
                flags.append("indexed")
            suffix = f" ({', '.join(flags)})" if flags else ""
            lines.append(f"  {a.name}: {a.type}{suffix}")
        for k, v in self.user_data.items():
            lines.append(f"  [user-data] {k} = {v}")
        return "\n".join(lines)


def _split_top(s: str, sep: str) -> List[str]:
    """Split on sep outside quotes/brackets."""
    out, depth, cur, q = [], 0, [], None
    for ch in s:
        if q:
            if ch == q:
                q = None
            cur.append(ch)
        elif ch in "'\"":
            q = ch
            cur.append(ch)
        elif ch in "([":
            depth += 1
            cur.append(ch)
        elif ch in ")]":
            depth -= 1
            cur.append(ch)
        elif ch == sep and depth == 0:
            out.append("".join(cur))
            cur = []
        else:
            cur.append(ch)
    out.append("".join(cur))
    return out
