"""Flight client for the sidecar: a GeoDataset-shaped remote API.

The thin-adapter role of the reference's client-side coprocessor wrapper
(GeoMesaCoprocessor.scala:29 — serialize options, stream results, merge):
callers get the same operations a local GeoDataset offers, executed in the
sidecar process, with Arrow as the interchange.
"""

from __future__ import annotations

import json
import re
import threading
import uuid
from typing import Any, Dict, List, Optional, Sequence

import numpy as np
import pyarrow as pa
import pyarrow.flight as fl

from geomesa_tpu import config, resilience, tracing
from geomesa_tpu.resilience import QueryTimeoutError
from geomesa_tpu.stats import sketches as sk

#: Flight header carrying the client's trace id (lower-case: gRPC metadata
#: keys are case-normalized). The server middleware reads it and opens its
#: server-side root span with the SAME id, so client and server spans (and
#: both audit events) join on one trace (docs/OBSERVABILITY.md).
TRACE_HEADER = "x-geomesa-trace-id"

#: Serving headers (docs/SERVING.md): the caller's fair-share identity
#: (``geomesa.user``) and the remaining deadline budget in ms — the
#: server's admission queue sheds typed-and-early when the budget can't
#: be met, instead of burning device time on a guaranteed wire timeout.
USER_HEADER = "x-geomesa-user"
DEADLINE_HEADER = "x-geomesa-deadline-ms"

#: Fleet headers (docs/RESILIENCE.md §7). Responses from a fleet replica
#: carry its identity and per-schema fleet-epoch map (the gossip channel);
#: requests from a router carry the epochs the replica must be AT before
#: serving (``x-geomesa-fleet-epochs``) and, on mutations, the epoch the
#: write ESTABLISHES (``x-geomesa-fleet-stamp``).
REPLICA_HEADER = "x-geomesa-replica-id"
FLEET_EPOCHS_HEADER = "x-geomesa-fleet-epochs"
FLEET_STAMP_HEADER = "x-geomesa-fleet-stamp"

#: Cross-replica trace stitching (docs/OBSERVABILITY.md §9, PROTOCOL
#: v1.7): each traced RPC mints a per-call span token, records it on the
#: local ``sidecar.call`` span (``span_token`` attribute) and sends it in
#: this header; the server's root span echoes it as ``parent_span``, so
#: the fleet stitcher can graft the replica's subtree under the exact
#: client span that made the call.
PARENT_SPAN_HEADER = "x-geomesa-parent-span"


class _FleetHeaderMiddleware(fl.ClientMiddleware):
    """Captures the replica's response headers (id + epoch gossip) into
    the owning client — docs/RESILIENCE.md §7 epoch propagation."""

    def __init__(self, sink: "GeoFlightClient"):
        self._sink = sink

    def received_headers(self, headers):
        try:
            vals = headers.get(REPLICA_HEADER) or ()
            rid = vals[0] if vals else None
            if isinstance(rid, bytes):
                rid = rid.decode(errors="replace")
            evals = headers.get(FLEET_EPOCHS_HEADER) or ()
            epochs = None
            if evals:
                raw = evals[0]
                if isinstance(raw, bytes):
                    raw = raw.decode(errors="replace")
                epochs = {str(k): int(v)
                          for k, v in json.loads(raw).items()}
        except Exception:
            return  # malformed gossip must never fail a healthy call
        self._sink._note_fleet_headers(rid, epochs)


class _FleetHeaderFactory(fl.ClientMiddlewareFactory):
    def __init__(self, sink: "GeoFlightClient"):
        self._sink = sink

    def start_call(self, info):
        return _FleetHeaderMiddleware(self._sink)

#: structured error-code prefix on Flight error messages (PROTOCOL.md §7.1):
#: "[GM-ARG] unknown schema 'x'" — lets clients classify retryable vs fatal
#: without string-matching free-form text.
_CODE_RE = re.compile(r"\[(GM-[A-Z]+)\]")

#: codes a client may retry (transient server states); everything else is
#: fatal — the same request would fail the same way. GM-OVERLOADED is
#: admission-queue backpressure: the server is healthy but saturated, and
#: the retry policy's backoff is exactly the right response.
#: GM-DRAINING is a drained/respawned serving slot (docs/RESILIENCE.md
#: §6): retryable for unary requests — a respawned slot serves the
#: retry — while streams re-open at the caller's layer.
RETRYABLE_CODES = {"GM-INTERNAL", "GM-UNAVAILABLE", "GM-OVERLOADED",
                   "GM-DRAINING"}

#: codes that ARE a server response (the callee is healthy): they close
#: the breaker rather than charging it — a user's bad/late/shed query
#: must never fence the sidecar off for everyone.
_RESPONSE_CODES = ("GM-ARG", "GM-TIMEOUT", "GM-SHED", "GM-OVERLOADED",
                   "GM-DRAINING")


def error_code(exc: BaseException) -> Optional[str]:
    """The ``GM-*`` code carried by a Flight error, or None."""
    m = _CODE_RE.search(str(exc))
    return m.group(1) if m else None


def is_retryable(exc: BaseException) -> bool:
    """Transport-level classification for the retry policy: coded errors
    retry iff their code is in RETRYABLE_CODES; uncoded transport failures
    (connection refused/reset, deadline on the channel) are retryable;
    coded-fatal and client-side errors are not."""
    code = error_code(exc)
    if code is not None:
        return code in RETRYABLE_CODES
    if isinstance(exc, (fl.FlightUnavailableError, fl.FlightInternalError)):
        return True
    if isinstance(exc, fl.FlightTimedOutError):
        # per-call timeout: the server may just be slow — one retry is
        # worth it, and a live query deadline bounds the total spend
        return True
    return False


def _dense_grid(t: pa.Table, shape, dtype) -> np.ndarray:
    """Sparse (row, col, weight) wire encoding -> dense grid."""
    grid = np.zeros(shape, dtype)
    if t.num_rows:
        grid[t["row"].to_numpy(), t["col"].to_numpy()] = t["weight"].to_numpy()
    return grid


class GeoFlightClient:
    """Flight client with the full client-side resilience stack: per-call
    timeouts (``geomesa.sidecar.timeout``, tightened to any live query
    deadline), seeded exponential-backoff retries of retryable failures
    with channel reconnect between attempts, and a per-location circuit
    breaker shared across client instances (a dead sidecar fails fast
    instead of paying the timeout on every call)."""

    #: whether the most recent :meth:`count` on this client was served as
    #: a speculative (coarse-estimate) answer under server overload
    last_count_speculative: bool = False

    def __init__(self, location: str, retry_seed: Optional[int] = None,
                 header_provider=None, **kw):
        self.location = location
        #: extra-request-header hook (docs/RESILIENCE.md §7): a zero-arg
        #: callable returning ``[(name-bytes, value-bytes), ...]`` merged
        #: into every call's headers — the fleet router injects its
        #: per-schema epoch requirements and write stamps through it
        self.header_provider = header_provider
        #: last replica identity / per-schema fleet-epoch map gossiped
        #: back by the server (None until a fleet replica answers)
        self.last_replica_id: Optional[str] = None
        self.last_epochs: Optional[Dict[str, int]] = None
        kw = dict(kw)
        kw["middleware"] = list(kw.get("middleware") or ()) + [
            _FleetHeaderFactory(self)
        ]
        self._kw = kw
        self._client = fl.FlightClient(location, **kw)
        self._lock = threading.Lock()
        self._retry = resilience.RetryPolicy.from_config(seed=retry_seed)
        self._breaker = resilience.breaker(f"sidecar:{location}")

    def _note_fleet_headers(self, rid: Optional[str],
                            epochs: Optional[Dict[str, int]]) -> None:
        if rid is None and epochs is None:
            return
        with self._lock:
            if rid is not None:
                self.last_replica_id = rid
            if epochs is not None:
                self.last_epochs = epochs

    def close(self):
        self._client.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    # -- resilience plumbing -----------------------------------------------
    @staticmethod
    def _effective_timeout_s() -> Optional[float]:
        """Per-call timeout: the configured sidecar timeout, tightened to
        the remaining query deadline when one is active (deadline
        propagation — a 2 s query budget never waits 30 s on the wire)."""
        ms = config.SIDECAR_TIMEOUT.to_duration_ms()
        t = ms / 1000.0 if ms is not None else None
        rem = resilience.current_deadline().remaining_s()
        if rem is not None:
            rem = max(rem, 0.001)  # expired: fail via a tiny wire timeout
            t = rem if t is None else min(t, rem)
        return t

    def _call_options(self) -> Optional[fl.FlightCallOptions]:
        kw = {}
        headers = []
        t = self._effective_timeout_s()
        if t is not None:
            kw["timeout"] = t
            # deadline propagation into the server's admission queue: the
            # server sheds typed-and-early when this budget can't be met
            headers.append(
                (DEADLINE_HEADER.encode(), str(int(t * 1000)).encode())
            )
        tid = tracing.current_trace_id()
        if tid is not None:
            headers.append((TRACE_HEADER.encode(), tid.encode()))
            span = tracing.current_span()
            if span is not None and span is not tracing.NOOP:
                # mint the per-call stitch token (one uuid per attempt:
                # the surviving attempt's token is the one left on the
                # span, matching the server tree that actually answered)
                token = uuid.uuid4().hex[:16]
                span.set(span_token=token)
                headers.append((PARENT_SPAN_HEADER.encode(),
                                token.encode()))
        user = config.USER.get()
        if user:
            headers.append((USER_HEADER.encode(), user.encode()))
        if self.header_provider is not None:
            try:
                headers.extend(self.header_provider())
            except Exception:
                pass  # a torn provider must never fail a healthy call
        if headers:
            kw["headers"] = headers
        return fl.FlightCallOptions(**kw) if kw else None

    def _reconnect(self):
        """Swap in a fresh channel (the old one may be a stale connection
        to a restarted server). The old channel is abandoned, NOT closed:
        another thread may have an in-flight RPC on it, and closing would
        abort a healthy call (and charge the shared breaker for it) — GC
        reclaims the dropped channel."""
        with self._lock:
            self._client = fl.FlightClient(self.location, **self._kw)

    def _invoke(self, fault_site: str, fn, retry: bool = True):
        """Breaker + retry + reconnect envelope shared by every RPC.
        ``retry=False`` (writes) still gets the breaker and timeout but
        never re-sends: a do_put whose ack was lost may have committed."""
        if resilience.current_deadline().expired:
            # the query budget is gone before dialing: raise the typed
            # timeout directly — a client-side deadline says nothing about
            # the sidecar's health, so the breaker must not be charged
            raise QueryTimeoutError(
                "query deadline expired before sidecar call"
            )
        self._breaker.allow()

        def attempt():
            resilience.fault_point(fault_site)
            return fn()

        def run():
            if not retry:
                return attempt()
            return self._retry.call(
                attempt,
                retryable=is_retryable,
                deadline=resilience.current_deadline(),
                # reconnect only on UNCODED transport failures — a coded
                # response (GM-OVERLOADED backpressure especially) came
                # from a healthy channel, and redialing per attempt would
                # flood a saturated server with handshakes at peak load
                on_retry=lambda i, e: (
                    None if error_code(e) in _RESPONSE_CODES
                    else self._reconnect()
                ),
            )

        # span the RPC: a child when a query trace is already open, else a
        # fresh root (a bare client call is its own trace) — either way the
        # trace id is on the context when _call_options builds the headers
        cm = tracing.span("sidecar.call", site=fault_site)
        if cm is tracing.NOOP:
            cm = tracing.start("sidecar.call", site=fault_site)
        try:
            with cm:
                out = run()
        except Exception as e:
            code = error_code(e)
            if code in _RESPONSE_CODES:
                # a coded domain error / timeout / shed / backpressure IS
                # a server response: the callee is healthy — only
                # transport failures and GM-INTERNAL count toward opening
                # the circuit (bad user queries must never fence the
                # sidecar off for everyone)
                self._breaker.record_success()
            else:
                self._breaker.record_failure()
            if code == "GM-SHED":
                from geomesa_tpu.resilience import DeadlineShedError

                raise DeadlineShedError(str(e)) from e
            if code == "GM-TIMEOUT":
                raise QueryTimeoutError(str(e)) from e
            if code == "GM-DRAINING":
                from geomesa_tpu.resilience import DeviceDrainError

                raise DeviceDrainError(str(e)) from e
            raise
        self._breaker.record_success()
        return out

    #: actions that mutate server state: like do_put, never retried — a
    #: lost ack may mean the mutation committed, and a blind re-send would
    #: surface a bogus "already exists"/"unknown schema" for a call that
    #: actually succeeded
    _MUTATING_ACTIONS = frozenset({"create-schema", "delete-schema"})

    # -- actions -----------------------------------------------------------
    def _action(self, kind: str, body: Optional[Dict] = None) -> Dict:
        action = fl.Action(kind, json.dumps(body or {}).encode())

        def go():
            opts = self._call_options()
            results = (
                list(self._client.do_action(action, opts))
                if opts is not None else list(self._client.do_action(action))
            )
            return (
                json.loads(results[0].body.to_pybytes().decode())
                if results else {}
            )

        return self._invoke(
            "sidecar.do_action", go,
            retry=kind not in self._MUTATING_ACTIONS,
        )

    def version(self) -> Dict:
        """Server library + protocol version."""
        return self._action("version")

    def check_version(self) -> Dict:
        """Handshake (GeoMesaDataStore distributed-version check analog):
        raises if the server speaks an incompatible protocol."""
        from geomesa_tpu.sidecar.service import PROTOCOL_VERSION

        try:
            info = self.version()
        except fl.FlightServerError as e:
            if "unknown action" in str(e):
                # pre-handshake server: the exact case this check exists for
                raise RuntimeError(
                    "sidecar protocol mismatch: server predates the version "
                    f"handshake, client={PROTOCOL_VERSION}; upgrade the server"
                ) from None
            raise
        server = int(info.get("protocol", -1))
        if server != PROTOCOL_VERSION:
            raise RuntimeError(
                f"sidecar protocol mismatch: server={server} "
                f"client={PROTOCOL_VERSION}; upgrade the older side"
            )
        return info

    def create_schema(self, name: str, spec: str) -> str:
        return self._action("create-schema", {"name": name, "spec": spec})["created"]

    def delete_schema(self, name: str):
        self._action("delete-schema", {"name": name})

    def list_schemas(self) -> List[str]:
        return self._action("list-schemas")["schemas"]

    def describe(self, name: str) -> str:
        return self._action("describe", {"name": name})["describe"]

    def schema_spec(self, name: str) -> str:
        """The schema's machine-readable spec string (the fleet router
        rebuilds the FeatureType locally from it for cell-affinity
        decomposition — docs/RESILIENCE.md §7)."""
        return self._action("describe", {"name": name})["spec"]

    def replica_status(self) -> Dict:
        """Fleet-replica status: identity, draining flag, per-schema
        fleet epochs, and the serving snapshot (docs/RESILIENCE.md §7)."""
        return self._action("replica-status")

    def drain(self, reason: Optional[str] = None) -> Dict:
        """Put the replica into DRAINING: every subsequent non-admin
        request answers ``[GM-DRAINING]`` (retryable — routers fail the
        traffic over to other ring owners) until :meth:`undrain`."""
        body: Dict = {}
        if reason:
            body["reason"] = str(reason)
        return self._action("drain", body)

    def undrain(self) -> Dict:
        """Re-admit a drained replica to serving."""
        return self._action("undrain")

    def cache_export(self, name: str,
                     limit: Optional[int] = None) -> Dict:
        """Warm-handoff source (docs/RESILIENCE.md §7): the replica's
        hottest current-epoch cache entries for ``name`` (wire-encoded)
        plus the data guard ``cache_import`` verifies. Served even while
        the replica is DRAINING — the handoff runs mid-drain."""
        body: Dict = {"name": name}
        if limit is not None:
            body["limit"] = int(limit)
        return self._action("cache-export", body)

    def cache_import(self, name: str, guard: Dict, entries) -> Dict:
        """Warm-handoff sink: admit ``cache_export`` entries under the
        replica's live epoch iff ``guard`` (row count + spec) matches its
        store — a drained replica's warm cells move to the new ring owner
        instead of dying with the process."""
        return self._action("cache-import", {
            "name": name, "guard": guard, "entries": entries,
        })

    def subscribe(self, name: str, aggregate: str, bbox=None,
                  region: Optional[str] = None, width: int = 256,
                  height: int = 256, levels: Optional[int] = None,
                  stat_spec: Optional[str] = None,
                  sub_id: Optional[str] = None) -> str:
        """Register a standing viewport on the sidecar (docs/STANDING.md;
        PROTOCOL §5 v1.6): every applied ingest batch then updates the
        result incrementally. Returns the subscription id."""
        body: Dict = {"name": name, "aggregate": aggregate,
                      "width": int(width), "height": int(height)}
        if bbox is not None:
            body["bbox"] = [float(v) for v in bbox]
        if region is not None:
            body["region"] = region
        if levels is not None:
            body["levels"] = int(levels)
        if stat_spec is not None:
            body["stat_spec"] = stat_spec
        if sub_id is not None:
            body["sub_id"] = sub_id
        return self._action("subscribe", body)["sub_id"]

    def unsubscribe(self, sub_id: str) -> bool:
        return bool(self._action("unsubscribe",
                                 {"sub_id": sub_id})["removed"])

    def subscribe_poll(self, sub_id: str, cursor: int = 0) -> Dict:
        """Current standing result (wire-encoded) plus every update
        record past ``cursor``. ``[GM-SUB-UNKNOWN]`` means this replica
        does not own the subscription (it migrated) — fleet routers fail
        over to the next ring owner."""
        return self._action("subscribe-poll",
                            {"sub_id": sub_id, "cursor": int(cursor)})

    def subscribe_stats(self) -> Dict:
        """Standing-query groups + subscriber counts (operator view)."""
        return self._action("subscribe-stats")["subscriptions"]

    def subscribe_export(self, name: Optional[str] = None,
                         keys: Optional[Sequence[str]] = None,
                         remove: bool = False) -> Dict:
        """Warm-handoff source for standing results (docs/STANDING.md):
        wire-encoded groups + per-schema guards. Served mid-drain, like
        ``cache_export``. ``remove=True`` drops the exported groups from
        the source (the leaver's half of a migration)."""
        body: Dict = {}
        if name is not None:
            body["name"] = name
        if keys is not None:
            body["keys"] = list(keys)
        if remove:
            body["remove"] = True
        return self._action("subscribe-export", body)

    def subscribe_import(self, payload: Dict) -> Dict:
        """Warm-handoff sink: adopt exported standing groups verbatim iff
        the per-schema guard matches, else re-scan locally (``resync``)."""
        return self._action("subscribe-import", payload)

    def explain(self, name: str, ecql: str = "INCLUDE") -> str:
        return self._action("explain", {"name": name, "ecql": ecql})["explain"]

    def count(self, name: str, ecql: str = "INCLUDE", exact: bool = True,
              auths: Optional[Sequence[str]] = None,
              region: Optional[str] = None,
              speculative_ok: bool = False) -> int:
        """Feature count. ``speculative_ok=True`` opts into the typed
        DEGRADED answer under server overload (docs/SERVING.md): a count
        the server would deadline-shed returns the planner's coarse
        estimate instead of failing ``[GM-SHED]``;
        :attr:`last_count_speculative` reports whether the most recent
        count on this client was served speculatively."""
        body = {"name": name, "ecql": ecql, "exact": exact}
        if auths is not None:
            body["auths"] = list(auths)
        if region is not None:
            # WKT polygon; the server folds it into the ecql BEFORE fusion
            # keys are built (docs/CACHE.md polygon regions)
            body["region"] = region
        if speculative_ok:
            body["speculative_ok"] = True
        out = self._action("count", body)
        self.last_count_speculative = bool(out.get("speculative", False))
        return out["count"]

    def _join_body(self, left: str, right: str, predicate: str,
                   distance, dx, dy, ecql: str, right_ecql: str,
                   level, auths) -> Dict:
        body: Dict[str, Any] = {
            "left": left, "right": right, "predicate": predicate,
            "ecql": ecql, "right_ecql": right_ecql,
        }
        if distance is not None:
            body["distance"] = float(distance)
        if dx is not None:
            body["dx"] = float(dx)
        if dy is not None:
            body["dy"] = float(dy)
        if level is not None:
            body["level"] = int(level)
        if auths is not None:
            body["auths"] = list(auths)
        return body

    def join_count(self, left: str, right: str, *, predicate: str,
                   distance=None, dx=None, dy=None,
                   ecql: str = "INCLUDE", right_ecql: str = "INCLUDE",
                   level: Optional[int] = None,
                   auths: Optional[Sequence[str]] = None) -> int:
        """Spatial-join matched-pair count (docs/JOIN.md; PROTOCOL
        "join-count"): ``predicate`` is ``"bbox"`` (half-widths
        ``dx``/``dy``) or ``"dwithin"`` (planar degree ``distance``).
        ``auths`` filter BOTH sides' scans. Identical concurrent
        requests fuse into one co-partitioned join on the server."""
        out = self._action("join-count", self._join_body(
            left, right, predicate, distance, dx, dy, ecql, right_ecql,
            level, auths,
        ))
        return out["count"]

    def join_explain(self, left: str, right: str, *, predicate: str,
                     distance=None, dx=None, dy=None,
                     ecql: str = "INCLUDE", right_ecql: str = "INCLUDE",
                     level: Optional[int] = None,
                     auths: Optional[Sequence[str]] = None,
                     analyze: bool = False) -> str:
        """Spatial-join plan explain: the co-partition pruning account
        (cells, candidate pairs vs naive N*M, strip fraction)."""
        body = self._join_body(left, right, predicate, distance, dx, dy,
                               ecql, right_ecql, level, auths)
        if analyze:
            body["analyze"] = True
        return self._action("join-explain", body)["explain"]

    def audit(self, n: int = 100) -> List[Dict]:
        return self._action("audit", {"n": n})["events"]

    def metrics(self) -> Dict:
        return self._action("metrics")["metrics"]

    def metrics_export(self) -> Dict:
        """Federation source (docs/OBSERVABILITY.md §9, PROTOCOL v1.7):
        the replica's STRUCTURED metrics snapshot (counters, gauges,
        histogram buckets), heat-table rows, and local health facts —
        the payload ``fleet/obs.py`` merges fleet-wide. Admin: served
        mid-drain."""
        return self._action("metrics-export")

    def trace_fetch(self, trace_id: str) -> Dict:
        """The finished trace(s) behind ``trace_id`` from the replica's
        retention ring (PROTOCOL v1.7): ``{"replica", "trace", "traces"}``
        where ``traces`` holds EVERY retained root for the id (a replica
        that served several scatter groups of one query has several) and
        ``trace`` is the newest, None when unknown/evicted. The fleet
        stitcher grafts each subtree under the router span whose
        ``span_token`` matches the subtree root's ``parent_span``."""
        return self._action("trace-fetch", {"trace_id": str(trace_id)})

    def device_health(self) -> Dict:
        """Per-device health map (ok/cordoned/broken, reassignment
        counts, last failure — docs/RESILIENCE.md §6)."""
        return self._action("device-health")["devices"]

    def cordon_device(self, device: int,
                      reason: Optional[str] = None) -> Dict:
        """Remove a device from the server's scheduling (sharded-scan
        fan-out + pool slot pinning) without a restart."""
        body: Dict = {"device": int(device)}
        if reason:
            body["reason"] = str(reason)
        return self._action("cordon-device", body)

    def uncordon_device(self, device: int) -> Dict:
        """Re-admit an explicitly cordoned device."""
        return self._action("uncordon-device", {"device": int(device)})

    def serving_stats(self) -> Dict:
        """Server-side admission queue snapshot + per-user serving rollups
        (docs/SERVING.md)."""
        return self._action("serving-stats")

    # -- reads -------------------------------------------------------------
    def _get(self, opts: Dict) -> pa.Table:
        ticket = fl.Ticket(json.dumps(opts).encode())

        def go():
            copts = self._call_options()
            reader = (
                self._client.do_get(ticket, copts)
                if copts is not None else self._client.do_get(ticket)
            )
            return reader.read_all()

        return self._invoke("sidecar.do_get", go)

    def query(self, name: str, ecql: str = "INCLUDE", properties=None,
              max_features=None, sampling=None, sample_by=None,
              auths: Optional[Sequence[str]] = None) -> pa.Table:
        opts = {"op": "query", "schema": name, "ecql": ecql}
        if properties is not None:
            opts["properties"] = list(properties)
        if max_features is not None:
            opts["max_features"] = max_features
        if sampling is not None:
            opts["sampling"] = sampling
        if sample_by is not None:
            opts["sample_by"] = sample_by
        if auths is not None:
            opts["auths"] = list(auths)
        return self._get(opts)

    def density(self, name: str, ecql: str = "INCLUDE", bbox=None,
                width: int = 256, height: int = 256,
                weight: Optional[str] = None,
                auths: Optional[Sequence[str]] = None,
                region: Optional[str] = None) -> np.ndarray:
        opts = {
            "op": "density", "schema": name, "ecql": ecql,
            "width": width, "height": height,
        }
        if bbox is not None:
            opts["bbox"] = list(bbox)
        if weight is not None:
            opts["weight"] = weight
        if auths is not None:
            opts["auths"] = list(auths)
        if region is not None:
            opts["region"] = region  # WKT; folded server-side (CACHE.md)
        return _dense_grid(self._get(opts), (height, width), np.float32)

    def density_curve(self, name: str, ecql: str = "INCLUDE", level: int = 9,
                      bbox=None, weight: Optional[str] = None,
                      auths: Optional[Sequence[str]] = None):
        """Morton-block-aligned density (tile pyramids): returns
        ``(grid float64, snapped_bbox)`` — see PROTOCOL §3."""
        opts = {"op": "density_curve", "schema": name, "ecql": ecql,
                "level": level}
        if bbox is not None:
            opts["bbox"] = list(bbox)
        if weight is not None:
            opts["weight"] = weight
        if auths is not None:
            opts["auths"] = list(auths)
        t = self._get(opts)
        snapped = tuple(json.loads(
            t.schema.metadata[b"geomesa:snapped_bbox"].decode()
        ))
        n_blocks = 1 << level
        nx = round((snapped[2] - snapped[0]) / 360.0 * n_blocks)
        ny = round((snapped[3] - snapped[1]) / 180.0 * n_blocks)
        return _dense_grid(t, (ny, nx), np.float64), snapped

    def stats(self, name: str, stat_spec: str, ecql: str = "INCLUDE",
              auths: Optional[Sequence[str]] = None,
              region: Optional[str] = None) -> sk.Stat:
        opts = {"op": "stats", "schema": name, "ecql": ecql, "stat": stat_spec}
        if auths is not None:
            opts["auths"] = list(auths)
        if region is not None:
            opts["region"] = region  # WKT; folded server-side (CACHE.md)
        t = self._get(opts)
        return sk.Stat.from_json(t["value"][0].as_py())

    def export_bin(self, name: str, ecql: str = "INCLUDE",
                   track: Optional[str] = None,
                   label: Optional[str] = None) -> bytes:
        opts = {"op": "bin", "schema": name, "ecql": ecql}
        if track:
            opts["track"] = track
        if label:
            opts["label"] = label
        t = self._get(opts)
        return t["bin"][0].as_py() if t.num_rows else b""

    # -- writes ------------------------------------------------------------
    def insert_arrow(self, name: str, table: "pa.Table | pa.RecordBatch"):
        if isinstance(table, pa.RecordBatch):
            table = pa.Table.from_batches([table])
        descriptor = fl.FlightDescriptor.for_command(
            json.dumps({"schema": name}).encode()
        )

        def go():
            copts = self._call_options()
            writer, _ = (
                self._client.do_put(descriptor, table.schema, copts)
                if copts is not None
                else self._client.do_put(descriptor, table.schema)
            )
            writer.write_table(table)
            writer.close()

        # retry=False: an upload whose ack was lost may have committed —
        # re-sending would double-insert (the server ingest is transactional
        # per stream, but not idempotent across streams)
        self._invoke("sidecar.do_put", go, retry=False)
