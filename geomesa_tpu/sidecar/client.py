"""Flight client for the sidecar: a GeoDataset-shaped remote API.

The thin-adapter role of the reference's client-side coprocessor wrapper
(GeoMesaCoprocessor.scala:29 — serialize options, stream results, merge):
callers get the same operations a local GeoDataset offers, executed in the
sidecar process, with Arrow as the interchange.
"""

from __future__ import annotations

import json
from typing import Dict, List, Optional, Sequence

import numpy as np
import pyarrow as pa
import pyarrow.flight as fl

from geomesa_tpu.stats import sketches as sk


def _dense_grid(t: pa.Table, shape, dtype) -> np.ndarray:
    """Sparse (row, col, weight) wire encoding -> dense grid."""
    grid = np.zeros(shape, dtype)
    if t.num_rows:
        grid[t["row"].to_numpy(), t["col"].to_numpy()] = t["weight"].to_numpy()
    return grid


class GeoFlightClient:
    def __init__(self, location: str, **kw):
        self._client = fl.FlightClient(location, **kw)

    def close(self):
        self._client.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    # -- actions -----------------------------------------------------------
    def _action(self, kind: str, body: Optional[Dict] = None) -> Dict:
        action = fl.Action(kind, json.dumps(body or {}).encode())
        results = list(self._client.do_action(action))
        return json.loads(results[0].body.to_pybytes().decode()) if results else {}

    def version(self) -> Dict:
        """Server library + protocol version."""
        return self._action("version")

    def check_version(self) -> Dict:
        """Handshake (GeoMesaDataStore distributed-version check analog):
        raises if the server speaks an incompatible protocol."""
        from geomesa_tpu.sidecar.service import PROTOCOL_VERSION

        try:
            info = self.version()
        except fl.FlightServerError as e:
            if "unknown action" in str(e):
                # pre-handshake server: the exact case this check exists for
                raise RuntimeError(
                    "sidecar protocol mismatch: server predates the version "
                    f"handshake, client={PROTOCOL_VERSION}; upgrade the server"
                ) from None
            raise
        server = int(info.get("protocol", -1))
        if server != PROTOCOL_VERSION:
            raise RuntimeError(
                f"sidecar protocol mismatch: server={server} "
                f"client={PROTOCOL_VERSION}; upgrade the older side"
            )
        return info

    def create_schema(self, name: str, spec: str) -> str:
        return self._action("create-schema", {"name": name, "spec": spec})["created"]

    def delete_schema(self, name: str):
        self._action("delete-schema", {"name": name})

    def list_schemas(self) -> List[str]:
        return self._action("list-schemas")["schemas"]

    def describe(self, name: str) -> str:
        return self._action("describe", {"name": name})["describe"]

    def explain(self, name: str, ecql: str = "INCLUDE") -> str:
        return self._action("explain", {"name": name, "ecql": ecql})["explain"]

    def count(self, name: str, ecql: str = "INCLUDE", exact: bool = True,
              auths: Optional[Sequence[str]] = None) -> int:
        body = {"name": name, "ecql": ecql, "exact": exact}
        if auths is not None:
            body["auths"] = list(auths)
        return self._action("count", body)["count"]

    def audit(self, n: int = 100) -> List[Dict]:
        return self._action("audit", {"n": n})["events"]

    def metrics(self) -> Dict:
        return self._action("metrics")["metrics"]

    # -- reads -------------------------------------------------------------
    def _get(self, opts: Dict) -> pa.Table:
        ticket = fl.Ticket(json.dumps(opts).encode())
        return self._client.do_get(ticket).read_all()

    def query(self, name: str, ecql: str = "INCLUDE", properties=None,
              max_features=None, sampling=None, sample_by=None,
              auths: Optional[Sequence[str]] = None) -> pa.Table:
        opts = {"op": "query", "schema": name, "ecql": ecql}
        if properties is not None:
            opts["properties"] = list(properties)
        if max_features is not None:
            opts["max_features"] = max_features
        if sampling is not None:
            opts["sampling"] = sampling
        if sample_by is not None:
            opts["sample_by"] = sample_by
        if auths is not None:
            opts["auths"] = list(auths)
        return self._get(opts)

    def density(self, name: str, ecql: str = "INCLUDE", bbox=None,
                width: int = 256, height: int = 256,
                weight: Optional[str] = None,
                auths: Optional[Sequence[str]] = None) -> np.ndarray:
        opts = {
            "op": "density", "schema": name, "ecql": ecql,
            "width": width, "height": height,
        }
        if bbox is not None:
            opts["bbox"] = list(bbox)
        if weight is not None:
            opts["weight"] = weight
        if auths is not None:
            opts["auths"] = list(auths)
        return _dense_grid(self._get(opts), (height, width), np.float32)

    def density_curve(self, name: str, ecql: str = "INCLUDE", level: int = 9,
                      bbox=None, weight: Optional[str] = None,
                      auths: Optional[Sequence[str]] = None):
        """Morton-block-aligned density (tile pyramids): returns
        ``(grid float64, snapped_bbox)`` — see PROTOCOL §3."""
        opts = {"op": "density_curve", "schema": name, "ecql": ecql,
                "level": level}
        if bbox is not None:
            opts["bbox"] = list(bbox)
        if weight is not None:
            opts["weight"] = weight
        if auths is not None:
            opts["auths"] = list(auths)
        t = self._get(opts)
        snapped = tuple(json.loads(
            t.schema.metadata[b"geomesa:snapped_bbox"].decode()
        ))
        n_blocks = 1 << level
        nx = round((snapped[2] - snapped[0]) / 360.0 * n_blocks)
        ny = round((snapped[3] - snapped[1]) / 180.0 * n_blocks)
        return _dense_grid(t, (ny, nx), np.float64), snapped

    def stats(self, name: str, stat_spec: str, ecql: str = "INCLUDE",
              auths: Optional[Sequence[str]] = None) -> sk.Stat:
        opts = {"op": "stats", "schema": name, "ecql": ecql, "stat": stat_spec}
        if auths is not None:
            opts["auths"] = list(auths)
        t = self._get(opts)
        return sk.Stat.from_json(t["value"][0].as_py())

    def export_bin(self, name: str, ecql: str = "INCLUDE",
                   track: Optional[str] = None,
                   label: Optional[str] = None) -> bytes:
        opts = {"op": "bin", "schema": name, "ecql": ecql}
        if track:
            opts["track"] = track
        if label:
            opts["label"] = label
        t = self._get(opts)
        return t["bin"][0].as_py() if t.num_rows else b""

    # -- writes ------------------------------------------------------------
    def insert_arrow(self, name: str, table: "pa.Table | pa.RecordBatch"):
        if isinstance(table, pa.RecordBatch):
            table = pa.Table.from_batches([table])
        descriptor = fl.FlightDescriptor.for_command(
            json.dumps({"schema": name}).encode()
        )
        writer, _ = self._client.do_put(descriptor, table.schema)
        writer.write_table(table)
        writer.close()
