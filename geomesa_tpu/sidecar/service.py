"""Arrow Flight service over a GeoDataset.

Protocol (coprocessor option-map analog, reference
GeoMesaCoprocessor.scala:44-61 serialized scan options):

* ``do_get(ticket)`` — ticket bytes are a JSON object:
    {"op": "query",   "schema": s, "ecql": e, "properties": [...],
     "auths": [...], "max_features": n, "sampling": n}
    {"op": "density", "schema": s, "ecql": e, "bbox": [xmin,ymin,xmax,ymax],
     "width": w, "height": h, "weight": attr}   -> sparse (row,col,weight)
    {"op": "density_curve", "schema": s, "ecql": e, "level": l,
     "bbox": [...], "weight": attr}  -> sparse blocks + snapped-bbox metadata
    {"op": "stats",   "schema": s, "ecql": e, "stat": "MinMax(a);..."}
    {"op": "bin",     "schema": s, "ecql": e, "track": attr, "label": attr}
* ``do_put`` — ingest an Arrow stream into the descriptor's schema.
* ``do_action`` — JSON body actions: create-schema, delete-schema,
  describe, explain, count, list-schemas, audit, metrics.
* ``list_flights`` — one FlightInfo per schema.

Every response that is not a feature stream is a single record batch whose
schema documents its payload (density = row/col/weight like the reference's
sparse DensityScan encoding, DensityScan.scala:95-106).
"""

from __future__ import annotations

import json
import os
import re
import threading
from typing import Dict, Iterator, Optional

import numpy as np
import pyarrow as pa
import pyarrow.flight as fl

from geomesa_tpu import tracing
from geomesa_tpu.api.dataset import GeoDataset, Query


#: RPC protocol version; clients refuse pushdown when the major differs
#: (the reference's server-side iterator-version compatibility contract)
PROTOCOL_VERSION = 1

#: headers carried per call (sidecar/client.py sends all three): the
#: client's trace id, its fair-share identity, and its remaining deadline
#: budget in ms (serving admission sheds when the budget can't be met —
#: docs/SERVING.md)
_TRACE_HEADER = "x-geomesa-trace-id"
_USER_HEADER = "x-geomesa-user"
_DEADLINE_HEADER = "x-geomesa-deadline-ms"
#: opt-in to the typed speculative (coarse-estimate) answer when the
#: server would deadline-shed a count (docs/SERVING.md); the request-body
#: ``speculative_ok`` flag is the equivalent hint
_SPECULATIVE_HEADER = "x-geomesa-speculative-ok"
#: fleet epoch propagation (docs/RESILIENCE.md §7): the router's required
#: per-schema fleet epochs (serve only after catching up), the epoch a
#: stamped WRITE establishes, and — outbound — this replica's identity +
#: epoch map gossiped back on every response
_FLEET_EPOCHS_HEADER = "x-geomesa-fleet-epochs"
_FLEET_STAMP_HEADER = "x-geomesa-fleet-stamp"
_REPLICA_HEADER = "x-geomesa-replica-id"
#: cross-replica trace stitching (docs/OBSERVABILITY.md §9): the caller's
#: per-call span token — the server's ROOT span records it as a
#: ``parent_span`` attribute, so the fleet stitcher can graft this
#: replica's subtree under the router span that made the call (v1.7,
#: additive; same token grammar as trace ids)
_PARENT_SPAN_HEADER = "x-geomesa-parent-span"


class _CallHeaders(fl.ServerMiddleware):
    """Per-call carrier of the client's serving headers (read from the
    Flight headers by the factory; the handlers fetch it via context).
    On a fleet replica it is also the response-header gossip channel:
    :meth:`sending_headers` stamps the replica id and its per-schema
    fleet-epoch map onto every response (docs/RESILIENCE.md §7)."""

    def __init__(self, trace_id: Optional[str], user: Optional[str],
                 budget_s: Optional[float], speculative: bool = False,
                 epochs: Optional[Dict[str, int]] = None,
                 stamp: Optional[Dict[str, int]] = None,
                 server: "Optional[GeoFlightServer]" = None,
                 parent_span: Optional[str] = None):
        self.trace_id = trace_id
        self.user = user
        self.budget_s = budget_s
        self.speculative = speculative
        self.epochs = epochs
        self.stamp = stamp
        self.server = server
        self.parent_span = parent_span

    def sending_headers(self):
        srv = self.server
        if srv is None or srv.replica_id is None:
            return {}
        return {
            _REPLICA_HEADER: str(srv.replica_id),
            _FLEET_EPOCHS_HEADER: json.dumps(srv.fleet_epochs()),
        }


_TRACE_ID_RE = re.compile(r"^[0-9A-Za-z_-]{1,64}$")
#: user identities are looser than trace ids — emails and dotted/scoped
#: names ("alice@example.com", "svc.ingest:prod") must survive, or fair
#: share silently collapses those users into one "anonymous" bucket; still
#: a single printable token (no whitespace/control chars) with a hard cap,
#: since it flows into audit hints and JSONL
_USER_RE = re.compile(r"^[0-9A-Za-z@._+:/=-]{1,128}$")


def _header(headers, name: str) -> Optional[str]:
    vals = headers.get(name) or headers.get(name.encode())
    if not vals:
        return None
    v = vals[0]
    return v.decode(errors="replace") if isinstance(v, bytes) else str(v)


class _TraceMiddlewareFactory(fl.ServerMiddlewareFactory):
    def __init__(self, server: "Optional[GeoFlightServer]" = None):
        # weak-ish backref for the fleet gossip headers; None keeps the
        # pre-fleet behavior (no outbound headers)
        self.server = server

    @staticmethod
    def _epoch_map(headers, name: str) -> Optional[Dict[str, int]]:
        raw = _header(headers, name)
        if raw is None:
            return None
        try:
            out = {str(k): int(v) for k, v in json.loads(raw).items()}
        except Exception:
            return None  # malformed gossip never fails a healthy call
        return out or None

    def start_call(self, info, headers):
        # the ids flow verbatim into audit hints and slow-trace JSONL:
        # refuse anything that isn't a short token (log-injection /
        # oversized-header hygiene; our own ids are 16 hex chars)
        tid = _header(headers, _TRACE_HEADER)
        if tid is not None and not _TRACE_ID_RE.match(tid):
            tid = None
        user = _header(headers, _USER_HEADER)
        if user is not None and not _USER_RE.match(user):
            user = None
        budget_s = None
        raw = _header(headers, _DEADLINE_HEADER)
        if raw is not None:
            try:
                budget_s = max(float(raw) / 1000.0, 0.0)
            except ValueError:
                pass
        spec = _header(headers, _SPECULATIVE_HEADER)
        speculative = spec is not None and spec.strip().lower() in (
            "1", "true", "yes"
        )
        epochs = self._epoch_map(headers, _FLEET_EPOCHS_HEADER)
        stamp = self._epoch_map(headers, _FLEET_STAMP_HEADER)
        parent = _header(headers, _PARENT_SPAN_HEADER)
        if parent is not None and not _TRACE_ID_RE.match(parent):
            parent = None
        fleet = self.server is not None \
            and self.server.replica_id is not None
        if tid is None and user is None and budget_s is None \
                and not speculative and epochs is None and stamp is None \
                and parent is None and not fleet:
            return None
        return _CallHeaders(tid, user, budget_s, speculative,
                            epochs=epochs, stamp=stamp, server=self.server,
                            parent_span=parent)


def _call_headers(context) -> _CallHeaders:
    try:
        mw = context.get_middleware("geomesa-trace")
    except Exception:
        mw = None
    return mw if mw is not None else _CallHeaders(None, None, None, False)


def _lib_version() -> str:
    try:
        import geomesa_tpu

        return getattr(geomesa_tpu, "__version__", "0.1.0")
    except Exception:
        return "0.1.0"


def _sparse_grid_batch(grid: np.ndarray, dtype) -> pa.RecordBatch:
    """Dense grid -> the sparse (row, col, weight) wire encoding shared by
    the density ops (reference DensityScan.scala:95-106 sparse encoding)."""
    rows, cols = np.nonzero(grid)
    return pa.record_batch(
        [
            pa.array(rows.astype(np.int32)),
            pa.array(cols.astype(np.int32)),
            pa.array(grid[rows, cols].astype(dtype)),
        ],
        names=["row", "col", "weight"],
    )


def _wire_schema(schema: pa.Schema) -> pa.Schema:
    """The on-wire variant of a §2 schema: dictionary<int32, utf8> fields
    ride as plain utf8 (PROTOCOL §3 v1.1 note — see do_get). Returns the
    input object unchanged when nothing is dictionary-encoded."""
    if not any(pa.types.is_dictionary(f.type) for f in schema):
        return schema
    return pa.schema(
        [
            pa.field(f.name, f.type.value_type, f.nullable)
            if pa.types.is_dictionary(f.type) else f
            for f in schema
        ],
        metadata=schema.metadata,
    )


def _query_from(opts: Dict) -> Query:
    return Query(
        ecql=opts.get("ecql", "INCLUDE"),
        max_features=opts.get("max_features"),
        properties=opts.get("properties"),
        sampling=opts.get("sampling"),
        sample_by=opts.get("sample_by"),
        index=opts.get("index"),
        auths=opts.get("auths"),
        sort_by=[tuple(s) for s in opts["sort_by"]] if opts.get("sort_by") else None,
    )


def _spec_errors(fn):
    """PROTOCOL.md §7: every server-raised error crosses the wire as a
    Flight error whose message leads with a structured ``[GM-*]`` code, so
    clients classify retryable vs fatal without parsing free-form text:

    * ``GM-ARG`` (fatal) — domain errors: unknown schema/attribute, bad
      ECQL, guard rejections, unsupported ops;
    * ``GM-TIMEOUT`` (fatal) — the server-side query deadline fired; the
      client maps it back to ``QueryTimeoutError``;
    * ``GM-INTERNAL`` (retryable) — unexpected server failure.

    Serving-scheduler rejections (docs/SERVING.md) carry their own codes:

    * ``GM-SHED`` (fatal to this attempt) — the query was shed at
      admission/dispatch because its deadline budget could not be met; no
      device work ran;
    * ``GM-OVERLOADED`` (retryable with backoff) — the bounded admission
      queue is full: backpressure from a healthy but saturated server.

    Already-coded Flight errors pass through untouched."""
    import functools

    from geomesa_tpu.resilience import (
        AdmissionRejectedError, DeadlineShedError, DeviceDrainError,
        QueryTimeoutError,
    )

    @functools.wraps(fn)
    def wrapped(*args, **kw):
        try:
            return fn(*args, **kw)
        except DeadlineShedError as e:
            # before QueryTimeoutError: shed is its subclass, and the
            # client distinguishes "server never started" from "ran out
            # of budget mid-scan"
            raise fl.FlightTimedOutError(f"[GM-SHED] {e}") from e
        except AdmissionRejectedError as e:
            raise fl.FlightUnavailableError(f"[GM-OVERLOADED] {e}") from e
        except DeviceDrainError as e:
            # PROTOCOL §7.1 v1.3: the serving slot (or its device) was
            # drained/died under this request — retryable: a respawned
            # slot serves the retry; streams must RE-OPEN, not resume
            raise fl.FlightUnavailableError(f"[GM-DRAINING] {e}") from e
        except QueryTimeoutError as e:
            raise fl.FlightTimedOutError(f"[GM-TIMEOUT] {e}") from e
        except (KeyError, ValueError, NotImplementedError) as e:
            msg = e.args[0] if e.args else str(e)
            raise fl.FlightServerError(f"[GM-ARG] {msg}") from e
        except fl.FlightError:
            raise  # already coded (or deliberately uncoded) by the handler
        except Exception as e:
            raise fl.FlightServerError(f"[GM-INTERNAL] {e!r}") from e

    return wrapped


def _coded_stream(it):
    """Code SCHEDULER failures that surface between stream chunks: a
    slot that dies/drains mid-stream raises from the continuation ticket
    inside ``QueryScheduler.iterate`` — OUTSIDE both the ``_spec_errors``
    decorator (do_get already returned) and the handler's own coded
    generator (the failure is in the driver, not the body) — so without
    this wrapper a drained stream crossed the wire as an UNCODED internal
    error the client could not classify as retryable. PROTOCOL §7.1:
    streams answer ``[GM-DRAINING]`` typed and RE-OPEN, never resume."""
    from geomesa_tpu.resilience import DeviceDrainError, QueryTimeoutError

    try:
        yield from it
    except DeviceDrainError as e:
        raise fl.FlightUnavailableError(f"[GM-DRAINING] {e}") from e
    except QueryTimeoutError as e:
        raise fl.FlightTimedOutError(f"[GM-TIMEOUT] {e}") from e


class GeoFlightServer(fl.FlightServerBase):
    """Flight server over a GeoDataset. Every dataset operation runs on
    the serving scheduler's dispatch-thread POOL (docs/SERVING.md;
    ``geomesa.serving.executors``, default 1) — the jit-deadlock
    discipline (gRPC owns the transport threads Flight handlers run on;
    compiling jax kernels there wedges nondeterministically in MLIR
    context creation, so all planning/compute routes through ordinary
    Python dispatch threads, one per executor slot, one slot per device)
    doubles as the serving bottleneck the scheduler manages: a bounded
    admission queue with deadline-aware ordering, per-user (weighted)
    fair share, typed load shedding, and cross-query fusion of compatible
    aggregates into one device pass — admission/fairness/fusion global,
    execution fanned across slots."""

    def __init__(self, dataset: Optional[GeoDataset] = None,
                 location: str = "grpc+tcp://127.0.0.1:0",
                 replica_id: Optional[str] = None,
                 fleet_root: Optional[str] = None, **kw):
        from geomesa_tpu import config

        #: fleet identity (docs/RESILIENCE.md §7): set (kwarg or
        #: geomesa.fleet.replica.id) makes this sidecar a fleet REPLICA —
        #: responses gossip the id + per-schema epoch map, stamped writes
        #: persist to the shared root, and the drain action is honored
        self.replica_id = replica_id if replica_id is not None \
            else config.FLEET_REPLICA_ID.get()
        self.fleet_root = fleet_root if fleet_root is not None \
            else config.FLEET_ROOT.get()
        self._fleet_lock = threading.Lock()
        self._fleet_epochs: Dict[str, int] = {}
        self._draining = False
        self._drain_reason: Optional[str] = None
        mw = dict(kw.pop("middleware", None) or {})
        mw.setdefault("geomesa-trace", _TraceMiddlewareFactory(self))
        super().__init__(location, middleware=mw, **kw)
        self.dataset = dataset if dataset is not None else GeoDataset()
        self._lock = threading.Lock()
        #: stamped-commit counter (docs/RESILIENCE.md §8): with the shared
        #: root's journal attached, each commit appends a delta record and
        #: advances the epoch marker; only every
        #: geomesa.journal.checkpoint.writes-th commit pays a full
        #: checkpoint save of the stamped schemas
        self._commit_count = 0
        if self.fleet_root:
            self.dataset.attach_journal(self.fleet_root)
        # the DATASET's scheduler, promoted to dispatch-thread mode: local
        # ops and Flight ops share one ledger and one fair-share domain
        self._sched = self.dataset.serving.start()

    # -- fleet epoch propagation (docs/RESILIENCE.md §7) -------------------
    def fleet_epochs(self) -> Dict[str, int]:
        with self._fleet_lock:
            return dict(self._fleet_epochs)

    #: root-side epoch marker (docs/RESILIENCE.md §7): written atomically
    #: by every stamped-write commit, read back after every refresh — a
    #: replica may only claim epoch E once the root PROVABLY contains E
    _FLEET_EPOCH_FILE = "fleet-epochs.json"

    def _root_epochs(self) -> Dict[str, int]:
        if not self.fleet_root:
            return {}
        from geomesa_tpu.fs import journal as journal_mod

        # crc-framed v2 marker (v1 legacy accepted; corruption quarantines
        # to `.quarantine` and reads as {} — the safe direction: redundant
        # refreshes, never a stale serve)
        epochs, _seq = journal_mod.read_epoch_marker(self.fleet_root)
        return epochs

    def _fleet_require(self, name: str, epoch: int) -> None:
        """Bring schema ``name`` up to fleet epoch ``epoch``: when the
        local epoch trails, re-read the schema from the shared root
        (dropping its covers with the replaced store) BEFORE serving —
        a restarted or failed-over replica can never answer from a
        pre-mutation store or cache. Runs on the dispatch thread, so the
        refresh serializes with this replica's queries.

        The local epoch advances only to what the root's epoch marker
        PROVES is present: a read stamped E that races the write
        establishing E (still applying on another replica) refreshes to
        the pre-E root and latches at the root's recorded epoch, so the
        NEXT request re-refreshes — it can never latch E over stale data
        and silently serve pre-mutation aggregates forever."""
        if epoch <= 0:
            return
        with self._fleet_lock:
            if self._fleet_epochs.get(name, 0) >= epoch:
                return
        from geomesa_tpu import metrics as metrics_mod

        with self._lock:
            # re-check under the dataset lock: a concurrent request may
            # have refreshed past us while we waited
            with self._fleet_lock:
                if self._fleet_epochs.get(name, 0) >= epoch:
                    return
            if self.fleet_root:
                self.dataset.refresh_schema(name, self.fleet_root)
                proven = self._root_epochs().get(name, 0)
            else:
                # no shared root to refresh from: drop the schema's
                # covers so nothing pre-mutation is ever served, and
                # take the requester's word for the epoch (there is no
                # root state to race against)
                proven = epoch
                try:
                    st = self.dataset._store(name)
                except KeyError:
                    pass
                else:
                    self.dataset.cache.store.invalidate(st.uid)
            latch = min(epoch, proven)
            with self._fleet_lock:
                if self._fleet_epochs.get(name, 0) < latch:
                    self._fleet_epochs[name] = latch
        metrics_mod.inc(metrics_mod.FLEET_EPOCH_REFRESH)

    def _fleet_before(self, h: "_CallHeaders") -> None:
        """Pre-op epoch sync: required read epochs catch all the way up;
        a write stamp establishing epoch E catches up to E-1 first (E's
        data is what THIS op is about to create)."""
        for name, e in sorted((h.epochs or {}).items()):
            self._fleet_require(name, int(e))
        for name, e in sorted((h.stamp or {}).items()):
            self._fleet_require(name, int(e) - 1)

    def _fleet_commit(self, stamp: Dict[str, int]) -> None:
        """Post-mutation commit for a router-stamped write: make the
        mutation durable at the shared root, record the new epochs in the
        root's marker file (what `_fleet_require` trusts), then advance
        the local epochs.

        With the root's journal attached (docs/RESILIENCE.md §8) the
        mutation is ALREADY durable — the dataset's mutation edges
        journaled it before applying, and the group-commit ack means it
        fsynced. The commit therefore only advances the marker (carrying
        the journal position) and pays a full checkpoint ``save`` every
        geomesa.journal.checkpoint.writes commits — the snapshot becomes
        the CHECKPOINT, not the commit, so a one-row stamped insert no
        longer rewrites the schema's whole chunk set. Trailing replicas
        recover via `refresh_schema`'s journal catch-up."""
        if self.fleet_root:
            from geomesa_tpu import config
            from geomesa_tpu.fs import journal as journal_mod

            with self._lock:
                j = self.dataset._journal
                if j is not None:
                    self._commit_count += 1
                    every = config.JOURNAL_CHECKPOINT_WRITES.to_int() or 256
                    if self._commit_count % every == 0:
                        # periodic checkpoint: bound replay length and
                        # journal size without paying a snapshot per write
                        self.dataset.save(self.fleet_root)
                else:
                    # journal disabled: legacy per-write snapshot commit
                    self.dataset.save(self.fleet_root, names=list(stamp))
                marker, _ = journal_mod.read_epoch_marker(self.fleet_root)
                for name, e in stamp.items():
                    if marker.get(name, 0) < int(e):
                        marker[name] = int(e)
                # concurrent commits on DIFFERENT replicas can race this
                # read-modify-replace; a lost entry only UNDER-states the
                # root's epoch, which costs redundant refreshes — never a
                # stale serve (the safe direction of the marker contract)
                journal_mod.write_epoch_marker(
                    self.fleet_root, marker,
                    journal_seq=j.last_seq() if j is not None else 0)
        with self._fleet_lock:
            for name, e in stamp.items():
                if self._fleet_epochs.get(name, 0) < int(e):
                    self._fleet_epochs[name] = int(e)

    def _serve(self, context, name: str, fn, op: Optional[str] = None,
               fuse=None, continuation: bool = False, speculative=None,
               admin: bool = False):
        """Admit ``fn`` to the dispatch queue and wait. Execution runs
        under a server-side root span that ADOPTS the client's trace id
        from the Flight header (so the server audit event and any
        server-side spans share the client's trace). ``force``: an
        incoming header is honored even when this process's own tracing
        knob is off — the client already opted in. The client's
        ``x-geomesa-user`` header keys fair share; its
        ``x-geomesa-deadline-ms`` budget drives admission shedding.
        ``admin`` ops (drain/undrain/status/version/observability) are
        served even while the replica is DRAINING — everything else
        answers typed ``[GM-DRAINING]`` so routers fail the traffic over
        (docs/RESILIENCE.md §7)."""
        from geomesa_tpu.resilience import DeviceDrainError

        h = _call_headers(context)
        tid = h.trace_id
        if self._draining and not admin and not continuation:
            raise DeviceDrainError(
                f"replica {self.replica_id or '?'} is draining"
                + (f" ({self._drain_reason})" if self._drain_reason else "")
                + "; route to another replica"
            )

        def go():
            with tracing.start(name, trace_id=tid, force=tid is not None,
                               remote=tid is not None) as root:
                if root is not tracing.NOOP:
                    w = self._sched.current_wait_ms()
                    if w:
                        root.set(queue_wait_ms=round(w, 3))
                    slot = self._sched.current_slot()
                    if slot:  # pool mode: which executor/device served
                        root.set(executor_slot=int(slot))
                    if self.replica_id is not None:
                        root.set(replica=str(self.replica_id))
                    if h.parent_span is not None:
                        # the caller's span token: the fleet stitcher
                        # grafts this replica subtree under the router
                        # span carrying the matching span_token attr
                        root.set(parent_span=str(h.parent_span))
                # fleet epoch sync BEFORE the op, commit AFTER a stamped
                # mutation succeeds (docs/RESILIENCE.md §7)
                self._fleet_before(h)
                out = fn()
                if h.stamp:
                    self._fleet_commit(h.stamp)
                return out

        # submit (never inline): after shutdown the scheduler raises here,
        # exactly like the stopped query thread did — a straggler RPC must
        # not compile jax on its gRPC transport thread
        return self._sched.submit(
            go, user=h.user, op=op or name, fuse=fuse,
            budget_s=h.budget_s, trace_id=tid, continuation=continuation,
            speculative=speculative,
        ).result()

    def _fuse_spec(self, op: str, opts: Dict):
        """Fusion eligibility for one wire request: compatible queued
        requests coalesce into one device pass; results wrap back into
        the op's wire frame per member (serving/fuse.py)."""
        from geomesa_tpu.serving import FuseSpec
        from geomesa_tpu.serving import fuse as fusemod

        name = opts.get("schema")
        if not name:
            return None
        key = fusemod.fuse_key(op, name, opts, ds=self.dataset)
        if key is None:
            return None

        def batch(tickets):
            from geomesa_tpu.serving.scheduler import FusedMemberError

            # run_batch failures fall back to per-member serial execution
            # (nothing committed yet); WRAP failures after the batch ran
            # must not — the device pass and audit events already
            # happened, so a bad member gets its own error instead
            raws = fusemod.run_batch(self.dataset, op, name, tickets)
            out = []
            for t, r in zip(tickets, raws):
                if isinstance(r, FusedMemberError):
                    # run_batch already failed this member's bookkeeping:
                    # pass its REAL error through — wrapping the sentinel
                    # would bury it under a framing TypeError
                    out.append(r)
                    continue
                try:
                    out.append(self._wrap_fused(op, t.fuse.payload, r))
                except Exception as e:
                    out.append(FusedMemberError(e))
            return out

        # "wire" prefix: wire tickets return Flight frames — they must
        # never coalesce with raw local tickets of the same query
        return FuseSpec(key=("wire", op, name) + key, payload=dict(opts),
                        batch=batch, schema=name)

    def _wrap_fused(self, op: str, opts: Dict, raw):
        """One member's raw fused result -> the op's wire frame (identical
        to what the serial handler would have returned)."""
        if op == "count":
            return iter([fl.Result(
                json.dumps({"count": int(raw)}).encode()
            )])
        if op == "density":
            batch = _sparse_grid_batch(raw, np.float32)
            return fl.RecordBatchStream(pa.Table.from_batches([batch]))
        if op == "density_curve":
            grid, snapped = raw
            batch = _sparse_grid_batch(grid, np.float64)
            return fl.RecordBatchStream(
                pa.Table.from_batches([batch]).replace_schema_metadata(
                    {b"geomesa:snapped_bbox":
                     json.dumps(list(snapped)).encode()}
                )
            )
        if op == "stats":
            batch = pa.record_batch(
                [pa.array([opts["stat"]]), pa.array([raw.to_json()])],
                names=["stat", "value"],
            )
            return fl.RecordBatchStream(pa.Table.from_batches([batch]))
        if op == "join_count":
            return iter([fl.Result(
                json.dumps({"count": int(raw)}).encode()
            )])
        raise ValueError(f"unfusable op {op!r}")

    def shutdown(self, *a, **kw):
        # stop the scheduler AFTER Flight drains active RPCs — those RPCs
        # hop onto the dispatch thread, and stopping it first would strand
        # them on futures nothing completes (shutdown would never return).
        # The dataset's scheduler drops back to inline mode: local ops on
        # the dataset keep working after the server is gone.
        out = super().shutdown(*a, **kw)
        self._sched.stop()
        return out

    def _fold_region(self, opts: Dict) -> Dict:
        """Fold an optional ``region`` polygon (WKT) into the request's
        ecql — the SAME composition GeoDataset's ``region=`` sugar does —
        BEFORE fusion keys are built, so two different polygons can never
        share a fusion key or a cached whole result (docs/SERVING.md,
        docs/CACHE.md)."""
        region = opts.pop("region", None)
        if region:
            name = opts.get("schema") or opts.get("name")
            opts["ecql"] = self.dataset._with_region(
                name, opts.get("ecql", "INCLUDE"), region
            )
        return opts

    # -- reads -------------------------------------------------------------
    @_spec_errors
    def do_get(self, context, ticket: fl.Ticket) -> fl.RecordBatchStream:
        # parse on the transport thread (cheap, no jax): the op's fusion
        # key must exist BEFORE the ticket queues, or nothing could
        # coalesce with it
        opts = self._fold_region(json.loads(ticket.ticket.decode()))
        op = opts.get("op", "query")
        fuse = None
        if op in ("density", "density_curve", "stats"):
            fuse = self._fuse_spec(op, opts)
        # speculative degraded answers (docs/SERVING.md): the request
        # flag or the x-geomesa-speculative-ok header opts density/stats
        # into the typed coarse fallback when admission sheds — the same
        # contract the count action carries
        speculative = None
        h = _call_headers(context)
        if op in ("density", "stats") and opts.get("schema") and (
                opts.get("speculative_ok") or h.speculative):
            tid = h.trace_id
            speculative = (
                lambda: self._speculative_get_frame(op, opts, tid)
            )
        return self._serve(
            context, "sidecar.do_get", lambda: self._do_get(opts),
            op=f"get:{op}", fuse=fuse, speculative=speculative,
        )

    def _speculative_get_frame(self, op: str, opts: Dict,
                               trace_id: Optional[str]):
        """The speculative density/stats wire frame: the coarse
        host-served estimate in the op's NORMAL frame (the ``speculative``
        marker rides the audit event, exactly like speculative counts).
        Runs under the dispatch thread via the scheduler's fallback."""
        ds = self.dataset
        name = opts["schema"]
        q = _query_from(opts)
        with tracing.start(f"{op}.speculative", trace_id=trace_id,
                           force=trace_id is not None, speculative=True):
            if op == "density":
                grid = ds._speculative_density(
                    name, q, bbox=opts.get("bbox"),
                    width=opts.get("width", 256),
                    height=opts.get("height", 256),
                    weight=opts.get("weight"),
                )
                return self._wrap_fused("density", opts, grid)
            stat = ds._speculative_stats(name, opts["stat"], q)
            return self._wrap_fused("stats", opts, stat)

    def _do_get(self, opts: Dict) -> fl.RecordBatchStream:
        op = opts.get("op", "query")
        name = opts["schema"]
        ds = self.dataset
        if op == "query":
            # streamed export (DeltaWriter.scala:53 / ArrowScan.scala:38-79
            # contract): incremental record batches; dictionary deltas ride
            # the IPC stream (emit_dictionary_deltas) so an append-only
            # vocabulary never forces a replacement. A partitioned store
            # streams partition-at-a-time — server peak memory is one
            # partition's matches, not the result set.
            from geomesa_tpu.io import arrow_io

            q = _query_from(opts)
            st = ds._store(name)
            st.flush()
            schema = arrow_io.arrow_schema(st.ft, q.properties, st.wkt_geoms())
            # String columns stream PLAIN utf8, decoded per chunk (PROTOCOL
            # §3 v1.1 note): pyarrow's GeneratorStream no longer writes
            # dictionary batches (nor Table chunks) correctly — clients hit
            # "expected number of dictionaries" — and a dictionary reader
            # accepts plain utf8 transparently via the stream schema.
            wire = _wire_schema(schema)

            # planning runs HERE (query_batches plans eagerly), so bad
            # ECQL / guard vetoes surface as FlightServerError via the
            # _spec_errors wrapper instead of escaping mid-stream
            batches = ds.query_batches(name, q)

            def gen():
                # mid-stream failures surface during gRPC iteration, OUTSIDE
                # the _spec_errors decorator (do_get already returned): apply
                # the same [GM-*] coding here so a streamed deadline expiry
                # is typed (not an uncoded internal error the client would
                # re-scan for nothing)
                from geomesa_tpu.resilience import QueryTimeoutError

                try:
                    for batch in batches:
                        if batch.n:
                            rb = arrow_io.batch_to_arrow(
                                st.ft, batch, st.dicts, q.properties
                            )
                            t = pa.Table.from_batches([rb])
                            if wire is not schema:
                                t = t.cast(wire)
                            yield from t.to_batches()
                except QueryTimeoutError as e:
                    raise fl.FlightTimedOutError(f"[GM-TIMEOUT] {e}") from e
                except fl.FlightError:
                    raise
                except Exception as e:
                    raise fl.FlightServerError(f"[GM-INTERNAL] {e!r}") from e

            # chunks are computed on the dispatch thread too: gRPC pulls
            # the stream from its own threads, but every next() hops back
            # (as continuation tickets — never bounded or shed mid-stream).
            # Chunks charge the STREAM OWNER's ledger (current_user() here
            # is the opening ticket's user), so a heavy exporter cannot
            # hide its load under "anonymous" and beat fair share.
            owner = self._sched.current_user()
            return fl.GeneratorStream(
                wire, _coded_stream(
                    self._sched.iterate(gen(), user=owner,
                                        op="get:query:stream")
                )
            )
        # serial framing delegates to _wrap_fused so the serial and fused
        # wire frames are the SAME code — they can never drift apart
        if op == "density":
            q = _query_from(opts)
            grid = ds.density(
                name, q, bbox=opts.get("bbox"),
                width=opts.get("width", 256), height=opts.get("height", 256),
                weight=opts.get("weight"),
            )
            return self._wrap_fused("density", opts, grid)
        if op == "density_curve":
            q = _query_from(opts)
            grid, snapped = ds.density_curve(
                name, q, level=opts.get("level", 9),
                bbox=opts.get("bbox"), weight=opts.get("weight"),
            )
            return self._wrap_fused("density_curve", opts, (grid, snapped))
        if op == "stats":
            q = _query_from(opts)
            stat = ds.stats(name, opts["stat"], q)
            return self._wrap_fused("stats", opts, stat)
        if op == "bin":
            q = _query_from(opts)
            blob = ds.export_bin(
                name, q, track=opts.get("track"), label=opts.get("label"),
                sort=opts.get("sort", True),
            )
            batch = pa.record_batch([pa.array([blob], pa.binary())], names=["bin"])
            return fl.RecordBatchStream(pa.Table.from_batches([batch]))
        raise fl.FlightServerError(f"[GM-ARG] unknown op {op!r}")

    # -- writes ------------------------------------------------------------
    @_spec_errors
    def do_put(self, context, descriptor, reader, writer):
        opts = json.loads(descriptor.command.decode()) if descriptor.command else {}
        name = opts.get("schema")
        if not name and descriptor.path:
            name = descriptor.path[0].decode()
        if not name:
            raise fl.FlightServerError("[GM-ARG] do_put needs a schema name")
        # Stage the stream chunk-by-chunk WITHOUT the write lock (a slow
        # uploader must not block other writers), then ingest + flush as
        # one locked transaction: a mid-stream failure commits nothing.
        staged = []
        while True:
            try:
                chunk = reader.read_chunk()
            except StopIteration:
                break
            if chunk.data is not None and chunk.data.num_rows:
                staged.append(chunk.data)
        def ingest():
            n = 0
            st = self.dataset._store(name)
            with self._lock:
                mark = len(st._buffer)
                try:
                    for rb in staged:
                        n += self.dataset.ingest_arrow(name, rb)
                    self.dataset.flush(name)
                except Exception:
                    del st._buffer[mark:]  # roll back this upload's batches
                    raise
            return n

        n = self._serve(context, "sidecar.do_put", ingest, op="put")
        writer  # (no app-metadata channel needed; count via describe/count)
        return n

    # -- actions -----------------------------------------------------------
    @_spec_errors
    def do_action(self, context, action: fl.Action) -> Iterator[fl.Result]:
        kind = action.type
        fuse = None
        # parse once on the transport thread (do_get's shape); bad JSON
        # leaves body None so _do_action re-parses and raises the typed
        # error on the dispatch thread, exactly as before
        try:
            body = json.loads(action.body.to_pybytes().decode()) \
                if action.body else {}
        except ValueError:
            body = None
        speculative = None
        if kind == "join-count" and body and body.get("left"):
            # repeat fusion: identical concurrent join-count requests
            # share one co-partitioned join (docs/JOIN.md)
            fuse = self._fuse_spec("join_count", {
                "schema": body["left"], "right": body.get("right"),
                "predicate": body.get("predicate"),
                "distance": body.get("distance"),
                "dx": body.get("dx"), "dy": body.get("dy"),
                "ecql": body.get("ecql", "INCLUDE"),
                "right_ecql": body.get("right_ecql", "INCLUDE"),
                "auths": body.get("auths"),
            })
        if kind == "count" and body and body.get("name"):
            body = self._fold_region(body)
            fuse = self._fuse_spec(
                "count", {**body, "schema": body["name"]}
            )
            h = _call_headers(context)
            if body.get("speculative_ok") or h.speculative:
                # opted-in degraded answer under overload: a deadline
                # shed (admission or dispatch) resolves to the typed
                # coarse estimate instead of [GM-SHED]. Host-only work —
                # planning without any device scan (docs/SERVING.md).
                # The client's trace id rides along so the speculative
                # audit event stays trace-correlated.
                speculative = (
                    lambda tid=h.trace_id:
                        self._speculative_count_frame(body, tid)
                )
        return self._serve(
            context, "sidecar.do_action",
            lambda: self._do_action(action, body),
            op=f"action:{kind}", fuse=fuse, speculative=speculative,
            admin=kind in self._ADMIN_ACTIONS,
        )

    #: actions served even while DRAINING (docs/RESILIENCE.md §7): the
    #: drain lifecycle itself, plus the observability surface an operator
    #: needs to watch a drain complete
    _ADMIN_ACTIONS = frozenset({
        "drain", "undrain", "replica-status", "version", "metrics",
        "serving-stats", "cache-stats", "device-health", "audit",
        # fleet observability plane (docs/OBSERVABILITY.md §9): federation
        # scrapes and trace stitching must keep working through a drain —
        # that is when an operator most needs them
        "metrics-export", "trace-fetch",
        # a DRAINING replica must still export its hot entries: the warm
        # handoff runs after drain (docs/RESILIENCE.md §7)
        "cache-export",
        # same rule for standing-query migration (docs/STANDING.md):
        # subscriptions leave a drained replica via subscribe-export
        "subscribe-export", "subscribe-stats",
    })

    def _speculative_count_frame(self, body: Dict,
                                 trace_id: Optional[str] = None
                                 ) -> Iterator[fl.Result]:
        """The speculative count's wire frame: the coarse estimate plus
        the ``speculative`` marker (clients surface it typed). Runs under
        the CLIENT's trace id (admission sheds resolve on the transport
        thread, where no server span is active) so the audit marker
        correlates to the caller's trace."""
        with tracing.start("count.speculative", trace_id=trace_id,
                           force=trace_id is not None):
            n = self.dataset._speculative_count(
                body["name"], _query_from(body)
            )
        return iter([fl.Result(
            json.dumps({"count": int(n), "speculative": True}).encode()
        )])

    def _do_action(self, action: fl.Action,
                   body: Optional[Dict] = None) -> Iterator[fl.Result]:
        if body is None:
            body = json.loads(action.body.to_pybytes().decode()) \
                if action.body else {}
        ds = self.dataset
        kind = action.type

        def ok(payload) -> Iterator[fl.Result]:
            yield fl.Result(json.dumps(payload).encode())

        if kind == "create-schema":
            with self._lock:
                ft = ds.create_schema(body["name"], body["spec"])
            return ok({"created": ft.name, "spec": ft.spec()})
        if kind == "delete-schema":
            with self._lock:
                ds.delete_schema(body["name"])
            return ok({"deleted": body["name"]})
        if kind == "list-schemas":
            return ok({"schemas": ds.list_schemas()})
        if kind == "describe":
            # "spec" is additive (PROTOCOL §4): the fleet router rebuilds
            # the FeatureType locally for cell-affinity decomposition
            return ok({"describe": ds.describe(body["name"]),
                       "spec": ds.get_schema(body["name"]).spec()})
        if kind == "explain":
            return ok({"explain": ds.explain(body["name"], _query_from(body))})
        if kind == "count":
            n = ds.count(body["name"], _query_from(body),
                         exact=body.get("exact", True))
            return self._wrap_fused("count", body, n)
        if kind == "join-count":
            # the spatial join's aggregate form (docs/JOIN.md; PROTOCOL
            # "join-count"): exact matched-pair count, co-partitioned.
            # Request auths apply to BOTH sides (Query objects, not raw
            # text — visibility must filter each side's scan)
            from geomesa_tpu.api.dataset import Query as _Q

            auths = body.get("auths")
            n = ds.join_count(
                body["left"], body["right"],
                predicate=body["predicate"],
                distance=body.get("distance"),
                dx=body.get("dx"), dy=body.get("dy"),
                left_query=_Q(ecql=body.get("ecql", "INCLUDE"),
                              auths=auths),
                right_query=_Q(ecql=body.get("right_ecql", "INCLUDE"),
                               auths=auths),
                level=body.get("level"),
            )
            return self._wrap_fused("join_count", body, n)
        if kind == "join-explain":
            from geomesa_tpu.api.dataset import Query as _Q

            auths = body.get("auths")
            return ok({"explain": ds.explain_join(
                body["left"], body["right"],
                predicate=body["predicate"],
                distance=body.get("distance"),
                dx=body.get("dx"), dy=body.get("dy"),
                left_query=_Q(ecql=body.get("ecql", "INCLUDE"),
                              auths=auths),
                right_query=_Q(ecql=body.get("right_ecql", "INCLUDE"),
                               auths=auths),
                level=body.get("level"),
                analyze=bool(body.get("analyze")),
            )})
        if kind == "audit":
            evs = ds.audit.recent(body.get("n", 100))
            return ok({"events": [json.loads(e.to_json()) for e in evs]})
        if kind == "metrics":
            from geomesa_tpu import metrics

            return ok({"metrics": metrics.registry().report()})
        if kind == "metrics-export":
            # federation source (PROTOCOL v1.7, docs/OBSERVABILITY.md §9):
            # the STRUCTURED registry snapshot (counters/gauges/histogram
            # buckets — not rendered text) the fleet router merges, plus
            # this replica's heat rows and the local health facts the
            # fleet /healthz composes
            from geomesa_tpu import heat, metrics, obs

            try:
                health = obs.health()
            except Exception as e:  # pragma: no cover - defensive
                health = {"status": "unknown", "error": str(e)}
            return ok({
                "replica": self.replica_id,
                "metrics": metrics.registry().export_snapshot(),
                "heat": heat.snapshot(),
                "health": health,
            })
        if kind == "trace-fetch":
            # stitching source (PROTOCOL v1.7): the finished trace(s)
            # behind one id from the retention ring, as span-tree dicts —
            # a replica that served several scatter groups of one query
            # retains several roots under the same id, and returns ALL of
            # them in one round trip. ``trace`` is the newest (simple
            # clients); empty ``traces`` means unknown/evicted — the
            # stitcher degrades to a partial tree, never blocks.
            tid = body["trace_id"]
            return ok({"replica": self.replica_id,
                       "trace": tracing.finished_trace(tid),
                       "traces": tracing.finished_traces(tid)})
        if kind == "cache-stats":
            # the aggregate cache is dataset-scoped, so every Flight query
            # of this sidecar shares it; this is the operator's view of
            # residency + hit rates (docs/CACHE.md)
            return ok({"cache": ds.cache.store.snapshot()})
        if kind == "cache-export":
            # warm-handoff source (docs/RESILIENCE.md §7): this replica's
            # hottest current-epoch entries for one schema, wire-encoded,
            # plus the data guard the importer must verify. Admin —
            # exports keep working mid-drain, which is exactly when the
            # handoff runs.
            name = body["name"]
            st = ds._store(name)
            limit = body.get("limit")
            epoch, entries = ds.cache.store.export_wire(
                st.uid, limit=None if limit is None else int(limit)
            )
            if epoch is None or epoch != st.version:
                # the cache predates/outlived this store's state: nothing
                # here is provably valid to hand off (the persist.py rule)
                entries = []
            return ok({
                "name": name, "entries": entries,
                "guard": {"count": int(st.count), "spec": st.ft.spec()},
            })
        if kind == "cache-import":
            # warm-handoff sink: admit exported entries under the LIVE
            # store's current epoch iff the guard proves both replicas
            # see the same logical data (count + spec — the same check
            # lake cache restore applies), so normal epoch invalidation
            # keeps protecting every later mutation.
            name = body["name"]
            st = ds._store(name)
            guard = body.get("guard") or {}
            if (int(guard.get("count", -1)) != int(st.count)
                    or guard.get("spec") != st.ft.spec()):
                return ok({"name": name, "restored": 0,
                           "skipped": "guard mismatch"})
            n = ds.cache.store.import_wire(
                st.uid, st.version, body.get("entries") or []
            )
            return ok({"name": name, "restored": n})
        if kind == "subscribe":
            # standing viewport registration (docs/STANDING.md; PROTOCOL
            # §5 v1.6). The router pre-computes the sub_id so the route
            # key is decided fleet-side; direct clients omit it and the
            # engine derives one from the viewport's center cell.
            sid = ds.subscribe(
                body["name"], body["aggregate"],
                bbox=body.get("bbox"), region=body.get("region"),
                width=int(body.get("width", 256)),
                height=int(body.get("height", 256)),
                levels=body.get("levels"),
                stat_spec=body.get("stat_spec"),
                sub_id=body.get("sub_id"),
            )
            return ok({"sub_id": sid})
        if kind == "unsubscribe":
            return ok({"sub_id": body["sub_id"],
                       "removed": ds.unsubscribe(body["sub_id"])})
        if kind == "subscribe-poll":
            from geomesa_tpu.subscribe import UnknownSubscription

            try:
                out = ds.subscription_poll(
                    body["sub_id"], cursor=int(body.get("cursor", 0))
                )
            except UnknownSubscription as e:
                # typed so the fleet router fails over to the next ring
                # owner instead of surfacing a fatal GM-ARG: after a
                # membership change the subscription lives elsewhere
                raise fl.FlightServerError(
                    f"[GM-SUB-UNKNOWN] {e.args[0] if e.args else e}"
                ) from e
            return ok(out)
        if kind == "subscribe-stats":
            eng = getattr(ds, "standing", None)
            snap = (eng.snapshot() if eng is not None
                    else {"groups": [], "subscribers": 0})
            return ok({"subscriptions": snap})
        if kind == "subscribe-export":
            # warm-handoff source for STANDING results (docs/STANDING.md,
            # RESILIENCE.md §7): like cache-export, admin — the migration
            # runs after drain. Unregistered engines export nothing.
            eng = getattr(ds, "standing", None)
            if eng is None:
                return ok({"groups": [], "guards": {}})
            return ok(eng.export_groups(
                schema=body.get("name"), keys=body.get("keys"),
                remove=bool(body.get("remove")),
            ))
        if kind == "subscribe-import":
            # warm-handoff sink: adopt exported standing groups verbatim
            # iff the per-schema {count, spec} guard matches (the
            # cache-import rule); otherwise re-scan locally ("resync")
            out = ds._standing_engine().import_groups(body)
            return ok(out)
        if kind == "serving-stats":
            # queue depth + per-user ledger (docs/SERVING.md; the same
            # rollup /debug/queries exposes)
            return ok({
                "serving": self._sched.snapshot(),
                "users": self._sched.user_rollups(),
            })
        if kind == "device-health":
            from geomesa_tpu.parallel import health as phealth

            return ok({"devices": phealth.registry().snapshot()})
        if kind == "cordon-device":
            # operator drain without a restart (docs/RESILIENCE.md §6):
            # the device leaves the sharded fan-out and pool pinning; the
            # next supervision round re-clamps the pool width
            from geomesa_tpu.parallel import health as phealth

            did = int(body["device"])
            phealth.registry().cordon(
                did, reason=str(body.get("reason") or "sidecar")
            )
            self._sched.supervise()
            return ok({"cordoned": did,
                       "devices": phealth.registry().snapshot()})
        if kind == "uncordon-device":
            from geomesa_tpu.parallel import health as phealth

            did = int(body["device"])
            cleared = phealth.registry().uncordon(did)
            self._sched.supervise()
            return ok({"uncordoned": did, "was_cordoned": bool(cleared),
                       "devices": phealth.registry().snapshot()})
        if kind == "drain":
            # replica-side drain (docs/RESILIENCE.md §7): every new
            # non-admin request answers [GM-DRAINING] (retryable — the
            # router fails the traffic over to other ring owners);
            # in-flight work completes normally
            self._draining = True
            self._drain_reason = str(body.get("reason") or "operator")
            return ok({"draining": True, "reason": self._drain_reason,
                       "replica": self.replica_id})
        if kind == "undrain":
            self._draining = False
            self._drain_reason = None
            return ok({"draining": False, "replica": self.replica_id})
        if kind == "replica-status":
            return ok({
                "replica": self.replica_id,
                "draining": self._draining,
                "drain_reason": self._drain_reason,
                "epochs": self.fleet_epochs(),
                "fleet_root": self.fleet_root,
                "serving": self._sched.snapshot(),
                "schemas": ds.list_schemas(),
            })
        if kind == "version":
            # the distributed-version handshake (GeoMesaDataStore.scala:
            # 498-503, 615-667: client checks the server-side iterator
            # version before planning pushdown scans)
            return ok({
                "version": _lib_version(), "protocol": PROTOCOL_VERSION,
            })
        raise fl.FlightServerError(f"[GM-ARG] unknown action {kind!r}")

    def list_actions(self, context):
        return [
            ("version", "server library + protocol version handshake"),
            ("create-schema", "register a feature type: {name, spec}"),
            ("delete-schema", "drop a feature type: {name}"),
            ("list-schemas", "type names"),
            ("describe", "schema details: {name}"),
            ("explain", "query plan: {name, ecql}"),
            ("count", "feature count: {name, ecql, exact}"),
            ("join-count", "spatial-join matched-pair count: {left, "
                           "right, predicate, distance|dx+dy, ecql, "
                           "right_ecql, level}"),
            ("join-explain", "spatial-join plan: {left, right, predicate, "
                             "distance|dx+dy, ecql, right_ecql, analyze}"),
            ("audit", "recent query events: {n}"),
            ("metrics", "metrics registry snapshot"),
            ("metrics-export", "structured registry snapshot + heat rows "
                               "+ local health facts for fleet federation"),
            ("trace-fetch", "one finished trace's span tree from the "
                            "retention ring: {trace_id}"),
            ("cache-stats", "aggregate cache residency + hit counters"),
            ("serving-stats", "admission queue depth + per-user rollups"),
            ("device-health", "per-device health map (ok/cordoned/broken)"),
            ("cordon-device", "drain a device from scheduling: "
                              "{device, reason}"),
            ("uncordon-device", "re-admit a cordoned device: {device}"),
            ("drain", "drain this replica: new non-admin requests answer "
                      "[GM-DRAINING] until undrain: {reason}"),
            ("undrain", "re-admit a drained replica to serving"),
            ("cache-export", "warm-handoff source: hottest current-epoch "
                             "cache entries + data guard: {name, limit}"),
            ("cache-import", "warm-handoff sink: admit exported entries "
                             "under the live epoch iff the guard matches: "
                             "{name, guard, entries}"),
            ("replica-status", "fleet-replica identity, drain state, and "
                               "per-schema fleet epochs"),
            ("subscribe", "register a standing viewport: {name, aggregate, "
                          "bbox|region, width, height, levels, stat_spec, "
                          "sub_id?} -> {sub_id}"),
            ("unsubscribe", "drop a standing subscription: {sub_id}"),
            ("subscribe-poll", "current standing result + updates past "
                               "cursor: {sub_id, cursor}"),
            ("subscribe-stats", "standing-query groups + subscriber counts"),
            ("subscribe-export", "warm-handoff source: standing groups + "
                                 "per-schema guards: {name?, keys?, remove?}"),
            ("subscribe-import", "warm-handoff sink: adopt exported groups "
                                 "iff the guard matches, else resync: "
                                 "{groups, guards}"),
        ]

    # -- discovery ---------------------------------------------------------
    def list_flights(self, context, criteria):
        from geomesa_tpu.io import arrow_io

        for name in self.dataset.list_schemas():
            ft = self.dataset.get_schema(name)
            descriptor = fl.FlightDescriptor.for_path(name.encode())
            ticket = fl.Ticket(json.dumps({"op": "query", "schema": name}).encode())
            yield fl.FlightInfo(
                _wire_schema(arrow_io.arrow_schema(ft)), descriptor,
                [fl.FlightEndpoint(ticket, [])], -1, -1,
            )

    def get_flight_info(self, context, descriptor):
        from geomesa_tpu.io import arrow_io

        name = descriptor.path[0].decode()
        ft = self.dataset.get_schema(name)
        ticket = fl.Ticket(json.dumps({"op": "query", "schema": name}).encode())
        return fl.FlightInfo(
            _wire_schema(arrow_io.arrow_schema(ft)), descriptor,
            [fl.FlightEndpoint(ticket, [])], -1, -1,
        )


def serve(dataset: Optional[GeoDataset] = None, port: int = 8815,
          host: str = "127.0.0.1") -> GeoFlightServer:
    """Start a sidecar (blocking ``server.serve()`` is up to the caller;
    the server is already listening when this returns)."""
    return GeoFlightServer(dataset, f"grpc+tcp://{host}:{port}")
