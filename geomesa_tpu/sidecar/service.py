"""Arrow Flight service over a GeoDataset.

Protocol (coprocessor option-map analog, reference
GeoMesaCoprocessor.scala:44-61 serialized scan options):

* ``do_get(ticket)`` — ticket bytes are a JSON object:
    {"op": "query",   "schema": s, "ecql": e, "properties": [...],
     "auths": [...], "max_features": n, "sampling": n}
    {"op": "density", "schema": s, "ecql": e, "bbox": [xmin,ymin,xmax,ymax],
     "width": w, "height": h, "weight": attr}   -> sparse (row,col,weight)
    {"op": "density_curve", "schema": s, "ecql": e, "level": l,
     "bbox": [...], "weight": attr}  -> sparse blocks + snapped-bbox metadata
    {"op": "stats",   "schema": s, "ecql": e, "stat": "MinMax(a);..."}
    {"op": "bin",     "schema": s, "ecql": e, "track": attr, "label": attr}
* ``do_put`` — ingest an Arrow stream into the descriptor's schema.
* ``do_action`` — JSON body actions: create-schema, delete-schema,
  describe, explain, count, list-schemas, audit, metrics.
* ``list_flights`` — one FlightInfo per schema.

Every response that is not a feature stream is a single record batch whose
schema documents its payload (density = row/col/weight like the reference's
sparse DensityScan encoding, DensityScan.scala:95-106).
"""

from __future__ import annotations

import json
import threading
from typing import Dict, Iterator, Optional

import numpy as np
import pyarrow as pa
import pyarrow.flight as fl

from geomesa_tpu.api.dataset import GeoDataset, Query


#: RPC protocol version; clients refuse pushdown when the major differs
#: (the reference's server-side iterator-version compatibility contract)
PROTOCOL_VERSION = 1


def _lib_version() -> str:
    try:
        import geomesa_tpu

        return getattr(geomesa_tpu, "__version__", "0.1.0")
    except Exception:
        return "0.1.0"


def _sparse_grid_batch(grid: np.ndarray, dtype) -> pa.RecordBatch:
    """Dense grid -> the sparse (row, col, weight) wire encoding shared by
    the density ops (reference DensityScan.scala:95-106 sparse encoding)."""
    rows, cols = np.nonzero(grid)
    return pa.record_batch(
        [
            pa.array(rows.astype(np.int32)),
            pa.array(cols.astype(np.int32)),
            pa.array(grid[rows, cols].astype(dtype)),
        ],
        names=["row", "col", "weight"],
    )


def _query_from(opts: Dict) -> Query:
    return Query(
        ecql=opts.get("ecql", "INCLUDE"),
        max_features=opts.get("max_features"),
        properties=opts.get("properties"),
        sampling=opts.get("sampling"),
        sample_by=opts.get("sample_by"),
        index=opts.get("index"),
        auths=opts.get("auths"),
        sort_by=[tuple(s) for s in opts["sort_by"]] if opts.get("sort_by") else None,
    )


def _spec_errors(fn):
    """PROTOCOL.md §7: domain errors (unknown schema/attribute, guard
    rejections, unsupported ops) cross the wire as FlightServerError with
    the original message — never as raw Arrow-mapped Python exceptions."""
    import functools

    @functools.wraps(fn)
    def wrapped(*args, **kw):
        try:
            return fn(*args, **kw)
        except (KeyError, ValueError, NotImplementedError) as e:
            msg = e.args[0] if e.args else str(e)
            raise fl.FlightServerError(str(msg)) from e

    return wrapped


class GeoFlightServer(fl.FlightServerBase):
    def __init__(self, dataset: Optional[GeoDataset] = None,
                 location: str = "grpc+tcp://127.0.0.1:0", **kw):
        super().__init__(location, **kw)
        self.dataset = dataset if dataset is not None else GeoDataset()
        self._lock = threading.Lock()

    # -- reads -------------------------------------------------------------
    @_spec_errors
    def do_get(self, context, ticket: fl.Ticket) -> fl.RecordBatchStream:
        opts = json.loads(ticket.ticket.decode())
        op = opts.get("op", "query")
        name = opts["schema"]
        ds = self.dataset
        if op == "query":
            # streamed export (DeltaWriter.scala:53 / ArrowScan.scala:38-79
            # contract): incremental record batches; dictionary deltas ride
            # the IPC stream (emit_dictionary_deltas) so an append-only
            # vocabulary never forces a replacement. A partitioned store
            # streams partition-at-a-time — server peak memory is one
            # partition's matches, not the result set.
            from geomesa_tpu.io import arrow_io

            q = _query_from(opts)
            st = ds._store(name)
            st.flush()
            schema = arrow_io.arrow_schema(st.ft, q.properties, st.wkt_geoms())

            # planning runs HERE (query_batches plans eagerly), so bad
            # ECQL / guard vetoes surface as FlightServerError via the
            # _spec_errors wrapper instead of escaping mid-stream
            batches = ds.query_batches(name, q)

            def gen():
                # chunks ride as single-batch Tables: pyarrow's
                # GeneratorStream only writes dictionary batches on its
                # Table path (bare RecordBatches lose them and the client
                # fails with "expected number of dictionaries")
                any_ = False
                for batch in batches:
                    if batch.n:
                        any_ = True
                        rb = arrow_io.batch_to_arrow(
                            st.ft, batch, st.dicts, q.properties
                        )
                        yield pa.Table.from_batches([rb])
                if not any_:
                    yield schema.empty_table()

            return fl.GeneratorStream(schema, gen())
        if op == "density":
            q = _query_from(opts)
            grid = ds.density(
                name, q, bbox=opts.get("bbox"),
                width=opts.get("width", 256), height=opts.get("height", 256),
                weight=opts.get("weight"),
            )
            batch = _sparse_grid_batch(grid, np.float32)
            return fl.RecordBatchStream(pa.Table.from_batches([batch]))
        if op == "density_curve":
            q = _query_from(opts)
            grid, snapped = ds.density_curve(
                name, q, level=opts.get("level", 9),
                bbox=opts.get("bbox"), weight=opts.get("weight"),
            )
            batch = _sparse_grid_batch(grid, np.float64)
            return fl.RecordBatchStream(
                pa.Table.from_batches([batch]).replace_schema_metadata(
                    {b"geomesa:snapped_bbox": json.dumps(list(snapped)).encode()}
                )
            )
        if op == "stats":
            q = _query_from(opts)
            stat = ds.stats(name, opts["stat"], q)
            batch = pa.record_batch(
                [pa.array([opts["stat"]]), pa.array([stat.to_json()])],
                names=["stat", "value"],
            )
            return fl.RecordBatchStream(pa.Table.from_batches([batch]))
        if op == "bin":
            q = _query_from(opts)
            blob = ds.export_bin(
                name, q, track=opts.get("track"), label=opts.get("label"),
                sort=opts.get("sort", True),
            )
            batch = pa.record_batch([pa.array([blob], pa.binary())], names=["bin"])
            return fl.RecordBatchStream(pa.Table.from_batches([batch]))
        raise fl.FlightServerError(f"unknown op {op!r}")

    # -- writes ------------------------------------------------------------
    @_spec_errors
    def do_put(self, context, descriptor, reader, writer):
        opts = json.loads(descriptor.command.decode()) if descriptor.command else {}
        name = opts.get("schema")
        if not name and descriptor.path:
            name = descriptor.path[0].decode()
        if not name:
            raise fl.FlightServerError("do_put needs a schema name")
        # Stage the stream chunk-by-chunk WITHOUT the write lock (a slow
        # uploader must not block other writers), then ingest + flush as
        # one locked transaction: a mid-stream failure commits nothing.
        staged = []
        while True:
            try:
                chunk = reader.read_chunk()
            except StopIteration:
                break
            if chunk.data is not None and chunk.data.num_rows:
                staged.append(chunk.data)
        n = 0
        st = self.dataset._store(name)
        with self._lock:
            mark = len(st._buffer)
            try:
                for rb in staged:
                    n += self.dataset.ingest_arrow(name, rb)
                self.dataset.flush(name)
            except Exception:
                del st._buffer[mark:]  # roll back this upload's batches
                raise
        writer  # (no app-metadata channel needed; count via describe/count)
        return n

    # -- actions -----------------------------------------------------------
    @_spec_errors
    def do_action(self, context, action: fl.Action) -> Iterator[fl.Result]:
        body = json.loads(action.body.to_pybytes().decode()) if action.body else {}
        ds = self.dataset
        kind = action.type

        def ok(payload) -> Iterator[fl.Result]:
            yield fl.Result(json.dumps(payload).encode())

        if kind == "create-schema":
            with self._lock:
                ft = ds.create_schema(body["name"], body["spec"])
            return ok({"created": ft.name, "spec": ft.spec()})
        if kind == "delete-schema":
            with self._lock:
                ds.delete_schema(body["name"])
            return ok({"deleted": body["name"]})
        if kind == "list-schemas":
            return ok({"schemas": ds.list_schemas()})
        if kind == "describe":
            return ok({"describe": ds.describe(body["name"])})
        if kind == "explain":
            return ok({"explain": ds.explain(body["name"], _query_from(body))})
        if kind == "count":
            n = ds.count(body["name"], _query_from(body),
                         exact=body.get("exact", True))
            return ok({"count": int(n)})
        if kind == "audit":
            evs = ds.audit.recent(body.get("n", 100))
            return ok({"events": [json.loads(e.to_json()) for e in evs]})
        if kind == "metrics":
            from geomesa_tpu import metrics

            return ok({"metrics": metrics.registry().report()})
        if kind == "version":
            # the distributed-version handshake (GeoMesaDataStore.scala:
            # 498-503, 615-667: client checks the server-side iterator
            # version before planning pushdown scans)
            return ok({
                "version": _lib_version(), "protocol": PROTOCOL_VERSION,
            })
        raise fl.FlightServerError(f"unknown action {kind!r}")

    def list_actions(self, context):
        return [
            ("version", "server library + protocol version handshake"),
            ("create-schema", "register a feature type: {name, spec}"),
            ("delete-schema", "drop a feature type: {name}"),
            ("list-schemas", "type names"),
            ("describe", "schema details: {name}"),
            ("explain", "query plan: {name, ecql}"),
            ("count", "feature count: {name, ecql, exact}"),
            ("audit", "recent query events: {n}"),
            ("metrics", "metrics registry snapshot"),
        ]

    # -- discovery ---------------------------------------------------------
    def list_flights(self, context, criteria):
        from geomesa_tpu.io import arrow_io

        for name in self.dataset.list_schemas():
            ft = self.dataset.get_schema(name)
            descriptor = fl.FlightDescriptor.for_path(name.encode())
            ticket = fl.Ticket(json.dumps({"op": "query", "schema": name}).encode())
            yield fl.FlightInfo(
                arrow_io.arrow_schema(ft), descriptor,
                [fl.FlightEndpoint(ticket, [])], -1, -1,
            )

    def get_flight_info(self, context, descriptor):
        from geomesa_tpu.io import arrow_io

        name = descriptor.path[0].decode()
        ft = self.dataset.get_schema(name)
        ticket = fl.Ticket(json.dumps({"op": "query", "schema": name}).encode())
        return fl.FlightInfo(
            arrow_io.arrow_schema(ft), descriptor,
            [fl.FlightEndpoint(ticket, [])], -1, -1,
        )


def serve(dataset: Optional[GeoDataset] = None, port: int = 8815,
          host: str = "127.0.0.1") -> GeoFlightServer:
    """Start a sidecar (blocking ``server.serve()`` is up to the caller;
    the server is already listening when this returns)."""
    return GeoFlightServer(dataset, f"grpc+tcp://{host}:{port}")
