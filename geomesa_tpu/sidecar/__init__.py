"""Arrow Flight sidecar: the RPC boundary of the framework.

The reference ships aggregation programs to remote compute as a serialized
option map over a custom protobuf coprocessor protocol and streams partial
results back (HBase: GeoMesaCoprocessor.scala:29-70 client loop +
CoprocessorScan.scala:35 server; SURVEY.md §5 "distributed communication
backend"). Here that role is played by Arrow Flight gRPC: tickets/actions
carry a JSON option map, results stream back as Arrow record batches —
the transport a JVM/GeoTools front-end (or any Arrow client) uses to reach
the TPU-resident dataset.
"""

from geomesa_tpu.sidecar.service import GeoFlightServer, PROTOCOL_VERSION, serve
from geomesa_tpu.sidecar.client import GeoFlightClient

__all__ = ["GeoFlightServer", "GeoFlightClient", "PROTOCOL_VERSION", "serve"]
