"""FileSystem storage (FSDS analog): partitioned Parquet datasets."""

from geomesa_tpu.fs.storage import (  # noqa: F401
    AttributeScheme, CompositeScheme, DateTimeScheme, FileSystemStorage,
    PartitionScheme, Z2Scheme, scheme_from_config,
)
