"""Partitioned Parquet storage — the FileSystem datastore (FSDS) analog.

Reference parity (SURVEY.md §2.5 FileSystem row): partitioned Parquet files
with a `PartitionScheme` (fs/storage/api/PartitionScheme.scala; impls
DateTimeScheme, Z2Scheme/XZ2Scheme, AttributeScheme, composite at
storage/common/partitions/*), filter -> partition pruning (FilterConverter's
Parquet predicate pushdown), file-backed metadata, and compaction.

This is the cold tier of the TPU framework: partitions on disk -> Arrow ->
HBM shards. Partition names are directory paths; pruning intersects each
existing partition's bounds with the query's extracted spatial/temporal/
attribute bounds (the planning-time analog of Parquet row-group pushdown —
actual row filtering happens in the compiled predicate after load).
"""

from __future__ import annotations

import json
import os
import threading
import uuid
import weakref
from dataclasses import dataclass
from typing import Dict, Iterator, List, Optional, Sequence, Tuple

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq

from geomesa_tpu import resilience
from geomesa_tpu.curves.zorder import NormalizedDimension, deinterleave2, interleave2
from geomesa_tpu.filter import ir, parse_ecql
from geomesa_tpu.io import arrow_io
from geomesa_tpu.schema.columns import ColumnBatch, DictionaryEncoder, encode_batch
from geomesa_tpu.schema.feature_type import FeatureType


class PartitionScheme:
    """Maps features -> partition names and query bounds -> partition subset."""

    kind = "base"

    def names(self, ft: FeatureType, batch: ColumnBatch,
              dicts: Dict[str, DictionaryEncoder]) -> np.ndarray:
        """Partition name per row (object array)."""
        raise NotImplementedError

    def keep(self, ft: FeatureType, name: str, f: ir.Filter) -> bool:
        """May partition ``name`` contain rows matching ``f``?"""
        raise NotImplementedError

    def name_depth(self) -> int:
        """Path segments per partition name (CompositeScheme splitting)."""
        return 1

    def config(self) -> Dict:
        raise NotImplementedError


class DateTimeScheme(PartitionScheme):
    """Time-partitioned directories (DateTimeScheme analog). ``step`` in
    {year, month, day, hour}; names like 2020/01/05 (day)."""

    kind = "datetime"
    _FMT = {"year": "%Y", "month": "%Y/%m", "day": "%Y/%m/%d", "hour": "%Y/%m/%d/%H"}

    def __init__(self, step: str = "day"):
        if step not in self._FMT:
            raise ValueError(f"unknown datetime step {step!r}")
        self.step = step

    def names(self, ft, batch, dicts):
        dtg = ft.dtg_field
        if dtg is None:
            raise ValueError("DateTimeScheme requires a date attribute")
        ts = batch.columns[dtg].astype("datetime64[ms]")
        unit = {"year": "Y", "month": "M", "day": "D", "hour": "h"}[self.step]
        # numpy ISO strings: 2020-01-05T13 -> 2020/01/05/13 path segments
        iso = np.datetime_as_string(ts.astype(f"datetime64[{unit}]"))
        return np.array(
            [s.replace("-", "/").replace("T", "/") for s in iso], dtype=object
        )

    def name_depth(self) -> int:
        return len(self._FMT[self.step].split("/"))

    def _bounds_ms(self, name: str) -> Tuple[int, int]:
        from datetime import datetime, timezone

        parts = [int(p) for p in name.split("/")]
        y = parts[0]
        mo = parts[1] if len(parts) > 1 else 1
        d = parts[2] if len(parts) > 2 else 1
        h = parts[3] if len(parts) > 3 else 0
        lo = datetime(y, mo, d, h, tzinfo=timezone.utc)
        if self.step == "year":
            hi = datetime(y + 1, 1, 1, tzinfo=timezone.utc)
        elif self.step == "month":
            hi = (datetime(y + 1, 1, 1, tzinfo=timezone.utc)
                  if mo == 12 else datetime(y, mo + 1, 1, tzinfo=timezone.utc))
        else:
            from datetime import timedelta

            hi = lo + (timedelta(days=1) if self.step == "day" else timedelta(hours=1))
        to_ms = lambda t: int(t.timestamp() * 1000)  # noqa: E731
        return to_ms(lo), to_ms(hi)

    def keep(self, ft, name, f):
        dtg = ft.dtg_field
        if dtg is None:
            return True
        iv = ir.extract_intervals(f, dtg)
        if iv.disjoint:
            return False
        if iv.is_empty:
            return True  # unconstrained
        lo, hi = self._bounds_ms(name)
        return any(qlo < hi and lo <= qhi for qlo, qhi in iv.values)

    def config(self):
        return {"kind": self.kind, "step": self.step}


class Z2Scheme(PartitionScheme):
    """Spatial partitions by coarse Z2 cell of the point/centroid
    (Z2Scheme/XZ2Scheme analog). ``bits`` per dimension (2 => 16 cells)."""

    kind = "z2"

    def __init__(self, bits: int = 2):
        self.bits = bits
        self._nx = NormalizedDimension(-180.0, 180.0, bits)
        self._ny = NormalizedDimension(-90.0, 90.0, bits)

    def names(self, ft, batch, dicts):
        g = ft.geom_field
        ix = self._nx.normalize(batch.columns[g + "__x"])
        iy = self._ny.normalize(batch.columns[g + "__y"])
        z = interleave2(ix, iy)
        width = max(1, (2 * self.bits + 3) // 4)
        return np.array([f"z2_{int(v):0{width}x}" for v in z], dtype=object)

    def _cell_bbox(self, name: str):
        z = int(name[3:], 16)
        ix, iy = deinterleave2(np.array([z], np.uint64))
        dx = 360.0 / (1 << self.bits)
        dy = 180.0 / (1 << self.bits)
        x0 = -180.0 + float(ix[0]) * dx
        y0 = -90.0 + float(iy[0]) * dy
        return (x0, y0, x0 + dx, y0 + dy)

    def keep(self, ft, name, f):
        g = ft.geom_field
        if g is None:
            return True
        fv = ir.extract_geometries(f, g)
        if fv.disjoint:
            return False
        if fv.is_empty:
            return True  # unconstrained
        xmin, ymin, xmax, ymax = self._cell_bbox(name)
        eps = 1e-9
        for geom in fv.values:
            gx0, gy0, gx1, gy1 = geom.bounds()
            if gx0 <= xmax + eps and gx1 >= xmin - eps and gy0 <= ymax + eps and gy1 >= ymin - eps:
                return True
        return False

    def config(self):
        return {"kind": self.kind, "bits": self.bits}


class AttributeScheme(PartitionScheme):
    """One partition per attribute value (AttributeScheme analog).

    Values become directory names ``v_<percent-encoded>`` — the ``v_`` prefix
    guarantees a name can never be '.', '..', or the null sentinel, and
    percent-encoding removes '/', so values cannot cross directory
    boundaries or escape the dataset root."""

    kind = "attribute"

    def __init__(self, attr: str):
        self.attr = attr

    @staticmethod
    def _encode(v: Optional[str]) -> str:
        from urllib.parse import quote

        if v is None:
            return "__null__"
        return "v_" + quote(str(v), safe="")

    @staticmethod
    def _decode(name: str) -> Optional[str]:
        from urllib.parse import unquote

        if name == "__null__":
            return None
        return unquote(name[2:])

    def names(self, ft, batch, dicts):
        a = ft.attr(self.attr)
        col = batch.columns[self.attr]
        if a.type == "string":
            vocab = dicts[self.attr].values
            raw = [None if c < 0 else vocab[c] for c in col]
        else:
            raw = [str(v) for v in col]
        return np.array([self._encode(v) for v in raw], dtype=object)

    def keep(self, ft, name, f):
        fv = ir.extract_attr_bounds(f, self.attr)
        if fv.disjoint:
            return False
        if fv.is_empty:
            return True  # unconstrained
        raw = self._decode(name)
        if raw is None:
            return False  # nulls match no equality/range predicate
        a = ft.attr(self.attr)
        for lo, hi in fv.values:
            if a.type not in ("string", "date"):
                try:
                    v = float(raw)
                except ValueError:
                    return True
                lo2 = -np.inf if lo is None else float(lo)
                hi2 = np.inf if hi is None else float(hi)
                if lo2 <= v <= hi2:
                    return True
            elif lo is not None and hi is not None and str(lo) == str(hi):
                if raw == str(lo):
                    return True
            else:
                # string range: conservative (partition may match)
                return True
        return False

    def config(self):
        return {"kind": self.kind, "attr": self.attr}


class CompositeScheme(PartitionScheme):
    """Nested partitioning a/b (composite scheme analog)."""

    kind = "composite"

    def __init__(self, schemes: Sequence[PartitionScheme]):
        self.schemes = list(schemes)

    def names(self, ft, batch, dicts):
        parts = [s.names(ft, batch, dicts) for s in self.schemes]
        return np.array(["/".join(p) for p in zip(*parts)], dtype=object)

    def keep(self, ft, name, f):
        pieces = name.split("/")
        i = 0
        for s in self.schemes:
            depth = s.name_depth()
            sub = "/".join(pieces[i : i + depth])
            if not s.keep(ft, sub, f):
                return False
            i += depth
        return True

    def name_depth(self) -> int:
        return sum(s.name_depth() for s in self.schemes)

    def config(self):
        return {"kind": self.kind, "schemes": [s.config() for s in self.schemes]}


def scheme_from_config(cfg: Dict) -> PartitionScheme:
    kind = cfg["kind"]
    if kind == "datetime":
        return DateTimeScheme(cfg.get("step", "day"))
    if kind == "z2":
        return Z2Scheme(int(cfg.get("bits", 2)))
    if kind == "attribute":
        return AttributeScheme(cfg["attr"])
    if kind == "composite":
        return CompositeScheme([scheme_from_config(c) for c in cfg["schemes"]])
    raise ValueError(f"unknown partition scheme {kind!r}")


#: live FileSystemStorage instances (weak — GC'd stores drop out), so
#: /healthz can expose every instance's quarantine MAP (which files, which
#: errors), not just the aggregate counters (docs/OBSERVABILITY.md)
_instances: "weakref.WeakSet" = weakref.WeakSet()


def quarantine_snapshot() -> Dict[str, Dict[str, str]]:
    """root -> {file path -> first failure} for every live storage
    instance with a non-empty quarantine (the /healthz ``fs_quarantine``
    payload; obs.py reads this lazily so pyarrow stays optional)."""
    out: Dict[str, Dict[str, str]] = {}
    for st in list(_instances):
        qm = st.quarantined()
        if qm:
            out.setdefault(st.root, {}).update(qm)
    return out


class FileSystemStorage:
    """A directory of partitioned Parquet files + JSON metadata per type.

    Layout::

        root/<type>/metadata.json
        root/<type>/data/<partition>/<uuid>.parquet
    """

    def __init__(self, root: str):
        self.root = root
        os.makedirs(root, exist_ok=True)
        self._lock = threading.Lock()
        #: corrupt-partition quarantine: file path -> first failure (repr).
        #: Quarantined files are skipped without re-parsing on later reads;
        #: strict (non-partial) reads still raise for them.
        self._quarantine: Dict[str, str] = {}
        _instances.add(self)  # /healthz exposes each live instance's map

    def _guarded_io(self, fn):
        """Run one root I/O under the per-root ``fs.root:<abspath>``
        breaker (docs/RESILIENCE.md; the remote-root arc of the lake
        tier, docs/LAKE.md): open-circuit fences fast, transient
        failures charge the breaker, success resets it. Only
        ``OSError``s feed it — per-file corruption is the quarantine's
        business, not the root's."""
        return resilience.guarded_root_io(self.root, fn)

    # -- metadata ----------------------------------------------------------
    def _meta_path(self, name: str) -> str:
        return os.path.join(self.root, name, "metadata.json")

    def _load_meta(self, name: str) -> Dict:
        try:
            with open(self._meta_path(name)) as fh:
                return json.load(fh)
        except FileNotFoundError:
            raise KeyError(f"no filesystem type {name!r} under {self.root}")

    def _save_meta(self, name: str, meta: Dict):
        # crash-safe persistence: serialize to a same-directory temp file,
        # fsync it, then atomically replace — a crash at ANY point leaves
        # either the old complete metadata or the new complete metadata,
        # never torn JSON that would poison every later open. The directory
        # fsync makes the rename itself durable.
        path = self._meta_path(name)
        tmp = path + f".{uuid.uuid4().hex[:8]}.tmp"
        resilience.fault_point("fs.write_meta", name=name, path=path)
        try:
            with open(tmp, "w") as fh:
                json.dump(meta, fh, indent=2)
                fh.flush()
                os.fsync(fh.fileno())
            # durable_replace = atomic rename + parent-dir fsync (the shared
            # publish sequence; filesystems refusing dir fsync stay atomic)
            resilience.durable_replace(tmp, path)
        except BaseException:
            try:
                os.remove(tmp)
            except OSError:
                pass
            raise

    def list_types(self) -> List[str]:
        out = []
        for d in sorted(os.listdir(self.root)):
            if os.path.exists(self._meta_path(d)):
                out.append(d)
        return out

    def create(self, ft: FeatureType, scheme: Optional[PartitionScheme] = None,
               fmt: str = "parquet"):
        """``fmt``: "parquet" (default) or "arrow" (IPC files — the
        reference ships both a Parquet and an Arrow file-system encoding;
        ArrowDataStore.scala / ParquetFileSystemStorage.scala)."""
        if fmt not in ("parquet", "arrow"):
            raise ValueError(f"unknown storage format {fmt!r}")
        if os.path.exists(self._meta_path(ft.name)):
            raise ValueError(f"type {ft.name!r} already exists")
        scheme = scheme or (
            DateTimeScheme("day") if ft.dtg_field else Z2Scheme(2)
        )
        os.makedirs(os.path.join(self.root, ft.name, "data"), exist_ok=True)
        self._save_meta(ft.name, {
            "spec": ft.spec(),
            "scheme": scheme.config(),
            "format": fmt,
            "partitions": {},   # name -> [file names]
            "count": 0,
        })

    # -- format-dispatched file IO ----------------------------------------
    @staticmethod
    def _write_file(table: pa.Table, path: str):
        if path.endswith(".arrow"):
            arrow_io.write_ipc(path, table.to_batches(), table.schema)
        else:
            pq.write_table(table, path)

    @staticmethod
    def _read_file(path: str, columns=None) -> pa.Table:
        # both formats raise on a requested-but-missing column, so
        # schema-evolution behavior cannot silently diverge by format
        resilience.fault_point("fs.read_partition", path=path)
        if path.endswith(".arrow"):
            t = arrow_io.read_ipc(path)
            if columns is not None:
                missing = [c for c in columns if c not in t.column_names]
                if missing:
                    raise KeyError(
                        f"columns {missing} not present in {path} "
                        f"(has: {t.column_names})"
                    )
                t = t.select(list(columns))
            return t
        if columns is not None:
            # surface a requested-but-missing column as the same KeyError
            # the arrow branch raises (parquet would raise ArrowInvalid,
            # which the degraded-read path would mistake for corruption
            # and quarantine a healthy file)
            schema = pq.read_schema(path)
            missing = [c for c in columns if schema.get_field_index(c) < 0]
            if missing:
                raise KeyError(
                    f"columns {missing} not present in {path} "
                    f"(has: {schema.names})"
                )
        return pq.read_table(path, columns=columns)

    @staticmethod
    def _read_file_schema(path: str) -> pa.Schema:
        if path.endswith(".arrow"):
            return arrow_io.read_ipc(path).schema
        return pq.read_schema(path)

    def schema(self, name: str) -> FeatureType:
        return FeatureType.from_spec(name, self._load_meta(name)["spec"])

    def scheme(self, name: str) -> PartitionScheme:
        return scheme_from_config(self._load_meta(name)["scheme"])

    def partitions(self, name: str) -> List[str]:
        return sorted(self._load_meta(name)["partitions"])

    def count(self, name: str) -> int:
        return int(self._load_meta(name).get("count", 0))

    # -- write -------------------------------------------------------------
    def write(self, name: str, data: Dict, fids=None) -> int:
        """Append a batch, splitting rows across partitions."""
        with self._lock:
            meta = self._load_meta(name)
            ft = FeatureType.from_spec(name, meta["spec"])
            scheme = scheme_from_config(meta["scheme"])
            dicts: Dict[str, DictionaryEncoder] = {}
            batch = encode_batch(ft, data, dicts, fids)
            pnames = scheme.names(ft, batch, dicts)
            ext = ".arrow" if meta.get("format") == "arrow" else ".parquet"
            for p in np.unique(pnames):
                sel = batch.select(pnames == p)
                rb = arrow_io.batch_to_arrow(ft, sel, dicts)
                pdir = os.path.join(self.root, name, "data", str(p))
                os.makedirs(pdir, exist_ok=True)
                fname = uuid.uuid4().hex[:16] + ext
                self._guarded_io(lambda: self._write_file(
                    pa.Table.from_batches([rb]), os.path.join(pdir, fname)
                ))
                meta["partitions"].setdefault(str(p), []).append(fname)
            meta["count"] = meta.get("count", 0) + batch.n
            self._save_meta(name, meta)
            return batch.n

    # -- read --------------------------------------------------------------
    def prune(self, name: str, ecql: "str | ir.Filter" = "INCLUDE") -> List[str]:
        """Partitions that may match the filter (pushdown pruning)."""
        meta = self._load_meta(name)
        ft = FeatureType.from_spec(name, meta["spec"])
        scheme = scheme_from_config(meta["scheme"])
        f = parse_ecql(ecql) if isinstance(ecql, str) else ecql
        return [p for p in sorted(meta["partitions"]) if scheme.keep(ft, p, f)]

    def _read_or_quarantine(self, part: str, path: str,
                            columns=None) -> Optional[pa.Table]:
        """One partition file, under the degradation contract
        (docs/RESILIENCE.md): a corrupt/unreadable file is quarantined and
        — when the operation allows partial results — recorded + skipped
        (returns None); strict reads raise. A missing REQUESTED column is
        a schema-evolution error, never a corruption skip.

        Transient I/O failures (``OSError``: fd pressure, an NFS blip) are
        retried in place via :class:`resilience.RetryPolicy` (the standard
        ``geomesa.retry.*`` knobs) and — even when retries are exhausted —
        are NEVER quarantined: the next read re-attempts the file, so one
        blip cannot lose the partition until process restart (ROADMAP open
        item). Only non-OSError parse failures (real corruption) enter the
        quarantine, and :meth:`clear_quarantine` re-admits those after an
        operator repairs the file."""
        prior = self._quarantine.get(path)
        if prior is not None:
            err = RuntimeError(f"quarantined: {prior}")
            if resilience.partial_allowed():
                resilience.record_skip("fs.read_partition", path, err, phase=part)
                return None
            raise err
        try:
            policy = resilience.RetryPolicy.from_config()
            return self._guarded_io(lambda: policy.call(
                lambda: self._read_file(path, columns=columns),
                # a missing file will not heal by retrying; other OSErrors
                # (EMFILE, ESTALE, EIO on network mounts) often do
                retryable=lambda e: isinstance(e, OSError)
                and not isinstance(e, FileNotFoundError),
                deadline=resilience.current_deadline(),
            ))
        except KeyError:
            raise  # requested-but-missing column: the strict §schema contract
        except (OSError, resilience.CircuitOpenError) as e:
            # transient path (incl. a fenced root) — recorded/raised but
            # NOT quarantined: the root healing re-admits every file
            if resilience.partial_allowed():
                resilience.record_skip("fs.read_partition", path, e, phase=part)
                return None
            raise
        except Exception as e:
            with self._lock:
                self._quarantine[path] = repr(e)
            if resilience.partial_allowed():
                resilience.record_skip("fs.read_partition", path, e, phase=part)
                return None
            raise

    def quarantined(self) -> Dict[str, str]:
        """Quarantined file paths -> first failure (advisory copy)."""
        return dict(self._quarantine)

    def clear_quarantine(self, path: Optional[str] = None) -> List[str]:
        """Re-admit quarantined file(s) for reading: the operator re-read
        path after a corrupt file is repaired/restored (``path=None``
        clears everything). Returns the paths cleared. The next read
        re-parses them — and re-quarantines on repeat failure."""
        with self._lock:
            if path is not None:
                cleared = (
                    [path] if self._quarantine.pop(path, None) is not None
                    else []
                )
            else:
                cleared = list(self._quarantine)
                self._quarantine.clear()
        return cleared

    def read(self, name: str, ecql: "str | ir.Filter" = "INCLUDE",
             columns: Optional[Sequence[str]] = None) -> pa.Table:
        """Read all (pruned) partitions as one Arrow table. Row-level
        filtering is left to the caller's compiled predicate. Under
        ``resilience.allow_partial()`` (or ``geomesa.scan.partial``) corrupt
        partition files are quarantined + skipped and the surviving rows
        returned; strict mode raises on the first corrupt file."""
        meta = self._load_meta(name)
        tables = []
        for p in self.prune(name, ecql):
            pdir = os.path.join(self.root, name, "data", p)
            for fname in meta["partitions"][p]:
                t = self._read_or_quarantine(
                    p, os.path.join(pdir, fname), columns=columns
                )
                if t is not None:
                    tables.append(t)
        if not tables:
            # match the schema of existing files if any (WKT vs point geometry)
            schema = None
            for p in sorted(meta["partitions"]):
                for fname in meta["partitions"][p]:
                    path = os.path.join(self.root, name, "data", p, fname)
                    if path in self._quarantine:
                        continue
                    try:
                        schema = self._read_file_schema(path)
                    except Exception:
                        # unreadable schema source: the spec-derived schema
                        # below still serves (degraded reads must not die
                        # probing a corrupt file for its schema)
                        continue
                    break
                if schema is not None:
                    break
            if schema is None:
                ft = FeatureType.from_spec(name, meta["spec"])
                schema = arrow_io.arrow_schema(ft)
            if columns is not None:
                missing = [c for c in columns
                           if schema.get_field_index(c) < 0]
                if missing:
                    # same strict contract as _read_file: a requested
                    # column the table cannot supply is an error, even
                    # when pruning selected zero files
                    raise KeyError(
                        f"columns {missing} not present in {name} "
                        f"(has: {schema.names})"
                    )
                schema = pa.schema([schema.field(c) for c in columns])
            return schema.empty_table()
        schema = pa.unify_schemas([t.schema for t in tables], promote_options="permissive")
        return pa.concat_tables([t.cast(schema) for t in tables]).unify_dictionaries()

    def read_partition(self, name: str, partition: str) -> pa.Table:
        meta = self._load_meta(name)
        pdir = os.path.join(self.root, name, "data", partition)
        tables = []
        for f in meta["partitions"][partition]:
            t = self._read_or_quarantine(partition, os.path.join(pdir, f))
            if t is not None:
                tables.append(t)
        if not tables:
            ft = FeatureType.from_spec(name, meta["spec"])
            return arrow_io.arrow_schema(ft).empty_table()
        schema = pa.unify_schemas([t.schema for t in tables], promote_options="permissive")
        return pa.concat_tables([t.cast(schema) for t in tables]).unify_dictionaries()

    def read_partial(self, name: str, ecql: "str | ir.Filter" = "INCLUDE",
                     columns: Optional[Sequence[str]] = None,
                     ) -> "resilience.PartialResult[pa.Table]":
        """Typed degraded read: the surviving rows plus a structured account
        of every skipped partition file (the GeoBlocks-style contract —
        exact over what survived, explicit about what didn't)."""
        meta = self._load_meta(name)
        pruned = self.prune(name, ecql)
        total = sum(len(meta["partitions"][p]) for p in pruned)
        with resilience.allow_partial() as partial:
            table = self.read(name, ecql, columns)
        return resilience.PartialResult(
            value=table,
            skipped=list(partial.skipped),
            total_parts=total,  # unit of work here = one partition file
            ok_parts=total - len({s.part for s in partial.skipped}),
        )

    # -- maintenance -------------------------------------------------------
    def compact(self, name: str, partition: Optional[str] = None) -> int:
        """Merge each partition's files into one (compaction analog).
        Returns number of files removed."""
        with self._lock:
            meta = self._load_meta(name)
            removed = 0
            targets = [partition] if partition else list(meta["partitions"])
            for p in targets:
                files = meta["partitions"].get(p, [])
                if len(files) <= 1:
                    continue
                pdir = os.path.join(self.root, name, "data", p)
                tables = [self._read_file(os.path.join(pdir, f)) for f in files]
                schema = pa.unify_schemas(
                    [t.schema for t in tables], promote_options="permissive"
                )
                merged = pa.concat_tables(
                    [t.cast(schema) for t in tables]
                ).unify_dictionaries()
                ext = ".arrow" if meta.get("format") == "arrow" else ".parquet"
                fname = uuid.uuid4().hex[:16] + ext
                self._write_file(merged, os.path.join(pdir, fname))
                for f in files:
                    os.remove(os.path.join(pdir, f))
                    removed += 1
                meta["partitions"][p] = [fname]
            self._save_meta(name, meta)
            return removed

    def delete_type(self, name: str):
        import shutil

        self._load_meta(name)
        shutil.rmtree(os.path.join(self.root, name))

    # -- bulk load into the device store ------------------------------------
    def load_into(self, dataset, name: str, ecql: "str | ir.Filter" = "INCLUDE") -> int:
        """Ingest (pruned) partitions into a GeoDataset store."""
        ft = self.schema(name)
        if name not in dataset.list_schemas():
            dataset.create_schema(FeatureType.from_spec(name, ft.spec()))
        table = self.read(name, ecql)
        if table.num_rows == 0:
            return 0
        data, fids = arrow_io.table_to_data(ft, table)
        n = dataset.insert(name, data, fids)
        dataset.flush(name)
        return n
