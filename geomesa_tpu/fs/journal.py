"""Durable mutation journal — per-root write-ahead log with group commit.

The reference GeoMesa never owned durability: acked mutations landed in
Accumulo/HBase, whose BigTable-style WALs replay acked writes after a
tablet-server crash. Our TPU-native stack replaced those backends with an
in-memory columnar store plus explicit checkpoints (``GeoDataset.save``,
spill, lake containers) — so an acked ``insert``/``delete_features``/
stream batch arriving *between* checkpoints died with the process. This
module closes that hole (docs/RESILIENCE.md §8 "Durability contract"):

* **Framing**: each record is one crc32-guarded frame —
  ``u32le payload_len | u32le crc32(payload) | payload`` — appended to a
  segment file under ``<root>/journal/``. A torn tail (crash mid-write)
  truncates cleanly at the last valid frame on the next open; it can
  never fail the root.
* **Group commit**: a dedicated committer thread drains every pending
  append into ONE ``write`` + ONE ``fsync`` per round, then optionally
  widens the batch by waiting ``geomesa.journal.group.ms`` before the
  next drain. Callers block until their record is durable, so the
  **ack = durable** invariant holds without a per-write fsync; the fsync
  latency itself is the natural batching window for concurrent writers
  (commit pipelining).
* **Checkpoint interplay**: ``GeoDataset.save`` stamps each schema's
  manifest entry with the journal position it captured
  (``journal_seq``) and then truncates segment-wise — a segment whose
  every record is covered by ALL checkpointed schemas is deleted.
  ``GeoDataset.load`` replays records past each schema's checkpointed
  position, in global sequence order.
* **Fault points** (docs/RESILIENCE.md §6): ``journal.append`` fires on
  the appending thread before the record is queued, ``journal.fsync``
  on the committer thread before each group fsync, ``journal.replay``
  per segment during recovery — so chaos/crash tests drive torn writes,
  fsync failures, and mid-replay crashes deterministically.

Multi-process note (the fleet-root case, docs/RESILIENCE.md §7): segment
names embed the owning pid, so two replicas appending to one shared root
never interleave frames within a file. Per-schema record ordering across
processes is guaranteed by the router's write stamping (one replica owns
a schema's writes at a time) plus the rule that a replica opens the
journal — adopting ``max(seq)`` — before its first append.
"""

from __future__ import annotations

import json
import os
import struct
import threading
import time
import weakref
import zlib
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from geomesa_tpu import config, metrics, resilience
from geomesa_tpu.resilience import (  # noqa: F401  (re-exported surface)
    durable_replace, durable_write_json, fsync_dir,
)

# json bytes, blob bytes, crc32(json + blob). Bulk array payloads ride
# the raw blob section AFTER the json document (tag "ndr" below) so the
# json encoder never has to escape-scan hundreds of KB of base64 — the
# single largest CPU cost of journaling a 4k-row insert batch.
_FRAME_HDR = struct.Struct("<III")
_SEG_MAGIC = b"GMJ2"
_SEG_PREFIX = "seg-"
_SEG_SUFFIX = ".gmj"
JOURNAL_DIR = "journal"

#: every live journal, for the /healthz lag snapshot (obs.py reaches in
#: through sys.modules, same pattern as the fs quarantine section)
_JOURNALS: "weakref.WeakSet[MutationJournal]" = weakref.WeakSet()


class JournalError(Exception):
    """A journal append could not be made durable (the mutation that
    asked for it must NOT be acked)."""


# ---------------------------------------------------------------------------
# Fleet epoch marker (crc + fsync framed — ISSUE 16 satellite)
# ---------------------------------------------------------------------------

EPOCH_MARKER_FILE = "fleet-epochs.json"


def _marker_crc(epochs: Dict[str, int], journal_seq: int) -> int:
    canon = json.dumps({"epochs": epochs, "journal_seq": int(journal_seq)},
                       sort_keys=True, separators=(",", ":"))
    return zlib.crc32(canon.encode()) & 0xFFFFFFFF


def write_epoch_marker(root: str, epochs: Dict[str, int],
                       journal_seq: int = 0) -> None:
    """Publish the fleet epoch marker with crc framing + full fsync
    discipline (file AND directory). ``journal_seq`` records the journal
    position the marker proves durable — a trailing replica knows every
    record up to it is on disk."""
    epochs = {k: int(v) for k, v in epochs.items()}
    durable_write_json(os.path.join(root, EPOCH_MARKER_FILE), {
        "v": 2,
        "epochs": epochs,
        "journal_seq": int(journal_seq),
        "crc": _marker_crc(epochs, journal_seq),
    })


def read_epoch_marker(root: str) -> Tuple[Dict[str, int], int]:
    """Read the marker, verifying the crc frame. Corruption QUARANTINES
    typed (the file moves aside to ``.quarantine``, the
    ``fleet.epoch.marker.quarantined`` counter bumps, the degradation
    trail records it) and reads as empty — the SAFE direction: an empty
    marker understates proven epochs, forcing a redundant refresh, never
    a stale serve. Returns ``(epochs, journal_seq)``."""
    path = os.path.join(root, EPOCH_MARKER_FILE)
    try:
        with open(path) as fh:
            doc = json.load(fh)
    except FileNotFoundError:
        return {}, 0
    except (OSError, ValueError) as e:
        _quarantine_marker(path, e)
        return {}, 0
    if not isinstance(doc, dict):
        _quarantine_marker(path, ValueError("marker is not an object"))
        return {}, 0
    if "crc" not in doc and "epochs" not in doc:
        # v1 legacy flat {schema: epoch} marker — accepted verbatim
        try:
            return {str(k): int(v) for k, v in doc.items()}, 0
        except (TypeError, ValueError) as e:
            _quarantine_marker(path, e)
            return {}, 0
    try:
        epochs = {str(k): int(v) for k, v in doc.get("epochs", {}).items()}
        seq = int(doc.get("journal_seq", 0))
        if int(doc["crc"]) != _marker_crc(epochs, seq):
            raise ValueError("crc mismatch")
    except (TypeError, KeyError, ValueError) as e:
        _quarantine_marker(path, e)
        return {}, 0
    return epochs, seq


def _quarantine_marker(path: str, error: BaseException) -> None:
    metrics.inc(metrics.FLEET_EPOCH_MARKER_QUARANTINED)
    resilience.record_skip("fleet.epoch.marker", path, error, phase="decode")
    try:
        os.replace(path, path + ".quarantine")
    except OSError:
        pass


# ---------------------------------------------------------------------------
# Typed record payload encoding (exact Python round trip, JSON carrier)
# ---------------------------------------------------------------------------


_PLIST_TYPES = frozenset({bool, int, float, str, type(None)})


def enc_value(v: Any, sink: Optional[List[bytes]] = None) -> Any:
    """Encode one attribute value (or column of values) to a JSON-safe
    form that :func:`dec_value` restores EXACTLY — tuples stay tuples
    (points), numpy arrays keep their dtype, datetimes keep ms precision.
    Exactness here is what makes recovery bit-identical.

    ``sink`` (a list the caller hands to :meth:`MutationJournal.append`
    as ``blobs``) enables the raw-blob fast path for ndarrays: the bytes
    travel in the frame's blob section and the json carries only an
    ``ndr`` marker — no base64, nothing large for json to escape-scan.
    Without a sink, arrays fall back to the self-contained ``ndb``
    (base64-in-json) form."""
    if v is None or isinstance(v, (bool, int, float, str)):
        return v
    if isinstance(v, np.datetime64):
        return {"~": "dt64",
                "v": int(v.astype("datetime64[ms]").astype(np.int64))}
    if isinstance(v, np.generic):
        return enc_value(v.item())
    if isinstance(v, np.ndarray):
        if v.dtype.kind == "M":
            v = v.astype("datetime64[ms]")
        if v.dtype.kind in "OU":
            return {"~": "list", "v": [enc_value(x, sink) for x in v.tolist()]}
        # raw little-endian bytes: bit-exact by construction (no float
        # repr round trip) and far cheaper to encode than tolist()+json
        # for a 4k-row column — what keeps group-commit inserts inside
        # the bench overhead gate
        a = np.ascontiguousarray(v)
        if a.dtype.byteorder == ">":
            a = a.astype(a.dtype.newbyteorder("<"))
        if sink is not None:
            raw = a.tobytes()
            sink.append(raw)
            return {"~": "ndr", "d": str(a.dtype), "s": list(a.shape),
                    "i": len(sink) - 1, "n": len(raw)}
        import base64

        return {"~": "ndb", "d": str(a.dtype), "s": list(a.shape),
                "v": base64.b64encode(a.tobytes()).decode()}
    if isinstance(v, tuple):
        return {"~": "tup", "v": [enc_value(x, sink) for x in v]}
    if isinstance(v, list):
        # scalar fast path: a list of JSON-native scalars rides verbatim
        # (dec_value returns non-dict values unchanged — same type, same
        # values) instead of paying one enc_value call per element. The
        # guard runs at C speed: one type() per element via map, one set.
        if set(map(type, v)) <= _PLIST_TYPES:
            return {"~": "plist", "v": v}
        return {"~": "list", "v": [enc_value(x, sink) for x in v]}
    if isinstance(v, bytes):
        import base64

        return {"~": "b64", "v": base64.b64encode(v).decode()}
    if isinstance(v, dict):
        return {"~": "map",
                "v": {str(k): enc_value(x, sink) for k, x in v.items()}}
    raise TypeError(f"unjournalable value type {type(v).__name__}")


def dec_value(v: Any) -> Any:
    if not isinstance(v, dict):
        return v
    t = v["~"]
    if t == "dt64":
        return np.datetime64(int(v["v"]), "ms")
    if t == "ndt":
        return np.asarray(v["v"], np.int64).astype("datetime64[ms]")
    if t == "nd":
        return np.asarray(v["v"], np.dtype(v["d"]))
    if t == "ndb":
        import base64

        a = np.frombuffer(base64.b64decode(v["v"]), np.dtype(v["d"]))
        return a.reshape(v.get("s") or (a.size,)).copy()
    if t == "ndr":
        # raw bytes were re-attached by _attach_blobs at segment read
        # time; a marker without them means the blob section was lost
        raw = v.get("_raw")
        if raw is None:
            raise ValueError("ndr marker with no attached blob bytes")
        a = np.frombuffer(raw, np.dtype(v["d"]))
        return a.reshape(v.get("s") or (a.size,)).copy()
    if t == "plist":
        return list(v["v"])
    if t == "tup":
        return tuple(dec_value(x) for x in v["v"])
    if t == "list":
        return [dec_value(x) for x in v["v"]]
    if t == "b64":
        import base64

        return base64.b64decode(v["v"])
    if t == "map":
        return {k: dec_value(x) for k, x in v["v"].items()}
    raise ValueError(f"unknown journal value tag {t!r}")


def enc_columns(data: Dict[str, Any],
                sink: Optional[List[bytes]] = None) -> Dict[str, Any]:
    return {k: enc_value(v, sink) for k, v in data.items()}


def _attach_blobs(rec: Dict[str, Any], blob: bytes) -> None:
    """Re-attach the frame's raw blob section to the record's ``ndr``
    markers (in place). Offsets are derived from each marker's declared
    length in blob-index order, so the json walk order need not match
    the encode order."""
    markers: List[Dict[str, Any]] = []

    def walk(o: Any) -> None:
        if isinstance(o, dict):
            if o.get("~") == "ndr":
                markers.append(o)
                return
            for x in o.values():
                walk(x)
        elif isinstance(o, list):
            for x in o:
                walk(x)

    walk(rec)
    off = 0
    for m in sorted(markers, key=lambda m: int(m.get("i", 0))):
        n = int(m.get("n", 0))
        m["_raw"] = blob[off:off + n]
        off += n


def dec_columns(data: Dict[str, Any]) -> Dict[str, Any]:
    return {k: dec_value(v) for k, v in data.items()}


# ---------------------------------------------------------------------------
# The journal
# ---------------------------------------------------------------------------


class _Pending:
    __slots__ = ("frame", "event", "error")

    def __init__(self, frame: bytes):
        self.frame = frame
        self.event = threading.Event()
        self.error: Optional[BaseException] = None


class MutationJournal:
    """Append-only, crc-framed, fsync'd mutation log for one storage root.

    ``append`` blocks until the record is durable (group-committed) and
    returns its sequence number; ``records`` replays in sequence order
    with torn-tail truncation; ``checkpoint`` deletes fully-covered
    segments after a successful ``save``."""

    def __init__(self, root: str, create: bool = True):
        self.root = root
        self.dir = os.path.join(root, JOURNAL_DIR)
        if create and not os.path.isdir(self.dir):
            os.makedirs(self.dir, exist_ok=True)
            fsync_dir(os.path.abspath(root))
        self._lock = threading.Lock()          # seq + pending queue
        self._io_lock = threading.Lock()       # segment file handle
        self._commit_mutex = threading.Lock()  # at most one commit leader
        self._fh = None
        self._seg_bytes = 0
        self._pending: List[_Pending] = []
        self._widen = False
        self._closed = False
        self.group_ms = _to_float(config.JOURNAL_GROUP_MS, 2.0)
        self.segment_bytes = max(
            1 << 16, config.JOURNAL_SEGMENT_BYTES.to_int() or (8 << 20))
        self._seq = 0
        self.replayed = 0
        self._recover_segments()
        _JOURNALS.add(self)
        # process-wide pending-frame gauge (the /healthz journal section
        # carries the per-root breakdown via lag_snapshot)
        metrics.registry().gauge(
            metrics.JOURNAL_LAG,
            fn=lambda: float(sum(lag_snapshot().values())), replace=True)

    # -- write path --------------------------------------------------------
    def last_seq(self) -> int:
        with self._lock:
            return self._seq

    def lag(self) -> int:
        """Appended-but-not-yet-durable records (the /healthz gauge)."""
        with self._lock:
            return len(self._pending)

    def append(self, record: Dict[str, Any],
               blobs: Optional[List[bytes]] = None) -> int:
        """Frame + group-commit one record; BLOCKS until it is on disk
        (or raises :class:`JournalError`, in which case the caller must
        not ack the mutation). Returns the record's sequence number.

        ``blobs``: the sink list filled by :func:`enc_columns` /
        :func:`enc_value` — raw array bytes carried in the frame's blob
        section, referenced by the record's ``ndr`` markers."""
        if self._closed:
            raise JournalError("journal is closed")
        resilience.fault_point(
            "journal.append", kind=record.get("kind"),
            schema=record.get("schema"), root=self.root)
        blob = b"".join(blobs) if blobs else b""
        with self._lock:
            self._seq += 1
            record = dict(record)
            record["seq"] = self._seq
            seq = self._seq
            payload = json.dumps(record, separators=(",", ":")).encode()
            crc = zlib.crc32(blob, zlib.crc32(payload)) & 0xFFFFFFFF
            frame = _FRAME_HDR.pack(
                len(payload), len(blob), crc) + payload + blob
            p = _Pending(frame)
            self._pending.append(p)
        self._commit_or_follow(p)
        if p.error is not None:
            raise JournalError(
                f"journal append not durable: {p.error!r}") from p.error
        metrics.inc(metrics.JOURNAL_APPENDS)
        return seq

    def _commit_or_follow(self, p: _Pending) -> None:
        # Leader-based group commit: the first appender to take the commit
        # mutex drains the WHOLE pending queue into one write+fsync; frames
        # that arrive while a leader is inside fsync pile up and ride the
        # next leader's batch. Grouping thus emerges from fsync duration
        # itself — a lone writer runs at pure fsync speed with no thread
        # handoff — while the adaptive window (only opened after a batch
        # actually contained >1 frame, i.e. concurrency was observed)
        # lets bursty multi-writer load amortise further without taxing
        # single-writer latency with group_ms per append.
        while not p.event.is_set():
            if self._commit_mutex.acquire(timeout=0.05):
                try:
                    if p.event.is_set():
                        return
                    if self._widen and self.group_ms > 0:
                        time.sleep(self.group_ms / 1000.0)
                    with self._lock:
                        batch, self._pending = self._pending, []
                    if batch:
                        self._widen = len(batch) > 1
                        self._commit_batch(batch)
                finally:
                    self._commit_mutex.release()
            else:
                p.event.wait(timeout=0.05)

    def _commit_batch(self, batch: List[_Pending]) -> None:
        err: Optional[BaseException] = None
        t0 = time.perf_counter()
        try:
            with self._io_lock:
                self._ensure_segment(sum(len(p.frame) for p in batch))
                self._fh.write(b"".join(p.frame for p in batch))
                self._fh.flush()
                resilience.fault_point("journal.fsync", root=self.root,
                                       batch=len(batch))
                os.fsync(self._fh.fileno())
        except BaseException as e:  # waiters must never hang
            err = e
            # the segment tail state is unknown after a failed write or
            # fsync: roll to a fresh segment so later commits cannot
            # silently extend a torn one (replay truncates the tear)
            with self._io_lock:
                self._close_segment()
        fsync_s = time.perf_counter() - t0
        metrics.registry().histogram(
            metrics.JOURNAL_FSYNC_MS, metrics.JOURNAL_FSYNC_BUCKETS_MS,
            unit=None).observe(fsync_s * 1000.0)
        metrics.registry().histogram(
            metrics.JOURNAL_GROUP_SIZE, metrics.JOURNAL_GROUP_BUCKETS,
            unit=None).observe(float(len(batch)))
        for p in batch:
            p.error = err
            p.event.set()

    def _ensure_segment(self, nbytes: int) -> None:
        if self._fh is not None and \
                self._seg_bytes + nbytes > self.segment_bytes:
            self._close_segment()
        if self._fh is None:
            with self._lock:
                start = self._seq
            name = f"{_SEG_PREFIX}{start:016d}-{os.getpid()}{_SEG_SUFFIX}"
            path = os.path.join(self.dir, name)
            os.makedirs(self.dir, exist_ok=True)  # dir may have been swept
            self._fh = open(path, "ab")
            if self._fh.tell() == 0:
                self._fh.write(_SEG_MAGIC)
            self._fh.flush()
            os.fsync(self._fh.fileno())
            fsync_dir(self.dir)  # the segment's dir entry must be durable
            self._seg_bytes = self._fh.tell()

    def _close_segment(self) -> None:
        if self._fh is not None:
            try:
                self._fh.close()
            except OSError:
                pass
            self._fh = None
            self._seg_bytes = 0

    def close(self) -> None:
        self._closed = True
        with self._commit_mutex:
            with self._lock:
                batch, self._pending = self._pending, []
            if batch:
                self._commit_batch(batch)
            with self._io_lock:
                self._close_segment()

    # -- read / recovery path ----------------------------------------------
    def _segments(self) -> List[str]:
        try:
            names = os.listdir(self.dir)
        except OSError:
            return []
        segs = [n for n in names
                if n.startswith(_SEG_PREFIX) and n.endswith(_SEG_SUFFIX)]
        # (start seq, name) orders same-process segments by position and
        # breaks cross-process ties deterministically
        return [os.path.join(self.dir, n) for n in sorted(segs)]

    def _recover_segments(self) -> None:
        """Open-time hygiene: truncate torn tails NOW (before any append
        could extend past them) and adopt ``max(seq)`` so new records
        sequence after every durable one."""
        top = 0
        for path in self._segments():
            recs, good, total = _read_segment(path)
            if good < total:
                _truncate_segment(path, good, total)
            for r in recs:
                top = max(top, int(r.get("seq", 0)))
        self._seq = top

    def records(self, schema: Optional[str] = None, after_seq: int = 0,
                truncate: bool = False) -> List[Dict[str, Any]]:
        """All valid records in sequence order. ``truncate=True`` also
        repairs torn tails on disk (recovery); leave it False when
        reading a SHARED root another process may still be appending to —
        a half-written in-flight frame reads as a tail and is simply not
        returned, never damaged."""
        out: List[Dict[str, Any]] = []
        for path in self._segments():
            resilience.fault_point("journal.replay",
                                   segment=os.path.basename(path))
            recs, good, total = _read_segment(path)
            if good < total and truncate:
                _truncate_segment(path, good, total)
            out.extend(recs)
        if schema is not None:
            out = [r for r in out if r.get("schema") == schema]
        if after_seq:
            out = [r for r in out if int(r.get("seq", 0)) > after_seq]
        out.sort(key=lambda r: int(r.get("seq", 0)))
        return out

    def checkpoint(self, upto_seq: int) -> int:
        """Delete segments whose EVERY record has ``seq <= upto_seq``
        (they are fully covered by the checkpoint every schema just
        persisted). The active segment rolls first so it is eligible
        too. Returns bytes reclaimed."""
        with self._io_lock:
            self._close_segment()
            freed = 0
            for path in self._segments():
                recs, good, _total = _read_segment(path)
                if recs and max(int(r.get("seq", 0)) for r in recs) > upto_seq:
                    continue
                if not recs and good <= len(_SEG_MAGIC):
                    pass  # empty shell: always reclaimable
                try:
                    freed += os.path.getsize(path)
                    os.remove(path)
                except OSError:
                    continue
            if freed:
                fsync_dir(self.dir)
                metrics.registry().counter(
                    metrics.JOURNAL_TRUNCATED_BYTES).inc(freed)
        return freed

    # -- status (CLI / healthz) --------------------------------------------
    def status(self) -> Dict[str, Any]:
        segs = []
        n = 0
        for path in self._segments():
            recs, good, total = _read_segment(path)
            segs.append({
                "file": os.path.basename(path),
                "bytes": total,
                "records": len(recs),
                "seq_lo": min((int(r["seq"]) for r in recs), default=0),
                "seq_hi": max((int(r["seq"]) for r in recs), default=0),
                "torn_bytes": total - good,
            })
            n += len(recs)
        return {"dir": self.dir, "segments": segs, "records": n,
                "last_seq": self.last_seq(), "pending": self.lag()}


def _read_segment(path: str) -> Tuple[List[Dict[str, Any]], int, int]:
    """Parse one segment. Returns ``(records, last_good_offset,
    total_bytes)`` — a crc mismatch, truncated header, or short payload
    stops the parse at the last valid frame boundary (torn tail)."""
    try:
        with open(path, "rb") as fh:
            buf = fh.read()
    except OSError:
        return [], 0, 0
    total = len(buf)
    off = 0
    if buf[:len(_SEG_MAGIC)] == _SEG_MAGIC:
        off = len(_SEG_MAGIC)
    recs: List[Dict[str, Any]] = []
    good = off
    while off + _FRAME_HDR.size <= total:
        jln, bln, crc = _FRAME_HDR.unpack_from(buf, off)
        start = off + _FRAME_HDR.size
        end = start + jln + bln
        if jln <= 0 or bln < 0 or end > total:
            break
        if (zlib.crc32(buf[start:end]) & 0xFFFFFFFF) != crc:
            break
        try:
            rec = json.loads(buf[start:start + jln])
        except ValueError:
            break
        if bln:
            _attach_blobs(rec, buf[start + jln:end])
        recs.append(rec)
        off = end
        good = end
    return recs, good, total


def _truncate_segment(path: str, good: int, total: int) -> None:
    """Clip a torn tail at the last valid frame boundary (never fails the
    root — the partial frame was never acked, by the ack = durable
    ordering it could not have been)."""
    try:
        with open(path, "r+b") as fh:
            fh.truncate(good)
            fh.flush()
            os.fsync(fh.fileno())
    except OSError:
        return
    metrics.registry().counter(
        metrics.JOURNAL_TRUNCATED_BYTES).inc(max(total - good, 0))
    metrics.inc(metrics.JOURNAL_TORN_TAILS)


def _to_float(prop, default: float) -> float:
    try:
        v = prop.get()
        return default if v is None else float(v)
    except (TypeError, ValueError):
        return default


def journal_exists(root: str) -> bool:
    """True when ``root`` has a journal directory with segments (the
    load-time attach decision — no directory is ever created here)."""
    d = os.path.join(root, JOURNAL_DIR)
    try:
        return any(n.endswith(_SEG_SUFFIX) for n in os.listdir(d))
    except OSError:
        return False


def lag_snapshot() -> Dict[str, int]:
    """root -> pending (appended, not yet durable) records, across every
    live journal in the process — the /healthz journal section."""
    out: Dict[str, int] = {}
    for j in list(_JOURNALS):
        try:
            out[j.root] = j.lag()
        except Exception:
            continue
    return out
