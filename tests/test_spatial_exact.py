"""Exact spatial predicate parity over extent (line/polygon) columns.

The reference evaluates exact JTS predicates everywhere
(geomesa-filter/.../factory/FastFilterFactory.scala:395, relation ops in
geomesa-spark-jts/.../udf/SpatialRelationFunctions.scala). Here the dense
scan uses a coarse bbox mask and the executor refines coarse-true rows
against the host __wkt columns — these tests assert the end result matches
a brute-force geofn oracle exactly (no over- or under-selection).
"""

import numpy as np
import pytest

from geomesa_tpu import GeoDataset, geofn
from geomesa_tpu.utils import geometry as geo

N = 600


def _rand_lines(rng, n):
    """Short 3-vertex polylines around the test region."""
    out = []
    for _ in range(n):
        x0 = rng.uniform(-10, 10)
        y0 = rng.uniform(-10, 10)
        steps = rng.uniform(-1.5, 1.5, (2, 2))
        pts = np.cumsum(np.vstack([[x0, y0], steps]), axis=0)
        out.append(geo.LineString(pts))
    return out


def _rand_polys(rng, n):
    """Small random triangles/quads (star-convex, non-self-intersecting)."""
    out = []
    for _ in range(n):
        cx, cy = rng.uniform(-10, 10, 2)
        k = int(rng.integers(3, 6))
        ang = np.sort(rng.uniform(0, 2 * np.pi, k))
        r = rng.uniform(0.3, 1.6, k)
        xs = cx + r * np.cos(ang)
        ys = cy + r * np.sin(ang)
        ring = [(float(x), float(y)) for x, y in zip(xs, ys)]
        ring.append(ring[0])
        out.append(geo.Polygon(tuple(ring)))
    return out


def _mk_ds(geoms, typ):
    ds = GeoDataset(n_shards=4)
    ds.create_schema("t", f"tag:String,dtg:Date,*geom:{typ}")
    n = len(geoms)
    ds.insert(
        "t",
        {
            "tag": [f"r{i}" for i in range(n)],
            "dtg": np.full(n, np.datetime64("2021-06-01", "ms")),
            "geom": [g.wkt() for g in geoms],
        },
        fids=[f"f{i}" for i in range(n)],
    )
    ds.flush()
    return ds


LIT_POLY = "POLYGON ((-2 -2, 4 -1, 5 4, -1 5, -3 1, -2 -2))"
LIT_LINE = "LINESTRING (-8 -8, 0 0, 3 6, 9 2)"
OPS = {
    "INTERSECTS": lambda g, lit: geofn.st_intersects(g, lit),
    "DISJOINT": lambda g, lit: not geofn.st_intersects(g, lit),
    "WITHIN": lambda g, lit: geofn.st_within(g, lit),
    "CONTAINS": lambda g, lit: geofn.st_contains(g, lit),
    "CROSSES": lambda g, lit: geofn.st_crosses(g, lit),
    "OVERLAPS": lambda g, lit: geofn.st_overlaps(g, lit),
    "TOUCHES": lambda g, lit: geofn.st_touches(g, lit),
}


def _oracle_fids(geoms, op, lit):
    pred = OPS[op]
    return {f"f{i}" for i, g in enumerate(geoms) if bool(pred(g, lit))}


def _query_fids(ds, ecql):
    fc = ds.query("t", ecql)
    return set(fc.fids) if len(fc) else set()


@pytest.fixture(scope="module")
def line_ds():
    rng = np.random.default_rng(7)
    geoms = _rand_lines(rng, N)
    return _mk_ds(geoms, "LineString"), geoms


@pytest.fixture(scope="module")
def poly_ds():
    rng = np.random.default_rng(11)
    geoms = _rand_polys(rng, N)
    return _mk_ds(geoms, "Polygon"), geoms


@pytest.mark.parametrize("op", ["INTERSECTS", "DISJOINT", "WITHIN", "CROSSES"])
@pytest.mark.parametrize("lit_wkt", [LIT_POLY, LIT_LINE])
def test_line_column_exact(line_ds, op, lit_wkt):
    ds, geoms = line_ds
    lit = geo.parse_wkt(lit_wkt)
    got = _query_fids(ds, f"{op}(geom, {lit_wkt})")
    want = _oracle_fids(geoms, op, lit)
    assert got == want, (op, len(got), len(want))
    assert ds.count("t", f"{op}(geom, {lit_wkt})") == len(want)


@pytest.mark.parametrize(
    "op", ["INTERSECTS", "DISJOINT", "WITHIN", "CONTAINS", "OVERLAPS"]
)
def test_polygon_column_exact(poly_ds, op):
    ds, geoms = poly_ds
    lit = geo.parse_wkt(LIT_POLY)
    got = _query_fids(ds, f"{op}(geom, {LIT_POLY})")
    want = _oracle_fids(geoms, op, lit)
    assert got == want, (op, len(got), len(want))


def test_polygon_contains_point_literal(poly_ds):
    ds, geoms = poly_ds
    lit_wkt = "POINT (1 1)"
    lit = geo.parse_wkt(lit_wkt)
    got = _query_fids(ds, f"CONTAINS(geom, {lit_wkt})")
    want = _oracle_fids(geoms, "CONTAINS", lit)
    assert got == want
    assert want  # some triangle around origin should contain it


def test_negated_intersects_polarity(poly_ds):
    """NOT INTERSECTS == DISJOINT: the coarse mask must stay a superset
    under negation (subset/certain masks inside NOT)."""
    ds, geoms = poly_ds
    a = _query_fids(ds, f"NOT (INTERSECTS(geom, {LIT_POLY}))")
    b = _query_fids(ds, f"DISJOINT(geom, {LIT_POLY})")
    lit = geo.parse_wkt(LIT_POLY)
    want = _oracle_fids(geoms, "DISJOINT", lit)
    assert a == b == want


def test_compound_filter_with_refinement(line_ds):
    """Attribute predicate AND exact spatial over an extent column."""
    ds, geoms = line_ds
    lit = geo.parse_wkt(LIT_POLY)
    got = _query_fids(ds, f"tag = 'r5' AND INTERSECTS(geom, {LIT_POLY})")
    inter = _oracle_fids(geoms, "INTERSECTS", lit)
    assert got == ({"f5"} & inter)


def test_extent_dwithin_exact(line_ds):
    ds, geoms = line_ds
    ecql = "DWITHIN(geom, POINT(0 0), 200000, meters)"
    got = _query_fids(ds, ecql)
    want = {
        f"f{i}"
        for i, g in enumerate(geoms)
        if float(geofn.st_distanceSphere(g, geo.Point(0.0, 0.0))) <= 200000
    }
    assert got == want
    assert want and len(want) < N


def test_point_column_line_literal_exact():
    """INTERSECTS(point column, LINESTRING) is exact on-segment, not bbox."""
    rng = np.random.default_rng(3)
    n = 400
    xs = rng.uniform(-10, 10, n)
    ys = rng.uniform(-10, 10, n)
    # plant points exactly on the segment (0,0)->(6,6)
    on = rng.integers(0, n, 25)
    t = rng.uniform(0, 1, 25)
    xs[on] = 6 * t
    ys[on] = 6 * t
    ds = GeoDataset(n_shards=4)
    ds.create_schema("t", "dtg:Date,*geom:Point")
    ds.insert(
        "t",
        {
            "dtg": np.full(n, np.datetime64("2021-06-01", "ms")),
            "geom__x": xs,
            "geom__y": ys,
        },
        fids=[f"f{i}" for i in range(n)],
    )
    ds.flush()
    lit_wkt = "LINESTRING (0 0, 6 6)"
    got = _query_fids(ds, f"INTERSECTS(geom, {lit_wkt})")
    lit = geo.parse_wkt(lit_wkt)
    want = {
        f"f{i}"
        for i in range(n)
        if bool(geofn.st_intersects(lit, (np.array([xs[i]]), np.array([ys[i]])))[0])
    }
    assert got == want
    assert len(want) >= 25  # the planted points (bbox-only would over-select)
    bbox_count = int(((xs >= 0) & (xs <= 6) & (ys >= 0) & (ys <= 6)).sum())
    assert len(want) < bbox_count


def test_point_column_touches_polygon_boundary():
    """TOUCHES(point column, polygon) selects boundary points only."""
    ds = GeoDataset(n_shards=2)
    ds.create_schema("t", "dtg:Date,*geom:Point")
    xs = np.array([0.5, 0.0, 2.0, 1.0])  # inside, on edge, outside, on vertex
    ys = np.array([0.5, 0.5, 2.0, 1.0])
    ds.insert(
        "t",
        {
            "dtg": np.full(4, np.datetime64("2021-06-01", "ms")),
            "geom__x": xs,
            "geom__y": ys,
        },
        fids=["in", "edge", "out", "vertex"],
    )
    ds.flush()
    poly = "POLYGON ((0 0, 1 0, 1 1, 0 1, 0 0))"
    assert _query_fids(ds, f"TOUCHES(geom, {poly})") == {"edge", "vertex"}
    assert _query_fids(ds, f"WITHIN(geom, {poly})") == {"in"}
    assert _query_fids(ds, f"INTERSECTS(geom, {poly})") == {"in", "edge", "vertex"}


def test_density_respects_refinement(line_ds):
    """Aggregations must run on the refined mask, not the coarse superset."""
    ds, geoms = line_ds
    lit = geo.parse_wkt(LIT_POLY)
    want = len(_oracle_fids(geoms, "INTERSECTS", lit))
    grid = ds.density(
        "t", f"INTERSECTS(geom, {LIT_POLY})",
        bbox=(-12, -12, 12, 12), width=32, height=32,
    )
    assert int(round(float(grid.sum()))) == want


def test_wkt_full_precision_round_trip():
    """WKT is the master store for extents — formatting must round-trip f64
    exactly (the refinement pass parses it back)."""
    x = 100.12345678901234
    p = geo.Polygon(((x, 0.0), (x + 1, 0.0), (x + 1, 1.0), (x, 1.0), (x, 0.0)))
    q = geo.parse_wkt(p.wkt())
    assert q.bounds()[0] == x


def test_polygon_equals_self():
    ds = GeoDataset(n_shards=2)
    ds.create_schema("t", "dtg:Date,*geom:Polygon")
    wkt = "POLYGON ((100.12345678901 0, 101.2 0, 101.2 1.5, 100.12345678901 0))"
    ds.insert(
        "t",
        {"dtg": [np.datetime64("2021-06-01", "ms")], "geom": [wkt]},
        fids=["a"],
    )
    ds.flush()
    assert _query_fids(ds, f"EQUALS(geom, {wkt})") == {"a"}


def test_not_bbox_matches_not_intersects(line_ds):
    """NOT BBOX must agree with NOT INTERSECTS of the box polygon (exact
    BBOX semantics; loose-bbox is the opt-out)."""
    ds, geoms = line_ds
    box = "BBOX(geom, -2, -2, 3, 3)"
    poly = "POLYGON ((-2 -2, 3 -2, 3 3, -2 3, -2 -2))"
    assert _query_fids(ds, f"NOT ({box})") == _query_fids(
        ds, f"NOT (INTERSECTS(geom, {poly}))"
    )
    # positive direction too
    assert _query_fids(ds, box) == _query_fids(ds, f"INTERSECTS(geom, {poly})")


def test_point_contains_multipoint_literal():
    ds = GeoDataset(n_shards=2)
    ds.create_schema("t", "dtg:Date,*geom:Point")
    ds.insert(
        "t",
        {
            "dtg": np.full(2, np.datetime64("2021-06-01", "ms")),
            "geom__x": np.array([1.0, 2.0]),
            "geom__y": np.array([1.0, 2.0]),
        },
        fids=["a", "b"],
    )
    ds.flush()
    # a single point cannot contain two distinct points
    assert _query_fids(ds, "CONTAINS(geom, MULTIPOINT (1 1, 2 2))") == set()
    # but a degenerate single-point multipoint is fine
    assert _query_fids(ds, "CONTAINS(geom, MULTIPOINT (1 1))") == {"a"}
    assert _query_fids(ds, "EQUALS(geom, POINT (2 2))") == {"b"}


def test_stream_extent_geometry_query():
    """Streaming grid index must bucket extents by bbox, not centroid."""
    from geomesa_tpu.stream.live import StreamingDataset

    sd = StreamingDataset()
    sd.create_schema("s", "dtg:Date,*geom:Polygon")
    sd.write(
        "s",
        {
            "dtg": [np.datetime64("2021-06-01", "ms")],
            "geom": ["POLYGON ((0 0, 40 0, 40 40, 0 40, 0 0))"],
        },
        fids=["big"],
    )
    got = sd.query(
        "s",
        "INTERSECTS(geom, POLYGON ((0.5 0.5, 1.5 0.5, 1.5 1.5, 0.5 1.5, 0.5 0.5)))",
    )
    assert got.n == 1
