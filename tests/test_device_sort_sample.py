"""Device pushdown of sort+limit (top-k) and per-key sampling
(SortingSimpleFeatureIterator / SamplingIterator analogs — both
previously host-only post-passes)."""

import numpy as np
import pytest

from geomesa_tpu import GeoDataset
from geomesa_tpu.api.dataset import Query
from geomesa_tpu.filter.ecql import parse_iso_ms


@pytest.fixture
def ds_data():
    rng = np.random.default_rng(5)
    n = 40_000
    lo = parse_iso_ms("2020-01-01")
    hi = parse_iso_ms("2020-02-01")
    data = {
        "geom__x": rng.uniform(-120, -70, n),
        "geom__y": rng.uniform(25, 50, n),
        "dtg": rng.integers(lo, hi, n).astype("datetime64[ms]"),
        "weight": rng.uniform(0, 1, n).astype(np.float32),
        "kind": rng.choice(["a", "b", "c", "d"], n),
        "code": rng.integers(0, 50, n).astype(np.int32),
    }
    ds = GeoDataset(n_shards=4)
    ds.create_schema(
        "t", "weight:Float,kind:String,code:Integer,dtg:Date,*geom:Point"
    )
    ds.insert("t", data, fids=np.arange(n).astype(str))
    ds.flush("t")
    return ds, data


ECQL = "BBOX(geom, -100, 30, -80, 45)"


def _mask(data):
    x, y = data["geom__x"], data["geom__y"]
    return (x >= -100) & (x <= -80) & (y >= 30) & (y <= 45)


def test_topk_sorted_query_matches_host(ds_data):
    ds, data = ds_data
    m = _mask(data)
    for desc, k in ((True, 7), (False, 7), (True, 100)):
        out = ds.query("t", Query(ecql=ECQL, sort_by=[("weight", desc)],
                                  max_features=k))
        w = np.sort(data["weight"][m])
        want = w[::-1][:k] if desc else w[:k]
        np.testing.assert_allclose(
            out.columns["weight"], want, rtol=0, atol=0
        )


def test_topk_projection(ds_data):
    ds, data = ds_data
    out = ds.query("t", Query(ecql=ECQL, sort_by=[("weight", True)],
                              max_features=5, properties=["weight"]))
    assert len(out) == 5
    assert "weight" in out.columns


def test_sample_by_device_matches_host(ds_data, monkeypatch):
    ds, data = ds_data
    # string key (dictionary codes ride the device as int32)
    n_dev = ds.count("t", Query(ecql=ECQL, sampling=10, sample_by="kind"))
    monkeypatch.setenv("GEOMESA_COMPACT_ENABLED", "false")
    n_dev2 = ds.count("t", Query(ecql=ECQL, sampling=10, sample_by="kind"))
    monkeypatch.delenv("GEOMESA_COMPACT_ENABLED")
    assert n_dev == n_dev2
    # host oracle: per-key 1-in-10 over matched rows
    m = _mask(data)
    want = 0
    for kname in ("a", "b", "c", "d"):
        cnt = int((m & (data["kind"] == kname)).sum())
        want += -(-cnt // 10)
    assert n_dev == want


def test_sample_by_int_key(ds_data):
    ds, data = ds_data
    n_dev = ds.count("t", Query(ecql=ECQL, sampling=5, sample_by="code"))
    m = _mask(data)
    want = sum(
        -(-int((m & (data["code"] == c)).sum()) // 5)
        for c in np.unique(data["code"])
    )
    assert n_dev == want


def test_sample_by_null_keys(ds_data):
    """Null sample keys form their own group on both paths (host parity:
    DictionaryEncoder codes None as -1)."""
    ds, data = ds_data
    n = 5_000
    rng = np.random.default_rng(8)
    kinds = rng.choice(["x", None, "y"], n)
    d2 = {
        "geom__x": rng.uniform(-99, -81, n),
        "geom__y": rng.uniform(31, 44, n),
        "dtg": np.full(n, parse_iso_ms("2020-01-10")).astype("datetime64[ms]"),
        "weight": np.ones(n, np.float32),
        "kind": kinds,
        "code": np.zeros(n, np.int32),
    }
    ds2 = GeoDataset(n_shards=2)
    ds2.create_schema(
        "t", "weight:Float,kind:String,code:Integer,dtg:Date,*geom:Point"
    )
    ds2.insert("t", d2, fids=np.arange(n).astype(str))
    ds2.flush("t")
    got = ds2.count("t", Query(ecql="INCLUDE", sampling=7, sample_by="kind"))
    want = sum(
        -(-int((kinds == kname).sum()) // 7) for kname in ("x", "y")
    ) + -(-int(sum(k is None for k in kinds)) // 7)
    assert got == want


def test_string_sort_stays_on_host(ds_data):
    """ORDER BY a string column must rank lexicographically, not by
    dictionary code (insertion order) — so the device top-k declines."""
    ds, data = ds_data
    m = _mask(data)
    out = ds.query("t", Query(ecql=ECQL, sort_by=[("kind", False)],
                              max_features=5))
    st = ds._store("t")
    got = st.dicts["kind"].decode(out.columns["kind"])
    want = np.sort(data["kind"][m].astype(str))[:5]
    assert got == list(want)


def test_sample_by_float_falls_back_to_host(ds_data):
    ds, data = ds_data
    # float keys would merge distinct values at f32: host path, still exact
    n = ds.count("t", Query(ecql=ECQL, sampling=3, sample_by="weight"))
    m = _mask(data)
    want = sum(
        -(-int((m & (data["weight"] == w)).sum()) // 3)
        for w in np.unique(data["weight"][m])
    )
    assert n == want


def test_multikey_sort_device_pushdown(ds_data):
    """r5: multi-key sorts push the primary-key top-k selection to the
    device (threshold select + tie gather); order matches the host's
    full stable multi-key sort exactly, and the audit records the path."""
    ds, data = ds_data
    q = Query(ecql=ECQL, sort_by=[("weight", False), ("code", True)],
              max_features=500)
    fc = ds.query("t", q)
    # host oracle: full filter + lexicographic sort
    m = _mask(data)
    idx = np.nonzero(m)[0]
    order = np.lexsort((-data["code"][idx], data["weight"][idx]))
    want_vals = data["weight"][idx][order][:500]
    got = fc.batch.columns["weight"]
    assert len(got) == min(500, len(idx))
    assert np.allclose(np.asarray(got, np.float64), want_vals)
    ev = ds.audit.recent(1)[0]
    assert "device-topk" in str(ev.hints.get("exec_path", {}))


def test_large_k_threshold_select(ds_data):
    """k far beyond the old 32-row argmin gate ranks on device."""
    ds, data = ds_data
    q = Query(ecql=ECQL, sort_by=[("weight", True)], max_features=3000)
    fc = ds.query("t", q)
    m = _mask(data)
    want = np.sort(data["weight"][m].astype(np.float64))[::-1][:3000]
    assert np.allclose(
        np.asarray(fc.batch.columns["weight"], np.float64), want)


def test_sample_by_large_vocab_hash(ds_data):
    """r5: a 10k-vocab sample key runs the hash-bucketed device kernel
    (deterministic, ~1/n overall) and explain names the path."""
    rng = np.random.default_rng(3)
    n = 30_000
    ds2 = GeoDataset(n_shards=2)
    ds2.create_schema("big", "key:String,val:Double,*geom:Point")
    data = {
        "key": np.array([f"k{rng.integers(0, 10_000)}" for _ in range(n)],
                        dtype=object),
        "val": rng.uniform(0, 1, n),
        "geom__x": rng.uniform(-10, 10, n),
        "geom__y": rng.uniform(-10, 10, n),
    }
    ds2.insert("big", data, fids=np.arange(n).astype(str))
    ds2.flush()
    q = Query(ecql="INCLUDE", sampling=5, sample_by="key")
    got = ds2.count("big", q)
    # per-bucket ceil(matches/5) summed over 64 buckets: between n/5 and
    # n/5 + 64, and deterministic
    assert n / 5 <= got <= n / 5 + 64
    assert got == ds2.count("big", q)
    ex = ds2.explain("big", q, analyze=True)
    assert "sampling: hash" in ex and "Execution path" in ex


def test_sample_hash_modes_by_backend(ds_data):
    """Review r5: a host-only store (prefer_device=False) keeps the
    reference's EXACT per-key counter even for wide vocabularies (the
    hash approximation only buys anything when a device scan runs); a
    device-preferring store hash-buckets deterministically, and its own
    host fallback twin (_host_mask) uses the same buckets."""
    rng = np.random.default_rng(4)
    n = 8_000
    common = {
        "key": np.array([f"k{rng.integers(0, 5_000)}" for _ in range(n)],
                        dtype=object),
        "val": rng.uniform(0, 1, n),
        "geom__x": rng.uniform(-10, 10, n),
        "geom__y": rng.uniform(-10, 10, n),
    }
    q = Query(ecql="INCLUDE", sampling=7, sample_by="key")
    host = GeoDataset(n_shards=2, prefer_device=False)
    host.create_schema("p", "key:String,val:Double,*geom:Point")
    host.insert("p", common, fids=np.arange(n).astype(str))
    host.flush()
    # exact per-key: every distinct matched key keeps ceil(rows/7)
    keys, cnts = np.unique(common["key"], return_counts=True)
    exact_want = int(sum(-(-int(c) // 7) for c in cnts))
    assert host.count("p", q) == exact_want
    dev = GeoDataset(n_shards=2, prefer_device=True)
    dev.create_schema("p", "key:String,val:Double,*geom:Point")
    dev.insert("p", common, fids=np.arange(n).astype(str))
    dev.flush()
    got = dev.count("p", q)
    assert n / 7 <= got <= n / 7 + 64  # per-bucket counters
    assert got == dev.count("p", q)  # deterministic


def test_multikey_ties_at_boundary_small_k(ds_data):
    """Review r5: small-k multi-key sorts must include boundary ties
    (the argmin path would drop a tie that wins on the secondary key)."""
    ds, _ = ds_data
    rng = np.random.default_rng(9)
    n = 2000
    d2 = GeoDataset(n_shards=2)
    d2.create_schema("tie", "w:Float,c:Integer,*geom:Point")
    # heavy ties on the primary key
    w = rng.choice(np.array([1.0, 2.0, 3.0], np.float32), n)
    c = rng.integers(0, 1000, n).astype(np.int32)
    d2.insert("tie", {"w": w, "c": c,
                      "geom__x": rng.uniform(-10, 10, n),
                      "geom__y": rng.uniform(-10, 10, n)},
              fids=np.arange(n).astype(str))
    d2.flush()
    q = Query("INCLUDE", sort_by=[("w", False), ("c", False)],
              max_features=10)
    fc = d2.query("tie", q)
    order = np.lexsort((c, w))
    want_c = c[order][:10]
    assert np.array_equal(np.asarray(fc.batch.columns["c"]), want_c)


def test_underfilled_topk_falls_back(ds_data):
    """cnt < k (few matches) routes to the host full path, not a batch
    polluted with padding/masked rows."""
    ds, data = ds_data
    q = Query(ecql="weight > 0.999", sort_by=[("weight", True)],
              max_features=3000)
    fc = ds.query("t", q)
    m = data["weight"] > 0.999
    assert len(fc) == int(m.sum()) < 3000
    want = np.sort(data["weight"][m].astype(np.float64))[::-1]
    assert np.allclose(np.asarray(fc.batch.columns["weight"], np.float64),
                       want)


def test_partitioned_sorted_query_pushdown(tmp_path):
    """r5: sorted+limited queries on a PARTITIONED store push per-
    partition top-k candidate selection down instead of gathering every
    match; results match a flat store exactly."""
    from geomesa_tpu.filter.ecql import parse_iso_ms as iso

    rng = np.random.default_rng(11)
    n = 30_000
    data = {
        "geom__x": rng.uniform(-120, -70, n),
        "geom__y": rng.uniform(25, 50, n),
        "dtg": rng.integers(iso("2020-01-01"), iso("2020-03-01"), n
                            ).astype("datetime64[ms]"),
        "weight": rng.uniform(0, 1, n),
        "code": rng.integers(0, 50, n).astype(np.int32),
    }
    spec = "weight:Double,code:Integer,dtg:Date,*geom:Point"
    flat = GeoDataset(n_shards=2)
    flat.create_schema("t", spec)
    flat.insert("t", data, fids=np.arange(n).astype(str))
    flat.flush()
    part = GeoDataset(n_shards=2)
    part.create_schema("t", spec + ";geomesa.partition='time'")
    st = part._store("t")
    st.max_resident = 2
    st._spill_dir = str(tmp_path / "spill")
    part.insert("t", data, fids=np.arange(n).astype(str))
    part.flush()
    q = Query("BBOX(geom, -110, 28, -80, 48)",
              sort_by=[("weight", True), ("code", False)],
              max_features=800)
    a = flat.query("t", q).batch
    b = part.query("t", q).batch
    assert a.n == b.n == 800
    assert np.allclose(np.asarray(a.columns["weight"], np.float64),
                       np.asarray(b.columns["weight"], np.float64))
    assert np.array_equal(a.columns["code"], b.columns["code"])
    ev = part.audit.recent(1)[0]
    assert "device-topk" in str(ev.hints.get("exec_path", {}))


def test_partitioned_string_sort_not_stamped_as_pushdown(tmp_path):
    """Review r5: when every partition declines device selection (string
    sort key), the audit must NOT claim device-topk."""
    from geomesa_tpu.filter.ecql import parse_iso_ms as iso

    rng = np.random.default_rng(13)
    n = 4000
    ds = GeoDataset(n_shards=2)
    ds.create_schema(
        "t", "kind:String,dtg:Date,*geom:Point;geomesa.partition='time'")
    st = ds._store("t")
    st._spill_dir = str(tmp_path / "spill")
    data = {
        "kind": rng.choice(["a", "b", "c"], n),
        "dtg": rng.integers(iso("2020-01-01"), iso("2020-03-01"), n
                            ).astype("datetime64[ms]"),
        "geom__x": rng.uniform(-10, 10, n),
        "geom__y": rng.uniform(-10, 10, n),
    }
    ds.insert("t", data, fids=np.arange(n).astype(str))
    ds.flush()
    fc = ds.query("t", Query("INCLUDE", sort_by=[("kind", False)],
                             max_features=5))
    got = st.dicts["kind"].decode(fc.batch.columns["kind"])
    assert got == sorted(data["kind"].astype(str))[:5]
    ev = ds.audit.recent(1)[0]
    assert "device-topk" not in str(ev.hints.get("exec_path", {}))


def test_pallas_uneven_mesh_fallback_is_recorded(monkeypatch):
    """r5: the use_pallas_sharded uneven-mesh XLA fallback (previously
    silent, pallas_kernels.py gate) leaves a dispatch record."""
    import jax
    from jax.sharding import Mesh

    from geomesa_tpu.kernels import pallas_kernels as pk

    monkeypatch.setattr(pk, "_backend_ok", lambda: True)
    mesh = Mesh(np.array(jax.devices()[:8]), axis_names=("shard",))
    pk.take_dispatch()  # drain
    assert pk.use_pallas_sharded(mesh, 16, kernel="pip")  # even: no record
    assert pk.take_dispatch() == {}
    # bare capability probes stay side-effect-free
    assert not pk.use_pallas_sharded(mesh, 7)
    assert pk.take_dispatch() == {}
    # named refusal is recorded
    assert not pk.use_pallas_sharded(mesh, 7, kernel="pip")
    d = pk.take_dispatch()
    assert "xla-fallback" in d["pip"] and "7 rows" in d["pip"]
