"""Device pushdown of sort+limit (top-k) and per-key sampling
(SortingSimpleFeatureIterator / SamplingIterator analogs — both
previously host-only post-passes)."""

import numpy as np
import pytest

from geomesa_tpu import GeoDataset
from geomesa_tpu.api.dataset import Query
from geomesa_tpu.filter.ecql import parse_iso_ms


@pytest.fixture
def ds_data():
    rng = np.random.default_rng(5)
    n = 40_000
    lo = parse_iso_ms("2020-01-01")
    hi = parse_iso_ms("2020-02-01")
    data = {
        "geom__x": rng.uniform(-120, -70, n),
        "geom__y": rng.uniform(25, 50, n),
        "dtg": rng.integers(lo, hi, n).astype("datetime64[ms]"),
        "weight": rng.uniform(0, 1, n).astype(np.float32),
        "kind": rng.choice(["a", "b", "c", "d"], n),
        "code": rng.integers(0, 50, n).astype(np.int32),
    }
    ds = GeoDataset(n_shards=4)
    ds.create_schema(
        "t", "weight:Float,kind:String,code:Integer,dtg:Date,*geom:Point"
    )
    ds.insert("t", data, fids=np.arange(n).astype(str))
    ds.flush("t")
    return ds, data


ECQL = "BBOX(geom, -100, 30, -80, 45)"


def _mask(data):
    x, y = data["geom__x"], data["geom__y"]
    return (x >= -100) & (x <= -80) & (y >= 30) & (y <= 45)


def test_topk_sorted_query_matches_host(ds_data):
    ds, data = ds_data
    m = _mask(data)
    for desc, k in ((True, 7), (False, 7), (True, 100)):
        out = ds.query("t", Query(ecql=ECQL, sort_by=[("weight", desc)],
                                  max_features=k))
        w = np.sort(data["weight"][m])
        want = w[::-1][:k] if desc else w[:k]
        np.testing.assert_allclose(
            out.columns["weight"], want, rtol=0, atol=0
        )


def test_topk_projection(ds_data):
    ds, data = ds_data
    out = ds.query("t", Query(ecql=ECQL, sort_by=[("weight", True)],
                              max_features=5, properties=["weight"]))
    assert len(out) == 5
    assert "weight" in out.columns


def test_sample_by_device_matches_host(ds_data, monkeypatch):
    ds, data = ds_data
    # string key (dictionary codes ride the device as int32)
    n_dev = ds.count("t", Query(ecql=ECQL, sampling=10, sample_by="kind"))
    monkeypatch.setenv("GEOMESA_COMPACT_ENABLED", "false")
    n_dev2 = ds.count("t", Query(ecql=ECQL, sampling=10, sample_by="kind"))
    monkeypatch.delenv("GEOMESA_COMPACT_ENABLED")
    assert n_dev == n_dev2
    # host oracle: per-key 1-in-10 over matched rows
    m = _mask(data)
    want = 0
    for kname in ("a", "b", "c", "d"):
        cnt = int((m & (data["kind"] == kname)).sum())
        want += -(-cnt // 10)
    assert n_dev == want


def test_sample_by_int_key(ds_data):
    ds, data = ds_data
    n_dev = ds.count("t", Query(ecql=ECQL, sampling=5, sample_by="code"))
    m = _mask(data)
    want = sum(
        -(-int((m & (data["code"] == c)).sum()) // 5)
        for c in np.unique(data["code"])
    )
    assert n_dev == want


def test_sample_by_null_keys(ds_data):
    """Null sample keys form their own group on both paths (host parity:
    DictionaryEncoder codes None as -1)."""
    ds, data = ds_data
    n = 5_000
    rng = np.random.default_rng(8)
    kinds = rng.choice(["x", None, "y"], n)
    d2 = {
        "geom__x": rng.uniform(-99, -81, n),
        "geom__y": rng.uniform(31, 44, n),
        "dtg": np.full(n, parse_iso_ms("2020-01-10")).astype("datetime64[ms]"),
        "weight": np.ones(n, np.float32),
        "kind": kinds,
        "code": np.zeros(n, np.int32),
    }
    ds2 = GeoDataset(n_shards=2)
    ds2.create_schema(
        "t", "weight:Float,kind:String,code:Integer,dtg:Date,*geom:Point"
    )
    ds2.insert("t", d2, fids=np.arange(n).astype(str))
    ds2.flush("t")
    got = ds2.count("t", Query(ecql="INCLUDE", sampling=7, sample_by="kind"))
    want = sum(
        -(-int((kinds == kname).sum()) // 7) for kname in ("x", "y")
    ) + -(-int(sum(k is None for k in kinds)) // 7)
    assert got == want


def test_string_sort_stays_on_host(ds_data):
    """ORDER BY a string column must rank lexicographically, not by
    dictionary code (insertion order) — so the device top-k declines."""
    ds, data = ds_data
    m = _mask(data)
    out = ds.query("t", Query(ecql=ECQL, sort_by=[("kind", False)],
                              max_features=5))
    st = ds._store("t")
    got = st.dicts["kind"].decode(out.columns["kind"])
    want = np.sort(data["kind"][m].astype(str))[:5]
    assert got == list(want)


def test_sample_by_float_falls_back_to_host(ds_data):
    ds, data = ds_data
    # float keys would merge distinct values at f32: host path, still exact
    n = ds.count("t", Query(ecql=ECQL, sampling=3, sample_by="weight"))
    m = _mask(data)
    want = sum(
        -(-int((m & (data["weight"] == w)).sum()) // 3)
        for w in np.unique(data["weight"][m])
    )
    assert n == want
