"""Randomized converter differential test: generated CSV/JSON inputs
with injected malformations (bad numbers, bad dates, short rows,
quoting) must convert with EXACTLY the oracle's good/bad row split, and
every successfully-converted value must round-trip bit-exactly into the
dataset. Ingest is where silent corruption enters a store — the fuzz
pins the error-isolation contract (one bad row never skews its
neighbors)."""

pytestmark = __import__("pytest").mark.fuzz

import json

import numpy as np
import pytest

from geomesa_tpu import GeoDataset

SPEC = "name:String,age:Integer,w:Double,dtg:Date,*geom:Point"

CSV_CONFIG = {
    "type": "delimited-text",
    "format": "CSV",
    "id-field": "$1",
    "options": {"skip-lines": 1, "error-mode": "skip-bad-records"},
    "fields": [
        {"name": "name", "transform": "trim($2)"},
        {"name": "age", "transform": "toInt($3)"},
        {"name": "w", "transform": "toDouble($4)"},
        {"name": "dtg", "transform": "date('yyyy-MM-dd', $5)"},
        {"name": "geom", "transform": "point(toDouble($6), toDouble($7))"},
    ],
}

JSON_CONFIG = {
    "type": "json",
    "feature-path": "$.rows[*]",
    "id-field": "$fid",
    "options": {"error-mode": "skip-bad-records"},
    "fields": [
        {"name": "fid", "path": "$.id"},
        {"name": "name", "path": "$.name"},
        {"name": "age_raw", "path": "$.age"},
        {"name": "age", "transform": "toInt($age_raw)"},
        {"name": "w_raw", "path": "$.w"},
        {"name": "w", "transform": "toDouble($w_raw)"},
        {"name": "d_raw", "path": "$.d"},
        {"name": "dtg", "transform": "date('yyyy-MM-dd', $d_raw)"},
        {"name": "x", "path": "$.x"},
        {"name": "y", "path": "$.y"},
        {"name": "geom", "transform": "point($x, $y)"},
    ],
}


def _rand_rows(rng, n):
    """(csv_lines, json_rows, good_flags, values). A row is 'bad' when a
    typed field cannot parse."""
    lines, jrows, good, vals = [], [], [], []
    for i in range(n):
        name = ["ann", "bo b", "c,d", "efg"][rng.integers(0, 4)]
        age = int(rng.integers(0, 99))
        w = round(float(rng.uniform(-5, 5)), 3)
        day = int(rng.integers(1, 28))
        x = round(float(rng.uniform(-170, 170)), 3)
        y = round(float(rng.uniform(-80, 80)), 3)
        corrupt = rng.integers(0, 9)  # 0-4 = clean
        age_s, w_s, d_s = str(age), repr(w), f"2020-01-{day:02d}"
        is_good = True
        if corrupt == 5:
            age_s, is_good = "NaNish", False
        elif corrupt == 6:
            d_s, is_good = "01/2020/99", False
        elif corrupt == 7:
            w_s, is_good = "", False
        elif corrupt == 8:
            # MULTIPLE bad fields in one row must count as ONE failed
            # record, not one per field (fuzz-found converter bug, r5)
            age_s, w_s, d_s, is_good = "bad", "also-bad", "nope", False
        q = f'"{name}"' if "," in name else name
        lines.append(f"r{i},{q},{age_s},{w_s},{d_s},{x},{y}")
        jrows.append({"id": f"r{i}", "name": name, "age": age_s,
                      "w": w_s if w_s else None, "d": d_s, "x": x, "y": y})
        good.append(is_good)
        vals.append((f"r{i}", name, age, w, f"2020-01-{day:02d}", x, y))
    return lines, jrows, good, vals


@pytest.mark.parametrize("fmt", ["csv", "json"])
def test_random_malformed_inputs(fmt):
    rng = np.random.default_rng(808)
    for case in range(8):
        n = int(rng.integers(20, 60))
        lines, jrows, good, vals = _rand_rows(rng, n)
        ds = GeoDataset(n_shards=1, prefer_device=False)
        ds.create_schema("t", SPEC)
        if fmt == "csv":
            src = "id,name,age,w,date,lon,lat\n" + "\n".join(lines) + "\n"
            ctx = ds.ingest("t", src, CSV_CONFIG)
        else:
            src = json.dumps({"rows": jrows})
            ctx = ds.ingest("t", src, JSON_CONFIG)
        want_good = sum(good)
        assert ctx.success == want_good, (fmt, case, ctx.errors[:3])
        assert ctx.failure == n - want_good, (fmt, case)
        assert ds.count("t") == want_good
        if want_good == 0:
            continue
        # every good row round-trips exactly; bad neighbors don't skew it
        fc = ds.query("t", "INCLUDE")
        d = fc.to_dict()
        got = {fid: (nm, a, ww, dd, gg) for fid, nm, a, ww, dd, gg in zip(
            fc.fids, d["name"], d["age"], d["w"], d["dtg"], d["geom"])}
        for (fid, nm, a, ww, ds_, x, y), g in zip(vals, good):
            if not g:
                assert fid not in got, (fmt, case, fid)
                continue
            gnm, ga, gw, gd, gg = got[fid]
            assert gnm == nm.strip() and ga == a, (fmt, case, fid)
            assert gw == ww, (fmt, case, fid)  # f64 exact, not approx
            assert str(np.datetime64(gd, "D")) == ds_, (fmt, case, fid)
            assert gg[0] == pytest.approx(x, abs=5e-7)  # f32 coord store
            assert gg[1] == pytest.approx(y, abs=5e-7), (fmt, case, fid)
