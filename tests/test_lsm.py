"""LSM append path: incremental flushes must be indistinguishable from one
bulk load across every index family."""

import numpy as np
import pytest

from geomesa_tpu import GeoDataset
from geomesa_tpu.api.dataset import Query

SPEC = "name:String:index=true,v:Integer:index=true,dtg:Date,*geom:Point"


def _data(n, seed):
    rng = np.random.default_rng(seed)
    return {
        "geom__x": rng.uniform(-120, -70, n),
        "geom__y": rng.uniform(25, 50, n),
        "dtg": rng.integers(1577836800000, 1585699200000, n).astype("datetime64[ms]"),
        "name": rng.choice(["a", "b", "c", "d"], n),
        "v": rng.integers(0, 1000, n),
    }


def test_incremental_equals_bulk():
    n = 6000
    data = _data(n, 0)
    fids = np.array([f"f{i}" for i in range(n)])

    bulk = GeoDataset(n_shards=4)
    bulk.create_schema("t", SPEC)
    bulk.insert("t", data, fids=fids)
    bulk.flush("t")

    inc = GeoDataset(n_shards=4)
    inc.create_schema("t", SPEC)
    for s in range(0, n, 1000):  # six incremental flushes
        e = s + 1000
        inc.insert("t", {k: v[s:e] for k, v in data.items()}, fids=fids[s:e])
        inc.flush("t")

    queries = [
        "BBOX(geom, -100, 30, -80, 45)",
        "BBOX(geom, -100, 30, -80, 45) AND "
        "dtg DURING 2020-01-10T00:00:00Z/2020-02-20T00:00:00Z",
        "name = 'a'",
        "v BETWEEN 100 AND 300",
        "IN ('f5', 'f4999', 'f17')",
        "INTERSECTS(geom, POLYGON ((-110 28, -75 28, -75 48, -110 48, -110 28)))",
    ]
    for q in queries:
        cb, ci = bulk.count("t", q), inc.count("t", q)
        assert cb == ci, (q, cb, ci)
        fb = sorted(bulk.query("t", q).to_dict()["__fid__"])
        fi = sorted(inc.query("t", q).to_dict()["__fid__"])
        assert fb == fi, q
    # per-index table invariants: sorted keys, full coverage, no dupes
    for name, table in inc._store("t").tables.items():
        assert table.n == n
        assert len(np.unique(table.order)) == n
        for k, col in table.key_columns.items():
            if col.dtype.kind in ("O", "U"):
                assert all(col[i] <= col[i + 1] for i in range(len(col) - 1))
        # (bin, key) pair tables: verify lexicographic order
        kc = list(table.keyspace.key_cols)
        if len(kc) == 2 and all(c in table.key_columns for c in kc):
            b = table.key_columns[kc[0]]
            z = table.key_columns[kc[1]]
            assert (np.diff(b.astype(np.int64)) >= 0).all()
            same = b[1:] == b[:-1]
            assert (z[1:][same] >= z[:-1][same]).all()
        elif len(kc) == 1 and kc[0] in table.key_columns:
            col = table.key_columns[kc[0]]
            if col.dtype.kind not in ("O", "U"):
                assert (np.diff(col.astype(np.float64)) >= 0).all()


def test_incremental_stats_match_bulk():
    n = 4000
    data = _data(n, 1)
    fids = np.array([f"f{i}" for i in range(n)])
    bulk = GeoDataset(n_shards=2)
    bulk.create_schema("t", SPEC)
    bulk.insert("t", data, fids=fids)
    bulk.flush("t")
    inc = GeoDataset(n_shards=2)
    inc.create_schema("t", SPEC)
    for s in range(0, n, 500):
        inc.insert("t", {k: v[s:s + 500] for k, v in data.items()},
                   fids=fids[s:s + 500])
        inc.flush("t")
    zb = bulk.z3_histogram("t")
    zi = inc.z3_histogram("t")
    assert set(zb.bins) == set(zi.bins)
    for k in zb.bins:
        np.testing.assert_array_equal(zb.bins[k], zi.bins[k])
    assert bulk.min_max("t", "v", exact=False) == inc.min_max("t", "v", exact=False)


def test_append_after_delete():
    n = 2000
    data = _data(n, 2)
    ds = GeoDataset(n_shards=2)
    ds.create_schema("t", SPEC)
    ds.insert("t", data, fids=np.array([f"f{i}" for i in range(n)]))
    ds.flush("t")
    removed = ds.delete_features("t", "v < 500")
    assert 0 < removed < n
    # append after a delete: cached key columns were filtered by the delete
    extra = _data(300, 3)
    ds.insert("t", extra, fids=np.array([f"x{i}" for i in range(300)]))
    ds.flush("t")
    assert ds.count("t") == n - removed + 300
    want = int((extra["v"] >= 500).sum()) + 0  # originals with v<500 removed
    assert ds.count("t", "v < 500") == int((extra["v"] < 500).sum())
