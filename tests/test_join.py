"""TPU-native spatial joins (docs/JOIN.md): SFC co-partitioned
build/probe with a bucketed pairwise kernel.

The load-bearing guarantee is BIT-IDENTITY against the naive numpy N*M
reference (``kernels.join.brute_force_pairs``): the co-partition decides
only WHICH pairs are tested, never how a tested pair decides — both
sides run the identical f32 ``pair_mask`` arithmetic. Covered: both
predicates (incl. cell-edge / inclusive-equality pairs, empty cells,
strip-only matches), a seeded property walk across store epochs, the
sharded 8-virtual-device path (conftest forces 8 CPU devices),
degradation with exact survivor totals, the recompile-free repeat proof,
and the explain/audit shapes.

Satellite coverage rides along (one PR, one file): distinct-filter
density_curve batching, speculative density/stats answers, join_count
repeat fusion, and the content-addressed compact-descriptor share.
"""

import numpy as np
import pytest

from geomesa_tpu import GeoDataset, config, metrics, resilience
from geomesa_tpu.kernels import join as kjoin
from geomesa_tpu.planning import join_exec


def _clustered(rng, n, n_hot=12, spread=0.4, lo=-60, hi=60):
    cx = rng.uniform(lo, hi, n_hot)
    cy = rng.uniform(lo / 2, hi / 2, n_hot)
    k = rng.integers(0, n_hot, n)
    return (np.clip(cx[k] + rng.normal(0, spread, n), -179, 179),
            np.clip(cy[k] + rng.normal(0, spread, n), -89, 89))


def _mkds(seed=7, na=1500, nb=1200):
    ds = GeoDataset()
    ds.create_schema("a", "name:String,*geom:Point")
    ds.create_schema("b", "tag:String,*geom:Point")
    rng = np.random.default_rng(seed)
    ax, ay = _clustered(rng, na)
    bx, by = _clustered(rng, nb)
    ds.insert("a", {"name": [f"n{i % 5}" for i in range(na)],
                    "geom": list(zip(ax, ay))})
    ds.insert("b", {"tag": [f"t{i % 3}" for i in range(nb)],
                    "geom": list(zip(bx, by))})
    ds.flush()
    return ds


def _ref(ds, predicate, left="a", right="b", lq="INCLUDE", rq="INCLUDE",
         **kw):
    p0, p1 = kjoin.pair_params(predicate, **kw)
    lfc, rfc = ds.query(left, lq), ds.query(right, rq)
    lx, ly = lfc.batch.columns["geom__x"], lfc.batch.columns["geom__y"]
    rx, ry = rfc.batch.columns["geom__x"], rfc.batch.columns["geom__y"]
    if predicate == kjoin.JOIN_DWITHIN_METERS:
        lux, luy, luz = kjoin.unit_vectors(lx, ly)
        rux, ruy, ruz = kjoin.unit_vectors(rx, ry)
        return kjoin.brute_force_pairs(
            lux, luy, rux, ruy, predicate, p0, p1, lz=luz, rz=ruz,
        )
    return kjoin.brute_force_pairs(lx, ly, rx, ry, predicate, p0, p1)


# ---------------------------------------------------------------------------
# bit-identity vs brute force
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("predicate,kw", [
    ("dwithin", {"distance": 0.35}),
    ("bbox", {"dx": 0.25, "dy": 0.15}),
    ("dwithin_meters", {"distance": 30_000.0}),
])
def test_join_bit_identical_vs_brute_force(predicate, kw):
    ds = _mkds()
    res = ds.join("a", "b", predicate=predicate, **kw)
    ref = _ref(ds, predicate, **kw)
    assert res.count == len(ref)
    assert np.array_equal(res.pairs, ref)
    assert ds.join_count("a", "b", predicate=predicate, **kw) == len(ref)
    # the grid filter actually pruned on the clustered layout
    assert res.stats.candidate_fraction < 0.2
    assert res.stats.cells_joint > 0


def test_join_device_matches_host_path():
    ds_dev = _mkds(seed=21)
    ds_host = _mkds(seed=21)
    ds_host.prefer_device = False
    for predicate, kw in (("dwithin", {"distance": 0.3}),
                          ("bbox", {"dx": 0.2, "dy": 0.2}),
                          ("dwithin_meters", {"distance": 25_000.0})):
        a = ds_dev.join("a", "b", predicate=predicate, **kw)
        b = ds_host.join("a", "b", predicate=predicate, **kw)
        assert a.count == b.count
        assert np.array_equal(a.pairs, b.pairs)


def test_join_dwithin_meters_antimeridian_and_pole():
    """The great-circle predicate matches across lon ±180 and over the
    pole — the strip machinery's modular lon windows and full-wrap
    high-latitude reach must probe those cells (planar predicates never
    face this: |lx-rx| does not wrap)."""
    ds = GeoDataset()
    ds.create_schema("a", "name:String,*geom:Point")
    ds.create_schema("b", "tag:String,*geom:Point")
    rng = np.random.default_rng(11)
    n = 400
    # half the rows hug the antimeridian (both signs), a band sits near
    # the north pole, the rest scatter mid-latitudes
    def side(seed):
        r = np.random.default_rng(seed)
        lon = np.concatenate([
            r.uniform(179.0, 180.0, n // 4),
            r.uniform(-180.0, -179.0, n // 4),
            r.uniform(-170.0, 170.0, n // 4),
            r.uniform(-180.0, 180.0, n - 3 * (n // 4)),
        ])
        lat = np.concatenate([
            r.uniform(55.0, 60.0, n // 4),
            r.uniform(55.0, 60.0, n // 4),
            r.uniform(-45.0, 45.0, n // 4),
            r.uniform(88.5, 90.0, n - 3 * (n // 4)),
        ])
        return lon, lat
    ax, ay = side(1)
    bx, by = side(2)
    ds.insert("a", {"name": ["x"] * n, "geom": list(zip(ax, ay))})
    ds.insert("b", {"tag": ["y"] * n, "geom": list(zip(bx, by))})
    ds.flush()
    for d in (20_000.0, 150_000.0):
        res = ds.join("a", "b", predicate="dwithin_meters", distance=d)
        ref = _ref(ds, "dwithin_meters", distance=d)
        assert res.count == len(ref)
        assert np.array_equal(res.pairs, ref)
        # cross-antimeridian pairs actually exist in this layout (the
        # test would vacuously pass without them)
        lons = ax[ref[:, 0]], bx[ref[:, 1]]
        assert (np.abs(lons[0] - lons[1]) > 300).any()
    # explain_join(analyze=True) shares run_join's operand dispatch —
    # dwithin_meters analyzes on unit vectors, counting identically
    ex = ds.explain_join("a", "b", predicate="dwithin_meters",
                         distance=150_000.0, analyze=True)
    want = ds.join_count("a", "b", predicate="dwithin_meters",
                         distance=150_000.0)
    assert f"matched (analyze): {want}" in ex


def test_join_dwithin_meters_inclusive_edge_exact():
    """A pair at EXACTLY the f32 chord threshold decides inclusively —
    and identically — in kernel and reference (the <= contract)."""
    # two points d meters apart along the equator: arc == lon delta
    d = 10_000.0
    ddeg = np.degrees(d / kjoin.EARTH_RADIUS_M)
    ds = GeoDataset()
    ds.create_schema("a", "name:String,*geom:Point")
    ds.create_schema("b", "tag:String,*geom:Point")
    ds.insert("a", {"name": ["p"], "geom": [(10.0, 0.0)]})
    ds.insert("b", {"tag": ["q", "r"],
                    "geom": [(10.0 + ddeg, 0.0), (10.0 + 3 * ddeg, 0.0)]})
    ds.flush()
    res = ds.join("a", "b", predicate="dwithin_meters", distance=d)
    ref = _ref(ds, "dwithin_meters", distance=d)
    assert np.array_equal(res.pairs, ref)
    assert res.count == len(ref) <= 1  # the 3d point never matches


def test_join_cell_edge_and_inclusive_equality_pairs():
    """Pairs straddling SFC cell edges (strip-only matches) and pairs at
    EXACTLY the predicate distance (inclusive <=) must decide like the
    reference."""
    ds = GeoDataset()
    ds.create_schema("a", "*geom:Point")
    ds.create_schema("b", "*geom:Point")
    # level-whatever cell edges sit at dyadic lon/lat values: 11.25 is an
    # edge at level 5 (360/32), 0.0 at every level. d = 0.25 is exact in
    # f32, so dist == d pairs exercise the inclusive boundary.
    d = 0.25
    left = [(11.25 - 0.01, 5.0), (0.0, 0.0), (-45.0, -22.5), (170.0, 80.0)]
    right = [(11.25 + 0.01, 5.0),           # strip-only: cells differ
             (d, 0.0),                      # exactly d away (inclusive)
             (-45.0 + d, -22.5),            # exactly d, across an edge
             (10.0, 10.0)]                  # matches nothing
    ds.insert("a", {"geom": left})
    ds.insert("b", {"geom": right})
    res = ds.join("a", "b", predicate="dwithin", distance=d)
    ref = _ref(ds, "dwithin", distance=d)
    assert np.array_equal(res.pairs, ref)
    assert res.count == len(ref) >= 3
    # the strip actually carried a match: the (0) pair's cells differ
    assert res.stats.strip_entries > 0


def test_join_empty_cells_and_disjoint_sides():
    ds = GeoDataset()
    ds.create_schema("a", "*geom:Point")
    ds.create_schema("b", "*geom:Point")
    rng = np.random.default_rng(3)
    ds.insert("a", {"geom": list(zip(rng.uniform(-60, -40, 300),
                                     rng.uniform(-30, -10, 300)))})
    ds.insert("b", {"geom": list(zip(rng.uniform(40, 60, 300),
                                     rng.uniform(10, 30, 300)))})
    res = ds.join("a", "b", predicate="dwithin", distance=0.5)
    assert res.count == 0 and len(res.pairs) == 0
    assert res.stats.cells_joint == 0
    assert res.stats.candidate_pairs == 0


def test_join_filtered_sides_and_streaming_batches():
    ds = _mkds(seed=9)
    lq = "BBOX(geom, -60, -30, 20, 30)"
    res = ds.join_spatial("a", "b", predicate="bbox", dx=0.3, dy=0.3,
                          left_query=lq)
    ref = _ref(ds, "bbox", lq=lq, dx=0.3, dy=0.3)
    assert res.count == len(ref)
    assert np.array_equal(res.pairs, ref)
    # streaming: chunks tile the pair set in order, right cols prefixed
    rows = 0
    for b in res.batches(batch_rows=97):
        assert b.n <= 97
        assert "right.geom__x" in b.columns and "geom__x" in b.columns
        rows += b.n
    assert rows == res.count


def test_join_rejects_non_point_and_missing_params():
    ds = GeoDataset()
    ds.create_schema("pt", "*geom:Point")
    ds.create_schema("ln", "*geom:LineString")
    with pytest.raises(ValueError, match="POINT"):
        ds.join("pt", "ln", predicate="dwithin", distance=1.0)
    with pytest.raises(ValueError):
        ds.join("pt", "pt", predicate="dwithin")  # no distance
    with pytest.raises(ValueError):
        ds.join("pt", "pt", predicate="nope", distance=1.0)
    with pytest.raises(ValueError):
        ds.join("pt", "pt")  # neither attrs nor predicate


# ---------------------------------------------------------------------------
# seeded property walk across epochs + recompile-free repeats
# ---------------------------------------------------------------------------


def test_join_property_walk_across_epochs_recompile_free():
    """Mutate the store across epochs (appends of the same batch size);
    every epoch's join must match brute force AND pay zero fresh traces
    after the first epoch warmed the shape buckets."""
    ds = _mkds(seed=31, na=900, nb=800)
    rng = np.random.default_rng(77)
    reg = join_exec.join_registry()
    ds.join_count("a", "b", predicate="dwithin", distance=0.3)  # warm
    warm = sum(reg.traces().values())
    for epoch in range(3):
        nx, ny = _clustered(rng, 100)
        ds.insert("a", {"name": ["m"] * 100, "geom": list(zip(nx, ny))})
        nx, ny = _clustered(rng, 100)
        ds.insert("b", {"tag": ["m"] * 100, "geom": list(zip(nx, ny))})
        ds.flush()
        for predicate, kw in (("dwithin", {"distance": 0.3}),
                              ("bbox", {"dx": 0.2, "dy": 0.25})):
            res = ds.join("a", "b", predicate=predicate, **kw)
            ref = _ref(ds, predicate, **kw)
            assert res.count == len(ref), (epoch, predicate)
            assert np.array_equal(res.pairs, ref), (epoch, predicate)
    # pow2/ladder bucketing: fresh data of similar size re-lands on the
    # warmed kernel shapes (the CI-gated recompiles==0 contract). The
    # bbox predicate pays its own first-trace on epoch 0.
    ds.join_count("a", "b", predicate="dwithin", distance=0.3)
    ds.join_count("a", "b", predicate="bbox", dx=0.2, dy=0.25)
    grew = sum(reg.traces().values()) - warm
    assert grew <= 2, f"{grew} fresh traces beyond the per-predicate warmup"


def test_join_repeat_zero_recompiles_mutated_values():
    """Same sizes, fresh coordinate values: strictly zero recompiles."""
    ds = _mkds(seed=41, na=600, nb=500)
    reg = join_exec.join_registry()
    ds.join_count("a", "b", predicate="dwithin", distance=0.3)
    before = sum(reg.traces().values())
    for s in range(3):
        ds2 = _mkds(seed=100 + s, na=600, nb=500)
        ref = _ref(ds2, "dwithin", distance=0.3)
        assert ds2.join_count("a", "b", predicate="dwithin",
                              distance=0.3) == len(ref)
    assert sum(reg.traces().values()) == before, "warm join recompiled"


# ---------------------------------------------------------------------------
# sharded 8-virtual-device bit-identity + degradation
# ---------------------------------------------------------------------------


def test_join_sharded_8dev_bit_identical():
    """conftest forces 8 CPU devices: the tile fan-out engages and the
    result must match both the single-device and brute-force answers."""
    import jax

    ds = _mkds(seed=51, na=2000, nb=1800)
    res = ds.join("a", "b", predicate="dwithin", distance=0.3)
    ref = _ref(ds, "dwithin", distance=0.3)
    assert np.array_equal(res.pairs, ref)
    if len(jax.devices()) >= 2:
        assert res.stats.devices >= 2  # the fan-out actually engaged
    # forced single-device: identical
    with config.MESH_DEVICES.scoped("1"):
        res1 = ds.join("a", "b", predicate="dwithin", distance=0.3)
    assert res1.stats.devices == 1
    assert np.array_equal(res1.pairs, res.pairs)


def test_join_degradation_exact_survivor_totals(monkeypatch):
    ds = _mkds(seed=61, na=1500, nb=1300)
    ref = _ref(ds, "dwithin", distance=0.3)
    real = join_exec._run_slice
    fail_first = {"armed": True}

    def flaky(plan, lo, hi, *a, **kw):
        if fail_first["armed"] and lo == 0:
            fail_first["armed"] = False
            raise RuntimeError("injected device fault")
        return real(plan, lo, hi, *a, **kw)

    monkeypatch.setattr(join_exec, "_run_slice", flaky)
    # strict mode: the failure surfaces
    with pytest.raises(RuntimeError, match="injected"):
        ds.join("a", "b", predicate="dwithin", distance=0.3)
    # degraded mode: skipped tile range recorded, survivors exact
    fail_first["armed"] = True
    with resilience.allow_partial() as partial:
        res = ds.join("a", "b", predicate="dwithin", distance=0.3)
    assert res.degraded and res.stats.skipped
    assert partial.skipped and partial.skipped[0].source == "join"
    assert res.count == len(res.pairs) <= len(ref)
    ref_set = {tuple(p) for p in ref}
    assert all(tuple(p) in ref_set for p in res.pairs)
    # audit carries the degradation account
    ev = [e for e in ds.audit.recent(10) if e.hints.get("op") == "join"][-1]
    assert ev.hints.get("degraded")


# ---------------------------------------------------------------------------
# explain / audit / serving shapes
# ---------------------------------------------------------------------------


def test_join_explain_and_audit_shape():
    ds = _mkds(seed=71)
    n = ds.join_count("a", "b", predicate="dwithin", distance=0.3)
    ev = [e for e in ds.audit.recent(10) if e.hints.get("op") == "join"][-1]
    assert ev.hints["predicate"] == "dwithin"
    assert ev.hints["right"] == "b"
    assert ev.hints["candidate_pairs"] > 0
    assert ev.hints["naive_pairs"] == 1500 * 1200
    assert 0.0 <= ev.hints["strip_fraction"] <= 1.0
    assert ev.hits == n
    exp = ds.explain_join("a", "b", predicate="dwithin", distance=0.3,
                          analyze=True)
    for marker in ("Join", "candidate pairs", "boundary-strip fraction",
                   "co-partition level", "matched (analyze)"):
        assert marker in exp, exp


def test_join_admission_shed_and_metrics():
    ds = _mkds(seed=81, na=300, nb=300)
    with resilience.deadline_scope(0.0):
        with pytest.raises(resilience.DeadlineShedError):
            ds.join_count("a", "b", predicate="dwithin", distance=0.3)
    c0 = metrics.registry().counter(metrics.JOIN_QUERIES).value
    ds.join_count("a", "b", predicate="dwithin", distance=0.3)
    assert metrics.registry().counter(metrics.JOIN_QUERIES).value == c0 + 1


def test_join_count_repeat_fusion_key():
    from geomesa_tpu.serving import fuse as fusemod

    opts = {"right": "b", "predicate": "dwithin", "distance": 0.3,
            "ecql": "INCLUDE", "right_ecql": "INCLUDE"}
    k1 = fusemod.fuse_key("join_count", "a", dict(opts))
    k2 = fusemod.fuse_key("join_count", "a", dict(opts))
    assert k1 is not None and k1 == k2
    k3 = fusemod.fuse_key("join_count", "a", {**opts, "distance": 0.4})
    assert k3 != k1
    k4 = fusemod.fuse_key("join_count", "a", {**opts, "right": "c"})
    assert k4 != k1


def test_join_sidecar_round_trip():
    from geomesa_tpu.sidecar.client import GeoFlightClient
    from geomesa_tpu.sidecar.service import GeoFlightServer

    ds = _mkds(seed=91, na=400, nb=350)
    srv = GeoFlightServer(ds, "grpc+tcp://127.0.0.1:0")
    try:
        cl = GeoFlightClient(f"grpc+tcp://127.0.0.1:{srv.port}")
        local = ds.join_count("a", "b", predicate="bbox", dx=0.2, dy=0.2)
        assert cl.join_count("a", "b", predicate="bbox",
                             dx=0.2, dy=0.2) == local
        exp = cl.join_explain("a", "b", predicate="bbox", dx=0.2, dy=0.2)
        assert "candidate pairs" in exp
        cl.close()
    finally:
        srv.shutdown()


def test_join_sidecar_auths_filter_both_sides():
    """Request auths must filter BOTH join sides' scans — a restricted
    caller can never count pairs its auths cannot see."""
    from geomesa_tpu.sidecar.client import GeoFlightClient
    from geomesa_tpu.sidecar.service import GeoFlightServer

    ds = GeoDataset()
    ds.create_schema("a", "*geom:Point")
    ds.create_schema("b", "*geom:Point")
    # two coincident points per side: one open, one secret
    ds.insert("a", {"geom": [(0.0, 0.0), (0.01, 0.0)]},
              visibilities=["", "secret"])
    ds.insert("b", {"geom": [(0.0, 0.01), (0.01, 0.01)]},
              visibilities=["", "secret"])
    srv = GeoFlightServer(ds, "grpc+tcp://127.0.0.1:0")
    try:
        cl = GeoFlightClient(f"grpc+tcp://127.0.0.1:{srv.port}")
        full = cl.join_count("a", "b", predicate="dwithin", distance=0.5,
                             auths=["secret"])
        restricted = cl.join_count("a", "b", predicate="dwithin",
                                   distance=0.5, auths=[])
        assert full == 4 and restricted == 1, (full, restricted)
        cl.close()
    finally:
        srv.shutdown()


# ---------------------------------------------------------------------------
# satellite: distinct-filter density_curve batching
# ---------------------------------------------------------------------------


def _curve_ds(seed=5, n=8000):
    ds = GeoDataset()
    ds.create_schema("p", "w:Double,*geom:Point")
    rng = np.random.default_rng(seed)
    ds.insert("p", {"w": rng.uniform(0, 1, n),
                    "geom": list(zip(rng.uniform(-60, 60, n),
                                     rng.uniform(-30, 30, n)))})
    ds.flush()
    return ds


def test_density_curve_filter_batch_bit_identical():
    ds = _curve_ds()
    queries = [f"BBOX(geom, {x0}, -20, {x0 + 30}, 20)"
               for x0 in (-50, -30, -10, 10, 25)]
    bboxes = [(x0, -20, x0 + 30, 20) for x0 in (-50, -30, -10, 10, 25)]
    out = ds.density_curve_filter_batch("p", queries, level=6,
                                        bboxes=bboxes)
    assert out is not None
    for (g, snap), q, bb in zip(out, queries, bboxes):
        gs, ss = ds.density_curve("p", q, level=6, bbox=bb)
        assert ss == snap
        assert np.array_equal(g, gs)


def test_density_curve_filter_batch_weighted_and_fuse_key():
    ds = _curve_ds(seed=6)
    queries = ["BBOX(geom, -50, -20, -20, 20)", "BBOX(geom, -30, -20, 0, 20)"]
    bboxes = [(-50, -20, -20, 20), (-30, -20, 0, 20)]
    out = ds.density_curve_filter_batch("p", queries, level=6,
                                        bboxes=bboxes, weight="w")
    assert out is not None
    for (g, _), q, bb in zip(out, queries, bboxes):
        gs, _ = ds.density_curve("p", q, level=6, bbox=bb, weight="w")
        assert np.array_equal(g, gs)
    # structural fuse key: distinct bbox literals share one curve key
    from geomesa_tpu.serving import fuse as fusemod

    with config.SERVING_FUSION_DISTINCT.scoped("true"):
        k1 = fusemod.fuse_key("density_curve", "p",
                              {"ecql": queries[0], "level": 6}, ds=ds)
        k2 = fusemod.fuse_key("density_curve", "p",
                              {"ecql": queries[1], "level": 6}, ds=ds)
    assert k1 is not None and k1 == k2
    assert k1[2][0] == "skel"


def test_density_curve_distinct_fusion_through_scheduler():
    """Distinct-filter curve requests queued together fuse through the
    structural key and de-interleave bit-identically to serial runs."""
    import threading

    from geomesa_tpu.serving import fuse as fusemod

    ds = _curve_ds(seed=7)
    queries = [f"BBOX(geom, {x0}, -20, {x0 + 30}, 20)"
               for x0 in (-50, -30, -10)]
    bboxes = [(x0, -20, x0 + 30, 20) for x0 in (-50, -30, -10)]
    serial = [ds.density_curve("p", q, level=6, bbox=bb)
              for q, bb in zip(queries, bboxes)]
    with config.SERVING_FUSION_DISTINCT.scoped("true"):
        sched = ds.serving.start()
        gate = threading.Event()
        started = threading.Event()

        def stall():
            started.set()
            return gate.wait(30)

        stall_fut = sched.submit(stall, user="stall", op="stall")
        assert started.wait(10)
        try:
            futs = [
                sched.submit(
                    (lambda q=q, bb=bb:
                     ds.density_curve("p", q, level=6, bbox=bb)),
                    user=f"u{i}", op="density_curve",
                    fuse=fusemod.make_spec(
                        ds, "density_curve", "p",
                        {"ecql": q, "level": 6, "bbox": bb},
                    ),
                )
                for i, (q, bb) in enumerate(zip(queries, bboxes))
            ]
            gate.set()
            got = [f.result(timeout=60) for f in futs]
        finally:
            gate.set()
            sched.stop()
    for (g, snap), (gs, ss) in zip(got, serial):
        assert snap == ss
        assert np.array_equal(g, gs)


def test_density_curve_filter_batch_fallback_none_for_mixed_templates():
    ds = _curve_ds(seed=8)
    out = ds.density_curve_filter_batch(
        "p", ["BBOX(geom, -50, -20, -20, 20)", "w > 0.5"], level=6,
        bboxes=[(-50, -20, -20, 20), None],
    )
    assert out is None  # caller degrades to per-member serial


# ---------------------------------------------------------------------------
# satellite: speculative density / stats
# ---------------------------------------------------------------------------


def test_speculative_density_inline():
    ds = _curve_ds(seed=10)
    q = "BBOX(geom, -30, -15, 30, 15)"
    with resilience.deadline_scope(0.0):
        with pytest.raises(resilience.DeadlineShedError):
            ds.density("p", q, bbox=(-30, -15, 30, 15))
    spec = metrics.registry().counter(metrics.SERVING_SPECULATIVE)
    s0 = spec.value
    with resilience.deadline_scope(0.0):
        g = ds.density("p", q, bbox=(-30, -15, 30, 15), width=64,
                       height=32, speculative_ok=True)
    assert g.shape == (32, 64) and float(g.sum()) > 0
    assert spec.value == s0 + 1
    ev = [e for e in ds.audit.recent(10) if e.hints.get("speculative")][-1]
    assert ev.hints["op"] == "density" and ev.hints["shed"] is True
    # healthy deadline: the exact grid still serves
    with resilience.deadline_scope(30.0):
        exact = ds.density("p", q, bbox=(-30, -15, 30, 15), width=64,
                           height=32, speculative_ok=True)
    assert float(exact.sum()) == ds.count("p", q)


def test_speculative_stats_inline():
    ds = GeoDataset()
    ds.create_schema("s", "v:Double:index=true,*geom:Point")
    rng = np.random.default_rng(12)
    n = 1000
    ds.insert("s", {"v": rng.uniform(5, 9, n),
                    "geom": list(zip(rng.uniform(-10, 10, n),
                                     rng.uniform(-10, 10, n)))})
    ds.flush()
    with resilience.deadline_scope(0.0):
        with pytest.raises(resilience.DeadlineShedError):
            ds.stats("s", "MinMax(v);Count()")
        out = ds.stats("s", "MinMax(v);Count()", speculative_ok=True)
    mm, cnt = out.stats
    assert cnt.count == n  # unfiltered count: exact from the store
    assert mm.value()["min"] is not None  # persisted write-time sketch
    ev = [e for e in ds.audit.recent(10) if e.hints.get("speculative")][-1]
    assert ev.hints["op"] == "stats" and ev.hints["served_leaves"] == 2


# ---------------------------------------------------------------------------
# satellite: content-addressed compact-descriptor share
# ---------------------------------------------------------------------------


def test_compact_descriptor_share_across_query_texts():
    """Two query TEXTS (distinct plans / window tokens) resolving the
    SAME scan windows share one built descriptor instead of each paying
    the argsort/repeat rebuild (docs/PERF.md "Shared descriptors");
    results stay identical."""
    ds = GeoDataset()
    ds.create_schema("c", "w:Double,*geom:Point")
    rng = np.random.default_rng(15)
    n = 60_000
    ds.insert("c", {"w": rng.uniform(0, 1, n),
                    "geom": list(zip(rng.uniform(-60, 60, n),
                                     rng.uniform(-30, 30, n)))})
    ds.flush()
    q1 = "BBOX(geom, -10, -5, 10, 5)"
    # different text + residual => different plan/window token, but the
    # KEY plan (the bbox) resolves the identical windows
    q2 = f"{q1} AND w >= 0"
    ctr = metrics.registry().counter(metrics.COMPACT_DESC_SHARED)
    with config.CACHE_ENABLED.scoped("false"), \
            config.COMPACT_MIN_ROWS.scoped("1"):
        n1 = ds.count("c", q1)
        before = ctr.value
        n2 = ds.count("c", q2)
        after = ctr.value
    assert n1 == n2
    assert after > before, "descriptor rebuilt instead of shared"
