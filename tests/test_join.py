"""TPU-native spatial joins (docs/JOIN.md): SFC co-partitioned
build/probe with a bucketed pairwise kernel.

The load-bearing guarantee is BIT-IDENTITY against the naive numpy N*M
reference (``kernels.join.brute_force_pairs``): the co-partition decides
only WHICH pairs are tested, never how a tested pair decides — both
sides run the identical f32 ``pair_mask`` arithmetic. Covered: both
predicates (incl. cell-edge / inclusive-equality pairs, empty cells,
strip-only matches), a seeded property walk across store epochs, the
sharded 8-virtual-device path (conftest forces 8 CPU devices),
degradation with exact survivor totals, the recompile-free repeat proof,
and the explain/audit shapes.

Satellite coverage rides along (one PR, one file): distinct-filter
density_curve batching, speculative density/stats answers, join_count
repeat fusion, and the content-addressed compact-descriptor share.
"""

import numpy as np
import pytest

from geomesa_tpu import GeoDataset, config, metrics, resilience
from geomesa_tpu.kernels import join as kjoin
from geomesa_tpu.planning import join_exec


def _clustered(rng, n, n_hot=12, spread=0.4, lo=-60, hi=60):
    cx = rng.uniform(lo, hi, n_hot)
    cy = rng.uniform(lo / 2, hi / 2, n_hot)
    k = rng.integers(0, n_hot, n)
    return (np.clip(cx[k] + rng.normal(0, spread, n), -179, 179),
            np.clip(cy[k] + rng.normal(0, spread, n), -89, 89))


def _mkds(seed=7, na=1500, nb=1200):
    ds = GeoDataset()
    ds.create_schema("a", "name:String,*geom:Point")
    ds.create_schema("b", "tag:String,*geom:Point")
    rng = np.random.default_rng(seed)
    ax, ay = _clustered(rng, na)
    bx, by = _clustered(rng, nb)
    ds.insert("a", {"name": [f"n{i % 5}" for i in range(na)],
                    "geom": list(zip(ax, ay))})
    ds.insert("b", {"tag": [f"t{i % 3}" for i in range(nb)],
                    "geom": list(zip(bx, by))})
    ds.flush()
    return ds


def _ref(ds, predicate, left="a", right="b", lq="INCLUDE", rq="INCLUDE",
         **kw):
    p0, p1 = kjoin.pair_params(predicate, **kw)
    lfc, rfc = ds.query(left, lq), ds.query(right, rq)
    lx, ly = lfc.batch.columns["geom__x"], lfc.batch.columns["geom__y"]
    rx, ry = rfc.batch.columns["geom__x"], rfc.batch.columns["geom__y"]
    if predicate == kjoin.JOIN_DWITHIN_METERS:
        lux, luy, luz = kjoin.unit_vectors(lx, ly)
        rux, ruy, ruz = kjoin.unit_vectors(rx, ry)
        return kjoin.brute_force_pairs(
            lux, luy, rux, ruy, predicate, p0, p1, lz=luz, rz=ruz,
        )
    return kjoin.brute_force_pairs(lx, ly, rx, ry, predicate, p0, p1)


# ---------------------------------------------------------------------------
# bit-identity vs brute force
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("predicate,kw", [
    ("dwithin", {"distance": 0.35}),
    ("bbox", {"dx": 0.25, "dy": 0.15}),
    ("dwithin_meters", {"distance": 30_000.0}),
])
def test_join_bit_identical_vs_brute_force(predicate, kw):
    ds = _mkds()
    res = ds.join("a", "b", predicate=predicate, **kw)
    ref = _ref(ds, predicate, **kw)
    assert res.count == len(ref)
    assert np.array_equal(res.pairs, ref)
    assert ds.join_count("a", "b", predicate=predicate, **kw) == len(ref)
    # the grid filter actually pruned on the clustered layout
    assert res.stats.candidate_fraction < 0.2
    assert res.stats.cells_joint > 0


def test_join_device_matches_host_path():
    ds_dev = _mkds(seed=21)
    ds_host = _mkds(seed=21)
    ds_host.prefer_device = False
    for predicate, kw in (("dwithin", {"distance": 0.3}),
                          ("bbox", {"dx": 0.2, "dy": 0.2}),
                          ("dwithin_meters", {"distance": 25_000.0})):
        a = ds_dev.join("a", "b", predicate=predicate, **kw)
        b = ds_host.join("a", "b", predicate=predicate, **kw)
        assert a.count == b.count
        assert np.array_equal(a.pairs, b.pairs)


def test_join_dwithin_meters_antimeridian_and_pole():
    """The great-circle predicate matches across lon ±180 and over the
    pole — the strip machinery's modular lon windows and full-wrap
    high-latitude reach must probe those cells (planar predicates never
    face this: |lx-rx| does not wrap)."""
    ds = GeoDataset()
    ds.create_schema("a", "name:String,*geom:Point")
    ds.create_schema("b", "tag:String,*geom:Point")
    rng = np.random.default_rng(11)
    n = 400
    # half the rows hug the antimeridian (both signs), a band sits near
    # the north pole, the rest scatter mid-latitudes
    def side(seed):
        r = np.random.default_rng(seed)
        lon = np.concatenate([
            r.uniform(179.0, 180.0, n // 4),
            r.uniform(-180.0, -179.0, n // 4),
            r.uniform(-170.0, 170.0, n // 4),
            r.uniform(-180.0, 180.0, n - 3 * (n // 4)),
        ])
        lat = np.concatenate([
            r.uniform(55.0, 60.0, n // 4),
            r.uniform(55.0, 60.0, n // 4),
            r.uniform(-45.0, 45.0, n // 4),
            r.uniform(88.5, 90.0, n - 3 * (n // 4)),
        ])
        return lon, lat
    ax, ay = side(1)
    bx, by = side(2)
    ds.insert("a", {"name": ["x"] * n, "geom": list(zip(ax, ay))})
    ds.insert("b", {"tag": ["y"] * n, "geom": list(zip(bx, by))})
    ds.flush()
    for d in (20_000.0, 150_000.0):
        res = ds.join("a", "b", predicate="dwithin_meters", distance=d)
        ref = _ref(ds, "dwithin_meters", distance=d)
        assert res.count == len(ref)
        assert np.array_equal(res.pairs, ref)
        # cross-antimeridian pairs actually exist in this layout (the
        # test would vacuously pass without them)
        lons = ax[ref[:, 0]], bx[ref[:, 1]]
        assert (np.abs(lons[0] - lons[1]) > 300).any()
    # explain_join(analyze=True) shares run_join's operand dispatch —
    # dwithin_meters analyzes on unit vectors, counting identically
    ex = ds.explain_join("a", "b", predicate="dwithin_meters",
                         distance=150_000.0, analyze=True)
    want = ds.join_count("a", "b", predicate="dwithin_meters",
                         distance=150_000.0)
    assert f"matched (analyze): {want}" in ex


def test_join_dwithin_meters_inclusive_edge_exact():
    """A pair at EXACTLY the f32 chord threshold decides inclusively —
    and identically — in kernel and reference (the <= contract)."""
    # two points d meters apart along the equator: arc == lon delta
    d = 10_000.0
    ddeg = np.degrees(d / kjoin.EARTH_RADIUS_M)
    ds = GeoDataset()
    ds.create_schema("a", "name:String,*geom:Point")
    ds.create_schema("b", "tag:String,*geom:Point")
    ds.insert("a", {"name": ["p"], "geom": [(10.0, 0.0)]})
    ds.insert("b", {"tag": ["q", "r"],
                    "geom": [(10.0 + ddeg, 0.0), (10.0 + 3 * ddeg, 0.0)]})
    ds.flush()
    res = ds.join("a", "b", predicate="dwithin_meters", distance=d)
    ref = _ref(ds, "dwithin_meters", distance=d)
    assert np.array_equal(res.pairs, ref)
    assert res.count == len(ref) <= 1  # the 3d point never matches


def test_join_cell_edge_and_inclusive_equality_pairs():
    """Pairs straddling SFC cell edges (strip-only matches) and pairs at
    EXACTLY the predicate distance (inclusive <=) must decide like the
    reference."""
    ds = GeoDataset()
    ds.create_schema("a", "*geom:Point")
    ds.create_schema("b", "*geom:Point")
    # level-whatever cell edges sit at dyadic lon/lat values: 11.25 is an
    # edge at level 5 (360/32), 0.0 at every level. d = 0.25 is exact in
    # f32, so dist == d pairs exercise the inclusive boundary.
    d = 0.25
    left = [(11.25 - 0.01, 5.0), (0.0, 0.0), (-45.0, -22.5), (170.0, 80.0)]
    right = [(11.25 + 0.01, 5.0),           # strip-only: cells differ
             (d, 0.0),                      # exactly d away (inclusive)
             (-45.0 + d, -22.5),            # exactly d, across an edge
             (10.0, 10.0)]                  # matches nothing
    ds.insert("a", {"geom": left})
    ds.insert("b", {"geom": right})
    res = ds.join("a", "b", predicate="dwithin", distance=d)
    ref = _ref(ds, "dwithin", distance=d)
    assert np.array_equal(res.pairs, ref)
    assert res.count == len(ref) >= 3
    # the strip actually carried a match: the (0) pair's cells differ
    assert res.stats.strip_entries > 0


def test_join_empty_cells_and_disjoint_sides():
    ds = GeoDataset()
    ds.create_schema("a", "*geom:Point")
    ds.create_schema("b", "*geom:Point")
    rng = np.random.default_rng(3)
    ds.insert("a", {"geom": list(zip(rng.uniform(-60, -40, 300),
                                     rng.uniform(-30, -10, 300)))})
    ds.insert("b", {"geom": list(zip(rng.uniform(40, 60, 300),
                                     rng.uniform(10, 30, 300)))})
    res = ds.join("a", "b", predicate="dwithin", distance=0.5)
    assert res.count == 0 and len(res.pairs) == 0
    assert res.stats.cells_joint == 0
    assert res.stats.candidate_pairs == 0


def test_join_filtered_sides_and_streaming_batches():
    ds = _mkds(seed=9)
    lq = "BBOX(geom, -60, -30, 20, 30)"
    res = ds.join_spatial("a", "b", predicate="bbox", dx=0.3, dy=0.3,
                          left_query=lq)
    ref = _ref(ds, "bbox", lq=lq, dx=0.3, dy=0.3)
    assert res.count == len(ref)
    assert np.array_equal(res.pairs, ref)
    # streaming: chunks tile the pair set in order, right cols prefixed
    rows = 0
    for b in res.batches(batch_rows=97):
        assert b.n <= 97
        assert "right.geom__x" in b.columns and "geom__x" in b.columns
        rows += b.n
    assert rows == res.count


def test_join_rejects_non_point_and_missing_params():
    ds = GeoDataset()
    ds.create_schema("pt", "*geom:Point")
    ds.create_schema("ln", "*geom:LineString")
    with pytest.raises(ValueError, match="POINT"):
        ds.join("pt", "ln", predicate="dwithin", distance=1.0)
    with pytest.raises(ValueError):
        ds.join("pt", "pt", predicate="dwithin")  # no distance
    with pytest.raises(ValueError):
        ds.join("pt", "pt", predicate="nope", distance=1.0)
    with pytest.raises(ValueError):
        ds.join("pt", "pt")  # neither attrs nor predicate


# ---------------------------------------------------------------------------
# seeded property walk across epochs + recompile-free repeats
# ---------------------------------------------------------------------------


def test_join_property_walk_across_epochs_recompile_free():
    """Mutate the store across epochs (appends of the same batch size);
    every epoch's join must match brute force AND pay zero fresh traces
    after the first epoch warmed the shape buckets."""
    ds = _mkds(seed=31, na=900, nb=800)
    rng = np.random.default_rng(77)
    reg = join_exec.join_registry()
    ds.join_count("a", "b", predicate="dwithin", distance=0.3)  # warm
    ds.join_count("a", "b", predicate="bbox", dx=0.2, dy=0.25)  # warm
    warm = sum(reg.traces().values())
    for epoch in range(3):
        nx, ny = _clustered(rng, 100)
        ds.insert("a", {"name": ["m"] * 100, "geom": list(zip(nx, ny))})
        nx, ny = _clustered(rng, 100)
        ds.insert("b", {"tag": ["m"] * 100, "geom": list(zip(nx, ny))})
        ds.flush()
        for predicate, kw in (("dwithin", {"distance": 0.3}),
                              ("bbox", {"dx": 0.2, "dy": 0.25})):
            res = ds.join("a", "b", predicate=predicate, **kw)
            ref = _ref(ds, predicate, **kw)
            assert res.count == len(ref), (epoch, predicate)
            assert np.array_equal(res.pairs, ref), (epoch, predicate)
    # pow2/ladder bucketing: fresh data of similar size re-lands on the
    # warmed kernel shapes (the CI-gated recompiles==0 contract). Both
    # predicates warmed every adaptive site above; the only growth
    # allowed is one tile-count ladder crossing per predicate as the
    # store grows past a pow2 boundary.
    ds.join_count("a", "b", predicate="dwithin", distance=0.3)
    ds.join_count("a", "b", predicate="bbox", dx=0.2, dy=0.25)
    grew = sum(reg.traces().values()) - warm
    assert grew <= 2, f"{grew} fresh traces beyond the warmed shape buckets"


def test_join_repeat_zero_recompiles_mutated_values():
    """Same sizes, fresh coordinate values: strictly zero recompiles."""
    ds = _mkds(seed=41, na=600, nb=500)
    reg = join_exec.join_registry()
    ds.join_count("a", "b", predicate="dwithin", distance=0.3)
    before = sum(reg.traces().values())
    for s in range(3):
        ds2 = _mkds(seed=100 + s, na=600, nb=500)
        ref = _ref(ds2, "dwithin", distance=0.3)
        assert ds2.join_count("a", "b", predicate="dwithin",
                              distance=0.3) == len(ref)
    assert sum(reg.traces().values()) == before, "warm join recompiled"


# ---------------------------------------------------------------------------
# sharded 8-virtual-device bit-identity + degradation
# ---------------------------------------------------------------------------


def test_join_sharded_8dev_bit_identical():
    """conftest forces 8 CPU devices: the tile fan-out engages and the
    result must match both the single-device and brute-force answers."""
    import jax

    ds = _mkds(seed=51, na=2000, nb=1800)
    res = ds.join("a", "b", predicate="dwithin", distance=0.3)
    ref = _ref(ds, "dwithin", distance=0.3)
    assert np.array_equal(res.pairs, ref)
    if len(jax.devices()) >= 2:
        assert res.stats.devices >= 2  # the fan-out actually engaged
    # forced single-device: identical
    with config.MESH_DEVICES.scoped("1"):
        res1 = ds.join("a", "b", predicate="dwithin", distance=0.3)
    assert res1.stats.devices == 1
    assert np.array_equal(res1.pairs, res.pairs)


def test_join_degradation_exact_survivor_totals(monkeypatch):
    ds = _mkds(seed=61, na=1500, nb=1300)
    ref = _ref(ds, "dwithin", distance=0.3)
    real = join_exec._run_slice
    fail_first = {"armed": True}

    def flaky(plan, lo, hi, *a, **kw):
        if fail_first["armed"] and lo == 0:
            fail_first["armed"] = False
            raise RuntimeError("injected device fault")
        return real(plan, lo, hi, *a, **kw)

    monkeypatch.setattr(join_exec, "_run_slice", flaky)
    # strict mode: the failure surfaces
    with pytest.raises(RuntimeError, match="injected"):
        ds.join("a", "b", predicate="dwithin", distance=0.3)
    # degraded mode: skipped tile range recorded, survivors exact
    fail_first["armed"] = True
    with resilience.allow_partial() as partial:
        res = ds.join("a", "b", predicate="dwithin", distance=0.3)
    assert res.degraded and res.stats.skipped
    assert partial.skipped and partial.skipped[0].source == "join"
    assert res.count == len(res.pairs) <= len(ref)
    ref_set = {tuple(p) for p in ref}
    assert all(tuple(p) in ref_set for p in res.pairs)
    # audit carries the degradation account
    ev = [e for e in ds.audit.recent(10) if e.hints.get("op") == "join"][-1]
    assert ev.hints.get("degraded")


# ---------------------------------------------------------------------------
# explain / audit / serving shapes
# ---------------------------------------------------------------------------


def test_join_explain_and_audit_shape():
    ds = _mkds(seed=71)
    n = ds.join_count("a", "b", predicate="dwithin", distance=0.3)
    ev = [e for e in ds.audit.recent(10) if e.hints.get("op") == "join"][-1]
    assert ev.hints["predicate"] == "dwithin"
    assert ev.hints["right"] == "b"
    assert ev.hints["candidate_pairs"] > 0
    assert ev.hints["naive_pairs"] == 1500 * 1200
    assert 0.0 <= ev.hints["strip_fraction"] <= 1.0
    assert ev.hits == n
    exp = ds.explain_join("a", "b", predicate="dwithin", distance=0.3,
                          analyze=True)
    for marker in ("Join", "candidate pairs", "boundary-strip fraction",
                   "co-partition level", "matched (analyze)"):
        assert marker in exp, exp


def test_join_admission_shed_and_metrics():
    ds = _mkds(seed=81, na=300, nb=300)
    with resilience.deadline_scope(0.0):
        with pytest.raises(resilience.DeadlineShedError):
            ds.join_count("a", "b", predicate="dwithin", distance=0.3)
    c0 = metrics.registry().counter(metrics.JOIN_QUERIES).value
    ds.join_count("a", "b", predicate="dwithin", distance=0.3)
    assert metrics.registry().counter(metrics.JOIN_QUERIES).value == c0 + 1


def test_join_count_repeat_fusion_key():
    from geomesa_tpu.serving import fuse as fusemod

    opts = {"right": "b", "predicate": "dwithin", "distance": 0.3,
            "ecql": "INCLUDE", "right_ecql": "INCLUDE"}
    k1 = fusemod.fuse_key("join_count", "a", dict(opts))
    k2 = fusemod.fuse_key("join_count", "a", dict(opts))
    assert k1 is not None and k1 == k2
    k3 = fusemod.fuse_key("join_count", "a", {**opts, "distance": 0.4})
    assert k3 != k1
    k4 = fusemod.fuse_key("join_count", "a", {**opts, "right": "c"})
    assert k4 != k1


def test_join_sidecar_round_trip():
    from geomesa_tpu.sidecar.client import GeoFlightClient
    from geomesa_tpu.sidecar.service import GeoFlightServer

    ds = _mkds(seed=91, na=400, nb=350)
    srv = GeoFlightServer(ds, "grpc+tcp://127.0.0.1:0")
    try:
        cl = GeoFlightClient(f"grpc+tcp://127.0.0.1:{srv.port}")
        local = ds.join_count("a", "b", predicate="bbox", dx=0.2, dy=0.2)
        assert cl.join_count("a", "b", predicate="bbox",
                             dx=0.2, dy=0.2) == local
        exp = cl.join_explain("a", "b", predicate="bbox", dx=0.2, dy=0.2)
        assert "candidate pairs" in exp
        cl.close()
    finally:
        srv.shutdown()


def test_join_sidecar_auths_filter_both_sides():
    """Request auths must filter BOTH join sides' scans — a restricted
    caller can never count pairs its auths cannot see."""
    from geomesa_tpu.sidecar.client import GeoFlightClient
    from geomesa_tpu.sidecar.service import GeoFlightServer

    ds = GeoDataset()
    ds.create_schema("a", "*geom:Point")
    ds.create_schema("b", "*geom:Point")
    # two coincident points per side: one open, one secret
    ds.insert("a", {"geom": [(0.0, 0.0), (0.01, 0.0)]},
              visibilities=["", "secret"])
    ds.insert("b", {"geom": [(0.0, 0.01), (0.01, 0.01)]},
              visibilities=["", "secret"])
    srv = GeoFlightServer(ds, "grpc+tcp://127.0.0.1:0")
    try:
        cl = GeoFlightClient(f"grpc+tcp://127.0.0.1:{srv.port}")
        full = cl.join_count("a", "b", predicate="dwithin", distance=0.5,
                             auths=["secret"])
        restricted = cl.join_count("a", "b", predicate="dwithin",
                                   distance=0.5, auths=[])
        assert full == 4 and restricted == 1, (full, restricted)
        cl.close()
    finally:
        srv.shutdown()


# ---------------------------------------------------------------------------
# satellite: distinct-filter density_curve batching
# ---------------------------------------------------------------------------


def _curve_ds(seed=5, n=8000):
    ds = GeoDataset()
    ds.create_schema("p", "w:Double,*geom:Point")
    rng = np.random.default_rng(seed)
    ds.insert("p", {"w": rng.uniform(0, 1, n),
                    "geom": list(zip(rng.uniform(-60, 60, n),
                                     rng.uniform(-30, 30, n)))})
    ds.flush()
    return ds


def test_density_curve_filter_batch_bit_identical():
    ds = _curve_ds()
    queries = [f"BBOX(geom, {x0}, -20, {x0 + 30}, 20)"
               for x0 in (-50, -30, -10, 10, 25)]
    bboxes = [(x0, -20, x0 + 30, 20) for x0 in (-50, -30, -10, 10, 25)]
    out = ds.density_curve_filter_batch("p", queries, level=6,
                                        bboxes=bboxes)
    assert out is not None
    for (g, snap), q, bb in zip(out, queries, bboxes):
        gs, ss = ds.density_curve("p", q, level=6, bbox=bb)
        assert ss == snap
        assert np.array_equal(g, gs)


def test_density_curve_filter_batch_weighted_and_fuse_key():
    ds = _curve_ds(seed=6)
    queries = ["BBOX(geom, -50, -20, -20, 20)", "BBOX(geom, -30, -20, 0, 20)"]
    bboxes = [(-50, -20, -20, 20), (-30, -20, 0, 20)]
    out = ds.density_curve_filter_batch("p", queries, level=6,
                                        bboxes=bboxes, weight="w")
    assert out is not None
    for (g, _), q, bb in zip(out, queries, bboxes):
        gs, _ = ds.density_curve("p", q, level=6, bbox=bb, weight="w")
        assert np.array_equal(g, gs)
    # structural fuse key: distinct bbox literals share one curve key
    from geomesa_tpu.serving import fuse as fusemod

    with config.SERVING_FUSION_DISTINCT.scoped("true"):
        k1 = fusemod.fuse_key("density_curve", "p",
                              {"ecql": queries[0], "level": 6}, ds=ds)
        k2 = fusemod.fuse_key("density_curve", "p",
                              {"ecql": queries[1], "level": 6}, ds=ds)
    assert k1 is not None and k1 == k2
    assert k1[2][0] == "skel"


def test_density_curve_distinct_fusion_through_scheduler():
    """Distinct-filter curve requests queued together fuse through the
    structural key and de-interleave bit-identically to serial runs."""
    import threading

    from geomesa_tpu.serving import fuse as fusemod

    ds = _curve_ds(seed=7)
    queries = [f"BBOX(geom, {x0}, -20, {x0 + 30}, 20)"
               for x0 in (-50, -30, -10)]
    bboxes = [(x0, -20, x0 + 30, 20) for x0 in (-50, -30, -10)]
    serial = [ds.density_curve("p", q, level=6, bbox=bb)
              for q, bb in zip(queries, bboxes)]
    with config.SERVING_FUSION_DISTINCT.scoped("true"):
        sched = ds.serving.start()
        gate = threading.Event()
        started = threading.Event()

        def stall():
            started.set()
            return gate.wait(30)

        stall_fut = sched.submit(stall, user="stall", op="stall")
        assert started.wait(10)
        try:
            futs = [
                sched.submit(
                    (lambda q=q, bb=bb:
                     ds.density_curve("p", q, level=6, bbox=bb)),
                    user=f"u{i}", op="density_curve",
                    fuse=fusemod.make_spec(
                        ds, "density_curve", "p",
                        {"ecql": q, "level": 6, "bbox": bb},
                    ),
                )
                for i, (q, bb) in enumerate(zip(queries, bboxes))
            ]
            gate.set()
            got = [f.result(timeout=60) for f in futs]
        finally:
            gate.set()
            sched.stop()
    for (g, snap), (gs, ss) in zip(got, serial):
        assert snap == ss
        assert np.array_equal(g, gs)


def test_density_curve_filter_batch_fallback_none_for_mixed_templates():
    ds = _curve_ds(seed=8)
    out = ds.density_curve_filter_batch(
        "p", ["BBOX(geom, -50, -20, -20, 20)", "w > 0.5"], level=6,
        bboxes=[(-50, -20, -20, 20), None],
    )
    assert out is None  # caller degrades to per-member serial


# ---------------------------------------------------------------------------
# satellite: speculative density / stats
# ---------------------------------------------------------------------------


def test_speculative_density_inline():
    ds = _curve_ds(seed=10)
    q = "BBOX(geom, -30, -15, 30, 15)"
    with resilience.deadline_scope(0.0):
        with pytest.raises(resilience.DeadlineShedError):
            ds.density("p", q, bbox=(-30, -15, 30, 15))
    spec = metrics.registry().counter(metrics.SERVING_SPECULATIVE)
    s0 = spec.value
    with resilience.deadline_scope(0.0):
        g = ds.density("p", q, bbox=(-30, -15, 30, 15), width=64,
                       height=32, speculative_ok=True)
    assert g.shape == (32, 64) and float(g.sum()) > 0
    assert spec.value == s0 + 1
    ev = [e for e in ds.audit.recent(10) if e.hints.get("speculative")][-1]
    assert ev.hints["op"] == "density" and ev.hints["shed"] is True
    # healthy deadline: the exact grid still serves
    with resilience.deadline_scope(30.0):
        exact = ds.density("p", q, bbox=(-30, -15, 30, 15), width=64,
                           height=32, speculative_ok=True)
    assert float(exact.sum()) == ds.count("p", q)


def test_speculative_stats_inline():
    ds = GeoDataset()
    ds.create_schema("s", "v:Double:index=true,*geom:Point")
    rng = np.random.default_rng(12)
    n = 1000
    ds.insert("s", {"v": rng.uniform(5, 9, n),
                    "geom": list(zip(rng.uniform(-10, 10, n),
                                     rng.uniform(-10, 10, n)))})
    ds.flush()
    with resilience.deadline_scope(0.0):
        with pytest.raises(resilience.DeadlineShedError):
            ds.stats("s", "MinMax(v);Count()")
        out = ds.stats("s", "MinMax(v);Count()", speculative_ok=True)
    mm, cnt = out.stats
    assert cnt.count == n  # unfiltered count: exact from the store
    assert mm.value()["min"] is not None  # persisted write-time sketch
    ev = [e for e in ds.audit.recent(10) if e.hints.get("speculative")][-1]
    assert ev.hints["op"] == "stats" and ev.hints["served_leaves"] == 2


# ---------------------------------------------------------------------------
# satellite: content-addressed compact-descriptor share
# ---------------------------------------------------------------------------


def test_compact_descriptor_share_across_query_texts():
    """Two query TEXTS (distinct plans / window tokens) resolving the
    SAME scan windows share one built descriptor instead of each paying
    the argsort/repeat rebuild (docs/PERF.md "Shared descriptors");
    results stay identical."""
    ds = GeoDataset()
    ds.create_schema("c", "w:Double,*geom:Point")
    rng = np.random.default_rng(15)
    n = 60_000
    ds.insert("c", {"w": rng.uniform(0, 1, n),
                    "geom": list(zip(rng.uniform(-60, 60, n),
                                     rng.uniform(-30, 30, n)))})
    ds.flush()
    q1 = "BBOX(geom, -10, -5, 10, 5)"
    # different text + residual => different plan/window token, but the
    # KEY plan (the bbox) resolves the identical windows
    q2 = f"{q1} AND w >= 0"
    ctr = metrics.registry().counter(metrics.COMPACT_DESC_SHARED)
    with config.CACHE_ENABLED.scoped("false"), \
            config.COMPACT_MIN_ROWS.scoped("1"):
        n1 = ds.count("c", q1)
        before = ctr.value
        n2 = ds.count("c", q2)
        after = ctr.value
    assert n1 == n2
    assert after > before, "descriptor rebuilt instead of shared"


# ---------------------------------------------------------------------------
# adaptive strategy selection (docs/JOIN.md §10)
# ---------------------------------------------------------------------------


def _shaped(rng, shape, n):
    """Coordinate sets engineered per distribution shape: dense balanced
    hotspots, sparse wide scatter (tiny per-cell counts), skewed (one
    side's hotspots dwarf the other), or all three mixed."""
    if shape == "dense":
        return _clustered(rng, n, n_hot=4, spread=0.25)
    if shape == "sparse":
        return (rng.uniform(-170, 170, n // 4),
                rng.uniform(-85, 85, n // 4))
    if shape == "skewed":
        # a handful of hotspots; the caller makes one side heavy
        return _clustered(rng, n, n_hot=3, spread=0.15)
    dx, dy = _clustered(rng, n // 2, n_hot=4, spread=0.25)
    sx, sy = rng.uniform(-170, 170, n // 4), rng.uniform(-85, 85, n // 4)
    return np.concatenate([dx, sx]), np.concatenate([dy, sy])


def _shaped_ds(shape, seed):
    rng = np.random.default_rng(seed)
    na, nb = (1200, 90) if shape == "skewed" else (900, 800)
    ax, ay = _shaped(rng, shape, na)
    bx, by = _shaped(rng, shape, nb)
    ds = GeoDataset()
    ds.create_schema("a", "name:String,*geom:Point")
    ds.create_schema("b", "tag:String,*geom:Point")
    ds.insert("a", {"name": ["n"] * len(ax), "geom": list(zip(ax, ay))})
    ds.insert("b", {"tag": ["t"] * len(bx), "geom": list(zip(bx, by))})
    ds.flush()
    return ds


@pytest.mark.parametrize("shape", ["dense", "sparse", "skewed", "mixed"])
def test_join_adaptive_bit_identical_across_strategies(shape):
    """The load-bearing adaptive contract: per-cell routing (brute /
    split / pairwise) decides only WHICH kernel tests a pair, never how
    a tested pair decides — adaptive, single-strategy (the A/B
    baseline), and the numpy N*M reference return the IDENTICAL pair
    set on the 8-virtual-device path."""
    ds = _shaped_ds(shape, seed={"dense": 21, "sparse": 22,
                                 "skewed": 23, "mixed": 24}[shape])
    for predicate, kw in (("dwithin", {"distance": 0.3}),
                          ("bbox", {"dx": 0.2, "dy": 0.25})):
        ref = _ref(ds, predicate, **kw)
        res = ds.join("a", "b", predicate=predicate, **kw)
        assert np.array_equal(res.pairs, ref), (shape, predicate)
        assert res.count == len(ref)
        with config.JOIN_ADAPTIVE.scoped("false"):
            single = ds.join("a", "b", predicate=predicate, **kw)
        assert np.array_equal(single.pairs, ref), (shape, predicate)
        # the off-switch really is the pre-adaptive plan
        assert list(single.stats.strategy_cells) in ([], ["pairwise"])


def test_join_adaptive_host_path_bit_identical():
    """Same contract on the host (no-device) path."""
    ds = _shaped_ds("mixed", seed=25)
    ds.prefer_device = False
    for predicate, kw in (("dwithin", {"distance": 0.3}),
                          ("bbox", {"dx": 0.2, "dy": 0.25})):
        ref = _ref(ds, predicate, **kw)
        res = ds.join("a", "b", predicate=predicate, **kw)
        assert np.array_equal(res.pairs, ref), predicate
        assert res.stats.devices == 1


def test_join_adaptive_each_strategy_fires_with_decision_trail():
    """A mixed distribution routes cells to EVERY strategy, and the
    decision trail surfaces it: JoinStats histograms, the
    join.cells.<strategy> counters, and the explain Adaptive section."""
    ds = _shaped_ds("mixed", seed=26)
    # make a couple of cells skewed: one heavy left hotspot vs few rights
    rng = np.random.default_rng(27)
    hx = np.full(500, 12.345) + rng.normal(0, 0.02, 500)
    hy = np.full(500, 7.89) + rng.normal(0, 0.02, 500)
    ds.insert("a", {"name": ["h"] * 500, "geom": list(zip(hx, hy))})
    ds.insert("b", {"tag": ["h"] * 4,
                    "geom": [(12.345, 7.89)] * 4})
    ds.flush()
    before = {
        s: metrics.registry().counter(
            metrics.JOIN_CELLS_STRATEGY + s).value
        for s in ("pairwise", "brute", "split.l")
    }
    ref = _ref(ds, "dwithin", distance=0.3)
    res = ds.join("a", "b", predicate="dwithin", distance=0.3)
    assert np.array_equal(res.pairs, ref)
    st = res.stats
    assert st.adaptive
    assert st.strategy_cells.get("brute", 0) > 0
    assert st.strategy_cells.get("pairwise", 0) > 0
    assert st.strategy_cells.get("split.l", 0) > 0
    # estimated pairs cover every candidate; dispatched slots recorded
    assert sum(st.est_pairs.values()) == st.candidate_pairs
    assert set(st.dispatched_pairs) >= {"brute", "pairwise", "split.l"}
    for s in ("pairwise", "brute", "split.l"):
        after = metrics.registry().counter(
            metrics.JOIN_CELLS_STRATEGY + s).value
        assert after - before[s] == st.strategy_cells[s]
    exp = ds.explain_join("a", "b", predicate="dwithin", distance=0.3)
    assert "Adaptive" in exp
    assert "cells[brute]" in exp and "cells[split.l]" in exp
    assert "statistics read" in exp


def test_join_adaptive_skew_dispatches_fewer_slots():
    """Skewed cells in a split section pad the short axis narrow: the
    dispatched slot count must undercut the single-strategy plan's (the
    perf contract behind join_adaptive_speedup)."""
    ds = _shaped_ds("skewed", seed=28)
    res = ds.join("a", "b", predicate="dwithin", distance=0.3)
    with config.JOIN_ADAPTIVE.scoped("false"):
        single = ds.join("a", "b", predicate="dwithin", distance=0.3)
    assert np.array_equal(res.pairs, single.pairs)
    adaptive_slots = sum(res.stats.dispatched_pairs.values())
    single_slots = sum(single.stats.dispatched_pairs.values())
    assert adaptive_slots < single_slots, (
        res.stats.dispatched_pairs, single.stats.dispatched_pairs)


# ---------------------------------------------------------------------------
# polygon-dataset joins (docs/JOIN.md §10)
# ---------------------------------------------------------------------------

_POLYS = [
    # donut: hole must exclude interior points
    "POLYGON ((0 0, 8 0, 8 8, 0 8, 0 0), (3 3, 5 3, 5 5, 3 5, 3 3))",
    # large polygon spanning several co-partition cells: interior cells
    # must match WHOLESALE (zero pairwise work)
    "POLYGON ((20 -20, 60 -20, 60 20, 20 20, 20 -20))",
    # multipolygon: row matches if inside ANY part
    ("MULTIPOLYGON (((-30 -10, -25 -10, -25 -5, -30 -5, -30 -10)), "
     "((-20 -10, -15 -10, -15 -5, -20 -5, -20 -10)))"),
    # sliver far away
    "POLYGON ((100 40, 101 40, 101 41, 100 41, 100 40))",
]


def _poly_ds(seed=33, n=4000):
    from geomesa_tpu.utils import geometry as geo

    ds = GeoDataset()
    ds.create_schema("pts", "name:String,*geom:Point")
    ds.create_schema("polys", "kind:String,*geom:Polygon")
    rng = np.random.default_rng(seed)
    px = rng.uniform(-40, 70, n)
    py = rng.uniform(-30, 45, n)
    # pin points onto edges / vertices / hole boundary (inclusive-edge
    # f32 arithmetic must agree between kernel and reference exactly)
    edge = np.array([(0.0, 0.0), (8.0, 4.0), (3.0, 3.0), (5.0, 5.0),
                     (40.0, 20.0), (20.0, 0.0), (60.0, -20.0),
                     (-25.0, -7.5), (4.0, 4.0), (40.0, 0.0)])
    px = np.concatenate([px, edge[:, 0]])
    py = np.concatenate([py, edge[:, 1]])
    ds.insert("pts", {"name": ["p"] * len(px),
                      "geom": list(zip(px, py))})
    ds.insert("polys", {"kind": [f"k{i}" for i in range(len(_POLYS))],
                        "geom": np.array(_POLYS, object)})
    ds.flush()
    # pairs carry STORE row positions (the index sorts on insert): the
    # reference must read both sides back in store order, like _ref
    fc = ds.query("pts", "INCLUDE")
    px = fc.batch.columns["geom__x"]
    py = fc.batch.columns["geom__y"]
    wkts = ds.query("polys", "INCLUDE").batch.columns["geom__wkt"]
    geoms = [geo.parse_wkt(str(w)) for w in wkts]
    return ds, px, py, geoms


@pytest.mark.parametrize("predicate", ["pip", "poly_bbox"])
def test_join_polygon_bit_identical(predicate):
    """Polygon joins (holes, multipolygon, cell-edge points) are
    bit-identical to the N*M reference; the count path agrees."""
    ds, px, py, geoms = _poly_ds()
    ref = kjoin.polygon_brute_force(px, py, geoms, predicate)
    res = ds.join("pts", "polys", predicate=predicate)
    assert np.array_equal(res.pairs, ref), predicate
    assert res.count == len(ref)
    assert ds.join_count("pts", "polys", predicate=predicate) == len(ref)


def test_join_polygon_interior_cells_match_wholesale():
    """Cells classified INTERIOR contribute their rows with ZERO
    pairwise kernel work: wholesale pairs are non-zero for the large
    polygon and the kernel only sees boundary-cell candidates."""
    ds, px, py, geoms = _poly_ds()
    res = ds.join("pts", "polys", predicate="pip")
    st = res.stats
    assert st.wholesale_pairs > 0
    assert st.strategy_cells.get("interior", 0) > 0
    assert st.strategy_cells.get("boundary", 0) > 0
    # every kernel-tested candidate comes from a boundary cell, so the
    # candidate count is strictly under the full N*R cross product
    assert 0 < st.candidate_pairs < len(px) * len(geoms)
    exp = ds.explain_join("pts", "polys", predicate="pip")
    assert "Adaptive" in exp and "wholesale" in exp
    assert "classify_cells" in exp


def test_join_polygon_fuse_key_distinct_per_predicate():
    from geomesa_tpu.serving import fuse as fusemod

    opts = {"right": "polys", "ecql": "INCLUDE", "right_ecql": "INCLUDE"}
    keys = {
        fusemod.fuse_key("join_count", "pts",
                         {**opts, "predicate": p})
        for p in ("dwithin", "pip", "poly_bbox")
    }
    assert None not in keys
    assert len(keys) == 3


# ---------------------------------------------------------------------------
# window-pushdown side scans (docs/JOIN.md §10, docs/LAKE.md)
# ---------------------------------------------------------------------------


def test_join_pushdown_side_scan_exact_and_cheaper(tmp_path):
    """Count-only joins over a spilled partitioned right side stream the
    side per cell-group window: the total is EXACT (equal to the full
    materialized join) while loading strictly fewer side bytes than any
    full materialization would."""
    import contextlib

    from geomesa_tpu.api.dataset import Query
    from geomesa_tpu.filter.ecql import parse_iso_ms
    from geomesa_tpu.index.partitioned import PartitionedFeatureStore

    with contextlib.ExitStack() as stack:
        stack.enter_context(config.LAKE_ENABLED.scoped("true"))
        stack.enter_context(config.LAKE_ROWGROUP_ROWS.scoped("512"))
        ds = GeoDataset(n_shards=4)
        ds.create_schema(
            "t", "name:String,dtg:Date,*geom:Point;geomesa.partition='time'")
        st = ds._store("t")
        assert isinstance(st, PartitionedFeatureStore)
        st._spill_dir = str(tmp_path / "lake")
        rng = np.random.default_rng(44)
        n = 20_000
        cx = rng.uniform(-115, -75, 10)
        cy = rng.uniform(28, 47, 10)
        k = rng.integers(0, 10, n)
        x = np.clip(cx[k] + rng.normal(0, 0.25, n), -120, -70)
        y = np.clip(cy[k] + rng.normal(0, 0.25, n), 25, 50)
        ds.insert("t", {
            "name": [f"r{i % 9}" for i in range(n)],
            "dtg": rng.integers(parse_iso_ms("2020-01-01"),
                                parse_iso_ms("2020-02-01"),
                                n).astype("datetime64[ms]"),
            "geom__x": x, "geom__y": y,
        })
        ds.flush()
        st.spill_all()
    ds.create_schema("pts", "name:String,*geom:Point")
    # the left viewport covers a SUBSET of the side's hotspots: the
    # footer statistics must prune the groups holding only the rest
    k = rng.integers(0, 4, 600)
    lx = np.clip(cx[k] + rng.normal(0, 0.2, 600), -120, -70)
    ly = np.clip(cy[k] + rng.normal(0, 0.2, 600), 25, 50)
    ds.insert("pts", {"name": ["p"] * 600, "geom": list(zip(lx, ly))})
    ds.flush()

    ctr = metrics.registry().counter(metrics.JOIN_PUSHDOWN_BYTES)
    before = ctr.value
    pushed = ds.join_count("pts", "t", predicate="dwithin", distance=0.1)
    assert ctr.value > before, "pushdown path did not engage"
    with config.JOIN_PUSHDOWN.scoped("false"):
        plain = ds.join_count("pts", "t", predicate="dwithin", distance=0.1)
    full = ds.join("pts", "t", predicate="dwithin", distance=0.1)
    assert pushed == plain == full.count

    _, _, _, _, total, stats = ds._join_pushdown_count(
        "pts", "t", "dwithin", 0.1, None, None, Query(), Query(),
        None, False)
    assert total == pushed
    pd = stats.pushdown
    assert pd["bytes_loaded"] < pd["bytes_side"], pd
    assert pd["groups_loaded"] < pd["groups_side"] * pd["chunks"], pd

    # bbox predicate rides the same window path
    pb = ds.join_count("pts", "t", predicate="bbox", dx=0.1, dy=0.1)
    fb = ds.join("pts", "t", predicate="bbox", dx=0.1, dy=0.1)
    assert pb == fb.count
