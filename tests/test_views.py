"""Merged/routed dataset views, age-off, schema update, query timeout,
and the GeoMesaStats API surface."""

import numpy as np
import pytest

from geomesa_tpu import GeoDataset
from geomesa_tpu.api.dataset import Query
from geomesa_tpu.views import MergedDatasetView, RoutedDatasetView

SPEC = "name:String:index=true,v:Integer,dtg:Date,*geom:Point"


def _make(seed, n=2000, t0="2020-01-01", t1="2020-02-01"):
    rng = np.random.default_rng(seed)
    ds = GeoDataset(n_shards=2)
    ds.create_schema("t", SPEC)
    lo = np.datetime64(t0).astype("datetime64[ms]").astype(np.int64)
    hi = np.datetime64(t1).astype("datetime64[ms]").astype(np.int64)
    data = {
        "geom__x": rng.uniform(-20, 20, n),
        "geom__y": rng.uniform(-20, 20, n),
        "dtg": rng.integers(lo, hi, n).astype("datetime64[ms]"),
        "name": rng.choice(["a", "b", "c"], n),
        "v": rng.integers(0, 100, n),
    }
    ds.insert("t", data, fids=np.array([f"{seed}-{i}" for i in range(n)]))
    ds.flush("t")
    return ds, data


def test_merged_count_density_stats():
    ds1, d1 = _make(1)
    ds2, d2 = _make(2)
    view = MergedDatasetView([ds1, ds2])
    ecql = "BBOX(geom, -10, -10, 10, 10)"
    want = sum(
        int((
            (d["geom__x"] >= -10) & (d["geom__x"] <= 10)
            & (d["geom__y"] >= -10) & (d["geom__y"] <= 10)
        ).sum())
        for d in (d1, d2)
    )
    assert view.count("t", ecql) == want
    grid = view.density("t", ecql, bbox=(-10, -10, 10, 10), width=32, height=32)
    assert abs(float(grid.sum()) - want) < 1e-2
    mm = view.stats("t", "MinMax(v)").value()
    allv = np.concatenate([d1["v"], d2["v"]])
    assert (mm["min"], mm["max"]) == (allv.min(), allv.max())
    assert view.unique("t", "name") == ["a", "b", "c"]
    b = view.bounds("t")
    assert b[0] <= -19 and b[2] >= 19


def test_merged_query_dedupe_sort_limit():
    ds1, _ = _make(1, n=500)
    ds2, _ = _make(1, n=500)  # identical fids -> full dedupe
    view = MergedDatasetView([ds1, ds2])
    fc = view.query("t", Query(ecql="INCLUDE"))
    assert len(fc) == 500  # deduped by fid
    fc = view.query("t", Query(ecql="INCLUDE", sort_by=[("v", False)],
                               max_features=50))
    assert len(fc) == 50
    v = fc.to_dict()["v"]
    assert list(v) == sorted(v)


def test_merged_string_columns_decoded():
    ds1, _ = _make(1, n=300)
    ds2, _ = _make(2, n=300)
    view = MergedDatasetView([ds1, ds2])
    fc = view.query("t", "name = 'a'")
    names = set(fc.to_dict()["name"])
    assert names == {"a"}


def test_routed_view_by_attribute():
    ds1, _ = _make(1)
    ds2, _ = _make(2)
    view = RoutedDatasetView([
        ({"name", "v"}, ds1),   # attribute queries -> ds1
        (set(), ds2),           # default route -> ds2
    ])
    assert view.route("t", "name = 'a'") is ds1
    assert view.route("t", "BBOX(geom, 0, 0, 5, 5)") is ds2
    assert view.count("t", "name = 'a'") == ds1.count("t", "name = 'a'")


def test_routed_view_by_callable():
    from geomesa_tpu.filter import ir

    hot, _ = _make(1, t0="2020-06-01", t1="2020-07-01")
    cold, _ = _make(2, t0="2020-01-01", t1="2020-02-01")

    def is_recent(f):
        iv = ir.extract_intervals(f, "dtg")
        june = np.datetime64("2020-06-01").astype("datetime64[ms]").astype(np.int64)
        return not iv.is_empty and all(lo >= june for lo, hi in iv.values)

    view = RoutedDatasetView([(is_recent, hot), (set(), cold)])
    q = "dtg DURING 2020-06-10T00:00:00Z/2020-06-20T00:00:00Z"
    assert view.route("t", q) is hot
    assert view.route("t", "v > 5") is cold


def test_age_off():
    ds, data = _make(3)
    cutoff = "2020-01-15T00:00:00Z"
    want_removed = int(
        (data["dtg"] < np.datetime64("2020-01-15")).sum()
    )
    removed = ds.age_off("t", cutoff)
    assert removed == want_removed
    assert ds.count("t") == len(data["dtg"]) - want_removed
    # no survivors older than the cutoff
    assert ds.count("t", "dtg BEFORE 2020-01-15T00:00:00Z") == 0


def test_update_schema_add_attribute():
    ds, data = _make(4, n=400)
    before = ds.count("t")
    ft = ds.update_schema("t", "score:Float")
    assert ft.has("score")
    assert ds.count("t") == before  # data retained
    fc = ds.query("t", Query(max_features=5))
    assert "score" in fc.to_dict()
    # old attribute queries still work
    assert ds.count("t", "name = 'a'") > 0
    # new data can use the new attribute
    ds.insert("t", {
        "geom__x": np.array([1.0]), "geom__y": np.array([2.0]),
        "dtg": np.array(["2020-03-01"], "datetime64[ms]"),
        "name": np.array(["a"], object), "v": np.array([1]),
        "score": np.array([0.5], np.float32),
    }, fids=np.array(["new-1"]))
    ds.flush("t")
    assert ds.count("t") == before + 1


def test_update_schema_rejects_geometry():
    ds, _ = _make(5, n=50)
    with pytest.raises(ValueError):
        ds.update_schema("t", "geom2:Point")


def test_update_schema_integer_add_and_visibility_preserved():
    ds = GeoDataset(n_shards=2)
    ds.create_schema("t", SPEC)
    base = {
        "geom__x": np.array([1.0, 2.0]), "geom__y": np.array([3.0, 4.0]),
        "dtg": np.array(["2020-01-01", "2020-01-02"], "datetime64[ms]"),
        "name": np.array(["a", "b"], object), "v": np.array([1, 2]),
    }
    ds.insert("t", base, fids=np.array(["f1", "f2"]),
              visibilities=["admin", ""])
    ds.flush("t")
    assert ds.count("t", Query(auths=[])) == 1  # only the unlabelled row
    ds.update_schema("t", "age:Integer")
    # visibility labels survive the migration
    assert ds.count("t", Query(auths=[])) == 1
    assert ds.count("t", Query(auths=["admin"])) == 2
    # integer null-fill: zeros (documented fixed-width null representation)
    fc = ds.query("t", Query(auths=["admin"]))
    assert list(fc.to_dict()["age"]) == [0, 0]


def test_update_schema_polygon_geometry():
    ds = GeoDataset(n_shards=2)
    ds.create_schema("p", "dtg:Date,*geom:Polygon")
    ds.insert("p", {
        "geom": np.array(["POLYGON ((0 0, 4 0, 4 4, 0 4, 0 0))"], object),
        "dtg": np.array(["2020-01-01"], "datetime64[ms]"),
    }, fids=np.array(["p1"]))
    ds.flush("p")
    ds.update_schema("p", "score:Float")
    assert ds.count("p") == 1
    # extent predicates still work after migration
    assert ds.count("p", "BBOX(geom, 1, 1, 2, 2)") == 1
    assert ds.count("p", "BBOX(geom, 10, 10, 12, 12)") == 0


def test_update_schema_with_user_data():
    ds = GeoDataset(n_shards=2)
    ds.create_schema(
        "u", "v:Integer,dtg:Date,*geom:Point;geomesa.z3.interval='day'"
    )
    ds.insert("u", {
        "geom__x": np.array([1.0]), "geom__y": np.array([2.0]),
        "dtg": np.array(["2020-01-01"], "datetime64[ms]"),
        "v": np.array([7]),
    }, fids=np.array(["x1"]))
    ds.flush("u")
    ft = ds.update_schema("u", "score:Float")
    assert ft.has("score")
    assert ds.count("u") == 1
    assert ft.time_period == ds.get_schema("u").time_period


def test_merged_sort_descending_stable():
    """Descending primary key must not reverse the secondary key's order."""
    a = GeoDataset(n_shards=2)
    a.create_schema("t", SPEC)
    a.insert("t", {
        "geom__x": np.zeros(4), "geom__y": np.zeros(4),
        "dtg": np.array(["2020-01-01"] * 4, "datetime64[ms]"),
        "name": np.array(["b", "b", "a", "a"], object),
        "v": np.array([4, 2, 3, 1]),
    }, fids=np.array(["r1", "r2", "r3", "r4"]))
    a.flush("t")
    view = MergedDatasetView([a])
    fc = view.query("t", Query(sort_by=[("name", True), ("v", False)]))
    d = fc.to_dict()
    assert list(zip(d["name"], [int(x) for x in d["v"]])) == [
        ("b", 2), ("b", 4), ("a", 1), ("a", 3),
    ]


def test_merged_query_unknown_schema():
    ds, _ = _make(9, n=10)
    view = MergedDatasetView([ds])
    with pytest.raises(KeyError):
        view.query("nope")


def test_merged_sort_is_lexicographic():
    a = GeoDataset(n_shards=2)
    a.create_schema("t", SPEC)
    a.insert("t", {
        "geom__x": np.array([0.0, 0.0]), "geom__y": np.array([0.0, 0.0]),
        "dtg": np.array(["2020-01-01", "2020-01-01"], "datetime64[ms]"),
        "name": np.array(["zeta", "alpha"], object), "v": np.array([1, 2]),
    }, fids=np.array(["a1", "a2"]))
    a.flush("t")
    b = GeoDataset(n_shards=2)
    b.create_schema("t", SPEC)
    b.insert("t", {
        "geom__x": np.array([0.0, 0.0]), "geom__y": np.array([0.0, 0.0]),
        "dtg": np.array(["2020-01-01", "2020-01-01"], "datetime64[ms]"),
        "name": np.array(["mike", "beta"], object), "v": np.array([3, 4]),
    }, fids=np.array(["b1", "b2"]))
    b.flush("t")
    view = MergedDatasetView([a, b])
    fc = view.query("t", Query(sort_by=[("name", False)]))
    assert fc.to_dict()["name"] == ["alpha", "beta", "mike", "zeta"]


def test_query_timeout(monkeypatch):
    from geomesa_tpu.planning.executor import QueryTimeoutError

    ds, _ = _make(6, n=5000)
    monkeypatch.setenv("GEOMESA_QUERY_TIMEOUT", "0ms")
    # force the host path so the per-shard deadline check runs
    ds.prefer_device = False
    ds._executors.clear()
    with pytest.raises(QueryTimeoutError):
        ds.count("t", "BBOX(geom, -10, -10, 10, 10)")
    monkeypatch.delenv("GEOMESA_QUERY_TIMEOUT")
    assert ds.count("t", "BBOX(geom, -10, -10, 10, 10)") > 0


def test_stats_api_surface():
    ds, data = _make(7)
    h = ds.histogram("t", "v", bins=10)
    assert h.counts.sum() == len(data["v"])
    f = ds.frequency("t", "v", width=1024)
    assert f.count(5) >= int((data["v"] == 5).sum())  # count-min overestimates
    tk = ds.top_k("t", "name", k=2)
    assert len(tk) == 2 and tk[0][1] >= tk[1][1]
    mm = ds.min_max("t", "v", exact=False)  # persisted sketch path
    assert (mm["min"], mm["max"]) == (data["v"].min(), data["v"].max())
    z = ds.z3_histogram("t")
    assert z is not None and not z.is_empty


def test_tokenless_plan_windows_not_stale():
    """Reusing a raw-IR plan object across a mutation must see new rows
    (regression: cached device window arrays outliving store.version)."""
    from geomesa_tpu.filter import parse_ecql

    ds, _ = _make(11, n=1000)
    st = ds._store("t")
    ex = ds._executor(st)
    from geomesa_tpu.planning.planner import QueryPlanner

    plan = QueryPlanner(st).plan(parse_ecql("BBOX(geom, -20, -20, 20, 20)"))
    assert plan.__dict__.get("cache_token") is None
    c1 = ex.count(plan)
    ds.insert("t", {
        "geom__x": np.array([0.0]), "geom__y": np.array([0.0]),
        "dtg": np.array(["2020-01-15"], "datetime64[ms]"),
        "name": np.array(["a"], object), "v": np.array([1]),
    }, fids=np.array(["fresh"]))
    ds.flush("t")
    plan2 = QueryPlanner(st).plan(parse_ecql("BBOX(geom, -20, -20, 20, 20)"))
    assert ex.count(plan2) == c1 + 1
    # the ORIGINAL plan object, re-executed, must also see the new row
    assert ex.count(plan) == c1 + 1
