"""Resilience layer: policies, deterministic fault injection, and typed
partial-result degradation (docs/RESILIENCE.md).

The chaos scenarios here are the acceptance contract of the layer — every
seeded fault ends in a successful retry or a TYPED outcome (``Degraded``
account / ``QueryTimeoutError``), never a hang, a dead consumer, or a
silently wrong aggregate: degraded totals must equal the sum over the
partitions that survived.
"""

import glob
import os

import numpy as np
import pytest

from geomesa_tpu import GeoDataset, audit, config, resilience
from geomesa_tpu.filter.ecql import parse_iso_ms
from geomesa_tpu.resilience import (
    CircuitBreaker, CircuitOpenError, Deadline, InjectedFault, QueryTimeoutError,
    RetryPolicy, allow_partial, check_deadline, deadline_scope, fault_point,
    inject_faults,
)

SPEC = "name:String:index=true,weight:Double,dtg:Date,*geom:Point"
PSPEC = SPEC + ";geomesa.partition='time'"


def _data(n=3000, seed=7):
    rng = np.random.default_rng(seed)
    return {
        "name": [f"actor{i % 5}" for i in range(n)],
        "weight": rng.uniform(0, 10, n),
        "dtg": rng.integers(
            parse_iso_ms("2020-01-01"), parse_iso_ms("2020-02-15"), n
        ).astype("datetime64[ms]"),
        "geom__x": rng.uniform(-120, -70, n),
        "geom__y": rng.uniform(25, 50, n),
    }


# ---------------------------------------------------------------------------
# policy units
# ---------------------------------------------------------------------------


def test_retry_policy_deterministic_backoff():
    mk = lambda: RetryPolicy(  # noqa: E731
        attempts=5, base_ms=10, max_ms=60, jitter=0.5, seed=123
    )
    a, b = mk().delays_ms(), mk().delays_ms()
    assert a == b  # seeded jitter replays identically
    assert len(a) == 4
    # exponential shape under the cap: un-jittered would be 10, 20, 40, 60
    for d, hi in zip(a, (10, 20, 40, 60)):
        assert hi * 0.5 <= d <= hi


def test_retry_policy_retries_then_succeeds():
    calls = []

    def flaky():
        calls.append(1)
        if len(calls) < 3:
            raise OSError("transient")
        return "ok"

    p = RetryPolicy(attempts=3, base_ms=1, jitter=0.0, sleep=lambda s: None)
    assert p.call(flaky) == "ok"
    assert len(calls) == 3


def test_retry_policy_respects_classification_and_attempts():
    p = RetryPolicy(attempts=3, base_ms=1, jitter=0.0, sleep=lambda s: None)
    calls = []

    def fatal():
        calls.append(1)
        raise ValueError("bad request")

    with pytest.raises(ValueError):
        p.call(fatal, retryable=lambda e: isinstance(e, OSError))
    assert len(calls) == 1  # fatal: no retry

    calls.clear()

    def always():
        calls.append(1)
        raise OSError("down")

    with pytest.raises(OSError):
        RetryPolicy(attempts=3, base_ms=1, jitter=0.0,
                    sleep=lambda s: None).call(always)
    assert len(calls) == 3  # attempts exhausted


def test_retry_policy_stops_at_deadline():
    calls = []

    def always():
        calls.append(1)
        raise OSError("down")

    with deadline_scope(0.0) as d:
        with pytest.raises(OSError):
            RetryPolicy(attempts=10, base_ms=1, jitter=0.0,
                        sleep=lambda s: None).call(always, deadline=d)
    assert len(calls) == 1  # no budget left: first failure is final


def test_deadline_scope_and_nesting():
    with deadline_scope(None):
        check_deadline()  # unlimited: no-op
        with deadline_scope(0.0):
            with pytest.raises(QueryTimeoutError):
                check_deadline()
        check_deadline()  # inner scope popped
    assert resilience.current_deadline() is resilience.UNLIMITED
    assert Deadline.after(None).remaining_s() is None
    assert Deadline.after(100.0).remaining_s() > 99.0


def test_circuit_breaker_states():
    clock = [0.0]
    b = CircuitBreaker("t", threshold=3, reset_ms=1000, clock=lambda: clock[0])
    for _ in range(2):
        b.record_failure()
    b.allow()  # still closed below threshold
    b.record_failure()
    with pytest.raises(CircuitOpenError) as ei:
        b.allow()
    assert ei.value.retry_after_s <= 1.0
    clock[0] = 1.5
    assert b.state == CircuitBreaker.HALF_OPEN
    b.allow()  # trial call admitted
    b.record_failure()  # trial failed: re-open
    with pytest.raises(CircuitOpenError):
        b.allow()
    clock[0] = 3.0
    b.allow()
    b.record_success()
    assert b.state == CircuitBreaker.CLOSED


def test_circuit_breaker_half_open_admits_one_trial():
    # ROADMAP open item: half-open must probe with ONE in-flight trial,
    # not admit every concurrent caller
    clock = [0.0]
    b = CircuitBreaker("t1", threshold=1, reset_ms=1000, clock=lambda: clock[0])
    b.record_failure()
    clock[0] = 1.5
    assert b.state == CircuitBreaker.HALF_OPEN
    b.allow()  # the trial
    # a second caller while the trial is in flight is fenced
    with pytest.raises(CircuitOpenError):
        b.allow()
    # trial succeeds -> closed -> everyone admitted again
    b.record_success()
    assert b.state == CircuitBreaker.CLOSED
    b.allow()
    b.allow()


def test_circuit_breaker_half_open_trial_failure_reopens_and_refences():
    clock = [0.0]
    b = CircuitBreaker("t2", threshold=1, reset_ms=1000, clock=lambda: clock[0])
    b.record_failure()
    clock[0] = 1.5
    b.allow()  # trial admitted
    b.record_failure()  # trial failed: re-open
    with pytest.raises(CircuitOpenError):
        b.allow()
    # next window: a NEW single trial is admitted
    clock[0] = 3.0
    b.allow()
    with pytest.raises(CircuitOpenError):
        b.allow()
    b.record_success()


def test_circuit_breaker_superseded_trial_success_does_not_close():
    # a slow trial outlives its staleness window; a fresher trial is
    # admitted. The stale trial's LATE success (different thread) must
    # not close the circuit over the live trial's head; the live trial's
    # own report decides.
    import threading as _threading

    clock = [0.0]
    b = CircuitBreaker("t4", threshold=1, reset_ms=1000, clock=lambda: clock[0])
    b.record_failure()
    clock[0] = 1.5
    t = _threading.Thread(target=b.allow)  # trial 1, on its own thread
    t.start(); t.join()
    clock[0] = 2.6
    b.allow()  # trial 2 supersedes (this thread)
    t = _threading.Thread(target=b.record_success)  # trial 1's late report
    t.start(); t.join()
    assert b.state == CircuitBreaker.HALF_OPEN  # NOT closed
    b.record_success()  # the live trial decides
    assert b.state == CircuitBreaker.CLOSED


def test_circuit_breaker_stuck_trial_does_not_wedge_half_open():
    # a trial whose caller died without recording an outcome must not
    # fence the breaker forever: after a full reset window a new trial
    # is admitted
    clock = [0.0]
    b = CircuitBreaker("t3", threshold=1, reset_ms=1000, clock=lambda: clock[0])
    b.record_failure()
    clock[0] = 1.5
    b.allow()  # trial never reports back
    with pytest.raises(CircuitOpenError):
        b.allow()
    clock[0] = 2.6  # >= reset_ms past the stuck trial's start
    b.allow()
    b.record_success()
    assert b.state == CircuitBreaker.CLOSED


# ---------------------------------------------------------------------------
# fault injection plumbing
# ---------------------------------------------------------------------------


def test_fault_point_is_noop_when_uninstalled():
    assert resilience._injector is None  # off by default
    fault_point("anything.at.all", extra=1)  # must not raise


def test_inject_faults_requires_config_flag():
    with pytest.raises(RuntimeError, match="geomesa.fault.injection"):
        with inject_faults():
            pass


def test_injector_deterministic_and_bounded():
    with config.FAULT_INJECTION.scoped("true"):
        with inject_faults(seed=3) as inj:
            rule = inj.fail("edge.*", times=2)
            for _ in range(2):
                with pytest.raises(InjectedFault):
                    fault_point("edge.read")
            fault_point("edge.read")  # rule exhausted
            fault_point("other.site")  # never matched
            assert rule.hits == 2
            assert [s for s, _ in inj.fired] == ["edge.read", "edge.read"]
    fault_point("edge.read")  # uninstalled again


def test_injector_probabilistic_rules_replay_identically():
    def run(seed):
        fired = []
        with config.FAULT_INJECTION.scoped("true"):
            with inject_faults(seed=seed) as inj:
                inj.fail("p.*", times=None, p=0.5)
                for i in range(20):
                    try:
                        fault_point("p.x")
                        fired.append(0)
                    except InjectedFault:
                        fired.append(1)
        return fired

    assert run(9) == run(9)  # seeded: same coin flips
    assert 0 < sum(run(9)) < 20  # actually probabilistic


# ---------------------------------------------------------------------------
# chaos scenario 1: flaky Flight call -> retry succeeds; fatal -> no retry
# ---------------------------------------------------------------------------


@pytest.fixture()
def flight():
    from geomesa_tpu.sidecar import GeoFlightClient, GeoFlightServer

    resilience.reset_breakers()
    srv = GeoFlightServer(GeoDataset(n_shards=2, prefer_device=False))
    ds = srv.dataset
    ds.create_schema("t", SPEC)
    ds.insert("t", _data(500), fids=[f"f{i}" for i in range(500)])
    ds.flush("t")
    with GeoFlightClient(f"grpc+tcp://127.0.0.1:{srv.port}",
                         retry_seed=1) as client:
        yield srv, client
    srv.shutdown()
    resilience.reset_breakers()


def test_flaky_flight_call_retries_to_success(flight):
    import pyarrow.flight as fl

    _, client = flight
    with config.FAULT_INJECTION.scoped("true"), \
            config.RETRY_BASE_MS.scoped("1"):
        with inject_faults(seed=5) as inj:
            rule = inj.fail(
                "sidecar.do_action",
                lambda: fl.FlightUnavailableError("sidecar restarting"),
                times=2,
            )
            assert client.count("t") == 500  # 2 failures, then success
            assert rule.hits == 2
    assert client._breaker.state == CircuitBreaker.CLOSED


def test_fatal_flight_error_does_not_retry(flight):
    import pyarrow.flight as fl

    from geomesa_tpu.sidecar.client import error_code, is_retryable

    _, client = flight
    with pytest.raises(fl.FlightServerError) as ei:
        client.query("t", "NOT REAL ECQL ((")
    assert error_code(ei.value) == "GM-ARG"
    assert not is_retryable(ei.value)
    # uncoded transport failures stay retryable
    assert is_retryable(fl.FlightUnavailableError("conn refused"))


def test_server_timeout_maps_to_typed_error(flight, monkeypatch):
    _, client = flight
    monkeypatch.setenv("GEOMESA_QUERY_TIMEOUT", "0ms")
    with pytest.raises(QueryTimeoutError):
        client.count("t")
    monkeypatch.delenv("GEOMESA_QUERY_TIMEOUT")
    assert client.count("t") == 500  # recovers once the budget is sane


def test_breaker_fences_repeated_failures(flight):
    import pyarrow.flight as fl

    _, client = flight
    with config.FAULT_INJECTION.scoped("true"), \
            config.RETRY_ATTEMPTS.scoped("1"), \
            config.BREAKER_THRESHOLD.scoped("2"):
        resilience.reset_breakers()
        from geomesa_tpu.sidecar import GeoFlightClient

        with GeoFlightClient(client.location, retry_seed=2) as c2:
            with inject_faults(seed=5) as inj:
                inj.fail("sidecar.do_action",
                         lambda: fl.FlightUnavailableError("down"),
                         times=None)
                for _ in range(2):
                    with pytest.raises(fl.FlightUnavailableError):
                        c2.count("t")
                # threshold hit: calls now fail fast without touching the wire
                with pytest.raises(CircuitOpenError):
                    c2.count("t")
    resilience.reset_breakers()


def test_client_timeout_tightens_to_deadline(flight):
    _, client = flight
    with config.SIDECAR_TIMEOUT.scoped("30 s"):
        assert client._effective_timeout_s() == pytest.approx(30.0)
        with deadline_scope(2.0):
            assert client._effective_timeout_s() <= 2.0
    # a default is always configured: no call can hang forever
    assert config.SIDECAR_TIMEOUT.default is not None


# ---------------------------------------------------------------------------
# chaos scenario 2: corrupt partition file -> quarantine + typed degradation
# ---------------------------------------------------------------------------


@pytest.fixture()
def fs_store(tmp_path):
    from geomesa_tpu.fs.storage import DateTimeScheme, FileSystemStorage
    from geomesa_tpu.schema.feature_type import FeatureType

    fs = FileSystemStorage(str(tmp_path))
    ft = FeatureType.from_spec("t", SPEC)
    fs.create(ft, DateTimeScheme("month"))
    fs.write("t", _data(2000))
    assert len(fs.partitions("t")) == 2  # jan + feb
    return fs


def _corrupt_one_file(fs, name="t"):
    files = sorted(glob.glob(os.path.join(fs.root, name, "data", "**", "*.parquet"),
                             recursive=True))
    assert files
    with open(files[0], "wb") as fh:
        fh.write(b"\x00garbage not parquet\xff" * 32)
    return files[0]


def test_corrupt_partition_strict_read_raises(fs_store):
    _corrupt_one_file(fs_store)
    with pytest.raises(Exception):
        fs_store.read("t")


def test_corrupt_partition_degrades_with_exact_surviving_total(fs_store):
    full = fs_store.read("t")
    per_part = {p: fs_store.read_partition("t", p).num_rows
                for p in fs_store.partitions("t")}
    assert sum(per_part.values()) == full.num_rows == 2000

    bad = _corrupt_one_file(fs_store)
    audit.degradations.clear()
    pr = fs_store.read_partial("t")
    assert pr.degraded
    assert [s.part for s in pr.skipped] == [bad]
    # the degraded aggregate equals the sum over SURVIVING partition files —
    # never an estimate, never silently the old total
    bad_part = pr.skipped[0].phase
    survivors = sum(n for p, n in per_part.items() if p != bad_part)
    assert pr.value.num_rows == survivors
    assert 0 < pr.value.num_rows < 2000
    assert pr.ok_parts == pr.total_parts - 1
    # quarantined: later reads skip without re-parsing; strict still raises
    assert bad in fs_store.quarantined()
    with pytest.raises(Exception):
        fs_store.read("t")
    # recorded through the audit degradation trail
    assert any(e.part == bad for e in audit.degradations.recent())


def test_corrupt_partition_config_flag_degrades_plain_read(fs_store):
    _corrupt_one_file(fs_store)
    with config.SCAN_PARTIAL.scoped("true"):
        t = fs_store.read("t")
    assert 0 < t.num_rows < 2000


def test_missing_column_is_schema_error_not_corruption(fs_store):
    # a requested-but-missing column must raise (schema-evolution contract)
    # WITHOUT quarantining the healthy file, even under partial mode
    with config.SCAN_PARTIAL.scoped("true"):
        with pytest.raises(KeyError):
            fs_store.read("t", columns=["name", "not_a_column"])
    assert not fs_store.quarantined()
    assert fs_store.read("t").num_rows == 2000  # file still healthy


def test_every_file_corrupt_degrades_to_empty_not_error(fs_store):
    for f in glob.glob(os.path.join(fs_store.root, "t", "data", "**",
                                    "*.parquet"), recursive=True):
        with open(f, "wb") as fh:
            fh.write(b"\xde\xad")
    pr = fs_store.read_partial("t")
    assert pr.degraded and pr.ok_parts == 0
    assert pr.value.num_rows == 0  # typed empty survivor set, not a crash


def test_transient_oserror_retries_in_place(fs_store):
    # an NFS blip (OSError) heals within one read via RetryPolicy: two
    # injected failures, the third attempt succeeds — nothing quarantined
    with config.FAULT_INJECTION.scoped("true"), \
            config.RETRY_BASE_MS.scoped("0"):
        with inject_faults(seed=0) as inj:
            inj.fail("fs.read_partition", OSError("stale NFS handle"),
                     times=2)
            assert fs_store.read("t").num_rows == 2000
    assert not fs_store.quarantined()


def test_transient_oserror_never_quarantines_partition(fs_store):
    # retries exhausted: the read fails (strict) or degrades (partial),
    # but the file is NOT quarantined — the next read re-attempts it, so
    # one blip cannot lose the partition until restart (ROADMAP item)
    with config.FAULT_INJECTION.scoped("true"), \
            config.RETRY_BASE_MS.scoped("0"), \
            config.RETRY_ATTEMPTS.scoped("1"):
        with inject_faults(seed=0) as inj:
            inj.fail("fs.read_partition", OSError("EIO"), times=None)
            with pytest.raises(OSError):
                fs_store.read("t")
            with config.SCAN_PARTIAL.scoped("true"):
                assert fs_store.read("t").num_rows < 2000
    assert not fs_store.quarantined()
    # the blip passed (injector gone): full data is back, no restart needed
    assert fs_store.read("t").num_rows == 2000


def test_clear_quarantine_readmits_repaired_file(fs_store):
    files = sorted(glob.glob(os.path.join(
        fs_store.root, "t", "data", "**", "*.parquet"), recursive=True))
    good = open(files[0], "rb").read()
    bad = _corrupt_one_file(fs_store)
    with config.SCAN_PARTIAL.scoped("true"):
        assert fs_store.read("t").num_rows < 2000
    assert bad in fs_store.quarantined()
    # operator repairs the file, then re-admits it
    with open(bad, "wb") as fh:
        fh.write(good)
    assert fs_store.clear_quarantine(bad) == [bad]
    assert not fs_store.quarantined()
    assert fs_store.read("t").num_rows == 2000
    # clearing an unknown path is a no-op
    assert fs_store.clear_quarantine("/nope") == []


def test_metadata_save_is_atomic(fs_store, monkeypatch):
    import geomesa_tpu.fs.storage as stmod

    count0 = fs_store.count("t")

    def torn(obj, fh, **kw):  # crash mid-serialization
        fh.write('{"spec": "tor')
        raise RuntimeError("crash mid-write")

    monkeypatch.setattr(stmod.json, "dump", torn)
    with pytest.raises(RuntimeError, match="crash mid-write"):
        fs_store.write("t", _data(50))
    monkeypatch.undo()
    # the torn temp never replaced the real metadata, and no debris remains
    assert fs_store.count("t") == count0
    assert fs_store.read("t").num_rows == 2000
    assert not glob.glob(os.path.join(fs_store.root, "t", "*.tmp"))


def test_metadata_save_fault_point(fs_store):
    with config.FAULT_INJECTION.scoped("true"):
        with inject_faults(seed=0) as inj:
            inj.fail("fs.write_meta", times=1)
            with pytest.raises(InjectedFault):
                fs_store.write("t", _data(50))
    assert fs_store.count("t") == 2000  # old metadata intact
    assert fs_store.read("t").num_rows == 2000


# ---------------------------------------------------------------------------
# chaos scenario 3: poison stream message -> quarantine, consumer survives
# ---------------------------------------------------------------------------


def test_poison_stream_message_quarantined():
    from geomesa_tpu.stream.live import StreamingDataset

    ds = StreamingDataset()
    ds.create_schema("t", "name:String,*geom:Point")
    ds.write("t", {"name": ["a", "b"], "geom": [(0.0, 0.0), (1.0, 1.0)]},
             fids=["f0", "f1"])
    # a poison blob lands on the topic between two valid batches
    topic = ds._topics["t"]
    topic._logs[0].append(b"\x01\x02 not a geomessage")
    ds.write("t", {"name": ["c"], "geom": [(2.0, 2.0)]}, fids=["f2"])

    audit.degradations.clear()
    n = ds.poll("t")
    assert n == 3                      # every VALID message applied
    assert ds.quarantined["t"] == 1    # the poison one counted + skipped
    assert len(ds.cache("t")) == 3     # consumer alive, state correct
    assert ds.count("t") == 3
    assert any(e.source == "stream.poll.decode"
               for e in audit.degradations.recent())
    # the offset advanced PAST the poison message: no repeat quarantine
    assert ds.poll("t") == 0
    assert ds.quarantined["t"] == 1


def test_unappliable_message_quarantined_not_fatal():
    from geomesa_tpu.stream.live import StreamingDataset
    from geomesa_tpu.stream.messages import GeoMessage

    ds = StreamingDataset()
    ds.create_schema("t", "name:String,*geom:Point")
    # decodes fine but the geometry payload is garbage for the cache
    ds._topics["t"].send(GeoMessage.change("bad", {"geom": "not-a-point"}, 1))
    ds.write("t", {"name": ["a"], "geom": [(0.0, 0.0)]}, fids=["f0"])
    assert ds.poll("t") == 1
    assert ds.quarantined["t"] == 1
    assert ds.count("t") == 1


def test_poison_via_fault_injection_seeded():
    from geomesa_tpu.stream.live import StreamingDataset

    ds = StreamingDataset()
    ds.create_schema("t", "name:String,*geom:Point")
    ds.write("t", {"name": list("abcd"),
                   "geom": [(float(i), 0.0) for i in range(4)]},
             fids=[f"f{i}" for i in range(4)])
    with config.FAULT_INJECTION.scoped("true"):
        with inject_faults(seed=11) as inj:
            inj.fail("stream.poll.decode", times=1)
            assert ds.poll("t") == 3  # one injected poison, three applied
    assert ds.quarantined["t"] == 1
    assert ds.count("t") == 3


def test_throwing_listener_does_not_kill_consumer():
    from geomesa_tpu.stream.live import StreamingDataset

    ds = StreamingDataset()
    ds.create_schema("t", "name:String,*geom:Point")
    seen = []
    ds.add_listener("t", lambda m: seen.append(m.fid))
    ds.add_listener("t", lambda m: 1 / 0)
    ds.write("t", {"name": ["a", "b"], "geom": [(0.0, 0.0), (1.0, 1.0)]},
             fids=["f0", "f1"])
    assert ds.poll("t") == 2
    assert len(ds.cache("t")) == 2
    assert sorted(seen) == ["f0", "f1"]


# ---------------------------------------------------------------------------
# chaos scenario 4: partition scan faults + deadlines on partitioned scans
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def pds(tmp_path_factory):
    from geomesa_tpu.index.partitioned import PartitionedFeatureStore

    ds = GeoDataset(n_shards=4, prefer_device=False)
    ds.create_schema("t", PSPEC)
    st = ds._store("t")
    assert isinstance(st, PartitionedFeatureStore)
    st.max_resident = 2
    st._spill_dir = str(tmp_path_factory.mktemp("spill"))
    n = 6000
    ds.insert("t", _data(n), fids=np.arange(n).astype(str))
    ds.flush("t")
    return ds, n


def test_partition_scan_fault_strict_raises(pds):
    ds, _ = pds
    with config.FAULT_INJECTION.scoped("true"):
        with inject_faults(seed=2) as inj:
            inj.fail("exec.partition.scan", times=1)
            with pytest.raises(InjectedFault):
                ds.count("t")
    assert ds.count("t") == pds[1]  # store healthy afterwards


def test_partition_scan_fault_degrades_to_exact_survivor_totals(pds):
    ds, n = pds
    st = ds._store("t")
    per_bin = {b: st.child(b).count for b in st.partition_bins()}
    assert sum(per_bin.values()) == n

    with config.FAULT_INJECTION.scoped("true"):
        with inject_faults(seed=2) as inj:
            inj.fail("exec.partition.scan", times=1)
            with allow_partial() as partial:
                degraded = ds.count("t")
    assert partial.degraded and len(partial.skipped) == 1
    failed_bin = int(partial.skipped[0].part.split(":")[1])
    # the degraded aggregate equals the EXACT sum over surviving partitions
    assert degraded == n - per_bin[failed_bin]
    # the query audit event carries the skipped-partition account
    ev = ds.audit.recent(1)[0]
    assert ev.hints.get("degraded") and \
        ev.hints["degraded"][0]["part"] == f"bin:{failed_bin}"


def test_partition_density_degrades_additively(pds):
    ds, n = pds
    st = ds._store("t")
    per_bin = {b: st.child(b).count for b in st.partition_bins()}
    world = (-180.0, -90.0, 180.0, 90.0)
    full = ds.density("t", bbox=world, width=64, height=64)
    assert full.sum() == pytest.approx(n)

    with config.FAULT_INJECTION.scoped("true"):
        with inject_faults(seed=4) as inj:
            inj.fail("exec.partition.scan", times=1)
            with allow_partial() as partial:
                grid = ds.density("t", bbox=world, width=64, height=64)
    failed_bin = int(partial.skipped[0].part.split(":")[1])
    # degraded density = full density minus exactly the failed partition
    assert grid.sum() == pytest.approx(n - per_bin[failed_bin])


def test_partition_query_features_degrade(pds):
    ds, n = pds
    with config.FAULT_INJECTION.scoped("true"):
        with inject_faults(seed=6) as inj:
            inj.fail("exec.partition.scan", times=1)
            with allow_partial() as partial:
                fc = ds.query("t")
    assert partial.degraded
    assert 0 < len(fc) < n


def test_query_deadline_partitioned_scan_paths(pds, monkeypatch):
    ds, _ = pds
    monkeypatch.setenv("GEOMESA_QUERY_TIMEOUT", "0ms")
    with pytest.raises(QueryTimeoutError):
        ds.count("t")
    with pytest.raises(QueryTimeoutError):
        ds.query("t", "BBOX(geom, -100, 30, -80, 45)")
    with pytest.raises(QueryTimeoutError):
        ds.density("t", bbox=(-180, -90, 180, 90), width=32, height=32)
    with pytest.raises(QueryTimeoutError):
        ds.stats("t", "MinMax(weight)")
    # a deadline is NEVER degradable: partial mode must still raise (a
    # timed-out scan masquerading as degraded-but-complete would be a
    # silently wrong answer)
    with allow_partial():
        with pytest.raises(QueryTimeoutError):
            ds.count("t")
    monkeypatch.delenv("GEOMESA_QUERY_TIMEOUT")
    assert ds.count("t") == pds[1]


def test_query_deadline_multishard_single_store(monkeypatch):
    """Satellite coverage: the deadline fires on the plain multi-shard
    (non-partitioned) host path too, between per-shard passes."""
    ds = GeoDataset(n_shards=8, prefer_device=False)
    ds.create_schema("t", SPEC)
    ds.insert("t", _data(4000), fids=np.arange(4000).astype(str))
    ds.flush("t")
    monkeypatch.setenv("GEOMESA_QUERY_TIMEOUT", "0ms")
    with pytest.raises(QueryTimeoutError):
        ds.count("t")
    with pytest.raises(QueryTimeoutError):
        ds.query("t", "name = 'actor1'")
    monkeypatch.delenv("GEOMESA_QUERY_TIMEOUT")
    assert ds.count("t") == 4000


# ---------------------------------------------------------------------------
# drained mid-stream continuation -> re-open on a healthy replica
# ---------------------------------------------------------------------------


@pytest.mark.filterwarnings(
    "ignore::pytest.PytestUnhandledThreadExceptionWarning")
def test_drained_stream_reopens_on_healthy_replica():
    """The fleet-failover building block (docs/RESILIENCE.md §6/§7): a
    sidecar stream whose serving slot dies mid-stream fails typed
    ``[GM-DRAINING]`` — RETRYABLE, never resumed — and a fresh open of
    the same query on a HEALTHY replica returns the complete, identical
    result set. The client already re-raises the drain as retryable;
    this proves the re-open actually works."""
    from geomesa_tpu.resilience import DeviceDrainError
    from geomesa_tpu.sidecar import GeoFlightClient, GeoFlightServer
    from geomesa_tpu.sidecar.client import is_retryable

    def mkds():
        ds = GeoDataset(n_shards=2, prefer_device=False)
        ds.create_schema("t", SPEC + ";geomesa.partition='time'")
        ds.insert("t", _data(3000), fids=np.arange(3000).astype(str))
        ds.flush("t")
        return ds

    oracle = mkds()
    want = sorted(
        str(v) for v in oracle.query("t", "name = 'actor1'")
        .to_dict()["name"]
    )
    n_want = oracle.count("t", "name = 'actor1'")
    assert n_want > 0

    srv_a = GeoFlightServer(mkds())
    hits = {"n": 0}

    def after_chunks(ctx):
        # let the stream OPEN and serve at least one chunk before the
        # dispatcher dies (hit 1 = the opening do_get ticket)
        hits["n"] += 1
        return hits["n"] > 2

    try:
        with config.FAULT_INJECTION.scoped("true"), \
                config.RETRY_ATTEMPTS.scoped("1"), \
                inject_faults(seed=21) as inj:
            inj.fail("serving.slot.loop", SystemExit("chaos kill"),
                     times=1, where=after_chunks)
            with GeoFlightClient(
                f"grpc+tcp://127.0.0.1:{srv_a.port}"
            ) as ca:
                with pytest.raises(Exception) as ei:
                    ca.query("t", "name = 'actor1'")
        # typed + retryable: the caller's cue to RE-OPEN, never resume
        err = ei.value
        assert isinstance(err, DeviceDrainError) \
            or "GM-DRAINING" in str(err), repr(err)
        assert is_retryable(err), repr(err)
        # re-open on a healthy replica: complete and identical
        srv_b = GeoFlightServer(mkds())
        try:
            with GeoFlightClient(
                f"grpc+tcp://127.0.0.1:{srv_b.port}"
            ) as cb:
                got = cb.query("t", "name = 'actor1'")
            assert got.num_rows == n_want
            assert sorted(got["name"].to_pylist()) == want
        finally:
            srv_b.shutdown()
        # and the DRAINED server heals too (supervisor respawned the
        # slot): a re-open there also completes — failover never had to
        # write the replica off permanently
        with GeoFlightClient(
            f"grpc+tcp://127.0.0.1:{srv_a.port}"
        ) as ca2:
            assert ca2.query("t", "name = 'actor1'").num_rows == n_want
    finally:
        srv_a.shutdown()


# ---------------------------------------------------------------------------
# disabled-path guarantees
# ---------------------------------------------------------------------------


def test_resilience_defaults_off():
    assert config.FAULT_INJECTION.to_bool() is False
    assert config.SCAN_PARTIAL.to_bool() is False
    assert resilience._injector is None
    assert not resilience.partial_allowed()


def test_degraded_unwrap_is_strict():
    pr = resilience.PartialResult(value=41, skipped=[], total_parts=1, ok_parts=1)
    assert not pr.degraded and pr.unwrap() == 41
    pr = resilience.PartialResult(
        value=41,
        skipped=[resilience.Skipped("s", "p", "boom")],
        total_parts=2, ok_parts=1,
    )
    assert pr.degraded
    with pytest.raises(RuntimeError, match="degraded"):
        pr.unwrap()
