"""Serving scheduler tests (docs/SERVING.md): bounded admission,
deadline-aware shedding, per-user fair share, cross-query fusion
(correctness proof: bit-identical to serial, ≤ 2 device dispatches for a
fused batch of 8), the wire surface ([GM-SHED]/[GM-OVERLOADED], headers),
and the observability satellites (per-user rollups, stream lag, fs
quarantine in /healthz, arrow-store fault points)."""

import json
import os
import threading
import time

import numpy as np
import pytest

from geomesa_tpu import GeoDataset, config, metrics, resilience, tracing
from geomesa_tpu.api.dataset import Query
from geomesa_tpu.resilience import (
    AdmissionRejectedError, DeadlineShedError, deadline_scope,
)
from geomesa_tpu.serving import FuseSpec, QueryScheduler, fuse

ECQL = "BBOX(geom, -5, -5, 5, 5)"


@pytest.fixture(scope="module")
def ds():
    ds = GeoDataset(n_shards=2)
    ds.create_schema("t", "a:Integer,dtg:Date,*geom:Point")
    rng = np.random.default_rng(7)
    n = 4000
    ds.insert("t", {
        "geom__x": rng.uniform(-10, 10, n),
        "geom__y": rng.uniform(-10, 10, n),
        "dtg": rng.integers(0, 10**10, n).astype("datetime64[ms]"),
        "a": rng.integers(0, 5, n).astype(np.int32),
    }, fids=np.arange(n).astype(str))
    ds.flush("t")
    ds.count("t", ECQL)  # warm: plan + kernel + windows
    return ds


@pytest.fixture()
def sched(ds):
    s = ds.serving.start()
    yield s
    s.stop()


def _stall(sched, timeout=10.0):
    """Block the dispatch thread so subsequent submissions queue. Waits
    until the stall ticket is actually EXECUTING (not merely queued), so
    callers can rely on the queue being empty and the dispatcher busy."""
    gate = threading.Event()
    started = threading.Event()

    def fn():
        started.set()
        return gate.wait(timeout)

    fut = sched.submit(fn, user="stall", op="stall")
    assert started.wait(10), "stall ticket never dispatched"
    return gate, fut


# ---------------------------------------------------------------------------
# admission queue
# ---------------------------------------------------------------------------


def test_queue_full_rejects_typed(sched, ds):
    gate, fut = _stall(sched)
    try:
        with config.SERVING_QUEUE_DEPTH.scoped(2):
            sched.submit(lambda: 1, user="u", op="x")
            sched.submit(lambda: 2, user="u", op="x")
            with pytest.raises(AdmissionRejectedError):
                sched.submit(lambda: 3, user="u", op="x")
    finally:
        gate.set()
        fut.result(10)
    assert ds.serving.user_rollups()["u"]["rejected"] == 1


def test_expired_budget_sheds_before_any_device_work(sched):
    disp = metrics.registry().counter(metrics.EXEC_DEVICE_DISPATCH)
    d0 = disp.value
    with pytest.raises(DeadlineShedError):
        sched.submit(lambda: (_ for _ in ()).throw(AssertionError("ran")),
                     user="u", op="count", budget_s=0.0)
    assert disp.value == d0  # typed rejection, zero device work


def test_budget_lapsing_in_queue_sheds_at_dispatch(sched):
    gate, fut = _stall(sched)
    # estimate shedding off: force the dispatch-time check specifically
    with config.SERVING_SHED_ESTIMATE.scoped("false"):
        f = sched.submit(lambda: "ran", user="u", op="x", budget_s=0.02)
    time.sleep(0.08)  # budget lapses while queued
    gate.set()
    fut.result(10)
    with pytest.raises(DeadlineShedError) as ei:
        f.result(10)
    assert "no device work" in str(ei.value)


def test_estimated_wait_sheds_at_admission(sched):
    sched._ewma_all = 5.0  # recent queries "took 5 s"
    try:
        gate, fut = _stall(sched)
        sched.submit(lambda: 1, user="filler", op="x")  # queued depth > 0
        with pytest.raises(DeadlineShedError) as ei:
            sched.submit(lambda: 1, user="u", op="x", budget_s=0.5)
        assert "estimated queue wait" in str(ei.value)
        gate.set()
        fut.result(10)
    finally:
        sched._ewma_all = None


def test_continuations_bypass_queue_bound(sched):
    gate, fut = _stall(sched)
    try:
        with config.SERVING_QUEUE_DEPTH.scoped(1):
            sched.submit(lambda: 1, user="u", op="x")
            # a stream continuation must not be bounced by a full queue
            f = sched.submit(lambda: "chunk", user="u", op="stream",
                             continuation=True)
    finally:
        gate.set()
        fut.result(10)
    assert f.result(10) == "chunk"


def test_local_admission_sheds_expired_deadline(ds):
    disp = metrics.registry().counter(metrics.EXEC_DEVICE_DISPATCH)
    d0 = disp.value
    with deadline_scope(0.0):
        with pytest.raises(DeadlineShedError):
            ds.count("t", ECQL)
    assert disp.value == d0
    # DeadlineShedError still classifies as a timeout for existing callers
    assert issubclass(DeadlineShedError, resilience.QueryTimeoutError)


# ---------------------------------------------------------------------------
# fair share
# ---------------------------------------------------------------------------


def test_fair_share_prevents_burst_starvation(sched):
    done = []
    lock = threading.Lock()

    def work(tag, dur=0.02):
        def fn():
            time.sleep(dur)
            with lock:
                done.append(tag)
            return tag
        return fn

    gate, fut = _stall(sched)
    futs = [sched.submit(work(f"A{i}"), user="burst", op="w")
            for i in range(6)]
    futs += [sched.submit(work(f"B{i}"), user="interactive", op="w")
             for i in range(2)]
    gate.set()
    fut.result(10)
    for f in futs:
        f.result(30)
    # under FIFO, B0/B1 would run after all six A ops; fair share must
    # interleave them well before the burst drains
    assert done.index("B1") < done.index("A5"), done
    assert done.index("B0") <= 3, done


# ---------------------------------------------------------------------------
# cross-query fusion
# ---------------------------------------------------------------------------


def test_fused_count_batch_bit_identical_and_two_dispatches(sched, ds):
    serial = ds.count("t", ECQL)
    opts = {"ecql": ECQL}
    gate, fut = _stall(sched)
    futs = [
        sched.submit(lambda: ds.count("t", ECQL), user=f"u{i % 3}",
                     op="count", fuse=fuse.make_spec(ds, "count", "t", opts),
                     trace_id=f"member{i:011d}")
        for i in range(8)
    ]
    disp = metrics.registry().counter(metrics.EXEC_DEVICE_DISPATCH)
    fused_before = metrics.registry().counter(metrics.SERVING_FUSED).value
    d0 = disp.value
    gate.set()
    fut.result(10)
    results = [f.result(30) for f in futs]
    # correctness proof: bit-identical to serial execution, ≤ 2 dispatches
    assert results == [serial] * 8
    assert disp.value - d0 <= 2, disp.value - d0
    assert metrics.registry().counter(metrics.SERVING_FUSED).value \
        - fused_before >= 7
    # each fused member keeps its own audit event, carrying its trace id
    evs = [json.loads(e.to_json()) for e in ds.audit.recent(50)]
    fused_evs = [e for e in evs if e["hints"].get("fused")]
    tids = {e["hints"].get("trace_id") for e in fused_evs}
    assert {f"member{i:011d}" for i in range(1, 8)} <= tids


def test_mixed_batch_degrades_to_per_query(sched, ds):
    other = "BBOX(geom, 0, 0, 9, 9)"
    ds.count("t", other)  # warm the second kernel
    n1, n2 = ds.count("t", ECQL), ds.count("t", other)
    gate, fut = _stall(sched)
    futs = []
    for i in range(6):
        ecql = ECQL if i % 2 == 0 else other
        opts = {"ecql": ecql}
        futs.append(sched.submit(
            lambda e=ecql: ds.count("t", e), user="u", op="count",
            fuse=fuse.make_spec(ds, "count", "t", opts),
        ))
    gate.set()
    fut.result(10)
    results = [f.result(30) for f in futs]
    # incompatible kernel tokens -> separate groups, correct per-query
    assert results == [n1, n2, n1, n2, n1, n2]


def test_fused_density_curve_tiles_bit_identical(sched, ds):
    bboxes = [(-5, -5, 0, 0), (0, 0, 5, 5), (-5, 0, 0, 5), (-2, -2, 2, 2)]
    serial = [ds.density_curve("t", ECQL, level=6, bbox=b) for b in bboxes]
    disp = metrics.registry().counter(metrics.EXEC_DEVICE_DISPATCH)
    gate, fut = _stall(sched)
    futs = []
    for b in bboxes:
        opts = {"ecql": ECQL, "level": 6, "bbox": list(b)}
        futs.append(sched.submit(
            lambda bb=b: ds.density_curve("t", ECQL, level=6, bbox=bb),
            user="tiles", op="density_curve",
            fuse=fuse.make_spec(ds, "density_curve", "t", opts),
        ))
    d0 = disp.value
    gate.set()
    fut.result(10)
    out = [f.result(30) for f in futs]
    assert disp.value - d0 <= 2, disp.value - d0
    for (g, s), (gs, ss) in zip(out, serial):
        assert np.array_equal(g, gs)
        assert s == ss


def test_fusion_respects_master_switch(sched, ds):
    opts = {"ecql": ECQL}
    with config.SERVING_FUSION.scoped("false"):
        gate, fut = _stall(sched)
        futs = [
            sched.submit(lambda: ds.count("t", ECQL), user="u", op="count",
                         fuse=fuse.make_spec(ds, "count", "t", opts))
            for _ in range(3)
        ]
        fused0 = metrics.registry().counter(metrics.SERVING_FUSED).value
        gate.set()
        fut.result(10)
        [f.result(30) for f in futs]
    assert metrics.registry().counter(metrics.SERVING_FUSED).value == fused0


def test_failing_batch_falls_back_to_serial(sched):
    calls = []

    def boom(tickets):
        raise RuntimeError("batch exploded")

    spec = FuseSpec(key=("k",), batch=boom)
    gate, fut = _stall(sched)
    futs = [
        sched.submit(lambda i=i: calls.append(i) or i, user="u", op="x",
                     fuse=FuseSpec(key=("k",), batch=boom))
        for i in range(3)
    ]
    del spec
    gate.set()
    fut.result(10)
    assert [f.result(10) for f in futs] == [0, 1, 2]
    assert calls == [0, 1, 2]  # per-member serial fallback ran them all


def test_unfusable_hints_get_no_key():
    assert fuse.fuse_key("count", "t", {"ecql": ECQL, "sampling": 10}) is None
    assert fuse.fuse_key("count", "t", {"ecql": ECQL, "max_features": 5}) is None
    k1 = fuse.fuse_key("density_curve", "t",
                       {"ecql": ECQL, "level": 6, "bbox": [0, 0, 1, 1]})
    k2 = fuse.fuse_key("density_curve", "t",
                       {"ecql": ECQL, "level": 6, "bbox": [2, 2, 3, 3]})
    assert k1 == k2  # tile crops stack: bbox is data, not key
    assert fuse.fuse_key("count", "t", {"ecql": "INCLUDE"}) != \
        fuse.fuse_key("count", "t", {"ecql": ECQL})


def test_density_curve_batch_public_api(ds):
    bboxes = [(-5, -5, 0, 0), (0, 0, 5, 5)]
    serial = [ds.density_curve("t", ECQL, level=6, bbox=b) for b in bboxes]
    out = ds.density_curve_batch(
        "t", ECQL, level=6, bboxes=bboxes,
        members=[{"trace_id": "aaaa", "user": "u1"},
                 {"trace_id": "bbbb", "user": "u2"}],
    )
    for (g, s), (gs, ss) in zip(out, serial):
        assert np.array_equal(g, gs)
        assert s == ss
    evs = [json.loads(e.to_json()) for e in ds.audit.recent(4)]
    members = [e for e in evs if e["hints"].get("fused_batch") == 2]
    assert len(members) == 2
    assert {e["hints"]["trace_id"] for e in members} == {"aaaa", "bbbb"}


# ---------------------------------------------------------------------------
# metrics + rollups
# ---------------------------------------------------------------------------


def test_serving_metrics_visible_in_prometheus(ds):
    # self-sufficient: run one fused pair so every serving metric exists
    # even when this test runs alone
    s = ds.serving.start()
    try:
        gate, fut = _stall(s)
        spec = lambda: FuseSpec(key=("prom",), batch=lambda ts: [1] * len(ts))  # noqa: E731
        f1 = s.submit(lambda: 1, user="m", op="x", fuse=spec())
        f2 = s.submit(lambda: 1, user="m", op="x", fuse=spec())
        gate.set()
        fut.result(10)
        assert f1.result(10) == 1 and f2.result(10) == 1
    finally:
        s.stop()
    text = metrics.registry().prometheus()
    assert "geomesa_serving_queue_depth" in text
    assert "geomesa_serving_admitted" in text
    # queue-wait renders as a seconds histogram; the fusion batch-size
    # histogram is dimensionless (no _seconds suffix)
    assert "geomesa_serving_queue_wait_seconds_bucket" in text
    assert "geomesa_serving_fusion_batch_bucket" in text
    assert "geomesa_serving_fusion_batch_seconds" not in text


def test_debug_queries_carries_user_rollups(ds):
    from geomesa_tpu import obs

    ds.count("t", ECQL)
    out = obs.debug_queries(ds, 10)
    assert "anonymous" in out["users"]
    roll = out["users"]["anonymous"]
    assert roll["completed"] > 0 and roll["service_ms"] > 0
    assert "depth" in out["serving"]
    # the rollup and fair share share ONE ledger
    assert out["users"] == ds.serving.user_rollups()


# ---------------------------------------------------------------------------
# wire surface (sidecar)
# ---------------------------------------------------------------------------


@pytest.fixture()
def flight(ds):
    import pyarrow.flight  # noqa: F401

    from geomesa_tpu.sidecar.client import GeoFlightClient
    from geomesa_tpu.sidecar.service import GeoFlightServer

    server = GeoFlightServer(ds, "grpc+tcp://127.0.0.1:0")
    client = GeoFlightClient(f"grpc+tcp://127.0.0.1:{server.port}")
    yield server, client
    client.close()
    server.shutdown()
    resilience.reset_breakers()


def test_sidecar_user_header_feeds_shared_ledger(flight, ds):
    server, client = flight
    with config.USER.scoped("alice"):
        n = client.count("t", ECQL)
    assert n == ds.count("t", ECQL)
    roll = server._sched.user_rollups()
    assert roll["alice"]["completed"] >= 1
    stats = client.serving_stats()
    assert "alice" in stats["users"]
    assert stats["serving"]["running"] is True


def test_sidecar_sheds_with_gm_shed(flight):
    server, client = flight
    sched = server._sched
    # recent queries "took 30 s" and the queue is non-empty: a 10 s budget
    # provably cannot be met -> typed [GM-SHED] before any device work
    sched._ewma_all = 30.0
    gate, fut = _stall(sched)
    sched.submit(lambda: 1, user="filler", op="x")  # pending depth > 0
    try:
        with config.SIDECAR_TIMEOUT.scoped("10 s"):
            with pytest.raises(DeadlineShedError) as ei:
                client.count("t", ECQL)
        assert "GM-SHED" in str(ei.value)
    finally:
        sched._ewma_all = None
        gate.set()
        fut.result(10)


def test_sidecar_queue_full_is_gm_overloaded(flight):
    import pyarrow.flight as fl

    from geomesa_tpu.sidecar.client import error_code, is_retryable

    server, client = flight
    sched = server._sched
    os.environ["GEOMESA_SERVING_QUEUE_DEPTH"] = "1"
    gate, fut = _stall(sched)
    try:
        sched.submit(lambda: 1, user="u", op="x")  # fills the queue
        with config.RETRY_ATTEMPTS.scoped(1):
            with pytest.raises(fl.FlightUnavailableError) as ei:
                client.count("t", ECQL)
        assert error_code(ei.value) == "GM-OVERLOADED"
        assert is_retryable(ei.value)  # backpressure: retry with backoff
    finally:
        del os.environ["GEOMESA_SERVING_QUEUE_DEPTH"]
        gate.set()
        fut.result(10)


def test_sidecar_fuses_identical_wire_counts(flight, ds):
    from geomesa_tpu.sidecar.client import GeoFlightClient

    server, client = flight
    serial = ds.count("t", ECQL)
    sched = server._sched
    gate, fut = _stall(sched)
    out = []
    lock = threading.Lock()

    def call():
        with GeoFlightClient(client.location) as c:
            n = c.count("t", ECQL)
        with lock:
            out.append(n)

    threads = [threading.Thread(target=call) for _ in range(4)]
    for t in threads:
        t.start()
    time.sleep(0.5)  # let all four RPCs reach the queue
    disp = metrics.registry().counter(metrics.EXEC_DEVICE_DISPATCH)
    d0 = disp.value
    gate.set()
    fut.result(10)
    for t in threads:
        t.join(30)
    assert out == [serial] * 4
    assert disp.value - d0 <= 2


def test_streams_survive_queue_pressure(flight):
    server, client = flight
    os.environ["GEOMESA_SERVING_QUEUE_DEPTH"] = "1"
    try:
        t = client.query("t", ECQL)  # streamed op=query export
        assert t.num_rows > 0
    finally:
        del os.environ["GEOMESA_SERVING_QUEUE_DEPTH"]


# ---------------------------------------------------------------------------
# satellites: stream lag, fs quarantine in /healthz, arrow-store faults
# ---------------------------------------------------------------------------


def test_stream_lag_gauge_and_span():
    from geomesa_tpu.stream.live import StreamingDataset

    sds = StreamingDataset()
    sds.create_schema("s", "a:Integer,dtg:Date,*geom:Point")
    past = int(time.time() * 1000) - 5_000
    sds.write("s", {"a": [1], "dtg": [past], "geom": [(1.0, 2.0)]},
              ["f1"], ts_ms=[past])
    with config.TRACE_ENABLED.scoped("true"):
        with tracing.start("poll-test"):
            sds.poll("s")
        tree = tracing.last_trace().root.to_dict()
    names = [c["name"] for c in tree.get("children", ())]
    assert "stream.apply" in names
    lag = metrics.registry().gauge(metrics.STREAM_LAG).value
    assert lag >= 5_000  # event time 5 s in the past -> lag >= 5 s
    assert metrics.registry().gauge("stream.lag.s").value >= 5_000


def test_confluent_apply_lag():
    from geomesa_tpu.stream.confluent import SchemaRegistry, attach_confluent
    from geomesa_tpu.stream.live import StreamingDataset

    sds = StreamingDataset()
    sds.create_schema("c", "a:Integer,dtg:Date,*geom:Point")
    reg = SchemaRegistry()
    ser, ingest = attach_confluent(sds, "c", reg)
    past = int(time.time() * 1000) - 3_000
    blob = ser.serialize("f1", {"a": 1, "dtg": past, "geom": "POINT(1 2)"})
    ingest(blob, ts_ms=past)
    assert metrics.registry().gauge("stream.lag.c").value >= 3_000
    t = metrics.registry().timer(metrics.STREAM_APPLY)
    assert t.count >= 1


def test_healthz_exposes_fs_quarantine_map(tmp_path):
    import glob

    from geomesa_tpu import obs
    from geomesa_tpu.fs.storage import DateTimeScheme, FileSystemStorage
    from geomesa_tpu.schema.feature_type import FeatureType

    fs = FileSystemStorage(str(tmp_path))
    ft = FeatureType.from_spec("q", "a:Integer,dtg:Date,*geom:Point")
    fs.create(ft, DateTimeScheme("month"))
    fs.write("q", {
        "a": np.array([1, 2], np.int32),
        "dtg": np.array([0, 40 * 86_400_000], "datetime64[ms]"),
        "geom__x": np.array([1.0, 2.0]),
        "geom__y": np.array([1.0, 2.0]),
    })
    files = sorted(glob.glob(
        os.path.join(fs.root, "q", "data", "**", "*.parquet"),
        recursive=True,
    ))
    with open(files[0], "wb") as fh:
        fh.write(b"\x00not parquet\xff" * 16)
    with resilience.allow_partial():
        fs.read("q")
    assert files[0] in fs.quarantined()
    h = obs.health()
    assert files[0] in h["fs_quarantine"].get(fs.root, {})
    # clearing re-admits and the map empties
    fs.clear_quarantine()
    assert obs.health()["fs_quarantine"].get(fs.root) is None


def test_arrow_store_read_fault_point_retries(tmp_path):
    from geomesa_tpu.io.arrow_store import ArrowDataStore
    from geomesa_tpu.resilience import inject_faults
    from geomesa_tpu.schema.feature_type import FeatureType

    path = str(tmp_path / "s.arrow")
    ft = FeatureType.from_spec("s", "a:Integer,*geom:Point")
    store = ArrowDataStore(path, ft, create=True)
    store.append({"a": np.array([1, 2], np.int32),
                  "geom__x": np.array([1.0, 2.0]),
                  "geom__y": np.array([3.0, 4.0])}, fids=["a", "b"])
    store.close()
    with config.FAULT_INJECTION.scoped("true"), \
            config.RETRY_BASE_MS.scoped(1):
        with inject_faults(seed=3) as inj:
            # two transient blips, healed by the RetryPolicy in place
            inj.fail("io.arrow.read_ipc", lambda: OSError("nfs blip"),
                     times=2)
            reopened = ArrowDataStore(path)
            assert reopened.count() == 2
            assert [s for s, _ in inj.fired].count("io.arrow.read_ipc") == 2
    # write edge is a fault point too (not retried: rename isn't idempotent)
    with config.FAULT_INJECTION.scoped("true"):
        with inject_faults(seed=4) as inj:
            inj.fail("io.arrow.write_ipc", lambda: OSError("disk"), times=1)
            reopened.append({"a": np.array([3], np.int32),
                             "geom__x": np.array([5.0]),
                             "geom__y": np.array([6.0])}, fids=["c"])
            with pytest.raises(OSError):
                reopened.flush()
        reopened.flush()  # old file intact, re-flush succeeds
    assert ArrowDataStore(path).count() == 3


def test_placement_residency_ranking():
    """docs/SERVING.md §5c residency ranking: candidate slots rank by
    ACTUAL device-resident column bytes (probe), recency only breaks
    ties — so on wide pools a schema finds the slot still holding its
    columns even when another schema dispatched there since."""
    s = QueryScheduler()
    s._threads = {0: object(), 1: object(), 2: object()}
    s._schema_heat["pts"] = {2: 10.0, 1: 20.0}
    # no probe: pure recency — the most recent dispatcher (slot 1) wins
    assert s._rank_slot_locked("pts", 0) == 1
    # probe: slot 2 actually holds the columns, outranking recency
    s.set_residency_probe(lambda schema, slot: {2: 1 << 20}.get(slot, 0))
    assert s._rank_slot_locked("pts", 0) == 2
    # the current slot is already the best home: no defer
    assert s._rank_slot_locked("pts", 2) is None
    # a dead preferred slot falls out of the candidate set
    s._threads = {0: object(), 1: object()}
    assert s._rank_slot_locked("pts", 0) == 1
    # a torn probe degrades to recency — dispatch must never fail on it
    def boom(schema, slot):
        raise RuntimeError("torn cache walk")
    s.set_residency_probe(boom)
    assert s._rank_slot_locked("pts", 0) == 1
    # unknown schema: no candidates, no defer
    assert s._rank_slot_locked("other", 0) is None


def test_dataset_wires_residency_probe():
    """GeoDataset installs a live probe over its stores' device-column
    caches; after a device scan the scanned schema's columns are
    measurably resident on slot 0's device."""
    ds = GeoDataset(n_shards=2)
    assert ds.serving._residency_probe is not None
    ds.create_schema("pts", "name:String,*geom:Point")
    r = np.random.default_rng(4)
    n = 2000
    ds.insert("pts", {"name": ["a"] * n,
                      "geom__x": r.uniform(-10, 10, n),
                      "geom__y": r.uniform(-10, 10, n)})
    ds.flush()
    assert ds._residency_bytes("pts", 0) == 0  # nothing uploaded yet
    ds.count("pts", "BBOX(geom, -5, -5, 5, 5)")
    if ds.prefer_device:
        assert ds._residency_bytes("pts", 0) > 0
    assert ds._residency_bytes("nope", 0) == 0
