"""Stored-JSON attributes with jsonPath() pushdown (SURVEY §2.2 JSON-path
support; reference geomesa-feature-kryo json/ — the subject of the
reference's only JMH benchmark)."""

import numpy as np
import pytest

from geomesa_tpu import GeoDataset
from geomesa_tpu.filter.ecql import parse_iso_ms

SPEC = "props:Json,dtg:Date,*geom:Point"


@pytest.fixture(scope="module")
def ds():
    docs = [
        '{"type": "car", "speed": 42, "tags": ["a", "b"]}',
        '{"type": "truck", "speed": 80, "extra": {"axles": 3}}',
        '{"type": "car", "speed": 12}',
        '{"speed": 99}',
        None,
        'not valid json',
    ]
    n = len(docs)
    d = GeoDataset(n_shards=2)
    d.create_schema("j", SPEC)
    d.insert("j", {
        "props": docs,
        "dtg": np.full(n, parse_iso_ms("2022-01-01")).astype("datetime64[ms]"),
        "geom__x": np.linspace(-10, 10, n),
        "geom__y": np.zeros(n),
    }, fids=np.arange(n).astype(str))
    d.flush()
    return d


def test_jsonpath_equality(ds):
    assert ds.count("j", "jsonPath('$.type', props) = 'car'") == 2
    assert ds.count("j", "jsonPath('$.type', props) = 'truck'") == 1


def test_jsonpath_numeric_range(ds):
    assert ds.count("j", "jsonPath('$.speed', props) > 40") == 3
    assert ds.count("j", "jsonPath('$.speed', props) <= 42") == 2
    assert ds.count("j", "jsonPath('$.speed', props) BETWEEN 40 AND 90") == 2


def test_jsonpath_nested_and_null(ds):
    assert ds.count("j", "jsonPath('$.extra.axles', props) = 3") == 1
    assert ds.count("j", "jsonPath('$.type', props) IS NULL") == 3
    assert ds.count("j", "jsonPath('$.type', props) IS NOT NULL") == 3


def test_jsonpath_like_in_and_combination(ds):
    assert ds.count("j", "jsonPath('$.type', props) LIKE 'c%'") == 2
    assert ds.count("j", "jsonPath('$.type', props) IN ('car', 'truck')") == 3
    assert ds.count(
        "j", "jsonPath('$.type', props) = 'car' AND jsonPath('$.speed', props) > 20"
    ) == 1
    assert ds.count("j", "NOT (jsonPath('$.type', props) = 'car')") == 4


def test_jsonpath_array_wildcard(ds):
    assert ds.count("j", "jsonPath('$.tags[*]', props) = 'b'") == 1
    assert ds.count("j", "jsonPath('$.tags[0]', props) = 'a'") == 1


def test_json_roundtrip_query_and_arrow(ds):
    fc = ds.query("j", "jsonPath('$.type', props) = 'truck'")
    assert len(fc) == 1
    assert '"axles": 3' in fc.columns["props"][0]
    t = ds.to_arrow("j")
    assert t.num_rows == 6
    assert t["props"].null_count == 1


def test_jsonpath_on_non_json_attr_raises(ds):
    with pytest.raises(ValueError, match="requires a Json attribute"):
        ds.count("j", "jsonPath('$.a', dtg) = 1")


def test_indexed_json_attr_ingests(tmp_path):
    """r4 review: index=true on a Json attribute must not break ingest
    (no MinMax sketch over document text)."""
    d = GeoDataset(n_shards=2)
    d.create_schema("ji", "props:Json:index=true,dtg:Date,*geom:Point")
    d.insert("ji", {
        "props": ['{"a": 1}', None],
        "dtg": np.full(2, parse_iso_ms("2022-01-01")).astype("datetime64[ms]"),
        "geom__x": [0.0, 1.0], "geom__y": [0.0, 1.0],
    }, fids=["a", "b"])
    d.flush()
    assert d.count("ji") == 2
    assert d.count("ji", "jsonPath('$.a', props) = 1") == 1


def test_update_schema_adds_json(ds):
    d2 = GeoDataset(n_shards=2)
    d2.create_schema("u", "dtg:Date,*geom:Point")
    d2.insert("u", {
        "dtg": np.full(2, parse_iso_ms("2022-01-01")).astype("datetime64[ms]"),
        "geom__x": [0.0, 1.0], "geom__y": [0.0, 1.0],
    }, fids=["a", "b"])
    d2.flush()
    d2.update_schema("u", "props:Json")
    assert d2.count("u", "jsonPath('$.a', props) IS NULL") == 2


def test_temporal_on_jsonpath_raises(ds):
    with pytest.raises(ValueError, match="not supported on jsonPath"):
        ds.count("j", "jsonPath('$.t', props) AFTER 2022-01-01T00:00:00Z")
