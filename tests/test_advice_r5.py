"""Regression tests for the ADVICE r5 fixes riding the cache PR.

1. ``flush()``'s ``__vis__`` back-fill bumps the mutation epoch (an
   incremental checkpoint after the back-fill must fully rewrite, or old
   chunks reload without the column) and ``ColumnBatch.concat`` unions
   column sets with null-fill instead of silently intersecting.
2. literal/literal division by zero follows IEEE instead of raising an
   uncaught ZeroDivisionError at query time.
3. property-free comparisons fold to a constant Include/Exclude.
4. mixed-type literal comparisons dispatch on the op and raise a clean
   ValueError for genuinely incomparable orderings.
5. stream poison-message quarantine counters ride the metrics registry.
"""

import numpy as np
import pytest

from geomesa_tpu import metrics
from geomesa_tpu.api.dataset import GeoDataset, Query
from geomesa_tpu.filter import parse_ecql
from geomesa_tpu.filter.ir import Exclude, Include
from geomesa_tpu.schema.columns import ColumnBatch
from geomesa_tpu.security import VIS_COLUMN


@pytest.fixture()
def ds():
    d = GeoDataset(n_shards=2)
    d.create_schema("t", "weight:Float,*geom:Point")
    d.insert("t", {
        "geom__x": [1.0, 2.0], "geom__y": [1.0, 2.0], "weight": [0.5, 2.0],
    })
    d.flush("t")
    return d


# -- 1: __vis__ back-fill epoch + concat union ------------------------------

def test_vis_backfill_forces_full_rewrite(tmp_path):
    path = str(tmp_path / "store")
    ds = GeoDataset(n_shards=2)
    ds.create_schema("t", "name:String,*geom:Point")
    ds.insert("t", {"name": ["a", "b"], "geom__x": [1.0, 2.0],
                    "geom__y": [1.0, 2.0]})
    ds.flush("t")
    st = ds._store("t")
    # simulate a dataset persisted before visibility support
    st._all.columns.pop(VIS_COLUMN, None)
    st.dicts.pop(VIS_COLUMN, None)
    ds.save(path)

    ds2 = GeoDataset.load(path)
    ds2.insert("t", {"name": ["c"], "geom__x": [3.0], "geom__y": [3.0]},
               visibilities=["admin"])
    ds2.flush("t")  # back-fills __vis__ on old rows -> must bump the epoch
    ds2.save(path)  # would otherwise append one chunk WITHOUT rewriting

    ds3 = GeoDataset.load(path)
    st3 = ds3._store("t")
    st3.flush()
    assert VIS_COLUMN in st3._all.columns
    assert st3._all.columns[VIS_COLUMN].tolist() == [0, 0, 1]
    assert len(ds3.query("t", Query(auths=[]))) == 2        # admin row hidden
    assert len(ds3.query("t", Query(auths=["admin"]))) == 3


def test_concat_unions_columns_with_null_fill():
    a = ColumnBatch({
        "x": np.array([1.0, 2.0]),
        "s": np.array(["a", "b"], object),
        "flag": np.array([True, False]),
    }, 2)
    b = ColumnBatch({
        "x": np.array([3.0]),
        "v": np.array([7], np.int32),       # dict-code-shaped: null is -1
        VIS_COLUMN: np.array([2], np.int32),  # visibility: null is "" = 0
        "big": np.array([9], np.int64),
    }, 1)
    c = ColumnBatch.concat([a, b])
    assert c.n == 3
    assert set(c.columns) == {"x", "s", "flag", "v", VIS_COLUMN, "big"}
    assert c.columns["x"].tolist() == [1.0, 2.0, 3.0]
    assert c.columns["s"].tolist() == ["a", "b", None]
    assert c.columns["v"].tolist() == [-1, -1, 7]
    assert c.columns[VIS_COLUMN].tolist() == [0, 0, 2]
    assert c.columns["big"].tolist() == [0, 0, 9]
    assert c.columns["flag"].tolist() == [True, False, False]


# -- 2: literal division by zero -------------------------------------------

def test_literal_division_by_zero_is_ieee(ds):
    assert ds.count("t", "weight > 1 / 0") == 0     # weight > inf
    assert ds.count("t", "weight > -1 / 0") == 2    # weight > -inf
    assert ds.count("t", "weight * 2 > 0 / 0") == 0  # NaN compares False


# -- 3: property-free comparisons ------------------------------------------

POLY = "st_geomFromWKT('POLYGON((0 0,1 0,1 1,0 1,0 0))')"


def test_property_free_compare_folds_to_constant(ds):
    assert ds.count("t", f"st_area({POLY}) > 0.5") == 2   # area 1 -> Include
    assert ds.count("t", f"st_area({POLY}) > 2.5") == 0   # -> Exclude
    assert ds.count("t", f"weight > 1 AND st_area({POLY}) > 0.5") == 1


# -- 4: mixed-type literal comparisons -------------------------------------

def test_mixed_literal_comparison():
    assert isinstance(parse_ecql("1 = 'a'"), Exclude)   # equality: just False
    assert isinstance(parse_ecql("1 <> 'a'"), Include)
    assert isinstance(parse_ecql("'a' = 'a'"), Include)
    with pytest.raises(ValueError, match="incomparable literal types"):
        parse_ecql("1 < 'a'")
    with pytest.raises(ValueError, match="incomparable literal types"):
        parse_ecql("'a' >= 2")


# -- 5: quarantine counters in the metrics registry -------------------------

def test_stream_quarantine_counters_in_registry():
    from geomesa_tpu.stream.live import StreamingDataset
    from geomesa_tpu.stream.messages import GeoMessage

    sd = StreamingDataset()
    sd.create_schema("live", "name:String,*geom:Point")
    total_before = metrics.registry().counter("stream.poll.quarantined").value
    sd.write("live", {"name": ["ok"], "geom": [(1.0, 2.0)]}, ["f1"])
    # poison: a point payload the columnar encode cannot absorb
    sd._topics["live"].send(
        GeoMessage.change("bad", {"name": "x", "geom": "not-a-point"}, 1)
    )
    applied = sd.poll("live")
    assert applied == 1
    assert sd.quarantined["live"] == 1
    reg = metrics.registry()
    assert reg.counter("stream.poll.quarantined").value == total_before + 1
    assert reg.counter("stream.poll.quarantined.live").value >= 1
