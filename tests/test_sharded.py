"""Multi-device sharded partitioned scan + serving pool tests
(docs/SCALE.md, docs/SERVING.md). Runs on CPU: conftest forces
``--xla_force_host_platform_device_count=8``, so the fan-out paths
exercise 8 virtual devices in tier-1 — the in-process analog of the
reference's multi-tablet-server scans (SURVEY.md §2.9).

Covered invariants:

* sharded partitioned scan == single-device oracle BIT-identically for
  count / density / density_curve / stats (the merge is the fixed tree
  reduction of parallel/devices.tree_merge, in pruned-bin order, so the
  result is independent of device count and assignment);
* deterministic merge when partition-count % device-count != 0;
* degradation (a partition quarantined mid-sharded-scan) keeps exact
  survivor totals, identical to the serial path's degradation;
* the device_put prefetch overlap changes nothing: bit-identical grids
  and zero recompiles with the overlap on vs off;
* the serving pool actually parallelizes (every slot dispatches), keeps
  fusion bit-identical on one slot, honors per-user weights, and stands
  the sharded scan down while it owns the devices.
"""

import threading
import time

import numpy as np
import pytest

from geomesa_tpu import GeoDataset, config, metrics, resilience
from geomesa_tpu.filter.ecql import parse_iso_ms
from geomesa_tpu.index.partitioned import PartitionedFeatureStore
from geomesa_tpu.parallel import devices as pdev
from geomesa_tpu.resilience import InjectedFault, allow_partial, inject_faults

SPEC = "name:String:index=true,weight:Double,dtg:Date,*geom:Point"
PSPEC = SPEC + ";geomesa.partition='time'"
N = 12_000
ECQL = "BBOX(geom, -110, 28, -75, 48)"
BBOX = (-120.0, 25.0, -70.0, 50.0)
STATS = "MinMax(weight);Count();Enumeration(name)"


def _data(n=N, seed=11):
    rng = np.random.default_rng(seed)
    return {
        "name": [f"actor{i % 20}" for i in range(n)],
        "weight": rng.uniform(0, 10, n),
        "dtg": rng.integers(
            parse_iso_ms("2020-01-01"), parse_iso_ms("2020-03-01"), n
        ).astype("datetime64[ms]"),
        "geom__x": rng.uniform(-120, -70, n),
        "geom__y": rng.uniform(25, 50, n),
    }


@pytest.fixture(scope="module")
def pds(tmp_path_factory):
    """Partitioned dataset: ~9 weekly partitions, max_resident=1 so every
    multi-partition query streams through the (sharded) pipeline."""
    ds = GeoDataset(n_shards=4)
    ds.create_schema("t", PSPEC)
    st = ds._store("t")
    assert isinstance(st, PartitionedFeatureStore)
    st.max_resident = 1
    st._spill_dir = str(tmp_path_factory.mktemp("spill"))
    ds.insert("t", _data(), fids=np.arange(N).astype(str))
    ds.flush()
    return ds


def _ctr(name: str) -> float:
    return metrics.registry().counter(name).value


def _recompiles() -> float:
    return _ctr(metrics.KERNEL_RECOMPILES)


# ---------------------------------------------------------------------------
# sharded scan engages + bit-identity vs the single-device oracle
# ---------------------------------------------------------------------------


def test_sharded_scan_engages_on_the_virtual_mesh(pds):
    import jax

    assert len(jax.devices()) == 8  # conftest's forced virtual mesh
    devs = pdev.scan_devices()
    assert devs is not None and len(devs) == 8
    before = _ctr(metrics.SCAN_SHARDED)
    pds.count("t", ECQL)
    assert _ctr(metrics.SCAN_SHARDED) == before + 1
    # partitions really dispatched round-robin across > 1 device
    used = [
        d.id for d in devs
        if _ctr(f"{metrics.SCAN_SHARDED_DEVICE}.{d.id}") > 0
    ]
    assert len(used) > 1
    # and the audit/explain trail names the fan-out
    _, _, plan = pds._plan("t", ECQL)
    pds.count("t", ECQL)


def test_sharded_count_density_curve_stats_bit_identical(pds):
    c = pds.count("t", ECQL)
    d = pds.density("t", ECQL, bbox=BBOX, width=96, height=96)
    dc, snap = pds.density_curve("t", ECQL, level=6)
    s = pds.stats("t", STATS, ECQL)
    with config.MESH_DEVICES.scoped("off"):
        assert pds.count("t", ECQL) == c
        assert np.array_equal(
            pds.density("t", ECQL, bbox=BBOX, width=96, height=96), d
        )
        dc2, snap2 = pds.density_curve("t", ECQL, level=6)
        assert snap2 == snap and np.array_equal(dc2, dc)
        assert pds.stats("t", STATS, ECQL).to_json() == s.to_json()


def test_merge_deterministic_when_partitions_not_divisible(pds):
    """Pruned-partition count (~9) % device count != 0 for 2, 4, and 8
    devices: the tree merge depends only on pruned-bin order, so every
    fan-out width must produce the same bits as the serial scan."""
    bins = pds._store("t").partition_bins()
    with config.MESH_DEVICES.scoped("off"):
        want_c = pds.count("t")
        want_d = pds.density("t", bbox=BBOX, width=64, height=64)
    for width in ("2", "3", "8"):
        if width != "2":
            assert len(bins) % int(width) != 0  # the awkward remainders
        with config.MESH_DEVICES.scoped(width):
            assert pds.count("t") == want_c, width
            got = pds.density("t", bbox=BBOX, width=64, height=64)
            assert np.array_equal(got, want_d), width


def test_weighted_density_bit_identical(pds):
    d = pds.density("t", ECQL, bbox=BBOX, width=64, height=64,
                    weight="weight")
    with config.MESH_DEVICES.scoped("off"):
        d2 = pds.density("t", ECQL, bbox=BBOX, width=64, height=64,
                         weight="weight")
    assert np.array_equal(d, d2)


def test_tree_reducer_matches_tree_merge_association():
    """The streaming reducer the partitioned merges use must reproduce
    tree_merge's association EXACTLY for every input size — that identity
    is what lets the scan merge incrementally (O(log n) resident
    partials) without changing a single result bit."""
    comb = "({}+{})".format
    for n in range(0, 40):
        parts = [str(i) for i in range(n)]
        red = pdev.TreeReducer(comb)
        for p in parts:
            red.push(p)
        assert red.result() == pdev.tree_merge(parts, comb), n
    # None partials are dropped, matching tree_merge's filter
    red = pdev.TreeReducer(comb)
    for p in ["0", None, "1", "2", None]:
        red.push(p)
    assert red.result() == pdev.tree_merge(["0", "1", "2"], comb)


# ---------------------------------------------------------------------------
# degradation under the sharded fan-out
# ---------------------------------------------------------------------------


def test_sharded_degradation_keeps_exact_survivor_totals(pds):
    st = pds._store("t")
    per_bin = {b: st.child(b).count for b in st.partition_bins()}
    with config.FAULT_INJECTION.scoped("true"):
        with inject_faults(seed=2) as inj:
            inj.fail("exec.partition.scan", times=1)
            with allow_partial() as partial:
                degraded = pds.count("t")
    assert partial.degraded and len(partial.skipped) == 1
    failed_bin = int(partial.skipped[0].part.split(":")[1])
    assert degraded == N - per_bin[failed_bin]
    # strict mode still raises through the fan-out
    with config.FAULT_INJECTION.scoped("true"):
        with inject_faults(seed=2) as inj:
            inj.fail("exec.partition.scan", times=1)
            with pytest.raises(InjectedFault):
                pds.count("t")
    assert pds.count("t") == N  # healthy afterwards


def test_sharded_and_serial_degrade_identically(pds):
    """Same seeded fault, sharded vs single-device: the same partition is
    skipped and the partial grids match bit-for-bit."""
    def degraded_grid():
        with config.FAULT_INJECTION.scoped("true"):
            with inject_faults(seed=4) as inj:
                inj.fail("exec.partition.scan", times=1)
                with allow_partial() as partial:
                    g = pds.density("t", bbox=BBOX, width=64, height=64)
        return g, partial.skipped[0].part

    g_shard, part_shard = degraded_grid()
    with config.MESH_DEVICES.scoped("off"):
        g_ser, part_ser = degraded_grid()
    assert part_shard == part_ser
    assert np.array_equal(g_shard, g_ser)


# ---------------------------------------------------------------------------
# device_put prefetch overlap (docs/PERF.md)
# ---------------------------------------------------------------------------


def test_device_put_overlap_bit_identical_and_no_recompiles(pds):
    pds.density("t", ECQL, bbox=BBOX, width=64, height=64)  # warm
    before_over = _ctr(metrics.PIPELINE_DEVICE_PUT)
    base = _recompiles()
    with_overlap = pds.density("t", ECQL, bbox=BBOX, width=64, height=64)
    assert _ctr(metrics.PIPELINE_DEVICE_PUT) > before_over
    with config.PIPELINE_DEVICE_PUT.scoped("false"):
        without = pds.density("t", ECQL, bbox=BBOX, width=64, height=64)
    assert np.array_equal(with_overlap, without)
    # the overlapped upload hits the same per-device caches the query
    # thread would populate: a warm re-query never traces, either way
    assert _recompiles() == base


# ---------------------------------------------------------------------------
# serving pool (docs/SERVING.md)
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def ds():
    ds = GeoDataset(n_shards=2)
    ds.create_schema("t", "a:Integer,dtg:Date,*geom:Point")
    rng = np.random.default_rng(7)
    n = 4000
    ds.insert("t", {
        "geom__x": rng.uniform(-10, 10, n),
        "geom__y": rng.uniform(-10, 10, n),
        "dtg": rng.integers(0, 10**10, n).astype("datetime64[ms]"),
        "a": rng.integers(0, 5, n).astype(np.int32),
    }, fids=np.arange(n).astype(str))
    ds.flush("t")
    ds.count("t", "BBOX(geom, -5, -5, 5, 5)")  # warm plan + kernels
    return ds


def test_pool_actually_parallel_every_slot_dispatches(ds):
    """A 4-wide pool must run 4 tickets CONCURRENTLY: each ticket blocks
    on a barrier that only releases when all 4 execute at once, which is
    impossible unless 4 distinct dispatch threads picked one each."""
    width = 4
    barrier = threading.Barrier(width, timeout=15)
    with config.SERVING_EXECUTORS.scoped(str(width)):
        s = ds.serving.start()
        try:
            assert pdev.pool_width() == width  # scan stands down
            assert pdev.scan_devices() is None
            futs = [
                s.submit(lambda: barrier.wait(15), user=f"u{i}", op="op")
                for i in range(width)
            ]
            for f in futs:
                f.result(timeout=30)
            snap = s.snapshot()
            assert snap["executors"] == width
            slots = {
                k: v for k, v in snap["slot_dispatches"].items() if v > 0
            }
            assert len(slots) == width
            # per-slot dispatch counters surfaced for the bench/CI gate
            for slot in slots:
                assert _ctr(
                    f"{metrics.SERVING_EXECUTOR_DISPATCH}.{slot}"
                ) > 0
        finally:
            s.stop()
    assert pdev.pool_width() == 1  # devices released to the sharded scan


def test_pool_queries_match_serial_results(ds):
    boxes = [
        f"BBOX(geom, -5, -5, {x:.2f}, 5)" for x in np.linspace(0.5, 5, 12)
    ]
    want = [ds.count("t", q) for q in boxes]
    with config.SERVING_EXECUTORS.scoped("4"):
        s = ds.serving.start()
        try:
            futs = [
                s.submit((lambda q: lambda: ds.count("t", q))(q),
                         user=f"u{i % 3}", op="count")
                for i, q in enumerate(boxes)
            ]
            assert [f.result(timeout=60) for f in futs] == want
        finally:
            s.stop()


def test_pool_fusion_binds_to_one_slot_and_stays_bit_identical(ds):
    """Fusion stays GLOBAL on the pool: identical counts queued while the
    pool is stalled coalesce into one batch, executed entirely by ONE
    slot's thread — results bit-identical to serial, ≤ 2 device-dispatch
    groups for the batch (one straggler allowance, as on the single
    dispatch thread)."""
    q = "BBOX(geom, -5, -5, 4.5, 5)"
    want = ds.count("t", q)
    width = 2
    gate = threading.Event()
    started = [threading.Event() for _ in range(width)]

    def stall(i):
        def fn():
            started[i].set()
            gate.wait(15)
        return fn

    with config.SERVING_EXECUTORS.scoped(str(width)):
        s = ds.serving.start()
        try:
            stalls = [
                s.submit(stall(i), user=f"stall{i}", op="op")
                for i in range(width)
            ]
            for ev in started:
                assert ev.wait(15)  # both slots busy -> queries must queue
            from geomesa_tpu.serving import fuse

            fused_before = _ctr(metrics.SERVING_FUSED)
            futs = [
                s.submit((lambda: ds.count("t", q)), user="same",
                         op="count",
                         fuse=fuse.make_spec(ds, "count", "t", {"ecql": q}))
                for _ in range(6)
            ]
            gate.set()
            got = [f.result(timeout=60) for f in futs]
            for f in stalls:
                f.result(timeout=30)
            assert got == [want] * 6
            assert _ctr(metrics.SERVING_FUSED) >= fused_before + 4
        finally:
            s.stop()


def test_weighted_fair_share_prefers_heavy_user(ds, monkeypatch):
    """geomesa.serving.user.weight.<user>: under contention a weight-4
    user earns ~4x the dispatches of a weight-1 user — the least-
    attained-WEIGHTED-service order is heavy,heavy,heavy,heavy,light
    after the opening tie."""
    monkeypatch.setenv("GEOMESA_SERVING_USER_WEIGHT_HEAVY", "4")
    assert config.user_weight("heavy") == 4.0
    assert config.user_weight("light") == 1.0
    order = []
    gate = threading.Event()
    started = threading.Event()

    def work(tag):
        def fn():
            order.append(tag)
            time.sleep(0.004)  # comparable per-ticket service cost
        return fn

    with config.SERVING_EXECUTORS.scoped("1"):
        s = ds.serving.start()
        try:
            stall = s.submit(
                lambda: (started.set(), gate.wait(15)), user="stall",
                op="op",
            )
            assert started.wait(15)
            futs = []
            for i in range(6):  # interleaved arrivals
                futs.append(s.submit(work("light"), user="light", op="op"))
                futs.append(s.submit(work("heavy"), user="heavy", op="op"))
            gate.set()
            for f in futs:
                f.result(timeout=60)
            stall.result(timeout=30)
        finally:
            s.stop()
    # the first 6 dispatches: heavy dominates ~4:1 after the opening tie
    assert order.count("heavy") == order.count("light") == 6
    assert order[:6].count("heavy") >= 4, order
    # rollups surface the effective weight next to the service ledger
    roll = ds.serving.user_rollups()
    assert roll["heavy"]["weight"] == 4.0
    assert roll["light"]["weight"] == 1.0


def test_weight_captured_at_submission_scoped_override(ds):
    """A caller-scoped weight override must reach the dispatcher: the
    weight is captured into the ledger ON THE SUBMITTING THREAD (the
    dispatch thread's ambient config never sees scoped overrides)."""
    with config.SERVING_EXECUTORS.scoped("1"):
        s = ds.serving.start()
        try:
            prop = config.SystemProperty(
                "geomesa.serving.user.weight.scopedu", None
            )
            with prop.scoped("2.5"):
                s.submit(lambda: 1, user="scopedu", op="op").result(30)
            assert ds.serving.user_rollups()["scopedu"]["weight"] == 2.5
        finally:
            s.stop()


def test_user_weight_parsing_defaults():
    assert config.user_weight("nobody") == 1.0
    with config.SystemProperty(
        "geomesa.serving.user.weight.bad", None
    ).scoped("not-a-number"):
        assert config.user_weight("bad") == 1.0
    with config.SystemProperty(
        "geomesa.serving.user.weight.neg", None
    ).scoped("-2"):
        assert config.user_weight("neg") == 1.0


def test_sharded_scan_resumes_after_pool_stop(pds):
    """Pool ownership of the devices is scoped to start()..stop(): the
    sharded scan stands down while a >1 pool runs and re-engages after."""
    with config.SERVING_EXECUTORS.scoped("2"):
        s = pds.serving.start()
        try:
            assert pdev.scan_devices() is None
            before = _ctr(metrics.SCAN_SHARDED)
            # queries still run (serial partition stream) while the pool
            # owns the devices — and return the same results
            assert pds.count("t", ECQL) > 0
            assert _ctr(metrics.SCAN_SHARDED) == before
        finally:
            s.stop()
    before = _ctr(metrics.SCAN_SHARDED)
    pds.count("t", ECQL)
    assert _ctr(metrics.SCAN_SHARDED) == before + 1
