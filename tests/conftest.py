"""Test fixture: force an 8-virtual-device CPU backend.

This is the analog of the reference's in-process mini-clusters (SURVEY.md §4.3):
the full planner/executor/sharding stack runs against fake devices with no real
TPU, exactly as TestGeoMesaDataStore exercises the full planner with an
in-memory adapter.

Note: env vars are not enough here — the axon TPU plugin's sitecustomize calls
``jax.config.update("jax_platforms", ...)`` at interpreter startup, which
overrides JAX_PLATFORMS. We update jax.config back before any backend is
initialized.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_num_cpu_devices", 8)

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(scope="session")
def rng():
    return np.random.default_rng(42)
