"""Test fixture: force an 8-virtual-device CPU backend before jax imports.

This is the analog of the reference's in-process mini-clusters (SURVEY.md §4.3):
the full planner/executor/sharding stack runs against fake devices with no real
TPU, exactly as TestGeoMesaDataStore exercises the full planner with an
in-memory adapter.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(scope="session")
def rng():
    return np.random.default_rng(42)
