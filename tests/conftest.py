"""Test fixture: force an 8-virtual-device CPU backend.

This is the analog of the reference's in-process mini-clusters (SURVEY.md §4.3):
the full planner/executor/sharding stack runs against fake devices with no real
TPU, exactly as TestGeoMesaDataStore exercises the full planner with an
in-memory adapter.

Note: env vars are not enough here — the axon TPU plugin's sitecustomize calls
``jax.config.update("jax_platforms", ...)`` at interpreter startup, which
overrides JAX_PLATFORMS. We update jax.config back before any backend is
initialized.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")

if "--xla_force_host_platform_device_count" not in os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 8)
except AttributeError:
    # older jax: the XLA_FLAGS fallback above provides the 8 virtual devices
    pass

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(scope="session")
def rng():
    return np.random.default_rng(42)
