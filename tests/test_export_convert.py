"""Export formats (Avro/SHP/GML/ORC) and converter inputs
(XML / fixed-width / Parquet / Avro)."""

import io
import os

import numpy as np
import pytest

from geomesa_tpu import GeoDataset

SPEC = "name:String,v:Integer,w:Float,dtg:Date,*geom:Point"


def _ds(n=50, seed=0):
    rng = np.random.default_rng(seed)
    ds = GeoDataset(n_shards=2)
    ds.create_schema("t", SPEC)
    ds.insert("t", {
        "geom__x": rng.uniform(-10, 10, n),
        "geom__y": rng.uniform(-10, 10, n),
        "dtg": rng.integers(1577836800000, 1580515200000, n).astype("datetime64[ms]"),
        "name": rng.choice(["a", "b", None], n),
        "v": rng.integers(0, 100, n),
        "w": rng.uniform(0, 1, n),
    }, fids=np.array([f"f{i}" for i in range(n)]))
    ds.flush("t")
    return ds


# -- avro ---------------------------------------------------------------------

def test_avro_round_trip(tmp_path):
    from geomesa_tpu.io import avro_io

    ds = _ds()
    st = ds._store("t")
    path = str(tmp_path / "x.avro")
    avro_io.write_avro(path, st.ft, st._all, st.dicts)
    schema, records = avro_io.read_avro(path)
    assert schema["name"] == "t"
    assert len(records) == 50
    r0 = next(r for r in records if r["__fid__"] == "f0")
    d = ds.query("t").to_dict()
    i = d["__fid__"].index("f0") if isinstance(d["__fid__"], list) else list(d["__fid__"]).index("f0")
    assert r0["v"] == d["v"][i]
    assert r0["geom"].startswith("POINT")
    assert abs(r0["w"] - float(d["w"][i])) < 1e-6


def test_avro_none_string(tmp_path):
    from geomesa_tpu.io import avro_io

    ds = _ds()
    st = ds._store("t")
    buf = io.BytesIO()
    avro_io.write_avro(buf, st.ft, st._all, st.dicts)
    buf.seek(0)
    _, records = avro_io.read_avro(buf)
    names = [r["name"] for r in records]
    assert None in names and "a" in names


def test_avro_converter_ingest(tmp_path):
    from geomesa_tpu.io import avro_io

    src = _ds()
    st = src._store("t")
    path = str(tmp_path / "x.avro")
    avro_io.write_avro(path, st.ft, st._all, st.dicts)

    dst = GeoDataset(n_shards=2)
    dst.create_schema("t", SPEC)
    ctx = dst.ingest("t", path, {
        "type": "avro",
        "id-field": "$__fid__",
        "fields": [
            {"name": "geom", "transform": "point($geom)"},
        ],
    })
    assert ctx.success == 50
    assert dst.count("t") == 50
    assert sorted(dst.unique("t", "name"), key=str) == sorted(
        src.unique("t", "name"), key=str
    )


# -- shapefile ----------------------------------------------------------------

def test_shapefile_points(tmp_path):
    from geomesa_tpu.io import shapefile

    ds = _ds(n=20)
    st = ds._store("t")
    base = shapefile.write_shapefile(
        str(tmp_path / "pts.shp"), st.ft, st._all, st.dicts
    )
    for ext in (".shp", ".shx", ".dbf"):
        assert os.path.exists(base + ext)
    recs = shapefile.read_shapefile(base)
    assert len(recs) == 20
    assert all(t == shapefile.SHP_POINT for t, _ in recs)
    xs = sorted(p[0][0, 0] for _, p in recs)
    want = sorted(st._all.columns["geom__x"])
    np.testing.assert_allclose(xs, want, rtol=1e-12)


def test_shapefile_polygons(tmp_path):
    from geomesa_tpu.io import shapefile

    ds = GeoDataset(n_shards=2)
    ds.create_schema("p", "v:Integer,dtg:Date,*geom:Polygon")
    ds.insert("p", {
        "geom": np.array([
            "POLYGON ((0 0, 4 0, 4 4, 0 4, 0 0))",
            "POLYGON ((10 10, 14 10, 14 14, 10 14, 10 10), (11 11, 12 11, 12 12, 11 12, 11 11))",
        ], object),
        "dtg": np.array(["2020-01-01", "2020-01-02"], "datetime64[ms]"),
        "v": np.array([1, 2]),
    }, fids=np.array(["p1", "p2"]))
    ds.flush("p")
    st = ds._store("p")
    base = shapefile.write_shapefile(
        str(tmp_path / "polys.shp"), st.ft, st._all, st.dicts
    )
    recs = shapefile.read_shapefile(base)
    assert len(recs) == 2
    assert all(t == shapefile.SHP_POLYGON for t, _ in recs)
    donut = next(p for _, p in recs if len(p) == 2)  # shell + hole
    assert len(donut[0]) == 5


# -- gml ----------------------------------------------------------------------

def test_shapefile_multipoint(tmp_path):
    from geomesa_tpu.io import shapefile

    ds = GeoDataset(n_shards=2)
    ds.create_schema("mp", "dtg:Date,*geom:MultiPoint")
    ds.insert("mp", {
        "geom": np.array(["MULTIPOINT ((1 1), (2 2), (3 3))"], object),
        "dtg": np.array(["2020-01-01"], "datetime64[ms]"),
    }, fids=np.array(["m1"]))
    ds.flush("mp")
    st = ds._store("mp")
    base = shapefile.write_shapefile(
        str(tmp_path / "mp.shp"), st.ft, st._all, st.dicts
    )
    recs = shapefile.read_shapefile(base)
    assert recs[0][0] == shapefile.SHP_MULTIPOINT
    assert len(recs[0][1][0]) == 3  # all three points survive


def test_gml_quote_in_fid():
    import xml.etree.ElementTree as ET

    from geomesa_tpu.io import gml

    ds = GeoDataset(n_shards=2)
    ds.create_schema("t", "dtg:Date,*geom:Point")
    ds.insert("t", {
        "geom__x": np.array([1.0]), "geom__y": np.array([2.0]),
        "dtg": np.array(["2020-01-01"], "datetime64[ms]"),
    }, fids=np.array(['my"fid']))
    ds.flush("t")
    st = ds._store("t")
    text = gml.dumps(st.ft, st._all, st.dicts)
    ET.fromstring(text)  # must stay well-formed


def test_gml_export():
    import xml.etree.ElementTree as ET

    from geomesa_tpu.io import gml

    ds = _ds(n=5)
    st = ds._store("t")
    text = gml.dumps(st.ft, st._all, st.dicts)
    root = ET.fromstring(text)  # well-formed
    ns = {"gml": "http://www.opengis.net/gml", "geomesa": "http://geomesa.org"}
    members = root.findall("gml:featureMember", ns)
    assert len(members) == 5
    pos = members[0].find(".//gml:pos", ns)
    assert pos is not None and len(pos.text.split()) == 2


# -- CLI orc ------------------------------------------------------------------

def test_cli_export_orc_and_gml(tmp_path, monkeypatch):
    import pyarrow.orc as orc

    from geomesa_tpu import cli

    ds = _ds(n=10)
    cat = str(tmp_path / "cat")
    ds.save(cat)
    out = str(tmp_path / "x.orc")
    cli.main(["export", "-c", cat, "-f", "t", "-F", "orc", "-o", out])
    assert orc.read_table(out).num_rows == 10
    gml_out = str(tmp_path / "x.gml")
    cli.main(["export", "-c", cat, "-f", "t", "-F", "gml", "-o", gml_out])
    assert "FeatureCollection" in open(gml_out).read()


# -- converters ---------------------------------------------------------------

def test_xml_converter():
    ds = GeoDataset(n_shards=2)
    ds.create_schema("t", "name:String,dtg:Date,*geom:Point")
    xml = """
    <root>
      <obs id="a1"><who>alice</who><when>2020-01-05T00:00:00Z</when>
        <loc lon="1.5" lat="2.5"/></obs>
      <obs id="a2"><who>bob</who><when>2020-01-06T00:00:00Z</when>
        <loc lon="3.5" lat="4.5"/></obs>
    </root>
    """
    ctx = ds.ingest("t", xml, {
        "type": "xml",
        "feature-path": "obs",
        "id-field": "$id",
        "fields": [
            {"name": "id", "path": "@id"},
            {"name": "name", "path": "who"},
            {"name": "when_s", "path": "when"},
            {"name": "dtg", "transform": "isoDateTime($when_s)"},
            {"name": "lon", "path": "loc/@lon"},
            {"name": "lat", "path": "loc/@lat"},
            {"name": "geom", "transform": "point(toDouble($lon), toDouble($lat))"},
        ],
    })
    assert ctx.success == 2, ctx.errors
    d = ds.query("t").to_dict()
    assert sorted(d["name"]) == ["alice", "bob"]
    assert sorted(d["__fid__"]) == ["a1", "a2"]


def test_fixed_width_converter():
    ds = GeoDataset(n_shards=2)
    ds.create_schema("t", "name:String,dtg:Date,*geom:Point")
    #       0123456789012345678901234567890
    lines = (
        "alice 2020-01-05  1.50  2.50\n"
        "bob   2020-01-06  3.50  4.50\n"
    )
    ctx = ds.ingest("t", lines, {
        "type": "fixed-width",
        "fields": [
            {"name": "name", "start": 0, "width": 6},
            {"name": "d", "start": 6, "width": 12},
            {"name": "dtg", "transform": "date('yyyy-MM-dd', $d)"},
            {"name": "xs", "start": 18, "width": 6},
            {"name": "ys", "start": 24, "width": 6},
            {"name": "geom", "transform": "point(toDouble($xs), toDouble($ys))"},
        ],
    })
    assert ctx.success == 2, ctx.errors
    d = ds.query("t").to_dict()
    assert sorted(d["name"]) == ["alice", "bob"]
    assert sorted(x for x, y in d["geom"]) == [1.5, 3.5]


def test_gml_avro_export_with_projection(tmp_path):
    """Projected queries (Query.properties) export without the dropped
    columns instead of crashing."""
    from geomesa_tpu.api.dataset import Query
    from geomesa_tpu.io import avro_io, gml

    ds = _ds(n=8)
    st = ds._store("t")
    fc = ds.query("t", Query(properties=["name", "geom"]))
    text = gml.dumps(st.ft, fc.batch, st.dicts)
    assert "geomesa:name" in text and "geomesa:v" not in text
    buf = io.BytesIO()
    avro_io.write_avro(buf, st.ft, fc.batch, st.dicts)
    buf.seek(0)
    schema, records = avro_io.read_avro(buf)
    names = {f["name"] for f in schema["fields"]}
    assert names == {"__fid__", "name", "geom"}
    assert len(records) == 8


def test_parquet_converter_line_offsets(tmp_path):
    """Chunked columnar ingest must thread the batch offset so
    lineNo()-derived ids stay unique across chunks."""
    import pyarrow as pa
    import pyarrow.parquet as pq

    from geomesa_tpu.convert import EvaluationContext, converter_for

    path = str(tmp_path / "in.parquet")
    pq.write_table(pa.table({
        "lon": [1.0, 2.0, 3.0], "lat": [1.0, 2.0, 3.0],
        "ts": np.array(["2020-01-01"] * 3, "datetime64[ms]"),
    }), path)
    ds = GeoDataset(n_shards=2)
    ft = ds.create_schema("t", "dtg:Date,*geom:Point")
    conv = converter_for(ft, {
        "type": "parquet",
        "id-field": "toString(lineNo())",
        "fields": [
            {"name": "dtg", "transform": "$ts"},
            {"name": "geom", "transform": "point($lon, $lat)"},
        ],
    })
    ctx = EvaluationContext()
    fids = []
    for data, f in conv.convert(path, ctx, batch_size=1):  # 1-row chunks
        fids.extend(f.tolist() if hasattr(f, "tolist") else list(f))
    assert len(set(fids)) == 3, fids


def test_parquet_converter(tmp_path):
    import pyarrow as pa
    import pyarrow.parquet as pq

    path = str(tmp_path / "in.parquet")
    pq.write_table(pa.table({
        "who": ["alice", "bob"],
        "ts": np.array(["2020-01-05", "2020-01-06"], "datetime64[ms]"),
        "lon": [1.5, 3.5],
        "lat": [2.5, 4.5],
    }), path)
    ds = GeoDataset(n_shards=2)
    ds.create_schema("t", "name:String,dtg:Date,*geom:Point")
    ctx = ds.ingest("t", path, {
        "type": "parquet",
        "fields": [
            {"name": "name", "transform": "toString($who)"},
            {"name": "dtg", "transform": "$ts"},
            {"name": "geom", "transform": "point($lon, $lat)"},
        ],
    })
    assert ctx.success == 2, ctx.errors
    assert ds.count("t") == 2
    assert sorted(ds.query("t").to_dict()["name"]) == ["alice", "bob"]
