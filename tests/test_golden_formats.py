"""Golden wire-format tests (SURVEY.md §4's TPU translation item 4):
frozen byte images of every serialization surface, so format drift is an
explicit, reviewed change — never a silent break of external consumers
(the JVM client, BIN viewers, Avro readers, stat JSON parsers).

Regenerate with `python tests/test_golden_formats.py regen` after an
INTENTIONAL format change, and say so in the commit message.
"""

import functools
import io as _io
import json
import sys
from pathlib import Path

import numpy as np
import pytest

GOLDEN = Path(__file__).parent / "golden"


def _fixture_batch():
    from geomesa_tpu import GeoDataset

    ds = GeoDataset(n_shards=1, prefer_device=False)
    ds.create_schema(
        "g", "name:String,v:Integer,w:Double,dtg:Date,*geom:Point")
    ds.insert("g", {
        "name": np.array(["alpha", "beta", "alpha"], dtype=object),
        "v": np.array([1, -2, 3], np.int32),
        "w": np.array([1.5, 2.25, -3.75]),
        "dtg": np.array(["2020-01-05T00:00:01", "2020-01-06T12:30:00",
                         "2020-01-07T23:59:59"], dtype="datetime64[ms]"),
        "geom__x": np.array([10.0, -20.5, 30.25]),
        "geom__y": np.array([1.0, 2.5, -3.25]),
    }, fids=np.array(["f1", "f2", "f3"], dtype=object))
    ds.flush()
    st = ds._store("g")
    return ds, st


@functools.lru_cache(maxsize=1)
def _artifacts():
    """name -> bytes for every frozen surface."""
    from geomesa_tpu.io import bin_format, twkb
    from geomesa_tpu.io.avro_io import write_avro
    from geomesa_tpu.schema.feature_type import FeatureType
    from geomesa_tpu.stream.confluent import ConfluentSerializer, SchemaRegistry
    from geomesa_tpu.stream.messages import GeoMessage
    from geomesa_tpu.utils.geometry import parse_wkt

    out = {}

    # BIN track format: 16-byte and 24-byte records
    tracks = np.array([7, 7, 9], np.int32)
    dtg = np.array([1578182401000, 1578313800000, 1578441599000], np.int64)
    lat = np.array([1.0, 2.5, -3.25], np.float32)
    lon = np.array([10.0, -20.5, 30.25], np.float32)
    out["bin16.bin"] = bin_format.pack(tracks, dtg, lat, lon)
    out["bin24.bin"] = bin_format.pack(
        tracks, dtg, lat, lon, labels=np.array([11, 22, 33], np.int64))

    # TWKB geometries at default precision
    out["twkb_point.bin"] = twkb.encode(parse_wkt("POINT (10.5 -3.25)"))
    out["twkb_line.bin"] = twkb.encode(
        parse_wkt("LINESTRING (0 0, 1.5 2.5, -3 4)"))
    out["twkb_poly.bin"] = twkb.encode(
        parse_wkt("POLYGON ((0 0, 4 0, 4 4, 0 4, 0 0))"))

    # GeoMessage wire format (change / delete / clear)
    out["geomessage_change.bin"] = GeoMessage.change(
        "fid-1", {"a": 1, "b": "x"}, 1578182400123).serialize()
    out["geomessage_delete.bin"] = GeoMessage.delete(
        "fid-2", 1578182400456).serialize()
    out["geomessage_clear.bin"] = GeoMessage.clear(1578182400789).serialize()

    # Confluent frame: registry-assigned id 1 + avro record
    ft = FeatureType.from_spec("c", "name:String,v:Integer,*geom:Point")
    reg = SchemaRegistry()
    ser = ConfluentSerializer(reg, "c-value", ft)
    out["confluent_frame.bin"] = ser.serialize(
        "k1", {"name": "alpha", "v": 7, "geom": "POINT (1 2)"})

    # Avro container file with a FIXED sync marker
    ds, st = _fixture_batch()
    buf = _io.BytesIO()
    write_avro(buf, st.ft, st._all, st.dicts, sync=b"\x00" * 16)
    out["avro_container.bin"] = buf.getvalue()

    # stat JSON (cost-model persistence format)
    stats = {
        "minmax": ds.stats("g", "MinMax(w)", "INCLUDE").to_json(),
        "histogram": ds.stats("g", "Histogram(w,4,-4,4)", "INCLUDE").to_json(),
        "enum": ds.stats("g", "Enumeration(name)", "INCLUDE").to_json(),
        "count": ds.stats("g", "Count()", "INCLUDE").to_json(),
    }
    out["stats.json"] = json.dumps(stats, indent=1, sort_keys=True).encode()

    # schema spec round-trip string (the catalog's persisted form)
    out["spec.txt"] = st.ft.spec().encode()
    return out


GOLDEN_NAMES = (
    "bin16.bin", "bin24.bin", "twkb_point.bin", "twkb_line.bin",
    "twkb_poly.bin", "geomessage_change.bin", "geomessage_delete.bin",
    "geomessage_clear.bin", "confluent_frame.bin", "avro_container.bin",
    "stats.json", "spec.txt",
)


def test_golden_set_is_complete():
    """The artifact map, the parametrize list, and the files on disk
    must agree — a new surface without a checked golden (or a stale file)
    is exactly the silent drift this suite exists to prevent."""
    assert set(_artifacts()) == set(GOLDEN_NAMES)
    on_disk = {p.name for p in GOLDEN.iterdir()}
    assert on_disk == set(GOLDEN_NAMES)


@pytest.mark.parametrize("name", GOLDEN_NAMES)
def test_golden(name):
    arts = _artifacts()
    want = (GOLDEN / name).read_bytes()
    got = arts[name]
    assert got == want, (
        f"wire format {name} drifted ({len(got)} vs {len(want)} bytes). "
        "If intentional, regenerate: python tests/test_golden_formats.py regen"
    )


def test_goldens_decode():
    """The frozen bytes must also DECODE correctly (goldens aren't just
    stable — they are valid)."""
    from geomesa_tpu.io import twkb
    from geomesa_tpu.io.avro_io import read_avro
    from geomesa_tpu.stream.messages import GeoMessage

    g = twkb.decode((GOLDEN / "twkb_point.bin").read_bytes())
    assert g.wkt().startswith("POINT")
    m = GeoMessage.deserialize((GOLDEN / "geomessage_change.bin").read_bytes())
    assert m.fid == "fid-1" and m.payload == {"a": 1, "b": "x"}
    schema, rows = read_avro(_io.BytesIO(
        (GOLDEN / "avro_container.bin").read_bytes()))
    assert len(rows) == 3
    assert rows[0] == {
        "__fid__": "f1", "name": "alpha", "v": 1, "w": 1.5,
        "dtg": 1578182401000, "geom": "POINT (10.0 1.0)",
    }


if __name__ == "__main__":
    # direct invocation puts tests/ (not the repo root) on sys.path[0]
    sys.path.insert(0, str(Path(__file__).parent.parent))
    if len(sys.argv) > 1 and sys.argv[1] == "regen":
        GOLDEN.mkdir(exist_ok=True)
        for name, data in _artifacts().items():
            (GOLDEN / name).write_bytes(data)
            print(f"wrote tests/golden/{name} ({len(data)} bytes)")
