"""Schema spec parsing + columnar encoding tests."""

import numpy as np
import pytest

from geomesa_tpu.schema import ColumnBatch, DictionaryEncoder, FeatureType
from geomesa_tpu.schema.columns import decode_batch, encode_batch

SPEC = "name:String,age:Integer,weight:Double,dtg:Date,*geom:Point:srid=4326;geomesa.z3.interval='week'"


def test_spec_parse_and_roundtrip():
    ft = FeatureType.from_spec("people", SPEC)
    assert [a.name for a in ft.attributes] == ["name", "age", "weight", "dtg", "geom"]
    assert ft.attr("age").type == "int32"
    assert ft.attr("geom").is_point and ft.attr("geom").default_geom
    assert ft.geom_field == "geom"
    assert ft.dtg_field == "dtg"
    assert ft.time_period == "week"
    ft2 = FeatureType.from_spec("people", ft.spec())
    assert [a.type for a in ft2.attributes] == [a.type for a in ft.attributes]
    assert ft2.user_data == ft.user_data


def test_spec_errors():
    with pytest.raises(ValueError):
        FeatureType.from_spec("x", "a:Bogus")
    with pytest.raises(ValueError):
        FeatureType.from_spec("x", "a")
    with pytest.raises(ValueError):
        FeatureType.from_spec("x", "a:Int,a:Int")
    with pytest.raises(KeyError):
        FeatureType.from_spec("x", "a:Int").attr("b")


def test_encode_decode_batch(rng):
    ft = FeatureType.from_spec("t", SPEC)
    dicts = {}
    n = 100
    data = {
        "name": [f"n{i % 5}" for i in range(n)],
        "age": rng.integers(0, 90, n),
        "weight": rng.uniform(40, 100, n),
        "dtg": np.array(["2020-01-01T12:00:00"] * n, dtype="datetime64[ms]"),
        "geom__x": rng.uniform(-180, 180, n),
        "geom__y": rng.uniform(-90, 90, n),
    }
    batch = encode_batch(ft, data, dicts)
    assert batch.n == n
    assert batch["name"].dtype == np.int32
    assert len(dicts["name"]) == 5
    assert batch["dtg"].dtype == np.int64
    dec = decode_batch(ft, batch, dicts)
    assert dec["name"][:3] == ["n0", "n1", "n2"]
    np.testing.assert_allclose(dec["geom"][0][0], data["geom__x"][0])
    assert str(dec["dtg"][0]).startswith("2020-01-01T12:00")


def test_encode_wkt_points_and_nonpoint():
    ft = FeatureType.from_spec("t", "label:String,*geom:Polygon")
    dicts = {}
    batch = encode_batch(
        ft,
        {
            "label": ["a"],
            "geom": ["POLYGON ((0 0, 4 0, 4 4, 0 4, 0 0))"],
        },
        dicts,
    )
    assert batch["geom__xmin"][0] == 0 and batch["geom__xmax"][0] == 4
    assert batch["geom__x"][0] == 2  # centroid-ish


def test_dictionary_encoder_null_and_lookup():
    d = DictionaryEncoder()
    codes = d.encode(["a", None, "b", "a"])
    np.testing.assert_array_equal(codes, [0, -1, 1, 0])
    assert d.code_of("a") == 0
    assert d.code_of("zzz") == -2
    assert d.decode(codes) == ["a", None, "b", "a"]


def test_ragged_batch_rejected():
    ft = FeatureType.from_spec("t", "a:Int,*geom:Point")
    with pytest.raises(ValueError):
        encode_batch(ft, {"a": [1, 2], "geom__x": [0.0], "geom__y": [0.0]}, {})
