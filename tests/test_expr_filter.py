"""Expression filter grammar: property-vs-property, arithmetic, st_*
function calls (FastFilterFactory.scala:395 parity — arbitrary GeoTools
expressions; r4's grammar was a fixed predicate set)."""

import numpy as np
import pytest

from geomesa_tpu import GeoDataset
from geomesa_tpu.filter.ecql import parse_ecql
from geomesa_tpu.filter import ir


def _ds(n=20_000, seed=0):
    rng = np.random.default_rng(seed)
    ds = GeoDataset(n_shards=2)
    ds.create_schema(
        "t", "speed:Double,heading:Double,weight:Float,limit:Double,"
             "a:String,b:String,dtg:Date,*geom:Point")
    data = {
        "speed": rng.uniform(0, 100, n),
        "heading": rng.uniform(0, 100, n),
        "weight": rng.uniform(0, 10, n).astype(np.float32),
        "limit": rng.uniform(0, 20, n),
        "a": rng.choice(["x", "y", "z"], n),
        "b": rng.choice(["x", "y"], n),
        "dtg": rng.integers(1577836800000, 1580515200000, n
                            ).astype("datetime64[ms]"),
        "geom__x": rng.uniform(-120, -70, n),
        "geom__y": rng.uniform(25, 50, n),
    }
    ds.insert("t", data, fids=np.arange(n).astype(str))
    ds.flush()
    return ds, data


def test_parse_shapes():
    f = parse_ecql("speed > heading")
    assert isinstance(f, ir.ExprCompare)
    f = parse_ecql("weight * 2 < limit")
    assert isinstance(f.left, ir.Arith) and f.left.op == "*"
    f = parse_ecql("(a + b) * 2 >= c - 1")
    assert isinstance(f.left, ir.Arith) and f.left.op == "*"
    f = parse_ecql("st_area(geom) > 0.5")
    assert isinstance(f.left, ir.FnCall) and f.left.name == "st_area"
    # legacy forms keep the legacy IR (device pushdown intact)
    assert isinstance(parse_ecql("speed > 5"), ir.Compare)
    assert isinstance(parse_ecql("5 < speed"), ir.Compare)
    # boolean vs arithmetic parens disambiguate by backtracking
    f = parse_ecql("(speed > 5) AND (heading < speed)")
    assert isinstance(f, ir.And)


def test_prop_vs_prop_and_arithmetic():
    ds, d = _ds()
    assert ds.count("t", "speed > heading") == int(
        (d["speed"] > d["heading"]).sum())
    assert ds.count("t", "weight * 2 < limit") == int(
        (d["weight"].astype(np.float64) * 2 < d["limit"]).sum())
    assert ds.count("t", "NOT (speed > heading)") == int(
        (~(d["speed"] > d["heading"])).sum())
    assert ds.count("t", "speed / 2 > heading - 10") == int(
        (d["speed"] / 2 > d["heading"] - 10).sum())
    assert ds.count("t", "speed - heading >= 0") == int(
        (d["speed"] - d["heading"] >= 0).sum())


def test_combined_with_indexed_predicates():
    """The expression rides as a refinement on the indexed window scan."""
    ds, d = _ds()
    q = "BBOX(geom, -100, 30, -80, 45) AND speed / 2 > heading - 10"
    m = ((d["geom__x"] >= -100) & (d["geom__x"] <= -80)
         & (d["geom__y"] >= 30) & (d["geom__y"] <= 45))
    assert ds.count("t", q) == int(
        (m & (d["speed"] / 2 > d["heading"] - 10)).sum())


def test_f32_adversarial_boundaries():
    """Values whose f32 images collide must still compare with exact f64
    semantics (the interval-arithmetic coarse mask may not drop them)."""
    ds = GeoDataset(n_shards=1)
    ds.create_schema("e", "p:Double,q:Double,*geom:Point")
    base = 1.0
    eps64 = np.finfo(np.float64).eps
    p = np.array([base, base, base + eps64, base - eps64, 2.0])
    q = np.array([base, base + eps64, base, base, 2.0 + 1e-12])
    ds.insert("e", {"p": p, "q": q,
                    "geom__x": np.zeros(5), "geom__y": np.zeros(5)},
              fids=np.arange(5).astype(str))
    ds.flush()
    assert ds.count("e", "p = q") == int((p == q).sum())
    assert ds.count("e", "p < q") == int((p < q).sum())
    assert ds.count("e", "p <> q") == int((p != q).sum())
    assert ds.count("e", "NOT (p < q)") == int((~(p < q)).sum())


def test_division_by_zero_rows_excluded():
    ds = GeoDataset(n_shards=1)
    ds.create_schema("z", "num:Double,den:Double,*geom:Point")
    num = np.array([1.0, 2.0, 3.0, 4.0])
    den = np.array([1.0, 0.0, 2.0, 0.0])
    ds.insert("z", {"num": num, "den": den,
                    "geom__x": np.zeros(4), "geom__y": np.zeros(4)},
              fids=np.arange(4).astype(str))
    ds.flush()
    # 1/1=1 > 0.9 yes; 2/0=inf > 0.9 yes (inf is a value, not null);
    # 3/2=1.5 yes; 4/0=inf yes
    assert ds.count("z", "num / den > 0.9") == 4
    assert ds.count("z", "num / den < 2") == 2  # rows 0 and 2


def test_string_prop_vs_prop():
    ds, d = _ds()
    oracle = int((np.asarray(d["a"]) == np.asarray(d["b"])).sum())
    assert ds.count("t", "a = b") == oracle
    assert ds.count("t", "a <> b") == len(d["a"]) - oracle
    with pytest.raises(ValueError, match="ordering"):
        ds.count("t", "a < b")


def test_function_calls():
    ds, d = _ds()
    from geomesa_tpu.utils.geometry import haversine_m

    got = ds.count(
        "t", "st_distanceSphere(geom, st_geomFromWKT('POINT (-95 38)'))"
             " < 500000")
    dist = haversine_m(d["geom__x"], d["geom__y"], -95.0, 38.0)
    assert got == int((dist < 500000).sum())
    # function on both sides of arithmetic
    got = ds.count(
        "t", "st_distanceSphere(geom, st_geomFromWKT('POINT (-95 38)'))"
             " / 1000 < 500")
    assert got == int((dist / 1000 < 500).sum())


def test_st_area_on_extent_column():
    ds = GeoDataset(n_shards=1)
    ds.create_schema("p", "v:Double,*geom:Polygon")
    wkts = ["POLYGON ((0 0, 1 0, 1 1, 0 1, 0 0))",          # area 1
            "POLYGON ((0 0, 3 0, 3 3, 0 3, 0 0))",          # area 9
            "POLYGON ((0 0, 0.5 0, 0.5 0.5, 0 0.5, 0 0))"]  # area 0.25
    ds.insert("p", {"geom": np.array(wkts, object),
                    "v": np.arange(3.0)}, fids=["a", "b", "c"])
    ds.flush()
    assert ds.count("p", "st_area(geom) > 0.5") == 2
    assert ds.count("p", "st_area(geom) > 0.5 AND v < 1") == 1


def test_expr_errors():
    ds, _ = _ds(n=100, seed=9)
    with pytest.raises(ValueError, match="st_nosuch"):
        ds.count("t", "st_nosuch(geom) > 1")
    with pytest.raises(KeyError, match="nope"):
        ds.count("t", "nope > speed")
    with pytest.raises(ValueError):
        parse_ecql("speed + heading")  # expression without comparison


def test_constant_folding_keeps_legacy_ir():
    """Review r5: literal-only subtrees fold so pushdown survives."""
    f = parse_ecql("speed < - 2")
    assert isinstance(f, ir.Compare) and f.value == -2
    f = parse_ecql("speed < 1 + 1")
    assert isinstance(f, ir.Compare) and f.value == 2
    assert isinstance(parse_ecql("1 + 1 = 2"), ir.Include)
    assert isinstance(parse_ecql("1 + 1 = 3"), ir.Exclude)


def test_jsonpath_guards():
    with pytest.raises(ValueError, match="jsonPath"):
        parse_ecql("jsonPath('$.a', js) + 1 > 2")
    with pytest.raises(ValueError, match="jsonPath"):
        parse_ecql("st_area(jsonPath('$.a', js)) > 2")


def test_json_attr_rejected_in_expressions():
    ds = GeoDataset(n_shards=1)
    ds.create_schema("j", "js:Json,speed:Double,*geom:Point")
    ds.insert("j", {"js": np.array(['{"a": 1}'], object),
                    "speed": np.array([1.0]),
                    "geom__x": np.zeros(1), "geom__y": np.zeros(1)},
              fids=["a"])
    ds.flush()
    with pytest.raises(ValueError, match="jsonPath"):
        ds.count("j", "js > speed")


def test_int64_exact_beyond_2_53():
    """Review r5: Long columns compare exactly past the f64 mantissa."""
    ds = GeoDataset(n_shards=1)
    ds.create_schema("i", "p:Long,q:Long,*geom:Point")
    p = np.array([2**53, 2**53, 7], np.int64)
    q = np.array([2**53 + 1, 2**53, 7], np.int64)
    ds.insert("i", {"p": p, "q": q,
                    "geom__x": np.zeros(3), "geom__y": np.zeros(3)},
              fids=np.arange(3).astype(str))
    ds.flush()
    assert ds.count("i", "p = q") == 2
    assert ds.count("i", "p <> q") == 1
