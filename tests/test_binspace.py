"""Time-bin sequence parallelism over the 2-D (shard, bin) mesh
(8 virtual CPU devices via tests/conftest.py)."""

import numpy as np
import pytest

from geomesa_tpu.parallel import binspace


@pytest.fixture(scope="module")
def mesh():
    import jax

    if jax.device_count() < 8:
        pytest.skip("needs 8 devices")
    return binspace.mesh_2d(4, 2)


def _cols(S=8, L=512, seed=0):
    rng = np.random.default_rng(seed)
    return {
        "x": rng.uniform(-10, 10, (S, L)).astype(np.float32),
        "y": rng.uniform(-10, 10, (S, L)).astype(np.float32),
    }


def test_bin_parallel_count_and_density(mesh):
    S, L = 8, 512
    cols = _cols(S, L)
    # 4 bin windows per shard, full coverage
    edges = np.linspace(0, L, 5).astype(np.int32)
    starts = np.tile(edges[:-1], (S, 1))
    ends = np.tile(edges[1:], (S, 1))
    counts = np.full(S, L, np.int32)
    bbox = (-5.0, -5.0, 5.0, 5.0)

    def predicate(c, xp):
        return (
            (c["x"] >= bbox[0]) & (c["x"] <= bbox[2])
            & (c["y"] >= bbox[1]) & (c["y"] <= bbox[3])
        )

    def agg(c, m, xp):
        from geomesa_tpu.kernels.density import density_grid

        return {
            "count": m.sum(),
            "grid": density_grid(c["x"], c["y"], m, bbox, 32, 32, None, xp),
        }

    want = int(
        (
            (cols["x"] >= -5) & (cols["x"] <= 5)
            & (cols["y"] >= -5) & (cols["y"] <= 5)
        ).sum()
    )
    for stream in (1, 2, 4):
        out = binspace.bin_parallel_run(
            mesh, cols, starts, ends, counts, L, predicate, agg,
            stream_chunks=stream,
        )
        assert int(out["count"]) == want
        assert abs(float(np.asarray(out["grid"]).sum()) - want) < 1e-3


def test_partial_windows_and_padding(mesh):
    """Windows that don't cover every row, count K not divisible by the bin
    axis — padding must contribute nothing."""
    S, L = 8, 256
    cols = _cols(S, L, seed=1)
    # 3 windows (K=3, not divisible by n_bin=2): rows [0,50), [100,150), [200,250)
    starts = np.tile(np.array([0, 100, 200], np.int32), (S, 1))
    ends = np.tile(np.array([50, 150, 250], np.int32), (S, 1))
    counts = np.full(S, L, np.int32)

    pred = lambda c, xp: c["x"] > 0  # noqa: E731
    agg = lambda c, m, xp: {"count": m.sum()}  # noqa: E731

    rowmask = np.zeros(L, bool)
    for a, b in ((0, 50), (100, 150), (200, 250)):
        rowmask[a:b] = True
    want = int(((cols["x"] > 0) & rowmask[None, :]).sum())
    out = binspace.bin_parallel_run(
        mesh, cols, starts, ends, counts, L, pred, agg
    )
    assert int(out["count"]) == want


def test_executor_binspace_dispatch(mesh, monkeypatch):
    """GeoDataset on a (shard, bin) mesh: count/density route through the
    bin-space path (the GSPMD fallback is poisoned to prove it)."""
    from geomesa_tpu import GeoDataset
    from geomesa_tpu.planning.executor import Executor

    rng = np.random.default_rng(2)
    n = 50_000
    data = {
        "geom__x": rng.uniform(-125, -66, n),
        "geom__y": rng.uniform(24, 49, n),
        "dtg": rng.integers(1577836800000, 1580515200000, n).astype(
            "datetime64[ms]"
        ),
    }
    ds = GeoDataset(mesh=mesh, n_shards=8)
    ds.create_schema("t", "dtg:Date,*geom:Point")
    ds.insert("t", data, fids=np.arange(n).astype(str))
    ds.flush("t")

    def poisoned(self, *a, **k):
        raise AssertionError("GSPMD path used; binspace expected")

    monkeypatch.setenv("GEOMESA_TPU_STRICT_DEVICE", "1")
    monkeypatch.setattr(Executor, "_device_mask_and_agg", poisoned)

    ecql = (
        "BBOX(geom, -100, 30, -80, 45) AND "
        "dtg DURING 2020-01-05T00:00:00Z/2020-01-15T00:00:00Z"
    )
    m = (
        (data["geom__x"] >= -100) & (data["geom__x"] <= -80)
        & (data["geom__y"] >= 30) & (data["geom__y"] <= 45)
        & (data["dtg"] >= np.datetime64("2020-01-05"))
        & (data["dtg"] < np.datetime64("2020-01-15"))
    )
    assert ds.count("t", ecql) == int(m.sum())
    grid = ds.density("t", ecql, bbox=(-100, 30, -80, 45), width=64, height=64)
    assert abs(float(grid.sum()) - int(m.sum())) < 1e-2
