"""Columnar geo-lake tier (geomesa_tpu/lake/; docs/LAKE.md).

Tier-1 contracts:

* **format**: encode/decode round-trips are BIT-IDENTICAL for every
  column dtype the store spills (seeded property walk); the container
  detects truncation, torn footers, and flipped payload bytes as
  ``LakeCorruptError`` (never as garbage data);
* **scan identity**: a lake-backed partitioned scan is bit-identical to
  the legacy npz-backed scan for count/density/density_curve/stats —
  same filters, same 8-virtual-device mesh;
* **pushdown**: a selective bbox over spilled lake partitions loads
  < 30% of the payload bytes (row-group statistics pruning), still
  bit-identical to the full load;
* **quarantine**: a corrupt footer and a corrupt row group both
  quarantine exactly the damaged bin (transient OSErrors never do), and
  ``clear_spill_quarantine`` re-admits after repair;
* **cache persistence**: persisted flat-cell/hierarchy entries restore
  into a freshly loaded process and answer a warm zoom-out with ZERO
  device dispatches;
* **fs resilience**: a repeatedly failing storage root trips its
  circuit breaker (fenced fast) and heals on success.
"""

import contextlib
import glob
import os

import numpy as np
import pytest

from geomesa_tpu import config, metrics, resilience
from geomesa_tpu.api.dataset import GeoDataset, Query
from geomesa_tpu.filter.ecql import parse_iso_ms
from geomesa_tpu.index.partitioned import PartitionedFeatureStore
from geomesa_tpu.lake.format import (
    LakeCorruptError, LakeFile, LakeWriter, decode_array, encode_array,
)
from geomesa_tpu.lake.snapshot import SNAPSHOT_FILE, PartitionSnapshot

SPEC = "name:String:index=true,weight:Double,dtg:Date,*geom:Point"
PSPEC = SPEC + ";geomesa.partition='time'"


def _counter(name: str) -> int:
    return metrics.registry().counter(name).value


def _data(n, seed=11, clustered=False):
    rng = np.random.default_rng(seed)
    if clustered:
        # hotspots: selective bboxes prune most row groups
        cx = rng.uniform(-115, -75, 10)
        cy = rng.uniform(28, 47, 10)
        k = rng.integers(0, 10, n)
        x = np.clip(cx[k] + rng.normal(0, 0.25, n), -120, -70)
        y = np.clip(cy[k] + rng.normal(0, 0.25, n), 25, 50)
    else:
        x = rng.uniform(-120, -70, n)
        y = rng.uniform(25, 50, n)
    return {
        "name": [f"actor{i % 20}" for i in range(n)],
        "weight": rng.uniform(0, 10, n),
        "dtg": rng.integers(
            parse_iso_ms("2020-01-01"), parse_iso_ms("2020-02-01"), n
        ).astype("datetime64[ms]"),
        "geom__x": x,
        "geom__y": y,
    }


def _mkpart(tmp_path, n=20_000, seed=11, clustered=False, lake=True,
            rowgroup=2048):
    """A partitioned dataset with every partition spilled to disk."""
    with contextlib.ExitStack() as stack:
        stack.enter_context(
            config.LAKE_ENABLED.scoped("true" if lake else "false"))
        stack.enter_context(
            config.LAKE_ROWGROUP_ROWS.scoped(str(rowgroup)))
        ds = GeoDataset(n_shards=4)
        ds.create_schema("t", PSPEC)
        st = ds._store("t")
        assert isinstance(st, PartitionedFeatureStore)
        st._spill_dir = str(tmp_path / ("lake" if lake else "npz"))
        ds.insert("t", _data(n, seed, clustered),
                  fids=np.arange(n).astype(str))
        ds.flush()
        st.spill_all()
    return ds, st


# ---------------------------------------------------------------------------
# format: encode/decode property walk + container integrity
# ---------------------------------------------------------------------------


def test_encode_decode_property_walk():
    """Seeded walk over every spillable dtype x shape: bit-identical."""
    rng = np.random.default_rng(3)
    cases = []
    for n in (0, 1, 7, 1000):
        cases += [
            np.sort(rng.integers(-(2**62), 2**62, n)),          # sorted i64
            rng.integers(0, 2**31, n).astype(np.int32),
            rng.integers(0, 255, n).astype(np.uint8),
            rng.uniform(-1e9, 1e9, n),                           # f64
            np.sort(rng.uniform(-180, 180, n)).astype(np.float32),
            rng.uniform(0, 1, n) < 0.5,                          # bool
            rng.integers(0, 10**12, n).astype("datetime64[ms]"),
            np.asarray([f"s{i % 13}" for i in range(n)]),        # unicode
            np.full(n, 42, np.int64),                            # constant
        ]
    # adversarial float payloads: NaN, inf, -0.0 must round-trip bits
    cases.append(np.asarray([np.nan, np.inf, -np.inf, -0.0, 0.0, 1e-300]))
    for a in cases:
        meta, payload = encode_array(a)
        b = decode_array(meta, payload)
        assert b.dtype == a.dtype, meta
        assert a.tobytes() == b.tobytes(), meta  # BIT identity incl NaN


def test_container_round_trip_and_corruption_detection(tmp_path):
    p = str(tmp_path / "x.lake")
    w = LakeWriter(p)
    refs = [w.add_array(np.arange(100, dtype=np.int64) * k)
            for k in (1, 3, 7)]
    w.finish({"kind": "test"})
    f = LakeFile(p)
    for k, r in zip((1, 3, 7), refs):
        assert np.array_equal(f.read_array(r), np.arange(100) * k)
    raw = open(p, "rb").read()
    # truncation (lost tail) and a torn footer both fail structurally
    open(p, "wb").write(raw[: len(raw) // 2])
    with pytest.raises(LakeCorruptError):
        LakeFile(p)
    open(p, "wb").write(raw[:1] + b"X" + raw[2:])  # head magic
    with pytest.raises(LakeCorruptError):
        LakeFile(p)
    # a flipped PAYLOAD byte passes open (footer intact) but fails the
    # blob's crc at read time
    off = len(b"GMLAKE01") + 5
    open(p, "wb").write(raw[:off] + bytes([raw[off] ^ 0xFF])
                        + raw[off + 1:])
    f = LakeFile(p)
    with pytest.raises(LakeCorruptError):
        f.read_array(refs[0])


# ---------------------------------------------------------------------------
# scan identity: lake vs npz, all additive ops, sharded mesh included
# ---------------------------------------------------------------------------

SEL = ("BBOX(geom, -100, 30, -90, 40) AND "
       "dtg DURING 2020-01-05T00:00:00Z/2020-01-20T00:00:00Z")


@pytest.mark.slow  # gated in the lake-smoke CI job (runs unfiltered)
def test_lake_vs_npz_scan_bit_identity(tmp_path):
    lake, lst = _mkpart(tmp_path, lake=True)
    npz, nst = _mkpart(tmp_path, lake=False)
    assert glob.glob(str(tmp_path / "lake" / "*" / SNAPSHOT_FILE))
    assert glob.glob(str(tmp_path / "npz" / "*" / "data.npz"))
    for q in ("INCLUDE", SEL, "BBOX(geom, -95, 33, -88, 39)"):
        with config.LAKE_ENABLED.scoped("true"):
            assert lake.count("t", q) == npz.count("t", q)
            dl = lake.density("t", q, (-120, 25, -70, 50), 64, 32)
            dn = npz.density("t", q, (-120, 25, -70, 50), 64, 32)
            assert np.array_equal(dl, dn)
            cl = lake.density_curve("t", q, level=6)
            cn = npz.density_curve("t", q, level=6)
            assert np.array_equal(cl[0], cn[0])
            assert np.array_equal(cl[1], cn[1])
            sl = lake.stats("t", "MinMax(weight)", q)
            sn = npz.stats("t", "MinMax(weight)", q)
            assert sl.to_json() == sn.to_json()


@pytest.mark.slow  # gated in the lake-smoke CI job (runs unfiltered)
def test_lake_pushdown_loads_under_30pct_and_stays_exact(tmp_path):
    """The acceptance gate: a selective bbox over clustered lake
    partitions loads < 30% of total payload bytes, bit-identically."""
    n = 24_000
    lake, lst = _mkpart(tmp_path, n=n, clustered=True, lake=True,
                        rowgroup=384)
    npz, _ = _mkpart(tmp_path, n=n, clustered=True, lake=False)
    total = sum(
        PartitionSnapshot(d).payload_bytes(None)
        for d in lst.spilled.values()
    )
    assert total > 0
    # a tight box around one hotspot
    hot = _data(n, seed=11, clustered=True)
    hx, hy = hot["geom__x"][0], hot["geom__y"][0]
    q = f"BBOX(geom, {hx - 0.4}, {hy - 0.4}, {hx + 0.4}, {hy + 0.4})"
    before_skip = _counter(metrics.LAKE_BYTES_SKIPPED)
    before_scans = _counter(metrics.LAKE_PUSHDOWN_SCANS)
    with config.LAKE_ENABLED.scoped("true"):
        got = lake.count("t", q)
    assert got == npz.count("t", q)
    assert _counter(metrics.LAKE_PUSHDOWN_SCANS) > before_scans
    skipped = _counter(metrics.LAKE_BYTES_SKIPPED) - before_skip
    fraction = 1.0 - skipped / total
    assert fraction < 0.30, f"loaded {fraction:.2%} of payload bytes"


def test_lake_pushdown_partial_load_never_cached_as_resident(tmp_path):
    """A pruned partial load is EPHEMERAL: the next unwindowed query
    must see the whole partition, not a pruned residue."""
    lake, lst = _mkpart(tmp_path, n=8_000)
    with config.LAKE_ENABLED.scoped("true"):
        lake.count("t", "BBOX(geom, -100, 30, -99, 31)")
        assert lake.count("t", "INCLUDE") == 8_000


def test_lake_open_snapshot_survives_concurrent_respill(tmp_path):
    """Lazy blob reads go through the handle the footer was parsed from:
    a concurrent re-spill's rmtree + os.replace of the snapshot must not
    turn an in-flight pruned read into a crc mismatch (which would
    falsely quarantine a healthy partition). POSIX: the unlinked-but-
    open fd keeps serving the old file's bytes."""
    ds, st = _mkpart(tmp_path, n=4_000)
    b = next(iter(st.spilled))
    d = st.spilled[b]
    snap = PartitionSnapshot(d)
    want = {c: snap.read_column(c, [0]) for c in snap.columns[:1]}
    # simulate the re-spill racing later lazy reads: the dir is rebuilt
    import shutil as _sh
    _sh.rmtree(d)
    os.makedirs(d)
    with open(os.path.join(d, SNAPSHOT_FILE), "wb") as fh:
        fh.write(b"GMLAKE01" + b"\x00" * 64)  # different bytes entirely
    for c, v in want.items():
        got = snap.read_column(c, [0])  # still the OLD file's data
        assert np.array_equal(got, v)
    assert b not in st.spill_quarantine()


def test_lake_fully_pruned_nonprimary_never_quarantines(tmp_path):
    """A window that prunes EVERY row group on a non-primary index must
    yield an empty ephemeral child — decoding zero groups cannot recover
    key-column dtypes, and guessing used to crash the index rebuild and
    falsely quarantine a HEALTHY partition."""
    ds, st = _mkpart(tmp_path, n=6_000)
    b = next(iter(st.spilled))
    child = st.scan_child(b, {"index": "attr:name",
                              "boxes": [(100.0, 80.0, 101.0, 81.0)],
                              "times": None})
    assert child is not None and child.count == 0
    assert b not in st.spill_quarantine()
    note = child.__dict__["_lake_note"]
    assert note["groups_loaded"] == 0 and note["bytes_skipped"] > 0
    # the bin still serves a full load afterwards
    assert ds.count("t", "INCLUDE") == 6_000


def test_pushdown_fallback_counted_and_noted(tmp_path):
    """docs/LAKE.md §10: a pushdown request the snapshot cannot serve
    pruned (exotic keyspace / pre-lake npz snapshot) counts in
    ``lake.pushdown.fallback`` and says so in the explain/audit
    exec_path — the full load must never read as "pushdown covered
    everything"."""
    # exotic keyspace: a window naming an index the snapshot can't build
    ds, st = _mkpart(tmp_path, n=4_000, seed=13)
    b = next(iter(st.spilled))
    f0 = _counter("lake.pushdown.fallback")
    w = {"index": "bogus-keyspace",
         "boxes": [(-116.0, 27.0, -112.0, 31.0)], "times": None}
    child = st.scan_child(b, w)
    assert child is not None  # full load still serves the scan
    assert _counter("lake.pushdown.fallback") == f0 + 1
    assert w["fallbacks"] == [(int(b), "unknown-keyspace")]

    # pre-lake npz snapshots: every pushdown-eligible count falls back,
    # counted once per spilled bin and noted on the audit event
    ds2, st2 = _mkpart(tmp_path, n=4_000, seed=13, lake=False)
    f1 = _counter("lake.pushdown.fallback")
    n = ds2.count("t", "BBOX(geom, -116, 27, -112, 31)")
    assert n == ds2.count("t", "BBOX(geom, -116, 27, -112, 31)")
    assert _counter("lake.pushdown.fallback") > f1
    ev = ds2.audit.recent(2)[0]  # the FIRST (cold) count's event
    note = ev.hints["exec_path"].get("lake_fallback", "")
    assert "legacy-snapshot" in note, ev.hints["exec_path"]
    assert "full-loaded" in note


# ---------------------------------------------------------------------------
# round-trip edge cases: null fills, empty partitions
# ---------------------------------------------------------------------------


def test_lake_snapshot_null_fills_new_attribute(tmp_path):
    """A lake snapshot written BEFORE a schema update null-fills the new
    attribute on reload (schema_null_fills contract), full and pruned."""
    ds, st = _mkpart(tmp_path, n=5_000)
    ds.update_schema("t", "speed:Double")
    with config.LAKE_ENABLED.scoped("true"):
        fc = ds.query("t", Query("INCLUDE", properties=["name", "speed"]))
        cols = fc.batch.columns
        assert "speed" in cols
        assert len(cols["speed"]) == 5_000
        assert np.isnan(np.asarray(cols["speed"], np.float64)).all()
        # the pruned path null-fills too
        assert ds.count("t", "BBOX(geom, -100, 30, -95, 35)") >= 0


def test_lake_empty_partition_round_trip(tmp_path):
    ds, st = _mkpart(tmp_path, n=200)
    with config.LAKE_ENABLED.scoped("true"):
        ds.delete_features("t", "INCLUDE")
        ds.flush()
        st.spill_all()
        assert ds.count("t", "INCLUDE") == 0
        # schema + dtypes survive an empty reload
        fc = ds.query("t", "INCLUDE")
        assert fc.batch.n == 0


# ---------------------------------------------------------------------------
# quarantine: corrupt footer vs corrupt row group vs transient OSError
# ---------------------------------------------------------------------------


def _one_spilled_dir(st):
    b = sorted(st.spilled)[0]
    return b, st.spilled[b]


def test_corrupt_footer_quarantines_and_readmits(tmp_path):
    ds, st = _mkpart(tmp_path, n=4_000)
    b, d = _one_spilled_dir(st)
    p = os.path.join(d, SNAPSHOT_FILE)
    raw = open(p, "rb").read()
    open(p, "wb").write(raw[:-4] + b"XXXX")  # torn tail
    with config.LAKE_ENABLED.scoped("true"):
        with pytest.raises(ValueError, match="quarantine"):
            st.child(b)
        # fails FAST now (no re-parse)
        with pytest.raises(ValueError, match="quarantined"):
            st.child(b)
        open(p, "wb").write(raw)  # repair
        assert st.clear_spill_quarantine() == [b]
        assert st.child(b).count > 0


def test_corrupt_row_group_quarantines_and_readmits(tmp_path):
    """A flipped byte inside one LAZY column's row-group blob passes
    open (footer + eager key columns intact) and surfaces at first
    column decode mid-scan — the bin still quarantines (the lazy-column
    corruption hook), and a repair + clear re-admits it."""
    ds, st = _mkpart(tmp_path, n=4_000)
    b, d = _one_spilled_dir(st)
    p = os.path.join(d, SNAPSHOT_FILE)
    snap = PartitionSnapshot(d)
    ref = snap.groups[0]["cols"]["c/weight"]  # lazy attribute column
    off, length, _crc = snap.file.blobs[int(ref["b"])]
    raw = open(p, "rb").read()
    open(p, "wb").write(raw[:off] + bytes([raw[off] ^ 0xFF])
                        + raw[off + 1:])
    with config.LAKE_ENABLED.scoped("true"):
        child = st.child(b)  # opens fine: only the footer + keys read
        with pytest.raises(LakeCorruptError):
            child._all.columns["weight"]
        assert b in st._spill_quarantine
        open(p, "wb").write(raw)
        assert st.clear_spill_quarantine() == [b]
        # evict the half-poisoned resident and reload clean
        st.partitions.pop(b, None)
        st.spilled[b] = d
        fresh = st.child(b)
        assert len(fresh._all.columns["weight"]) == fresh.count


def test_transient_oserror_retries_never_quarantines(tmp_path):
    ds, st = _mkpart(tmp_path, n=2_000)
    b, d = _one_spilled_dir(st)
    # two transient failures then success: the retry ladder absorbs them
    with config.LAKE_ENABLED.scoped("true"), \
            config.FAULT_INJECTION.scoped("true"), \
            resilience.inject_faults(seed=1) as inj:
        inj.fail("index.spill.load", OSError(5, "EIO"), times=2)
        assert st.child(b).count > 0
    assert b not in st._spill_quarantine


# ---------------------------------------------------------------------------
# cache persistence: restart -> restore -> warm zoom-out, zero dispatches
# ---------------------------------------------------------------------------


def test_cache_persist_restore_zero_dispatch_zoom_out(tmp_path, rng):
    with contextlib.ExitStack() as stack:
        stack.enter_context(config.CACHE_ENABLED.scoped("true"))
        stack.enter_context(config.CACHE_CELLS_PER_AXIS.scoped("4"))
        ds = GeoDataset(n_shards=2)
        ds.create_schema("pts", SPEC)
        n = 4_000
        r = np.random.default_rng(5)
        ds.insert("pts", {
            "name": ["a"] * n,
            "weight": r.uniform(0, 2, n),
            "dtg": np.full(n, parse_iso_ms("2020-01-01")
                           ).astype("datetime64[ms]"),
            "geom__x": r.uniform(-170, 170, n),
            "geom__y": r.uniform(-80, 80, n),
        }, fids=np.arange(n).astype(str))
        ds.flush()
        warm = ["BBOX(geom, -90, -45, 0, 0)", "BBOX(geom, 0, -45, 90, 0)",
                "BBOX(geom, -90, 0, 0, 45)", "BBOX(geom, 0, 0, 90, 45)"]
        for q in warm:
            ds.count("pts", q)
        zoom = "BBOX(geom, -90, -45, 90, 45)"
        expect = ds.count("pts", zoom)  # promotes the hierarchy parent
        ckpt = str(tmp_path / "ckpt")
        cpath = str(tmp_path / "cache.lake")
        ds.save(ckpt)
        summary = ds.persist_cache(cpath)
        assert summary.get("pts", 0) > 0

        # "restart": a fresh dataset from the checkpoint + restored cache
        ds2 = GeoDataset.load(ckpt)
        out = ds2.restore_cache(cpath)
        assert out["pts"].get("restored", 0) > 0
        before = _counter(metrics.EXEC_DEVICE_DISPATCH)
        assert ds2.count("pts", zoom) == expect
        assert _counter(metrics.EXEC_DEVICE_DISPATCH) == before, \
            "warm zoom-out after restore must not dispatch"

        # guard: a restore against CHANGED data is refused
        ds2.insert("pts", {
            "name": ["x"], "weight": np.asarray([1.0]),
            "dtg": np.asarray([parse_iso_ms("2020-01-02")]
                              ).astype("datetime64[ms]"),
            "geom__x": np.asarray([1.0]), "geom__y": np.asarray([2.0]),
        }, fids=np.asarray(["zz"]))
        ds2.flush()
        out2 = ds2.restore_cache(cpath)
        assert "skipped" in out2["pts"]


# ---------------------------------------------------------------------------
# fs root circuit breaker (the lake tier's remote-root treatment)
# ---------------------------------------------------------------------------


def test_fs_root_breaker_fences_and_heals(tmp_path, monkeypatch):
    from geomesa_tpu.fs import DateTimeScheme, FileSystemStorage
    from geomesa_tpu.schema.feature_type import FeatureType

    root = str(tmp_path / "fsroot")
    fs = FileSystemStorage(root)
    ft = FeatureType.from_spec("t", "name:String,dtg:Date,*geom:Point")
    fs.create(ft, DateTimeScheme("day"))
    fs.write("t", {
        "name": ["a", "b"],
        "dtg": np.array(["2020-01-05"] * 2, "datetime64[ms]"),
        "geom__x": [1.0, 2.0], "geom__y": [1.0, 2.0],
    })
    part = fs.partitions("t")[0]

    boom = {"on": True}
    real = fs._read_file

    def flaky(path, columns=None):
        if boom["on"]:
            raise OSError(5, "EIO: dead mount")
        return real(path, columns=columns)

    monkeypatch.setattr(fs, "_read_file", flaky)
    resilience.reset_breakers()
    try:
        with config.RETRY_ATTEMPTS.scoped("1"), \
                config.BREAKER_THRESHOLD.scoped("3"):
            for _ in range(3):
                with pytest.raises(OSError):
                    fs.read_partition("t", part)
            # breaker open: fenced fast, typed — no disk attempt at all
            with pytest.raises(resilience.CircuitOpenError):
                fs.read_partition("t", part)
            # under allow_partial the fenced root degrades, not fails:
            # the fenced file skips, leaving an empty partition table
            with resilience.allow_partial():
                assert fs.read_partition("t", part).num_rows == 0
            # the mount heals: breaker reset re-admits every file
            boom["on"] = False
            resilience.reset_breakers()
            assert fs.read_partition("t", part) is not None
    finally:
        resilience.reset_breakers()


def test_push_window_coalesces_scatter_group_boxes(tmp_path):
    """Group-scoped plan bounds (ISSUE 15): a fleet-scattered sub-query
    carries one BBOX per owned cell (an OR of exactly-tiling half-open
    realizations) — the pushdown window coalesces the runs into a
    compact cover (never narrower: closing the one-ulp seams only
    widens), and the pruned scan stays bit-identical."""
    from geomesa_tpu.planning.partitioned_exec import _coalesce_boxes

    def prev(v):
        return float(np.nextafter(v, -np.inf))

    # a 4x2 run of half-open cell realizations (the decompose shape)
    cells = []
    for iy in range(2):
        for ix in range(4):
            x0, y0 = ix * 11.25, iy * 11.25
            cells.append((x0, y0, prev(x0 + 11.25), prev(y0 + 11.25)))
    out = _coalesce_boxes(list(cells))
    assert len(out) == 1
    x0, y0, x1, y1 = out[0]
    for b in cells:  # cover, never narrower
        assert x0 <= b[0] and y0 <= b[1] and x1 >= b[2] and y1 >= b[3]
    # disjoint islands stay separate
    assert len(_coalesce_boxes([(0, 0, 1, 1), (5, 5, 6, 6)])) == 2
    # and a scatter-shaped OR filter over the lake prunes bit-identically
    n = 24_000
    lake, _lst = _mkpart(tmp_path, n=n, clustered=True, lake=True,
                         rowgroup=384)
    npz, _ = _mkpart(tmp_path, n=n, clustered=True, lake=False)
    ors = " OR ".join(
        f"BBOX(geom, {x}, 10.0, {prev(x + 11.25)}, {prev(21.25)})"
        for x in (-45.0, -33.75, -22.5)
    )
    q = f"(name <> 'zz') AND ({ors})"
    with config.LAKE_ENABLED.scoped("true"):
        got = lake.count("t", q)
    assert got == npz.count("t", q)
