"""TWKB codec, Z3Frequency sketch, per-key sampling, query interceptors,
sidecar version handshake, and the new CLI commands."""

import numpy as np
import pytest

from geomesa_tpu import GeoDataset
from geomesa_tpu.api.dataset import Query


# -- twkb ---------------------------------------------------------------------

@pytest.mark.parametrize("wkt", [
    "POINT (12.3456789 -45.6789012)",
    "LINESTRING (0 0, 1.5 2.5, -3 4)",
    "POLYGON ((0 0, 4 0, 4 4, 0 4, 0 0), (1 1, 2 1, 2 2, 1 2, 1 1))",
    "MULTIPOINT ((1 1), (2 2))",
    "MULTILINESTRING ((0 0, 1 1), (2 2, 3 3))",
    "MULTIPOLYGON (((0 0, 1 0, 1 1, 0 1, 0 0)), ((5 5, 6 5, 6 6, 5 6, 5 5)))",
])
def test_twkb_round_trip(wkt):
    from geomesa_tpu.io import twkb
    from geomesa_tpu.utils.geometry import parse_wkt

    g = parse_wkt(wkt)
    data = twkb.encode(g, precision=7)
    g2 = twkb.decode(data)
    assert g2.kind == g.kind
    np.testing.assert_allclose(
        np.asarray(g2.bounds()), np.asarray(g.bounds()), atol=1e-6
    )
    # delta+varint coding should beat WKT text for typical geometries
    assert len(data) < len(wkt)


def test_twkb_precision():
    from geomesa_tpu.io import twkb
    from geomesa_tpu.utils.geometry import parse_wkt

    g = parse_wkt("POINT (12.123456789 45.987654321)")
    lo = twkb.decode(twkb.encode(g, precision=2))
    hi = twkb.decode(twkb.encode(g, precision=7))
    assert abs(lo.x - g.x) < 0.01
    assert abs(hi.x - g.x) < 1e-6
    assert len(twkb.encode(g, 2)) < len(twkb.encode(g, 7))
    with pytest.raises(ValueError):
        twkb.encode(g, 9)


# -- z3 frequency -------------------------------------------------------------

def test_z3_frequency_sketch():
    from geomesa_tpu.stats import parse_stat
    from geomesa_tpu.stats.sketches import Stat, Z3FrequencyStat

    st = parse_stat("Z3Frequency(geom,dtg,week,8)")
    assert isinstance(st, Z3FrequencyStat)
    rng = np.random.default_rng(0)
    n = 5000
    t0 = 1577836800000
    cols = {
        "geom__x": np.full(n, -90.0) + rng.normal(0, 0.001, n),
        "geom__y": np.full(n, 40.0) + rng.normal(0, 0.001, n),
        "dtg": t0 + rng.integers(0, 86_400_000, n),
    }
    st.observe(cols)
    assert not st.is_empty
    # query a specific point: the sketch must not under-count its cell
    qt = t0 + 1000
    b, off = st.binned.to_bin_and_offset(np.asarray([qt]))
    ab, aoff = st.binned.to_bin_and_offset(cols["dtg"])
    qkey = st._key(np.asarray([-90.0]), np.asarray([40.0]), off)[0]
    akeys = st._key(cols["geom__x"], cols["geom__y"], aoff)
    exact = int(((akeys == qkey) & (ab == b[0])).sum())
    got = st.count(int(b[0]), -90.0, 40.0, float(off[0]))
    assert got >= exact > 0  # count-min only over-counts
    # merge doubles counts; serialization round-trips
    st2 = parse_stat("Z3Frequency(geom,dtg,week,8)")
    st2.observe(cols)
    st2.merge(st)
    assert st2.count(int(b[0]), -90.0, 40.0, float(off[0])) >= 2 * exact
    st3 = Stat.from_json(st2.to_json())
    assert isinstance(st3, Z3FrequencyStat)
    assert st3.count(int(b[0]), -90.0, 40.0, float(off[0])) == st2.count(
        int(b[0]), -90.0, 40.0, float(off[0])
    )


# -- per-key sampling ---------------------------------------------------------

def test_sampling_mask_by_key():
    from geomesa_tpu.kernels.masks import sampling_mask_by_key

    keys = np.array([1, 1, 1, 1, 2, 2, 2, 3, 3, 3, 3, 3])
    mask = np.ones(len(keys), bool)
    out = sampling_mask_by_key(mask, 2, keys)
    # every key keeps ceil(count/2) rows: 2, 2 (of 3... wait 4->2, 3->2, 5->3)
    for k, want in ((1, 2), (2, 2), (3, 3)):
        assert out[keys == k].sum() == want
    # masked-out rows never sampled
    mask2 = mask.copy()
    mask2[:4] = False
    out2 = sampling_mask_by_key(mask2, 2, keys)
    assert out2[:4].sum() == 0


def test_query_sample_by():
    rng = np.random.default_rng(1)
    n = 3000
    ds = GeoDataset(n_shards=2)
    ds.create_schema("t", "track:String,dtg:Date,*geom:Point")
    ds.insert("t", {
        "geom__x": rng.uniform(-10, 10, n), "geom__y": rng.uniform(-10, 10, n),
        "dtg": np.full(n, 1577836800000, "datetime64[ms]"),
        "track": rng.choice(["a", "b", "c"], n),
    }, fids=np.arange(n).astype(str))
    ds.flush("t")
    fc = ds.query("t", Query(sampling=10, sample_by="track"))
    d = fc.to_dict()
    names, counts = np.unique(np.asarray(d["track"]), return_counts=True)
    full = {k: int((np.asarray(ds.query("t").to_dict()["track"]) == k).sum())
            for k in names}
    for k, c in zip(names, counts):
        want = -(-full[k] // 10)  # ceil
        assert c == want, (k, c, want)


# -- interceptors -------------------------------------------------------------

class _BBoxNarrower:
    """Rewrite INCLUDE queries to a bbox; veto huge grids via guard."""

    def rewrite(self, f, ft):
        from geomesa_tpu.filter import ir, parse_ecql

        if isinstance(f, ir.Include):
            return parse_ecql("BBOX(geom, -5, -5, 5, 5)")
        return f

    def guard(self, plan):
        if plan.est_count > 10_000_000:
            raise ValueError("too big")


def test_query_interceptor_rewrite_and_guard():
    from geomesa_tpu.planning import interceptors

    rng = np.random.default_rng(2)
    n = 2000
    ds = GeoDataset(n_shards=2)
    ds.create_schema("t", "dtg:Date,*geom:Point")
    ds.insert("t", {
        "geom__x": rng.uniform(-10, 10, n), "geom__y": rng.uniform(-10, 10, n),
        "dtg": np.full(n, 1577836800000, "datetime64[ms]"),
    }, fids=np.arange(n).astype(str))
    ds.flush("t")
    try:
        interceptors.register("t", _BBoxNarrower())
        got = ds.count("t", "INCLUDE")
        want = ds.count("t", "BBOX(geom, -5, -5, 5, 5)")
        assert got == want < n
    finally:
        interceptors.clear("t")
    assert ds.count("t", "INCLUDE") == n  # cleared


def test_interceptor_from_user_data():
    from geomesa_tpu.planning import interceptors

    ds = GeoDataset(n_shards=2)
    ds.create_schema(
        "u",
        "dtg:Date,*geom:Point;"
        f"geomesa.query.interceptors='{__name__}._BBoxNarrower'",
    )
    ds.insert("u", {
        "geom__x": np.array([0.0, 8.0]), "geom__y": np.array([0.0, 8.0]),
        "dtg": np.array(["2020-01-01"] * 2, "datetime64[ms]"),
    }, fids=np.array(["a", "b"]))
    ds.flush("u")
    assert ds.count("u", "INCLUDE") == 1  # rewritten to the small bbox


# -- sidecar version handshake ------------------------------------------------

def test_sidecar_version_handshake():
    fl = pytest.importorskip("pyarrow.flight")  # noqa: F841
    from geomesa_tpu.sidecar import GeoFlightClient, GeoFlightServer, PROTOCOL_VERSION

    ds = GeoDataset(n_shards=2)
    srv = GeoFlightServer(ds, "grpc+tcp://127.0.0.1:0")
    import threading

    t = threading.Thread(target=srv.serve, daemon=True)
    t.start()
    try:
        with GeoFlightClient(f"grpc+tcp://127.0.0.1:{srv.port}") as c:
            info = c.check_version()
            assert info["protocol"] == PROTOCOL_VERSION
            assert "version" in info
    finally:
        srv.shutdown()


# -- CLI ----------------------------------------------------------------------

def test_cli_env(capsys):
    from geomesa_tpu import cli

    cli.main(["env"])
    out = capsys.readouterr().out
    assert "geomesa.scan.ranges.target" in out
    assert "geomesa.query.timeout" in out


def test_cli_convert(tmp_path, capsys):
    from geomesa_tpu import cli

    conf = tmp_path / "conv.conf"
    conf.write_text(
        '{"type": "delimited-text", "format": "CSV", "id-field": "$1",'
        ' "fields": ['
        '{"name": "dtg", "transform": "date(\'yyyy-MM-dd\', $2)"},'
        '{"name": "geom", "transform": "point(toDouble($3), toDouble($4))"}'
        "]}"
    )
    data = tmp_path / "in.csv"
    data.write_text("a,2020-01-01,1.5,2.5\nb,2020-01-02,3.5,4.5\n")
    cli.main([
        "convert", "-f", "t", "-s", "dtg:Date,*geom:Point",
        "-C", str(conf), "-i", str(data),
    ])
    out = capsys.readouterr().out
    assert out.count("\n") == 2 and "geom" in out


def test_cli_playback(tmp_path, capsys):
    from geomesa_tpu import cli

    rng = np.random.default_rng(3)
    n = 50
    ds = GeoDataset(n_shards=2)
    ds.create_schema("t", "dtg:Date,*geom:Point")
    ds.insert("t", {
        "geom__x": rng.uniform(-10, 10, n), "geom__y": rng.uniform(-10, 10, n),
        "dtg": (1577836800000 + np.arange(n) * 1000).astype("datetime64[ms]"),
    }, fids=np.arange(n).astype(str))
    ds.flush("t")
    cat = str(tmp_path / "cat")
    ds.save(cat)
    cli.main(["playback", "-c", cat, "-f", "t", "--fast"])
    out = capsys.readouterr().out
    assert f"played back {n} features" in out
