"""Protocol conformance suite (docs/PROTOCOL.md v1).

Drives the FULL lifecycle — schema CRUD, Arrow ingest, CQL queries,
projection/limit/sampling, density, stats, BIN export, explain, audit,
selectivity counters, streaming, errors — exclusively through
``sidecar/client.py`` against a REAL subprocess server (no in-process
shortcuts), the way the GeoTools shim would. This is the compatibility
contract the JVM client (jvm/GeoMesaTpuFlightClient.java) codes against.
"""

import os
import socket
import subprocess
import sys
import time

import numpy as np
import pyarrow as pa
import pyarrow.flight as fl
import pytest

from geomesa_tpu.sidecar.client import GeoFlightClient

SPEC = "name:String:index=true,speed:Float,dtg:Date,*geom:Point"


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@pytest.fixture(scope="module")
def server(tmp_path_factory):
    catalog = str(tmp_path_factory.mktemp("catalog"))
    port = _free_port()
    # hermetic server: strip the axon TPU plugin's sitecustomize dir from
    # PYTHONPATH (it force-overrides JAX_PLATFORMS at interpreter startup,
    # which would make this suite compile over the device tunnel)
    pp = [
        p for p in os.environ.get("PYTHONPATH", "").split(os.pathsep)
        if p and "axon" not in p
    ]
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=os.pathsep.join(
        [os.path.dirname(os.path.dirname(os.path.abspath(__file__)))] + pp
    ))
    proc = subprocess.Popen(
        [sys.executable, "-m", "geomesa_tpu.cli", "serve",
         "--catalog", catalog, "--port", str(port)],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
    )
    loc = f"grpc+tcp://127.0.0.1:{port}"
    deadline = time.time() + 60
    last = None
    while time.time() < deadline:
        try:
            with GeoFlightClient(loc) as c:
                c.version()
            break
        except Exception as e:  # not up yet
            last = e
            if proc.poll() is not None:
                out = proc.stdout.read().decode()
                raise RuntimeError(f"server died: {out}")
            time.sleep(0.25)
    else:
        proc.kill()
        raise RuntimeError(f"server never came up: {last}")
    yield loc
    proc.terminate()
    proc.wait(timeout=20)


@pytest.fixture()
def client(server):
    with GeoFlightClient(server) as c:
        yield c


@pytest.fixture()
def seeded(client):
    """Idempotently ensure the shared 'conf' schema exists with the
    standard table, so every test also passes in isolation (-k / xdist),
    not just in file order."""
    if "conf" not in client.list_schemas():
        client.create_schema("conf", SPEC)
        client.insert_arrow("conf", _table())
    return client


N = 5_000


def _table(n=N, seed=1):
    rng = np.random.default_rng(seed)
    xs = rng.uniform(-120, -70, n)
    ys = rng.uniform(25, 50, n)
    flat = np.empty(2 * n)
    flat[0::2], flat[1::2] = xs, ys
    return pa.table({
        "__fid__": pa.array([f"f{i}" for i in range(n)], pa.utf8()),
        "name": pa.array([f"n{i % 5}" for i in range(n)]).dictionary_encode(),
        "speed": pa.array(rng.uniform(0, 30, n).astype(np.float32)),
        "dtg": pa.array(
            (np.datetime64("2024-05-01", "ms")
             + rng.integers(0, 20 * 86_400_000, n)), pa.timestamp("ms")
        ),
        "geom": pa.FixedSizeListArray.from_arrays(pa.array(flat), 2),
    })


CQL = "BBOX(geom, -100, 30, -80, 45) AND name = 'n1'"


def _oracle_mask(t):
    geom = np.asarray(t["geom"].combine_chunks().flatten())
    x, y = geom[0::2], geom[1::2]
    names = np.asarray(t["name"].to_pylist())
    return (x >= -100) & (x <= -80) & (y >= 30) & (y <= 45) & (names == "n1")


def test_01_version_handshake(client):
    info = client.check_version()
    assert info["protocol"] == 1


def test_02_schema_lifecycle(client):
    assert client.create_schema("lc", SPEC) == "lc"
    assert "lc" in client.list_schemas()
    desc = client.describe("lc")
    assert "name" in desc and "geom" in desc
    with pytest.raises(fl.FlightError):
        client.create_schema("lc", SPEC)  # duplicate
    client.delete_schema("lc")


def test_03_ingest_and_count(client):
    if "conf" not in client.list_schemas():
        client.create_schema("conf", SPEC)
    t = _table()
    client.insert_arrow("conf", t)
    assert client.count("conf") == N
    assert client.count("conf", CQL) == int(_oracle_mask(t).sum())


def test_04_query_cql_projection_limit(seeded):
    client = seeded
    t = _table()
    want = int(_oracle_mask(t).sum())
    got = client.query("conf", CQL)
    assert got.num_rows == want
    assert set(got["name"].to_pylist()) == {"n1"}
    # schema metadata carries the spec string (PROTOCOL §2)
    assert b"geomesa:spec" in got.schema.metadata
    proj = client.query("conf", properties=["speed"])
    assert set(proj.column_names) == {"__fid__", "speed"}
    assert client.query("conf", max_features=9).num_rows == 9
    samp = client.query("conf", sampling=10)
    assert 0 < samp.num_rows <= N // 10 + 1


def test_05_streaming_batches(seeded, server):
    """PROTOCOL §3: query results arrive as incremental record batches."""
    os.environ["GEOMESA_ARROW_BATCH_ROWS"] = "100000"
    ticket = fl.Ticket(b'{"op": "query", "schema": "conf"}')
    with GeoFlightClient(server) as c:
        reader = c._client.do_get(ticket)
        nbatches = rows = 0
        for chunk in reader:
            nbatches += 1
            rows += chunk.data.num_rows
    assert rows == N
    assert nbatches >= 1


def test_06_density(seeded):
    client = seeded
    t = _table()
    grid = client.density("conf", CQL, bbox=(-100, 30, -80, 45),
                          width=64, height=64)
    assert grid.shape == (64, 64)
    assert int(grid.sum()) == int(_oracle_mask(t).sum())


def test_07_stats(seeded):
    client = seeded
    t = _table()
    mm = client.stats("conf", "MinMax(speed)", CQL)
    speeds = np.asarray(t["speed"].to_pylist())[_oracle_mask(t)]
    v = mm.value()
    assert v["min"] == pytest.approx(float(speeds.min()), rel=1e-6)
    assert v["max"] == pytest.approx(float(speeds.max()), rel=1e-6)
    enum = client.stats("conf", "Enumeration(name)", CQL)
    assert set(enum.value().keys()) == {"n1"}


def test_08_bin_export(seeded):
    client = seeded
    t = _table()
    blob = client.export_bin("conf", CQL, track="name")
    want = int(_oracle_mask(t).sum())
    assert len(blob) == want * 16


def test_09_explain_and_audit(seeded):
    client = seeded
    plan = client.explain("conf", CQL)
    assert "Chosen index" in plan
    client.count("conf", CQL)
    evs = client.audit(5)
    assert evs
    last = evs[-1]
    # selectivity counters cross the wire (PROTOCOL §5)
    assert last["table_rows"] == N
    assert last["scanned"] >= last["hits"] > 0


def test_10_discovery(seeded):
    client = seeded
    infos = list(client._client.list_flights())
    names = [i.descriptor.path[0].decode() for i in infos]
    assert "conf" in names


def test_11_errors(seeded):
    client = seeded
    with pytest.raises(fl.FlightError, match="conf2|no schema"):
        client.count("conf2")
    with pytest.raises(fl.FlightError, match="nosuch"):
        client.count("conf", "nosuch = 3")
    with pytest.raises(fl.FlightError, match="unknown action"):
        client._action("bogus-action")


def test_12_delete_schema(client):
    # delete semantics on a data-bearing schema of its own
    client.create_schema("tmpdel", SPEC)
    client.insert_arrow("tmpdel", _table(500, seed=3))
    assert client.count("tmpdel") == 500
    client.delete_schema("tmpdel")
    assert "tmpdel" not in client.list_schemas()


def test_13_density_curve_over_wire(client):
    """PROTOCOL §3 density_curve: sparse blocks + snapped bbox metadata."""
    client.create_schema("tiles", SPEC)
    t = _table(2_000, seed=9)
    client.insert_arrow("tiles", t)
    grid, snapped = client.density_curve(
        "tiles", "BBOX(geom, -100, 30, -80, 45)", level=7,
        bbox=(-100, 30, -80, 45),
    )
    geom = np.asarray(t["geom"].combine_chunks().flatten())
    x, y = geom[0::2], geom[1::2]
    want = int(((x >= -100) & (x <= -80) & (y >= 30) & (y <= 45)).sum())
    assert int(grid.sum()) == want
    assert snapped[0] <= -100 and snapped[2] >= -80
    client.delete_schema("tiles")
