"""ArrowDataStore analog (io/arrow_store.py) + Arrow-format FSDS tier.

Reference parity: geomesa-arrow's ArrowDataStore queries/appends Arrow IPC
files (geomesa-arrow-gt/.../arrow/data/ArrowDataStore.scala); the fs
datastore ships multiple file encodings (ParquetFileSystemStorage.scala)."""

import numpy as np
import pytest

from geomesa_tpu import GeoDataset
from geomesa_tpu.filter.ecql import parse_iso_ms
from geomesa_tpu.fs.storage import DateTimeScheme, FileSystemStorage
from geomesa_tpu.io.arrow_store import ArrowDataStore
from geomesa_tpu.schema.feature_type import FeatureType


def _data(n=2000, seed=5):
    rng = np.random.default_rng(seed)
    lo, hi = parse_iso_ms("2020-01-01"), parse_iso_ms("2020-01-10")
    return {
        "name": rng.choice(["a", "b", "c"], n),
        "val": rng.uniform(0, 100, n).astype(np.float32),
        "dtg": rng.integers(lo, hi, n).astype("datetime64[ms]"),
        "geom__x": rng.uniform(-120, -70, n),
        "geom__y": rng.uniform(25, 50, n),
    }


SPEC = "name:String,val:Float,dtg:Date,*geom:Point"
ECQL = "BBOX(geom, -100, 30, -80, 45) AND val < 50"


def _oracle(data):
    x, y = data["geom__x"], data["geom__y"]
    return (
        (x >= -100) & (x <= -80) & (y >= 30) & (y <= 45)
        & (data["val"] < 50)
    )


def _export_ipc(tmp_path, data):
    ds = GeoDataset()
    ds.create_schema("pts", SPEC)
    ds.insert("pts", data, fids=np.arange(len(data["val"])).astype(str))
    ds.flush("pts")
    path = str(tmp_path / "pts.arrow")
    ds.export_arrow("pts", path)
    return path


def test_query_exported_file(tmp_path):
    data = _data()
    path = _export_ipc(tmp_path, data)
    store = ArrowDataStore(path)
    # feature type recovered from the embedded spec metadata
    assert store.feature_type.spec().startswith("name:String")
    assert store.count() == 2000
    m = _oracle(data)
    assert store.count(ECQL) == int(m.sum())
    fc = store.query(ECQL)
    assert len(fc) == int(m.sum())
    # density through the full executor stack
    g = store.density(ECQL, bbox=(-100, 30, -80, 45), width=64, height=64)
    assert g.sum() == int(m.sum())


def test_append_and_reopen(tmp_path):
    data = _data(500)
    path = _export_ipc(tmp_path, data)
    with ArrowDataStore(path) as store:
        more = _data(250, seed=9)
        store.append(more, fids=[f"x{i}" for i in range(250)])
        assert store.count() == 750  # visible before flush
    # context manager flushed; a fresh store sees everything
    again = ArrowDataStore(path)
    assert again.count() == 750


def test_create_new_store(tmp_path):
    path = str(tmp_path / "fresh.arrow")
    with pytest.raises(FileNotFoundError):
        ArrowDataStore(path)
    ft = FeatureType.from_spec("fresh", SPEC)
    with ArrowDataStore(path, ft=ft, create=True) as store:
        store.append(_data(100), fids=np.arange(100).astype(str))
    assert ArrowDataStore(path).count() == 100


def test_create_empty_store_reopens(tmp_path):
    """A created-but-never-appended store still writes its (empty) file."""
    path = str(tmp_path / "empty.arrow")
    ft = FeatureType.from_spec("empty", SPEC)
    with ArrowDataStore(path, ft=ft, create=True):
        pass
    again = ArrowDataStore(path)
    assert again.count() == 0
    assert again.feature_type.name == "empty"


def test_fs_storage_arrow_format(tmp_path):
    fs = FileSystemStorage(str(tmp_path))
    ft = FeatureType.from_spec("t", SPEC)
    fs.create(ft, DateTimeScheme("day"), fmt="arrow")
    data = _data(1500)
    fs.write("t", data, fids=np.arange(1500).astype(str))
    # files carry the .arrow extension
    import glob
    files = glob.glob(str(tmp_path / "t" / "data" / "**" / "*.arrow"),
                      recursive=True)
    assert files, "no .arrow partition files written"
    assert not glob.glob(str(tmp_path / "t" / "data" / "**" / "*.parquet"),
                         recursive=True)
    # pruned read round-trips
    table = fs.read("t", "dtg DURING 2020-01-02T00:00:00Z/2020-01-04T00:00:00Z")
    t = data["dtg"].astype(np.int64)
    lo = parse_iso_ms("2020-01-02")
    hi = parse_iso_ms("2020-01-04")
    # partition pruning is day-granular: the pruned table is a superset
    day_lo = parse_iso_ms("2020-01-02")
    day_hi = parse_iso_ms("2020-01-05")
    assert table.num_rows == int(((t >= day_lo) & (t < day_hi)).sum())
    # compaction keeps the format
    fs.write("t", _data(100, seed=11), fids=[f"y{i}" for i in range(100)])
    fs.compact("t")
    files = glob.glob(str(tmp_path / "t" / "data" / "**" / "*.arrow"),
                      recursive=True)
    assert files and not glob.glob(
        str(tmp_path / "t" / "data" / "**" / "*.parquet"), recursive=True
    )
    assert fs.count("t") == 1600
    # bulk load into a device store
    ds = GeoDataset()
    n = fs.load_into(ds, "t")
    assert n == 1600 and ds.count("t") == 1600
