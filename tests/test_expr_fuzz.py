"""Randomized differential test for the expression filter grammar:
random predicate trees (arithmetic over numeric properties + literals,
all comparison ops, AND/OR/NOT nesting) must count exactly like a
numpy f64 oracle — including rows made uncertain by the f32 device
prefilter (the interval-arithmetic superset + exact host refine must
compose to exact f64 semantics for EVERY tree, not just the
hand-written cases)."""

pytestmark = __import__("pytest").mark.fuzz
import numpy as np
import pytest

from geomesa_tpu import GeoDataset

N = 4_000
PROPS = ["a", "b", "c"]


@pytest.fixture(scope="module")
def fuzz_ds():
    rng = np.random.default_rng(99)
    # mixed magnitudes + exact duplicates + values that collide at f32
    base = rng.uniform(-100, 100, N)
    data = {
        "a": base,
        "b": np.where(rng.random(N) < 0.3, base, rng.uniform(-100, 100, N)),
        "c": rng.choice(np.array([0.0, 1.0, 2.5, 1e7, -3.25]), N),
        "geom__x": rng.uniform(-10, 10, N),
        "geom__y": rng.uniform(-10, 10, N),
    }
    ds = GeoDataset(n_shards=2)
    ds.create_schema("f", "a:Double,b:Double,c:Double,*geom:Point")
    ds.insert("f", data, fids=np.arange(N).astype(str))
    ds.flush()
    return ds, data


def _rand_expr(rng, depth):
    """Returns (ecql_text, numpy_eval_fn)."""
    if depth == 0 or rng.random() < 0.35:
        if rng.random() < 0.55:
            p = PROPS[rng.integers(0, len(PROPS))]
            return p, lambda d, p=p: d[p]
        v = round(float(rng.uniform(-50, 50)), 3)
        return repr(v), lambda d, v=v: np.full(N, v)
    op = "+-*/"[rng.integers(0, 4)]
    lt, lf = _rand_expr(rng, depth - 1)
    rt, rf = _rand_expr(rng, depth - 1)
    fn = {
        "+": lambda d: lf(d) + rf(d),
        "-": lambda d: lf(d) - rf(d),
        "*": lambda d: lf(d) * rf(d),
        "/": lambda d: _div(lf(d), rf(d)),
    }[op]
    return f"({lt} {op} {rt})", fn


def _div(a, b):
    with np.errstate(divide="ignore", invalid="ignore"):
        return a / b


def _rand_pred(rng, depth):
    if depth == 0 or rng.random() < 0.5:
        cmp_op = ["=", "<>", "<", "<=", ">", ">="][rng.integers(0, 6)]
        lt, lf = _rand_expr(rng, 2)
        rt, rf = _rand_expr(rng, 2)

        def fn(d, lf=lf, rf=rf, cmp_op=cmp_op):
            left, right = lf(d), rf(d)
            valid = ~(np.isnan(left) | np.isnan(right))
            m = {
                "=": left == right, "<>": left != right,
                "<": left < right, "<=": left <= right,
                ">": left > right, ">=": left >= right,
            }[cmp_op]
            return m & valid

        return f"{lt} {cmp_op} {rt}", fn
    kind = rng.integers(0, 3)
    lt, lf = _rand_pred(rng, depth - 1)
    if kind == 2:
        return f"NOT ({lt})", lambda d, lf=lf: ~lf(d)
    rt, rf = _rand_pred(rng, depth - 1)
    if kind == 0:
        return f"({lt}) AND ({rt})", lambda d, lf=lf, rf=rf: lf(d) & rf(d)
    return f"({lt}) OR ({rt})", lambda d, lf=lf, rf=rf: lf(d) | rf(d)


def test_random_expression_trees_match_oracle(fuzz_ds):
    ds, data = fuzz_ds
    rng = np.random.default_rng(7)
    checked = 0
    for case in range(120):
        text, fn = _rand_pred(rng, 2)
        with np.errstate(over="ignore", invalid="ignore"):
            want = int(fn(data).sum())
        try:
            got = ds.count("f", text)
        except ValueError as e:
            # planner guards may veto degenerate full-scan trees; a loud
            # veto is acceptable, a wrong count is not
            if "full" in str(e).lower():
                continue
            raise AssertionError(f"{text!r} raised {e}")
        assert got == want, (
            f"case {case}: {text!r} -> {got}, oracle {want}"
        )
        checked += 1
    assert checked >= 100  # the fuzz actually ran


def test_random_trees_under_bbox_window(fuzz_ds):
    """Same trees composed with an indexed spatial predicate: the device
    prefilter runs inside real scan windows."""
    ds, data = fuzz_ds
    rng = np.random.default_rng(21)
    box = (data["geom__x"] >= -5) & (data["geom__x"] <= 5) \
        & (data["geom__y"] >= -5) & (data["geom__y"] <= 5)
    for case in range(60):
        text, fn = _rand_pred(rng, 1)
        q = f"BBOX(geom, -5, -5, 5, 5) AND ({text})"
        with np.errstate(over="ignore", invalid="ignore"):
            want = int((box & fn(data)).sum())
        got = ds.count("f", q)
        assert got == want, f"case {case}: {q!r} -> {got}, oracle {want}"


def test_exclude_inside_and_does_not_crash_planner(fuzz_ds):
    """Fuzz-found (r5): a provably-empty arm inside AND (literal EXCLUDE
    or folded constants) crashed extract_geometries via _union_bounds([])."""
    ds, _ = fuzz_ds
    assert ds.count("f", "BBOX(geom, -5, -5, 5, 5) AND 1 = 2") == 0
    assert ds.count("f", "BBOX(geom, -5, -5, 5, 5) AND EXCLUDE") == 0
    assert ds.count("f", "a > 0 AND EXCLUDE") == 0
