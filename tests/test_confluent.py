"""Confluent-style schema-registry Avro streaming ingest
(geomesa-kafka-confluent parity: registry-framed wire format + Avro
schema resolution across producer/consumer schema versions)."""

import struct

import numpy as np
import pytest

from geomesa_tpu.schema.feature_type import FeatureType
from geomesa_tpu.stream.confluent import (
    ConfluentDeserializer, ConfluentSerializer, SchemaRegistry,
    attach_confluent,
)
from geomesa_tpu.stream.live import StreamingDataset

SPEC = "name:String,speed:Double,dtg:Date,*geom:Point"


def test_registry_ids_and_versions():
    reg = SchemaRegistry()
    ft1 = FeatureType.from_spec("s", SPEC)
    ft2 = FeatureType.from_spec("s", SPEC + ",extra:Integer")
    s1 = ConfluentSerializer(reg, "s-value", ft1)
    s2 = ConfluentSerializer(reg, "s-value", ft2)
    assert s1.schema_id != s2.schema_id
    assert reg.versions("s-value") == [s1.schema_id, s2.schema_id]
    assert reg.latest("s-value")[0] == s2.schema_id
    # identical schema re-registers to the same id
    assert ConfluentSerializer(reg, "other", ft1).schema_id == s1.schema_id
    with pytest.raises(KeyError):
        reg.by_id(999)


def test_wire_format_and_round_trip():
    reg = SchemaRegistry()
    ft = FeatureType.from_spec("s", SPEC)
    ser = ConfluentSerializer(reg, "s-value", ft)
    data = ser.serialize("f1", {
        "name": "alice", "speed": 12.5, "dtg": 1578182400000,
        "geom": "POINT (10 20)",
    })
    # Confluent framing: magic 0 + 4-byte big-endian id
    assert data[0] == 0
    assert struct.unpack(">I", data[1:5])[0] == ser.schema_id
    de = ConfluentDeserializer(reg, ft)
    fid, attrs = de.deserialize(data)
    assert fid == "f1" and attrs["name"] == "alice"
    assert attrs["speed"] == 12.5 and attrs["dtg"] == 1578182400000
    assert attrs["geom"] == "POINT (10 20)"
    with pytest.raises(ValueError, match="magic"):
        de.deserialize(b"\x01junk")


def test_schema_evolution_both_directions():
    """Old-writer -> new-reader fills defaults; new-writer -> old-reader
    drops the unknown field (Avro resolution rules)."""
    reg = SchemaRegistry()
    ft_v1 = FeatureType.from_spec("s", SPEC)
    ft_v2 = FeatureType.from_spec("s", SPEC + ",rank:Integer")
    ser_v1 = ConfluentSerializer(reg, "s-value", ft_v1)
    ser_v2 = ConfluentSerializer(reg, "s-value", ft_v2)
    old_msg = ser_v1.serialize("a", {"name": "x", "speed": 1.0,
                                     "dtg": 0, "geom": "POINT (0 0)"})
    new_msg = ser_v2.serialize("b", {"name": "y", "speed": 2.0, "dtg": 0,
                                     "geom": "POINT (1 1)", "rank": 7})
    # new reader consumes BOTH versions
    de_new = ConfluentDeserializer(reg, ft_v2)
    _, attrs = de_new.deserialize(old_msg)
    assert attrs["rank"] is None  # reader-only field -> default
    _, attrs = de_new.deserialize(new_msg)
    assert attrs["rank"] == 7
    # old reader consumes the new version, dropping 'rank'
    de_old = ConfluentDeserializer(reg, ft_v1)
    _, attrs = de_old.deserialize(new_msg)
    assert "rank" not in attrs and attrs["name"] == "y"


def test_streaming_ingest_and_tombstone():
    """Framed records drive the live store end-to-end: upserts become
    queryable features; a None-payload tombstone deletes by key."""
    sds = StreamingDataset()
    sds.create_schema("t", SPEC)
    reg = SchemaRegistry()
    ser, ingest = attach_confluent(sds, "t", reg)
    for i in range(20):
        ingest(ser.serialize(f"f{i}", {
            "name": "even" if i % 2 == 0 else "odd",
            "speed": float(i),
            "dtg": 1578182400000 + i,
            "geom": f"POINT ({i} 1)",
        }))
    sds.poll("t")
    assert len(sds.cache("t")) == 20
    got = sds.query("t", "speed > 15.5")
    assert got.n == 4
    # evolution mid-stream: a v2 producer appears
    ft_v2 = FeatureType.from_spec("t", SPEC + ",rank:Integer")
    ser2 = ConfluentSerializer(reg, "t-value", ft_v2)
    ingest(ser2.serialize("f99", {
        "name": "new", "speed": 50.0, "dtg": 1578182500000,
        "geom": "POINT (5 5)", "rank": 1,
    }))
    sds.poll("t")
    assert len(sds.cache("t")) == 21
    # tombstone delete
    ingest(None, fid="f0")
    sds.poll("t")
    assert len(sds.cache("t")) == 20
    assert sds.query("t", "name = 'even'").n == 9
