"""Cross-layer chaos suite (docs/RESILIENCE.md §6): device-level fault
tolerance on the 8-virtual-device mesh.

Drives the seeded fault-injection registry across the device-dispatch,
spill, stream, and serving edges and gates the core invariants:

* a failed device's partitions REASSIGN to survivors and the recovered
  result is BIT-IDENTICAL to the healthy run (the tree merge orders by
  pruned bin, never by device) — at mesh widths 2/4/8;
* exhausted retries degrade typed with EXACT survivor totals, never a
  hang;
* per-device breakers open after the configured consecutive failures and
  recover through the half-open trial; cordon/drain removes a device
  from scheduling without a restart (API, config knob, CLI);
* a killed pool dispatcher slot respawns within one scheduling round
  with the fair-share ledgers intact; a drained slot fails its pinned
  continuations typed (``[GM-DRAINING]``) and flags their traces for
  tail-sampling keep;
* the whole scenario replays identically under its seed (two runs, same
  outcomes).
"""

import threading
import time

import numpy as np
import pytest

from geomesa_tpu import GeoDataset, config, metrics, resilience, tracing
from geomesa_tpu.filter.ecql import parse_iso_ms
from geomesa_tpu.index.partitioned import PartitionedFeatureStore
from geomesa_tpu.parallel import devices as pdev
from geomesa_tpu.parallel import health as phealth
from geomesa_tpu.resilience import (
    DeviceDrainError, InjectedFault, allow_partial, inject_faults,
)

SPEC = "name:String:index=true,weight:Double,dtg:Date,*geom:Point"
PSPEC = SPEC + ";geomesa.partition='time'"
N = 9_000
ECQL = "BBOX(geom, -110, 28, -75, 48)"
BBOX = (-120.0, 25.0, -70.0, 50.0)


def _data(n=N, seed=23):
    rng = np.random.default_rng(seed)
    return {
        "name": [f"actor{i % 16}" for i in range(n)],
        "weight": rng.uniform(0, 10, n),
        "dtg": rng.integers(
            parse_iso_ms("2021-01-01"), parse_iso_ms("2021-03-01"), n
        ).astype("datetime64[ms]"),
        "geom__x": rng.uniform(-120, -70, n),
        "geom__y": rng.uniform(25, 50, n),
    }


@pytest.fixture(scope="module")
def pds(tmp_path_factory):
    ds = GeoDataset(n_shards=4)
    ds.create_schema("t", PSPEC)
    st = ds._store("t")
    assert isinstance(st, PartitionedFeatureStore)
    st.max_resident = 1
    st._spill_dir = str(tmp_path_factory.mktemp("chaos_spill"))
    ds.insert("t", _data(), fids=np.arange(N).astype(str))
    ds.flush()
    return ds


@pytest.fixture(autouse=True)
def _clean_health():
    """Every chaos test starts and ends with a pristine device-health
    registry and breaker set (faults must not leak between tests)."""
    phealth.reset()
    resilience.reset_breakers()
    yield
    phealth.reset()
    resilience.reset_breakers()


def _ctr(name: str) -> float:
    return metrics.registry().counter(name).value


def _fast_retries():
    return config.RETRY_BASE_MS.scoped("0")


# ---------------------------------------------------------------------------
# device health: states, breakers, cordon
# ---------------------------------------------------------------------------


def test_health_states_cordon_and_gauge():
    reg = phealth.registry()
    assert reg.state(0) == "ok" and reg.usable(0)
    reg.cordon(0, reason="maintenance")
    assert reg.state(0) == "cordoned" and not reg.usable(0)
    snap = reg.snapshot()["0"]
    assert snap["state"] == "cordoned"
    assert snap["cordon_reason"] == "maintenance"
    g = metrics.registry().gauge(f"{metrics.DEVICE_HEALTH_PREFIX}.0")
    assert g.value == 0.0
    assert reg.uncordon(0) is True
    assert reg.state(0) == "ok" and g.value == 1.0


def test_mesh_cordon_config_knob_excludes_devices():
    reg = phealth.registry()
    with config.MESH_CORDON.scoped("2, 5"):
        assert reg.state(2) == "cordoned" and reg.state(5) == "cordoned"
        assert reg.cordon_reason(2) == "geomesa.mesh.cordon"
        devs = pdev.scan_devices()
        assert devs is not None
        assert {d.id for d in devs} == {0, 1, 3, 4, 6, 7}
    assert reg.state(2) == "ok"


def test_breaker_opens_after_consecutive_failures_and_recovers():
    with config.DEVICE_BREAKER_THRESHOLD.scoped("2"), \
            config.DEVICE_BREAKER_RESET_MS.scoped("30"):
        reg = phealth.registry()
        err = RuntimeError("lane down")
        reg.record_failure(3, err)
        assert reg.state(3) == "ok"  # one failure < threshold
        reg.record_failure(3, err)
        assert reg.state(3) == "broken" and not reg.usable(3)
        assert reg.snapshot()["3"]["last_failure"].startswith("RuntimeError")
        # the broken device drops out of the fan-out
        devs = pdev.scan_devices()
        assert devs is not None and 3 not in {d.id for d in devs}
        # after the reset window the half-open trial is schedulable again
        time.sleep(0.05)
        assert reg.usable(3)  # trial admitted
        reg.record_success(3)
        assert reg.state(3) == "ok"


def test_latency_outlier_streak_trips_the_breaker():
    with config.DEVICE_BREAKER_THRESHOLD.scoped("2"), \
            config.DEVICE_LATENCY_OUTLIER.scoped("10"), \
            config.DEVICE_LATENCY_FLOOR_MS.scoped("50"):
        reg = phealth.registry()
        for _ in range(16):  # healthy mesh baseline ~1 ms
            reg.record_latency(0, 0.001)
            reg.record_latency(1, 0.001)
        reg.record_latency(6, 0.2)  # 200x the median, over the floor
        assert reg.state(6) == "ok"  # streak of 1 < threshold 2
        reg.record_latency(6, 0.2)
        assert reg.state(6) == "broken"
        assert "latency outlier" in reg.snapshot()["6"]["last_failure"]


# ---------------------------------------------------------------------------
# mid-scan reassignment: bit-identity + exact survivor totals
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("width", [2, 4, 8])
def test_single_device_failure_recovers_bit_identical(pds, width):
    """1 of W devices fails every dispatch mid-scan: its partitions
    requeue onto the survivors and the result is bit-identical to the
    healthy run — count and density, at mesh widths 2/4/8."""
    with config.MESH_DEVICES.scoped(str(width)):
        c0 = pds.count("t", ECQL)
        d0 = pds.density("t", ECQL, bbox=BBOX, width=64, height=64)
        bad = width - 1  # the last device of the scan rotation
        before = _ctr(metrics.SCAN_REASSIGNED)
        with config.FAULT_INJECTION.scoped("true"), _fast_retries(), \
                inject_faults(seed=7) as inj:
            inj.fail("scan.device.dispatch", InjectedFault("lane down"),
                     times=None, where=lambda c: c.get("device") == bad)
            c1 = pds.count("t", ECQL)
            d1 = pds.density("t", ECQL, bbox=BBOX, width=64, height=64)
            assert inj.fired  # the failing lane was actually exercised
        assert c1 == c0
        assert np.array_equal(d1, d0)
        assert _ctr(metrics.SCAN_REASSIGNED) > before
        assert phealth.registry().snapshot()[str(bad)]["reassigned"] > 0


def test_exhausted_retries_degrade_with_exact_survivor_totals(pds):
    """A partition that fails on EVERY device exhausts its retries and
    degrades typed: the count is exact over the surviving partitions
    (total - the dead partition's rows), never an estimate, never a
    hang."""
    st = pds._store("t")
    bins = sorted(st.part_counts)
    dead = bins[len(bins) // 2]
    total = pds.count("t", "INCLUDE")
    with config.FAULT_INJECTION.scoped("true"), _fast_retries(), \
            inject_faults(seed=11) as inj:
        inj.fail("scan.device.dispatch", InjectedFault("bad partition"),
                 times=None, where=lambda c: c.get("bin") == dead)
        with allow_partial() as partial:
            got = pds.count("t", "INCLUDE")
    assert partial.degraded
    assert {s.part for s in partial.skipped} == {f"bin:{dead}"}
    assert got == total - st.part_counts[dead]  # exact survivor totals
    # strict mode: the same failure is a typed error, not a wedge
    with config.FAULT_INJECTION.scoped("true"), _fast_retries(), \
            inject_faults(seed=11) as inj:
        inj.fail("scan.device.dispatch", InjectedFault("bad partition"),
                 times=None, where=lambda c: c.get("bin") == dead)
        with pytest.raises(InjectedFault):
            pds.count("t", "INCLUDE")


def test_cordoned_device_receives_no_partitions(pds):
    reg = phealth.registry()
    reg.cordon(2, reason="drain test")
    before = _ctr(f"{metrics.SCAN_SHARDED_DEVICE}.2")
    c_ref = None
    with config.MESH_DEVICES.scoped("off"):
        c_ref = pds.count("t", ECQL)
    assert pds.count("t", ECQL) == c_ref  # bit-identical around the hole
    assert _ctr(f"{metrics.SCAN_SHARDED_DEVICE}.2") == before
    reg.uncordon(2)


def test_mid_scan_cordon_is_honored_between_partitions(pds):
    """A device cordoned WHILE a scan runs stops receiving partitions at
    its next turn (the rotation checks health per dispatch)."""
    reg = phealth.registry()
    seen = []
    orig = phealth.DeviceHealthRegistry.usable

    def spy(self, did):
        out = orig(self, did)
        seen.append((did, out))
        if len(seen) == 3:  # cordon early, mid-scan
            reg.cordon(1, reason="mid-scan")
        return out

    try:
        phealth.DeviceHealthRegistry.usable = spy
        with config.MESH_DEVICES.scoped("off"):
            ref = pds.count("t", "INCLUDE")
        assert pds.count("t", "INCLUDE") == ref
    finally:
        phealth.DeviceHealthRegistry.usable = orig
        reg.uncordon(1)


# ---------------------------------------------------------------------------
# spill edges: transient retry, corrupt quarantine, store never loses data
# ---------------------------------------------------------------------------


def test_spill_load_transient_oserror_retries_in_place(pds):
    ref = pds.count("t", ECQL)
    with config.FAULT_INJECTION.scoped("true"), _fast_retries(), \
            inject_faults(seed=3) as inj:
        inj.fail("index.spill.load", OSError("nfs blip"), times=2)
        assert pds.count("t", ECQL) == ref  # retried, not degraded
        assert len(inj.fired) == 2
    assert pds._store("t").spill_quarantine() == {}


def test_spill_load_corruption_quarantines_and_clears(pds):
    st = pds._store("t")
    total = pds.count("t", "INCLUDE")
    with config.FAULT_INJECTION.scoped("true"), _fast_retries(), \
            inject_faults(seed=4) as inj:
        rule = inj.fail("index.spill.load", ValueError("bad npz"),
                        times=1)
        with allow_partial() as partial:
            got = pds.count("t", "INCLUDE")
        assert rule.hits == 1
    assert partial.degraded and len(partial.skipped) == 1
    (skip,) = partial.skipped
    assert skip.source == "index.spill.load"
    dead = int(skip.part.split(":")[1])
    assert got == total - st.part_counts[dead]
    # quarantined: the next load fails fast without re-parsing …
    q = st.spill_quarantine()
    assert list(q) == [dead]
    with allow_partial():
        assert pds.count("t", "INCLUDE") == got
    # … until the operator re-admits it
    assert st.clear_spill_quarantine() == [dead]
    assert pds.count("t", "INCLUDE") == total


def test_spill_store_failure_never_loses_the_partition(pds):
    st = pds._store("t")
    ref = pds.count("t", "INCLUDE")
    with config.FAULT_INJECTION.scoped("true"), \
            config.RETRY_ATTEMPTS.scoped("1"), \
            inject_faults(seed=5) as inj:
        inj.fail("index.spill.store", OSError("disk full"), times=None)
        # force fresh rows into a partition, then evict under the fault
        extra = _data(64, seed=99)
        pds.insert("t", extra, fids=[f"x{i}" for i in range(64)])
        try:
            pds.flush("t")
        except OSError:
            pass  # the spill backed off …
    # … but the partition stayed resident: nothing was lost
    assert pds.count("t", "INCLUDE") == ref + 64


# ---------------------------------------------------------------------------
# stream edge: poison records quarantine, never kill the consumer
# ---------------------------------------------------------------------------


def test_confluent_poison_record_quarantines():
    from geomesa_tpu.stream.confluent import SchemaRegistry, attach_confluent
    from geomesa_tpu.stream.live import StreamingDataset

    sds = StreamingDataset()
    sds.create_schema("c", SPEC)
    reg = SchemaRegistry()
    ser, ingest = attach_confluent(sds, "c", reg)
    before = _ctr("stream.confluent.quarantined")
    assert ingest(b"\x01not-a-frame") == ""        # bad magic
    assert ingest(None) == ""                      # keyless tombstone
    assert _ctr("stream.confluent.quarantined") == before + 2
    # the consumer loop survives: a good record still applies
    ingest(ser.serialize("f1", {
        "name": "ok", "weight": 1.0, "dtg": 1578182400000,
        "geom": "POINT (1 2)",
    }))
    sds.poll("c")
    assert len(sds.cache("c")) == 1


def test_confluent_injected_fault_quarantines():
    from geomesa_tpu.stream.confluent import SchemaRegistry, attach_confluent
    from geomesa_tpu.stream.live import StreamingDataset

    sds = StreamingDataset()
    sds.create_schema("c", SPEC)
    reg = SchemaRegistry()
    ser, ingest = attach_confluent(sds, "c", reg)
    good = ser.serialize("f1", {
        "name": "ok", "weight": 1.0, "dtg": 1578182400000,
        "geom": "POINT (1 2)",
    })
    with config.FAULT_INJECTION.scoped("true"), inject_faults(seed=6) as inj:
        inj.fail("stream.confluent.ingest", ValueError("decoder blew up"),
                 times=1)
        assert ingest(good) == ""   # quarantined, not raised
        assert ingest(good) == "f1"  # next record applies normally


# ---------------------------------------------------------------------------
# serving pool: slot death -> respawn; drain -> typed strand
# ---------------------------------------------------------------------------


@pytest.mark.filterwarnings(
    "ignore::pytest.PytestUnhandledThreadExceptionWarning")
def test_slot_death_respawns_within_one_round_ledgers_survive():
    ds = GeoDataset()
    ds.create_schema("s", SPEC)
    ds.insert("s", _data(128, seed=1), fids=np.arange(128).astype(str))
    ds.flush()
    died0 = _ctr(metrics.SERVING_SLOT_DIED)
    resp0 = _ctr(metrics.SERVING_SLOT_RESPAWN)
    with config.SERVING_EXECUTORS.scoped("2"), \
            config.FAULT_INJECTION.scoped("true"), \
            inject_faults(seed=8) as inj:
        inj.fail("serving.slot.loop", SystemExit("chaos kill"), times=1,
                 where=lambda c: c.get("slot") == 1)
        s = ds.serving.start()
        try:
            # slot 1 dies on its first loop iteration (the armed kill) —
            # wait on the death METRIC, not the width: a sibling slot's
            # wake-up may have respawned it already, which only proves
            # the supervisor is faster than this poll
            for _ in range(500):
                if _ctr(metrics.SERVING_SLOT_DIED) >= died0 + 1:
                    break
                time.sleep(0.01)
            assert _ctr(metrics.SERVING_SLOT_DIED) == died0 + 1
            # ledger state from before the death …
            s.submit(lambda: ds.count("s", "INCLUDE"),
                     user="alice", op="count").result(timeout=30)
            pre = s.user_rollups()["alice"]
            # … survives the respawn, which happens within the round the
            # next submission triggers
            s.submit(lambda: ds.count("s", "INCLUDE"),
                     user="alice", op="count").result(timeout=30)
            snap = s.snapshot()
            assert snap["executors"] == 2
            assert snap["respawns"] >= 1
            assert _ctr(metrics.SERVING_SLOT_RESPAWN) >= resp0 + 1
            post = s.user_rollups()["alice"]
            assert post["completed"] == pre["completed"] + 1
            assert post["service_ms"] >= pre["service_ms"]
            # queued/inflight work keeps flowing on the healed pool
            futs = [s.submit(lambda: ds.count("s", "INCLUDE"),
                             user=f"u{i}", op="count") for i in range(4)]
            for f in futs:
                f.result(timeout=30)
            # the respawn is visible in /debug/devices (pool digest)
            from geomesa_tpu import obs

            dd = obs.debug_devices(ds)
            assert dd["pool"]["respawns"] >= 1
            assert dd["pool"]["executors"] == 2
        finally:
            s.stop()


@pytest.mark.filterwarnings(
    "ignore::pytest.PytestUnhandledThreadExceptionWarning")
def test_slot_death_strands_pinned_continuation_typed():
    """A queued continuation pinned to a dying slot fails with the typed
    [GM-DRAINING] contract and its trace joins the always-keep classes
    with a serving.slot.died root-span event."""
    ds = GeoDataset()
    root = tracing.start("stream", trace_id="chaostrace000001",
                         force=True)
    trace = root.trace
    width = 2
    started = threading.Barrier(width + 1, timeout=15)
    release = threading.Event()

    def blocker():
        started.wait(15)
        release.wait(15)

    with config.SERVING_EXECUTORS.scoped(str(width)), \
            config.FAULT_INJECTION.scoped("true"), \
            inject_faults(seed=9) as inj:
        s = ds.serving.start()
        try:
            # occupy BOTH slots so the pinned continuation stays queued
            blockers = [s.submit(blocker, user="b", op="block")
                        for _ in range(width)]
            started.wait(15)  # both slots are EXECUTING their blocker
            cont = s.submit(lambda: "never runs", user="stream",
                            op="chunk", continuation=True, slot=1,
                            trace_id="chaostrace000001")
            # kill slot 1 at its NEXT loop iteration (after its blocker)
            inj.fail("serving.slot.loop", SystemExit("chaos kill"),
                     times=1, where=lambda c: c.get("slot") == 1)
            release.set()
            for f in blockers:
                f.result(timeout=30)
            with pytest.raises(DeviceDrainError, match="re-open"):
                cont.result(timeout=30)
            assert trace.slot_died is True
            from geomesa_tpu import tracing_export

            assert tracing_export.classify(trace) == "slot_died"
            names = [c.name for c in trace.root.children]
            assert "serving.slot.died" in names
        finally:
            s.stop()
    root.finish()


@pytest.mark.filterwarnings(
    "ignore::pytest.PytestUnhandledThreadExceptionWarning")
def test_respawned_slot_rejects_stale_generation_continuations():
    """A stream that opened under slot generation G must NOT silently
    resume on the respawned (G+1) dispatcher — the dead dispatcher's
    in-flight device work cannot be vouched for. A stale-generation
    continuation fails typed [GM-DRAINING] even though the slot LOOKS
    alive again."""
    ds = GeoDataset()
    with config.SERVING_EXECUTORS.scoped("2"), \
            config.FAULT_INJECTION.scoped("true"), \
            inject_faults(seed=10) as inj:
        s = ds.serving.start()
        try:
            with s._cv:
                gen0 = s._slot_gen[1]
            inj.fail("serving.slot.loop", SystemExit("chaos kill"),
                     times=1, where=lambda c: c.get("slot") == 1)
            s.submit(lambda: None, user="w", op="wake").result(timeout=30)
            # wait for the respawn (a new generation for slot 1)
            for _ in range(500):
                with s._cv:
                    alive = 1 in s._threads and s._slot_gen[1] > gen0
                if alive:
                    break
                time.sleep(0.01)
            with s._cv:
                assert s._slot_gen[1] > gen0
            # the slot is back — but THIS stream's chunks must re-open
            with pytest.raises(DeviceDrainError, match="re-open"):
                s.submit(lambda: "chunk", user="stream", op="chunk",
                         continuation=True, slot=1, slot_gen=gen0)
            # a freshly-opened stream (current generation) is served
            with s._cv:
                gen1 = s._slot_gen[1]
            assert s.submit(lambda: "chunk", user="stream", op="chunk",
                            continuation=True, slot=1,
                            slot_gen=gen1).result(timeout=30) == "chunk"
        finally:
            s.stop()


def test_cordon_drains_excess_slots_and_rejects_their_streams():
    """Cordoning devices below the pool width re-clamps it: the excess
    slot drains (typed), new pinned continuations for it are rejected
    [GM-DRAINING], and slot 0 keeps serving."""
    ds = GeoDataset()
    reg = phealth.registry()
    with config.SERVING_EXECUTORS.scoped("2"):
        s = ds.serving.start()
        try:
            assert s.snapshot()["executors"] == 2
            for did in range(1, 8):
                reg.cordon(did, reason="shrink")
            out = s.supervise()
            # a dispatcher wake-up's own supervision round (they run on
            # every wake-up) may have drained slot 1 before this explicit
            # call — or the drained slot may already have exited entirely
            # (it removes itself from _threads and _draining). Either
            # way: draining now, or already gone.
            with s._cv:
                draining = set(out["draining"]) | set(s._draining)
                gone = 1 not in s._threads
            assert 1 in draining or gone
            for _ in range(200):
                if s.snapshot()["executors"] == 1:
                    break
                time.sleep(0.01)
            assert s.snapshot()["executors"] == 1
            with pytest.raises(DeviceDrainError):
                s.submit(lambda: None, user="x", op="chunk",
                         continuation=True, slot=1)
            # the surviving slot still serves queries
            assert s.submit(lambda: 42, user="x",
                            op="q").result(timeout=30) == 42
            assert pdev.pool_width() == 1
        finally:
            s.stop()
            for did in range(1, 8):
                reg.uncordon(did)


def test_sidecar_wire_code_for_drained_slot():
    """DeviceDrainError crosses the Flight wire as [GM-DRAINING]
    (PROTOCOL §7.1, retryable)."""
    fl = pytest.importorskip("pyarrow.flight")
    from geomesa_tpu.sidecar.service import _spec_errors

    @_spec_errors
    def boom():
        raise DeviceDrainError("slot 1 drained; re-open the stream")

    with pytest.raises(fl.FlightUnavailableError, match=r"\[GM-DRAINING\]"):
        boom()


# ---------------------------------------------------------------------------
# the concurrent seeded scenario: deterministic, never hangs, breakers real
# ---------------------------------------------------------------------------


def _chaos_round(pds, seed: int):
    """One seeded chaos pass over the query + spill edges; returns the
    outcome list (results + degradation counts) for determinism
    comparison. Prefetch is disabled so every fault point fires on the
    query thread in program order — the property that makes the seeded
    run replayable."""
    outcomes = []
    with config.FAULT_INJECTION.scoped("true"), _fast_retries(), \
            config.PIPELINE_PREFETCH.scoped("false"), \
            inject_faults(seed=seed) as inj:
        inj.fail("scan.device.dispatch", InjectedFault("flaky lane"),
                 p=0.3, times=None)
        inj.fail("index.spill.load", OSError("nfs blip"), p=0.15,
                 times=None)
        for ecql in (ECQL, "INCLUDE", "BBOX(geom, -100, 30, -80, 45)"):
            with allow_partial() as partial:
                c = pds.count("t", ecql)
                d = pds.density("t", ecql, bbox=BBOX, width=32, height=32)
            outcomes.append(
                (c, float(d.sum()), len(partial.skipped),
                 sorted({s.part for s in partial.skipped}))
            )
        fired = list(inj.fired)
    return outcomes, fired


def test_chaos_scenario_is_seeded_deterministic_and_never_hangs(pds):
    t0 = time.monotonic()
    out1, fired1 = _chaos_round(pds, seed=42)
    phealth.reset()
    resilience.reset_breakers()
    pds._store("t").clear_spill_quarantine()
    out2, fired2 = _chaos_round(pds, seed=42)
    elapsed = time.monotonic() - t0
    assert out1 == out2            # identical outcomes under the seed
    assert fired1 == fired2        # identical fault schedule
    assert elapsed < 120           # and nothing wedged
    # a healthy follow-up run is untouched by the chaos residue
    phealth.reset()
    resilience.reset_breakers()
    pds._store("t").clear_spill_quarantine()
    with config.MESH_DEVICES.scoped("off"):
        ref = pds.count("t", ECQL)
    assert pds.count("t", ECQL) == ref


def test_chaos_breakers_open_and_healthz_reflects_reality(pds):
    """Persistent failure of one device opens its breaker mid-scan;
    /healthz degrades SOFTLY (200, capacity remains) and /debug/devices
    names the broken lane; recovery closes it again."""
    from geomesa_tpu import obs

    with config.DEVICE_BREAKER_THRESHOLD.scoped("2"), \
            config.DEVICE_BREAKER_RESET_MS.scoped("50"), \
            config.FAULT_INJECTION.scoped("true"), _fast_retries(), \
            inject_faults(seed=13) as inj:
        inj.fail("scan.device.dispatch", InjectedFault("dead lane"),
                 times=None, where=lambda c: c.get("device") == 4)
        with config.MESH_DEVICES.scoped("off"):
            ref = pds.count("t", "INCLUDE")
        assert pds.count("t", "INCLUDE") == ref   # reassigned, recovered
        # the second scan's first dispatch to device 4 is failure #2:
        # the breaker opens mid-scan and the lane drops out — still
        # bit-identical around the hole
        assert pds.count("t", "INCLUDE") == ref
        reg = phealth.registry()
        assert reg.state(4) == "broken"
        h = obs.health()
        assert h["status"] == "degraded" and h["soft"] is True
        assert 4 in h["mesh"]["broken"]
        assert h["mesh"]["usable"] == h["mesh"]["total"] - 1
        code, _, _ = obs.handle("/healthz")
        assert code == 200  # degraded-not-503: capacity remains
        dd = obs.debug_devices()
        assert dd["health"]["4"]["state"] == "broken"
    # recovery: reset window elapses, the next scan's trial succeeds
    time.sleep(0.08)
    assert pds.count("t", "INCLUDE") == ref
    assert phealth.registry().state(4) == "ok"
    assert obs.health()["status"] == "ok"


def test_healthz_hard_503_when_no_capacity_remains():
    from geomesa_tpu import obs

    reg = phealth.registry()
    obs.device_health()  # prime the device probe cache
    total = len(obs.device_health().get("devices") or ())
    assert total == 8
    for did in range(total):
        reg.cordon(did, reason="full drain")
    try:
        h = obs.health()
        assert h["status"] == "degraded" and h["soft"] is False
        code, _, _ = obs.handle("/healthz")
        assert code == 503
    finally:
        for did in range(total):
            reg.uncordon(did)


def test_cli_devices_cordon_uncordon(capsys):
    from geomesa_tpu import cli

    cli.main(["devices", "cordon", "6", "--reason", "maint"])
    out = capsys.readouterr().out
    assert '"cordoned"' in out and "maint" in out
    assert phealth.registry().state(6) == "cordoned"
    cli.main(["devices", "uncordon", "6"])
    capsys.readouterr()
    assert phealth.registry().state(6) == "ok"
    cli.main(["devices"])
    out = capsys.readouterr().out
    assert '"health"' in out
