"""Device coarse scan + host refine (r4): extent-geometry and big-int64
predicates keep their dense scan on the device; the host only refines
coarse-true candidates (AggregatingScan validate-then-aggregate split).
"""

import numpy as np
import pytest

from geomesa_tpu import GeoDataset
from geomesa_tpu.filter.ecql import parse_iso_ms

PSPEC = "track:Long,dtg:Date,*geom:Polygon"
N = 4_000


def _poly(cx, cy, r):
    return (
        f"POLYGON (({cx-r} {cy-r}, {cx+r} {cy-r}, {cx+r} {cy+r}, "
        f"{cx-r} {cy+r}, {cx-r} {cy-r}))"
    )


@pytest.fixture(scope="module")
def ds():
    rng = np.random.default_rng(17)
    ds = GeoDataset(n_shards=4)
    ds.create_schema("polys", PSPEC)
    cx = rng.uniform(-50, 50, N)
    cy = rng.uniform(-20, 20, N)
    r = rng.uniform(0.1, 2.0, N)
    # track ids straddle 2^40 so f32 cannot represent them exactly
    base = 1 << 40
    ds.insert("polys", {
        "track": base + rng.integers(0, 50, N),
        "dtg": rng.integers(
            parse_iso_ms("2021-03-01"), parse_iso_ms("2021-04-01"), N
        ).astype("datetime64[ms]"),
        "geom": [_poly(x, y, rr) for x, y, rr in zip(cx, cy, r)],
    }, fids=np.arange(N).astype(str))
    ds.flush()
    return ds, cx, cy, r


QUERY = "INTERSECTS(geom, POLYGON ((0 0, 30 0, 30 15, 0 15, 0 0)))"


def _oracle(cx, cy, r):
    # squares intersect the query box iff their bboxes overlap it
    return (cx + r >= 0) & (cx - r <= 30) & (cy + r >= 0) & (cy - r <= 15)


def test_polygon_query_uses_device_coarse(ds):
    d, cx, cy, r = ds
    st, _, plan = d._plan("polys", QUERY)
    ex = d._executor(st)
    setup = ex._scan_setup(plan)
    assert setup["coarse_device"] is True
    assert setup["use_device"] is False
    assert ex.count(plan) == int(_oracle(cx, cy, r).sum())
    # the coarse kernel actually ran on device and is reported
    assert plan.__dict__.get("device_coarse_ms", 0) > 0
    assert d.count("polys", QUERY) == int(_oracle(cx, cy, r).sum())
    ev = d.audit.recent(1)[-1]
    assert ev.hints.get("device_coarse_ms", 0) > 0


def test_polygon_density_matches_exact(ds):
    d, cx, cy, r = ds
    grid = d.density("polys", QUERY, bbox=(-60, -25, 60, 25),
                     width=32, height=32)
    assert int(grid.sum()) == int(_oracle(cx, cy, r).sum())


def test_explain_analyze_reports_device_coarse(ds):
    d, _, _, _ = ds
    out = d.explain("polys", QUERY, analyze=True)
    assert "Device coarse kernel" in out


def test_host_and_coarse_paths_agree(ds):
    d, cx, cy, r = ds
    host = GeoDataset(n_shards=4, prefer_device=False)
    host.create_schema("polys", PSPEC)
    # reuse the exact same rows via arrow round-trip
    host.ingest_arrow("polys", d.to_arrow("polys"))
    for q in (QUERY, f"{QUERY} AND track > {(1 << 40) + 25}"):
        assert host.count("polys", q) == d.count("polys", q), q


class TestInt64Exactness:
    """Predicates on int64 values beyond 2^24 must be exact on the device
    path (coarse f32 + host refine) — r1-r3 silently compared at f32."""

    @pytest.fixture(scope="class")
    def ids(self):
        rng = np.random.default_rng(3)
        n = 2_000
        ds = GeoDataset(n_shards=4)
        ds.create_schema("evs", "track:Long,dtg:Date,*geom:Point")
        base = 1 << 40
        tracks = base + np.arange(n, dtype=np.int64)  # all distinct, f32-colliding
        ds.insert("evs", {
            "track": tracks,
            "dtg": np.full(n, parse_iso_ms("2022-01-01")).astype("datetime64[ms]"),
            "geom__x": rng.uniform(-10, 10, n),
            "geom__y": rng.uniform(-10, 10, n),
        }, fids=np.arange(n).astype(str))
        ds.flush()
        return ds, tracks

    def test_equality_no_false_positives(self, ids):
        ds, tracks = ids
        # adjacent int64 values collide at f32: exact equality must return 1
        target = int(tracks[1001])
        assert ds.count("evs", f"track = {target}") == 1
        fc = ds.query("evs", f"track = {target}")
        assert len(fc) == 1 and int(fc.columns["track"][0]) == target

    def test_range_boundaries_exact(self, ids):
        ds, tracks = ids
        cut = int(tracks[500])
        assert ds.count("evs", f"track < {cut}") == 500
        assert ds.count("evs", f"track <= {cut}") == 501
        assert ds.count("evs", f"track > {cut}") == len(tracks) - 501
        assert ds.count("evs", f"track >= {cut}") == len(tracks) - 500

    def test_not_and_in(self, ids):
        ds, tracks = ids
        t0, t1 = int(tracks[10]), int(tracks[11])
        assert ds.count("evs", f"track IN ({t0}, {t1})") == 2
        assert ds.count("evs", f"NOT (track = {t0})") == len(tracks) - 1
        assert ds.count(
            "evs", f"track BETWEEN {t0} AND {t1}"
        ) == 2


def test_sampling_applied_once_on_coarse_path(ds):
    """r4 review: sampling must run exactly once (host, post-refine), not
    also inside the device coarse kernel."""
    from geomesa_tpu import Query

    d, cx, cy, r = ds
    host = GeoDataset(n_shards=4, prefer_device=False)
    host.create_schema("polys", PSPEC)
    host.ingest_arrow("polys", d.to_arrow("polys"))
    q = Query(ecql=QUERY, sampling=5)
    a = len(d.query("polys", q))
    b = len(host.query("polys", q))
    assert a == b
    exact = int(_oracle(cx, cy, r).sum())
    assert a == (exact + 4) // 5 or abs(a - exact // 5) <= 1
