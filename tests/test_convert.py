"""Converter framework tests (geomesa-convert parity: expressions, delimited
text, JSON, validation modes, type inference, HOCON configs)."""

import numpy as np
import pytest

from geomesa_tpu import GeoDataset
from geomesa_tpu.convert import (
    ConverterConfig, DelimitedTextConverter, EvaluationContext, JsonConverter,
    converter_for, infer_schema,
)
from geomesa_tpu.convert import expressions as ex
from geomesa_tpu.convert import hocon
from geomesa_tpu.schema.feature_type import FeatureType


# -- expressions -------------------------------------------------------------

def _ev(text, raw=None, fields=None, n=1):
    raw = raw or [np.array([""], dtype=object)]
    ctx = ex.Context(raw=raw, fields=fields or {}, n=n)
    return ex.parse(text).eval(ctx)


def test_expression_basics():
    a = np.array([" Hello "], dtype=object)
    assert _ev("trim($1)", [None, a])[0] == "Hello"
    assert _ev("lowercase(trim($1))", [None, a])[0] == "hello"
    assert _ev("concat('a', 'b', $1)", [None, a])[0] == "ab Hello "
    assert _ev("toInt('42')")[0] == 42
    assert _ev("toDouble('4.5')")[0] == 4.5
    assert _ev("add(toInt('2'), toInt('3'))")[0] == 5.0
    assert _ev("substr('abcdef', 1, 3)")[0] == "bc"
    assert _ev("regexReplace('l+', 'L', 'hello')")[0] == "heLo"


def test_expression_dates():
    out = _ev("date('yyyy-MM-dd HH:mm:ss', '2020-03-04 05:06:07')")
    assert out[0] == np.datetime64("2020-03-04T05:06:07", "ms")
    out = _ev("isoDate('2020-03-04T05:06:07Z')")
    assert out[0] == np.datetime64("2020-03-04T05:06:07", "ms")
    out = _ev("secsToDate(1583298367)")
    assert out[0] == np.datetime64(1583298367000, "ms")


def test_expression_point_and_id():
    out = _ev("point(toDouble('-100.5'), toDouble('45.25'))")
    assert out[0] == (-100.5, 45.25)
    assert _ev("md5('abc')")[0] == "900150983cd24fb0d6963f7d28e17f72"
    assert len(_ev("uuid()")[0]) == 32


def test_expression_try_and_default():
    assert _ev("try(toInt('nope'), 0)")[0] == 0
    assert _ev("withDefault(emptyToNull(''), 'dflt')")[0] == "dflt"
    with pytest.raises(ex.EvalError):
        _ev("nosuchfn(1)")


def test_field_chaining():
    f = {"a": np.array([7], dtype=object)}
    assert _ev("add($a, 1)", fields=f)[0] == 8.0
    with pytest.raises(ex.EvalError):
        _ev("$notyet")


# -- HOCON -------------------------------------------------------------------

def test_hocon_parse():
    cfg = hocon.loads("""
    // a comment
    geomesa.converters.mydata = {
      type = "delimited-text"
      format = CSV
      id-field = "md5($0)"
      options { skip-lines = 1 }
      fields = [
        { name = "dtg", transform = "date('yyyy-MM-dd', $1)" }
        { name = "geom", transform = "point(toDouble($2), toDouble($3))" }
      ]
    }
    """)
    c = ConverterConfig.parse(cfg)
    assert c.type == "delimited-text"
    assert c.options["skip-lines"] == 1
    assert len(c.fields) == 2
    # plain JSON also accepted
    assert hocon.loads('{"a": 1}') == {"a": 1}


# -- delimited text ----------------------------------------------------------

CSV_CONFIG = {
    "type": "delimited-text",
    "format": "CSV",
    "id-field": "$1",
    "options": {"skip-lines": 1, "error-mode": "skip-bad-records"},
    "fields": [
        {"name": "name", "transform": "trim($2)"},
        {"name": "age", "transform": "toInt($3)"},
        {"name": "dtg", "transform": "date('yyyy-MM-dd', $4)"},
        {"name": "geom", "transform": "point(toDouble($5), toDouble($6))"},
    ],
}

CSV_DATA = """id,name,age,date,lon,lat
a1, alice ,30,2020-01-05,-100.0,40.0
a2,bob,25,2020-01-06,-99.0,41.0
a3,carol,bad_age,2020-01-07,-98.0,42.0
a4,dan,40,2020-01-08,-300.0,42.0
a5,eve,35,2020-01-09,-97.0,43.0
"""


def test_delimited_converter():
    ft = FeatureType.from_spec("people", "name:String,age:Integer,dtg:Date,*geom:Point")
    conv = converter_for(ft, CSV_CONFIG)
    assert isinstance(conv, DelimitedTextConverter)
    ctx = EvaluationContext()
    batches = list(conv.convert(CSV_DATA, ctx))
    assert len(batches) == 1
    data, fids = batches[0]
    # row a3 (bad age) and a4 (lon out of range) dropped
    assert ctx.success == 3 and ctx.failure >= 2
    assert list(fids) == ["a1", "a2", "a5"]
    assert list(data["name"]) == ["alice", "bob", "eve"]


def test_delimited_raise_mode():
    cfg = dict(CSV_CONFIG)
    cfg["options"] = {"skip-lines": 1, "error-mode": "raise-errors"}
    ft = FeatureType.from_spec("people", "name:String,age:Integer,dtg:Date,*geom:Point")
    conv = converter_for(ft, cfg)
    with pytest.raises(ValueError):
        list(conv.convert(CSV_DATA))


def test_dataset_ingest_csv():
    ds = GeoDataset(n_shards=2)
    ds.create_schema("people", "name:String,age:Integer,dtg:Date,*geom:Point")
    ctx = ds.ingest("people", CSV_DATA, CSV_CONFIG)
    assert ctx.success == 3
    assert ds.count("people") == 3
    assert ds.count("people", "age > 30") == 1


# -- JSON --------------------------------------------------------------------

JSON_CONFIG = {
    "type": "json",
    "feature-path": "$.features[*]",
    "id-field": "$id",
    "fields": [
        {"name": "id", "path": "$.properties.id"},
        {"name": "name", "path": "$.properties.name"},
        {"name": "lon", "path": "$.geometry.coordinates[0]"},
        {"name": "lat", "path": "$.geometry.coordinates[1]"},
        {"name": "geom", "transform": "point($lon, $lat)"},
    ],
}

JSON_DATA = """
{"features": [
  {"properties": {"id": "j1", "name": "x"}, "geometry": {"coordinates": [-100.0, 40.0]}},
  {"properties": {"id": "j2", "name": "y"}, "geometry": {"coordinates": [-99.0, 41.0]}}
]}
"""


def test_json_converter():
    ft = FeatureType.from_spec("pts", "name:String,*geom:Point")
    conv = converter_for(ft, JSON_CONFIG)
    assert isinstance(conv, JsonConverter)
    ctx = EvaluationContext()
    (data, fids), = conv.convert(JSON_DATA, ctx)
    assert ctx.success == 2
    assert list(fids) == ["j1", "j2"]
    assert list(data["name"]) == ["x", "y"]
    assert data["geom"][0] == (-100.0, 40.0)


def test_failure_counted_once_with_physical_lines():
    ft = FeatureType.from_spec("people", "name:String,age:Integer,dtg:Date,*geom:Point")
    conv = converter_for(ft, CSV_CONFIG)
    ctx = EvaluationContext()
    list(conv.convert(CSV_DATA, ctx))
    # a3 (bad age) and a4 (out-of-range lon): exactly one failure each
    assert ctx.failure == 2
    # physical 1-based line numbers (header is line 1)
    assert any("line 4" in e for e in ctx.errors), ctx.errors
    assert any("line 5" in e for e in ctx.errors), ctx.errors


def test_hocon_eol_comments():
    cfg = hocon.loads("type = json // trailing\nformat = CSV # another\n")
    assert cfg == {"type": "json", "format": "CSV"}


def test_hocon_quoted_key_literal():
    assert hocon.loads('{ "a.b" = 1 }') == {"a.b": 1}
    assert hocon.loads("a.b = 1") == {"a": {"b": 1}}


def test_raw_record_dollar_zero():
    # $0 must be the verbatim input record, not a comma re-join
    ft = FeatureType.from_spec("t", "rec:String,v:String")
    cfg = {
        "type": "delimited-text", "format": {"delimiter": "|"},
        "fields": [
            {"name": "rec", "transform": "$0"},
            {"name": "v", "transform": "$1"},
        ],
    }
    conv = converter_for(ft, cfg)
    (data, _), = conv.convert("x,y|z\nx|y,z\n")
    assert list(data["rec"]) == ["x,y|z", "x|y,z"]
    assert list(data["v"]) == ["x,y", "x"]


# -- type inference ----------------------------------------------------------

def test_infer_schema():
    sample = "id,name,value,date,lon,lat\n1,abc,2.5,2020-01-01,-100.0,40.0\n2,def,3.5,2020-01-02,-99.0,41.0\n"
    ft, cfg = infer_schema(sample)
    types = {a.name: a.type for a in ft.attributes}
    assert types["geom"] == "point"
    assert types["value"] == "float64"
    assert types["date"] == "date"
    assert types["id"] == "int64"
    # inferred config actually ingests
    ds = GeoDataset(n_shards=2)
    ds.create_schema(ft)
    ctx = ds.ingest(ft.name, sample, cfg)
    assert ctx.success == 2
    assert ds.count(ft.name) == 2


def test_enrichment_cache_lookup(tmp_path):
    """cacheLookup(cache, key, field) with simple + csv caches
    (EnrichmentCacheFunctionFactory.scala:24, EnrichmentCache.scala:19)."""
    from geomesa_tpu.convert.converter import ConverterConfig, converter_for
    from geomesa_tpu.schema.feature_type import FeatureType

    csv_path = tmp_path / "lookup.csv"
    csv_path.write_text("id,name,pop\nUS,United States,331\nFR,France,67\n")
    conf = ConverterConfig.parse({
        "type": "delimited-text",
        "format": "CSV",
        "id-field": "$cc",
        "fields": [
            {"name": "cc", "transform": "$1"},
            {"name": "country", "transform": "cacheLookup('geo', $1, 'name')"},
            {"name": "pop", "transform": "cacheLookup('geo', $1, 'pop')"},
            {"name": "label", "transform": "cacheLookup('tags', $1, 'label')"},
            {"name": "lon", "transform": "toDouble($2)"},
            {"name": "lat", "transform": "toDouble($3)"},
            {"name": "geom", "transform": "point($lon, $lat)"},
        ],
        "caches": {
            "geo": {"type": "csv", "path": str(csv_path), "id-field": "id"},
            "tags": {"type": "simple",
                     "data": {"US": {"label": "us-tag"}}},
        },
    })
    ft = FeatureType.from_spec(
        "t", "cc:String,country:String,pop:String,label:String,*geom:Point"
    )
    conv = converter_for(ft, conf)
    (data, fids), = conv.convert(["US,-100.0,40.0", "FR,2.0,48.0"])
    assert list(data["country"]) == ["United States", "France"]
    assert list(data["pop"]) == ["331", "67"]
    assert list(data["label"]) == ["us-tag", None]


def test_jdbc_converter(tmp_path):
    """SQL-statement ingest via the embedded sqlite engine
    (geomesa-convert-jdbc, JdbcConverter.scala:29)."""
    import sqlite3

    from geomesa_tpu.convert.converter import ConverterConfig, converter_for
    from geomesa_tpu.schema.feature_type import FeatureType

    db = tmp_path / "pts.db"
    conn = sqlite3.connect(db)
    conn.execute("CREATE TABLE pts (name TEXT, lon REAL, lat REAL)")
    conn.executemany(
        "INSERT INTO pts VALUES (?, ?, ?)",
        [("a", -100.0, 40.0), ("b", -90.5, 35.25), ("c", -80.0, 30.0)],
    )
    conn.commit()
    conn.close()
    conf = ConverterConfig.parse({
        "type": "jdbc",
        "connection": f"sqlite:///{db}",
        "id-field": "$name",
        "fields": [
            {"name": "name", "transform": "$1"},
            {"name": "geom", "transform": "point(toDouble($2), toDouble($3))"},
        ],
    })
    ft = FeatureType.from_spec("p", "name:String,*geom:Point")
    conv = converter_for(ft, conf)
    batches = list(conv.convert("SELECT name, lon, lat FROM pts ORDER BY name"))
    assert len(batches) == 1
    data, fids = batches[0]
    assert list(data["name"]) == ["a", "b", "c"]
    assert data["geom"][0] == (-100.0, 40.0)
    assert data["geom"][1] == (-90.5, 35.25)
    assert list(fids) == ["a", "b", "c"]


def test_jdbc_rejects_foreign_schemes():
    from geomesa_tpu.convert.converter import ConverterConfig, converter_for
    from geomesa_tpu.schema.feature_type import FeatureType

    conf = ConverterConfig.parse({
        "type": "jdbc", "connection": "jdbc:postgresql://host/db",
        "fields": [{"name": "geom", "transform": "point(0.0, 0.0)"}],
    })
    ft = FeatureType.from_spec("p", "*geom:Point")
    conv = converter_for(ft, conf)
    with pytest.raises(ValueError, match="only sqlite"):
        list(conv.convert("SELECT 1"))


OSM_XML = """<?xml version='1.0' encoding='UTF-8'?>
<osm version="0.6">
  <node id="101" lat="48.8584" lon="2.2945" version="3" timestamp="2020-05-01T10:00:00Z">
    <tag k="name" v="Tour Eiffel"/>
    <tag k="tourism" v="attraction"/>
  </node>
  <node id="102" lat="40.6892" lon="-74.0445" version="5" timestamp="2020-06-02T11:30:00Z">
    <tag k="name" v="Statue of Liberty"/>
  </node>
  <node id="103" lat="51.5007" lon="-0.1246" version="2" timestamp="2020-07-03T12:45:00Z"/>
</osm>
"""


def test_osm_node_ingest_via_xml_converter():
    """OSM node extracts are plain XML: the xml converter covers the
    reference's geomesa-convert-osm node path (attributes via @, nested
    tag values via a child path)."""
    conf = {
        "type": "xml",
        "feature-path": "node",
        "id-field": "$osm_id",
        "fields": [
            {"name": "osm_id", "path": "@id"},
            {"name": "name", "path": "tag[@k='name']/@v"},
            {"name": "dtg", "transform": "isoDate($ts)"},
            {"name": "ts", "path": "@timestamp"},
            {"name": "lon", "path": "@lon"},
            {"name": "lat", "path": "@lat"},
            {"name": "geom", "transform": "point(toDouble($lon), toDouble($lat))"},
        ],
    }
    ds = GeoDataset(n_shards=2)
    ds.create_schema("osm", "osm_id:String,name:String,dtg:Date,*geom:Point")
    ctx = ds.ingest("osm", OSM_XML, conf)
    assert ctx.success == 3, ctx.errors
    assert ds.count("osm", "BBOX(geom, -80, 35, -70, 45)") == 1  # liberty
    fc = ds.query("osm", "name = 'Tour Eiffel'")
    assert len(fc) == 1 and fc.fids == ["101"]
