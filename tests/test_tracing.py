"""Observability layer tests (docs/OBSERVABILITY.md): span-tree tracing
through the query path, latency histograms, the slow-query log, the
exposition surface, and the off-by-default-cheap contract.

The contract under test:

* a traced query produces ONE span tree — plan, (cache cell lookups /
  residual scans when decomposed), per-partition {stage, device_put,
  kernel, sync} — with the same trace_id in the QueryEvent, the explain
  output, and (over Flight) the server-side audit;
* the prefetch worker adopts the query's span context the way it adopts
  config overrides, so staging spans land in the query's tree;
* with tracing disabled the span API returns a shared no-op singleton —
  no allocation, no trace state;
* histograms bucket correctly and render prometheus text p50/p99 can be
  derived from;
* a root span slower than geomesa.trace.slow.ms appends its full tree as
  JSONL through the SAME audit appender (file order = event order).
"""

import gc
import json
import tracemalloc

import numpy as np
import pytest

from geomesa_tpu import GeoDataset, config, metrics, tracing
from geomesa_tpu.filter.ecql import parse_iso_ms


def _mk_ds(n=5000, partitioned=False, seed=3, n_shards=2):
    spec = "name:String,weight:Float,dtg:Date,*geom:Point"
    if partitioned:
        spec += ";geomesa.partition='time'"
    ds = GeoDataset(n_shards=n_shards)
    ds.create_schema("t", spec)
    rng = np.random.default_rng(seed)
    lo, hi = parse_iso_ms("2020-01-01"), parse_iso_ms("2020-03-01")
    ds.insert("t", {
        "name": rng.choice(["a", "b"], n),
        "weight": rng.uniform(0, 1, n).astype(np.float32),
        "geom__x": rng.uniform(-120, -70, n),
        "geom__y": rng.uniform(25, 50, n),
        "dtg": rng.integers(lo, hi, n).astype("datetime64[ms]"),
    }, fids=np.arange(n).astype(str))
    ds.flush("t")
    return ds


BBOX = "BBOX(geom, -100, 30, -80, 45)"


def _names(tree, acc=None):
    acc = [] if acc is None else acc
    acc.append(tree["name"])
    for c in tree.get("children", ()):
        _names(c, acc)
    return acc


@pytest.fixture()
def traced():
    with config.TRACE_ENABLED.scoped("true"):
        yield


# ---------------------------------------------------------------------------
# off-path cheapness
# ---------------------------------------------------------------------------


def test_disabled_span_is_shared_noop_singleton():
    assert not tracing.enabled()
    assert tracing.span("plan") is tracing.NOOP
    assert tracing.span("scan.kernel") is tracing.NOOP
    assert tracing.start("query") is tracing.NOOP
    assert tracing.current_trace_id() is None
    # the singleton is inert under the full protocol
    with tracing.span("x") as s:
        assert s.set(part=1) is s


def test_disabled_span_path_allocates_nothing():
    tracing.span("warmup")  # warm any lazy state
    gc.collect()
    tracemalloc.start()
    for _ in range(1000):
        tracing.span("hot")
    current, peak = tracemalloc.get_traced_memory()
    tracemalloc.stop()
    # a single ContextVar read + singleton return: no per-call allocation
    # (the small constant slack absorbs interpreter-internal noise)
    assert peak < 2048, f"no-op span path allocated {peak} bytes over 1000 calls"


# ---------------------------------------------------------------------------
# span-tree shape
# ---------------------------------------------------------------------------


def test_plain_query_span_tree_and_audit_trace_id(traced):
    ds = _mk_ds()
    n = ds.count("t", BBOX)
    assert n > 0
    tr = tracing.last_trace()
    assert tr is not None
    tree = tr.root.to_dict()
    names = _names(tree)
    assert tree["name"] == "count"
    assert "plan" in names
    # the scan ran SOMEWHERE: device (kernel+sync) or host
    assert any(s.startswith("scan.") for s in names)
    ev = ds.audit.recent(1)[0]
    assert ev.hints.get("trace_id") == tr.trace_id


def test_explain_carries_trace_id_and_alert_section(traced):
    ds = _mk_ds(1000)
    out = ds.explain("t", BBOX)
    assert "Observability" in out
    assert "trace_id (this explain call):" in out
    assert "recompile alert:" in out
    tr = tracing.last_trace()
    assert tr.trace_id in out


def test_partitioned_query_tree_has_partition_and_stage_spans(traced):
    ds = _mk_ds(20_000, partitioned=True)
    with config.PIPELINE_PREFETCH.scoped("true"):
        n = ds.count("t", BBOX)
    assert n > 0
    tree = tracing.last_trace().root.to_dict()
    names = _names(tree)
    parts = [s for s in names if s == "scan.partition"]
    assert len(parts) >= 2, names
    # the prefetch WORKER opened these: span-context adoption across the
    # thread boundary (the worker snapshot/adopt pair)
    assert "scan.stage" in names, names


def test_cached_partial_query_tree(traced):
    ds = _mk_ds(20_000)
    with config.CACHE_ENABLED.scoped("true"):
        c1 = ds.count("t", BBOX)
        tree1 = tracing.last_trace().root.to_dict()
        # overlapping pan: partial-cover reuse
        c2 = ds.count("t", "BBOX(geom, -99, 30, -79, 45)")
        tree2 = tracing.last_trace().root.to_dict()
    assert c1 > 0 and c2 > 0
    n1, n2 = _names(tree1), _names(tree2)
    assert "cache.lookup" in n1
    assert "cache.cells" in n1 and "cache.merge" in n1
    assert "cache.cells" in n2
    ev = ds.audit.recent(1)[0]
    assert ev.hints["exec_path"]["cache"] in ("partial", "miss")


def test_query_batches_stream_trace(traced):
    ds = _mk_ds(2000)
    batches = list(ds.query_batches("t", BBOX))
    assert sum(b.n for b in batches) > 0
    tr = tracing.last_trace()
    assert tr.root.name == "query_batches"
    assert tr.root.duration_ms > 0
    ev = ds.audit.recent(1)[0]
    assert ev.hints.get("trace_id") == tr.trace_id


def test_span_budget_bounds_tree(traced):
    with config.TRACE_MAX_SPANS.scoped("4"):
        with tracing.start("query") as root:
            for i in range(16):
                with tracing.span(f"s{i}"):
                    pass
        tr = root.trace
    assert tr.n_spans <= 4
    assert tr.dropped > 0


def test_recompile_event_visible_in_trace(traced):
    ds = _mk_ds(4000)
    ds.count("t", BBOX)  # cold: compiles at least one kernel
    names = _names(tracing.last_trace().root.to_dict())
    assert "kernel.recompile" in names


# ---------------------------------------------------------------------------
# flight round-trip
# ---------------------------------------------------------------------------


def test_trace_id_round_trips_over_flight_headers(traced):
    pytest.importorskip("pyarrow.flight")
    from geomesa_tpu.sidecar import GeoFlightClient, GeoFlightServer

    srv = GeoFlightServer(GeoDataset(n_shards=1, prefer_device=False))
    try:
        with GeoFlightClient(f"grpc+tcp://127.0.0.1:{srv.port}") as c:
            c.create_schema("t", "name:String,*geom:Point")
            import pyarrow as pa

            c.insert_arrow("t", pa.table({
                "__fid__": ["1", "2"], "name": ["a", "b"],
                "geom__x": [0.0, 1.0], "geom__y": [0.0, 1.0],
            }))
            n = c.count("t", "INCLUDE")
            assert n == 2
            client_tid = tracing.last_trace().trace_id
        # the SERVER audit event carries the CLIENT'S trace id (propagated
        # as a Flight header, adopted by the server-side root span)
        ev = srv.dataset.audit.recent(1)[0]
        assert ev.hints.get("op") == "count"
        assert ev.hints.get("trace_id") == client_tid
    finally:
        srv.shutdown()


# ---------------------------------------------------------------------------
# histograms + gauges (metrics.py upgrades)
# ---------------------------------------------------------------------------


def test_histogram_bucket_math():
    h = metrics.Histogram()
    for v in (0.0004, 0.003, 0.003, 0.07, 20.0, 999.0):
        h.observe(v)
    snap = h.snapshot()
    assert snap["count"] == 6
    assert snap["counts"][0] == 1            # 0.0004 <= 0.0005
    assert snap["counts"][-1] == 1           # 999 -> +Inf overflow
    assert h.quantile(0.5) == 0.005          # 3rd of 6 lands in le=0.005
    assert h.quantile(1.0) == 30.0           # +Inf resolves to top bound
    assert abs(snap["sum_s"] - (0.0004 + 0.006 + 0.07 + 20.0 + 999.0)) < 1e-9


def test_histogram_prometheus_rendering():
    reg = metrics.MetricRegistry(prefix="t")
    reg.histogram("trace.plan").observe(0.002)
    reg.histogram("trace.plan").observe(0.2)
    text = reg.prometheus()
    lines = [ln for ln in text.splitlines() if "trace_plan" in ln]
    assert 't_trace_plan_seconds_bucket{le="0.0025"} 1' in lines
    assert 't_trace_plan_seconds_bucket{le="0.25"} 2' in lines
    assert 't_trace_plan_seconds_bucket{le="+Inf"} 2' in lines
    assert any(ln.startswith("t_trace_plan_seconds_count 2") for ln in lines)
    # cumulative monotone
    cums = [int(ln.rsplit(" ", 1)[1]) for ln in lines if "_bucket" in ln]
    assert cums == sorted(cums)


def test_timer_feeds_histogram_and_report_quantiles():
    reg = metrics.MetricRegistry(prefix="t")
    t = reg.timer("query.scan")
    for s in (0.001, 0.002, 0.004, 0.3):
        t.update(s)
    rep = reg.report()["query.scan"]
    assert rep["count"] == 4
    assert rep["p50_s"] <= rep["p99_s"]
    text = reg.prometheus()
    assert 't_query_scan_seconds_bucket{le="+Inf"} 4' in text
    # legacy lines preserved
    assert "t_query_scan_count 4" in text


def test_gauge_locked_and_explicit_replacement():
    reg = metrics.MetricRegistry(prefix="t")
    g = reg.gauge("x")
    g.set(3)
    assert g.value == 3.0

    fn1 = lambda: 1.0  # noqa: E731
    fn2 = lambda: 2.0  # noqa: E731
    reg.gauge("backed", fn1)
    reg.gauge("backed", fn1)  # same fn: idempotent
    with pytest.raises(ValueError):
        reg.gauge("backed", fn2)  # silent replacement refused
    assert reg.gauge("backed").value == 1.0
    reg.gauge("backed", fn2, replace=True)  # explicit replacement
    assert reg.gauge("backed").value == 2.0


# ---------------------------------------------------------------------------
# slow-query log
# ---------------------------------------------------------------------------


def test_slow_query_writes_span_tree_jsonl(tmp_path, traced):
    from geomesa_tpu import audit as audit_mod

    path = tmp_path / "audit.jsonl"
    ds = _mk_ds(2000)
    with config.AUDIT_PATH.scoped(str(path)), \
            config.TRACE_SLOW_MS.scoped("0"):
        n = ds.count("t", BBOX)
    audit_mod._appender.reset()
    assert n > 0
    tid = tracing.last_trace().trace_id
    lines = [json.loads(ln) for ln in path.read_text().splitlines()]
    kinds = [ln.get("kind", "query_event") for ln in lines]
    slow = [ln for ln in lines if ln.get("kind") == "slow_trace"]
    assert slow, kinds
    rec = slow[-1]
    assert rec["trace_id"] == tid
    assert rec["tree"]["name"] == "count"
    assert any(c["name"] == "plan" for c in rec["tree"].get("children", []))
    # the query event for the same query rides the same file, in order:
    # audit fires inside the root span, so event precedes its slow trace
    ev_idx = max(i for i, ln in enumerate(lines)
                 if ln.get("hints", {}).get("trace_id") == tid)
    slow_idx = lines.index(rec)
    assert ev_idx < slow_idx


def test_late_child_stretches_finished_root_for_slow_check(traced):
    # a streamed query's scan spans finish AFTER the sidecar do_get root
    # returned the stream object: the late finish must stretch the root
    # and still trip the slow-query threshold (once)
    import time as _t

    tracing.clear_slow_traces()
    with config.TRACE_SLOW_MS.scoped("5"):
        root = tracing.start("sidecar.do_get")
        with root:
            child = tracing.span("query_batches")
            child.t0 = _t.perf_counter()
        assert not tracing.slow_traces()  # root alone was under threshold
        _t.sleep(0.02)
        child.finish()
        assert tracing.slow_traces(), "late child must re-trip the check"
        n = len(tracing.slow_traces())
        child.finish()  # idempotent: one slow record per trace
        assert len(tracing.slow_traces()) == n


def test_query_batches_restores_enclosing_span(traced):
    ds = _mk_ds(1000)
    with tracing.start("outer") as outer:
        batches = ds.query_batches("t", BBOX)
        assert tracing.current_span() is outer, \
            "eager planning must restore the enclosing span"
        list(batches)
        assert tracing.current_span() is outer, \
            "stream exhaustion must restore the enclosing span"


def test_slow_trace_ring_served(traced):
    tracing.clear_slow_traces()
    ds = _mk_ds(1000)
    with config.TRACE_SLOW_MS.scoped("0"):
        ds.count("t", BBOX)
    recent = tracing.slow_traces()
    assert recent and recent[-1]["tree"]["name"] == "count"


# ---------------------------------------------------------------------------
# exposition surface
# ---------------------------------------------------------------------------


def test_obs_endpoints(traced):
    import urllib.request

    from geomesa_tpu import obs

    ds = _mk_ds(1000)
    ds.count("t", BBOX)
    srv = obs.serve(ds, port=0, background=True)
    try:
        port = srv.server_address[1]

        def get(path):
            with urllib.request.urlopen(
                f"http://127.0.0.1:{port}{path}", timeout=10
            ) as r:
                return r.status, r.read().decode()

        code, text = get("/metrics")
        assert code == 200
        assert "geomesa_query_plan_count" in text
        assert "geomesa_kernel_recompile_alert" in text
        assert "_seconds_bucket" in text  # histograms exposed
        code, body = get("/healthz")
        h = json.loads(body)
        assert code == 200 and h["status"] == "ok"
        assert "breakers" in h and "device" in h
        code, body = get("/debug/queries?n=5")
        d = json.loads(body)
        assert code == 200
        assert d["queries"] and d["queries"][-1]["type_name"] == "t"
        assert "degradations" in d and "slow_traces" in d
    finally:
        srv.shutdown()


def test_web_server_mounts_obs_routes():
    import urllib.request

    from geomesa_tpu import web

    ds = _mk_ds(500)
    ds.count("t", "INCLUDE")
    srv = web.serve(ds, port=0, background=True)
    try:
        port = srv.server_address[1]
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=10
        ) as r:
            assert r.status == 200
            assert "geomesa_" in r.read().decode()
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/healthz", timeout=10
        ) as r:
            assert json.loads(r.read())["status"] in ("ok", "degraded")
        # malformed ?n= must come back as a clean 400, not a dropped
        # connection (web.py routes obs paths before its own try/except)
        try:
            urllib.request.urlopen(
                f"http://127.0.0.1:{port}/debug/queries?n=abc", timeout=10
            )
            assert False, "expected HTTP 400"
        except urllib.error.HTTPError as e:
            assert e.code == 400
    finally:
        srv.shutdown()


def test_healthz_degraded_when_breaker_open():
    from geomesa_tpu import obs, resilience

    resilience.reset_breakers()
    try:
        b = resilience.breaker("sidecar:test-loc", threshold=1)
        b.record_failure()
        assert b.state == "open"
        h = obs.health()
        assert h["status"] == "degraded"
        assert "sidecar:test-loc" in h["open_breakers"]
    finally:
        resilience.reset_breakers()


# ---------------------------------------------------------------------------
# cli
# ---------------------------------------------------------------------------


def test_cli_trace_and_metrics(tmp_path, capsys):
    from geomesa_tpu import cli

    ds = _mk_ds(500)
    ds.save(str(tmp_path / "cat"))
    rc = cli.main([
        "trace", "-c", str(tmp_path / "cat"), "-f", "t", "-q", BBOX,
        "--op", "count", "--json",
    ])
    out = capsys.readouterr().out
    assert rc == 0
    d = json.loads(out)
    assert d["tree"]["name"] == "count"
    assert d["trace_id"]
    rc = cli.main(["metrics"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "geomesa_" in out
