"""Result reprojection (Query.srid — the reproject step of
QueryPlanner.runQuery's post-processing chain, QueryPlanner.scala:68-90)."""

import numpy as np
import pytest

from geomesa_tpu import GeoDataset, Query
from geomesa_tpu.utils import reproject as rp


def test_mercator_round_trip():
    rng = np.random.default_rng(2)
    x = rng.uniform(-179, 179, 1000)
    y = rng.uniform(-84, 84, 1000)
    mx, my = rp.to_mercator(x, y)
    x2, y2 = rp.from_mercator(mx, my)
    assert np.allclose(x, x2, atol=1e-9)
    assert np.allclose(y, y2, atol=1e-9)
    # known anchor: (0, 0) -> (0, 0); 180 deg -> earth half-circumference
    assert rp.to_mercator(np.array([0.0]), np.array([0.0]))[0][0] == 0
    mx180 = rp.to_mercator(np.array([180.0]), np.array([0.0]))[0][0]
    assert mx180 == pytest.approx(np.pi * rp.R)


def test_unknown_crs_raises():
    with pytest.raises(ValueError, match="32633"):
        rp.transformer(4326, 32633)
    # identity pair always works
    fn = rp.transformer(4326, 4326)
    assert fn(1.0, 2.0)[0] == 1.0


def test_query_srid_points():
    rng = np.random.default_rng(3)
    n = 5_000
    ds = GeoDataset(n_shards=2)
    ds.create_schema("t", "v:Float,*geom:Point")
    x = rng.uniform(-120, -70, n)
    y = rng.uniform(25, 50, n)
    ds.insert("t", {"geom__x": x, "geom__y": y,
                    "v": rng.uniform(0, 1, n).astype(np.float32)},
              fids=np.arange(n).astype(str))
    ds.flush("t")
    fc = ds.query("t", Query("BBOX(geom, -100, 30, -80, 45)", srid=3857))
    assert fc.srid == 3857
    m = (x >= -100) & (x <= -80) & (y >= 30) & (y <= 45)
    assert len(fc) == int(m.sum())
    # every point transformed; mercator CONUS x is around -1e7 meters
    gx = fc.batch.columns["geom__x"]
    assert (gx < -8e6).all() and (gx > -1.2e7).all()
    # round-trip matches the stored f32 coordinates
    bx, by = rp.from_mercator(gx, fc.batch.columns["geom__y"])
    assert np.allclose(np.sort(bx), np.sort(x[m].astype(np.float32)),
                       atol=1e-6)


def test_query_srid_wkt_geometries():
    ds = GeoDataset(n_shards=1)
    ds.create_schema("p", "*geom:Polygon")
    wkt = "POLYGON ((0 0, 10 0, 10 10, 0 10, 0 0))"
    ds.insert("p", {"geom": [wkt]}, fids=["a"])
    ds.flush("p")
    fc = ds.query("p", Query("INCLUDE", srid=3857))
    out = str(fc.batch.columns["geom__wkt"][0])
    assert out.startswith("POLYGON")
    # the (10, 10) vertex in mercator
    mx, my = rp.to_mercator(np.array([10.0]), np.array([10.0]))
    assert f"{mx[0]:.0f}" in out.replace(".0 ", " ") or "1113194" in out
