"""Result reprojection (Query.srid — the reproject step of
QueryPlanner.runQuery's post-processing chain, QueryPlanner.scala:68-90)."""

import numpy as np
import pytest

from geomesa_tpu import GeoDataset, Query
from geomesa_tpu.utils import reproject as rp


def test_mercator_round_trip():
    rng = np.random.default_rng(2)
    x = rng.uniform(-179, 179, 1000)
    y = rng.uniform(-84, 84, 1000)
    mx, my = rp.to_mercator(x, y)
    x2, y2 = rp.from_mercator(mx, my)
    assert np.allclose(x, x2, atol=1e-9)
    assert np.allclose(y, y2, atol=1e-9)
    # known anchor: (0, 0) -> (0, 0); 180 deg -> earth half-circumference
    assert rp.to_mercator(np.array([0.0]), np.array([0.0]))[0][0] == 0
    mx180 = rp.to_mercator(np.array([180.0]), np.array([0.0]))[0][0]
    assert mx180 == pytest.approx(np.pi * rp.R)


def test_unknown_crs_raises(monkeypatch):
    # 27700 (OSGB, Airy ellipsoid) has no built-in closed form; disable
    # the pyproj escape hatch so the test holds even where it's installed
    monkeypatch.setattr(rp, "_pyproj_transform", lambda s, d: None)
    with pytest.raises(ValueError, match="27700"):
        rp.transformer(4326, 27700)
    # identity pair always works
    fn = rp.transformer(4326, 4326)
    assert fn(1.0, 2.0)[0] == 1.0


def test_utm_anchors_and_round_trip():
    """EPSG:32631 (UTM 31N): exact anchors from the projection definition
    plus an external meridian-arc cross-check."""
    fwd = rp.transformer(4326, 32631)
    inv = rp.transformer(32631, 4326)
    # central meridian (3E) at the equator IS (500000, 0) by definition
    e, n = fwd(np.array([3.0]), np.array([0.0]))
    assert e[0] == pytest.approx(500000.0, abs=1e-6)
    assert n[0] == pytest.approx(0.0, abs=1e-6)
    # UTM south false northing: 10,000,000 at the equator
    es, ns = rp.transformer(4326, 32731)(np.array([3.0]), np.array([0.0]))
    assert ns[0] == pytest.approx(10_000_000.0, abs=1e-6)
    # external check: one degree of meridian arc at 40.5N is 111044.3 m
    # (WGS84 meridian-degree series), scaled by k0=0.9996 on the CM
    _, n40 = fwd(np.array([3.0]), np.array([40.0]))
    _, n41 = fwd(np.array([3.0]), np.array([41.0]))
    assert (n41[0] - n40[0]) == pytest.approx(0.9996 * 111044.3, abs=30)
    # round trip over the whole zone band
    rng = np.random.default_rng(4)
    lon = rng.uniform(0, 6, 2000)
    lat = rng.uniform(-80, 84, 2000)
    x, y = fwd(lon, lat)
    lon2, lat2 = inv(x, y)
    assert np.allclose(lon, lon2, atol=1e-9)
    assert np.allclose(lat, lat2, atol=1e-9)


def test_laea_3035_anchor_and_round_trip():
    """EPSG:3035: the projection center (10E, 52N) maps to the false
    origin (4321000, 3210000) exactly."""
    fwd = rp.transformer(4326, 3035)
    inv = rp.transformer(3035, 4326)
    x, y = fwd(np.array([10.0]), np.array([52.0]))
    assert x[0] == pytest.approx(4321000.0, abs=1e-6)
    assert y[0] == pytest.approx(3210000.0, abs=1e-6)
    rng = np.random.default_rng(5)
    lon = rng.uniform(-10, 35, 2000)
    lat = rng.uniform(35, 70, 2000)
    lon2, lat2 = inv(*fwd(lon, lat))
    assert np.allclose(lon, lon2, atol=1e-9)
    assert np.allclose(lat, lat2, atol=1e-9)


def test_albers_5070_anchor_and_round_trip():
    """EPSG:5070 (CONUS Albers): the projection origin (-96, 23) maps to
    (0, 0) exactly; the projection is equal-area (checked numerically on
    a small quad against the authalic sphere)."""
    fwd = rp.transformer(4326, 5070)
    inv = rp.transformer(5070, 4326)
    x, y = fwd(np.array([-96.0]), np.array([23.0]))
    assert x[0] == pytest.approx(0.0, abs=1e-6)
    assert y[0] == pytest.approx(0.0, abs=1e-6)
    rng = np.random.default_rng(6)
    lon = rng.uniform(-125, -66, 2000)
    lat = rng.uniform(24, 49, 2000)
    lon2, lat2 = inv(*fwd(lon, lat))
    assert np.allclose(lon, lon2, atol=1e-9)
    assert np.allclose(lat, lat2, atol=1e-9)
    # equal-area property: a 0.1-degree quad at 40N projects to an area
    # equal to its ellipsoidal area (within series truncation)
    d = 0.1
    qlon = np.array([-100.0, -100.0 + d, -100.0 + d, -100.0])
    qlat = np.array([40.0, 40.0, 40.0 + d, 40.0 + d])
    qx, qy = fwd(qlon, qlat)
    area = 0.5 * abs(
        np.dot(qx, np.roll(qy, -1)) - np.dot(qy, np.roll(qx, -1))
    )
    # ellipsoidal quad area ~ (pi/180 * d)^2 * cos(40) * M(40) * N(40)
    # with M,N the meridional/normal radii: 6361816 m and 6387345 m
    expect = (np.pi / 180 * d) ** 2 * np.cos(np.radians(40.05)) \
        * 6361816.0 * 6387345.0
    assert area == pytest.approx(expect, rel=1e-3)


def test_world_mercator_3395_vs_3857():
    """EPSG:3395 (ellipsoidal) shares x with 3857 but its y at 45N is
    ~0.5% smaller (the classic spherical-vs-ellipsoidal web map offset)."""
    fwd = rp.transformer(4326, 3395)
    x95, y95 = fwd(np.array([12.0]), np.array([45.0]))
    x57, y57 = rp.to_mercator(np.array([12.0]), np.array([45.0]))
    assert x95[0] == pytest.approx(x57[0], abs=1e-6)
    ratio = y95[0] / y57[0]
    assert 0.99 < ratio < 0.998
    inv = rp.transformer(3395, 4326)
    lon2, lat2 = inv(x95, y95)
    assert lon2[0] == pytest.approx(12.0, abs=1e-9)
    assert lat2[0] == pytest.approx(45.0, abs=1e-9)


def test_composed_projected_to_projected():
    """src->dst with neither side 4326 composes through geographic."""
    fn = rp.transformer(3857, 32631)
    mx, my = rp.to_mercator(np.array([3.0]), np.array([0.0]))
    e, n = fn(mx, my)
    assert e[0] == pytest.approx(500000.0, abs=1e-6)
    assert n[0] == pytest.approx(0.0, abs=1e-6)


def test_mercator_clamp_warns():
    with pytest.warns(RuntimeWarning, match="clamped"):
        rp.to_mercator(np.array([0.0]), np.array([89.0]))


def test_reproject_wkt_array_nulls_and_batching():
    fn = rp.transformer(4326, 3857)
    wkts = np.array(
        ["POINT (10 10)", None, "", "LINESTRING (0 0, 10 10)"],
        dtype=object,
    )
    out = rp.reproject_wkt_array(wkts, fn)
    assert out[1] is None and out[2] == ""
    mx, my = rp.to_mercator(np.array([10.0]), np.array([10.0]))
    assert f"{mx[0]:.6f}".rstrip("0") in out[0] or "POINT" in out[0]
    px, py = out[0].replace("POINT (", "").rstrip(")").split()
    assert float(px) == pytest.approx(mx[0])
    assert float(py) == pytest.approx(my[0])
    assert out[3].startswith("LINESTRING")


def test_query_batches_applies_srid():
    """ADVICE r4 (medium): the streaming path must carry the same CRS as
    query() — previously it silently streamed raw 4326."""
    rng = np.random.default_rng(7)
    n = 3000
    ds = GeoDataset(n_shards=2)
    ds.create_schema("s", "v:Float,*geom:Point")
    x, y = rng.uniform(-120, -70, n), rng.uniform(25, 50, n)
    ds.insert("s", {"geom__x": x, "geom__y": y,
                    "v": rng.uniform(0, 1, n).astype(np.float32)},
              fids=np.arange(n).astype(str))
    ds.flush("s")
    q = Query("BBOX(geom, -100, 30, -80, 45)", srid=3857)
    got = np.concatenate([
        b.columns["geom__x"] for b in ds.query_batches("s", q)
    ])
    ref = ds.query("s", q).batch.columns["geom__x"]
    assert np.allclose(np.sort(got), np.sort(ref))
    assert (got < -8e6).all()  # mercator meters, not degrees


def test_query_batches_unknown_srid_raises_eagerly(monkeypatch):
    monkeypatch.setattr(rp, "_pyproj_transform", lambda s, d: None)
    ds = GeoDataset(n_shards=1)
    ds.create_schema("e", "*geom:Point")
    ds.insert("e", {"geom__x": np.array([0.0]), "geom__y": np.array([0.0])},
              fids=["a"])
    ds.flush("e")
    # raises at call time, not mid-stream
    with pytest.raises(ValueError, match="27700"):
        ds.query_batches("e", Query("INCLUDE", srid=27700))


def test_transforms_are_jittable():
    """The (x, y, xp) contract: every built-in projection traces under
    jax.jit when handed xp=jnp (the module header's jit-ability claim)."""
    import jax
    import jax.numpy as jnp

    lon = np.array([3.0, 5.0])
    lat = np.array([40.0, 45.0])
    for code in (3857, 3395, 32631, 5070, 3035):
        fwd = rp.transformer(4326, code)
        inv = rp.transformer(code, 4326)

        def rt(lo, la, _f=fwd, _i=inv):
            return _i(*_f(lo, la, xp=jnp), xp=jnp)

        lo2, la2 = jax.jit(rt)(lon, lat)
        # f32 under jit without x64: ~1e-4 degrees is the dtype floor
        assert np.allclose(np.asarray(lo2), lon, atol=1e-3)
        assert np.allclose(np.asarray(la2), lat, atol=1e-3)


def test_query_srid_utm():
    """Query.srid works for any built-in code, not just 3857."""
    ds = GeoDataset(n_shards=1)
    ds.create_schema("u", "*geom:Point")
    ds.insert("u", {"geom__x": np.array([3.0]), "geom__y": np.array([0.0])},
              fids=["a"])
    ds.flush("u")
    fc = ds.query("u", Query("INCLUDE", srid=32631))
    assert fc.batch.columns["geom__x"][0] == pytest.approx(500000.0, abs=0.1)


def test_query_srid_points():
    rng = np.random.default_rng(3)
    n = 5_000
    ds = GeoDataset(n_shards=2)
    ds.create_schema("t", "v:Float,*geom:Point")
    x = rng.uniform(-120, -70, n)
    y = rng.uniform(25, 50, n)
    ds.insert("t", {"geom__x": x, "geom__y": y,
                    "v": rng.uniform(0, 1, n).astype(np.float32)},
              fids=np.arange(n).astype(str))
    ds.flush("t")
    fc = ds.query("t", Query("BBOX(geom, -100, 30, -80, 45)", srid=3857))
    assert fc.srid == 3857
    m = (x >= -100) & (x <= -80) & (y >= 30) & (y <= 45)
    assert len(fc) == int(m.sum())
    # every point transformed; mercator CONUS x is around -1e7 meters
    gx = fc.batch.columns["geom__x"]
    assert (gx < -8e6).all() and (gx > -1.2e7).all()
    # round-trip matches the stored f32 coordinates
    bx, by = rp.from_mercator(gx, fc.batch.columns["geom__y"])
    assert np.allclose(np.sort(bx), np.sort(x[m].astype(np.float32)),
                       atol=1e-6)


def test_query_srid_wkt_geometries():
    ds = GeoDataset(n_shards=1)
    ds.create_schema("p", "*geom:Polygon")
    wkt = "POLYGON ((0 0, 10 0, 10 10, 0 10, 0 0))"
    ds.insert("p", {"geom": [wkt]}, fids=["a"])
    ds.flush("p")
    fc = ds.query("p", Query("INCLUDE", srid=3857))
    out = str(fc.batch.columns["geom__wkt"][0])
    assert out.startswith("POLYGON")
    # the (10, 10) vertex in mercator
    mx, my = rp.to_mercator(np.array([10.0]), np.array([10.0]))
    assert f"{mx[0]:.0f}" in out.replace(".0 ", " ") or "1113194" in out
