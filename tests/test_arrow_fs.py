"""Arrow interchange + FileSystem (Parquet) storage tests.

Mirrors the reference's arrow/fs coverage (SimpleFeatureVectorTest,
DeltaWriter round-trips, ParquetFileSystemStorage + partition scheme tests).
"""

import numpy as np
import pyarrow as pa
import pytest

from geomesa_tpu import GeoDataset
from geomesa_tpu.api.dataset import Query
from geomesa_tpu.filter.ecql import parse_iso_ms
from geomesa_tpu.fs import (
    AttributeScheme, CompositeScheme, DateTimeScheme, FileSystemStorage,
    Z2Scheme, scheme_from_config,
)
from geomesa_tpu.io import arrow_io
from geomesa_tpu.schema.columns import DictionaryEncoder, encode_batch
from geomesa_tpu.schema.feature_type import FeatureType

SPEC = "name:String,age:Integer,dtg:Date,*geom:Point"


def _data(n=100, seed=0):
    rng = np.random.default_rng(seed)
    return {
        "name": [f"n{i % 7}" for i in range(n)],
        "age": rng.integers(0, 90, n).astype(np.int32),
        "dtg": rng.integers(
            parse_iso_ms("2020-01-01"), parse_iso_ms("2020-01-20"), n
        ).astype("datetime64[ms]"),
        "geom__x": rng.uniform(-120, -70, n),
        "geom__y": rng.uniform(25, 50, n),
    }


def test_arrow_round_trip():
    ft = FeatureType.from_spec("t", SPEC)
    dicts = {}
    data = _data(50)
    batch = encode_batch(ft, data, dicts, fids=[f"f{i}" for i in range(50)])
    rb = arrow_io.batch_to_arrow(ft, batch, dicts)
    assert rb.num_rows == 50
    assert pa.types.is_dictionary(rb.schema.field("name").type)
    assert pa.types.is_timestamp(rb.schema.field("dtg").type)
    data2, fids2 = arrow_io.table_to_data(ft, rb)
    assert fids2 == [f"f{i}" for i in range(50)]
    np.testing.assert_allclose(data2["geom__x"], data["geom__x"])
    assert data2["name"] == data["name"]
    np.testing.assert_array_equal(
        data2["dtg"].astype("datetime64[ms]"), data["dtg"]
    )


def test_arrow_ipc_file_and_dataset_export(tmp_path):
    ds = GeoDataset(n_shards=2)
    ds.create_schema("t", SPEC)
    ds.insert("t", _data(80))
    path = str(tmp_path / "out.arrow")
    ds.export_arrow("t", path, "age >= 30")
    table = arrow_io.read_ipc(path)
    expect = ds.count("t", "age >= 30")
    assert table.num_rows == expect
    assert table.schema.metadata[b"geomesa:spec"].decode().startswith("name:String")

    # re-ingest into a second dataset
    ds2 = GeoDataset(n_shards=2)
    ds2.create_schema("t", SPEC)
    assert ds2.ingest_arrow("t", path) == expect
    assert ds2.count("t") == expect
    assert sorted(ds2.unique("t", "name")) == sorted(
        v for v in set(ds.query("t", "age >= 30").to_dict()["name"])
    )


def test_delta_writer_merge():
    ft = FeatureType.from_spec("t", SPEC)
    dicts = {}
    w = arrow_io.DeltaWriter(ft, dicts)
    chunks = []
    for seed in range(3):
        data = _data(20, seed)
        data["name"] = [f"batch{seed}_{i % 3}" for i in range(20)]
        batch = encode_batch(ft, data, dicts)
        chunks.append(w.write(batch))
    chunks.append(w.close())
    merged = arrow_io.DeltaWriter.merge(chunks)
    assert merged.num_rows == 60
    names = merged.column("name").to_pylist()
    assert "batch0_0" in names and "batch2_2" in names
    # later chunks carry only dictionary deltas, not the full vocab: chunk 2's
    # payload must not re-ship chunk 0's entries
    assert b"batch0_0" in chunks[0]
    assert b"batch0_0" not in chunks[2]


def test_arrow_polygon_without_wkt_roundtrip():
    # ingest path that produces only x/y reference points (no __wkt column)
    ft = FeatureType.from_spec("t", "name:String,*geom:Polygon")
    dicts = {}
    batch = encode_batch(
        ft, {"name": ["a", "b"], "geom__x": [1.0, 2.0], "geom__y": [3.0, 4.0]}, dicts
    )
    rb = arrow_io.batch_to_arrow(ft, batch, dicts)  # must not raise
    assert rb.num_rows == 2
    data2, _ = arrow_io.table_to_data(ft, rb)
    np.testing.assert_allclose(data2["geom__x"], [1.0, 2.0])


def test_fs_attribute_value_with_slash(tmp_path):
    fs = FileSystemStorage(str(tmp_path))
    ft = FeatureType.from_spec("t", "name:String,dtg:Date,*geom:Point")
    fs.create(ft, CompositeScheme([DateTimeScheme("day"), AttributeScheme("name")]))
    fs.write("t", {
        "name": ["a/b", "../../evil", "ok"],
        "dtg": np.array(["2020-01-05"] * 3, "datetime64[ms]"),
        "geom__x": [1.0, 2.0, 3.0],
        "geom__y": [1.0, 2.0, 3.0],
    })
    # no files escape the dataset tree
    import os

    for root, _, files in [(r, d, f) for r, d, f in __import__("os").walk(str(tmp_path))]:
        assert os.path.realpath(root).startswith(os.path.realpath(str(tmp_path)))
    assert fs.read("t").num_rows == 3
    assert fs.read("t", "name = 'a/b'").num_rows >= 1
    pruned = fs.prune("t", "name = 'a/b'")
    assert len(pruned) == 1


@pytest.mark.parametrize("scheme_cfg", [
    {"kind": "datetime", "step": "day"},
    {"kind": "z2", "bits": 3},
    {"kind": "attribute", "attr": "name"},
    {"kind": "composite", "schemes": [
        {"kind": "datetime", "step": "day"}, {"kind": "attribute", "attr": "name"},
    ]},
])
def test_fs_storage_round_trip(tmp_path, scheme_cfg):
    fs = FileSystemStorage(str(tmp_path))
    ft = FeatureType.from_spec("t", SPEC)
    fs.create(ft, scheme_from_config(scheme_cfg))
    data = _data(200)
    fs.write("t", data, fids=[f"f{i}" for i in range(200)])
    assert fs.count("t") == 200
    assert len(fs.partitions("t")) > 1

    table = fs.read("t")
    assert table.num_rows == 200

    ds = GeoDataset(n_shards=2)
    n = fs.load_into(ds, "t")
    assert n == 200
    assert ds.count("t", "age < 30") == int((data["age"] < 30).sum())


def test_fs_partition_pruning_datetime(tmp_path):
    fs = FileSystemStorage(str(tmp_path))
    ft = FeatureType.from_spec("t", SPEC)
    fs.create(ft, DateTimeScheme("day"))
    fs.write("t", _data(300))
    all_parts = fs.partitions("t")
    pruned = fs.prune("t", "dtg DURING 2020-01-05T00:00:00Z/2020-01-07T00:00:00Z")
    assert 0 < len(pruned) < len(all_parts)
    assert set(pruned) <= set(all_parts)
    # pruned read still returns every matching row
    table = fs.read("t", "dtg DURING 2020-01-05T00:00:00Z/2020-01-07T00:00:00Z")
    dtg = _data(300)["dtg"].astype(np.int64)
    lo, hi = parse_iso_ms("2020-01-05"), parse_iso_ms("2020-01-07")
    assert table.num_rows >= int(((dtg >= lo) & (dtg <= hi)).sum())


def test_fs_partition_pruning_z2_and_compact(tmp_path):
    fs = FileSystemStorage(str(tmp_path))
    ft = FeatureType.from_spec("t", SPEC)
    fs.create(ft, Z2Scheme(3))
    for seed in range(3):  # several files per partition
        fs.write("t", _data(100, seed))
    pruned = fs.prune("t", "BBOX(geom, -100, 30, -95, 35)")
    assert 0 < len(pruned) < len(fs.partitions("t"))
    n_before = fs.read("t").num_rows
    removed = fs.compact("t")
    assert removed > 0
    assert fs.read("t").num_rows == n_before
    for p in fs.partitions("t"):
        assert len(fs._load_meta("t")["partitions"][p]) == 1


def test_fs_attribute_pruning(tmp_path):
    fs = FileSystemStorage(str(tmp_path))
    ft = FeatureType.from_spec("t", SPEC)
    fs.create(ft, AttributeScheme("name"))
    fs.write("t", _data(100))
    pruned = fs.prune("t", "name = 'n3'")
    assert pruned == ["v_n3"]
    assert fs.read("t", "name = 'n3'").num_rows == sum(
        1 for i in range(100) if i % 7 == 3
    )


def test_fs_attribute_hostile_values(tmp_path):
    # values that collide with sentinels or look like path traversal
    fs = FileSystemStorage(str(tmp_path))
    ft = FeatureType.from_spec("t", "name:String,dtg:Date,*geom:Point")
    fs.create(ft, AttributeScheme("name"))
    fs.write("t", {
        "name": ["__null__", "..", ".", "", "normal"],
        "dtg": np.array(["2020-01-05"] * 5, "datetime64[ms]"),
        "geom__x": [1.0] * 5,
        "geom__y": [2.0] * 5,
    })
    import os

    data_dir = os.path.join(str(tmp_path), "t", "data")
    # every partition dir is a direct, non-traversing child of data/
    for p in fs.partitions("t"):
        full = os.path.realpath(os.path.join(data_dir, p))
        assert os.path.dirname(full) == os.path.realpath(data_dir)
    assert fs.read("t").num_rows == 5
    # literal '__null__' value is distinct from the null sentinel
    assert fs.read("t", "name = '__null__'").num_rows == 1
    assert fs.read("t", "name = '..'").num_rows == 1
    assert fs.read("t", "name = ''").num_rows == 1
