"""Arrow Flight sidecar tests: in-process server + client round trips
(the coprocessor-protocol analog, SURVEY.md §5 distributed comm backend)."""

import numpy as np
import pyarrow as pa
import pytest

from geomesa_tpu.api.dataset import GeoDataset
from geomesa_tpu.io import bin_format
from geomesa_tpu.sidecar import GeoFlightClient, GeoFlightServer

SPEC = "name:String:index=true,speed:Float,dtg:Date,*geom:Point"


@pytest.fixture()
def server():
    srv = GeoFlightServer(GeoDataset(n_shards=2, prefer_device=False))
    yield srv
    srv.shutdown()


@pytest.fixture()
def client(server):
    with GeoFlightClient(f"grpc+tcp://127.0.0.1:{server.port}") as c:
        yield c


def _feature_table(n=200, seed=0):
    rng = np.random.default_rng(seed)
    ds = GeoDataset(n_shards=1, prefer_device=False)
    ds.create_schema("tmp", SPEC)
    ds.insert("tmp", {
        "name": [f"n{i % 3}" for i in range(n)],
        "speed": rng.uniform(0, 30, n).astype(np.float32),
        "dtg": (np.datetime64("2024-05-01", "ms")
                + rng.integers(0, 20 * 86_400_000, n)),
        "geom": [(float(x), float(y)) for x, y in
                 zip(rng.uniform(-20, 20, n), rng.uniform(-20, 20, n))],
    }, fids=[f"f{i}" for i in range(n)])
    return ds.to_arrow("tmp")


def test_schema_lifecycle(client):
    client.create_schema("t", SPEC)
    assert client.list_schemas() == ["t"]
    assert "name" in client.describe("t")
    client.delete_schema("t")
    assert client.list_schemas() == []


def test_put_query_roundtrip(client):
    client.create_schema("t", SPEC)
    table = _feature_table()
    client.insert_arrow("t", table)
    assert client.count("t") == 200
    got = client.query("t", "BBOX(geom, 0, 0, 20, 20) AND name = 'n1'")
    assert 0 < got.num_rows < 200
    names = set(got["name"].to_pylist())
    assert names == {"n1"}
    # projection
    got2 = client.query("t", properties=["speed"])
    assert "speed" in got2.column_names and "name" not in got2.column_names
    # limit
    assert client.query("t", max_features=7).num_rows == 7


def test_density_stream(client):
    client.create_schema("t", SPEC)
    client.insert_arrow("t", _feature_table())
    grid = client.density("t", bbox=(-20, -20, 20, 20), width=32, height=32)
    assert grid.shape == (32, 32)
    assert grid.sum() == pytest.approx(200)


def test_stats_sketch_over_wire(client):
    client.create_schema("t", SPEC)
    client.insert_arrow("t", _feature_table())
    st = client.stats("t", "MinMax(speed)")
    v = st.value()
    assert 0 <= v["min"] <= v["max"] <= 30
    enum = client.stats("t", "Enumeration(name)")
    assert sum(enum.value().values()) == 200


def test_polygon_region_over_wire(client):
    """The ``region`` option folds server-side into the ecql (before
    fusion keys are built — docs/CACHE.md polygon regions): count/density/
    stats over a WKT polygon match the explicit INTERSECTS conjunct."""
    client.create_schema("t", SPEC)
    client.insert_arrow("t", _feature_table())
    poly = "POLYGON((-15 -15, 15 -12, 12 14, -14 15, -15 -15))"
    exact = client.count("t", f"INTERSECTS(geom, {poly})")
    assert 0 < exact < 200
    assert client.count("t", region=poly) == exact
    grid = client.density("t", region=poly, bbox=(-20, -20, 20, 20),
                          width=32, height=32)
    assert grid.sum() == pytest.approx(exact)
    st = client.stats("t", "Count()", region=poly)
    assert st.value() == exact


def test_bin_export_over_wire(client):
    client.create_schema("t", SPEC)
    client.insert_arrow("t", _feature_table())
    blob = client.export_bin("t", track="name")
    assert len(blob) == 200 * bin_format.RECORD.itemsize
    recs = bin_format.unpack(blob)
    assert len(recs["lat"]) == 200


def test_explain_and_count_estimate(client):
    client.create_schema("t", SPEC)
    client.insert_arrow("t", _feature_table())
    exp = client.explain("t", "BBOX(geom, 0, 0, 10, 10)")
    assert "Chosen index" in exp
    est = client.count("t", "BBOX(geom, 0, 0, 10, 10)", exact=False)
    assert est >= 0


def test_visibility_auths_over_wire(server):
    # visibilities enforced through the ticket's auths
    ds = server.dataset
    ds.create_schema("v", "name:String,*geom:Point")
    ds.insert("v", {"name": ["a", "b"], "geom": [(0.0, 0.0), (1.0, 1.0)]},
              visibilities=["secret", ""])
    with GeoFlightClient(f"grpc+tcp://127.0.0.1:{server.port}") as c:
        assert c.count("v") == 2
        assert c.count("v", auths=[]) == 1
        assert c.query("v", auths=[]).num_rows == 1
        assert c.count("v", auths=["secret"]) == 2


def test_audit_and_metrics_actions(client):
    client.create_schema("t", SPEC)
    client.insert_arrow("t", _feature_table())
    client.count("t")
    events = client.audit()
    assert events and events[-1]["type_name"] == "t"
    m = client.metrics()
    assert m.get("ingest.features", 0) >= 200


def test_flight_info_discovery(server, client):
    client.create_schema("t", SPEC)
    infos = list(server.dataset and client._client.list_flights())
    assert len(infos) == 1
    # the advertised ticket streams the full schema
    client.insert_arrow("t", _feature_table())
    table = client._client.do_get(infos[0].endpoints[0].ticket).read_all()
    assert table.num_rows == 200


def test_unknown_op_errors(client):
    client.create_schema("t", SPEC)
    import json

    import pyarrow.flight as fl

    with pytest.raises(fl.FlightServerError):
        client._client.do_get(
            fl.Ticket(json.dumps({"op": "nope", "schema": "t"}).encode())
        ).read_all()


def test_streamed_export_chunks_partitioned(monkeypatch):
    """PROTOCOL §3 / DeltaWriter parity: a partitioned store's Flight
    export arrives as many bounded record batches (partition-at-a-time,
    re-chunked to GEOMESA_ARROW_BATCH_ROWS) — the server never builds the
    full result table."""
    import json

    import pyarrow.flight as fl

    monkeypatch.setenv("GEOMESA_ARROW_BATCH_ROWS", "10000")
    rng = np.random.default_rng(2)
    n = 120_000
    ds = GeoDataset(n_shards=4, prefer_device=False)
    ds.create_schema(
        "p", "name:String,dtg:Date,*geom:Point;geomesa.partition='time'"
    )
    ds.insert("p", {
        "name": [f"n{i % 3}" for i in range(n)],
        "dtg": (np.datetime64("2024-01-01", "ms")
                + rng.integers(0, 60 * 86_400_000, n)),
        "geom__x": rng.uniform(-20, 20, n),
        "geom__y": rng.uniform(-20, 20, n),
    }, fids=np.arange(n).astype(str))
    ds.flush()
    srv = GeoFlightServer(ds)
    try:
        client = fl.FlightClient(f"grpc+tcp://127.0.0.1:{srv.port}")
        ticket = fl.Ticket(json.dumps({"op": "query", "schema": "p"}).encode())
        rows = 0
        sizes = []
        for chunk in client.do_get(ticket):
            sizes.append(chunk.data.num_rows)
            rows += chunk.data.num_rows
        assert rows == n
        assert len(sizes) >= n // 10000  # many bounded chunks, not one table
        assert max(sizes) <= 10000
        client.close()
    finally:
        srv.shutdown()
