"""f32-uncertainty band certificate (r4): the device evaluates f64 columns
at f32; rows whose value collides with an f32-rounded query bound are the
only ones it can misclassify. The executor counts them once per (plan,
store version) — zero certifies the device result exact, nonzero reroutes
to the f64 host path. r1-r3 silently over-counted one bbox-edge row in the
20M bench because of exactly this.
"""

import numpy as np
import pytest

from geomesa_tpu import GeoDataset
from geomesa_tpu.filter.ecql import parse_iso_ms

SPEC = "v:Double,dtg:Date,*geom:Point"


def _mk(xs, ys, vs=None):
    n = len(xs)
    ds = GeoDataset(n_shards=2)
    ds.create_schema("t", SPEC)
    ds.insert("t", {
        "v": np.asarray(vs if vs is not None else np.zeros(n), np.float64),
        "dtg": np.full(n, parse_iso_ms("2022-01-01")).astype("datetime64[ms]"),
        "geom__x": np.asarray(xs, np.float64),
        "geom__y": np.asarray(ys, np.float64),
    }, fids=np.arange(n).astype(str))
    ds.flush()
    return ds


def test_bbox_edge_row_exact():
    """A point just OUTSIDE the bbox whose f32 image sits ON the bound must
    not be counted (f32 compare alone would include it)."""
    eps = 1e-9
    xs = [-90.0, -80.0 + eps, -80.0 - eps, -80.0, -70.0]
    ys = [35.0, 35.0, 35.0, 35.0, 35.0]
    assert np.float32(-80.0 + eps) == np.float32(-80.0)  # collides
    ds = _mk(xs, ys)
    q = "BBOX(geom, -100, 30, -80, 40)"
    # truth: -90, -80-eps, -80 inside; -80+eps and -70 outside
    assert ds.count("t", q) == 3
    fc = ds.query("t", q)
    assert sorted(fc.fids) == ["0", "2", "3"]
    # the band info was computed and found surviving uncertain rows
    st = ds._store("t")
    infos = st.__dict__.get("_band_verdicts", {}).values()
    assert any(len(v) for v in infos)


def test_clean_data_keeps_device_path():
    """Data with no f32-bound collisions certifies band-free: the device
    path stays in use (verdict True)."""
    rng = np.random.default_rng(3)
    ds = _mk(rng.uniform(-120, -70, 5000), rng.uniform(25, 50, 5000))
    q = "BBOX(geom, -100.5, 30.5, -80.5, 40.5)"
    x = ds._store("t")._all.columns["geom__x"]
    y = ds._store("t")._all.columns["geom__y"]
    want = int(((x >= -100.5) & (x <= -80.5) & (y >= 30.5) & (y <= 40.5)).sum())
    assert ds.count("t", q) == want
    verdicts = ds._store("t").__dict__.get("_band_verdicts", {})
    assert verdicts and all(len(v) == 0 for v in verdicts.values())


def test_float64_attribute_boundary():
    eps = 1e-12
    vs = [1.0, 2.0 + eps, 2.0 - eps, 2.0, 3.0]
    assert np.float32(2.0 + eps) == np.float32(2.0)
    ds = _mk(np.zeros(5), np.zeros(5), vs)
    assert ds.count("t", "v <= 2.0") == 3      # 1.0, 2.0-eps, 2.0
    assert ds.count("t", "v = 2.0") == 1
    assert ds.count("t", "v > 2.0") == 2       # 2.0+eps, 3.0


def test_not_polarity_band():
    eps = 1e-9
    xs = [-80.0 + eps, -90.0]
    ds = _mk(xs, [35.0, 35.0])
    # NOT bbox: the just-outside point must be counted
    assert ds.count("t", "NOT (BBOX(geom, -100, 30, -80, 40))") == 1


def test_band_exact_on_binspace_mesh():
    """The 2-D (shard, bin) mesh path must excise band rows like the GSPMD
    kernel (r4 review): one f32-colliding row outside the box must not be
    counted on a meshed dataset."""
    from geomesa_tpu.parallel import binspace

    eps = 1e-9
    mesh = binspace.mesh_2d(2, 2)
    ds = GeoDataset(mesh=mesh, n_shards=2)
    ds.create_schema("t", SPEC)
    n = 4_000
    rng = np.random.default_rng(5)
    xs = np.concatenate([rng.uniform(-120, -70, n - 1), [-80.0 + eps]])
    ys = np.concatenate([rng.uniform(25, 50, n - 1), [35.0]])
    ds.insert("t", {
        "v": np.zeros(n), "geom__x": xs, "geom__y": ys,
        "dtg": np.full(n, parse_iso_ms("2022-01-01")).astype("datetime64[ms]"),
    }, fids=np.arange(n).astype(str))
    ds.flush()
    q = "BBOX(geom, -100, 30, -80, 40)"
    want = int(((xs >= -100) & (xs <= -80) & (ys >= 30) & (ys <= 40)).sum())
    assert ds.count("t", q) == want
