"""Fleet-level observability tests (docs/OBSERVABILITY.md): per-device
utilization accounting, executor-slot occupancy, the per-query cost
ledger, the SLO burn-rate monitor, histogram exemplars, and the
/debug/devices + filtered /debug/queries surfaces.

Runs on the conftest-forced 8-virtual-device CPU mesh, so the sharded
fan-out's per-device attribution is exercised for real.
"""

import json

import numpy as np
import pytest

from geomesa_tpu import (
    GeoDataset, config, metrics, slo, tracing, utilization,
)
from geomesa_tpu.filter.ecql import parse_iso_ms
from geomesa_tpu.index.partitioned import PartitionedFeatureStore

BBOX = "BBOX(geom, -100, 30, -80, 45)"


def _mk_ds(n=4000, partitioned=False, seed=9, n_shards=2):
    spec = "name:String,weight:Float,dtg:Date,*geom:Point"
    if partitioned:
        spec += ";geomesa.partition='time'"
    ds = GeoDataset(n_shards=n_shards)
    ds.create_schema("t", spec)
    rng = np.random.default_rng(seed)
    lo, hi = parse_iso_ms("2020-01-01"), parse_iso_ms("2020-03-01")
    ds.insert("t", {
        "name": rng.choice(["a", "b"], n),
        "weight": rng.uniform(0, 1, n).astype(np.float32),
        "geom__x": rng.uniform(-120, -70, n),
        "geom__y": rng.uniform(25, 50, n),
        "dtg": rng.integers(lo, hi, n).astype("datetime64[ms]"),
    }, fids=np.arange(n).astype(str))
    ds.flush("t")
    return ds


# ---------------------------------------------------------------------------
# utilization interval math
# ---------------------------------------------------------------------------


def test_busy_fraction_window_math(monkeypatch):
    utilization.reset()
    now = [1000.0]
    monkeypatch.setattr(utilization, "_clock", lambda: now[0])
    with config.DEVICE_BUSY_WINDOW.scoped("10"):
        # 2s busy ending at t=1000 -> fraction 0.2 over the 10s window
        utilization.record_device(3, 2.0)
        frac = utilization.snapshot()["devices"]["3"]["busy_fraction"]
        assert frac == pytest.approx(0.2, abs=1e-6)
        # window start (999) bisects the interval: 1 of its 2 busy
        # seconds remains inside -> fraction 0.1
        now[0] = 1009.0
        u = utilization._devices[3]
        assert u.fraction() == pytest.approx(0.1, abs=1e-6)
        # fully rolled out
        now[0] = 1020.0
        assert u.fraction() == 0.0
        # totals never roll: the cumulative busy_s survives the window
        assert u.busy_s == pytest.approx(2.0)
        # overlapping concurrent intervals clamp at 1.0
        utilization.record_device(4, 8.0)
        utilization.record_device(4, 8.0)
        assert utilization._devices[4].fraction() == 1.0


def test_device_busy_feeds_gauge_and_trace_cost():
    utilization.reset()
    with config.TRACE_ENABLED.scoped("true"):
        with tracing.start("op_cost_test"):
            with utilization.device_busy(6):
                pass
            cost = tracing.current_cost()
    assert "device_ms.6" in cost
    g = metrics.registry().gauge(f"{metrics.DEVICE_BUSY_PREFIX}.6")
    assert 0.0 <= g.value <= 1.0
    snap = utilization.snapshot()
    assert snap["devices"]["6"]["intervals"] == 1


def test_sharded_scan_attributes_busy_time_across_devices(tmp_path):
    """The 8-virtual-device mesh: a sharded partitioned scan must leave
    busy intervals on MORE THAN ONE device (the CI smoke gate's
    in-process twin)."""
    import jax

    if len(jax.devices()) < 2:
        pytest.skip("needs the multi-device mesh")
    utilization.reset()
    ds = _mk_ds(20_000, partitioned=True)
    st = ds._store("t")
    assert isinstance(st, PartitionedFeatureStore)
    st.max_resident = 1
    st._spill_dir = str(tmp_path / "spill")
    n = ds.count("t", BBOX)
    assert n > 0
    busy = {k: v for k, v in utilization.snapshot()["devices"].items()
            if v["busy_s"] > 0}
    assert len(busy) > 1, f"busy time landed on {sorted(busy)} only"


def test_pool_slot_occupancy_and_wait_breakdown():
    utilization.reset()
    ds = _mk_ds(2000)
    with config.SERVING_EXECUTORS.scoped("2"):
        s = ds.serving.start()
        try:
            futs = [s.submit(lambda: ds.count("t", BBOX), user="u",
                             op="count") for _ in range(6)]
            [f.result(60) for f in futs]
        finally:
            s.stop()
    snap = utilization.snapshot()
    assert snap["slots"], "no slot occupancy recorded"
    assert sum(v["intervals"] for v in snap["slots"].values()) >= 6
    # queue-wait half of the breakdown recorded one sample per query
    assert snap["breakdown"]["waits"] >= 6
    assert snap["breakdown"]["device_time_s"] >= 0.0


# ---------------------------------------------------------------------------
# per-query cost ledger
# ---------------------------------------------------------------------------


def test_cost_ledger_rolls_into_user_rollups(tmp_path):
    ds = _mk_ds(20_000, partitioned=True)
    st = ds._store("t")
    st.max_resident = 1
    st._spill_dir = str(tmp_path / "spill")
    with config.TRACE_ENABLED.scoped("true"), config.USER.scoped("alice"):
        ds.count("t", BBOX)
    roll = ds.serving.user_rollups()["alice"]
    cost = roll["cost"]
    assert any(k.startswith("device_ms.") for k in cost), cost
    assert cost.get("partitions_scanned", 0) >= 2
    assert cost.get("bytes_staged", 0) > 0
    assert "partitions_pruned" in cost


def test_cache_hit_lands_in_cost_ledger():
    ds = _mk_ds(4000)
    with config.TRACE_ENABLED.scoped("true"), \
            config.CACHE_ENABLED.scoped("true"), \
            config.USER.scoped("bob"):
        ds.count("t", BBOX)
        ds.count("t", BBOX)  # whole-result hit
    cost = ds.serving.user_rollups()["bob"]["cost"]
    assert cost.get("cache_hits", 0) >= 1, cost


def test_explain_carries_cost_section():
    ds = _mk_ds(2000)
    with config.TRACE_ENABLED.scoped("true"):
        out = ds.explain("t", BBOX, analyze=True)
    assert "Cost" in out
    assert "device_ms." in out


# ---------------------------------------------------------------------------
# SLO burn-rate monitor
# ---------------------------------------------------------------------------


def _slo_scope(op, target_ms):
    return config.SystemProperty(
        f"geomesa.slo.{op}.p99.ms", None
    ).scoped(str(target_ms))


def test_slo_target_resolution():
    with _slo_scope("slo_res_op", 25):
        t = config.slo_targets()
        assert t["slo_res_op"] == 25.0


def test_burn_rate_window_arithmetic(monkeypatch):
    slo.reset()
    now = [10_000.0]
    monkeypatch.setattr(slo, "_clock", lambda: now[0])
    op = "slo_burn_op"
    hist = metrics.registry().histogram(f"trace.{op}")
    with _slo_scope(op, 100), \
            config.SLO_WINDOW_FAST_S.scoped("300"), \
            config.SLO_WINDOW_SLOW_S.scoped("3600"):
        m = slo.monitor()
        # t0: 100 healthy observations (1 ms, far under the 100 ms target)
        for _ in range(100):
            hist.observe(0.001)
        m.evaluate(force=True)
        assert m.burn(op, 300) == 0.0
        # t0+200s (t0 still inside the fast window): 96 healthy + 4 bad
        # on top of the 100 healthy -> 4/200 bad -> burn 2 over both
        # windows (the whole history sits inside each)
        now[0] += 200
        for _ in range(96):
            hist.observe(0.001)
        for _ in range(4):
            hist.observe(10.0)
        m.evaluate(force=True)
        assert m.burn(op, 300) == pytest.approx(
            (4 / 200) / slo.P99_BUDGET)
        assert m.burn(op, 3600) == pytest.approx(2.0)
        # t0+800s: the bad burst has rolled OUT of the fast window but is
        # still inside the slow one — fast burn recovers, slow remembers
        now[0] += 600
        hist.observe(0.001)
        m.evaluate(force=True)
        assert m.burn(op, 300) == 0.0
        slow_burn = m.burn(op, 3600)
        assert slow_burn > 1.0
        # the slo.burn.<op> gauge mirrors the fast window
        g = metrics.registry().gauge(f"{metrics.SLO_BURN_PREFIX}.{op}")
        assert g.value == 0.0
    slo.reset()


def test_healthz_degrades_when_fast_window_burns(monkeypatch):
    from geomesa_tpu import obs

    slo.reset()
    op = "slo_hot_op"
    hist = metrics.registry().histogram(f"trace.{op}")
    with _slo_scope(op, 1):
        for _ in range(10):
            hist.observe(5.0)  # every observation blows the 1 ms target
        h = obs.health()
        assert h["slo"][op]["hot"] is True
        assert op in h["slo_burning"]
        assert h["status"] == "degraded"
    slo.reset()
    # target retracted: healthy again (absent breakers/other burns)
    h = obs.health()
    assert op not in h.get("slo", {})


def test_over_count_snaps_target_to_bucket():
    h = metrics.Histogram()
    for v in (0.004, 0.004, 0.2, 0.2, 0.2):
        h.observe(v)
    # target 4 ms snaps to the 5 ms bucket bound: the two 4 ms
    # observations are within, the three 200 ms ones are over
    total, over = slo._over_count(h, 4.0)
    assert (total, over) == (5, 3)
    # a target beyond the largest bucket counts only +Inf overflow as over
    total, over = slo._over_count(h, 60_000.0)
    assert (total, over) == (5, 0)


# ---------------------------------------------------------------------------
# exemplars
# ---------------------------------------------------------------------------


def test_histogram_exemplar_links_bucket_to_trace():
    reg = metrics.MetricRegistry(prefix="t")
    h = reg.histogram("trace.exemplar_op")
    h.observe(0.002)                      # no exemplar
    h.observe(0.2, trace_id="abc123def")  # exemplar on the 0.25 bucket
    text = reg.prometheus(exemplars=True)
    ex_lines = [ln for ln in text.splitlines() if "# {" in ln]
    assert len(ex_lines) == 1
    assert 'le="0.25"' in ex_lines[0]
    assert 'trace_id="abc123def"' in ex_lines[0]
    assert "0.200000" in ex_lines[0]
    # exemplar-free histograms render exactly as before (OpenMetrics)
    plain = [ln for ln in text.splitlines() if 'le="0.0025"' in ln]
    assert plain == ['t_trace_exemplar_op_seconds_bucket{le="0.0025"} 1']
    # the CLASSIC text format stays exemplar-free: a '#' suffix on a
    # sample line is a parse error for standard version=0.0.4 scrapers
    assert "# {" not in reg.prometheus()


def test_metrics_route_negotiates_openmetrics_for_exemplars():
    from geomesa_tpu import obs

    metrics.observe("trace.negotiate_op", 0.01, trace_id="feedbeef")
    # no Accept header: classic text, no exemplars
    code, ctype, body = obs.handle("/metrics")
    assert code == 200 and "0.0.4" in ctype
    assert b"# {" not in body
    # OpenMetrics negotiated: exemplars + the required EOF trailer
    code, ctype, body = obs.handle(
        "/metrics", accept="application/openmetrics-text"
    )
    assert code == 200 and ctype.startswith("application/openmetrics-text")
    assert b'trace_id="feedbeef"' in body
    assert body.endswith(b"# EOF\n")


def test_traced_query_leaves_exemplars(tmp_path):
    ds = _mk_ds(2000)
    with config.TRACE_ENABLED.scoped("true"):
        ds.count("t", BBOX)
        tid = tracing.last_trace().trace_id
    snap = metrics.registry().histogram("trace.count").snapshot()
    tids = {e[0] for e in snap["exemplars"].values()}
    assert tid in tids


# ---------------------------------------------------------------------------
# endpoints
# ---------------------------------------------------------------------------


def test_debug_devices_endpoint():
    import urllib.request

    from geomesa_tpu import obs

    ds = _mk_ds(1000)
    ds.count("t", BBOX)
    srv = obs.serve(ds, port=0, background=True)
    try:
        port = srv.server_address[1]
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/debug/devices", timeout=10
        ) as r:
            assert r.status == 200
            d = json.loads(r.read())
        assert "devices" in d and "slots" in d and "breakdown" in d
        assert "slo" in d
        assert d["devices"], "no device usage recorded"
    finally:
        srv.shutdown()


def test_debug_queries_user_and_op_filters():
    from geomesa_tpu import obs

    ds = _mk_ds(2000)
    with config.USER.scoped("alice"):
        ds.count("t", BBOX)
        ds.density("t", BBOX, bbox=(-100, 30, -80, 45), width=16, height=16)
    with config.USER.scoped("bob"):
        ds.count("t", BBOX)
    all_q = obs.debug_queries(ds, n=50)
    assert len(all_q["queries"]) >= 3
    alice = obs.debug_queries(ds, n=50, user="alice")
    assert alice["queries"]
    assert all(e["user"] == "alice" for e in alice["queries"])
    assert set(alice["users"]) == {"alice"}
    dens = obs.debug_queries(ds, n=50, op="density")
    assert dens["queries"]
    assert all(e["hints"]["op"] == "density" for e in dens["queries"])
    # filters apply BEFORE the n cap
    one = obs.debug_queries(ds, n=1, user="alice", op="count")
    assert len(one["queries"]) == 1
    e = one["queries"][0]
    assert e["user"] == "alice" and e["hints"]["op"] == "count"
    # the HTTP route passes them through
    out = obs.handle("/debug/queries?n=5&user=bob&op=count", ds)
    assert out[0] == 200
    body = json.loads(out[2])
    assert all(e["user"] == "bob" for e in body["queries"])


def test_debug_queries_user_filter_joins_slow_traces():
    """Slow traces carry no user; the ?user= filter joins through the
    trace_id shared with that user's audit events, so one tenant's view
    never includes another's slow span trees."""
    from geomesa_tpu import obs

    tracing.clear_slow_traces()
    ds = _mk_ds(2000)
    with config.TRACE_ENABLED.scoped("true"), \
            config.TRACE_SLOW_MS.scoped("0"):
        with config.USER.scoped("alice"):
            ds.count("t", BBOX)
            alice_tid = tracing.last_trace().trace_id
        with config.USER.scoped("bob"):
            ds.count("t", BBOX)
            bob_tid = tracing.last_trace().trace_id
    out = obs.debug_queries(ds, n=50, user="alice")
    tids = {s["trace_id"] for s in out["slow_traces"]}
    assert alice_tid in tids
    assert bob_tid not in tids
