"""Pallas kernel parity (interpret mode on CPU) and the shape-generic
polygon predicate on the device path."""

import numpy as np
import pytest

from geomesa_tpu.kernels import pallas_kernels as pk
from geomesa_tpu.utils.geometry import parse_wkt


def _edge_table(wkt):
    p = parse_wkt(wkt)
    _, packed = pk.polygon_edge_tables(p)  # the builder production uses
    return packed, p


TRIANGLE = "POLYGON ((0 0, 10 0, 5 8, 0 0))"
DONUT = (
    "POLYGON ((0 0, 20 0, 20 20, 0 20, 0 0), (5 5, 15 5, 15 15, 5 15, 5 5))"
)


@pytest.mark.parametrize("wkt", [TRIANGLE, DONUT])
def test_pip_pallas_interpret_parity(wkt):
    import jax.numpy as jnp

    edges, poly = _edge_table(wkt)
    rng = np.random.default_rng(3)
    x = rng.uniform(-2, 22, 3000)
    y = rng.uniform(-2, 22, 3000)
    got = np.asarray(
        pk.pip_mask(jnp.asarray(x), jnp.asarray(y), edges, interpret=True)
    )
    want = poly.contains_points(x, y)
    # even-odd parity differs from contains() only exactly on the boundary;
    # random uniform points are almost surely off-boundary
    assert (got == want).mean() > 0.999


def test_pip_pallas_2d_shape():
    import jax.numpy as jnp

    edges, poly = _edge_table(TRIANGLE)
    rng = np.random.default_rng(5)
    x = rng.uniform(-2, 12, (4, 700))
    y = rng.uniform(-2, 10, (4, 700))
    got = np.asarray(
        pk.pip_mask(jnp.asarray(x), jnp.asarray(y), edges, interpret=True)
    )
    assert got.shape == (4, 700)
    want = poly.contains_points(x.ravel(), y.ravel()).reshape(4, 700)
    assert (got == want).mean() > 0.999


def test_polygon_predicate_device_2d():
    """The compiled INTERSECTS predicate must run on [S, L] device columns
    (no host fallback) — regression for the 1-D-only broadcast."""
    import jax.numpy as jnp

    from geomesa_tpu.filter import parse_ecql
    from geomesa_tpu.filter.compile import compile_filter
    from geomesa_tpu.schema.feature_type import FeatureType

    ft = FeatureType.from_spec("t", "*geom:Point")
    f = parse_ecql(f"INTERSECTS(geom, {TRIANGLE})")
    compiled = compile_filter(f, ft, {})
    rng = np.random.default_rng(7)
    x = rng.uniform(-2, 12, (3, 500))
    y = rng.uniform(-2, 10, (3, 500))
    dev = compiled({"geom__x": jnp.asarray(x), "geom__y": jnp.asarray(y)}, jnp)
    host = compiled(
        {"geom__x": x.ravel(), "geom__y": y.ravel()}, np
    ).reshape(3, 500)
    np.testing.assert_array_equal(np.asarray(dev), host)


def test_polygon_query_end_to_end():
    """Full dataset query with a non-rectangular polygon (device path when
    available, host fallback otherwise — results identical)."""
    from geomesa_tpu import GeoDataset

    rng = np.random.default_rng(11)
    n = 20_000
    data = {
        "geom__x": rng.uniform(-2, 22, n),
        "geom__y": rng.uniform(-2, 22, n),
        "dtg": np.full(n, 1577836800000, "datetime64[ms]"),
    }
    ds = GeoDataset(n_shards=4)
    ds.create_schema("p", "dtg:Date,*geom:Point")
    ds.insert("p", data, fids=np.arange(n).astype(str))
    ds.flush("p")
    cnt = ds.count("p", f"INTERSECTS(geom, {DONUT})")
    inside_outer = (
        (data["geom__x"] >= 0) & (data["geom__x"] <= 20)
        & (data["geom__y"] >= 0) & (data["geom__y"] <= 20)
    )
    inside_hole = (
        (data["geom__x"] > 5) & (data["geom__x"] < 15)
        & (data["geom__y"] > 5) & (data["geom__y"] < 15)
    )
    want = int((inside_outer & ~inside_hole).sum())
    assert abs(cnt - want) <= 2  # boundary-exact points may differ


def test_use_pallas_gate(monkeypatch):
    monkeypatch.setenv("GEOMESA_PALLAS", "0")
    assert not pk.use_pallas()


def test_use_pallas_sharded_gate(monkeypatch):
    monkeypatch.setenv("GEOMESA_PALLAS", "1")
    with pk.sharded_execution(True):
        assert not pk.use_pallas()


def test_edges_fit():
    assert pk.edges_fit(100)
    assert not pk.edges_fit(100_000)


def test_pallas_pip_under_sharded_mesh(monkeypatch):
    """r4: polygon fine-filtering keeps the hand kernel under a
    NamedSharding'd mesh via an inner shard_map (interpret mode here;
    device dispatch is identical modulo the interpret flag)."""
    import jax
    from jax.sharding import Mesh

    from geomesa_tpu import GeoDataset
    from geomesa_tpu.filter.ecql import parse_iso_ms

    monkeypatch.setenv("GEOMESA_PALLAS_INTERPRET", "1")
    calls = {"n": 0}
    real = pk.pip_mask_sharded

    def spy(*a, **kw):
        calls["n"] += 1
        return real(*a, **kw)

    monkeypatch.setattr(pk, "pip_mask_sharded", spy)
    mesh = Mesh(np.array(jax.devices()[:2]), axis_names=("shard",))
    rng = np.random.default_rng(8)
    n = 3_000
    ds = GeoDataset(mesh=mesh, n_shards=2)
    ds.create_schema("t", "dtg:Date,*geom:Point")
    x = rng.uniform(-10, 10, n)
    y = rng.uniform(-10, 10, n)
    ds.insert("t", {
        "dtg": np.full(n, parse_iso_ms("2022-01-01")).astype("datetime64[ms]"),
        "geom__x": x, "geom__y": y,
    }, fids=np.arange(n).astype(str))
    ds.flush()
    # non-rectangular polygon -> the crossing-parity kernel, not the bbox
    # fast path
    tri = "POLYGON ((-5 -5, 5 -5, 0 5, -5 -5))"
    got = ds.count("t", f"INTERSECTS(geom, {tri})")
    # independent even-odd crossing oracle over the triangle's edges
    verts = [(-5.0, -5.0), (5.0, -5.0), (0.0, 5.0), (-5.0, -5.0)]
    crossings = np.zeros(n, np.int64)
    for (x1, y1), (x2, y2) in zip(verts[:-1], verts[1:]):
        cond = (y1 > y) != (y2 > y)
        with np.errstate(divide="ignore", invalid="ignore"):
            xint = x1 + (y - y1) * (x2 - x1) / np.where(y2 == y1, 1.0, y2 - y1)
        crossings += (cond & (x < xint)).astype(np.int64)
    inside = crossings % 2 == 1
    assert got == int(inside.sum())
    assert calls["n"] >= 1, "sharded pallas path did not execute"
