"""Warm-path executor proof (docs/PERF.md): shape-bucketed, version-stable,
LRU-managed kernel caching + the double-buffered partition pipeline.

The contract under test:

* two same-shape queries compile once (registry hit on the repeat);
* distinct-but-same-bucket queries share one compiled kernel (the kNN
  kernel parameterizes location/radius as traced scalars, and shape
  bucketing folds their differing window counts into one K bucket);
* a store MUTATION does not recompile anything (kernel keys carry no store
  version — only the dictionary-growth fingerprint);
* dictionary growth DOES recompile (string predicates bake resolved codes
  into the closure — reusing it across growth would be a stale-closure bug);
* the partition prefetch pipeline returns bit-identical results to
  sequential execution, and the whole warm path is bit-identical to a cold
  run with bucketing + pipeline disabled.

These are the tier-1 recompile-regression tests: fast, CPU-only, no TPU.
"""

import numpy as np
import pytest

from geomesa_tpu import GeoDataset, config, metrics
from geomesa_tpu.filter.ecql import parse_iso_ms
from geomesa_tpu.kernels.registry import KernelRegistry, bucket_count


def _recompiles() -> int:
    return metrics.registry().counter(metrics.KERNEL_RECOMPILES).value


def _hits() -> int:
    return metrics.registry().counter(metrics.KERNEL_BUCKET_HIT).value


def _mk_data(n: int, seed: int = 11, names=("a", "b", "c")):
    rng = np.random.default_rng(seed)
    lo = parse_iso_ms("2020-01-01")
    hi = parse_iso_ms("2020-02-01")
    return {
        "name": rng.choice(list(names), n),
        "geom__x": rng.uniform(-120, -70, n),
        "geom__y": rng.uniform(25, 50, n),
        "dtg": rng.integers(lo, hi, n).astype("datetime64[ms]"),
        "weight": rng.uniform(0, 1, n).astype(np.float32),
    }


def _mk_ds(n: int = 20_000, partitioned: bool = False, seed: int = 11):
    spec = "name:String,weight:Float,dtg:Date,*geom:Point"
    if partitioned:
        spec += ";geomesa.partition='time'"
    ds = GeoDataset(n_shards=4)
    ds.create_schema("t", spec)
    ds.insert("t", _mk_data(n, seed), fids=np.arange(n).astype(str))
    ds.flush("t")
    return ds


DURING = "dtg DURING 2020-01-05T00:00:00Z/2020-01-25T00:00:00Z"


def _bbox_q(x0, y0, x1, y1):
    return f"BBOX(geom, {x0}, {y0}, {x1}, {y1}) AND {DURING}"


# ---------------------------------------------------------------------------
# registry unit behavior
# ---------------------------------------------------------------------------


def test_bucket_count_ladder():
    with config.COMPACT_BUCKETING.scoped("true"), \
            config.COMPACT_BUCKET_FLOOR.scoped("8"):
        # everything at or below the floor shares one bucket
        assert [bucket_count(k) for k in (0, 1, 2, 5, 8)] == [8] * 5
        # above the floor: powers of two
        assert bucket_count(9) == 16
        assert bucket_count(16) == 16
        assert bucket_count(17) == 32
    with config.COMPACT_BUCKETING.scoped("false"):
        # old behavior: exact pow2, no floor
        assert bucket_count(1) == 1
        assert bucket_count(3) == 4


def test_kernel_registry_lru_evicts_one_at_a_time():
    reg = KernelRegistry(capacity=2)
    reg.put(("site_a", 1), "k1")
    reg.put(("site_a", 2), "k2")
    assert reg.get(("site_a", 1)) == "k1"  # 1 is now MRU
    reg.put(("site_b", 3), "k3")           # evicts LRU = key 2 only
    assert len(reg) == 2
    assert reg.get(("site_a", 2)) is None
    assert reg.get(("site_a", 1)) == "k1"
    assert reg.get(("site_b", 3)) == "k3"
    # per-site trace accounting
    assert reg.traces("site_a") == 2
    assert reg.traces("site_b") == 1


def test_persistent_compile_cache_knob(tmp_path_factory):
    import jax

    from geomesa_tpu.kernels import registry as regmod

    # a session-stable dir: jax keeps writing cache entries here after the
    # test, so it must outlive a per-test tmp_path
    d = str(tmp_path_factory.mktemp("xla_cache"))
    assert regmod.enable_persistent_cache() is None  # unset -> disabled
    with config.COMPILE_CACHE_DIR.scoped(d):
        assert regmod.enable_persistent_cache() == d
    assert jax.config.jax_compilation_cache_dir == d


# ---------------------------------------------------------------------------
# compile behavior through the public API
# ---------------------------------------------------------------------------


def test_same_shape_query_compiles_once():
    ds = _mk_ds()
    q = _bbox_q(-100, 30, -80, 45)
    c1 = ds.count("t", q)
    r0, h0 = _recompiles(), _hits()
    c2 = ds.count("t", q)
    assert c2 == c1 > 0
    assert _recompiles() == r0        # zero new traces
    assert _hits() > h0               # served from the kernel registry


def test_mutation_does_not_recompile():
    ds = _mk_ds()
    q = _bbox_q(-100, 30, -80, 45)
    ds.count("t", q)
    r0 = _recompiles()
    # mutation with NO dictionary growth: known vocab, numeric columns
    ds.insert("t", _mk_data(3_000, seed=12),
              fids=(np.arange(3_000) + 1_000_000).astype(str))
    ds.flush("t")
    c = ds.count("t", q)
    assert c > 0
    assert _recompiles() == r0, "a store mutation must not retrace kernels"


def test_dictionary_growth_does_recompile_string_predicates():
    # the safety side of version-stable keys: string predicates bake
    # resolved dictionary codes, so vocabulary growth must NOT reuse the
    # stale closure
    ds = _mk_ds()
    q = f"name IN ('a', 'zed') AND {DURING}"
    c1 = ds.count("t", q)
    r0 = _recompiles()
    fresh = _mk_data(2_000, seed=13, names=("zed",))
    ds.insert("t", fresh, fids=(np.arange(2_000) + 2_000_000).astype(str))
    ds.flush("t")
    c2 = ds.count("t", q)
    assert c2 > c1  # the new 'zed' rows match now
    assert _recompiles() > r0  # grown vocab -> fresh closure


def test_distinct_same_bucket_queries_share_one_kernel():
    # kNN parameterizes origin/box as traced scalars and shares one cache
    # token; its expanding-radius windows differ per origin (K of 8 vs 16
    # at this data shape), but shape bucketing folds every K <= floor
    # into ONE compiled kernel
    with config.COMPACT_BUCKET_FLOOR.scoped("32"):
        ds = _mk_ds()
        origins = [(-100.0, 35.0), (-92.5, 40.0), (-85.0, 30.5)]
        assert len(ds.knn("t", *origins[0], k=5)) == 5
        r0 = _recompiles()
        for x, y in origins[1:]:
            assert len(ds.knn("t", x, y, k=5)) == 5
        assert _recompiles() == r0, (
            "distinct same-bucket kNN queries must share the compiled kernel"
        )
        # and without bucketing, the same sequence retraces per K shape
        with config.COMPACT_BUCKETING.scoped("false"):
            ds2 = _mk_ds()
            len(ds2.knn("t", *origins[0], k=5))
            r1 = _recompiles()
            for x, y in origins[1:]:
                len(ds2.knn("t", x, y, k=5))
            assert _recompiles() > r1


# ---------------------------------------------------------------------------
# the acceptance proof: >= 3 distinct-but-same-bucket queries, repeated
# after an insert — exactly one trace per (jit site, query), zero
# recompiles on the repeats, bit-identical to the cold A/B run
# ---------------------------------------------------------------------------


@pytest.fixture
def k_floor_64():
    # fold every window count at this data shape (K <= 64 across queries
    # AND partitions) into one bucket, so the one-trace-per-site
    # assertions are exact
    with config.COMPACT_BUCKET_FLOOR.scoped("64"):
        yield


def test_warm_path_proof_zero_recompiles_and_bit_identity(k_floor_64):
    queries = [
        _bbox_q(-100, 30, -80, 45),
        _bbox_q(-103, 31, -82, 44),
        _bbox_q(-97, 29, -78, 46),
    ]
    bbox = (-100.0, 30.0, -80.0, 45.0)

    ds = _mk_ds(partitioned=True)
    st = ds._store("t")
    reg = ds._executor(st).kernel_registry()
    counts1 = [ds.count("t", q) for q in queries]
    grids1 = [np.asarray(ds.density("t", q, bbox=bbox, width=64, height=64))
              for q in queries]
    # one trace per (jit site, query): the count site compiled exactly
    # once per distinct query, never more
    assert reg.traces("count") == len(queries)
    r0 = _recompiles()
    counts2 = [ds.count("t", q) for q in queries]
    grids2 = [np.asarray(ds.density("t", q, bbox=bbox, width=64, height=64))
              for q in queries]
    assert counts2 == counts1
    for a, b in zip(grids1, grids2):
        np.testing.assert_array_equal(a, b)
    assert _recompiles() == r0, "repeat queries must be compile-free"

    # mutate (no dictionary growth), then repeat: STILL zero recompiles
    extra = _mk_data(4_000, seed=21)
    ds.insert("t", extra, fids=(np.arange(4_000) + 500_000).astype(str))
    ds.flush("t")
    r1 = _recompiles()
    counts3 = [ds.count("t", q) for q in queries]
    grids3 = [np.asarray(ds.density("t", q, bbox=bbox, width=64, height=64))
              for q in queries]
    assert _recompiles() == r1, "post-mutation repeats must be compile-free"

    # A/B: a cold dataset holding the same final rows, with bucketing and
    # the prefetch pipeline disabled (the pre-warm-path executor) must
    # produce bit-identical results
    with config.COMPACT_BUCKETING.scoped("false"), \
            config.PIPELINE_PREFETCH.scoped("false"):
        cold = GeoDataset(n_shards=4)
        cold.create_schema(
            "t", "name:String,weight:Float,dtg:Date,*geom:Point"
            ";geomesa.partition='time'"
        )
        base = _mk_data(20_000, seed=11)
        cold.insert("t", base, fids=np.arange(20_000).astype(str))
        cold.insert("t", extra, fids=(np.arange(4_000) + 500_000).astype(str))
        cold.flush("t")
        cold_counts = [cold.count("t", q) for q in queries]
        cold_grids = [
            np.asarray(cold.density("t", q, bbox=bbox, width=64, height=64))
            for q in queries
        ]
    assert counts3 == cold_counts
    for a, b in zip(grids3, cold_grids):
        np.testing.assert_array_equal(a, b)


# ---------------------------------------------------------------------------
# double-buffered partition pipeline
# ---------------------------------------------------------------------------


def test_pipeline_bit_identical_and_prefetches():
    q = _bbox_q(-100, 30, -80, 45)
    bbox = (-100.0, 30.0, -80.0, 45.0)
    with config.MAX_RESIDENT_PARTITIONS.scoped("2"):
        ds = _mk_ds(n=30_000, partitioned=True)
        st = ds._store("t")
        assert len(st.partition_bins()) > 2  # spills + reloads exercised

        pf0 = metrics.registry().counter(metrics.PIPELINE_PREFETCH).value
        with config.PIPELINE_PREFETCH.scoped("true"):
            c_pipe = ds.count("t", q)
            g_pipe = np.asarray(
                ds.density("t", q, bbox=bbox, width=64, height=64))
            f_pipe = ds.query("t", q)
            # staged columns were consumed: partitions after the first
            # loaded while their predecessor executed
            assert metrics.registry().counter(
                metrics.PIPELINE_PREFETCH).value > pf0
        with config.PIPELINE_PREFETCH.scoped("false"):
            c_seq = ds.count("t", q)
            g_seq = np.asarray(
                ds.density("t", q, bbox=bbox, width=64, height=64))
            f_seq = ds.query("t", q)
    assert c_pipe == c_seq > 0
    np.testing.assert_array_equal(g_pipe, g_seq)
    assert len(f_pipe) == len(f_seq)
    assert sorted(f_pipe.fids) == sorted(f_seq.fids)


def test_pipeline_partitions_share_kernels_across_children(k_floor_64):
    # partitions of one store execute the same plan: one trace, many tables
    with config.MAX_RESIDENT_PARTITIONS.scoped("2"):
        ds = _mk_ds(n=30_000, partitioned=True)
        st = ds._store("t")
        ex = ds._executor(st)
        q = _bbox_q(-100, 30, -80, 45)
        assert ds.count("t", q) > 0
        # every partition child executed the count through ONE compiled
        # kernel (shard-length bucketing + shared registry)
        assert ex.kernel_registry().traces("count") == 1


# ---------------------------------------------------------------------------
# aggregate-cache cell queries share the kernel registry (ROADMAP item)
# ---------------------------------------------------------------------------


def test_cache_cell_kernels_survive_epoch_bump():
    ds = _mk_ds()
    q = _bbox_q(-100, 30, -80, 45)
    with config.CACHE_ENABLED.scoped("true"):
        c1 = ds.count("t", q)  # decomposes into cells; traces once per cell
        r0 = _recompiles()
        # mutation drops every cached RESULT (epoch bump) but must keep
        # every compiled cell kernel (version-stable keys)
        ds.insert("t", _mk_data(2_000, seed=31),
                  fids=(np.arange(2_000) + 700_000).astype(str))
        ds.flush("t")
        c2 = ds.count("t", q)
        assert c2 >= c1
        assert _recompiles() == r0, (
            "cold re-decomposition after a mutation must reuse cell kernels"
        )


# ---------------------------------------------------------------------------
# per-site recompile alert (docs/OBSERVABILITY.md; ROADMAP item closed)
# ---------------------------------------------------------------------------


def test_per_site_recompile_counters_and_alert_trip():
    from geomesa_tpu.kernels import registry as kreg

    kreg.reset_alert()
    ds = _mk_ds(n=8_000)
    q = _bbox_q(-100, 30, -80, 45)
    site_counter = metrics.registry().counter(
        f"{metrics.KERNEL_RECOMPILES}.count"
    )
    c0 = site_counter.value
    # threshold 0: the FIRST fresh trace at any site inside one query
    # window trips the alert gauge
    with config.KERNEL_ALERT_THRESHOLD.scoped("0"):
        assert ds.count("t", q) > 0
    assert site_counter.value > c0, "per-site recompile counter must move"
    gauge = metrics.registry().gauge(metrics.KERNEL_RECOMPILE_ALERT)
    assert gauge.value >= 1, "alert gauge must trip past the threshold"
    assert metrics.registry().counter(
        metrics.KERNEL_RECOMPILE_ALERTS
    ).value >= 1
    assert kreg.query_recompiles().get("count", 0) >= 1
    # surfaced in the exposition format (the /metrics contract)
    text = metrics.registry().prometheus()
    assert "geomesa_kernel_recompiles_count " in text
    assert "geomesa_kernel_recompile_alert " in text
    # a healthy (compile-free) warm repeat does NOT clear the latch: the
    # gauge stays visible for the scrape TTL so a trip can't be raced
    # away by the next query's window reset
    with config.KERNEL_ALERT_THRESHOLD.scoped("0"):
        r0 = _recompiles()
        assert ds.count("t", q) > 0
    assert _recompiles() == r0, "warm repeat must be compile-free"
    assert gauge.value >= 1, "alert latch must survive the next query"
    kreg.reset_alert()
    assert gauge.value == 0
