"""Hierarchical pre-aggregation + polygon regions (cache/hierarchy.py,
cache/cells.py decompose_region; docs/CACHE.md).

Tier-1 contracts:

* **zoom-out**: after fine-level queries warm the cells, a coarse query
  over the same region answers from the hierarchy — ZERO residual device
  dispatches, zero scanned rows — and is bit-identical to the uncached
  full-scan result (counts, unweighted density, exact-merge stats,
  density_curve across zoom levels);
* **polygon regions**: count/density/stats over a polygon (the ``region``
  sugar or an explicit INTERSECTS conjunct) match the exact scan
  bit-for-bit — interior cells from the cache, boundary cells scanned
  exactly — including points ON cell edges (the half-open ``[x0, x1)``
  ulp contract) and near polygon edges;
* **invalidation**: an insert/delete drops every pre-merged subtree with
  the flat cells (epoch mechanism) — a promoted parent can never serve a
  stale merge;
* **property**: seeded random pan/zoom/polygon sequences across epochs
  stay bit-identical to a cache-disabled oracle.
"""

import contextlib

import numpy as np
import pytest

from geomesa_tpu import config, metrics
from geomesa_tpu.api.dataset import GeoDataset
from geomesa_tpu.cache import decompose, decompose_region, hierarchy
from geomesa_tpu.filter import parse_ecql
from geomesa_tpu.schema.feature_type import FeatureType


def _counter(name: str) -> int:
    return metrics.registry().counter(name).value


def _dispatches() -> int:
    return _counter(metrics.EXEC_DEVICE_DISPATCH)


@contextlib.contextmanager
def _enabled(per_axis=None):
    """Cache on; optionally coarser decomposition (fewer cells per query)
    so warming stays cheap in tier-1."""
    with contextlib.ExitStack() as st:
        st.enter_context(config.CACHE_ENABLED.scoped("true"))
        if per_axis is not None:
            st.enter_context(
                config.CACHE_CELLS_PER_AXIS.scoped(str(per_axis)))
        yield


#: regional zoom-out shape (per_axis=4): the four 90x45 warm boxes
#: decompose at level 4 (22.5-deg cells), the containing 180x90 zoom-out
#: at level 3 (45-deg cells) — exactly one level coarser
ZOOM = "BBOX(geom, -90, -45, 90, 45)"
WARM4 = [
    "BBOX(geom, -90, -45, 0, 0)", "BBOX(geom, 0, -45, 90, 0)",
    "BBOX(geom, -90, 0, 0, 45)", "BBOX(geom, 0, 0, 90, 45)",
]
#: domain-spanning world query (per_axis=4: level 2, no strips — the
#: closed domain-edge cells own x=180 / y=90)
WORLD = "BBOX(geom, -180, -90, 180, 90)"
WORLD_WARM = [
    "BBOX(geom, -180, -90, 0, 0)", "BBOX(geom, 0, -90, 180, 0)",
    "BBOX(geom, -180, 0, 0, 90)", "BBOX(geom, 0, 0, 180, 90)",
]

POLY = "POLYGON((-100 -40, 100 -50, 120 60, -120 55, -100 -40))"
POLY_Q = f"INTERSECTS(geom, {POLY})"


@pytest.fixture()
def ds(rng):
    """Seeded global points, including rows exactly on level-4 cell edges
    (span 22.5 deg) and on the domain edges (x=180, y=90) the closed
    last-cell contract owns."""
    ds = GeoDataset(n_shards=2)
    ds.create_schema("pts", "type:String,weight:Float,*geom:Point")
    r = np.random.default_rng(11)
    n = 2500
    edges = np.arange(-90.0, 90.1, 22.5)
    bx, by = np.meshgrid(edges, edges[:5])
    x = np.concatenate([r.uniform(-170, 170, n), bx.ravel(),
                        [180.0, -180.0, 180.0]])
    y = np.concatenate([r.uniform(-85, 85, n), by.ravel(),
                        [90.0, -90.0, 0.0]])
    m = len(x)
    ds.insert("pts", {
        "geom__x": x, "geom__y": y,
        "weight": r.uniform(0, 2, m).astype(np.float32),
        "type": r.choice(["bus", "car"], m),
    }, fids=np.arange(m).astype(str))
    ds.flush("pts")
    return ds


# -- zoom-out: O(visible cells), zero residual ------------------------------

def test_warm_zoomout_zero_dispatch_bit_identical(ds):
    cold = ds.count("pts", WORLD)
    with _enabled(per_axis=4):
        for q in WORLD_WARM:
            ds.count("pts", q)  # fine-level warm (+ bottom-up rollup)
        d0 = _dispatches()
        warm = ds.count("pts", WORLD)
        assert _dispatches() == d0, "warm zoom-out dispatched to the device"
        ev = ds.audit.recent(1)[0]
        assert ev.scanned == 0
        hits, total = map(int, ev.hints["exec_path"]["cache_cells"].split("/"))
        assert hits == total > 0
    assert warm == cold


def test_zoomout_assembles_when_rollup_missing(ds):
    """Lazy on-miss assembly: fine cells populated WITHOUT the hierarchy
    (no rollup, no promoted parents), then a coarse query with it on —
    assembly is the only non-scan path and must serve every cell."""
    cold = ds.count("pts", WORLD)
    with _enabled(per_axis=4):
        with config.CACHE_HIERARCHY.scoped("false"):
            for q in WORLD_WARM:
                ds.count("pts", q)
        hh0 = _counter(metrics.CACHE_HIER_HIT)
        d0 = _dispatches()
        warm = ds.count("pts", WORLD)
        assert _dispatches() == d0
        assert _counter(metrics.CACHE_HIER_HIT) > hh0
        assert "hierarchy" in ds.audit.recent(1)[0].hints["exec_path"]
        assert warm == cold
        assert ds.count("pts", WORLD) == cold  # whole-result repeat


@pytest.mark.slow  # compile-heavy sweep: gated by the lake-smoke CI job
def test_zoomout_density_and_stats_bit_identical(ds):
    # raster decoupled from every filter bbox (dashboard shape), so the
    # density cells decompose and the zoom-out assembles; the filters are
    # domain-spanning, so the warm zoom-out has no strips to scan
    raster = (-120.0, -60.0, 120.0, 60.0)
    grid_cold = ds.density("pts", WORLD, bbox=raster, width=64, height=32)
    stat_cold = ds.stats("pts", "Count();MinMax(weight)", WORLD).value()
    with _enabled(per_axis=4):
        for q in WORLD_WARM:
            ds.density("pts", q, bbox=raster, width=64, height=32)
            ds.stats("pts", "Count();MinMax(weight)", q)
        d0 = _dispatches()
        grid_warm = ds.density("pts", WORLD, bbox=raster, width=64, height=32)
        stat_warm = ds.stats("pts", "Count();MinMax(weight)", WORLD).value()
        assert _dispatches() == d0
    assert np.array_equal(grid_cold, grid_warm)
    assert stat_warm == stat_cold


def test_density_curve_cross_level_downsample(ds):
    """Tile-pyramid zoom-out: level-k curve grids assemble from cached
    level-(k+1) chunks by downsample-add, bit-identical and dispatch-free."""
    bbox = (-180.0, -90.0, 180.0, 90.0)
    cold6, _ = ds.density_curve("pts", "INCLUDE", level=6, bbox=bbox)
    cold5, _ = ds.density_curve("pts", "INCLUDE", level=5, bbox=bbox)
    with _enabled():
        # warm level 6 WITHOUT rollup so the level-5 chunks can only come
        # from on-miss downsample assembly (the note proves the path; with
        # rollup on they'd be pre-merged direct hits — also dispatch-free)
        with config.CACHE_HIERARCHY.scoped("false"):
            g6, _ = ds.density_curve("pts", "INCLUDE", level=6, bbox=bbox)
        hh0 = _counter(metrics.CACHE_HIER_HIT)
        d0 = _dispatches()
        g5, _ = ds.density_curve("pts", "INCLUDE", level=5, bbox=bbox)
        assert _dispatches() == d0, "zoom-out level re-scanned"
        assert _counter(metrics.CACHE_HIER_HIT) > hh0
        assert "hierarchy" in ds.audit.recent(1)[0].hints["exec_path"]
        g5b, _ = ds.density_curve("pts", "INCLUDE", level=5, bbox=bbox)
    assert np.array_equal(cold6, g6)
    assert np.array_equal(cold5, g5)
    assert np.array_equal(cold5, g5b)


def test_density_curve_chunk_reuse_across_tiles(ds):
    """Adjacent tiles of one filter share block-space chunks: the second
    tile partially hits and stays bit-identical."""
    with _enabled():
        ds.density_curve("pts", "INCLUDE", level=6,
                         bbox=(-180.0, -90.0, 0.0, 90.0))
        p0 = _counter(metrics.CACHE_PARTIAL)
        g, _ = ds.density_curve("pts", "INCLUDE", level=6,
                                bbox=(-180.0, -90.0, 90.0, 90.0))
        assert _counter(metrics.CACHE_PARTIAL) == p0 + 1
    with config.CACHE_ENABLED.scoped("false"):
        cold, _ = ds.density_curve("pts", "INCLUDE", level=6,
                                   bbox=(-180.0, -90.0, 90.0, 90.0))
    assert np.array_equal(cold, g)


def test_density_curve_weighted_stays_whole_result(ds):
    bbox = (-180.0, -90.0, 180.0, 90.0)
    cold, _ = ds.density_curve("pts", "INCLUDE", level=5, bbox=bbox,
                               weight="weight")
    with _enabled():
        g1, _ = ds.density_curve("pts", "INCLUDE", level=5, bbox=bbox,
                                 weight="weight")
        assert "cache_chunk" not in ds.audit.recent(1)[0].hints["exec_path"]
        g2, _ = ds.density_curve("pts", "INCLUDE", level=5, bbox=bbox,
                                 weight="weight")
    assert np.array_equal(cold, g1) and np.array_equal(cold, g2)


def test_polygon_curve_chunks_share_and_skip_outside(ds):
    """Polygon density_curve chunk families (docs/CACHE.md "Polygon
    curve chunks"): interior chunks are served from the RESIDUAL-keyed
    family a plain (non-region) pyramid already warmed, outside chunks
    contribute zeros without scanning, and the assembled grid stays
    bit-identical to the undecomposed polygon scan."""
    bbox = (-180.0, -90.0, 180.0, 90.0)
    level = 6
    cold, snap0 = ds.density_curve("pts", level=level, bbox=bbox,
                                   region=POLY)
    with _enabled():
        # plain pyramid warms the residual-keyed chunk family
        plain, _ = ds.density_curve("pts", level=level, bbox=bbox)
        r0 = _counter(metrics.CACHE_CURVE_REGION)
        g, snap = ds.density_curve("pts", level=level, bbox=bbox,
                                   region=POLY)
        assert snap == snap0
        assert np.array_equal(g, cold), \
            "polygon curve chunk families broke bit-identity"
        assert _counter(metrics.CACHE_CURVE_REGION) == r0 + 1
        ev = ds.audit.recent(1)[0]
        path = ev.hints["exec_path"]
        note = path["cache_region_chunks"]
        assert "outside" in note and "interior" in note
        # interior chunks HIT the plain family the warm-up populated —
        # the over-scan the families exist to stop
        hits, total = map(int, path["cache_cells"].split("/"))
        assert hits > 0, (note, path)
        # and the polygon result is a strict subset of the plain pyramid
        assert g.sum() <= plain.sum()
        # fully warm repeat: whole-result hit, still bit-identical
        g2, _ = ds.density_curve("pts", level=level, bbox=bbox,
                                 region=POLY)
        assert np.array_equal(g2, cold)


def test_polygon_curve_warms_plain_family_for_later_queries(ds):
    """The sharing runs BOTH ways: a region pyramid's interior scans
    populate the residual-keyed family, so a later plain pyramid over
    the same residual reuses them."""
    bbox = (-180.0, -90.0, 180.0, 90.0)
    level = 6
    plain_cold, _ = ds.density_curve("pts", level=level, bbox=bbox)
    with _enabled():
        ds.density_curve("pts", level=level, bbox=bbox, region=POLY)
        g, _ = ds.density_curve("pts", level=level, bbox=bbox)
        ev = ds.audit.recent(1)[0]
        hits, total = map(
            int, ev.hints["exec_path"]["cache_cells"].split("/"))
        assert hits > 0, "plain pyramid reused nothing from the region run"
        assert np.array_equal(g, plain_cold)


# -- polygon regions --------------------------------------------------------

def test_polygon_count_density_stats_bit_identical(ds):
    cold_n = ds.count("pts", POLY_Q)
    raster = (-180.0, -90.0, 180.0, 90.0)
    cold_g = ds.density("pts", POLY_Q, bbox=raster, width=64, height=48)
    cold_s = ds.stats("pts", "Count();Enumeration(type)", POLY_Q).value()
    with _enabled():
        n1 = ds.count("pts", POLY_Q)
        ev = ds.audit.recent(1)[0]
        assert ev.hints["exec_path"].get("cache_region") == "polygon"
        assert ev.hints["exec_path"]["cache_boundary_cells"] > 0
        g1 = ds.density("pts", POLY_Q, bbox=raster, width=64, height=48)
        s1 = ds.stats("pts", "Count();Enumeration(type)", POLY_Q).value()
        n2 = ds.count("pts", POLY_Q)  # whole-result hit
        assert ds.audit.recent(1)[0].hints["exec_path"]["cache"] == "hit"
    assert n1 == n2 == cold_n
    assert np.array_equal(cold_g, g1)
    assert s1 == cold_s


def test_region_parameter_matches_explicit_conjunct(ds):
    exact = ds.count("pts", POLY_Q)
    assert ds.count("pts", region=POLY) == exact
    with _enabled():
        assert ds.count("pts", region=POLY) == exact
        assert ds.count("pts", "type = 'bus'", region=POLY) == \
            ds.count("pts", f"(type = 'bus') AND {POLY_Q}")


def test_polygon_cells_shared_with_bbox_queries(ds):
    """Interior polygon cells reuse cells a bbox query populated (same
    residual, same level): the polygon query then hits those instead of
    scanning them."""
    with _enabled():
        # a 180x90 box over the polygon's heart decomposes at level 4 —
        # the same level the polygon picks — and fully covers some of its
        # interior cells
        ds.count("pts", "BBOX(geom, -90, -45, 90, 45)")
        w0 = _counter(metrics.CACHE_HIT)
        n = ds.count("pts", POLY_Q)
        ev = ds.audit.recent(1)[0]
        hits, total = map(int, ev.hints["exec_path"]["cache_cells"].split("/"))
        assert hits > 0, "no interior polygon cell was served from cache"
        assert _counter(metrics.CACHE_HIT) == w0  # no whole-result hit
    assert n == ds.count("pts", POLY_Q)


def test_polygon_boundary_exactness_on_cell_edges():
    """Points ON level cell edges and ON/near the polygon boundary: the
    decomposed total equals the exact scan (half-open ulp contract +
    margin classification)."""
    ds = GeoDataset(n_shards=2)
    ds.create_schema("edge", "type:String,*geom:Point")
    # polygon aligned exactly with level-4 cell edges (22.5 multiples)
    poly = "POLYGON((-45 -22.5, 45 -22.5, 45 22.5, -45 22.5, -45 -22.5))"
    eps = 1e-9
    xs = [-45.0, 45.0, 0.0, 22.5, -22.5, 45.0 - eps, -45.0 + eps,
          45.0 + eps, -45.0 - eps, 22.5, 0.0]
    ys = [0.0, 0.0, 22.5, -22.5, 22.5, 0.0, 0.0, 0.0, 0.0,
          22.5 - eps, -22.5 + eps]
    m = len(xs)
    ds.insert("edge", {"geom__x": np.asarray(xs), "geom__y": np.asarray(ys),
                       "type": np.array(["a"] * m)},
              fids=np.arange(m).astype(str))
    ds.flush("edge")
    q = f"INTERSECTS(geom, {poly})"
    cold = ds.count("edge", q)
    with _enabled():
        assert ds.count("edge", q) == cold
        assert ds.count("edge", q) == cold


def test_polygon_with_hole_and_multipolygon(ds):
    holed = ("POLYGON((-120 -60, 120 -60, 120 70, -120 70, -120 -60), "
             "(-30 -20, 30 -20, 30 25, -30 25, -30 -20))")
    multi = ("MULTIPOLYGON(((-150 -70, -20 -70, -20 0, -150 0, -150 -70)), "
             "((20 10, 150 10, 150 80, 20 80, 20 10)))")
    for wkt in (holed, multi):
        q = f"INTERSECTS(geom, {wkt})"
        cold = ds.count("pts", q)
        with _enabled():
            assert ds.count("pts", q) == cold
            assert ds.count("pts", q) == cold


@pytest.mark.slow  # compile-heavy sweep: gated by the lake-smoke CI job
def test_polygon_partitioned_store_residual_fans_out(rng):
    """Boundary scans ride the ordinary planner/executor — on a
    partitioned store that is the partitioned (and, meshed, sharded)
    executor — and stay bit-identical."""
    ds = GeoDataset(n_shards=2)
    ds.create_schema(
        "part", "weight:Float,dtg:Date,*geom:Point;geomesa.partition='time'"
    )
    r = np.random.default_rng(5)
    n = 3000
    lo = np.datetime64("2020-01-01", "ms").astype(np.int64)
    ds.insert("part", {
        "geom__x": r.uniform(-60, 60, n), "geom__y": r.uniform(-50, 50, n),
        "weight": r.uniform(0, 1, n),
        "dtg": (lo + r.integers(0, 40 * 86_400_000, n)).astype("datetime64[ms]"),
    }, fids=np.arange(n).astype(str))
    ds.flush("part")
    poly = "POLYGON((-50 -40, 50 -45, 55 45, -55 40, -50 -40))"
    q = f"INTERSECTS(geom, {poly})"
    cold = ds.count("part", q)
    with config.CACHE_ENABLED.scoped("true"):
        assert ds.count("part", q) == cold
        assert ds.count("part", q) == cold
        assert ds.audit.recent(1)[0].hints["exec_path"]["cache"] == "hit"


# -- invalidation -----------------------------------------------------------

def test_subtree_invalidation_under_insert_delete(ds):
    with _enabled(per_axis=4):
        for q in WARM4:
            ds.count("pts", q)
        base = ds.count("pts", ZOOM)  # hierarchy-served
        assert _counter(metrics.CACHE_HIER_PROMOTE) > 0
        # an insert bumps the epoch: EVERY pre-merged parent must die with
        # the flat cells it summarizes
        ds.insert("pts", {
            "geom__x": [1.0, -80.0], "geom__y": [1.0, 40.0],
            "weight": [1.0, 1.0], "type": ["bus", "bus"],
        }, fids=["h1", "h2"])
        ds.flush("pts")
        assert ds.count("pts", ZOOM) == base + 2
        ds.delete_features("pts", "IN ('h1')")
        assert ds.count("pts", ZOOM) == base + 1
    assert ds.count("pts", ZOOM) == base + 1  # cache-disabled oracle


# -- seeded property test ---------------------------------------------------

def test_random_pan_zoom_polygon_sequence_bit_identical(ds):
    """Seeded random walk over pans, zooms, polygon counts, density
    rasters, and epoch bumps: every cached answer equals the cache-
    disabled oracle bit-for-bit."""
    r = np.random.default_rng(42)
    raster = (-180.0, -90.0, 180.0, 90.0)

    def random_query():
        kind = r.choice(["bbox", "zoom", "poly", "density"])
        if kind in ("bbox", "zoom", "density"):
            span = float(r.choice([45.0, 90.0, 180.0]))
            x0 = float(r.uniform(-180, 180 - span))
            y0 = float(r.uniform(-90, 90 - min(span, 90)))
            q = (f"BBOX(geom, {x0}, {y0}, {x0 + span}, "
                 f"{min(y0 + min(span, 90), 90.0)})")
            return kind, q
        k = int(r.integers(3, 7))
        ang = np.sort(r.uniform(0, 2 * np.pi, k))
        cxp, cyp = r.uniform(-60, 60), r.uniform(-40, 40)
        rad = r.uniform(25, 70)
        pts = [(cxp + rad * np.cos(a), cyp + rad * np.sin(a)) for a in ang]
        pts = [(float(np.clip(px, -179, 179)), float(np.clip(py, -89, 89)))
               for px, py in pts]
        ring = ", ".join(f"{px:.4f} {py:.4f}" for px, py in pts + [pts[0]])
        return kind, f"INTERSECTS(geom, POLYGON(({ring})))"

    fid = 20_000
    for step in range(10):
        kind, q = random_query()
        if kind == "density":
            with _enabled(per_axis=4):
                warm = ds.density("pts", q, bbox=raster, width=32, height=32)
            cold = ds.density("pts", q, bbox=raster, width=32, height=32)
            assert np.array_equal(cold, warm), (step, q)
        else:
            with _enabled(per_axis=4):
                warm_n = ds.count("pts", q)
            assert warm_n == ds.count("pts", q), (step, q)
        if step % 4 == 3:  # epoch bump mid-sequence
            ds.insert("pts", {
                "geom__x": [float(r.uniform(-170, 170))],
                "geom__y": [float(r.uniform(-85, 85))],
                "weight": [1.0], "type": ["car"],
            }, fids=[str(fid)])
            fid += 1
            ds.flush("pts")


# -- unit: decomposition / hierarchy shapes ---------------------------------

def _pt_ft():
    return FeatureType.from_spec("t", "type:String,*geom:Point")


def test_world_bbox_has_no_strips():
    d = decompose(parse_ecql(WORLD), _pt_ft())
    assert d is not None and not d.strips
    # domain-edge cells close at exactly 180 / 90
    n = 1 << d.level
    assert d.cell_boxes[(n - 1, n - 1)][2] == 180.0
    assert d.cell_boxes[(n - 1, n - 1)][3] == 90.0
    # interior cells off the domain edge keep the half-open ulp pull
    assert d.cell_boxes[(0, 0)][2] < -180.0 + 360.0 / n


def test_decompose_region_shapes():
    r = decompose_region(parse_ecql(POLY_Q), _pt_ft())
    assert r is not None
    assert r.cells and r.boundary
    assert r.residual_key == repr(parse_ecql("INCLUDE"))
    # interior and boundary are disjoint; runs cover exactly the boundary
    assert not set(r.cells) & set(r.boundary)
    assert len(r.boundary_boxes) <= len(r.boundary)
    # polygon under OR / extra spatial conjunct: not decomposable
    assert decompose_region(parse_ecql(
        f"{POLY_Q} OR type = 'bus'"), _pt_ft()) is None
    assert decompose_region(parse_ecql(
        f"{POLY_Q} AND BBOX(geom, 0, 0, 10, 10)"), _pt_ft()) is None
    with config.CACHE_POLYGON.scoped("false"):
        assert decompose_region(parse_ecql(POLY_Q), _pt_ft()) is None


def test_hierarchy_child_order_and_rollup():
    store = {}
    get = lambda lvl, c: store.get((lvl, c))  # noqa: E731
    put = lambda lvl, c, v: store.__setitem__((lvl, c), v)  # noqa: E731
    merge4 = lambda vals: sum(vals)  # noqa: E731
    assert hierarchy.children((3, 5)) == [(6, 10), (7, 10), (6, 11), (7, 11)]
    for ch, v in zip(hierarchy.children((0, 0)), (1, 2, 4, 8)):
        put(5, ch, v)
    assert hierarchy.assemble(get, put, merge4, 4, (0, 0)) == 15
    assert store[(4, (0, 0))] == 15  # promoted
    # rollup: completing a sibling quad writes the parent bottom-up
    store.clear()
    for ch, v in zip(hierarchy.children((1, 1)), (1, 1, 1, 1)):
        put(3, ch, v)
    assert hierarchy.rollup(get, put, merge4, 3, (2, 2)) == 1
    assert store[(2, (1, 1))] == 4


def test_curve_downsample_exact():
    g = np.arange(16, dtype=np.float64).reshape(4, 4)
    d = hierarchy.downsample(g)
    assert d.shape == (2, 2)
    assert d[0, 0] == g[0, 0] + g[0, 1] + g[1, 0] + g[1, 1]


# -- satellites: fusion keys, shape baselines, slo breakers -----------------

def test_polygon_region_keys_fusion_distinctly():
    from geomesa_tpu.serving import fuse

    a = fuse.fuse_key("count", "pts", {"ecql": f"({POLY_Q})"})
    b = fuse.fuse_key(
        "count", "pts",
        {"ecql": "(INTERSECTS(geom, POLYGON((0 0, 9 0, 9 9, 0 9, 0 0))))"},
    )
    assert a is not None and b is not None and a != b
    # an unfolded raw region never fuses (allow-list fail-safe)
    assert fuse.fuse_key("count", "pts",
                         {"ecql": "INCLUDE", "region": POLY}) is None


def test_latency_outlier_baselines_per_kernel_shape():
    """A slow-but-legitimate kernel shape must not trip a device whose
    other shapes are fast — and a straggler within one shape still does
    (carried RESILIENCE.md follow-up)."""
    from geomesa_tpu import resilience
    from geomesa_tpu.parallel import health as phealth

    phealth.reset()
    resilience.reset_breakers()
    try:
        with config.DEVICE_LATENCY_OUTLIER.scoped("3"), \
                config.DEVICE_LATENCY_FLOOR_MS.scoped("1"), \
                config.DEVICE_BREAKER_THRESHOLD.scoped("3"):
            reg = phealth.registry()
            # two shapes with honestly different costs on device 0
            for _ in range(16):
                reg.record_latency(0, 0.002, shape=("count", 1))
                reg.record_latency(0, 0.200, shape=("density", 8))
            # under ONE mesh-wide baseline the 0.2s density syncs would be
            # 100x the mixed median and break device 0; per-shape they ARE
            # the median
            assert reg.state(0) == phealth.OK
            assert len(reg.latency_baselines()) == 2
            # a true straggler inside one shape still trips
            for _ in range(8):
                reg.record_latency(1, 0.002, shape=("count", 1))
            for _ in range(3):
                reg.record_latency(1, 0.5, shape=("count", 1))
            assert reg.state(1) == phealth.BROKEN
    finally:
        phealth.reset()
        resilience.reset_breakers()


def test_breaker_open_rides_slo_surface():
    from geomesa_tpu import obs, resilience, slo

    slo.reset()
    resilience.reset_breakers()
    try:
        br = resilience.breaker("hier-test-sink", threshold=1,
                                reset_ms=60_000)
        br.record_failure()
        assert br.state == "open"
        states = slo.sync_breaker_gauges()
        assert states.get("hier-test-sink") == "open"
        report = metrics.registry().report()
        assert report.get("slo.breaker.hier-test-sink") == 1.0
        payload = obs.health()
        assert "hier-test-sink" in payload["open_breakers"]
        assert "breaker open" in payload.get("breaker_note", "")
        assert payload["status"] == "degraded"
    finally:
        resilience.reset_breakers()
        slo.reset()


def test_explain_hierarchy_section(ds):
    with _enabled(per_axis=4):
        for q in WORLD_WARM:
            ds.count("pts", q)
        out = ds.explain("pts", WORLD)
        assert "Hierarchy" in out
        assert "levels hit" in out
        assert "residual fraction" in out
    out2 = ds.explain("pts", "INCLUDE", region=POLY)
    assert "polygon cover" in out2
    assert "boundary cells" in out2
