"""Query-axis megakernel tests (docs/SERVING.md "Query-axis batching"):
M *distinct* viewports through one device dispatch.

The load-bearing guarantee is the cross-member leak guard: every member
of a batched count/density/stats pass must be BIT-IDENTICAL to its own
serial execution — seeded property tests assert it at M ∈ {2, 5, 8} on
both the plain single-store path and the partitioned path over the
8-virtual-device mesh (conftest forces 8 CPU devices). Around the
tentpole: structural fuse keys (literal-differing ECQL fuses, residual-
differing never does), the ≤2-dispatch fusion proof, kernel reuse across
batches (literals are DATA — a new viewport set never recompiles),
registry eviction accounting, speculative counts, and pool-aware
placement."""

import threading

import numpy as np
import pytest

from geomesa_tpu import GeoDataset, config, metrics, resilience, tracing
from geomesa_tpu.api.dataset import Query
from geomesa_tpu.filter import parse_ecql
from geomesa_tpu.filter import template as ftpl
from geomesa_tpu.kernels.registry import KernelRegistry, bucket_batch
from geomesa_tpu.serving import fuse as fusemod


def _bbox_ecql(b, extra="speed > 20"):
    base = f"BBOX(geom, {b[0]}, {b[1]}, {b[2]}, {b[3]})"
    return f"{base} AND {extra}" if extra else base


def _rand_boxes(rng, m):
    out = []
    for _ in range(m):
        x0 = float(rng.uniform(-70, 30))
        y0 = float(rng.uniform(-35, 15))
        out.append((x0, y0, x0 + float(rng.uniform(5, 60)),
                    y0 + float(rng.uniform(5, 30))))
    return out


@pytest.fixture(scope="module")
def ds():
    ds = GeoDataset(n_shards=4)
    ds.create_schema("pts", "speed:Float,kind:String,dtg:Date,*geom:Point")
    rng = np.random.default_rng(11)
    n = 3000
    t0 = np.datetime64("2024-01-01T00:00:00") \
        .astype("datetime64[ms]").astype(np.int64)
    ds.insert("pts", {
        "speed": rng.uniform(0, 100, n),
        "kind": rng.choice(["a", "b", "c"], n),
        "dtg": (t0 + rng.integers(0, 90 * 86400 * 1000, n))
        .astype("datetime64[ms]"),
        "geom": list(zip(rng.uniform(-80, 80, n),
                         rng.uniform(-40, 40, n))),
    }, fids=np.arange(n).astype(str))
    ds.flush("pts")
    return ds


@pytest.fixture(scope="module")
def pds():
    """Time-partitioned twin: the sharded fan-out engages on the
    8-virtual-device mesh (conftest). Kept SMALL (a handful of weekly
    bins) — per-partition dispatch overhead on the virtual mesh
    dominates tier-1 wall time, and the bit-identity contract is
    partition-count-independent."""
    ds = GeoDataset(n_shards=2)
    ds.create_schema(
        "ppts", "speed:Float,dtg:Date,*geom:Point;geomesa.partition='time'"
    )
    rng = np.random.default_rng(13)
    n = 2200
    t0 = np.datetime64("2024-01-01T00:00:00") \
        .astype("datetime64[ms]").astype(np.int64)
    ds.insert("ppts", {
        "speed": rng.uniform(0, 100, n),
        "dtg": (t0 + rng.integers(0, 30 * 86400 * 1000, n))
        .astype("datetime64[ms]"),
        "geom": list(zip(rng.uniform(-80, 80, n),
                         rng.uniform(-40, 40, n))),
    }, fids=np.arange(n).astype(str))
    ds.flush("ppts")
    return ds


# ---------------------------------------------------------------------------
# structural templates (filter/template.py)
# ---------------------------------------------------------------------------


def test_template_literals_split_and_keys(ds):
    st = ds._store("pts")
    a = ftpl.split_literals(
        parse_ecql(_bbox_ecql((-10, -10, 10, 10))), st.ft)
    b = ftpl.split_literals(
        parse_ecql(_bbox_ecql((3, -7, 40, 12))), st.ft)
    assert a is not None and b is not None
    # same structure, different literals: one kernel
    assert a.key == b.key
    assert not np.array_equal(a.lits_f, b.lits_f)
    # a different residual is a different kernel
    c = ftpl.split_literals(
        parse_ecql(_bbox_ecql((-10, -10, 10, 10), "speed > 30")), st.ft)
    assert c is not None and c.key != a.key


def test_template_during_slots(ds):
    st = ds._store("pts")
    q1 = ("BBOX(geom, -10, -10, 10, 10) AND dtg DURING "
          "2024-01-01T00:00:00Z/2024-02-01T00:00:00Z")
    q2 = ("BBOX(geom, -5, -2, 30, 20) AND dtg DURING "
          "2024-02-10T00:00:00Z/2024-03-01T00:00:00Z")
    a = ftpl.split_literals(parse_ecql(q1), st.ft)
    b = ftpl.split_literals(parse_ecql(q2), st.ft)
    assert a is not None and a.key == b.key
    assert [s.kind for s in a.slots] == ["bbox", "during"]
    assert len(a.lits_f) == 4 and len(a.lits_i) == 4
    assert not np.array_equal(a.lits_i, b.lits_i)


def test_template_no_slot_or_shielded(ds):
    st = ds._store("pts")
    # no viewport literal at all
    assert ftpl.split_literals(parse_ecql("speed > 5"), st.ft) is None
    # a bbox under OR is NOT slotted (polarity shield): it stays in the
    # residual, so the two queries key apart
    a = ftpl.split_literals(parse_ecql(
        "BBOX(geom, 0, 0, 5, 5) AND "
        "(BBOX(geom, -9, -9, -1, -1) OR speed > 50)"), st.ft)
    b = ftpl.split_literals(parse_ecql(
        "BBOX(geom, 0, 0, 5, 5) AND "
        "(BBOX(geom, -8, -8, -2, -2) OR speed > 50)"), st.ft)
    assert a is not None and b is not None
    assert len(a.slots) == 1
    assert a.key != b.key


# ---------------------------------------------------------------------------
# the cross-member leak guard: batched == serial, bit-identical,
# at M ∈ {2, 5, 8}, plain AND partitioned/8-virtual-device paths
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("m", [2, 5, 8])
def test_count_batch_bit_identical_plain(ds, m):
    rng = np.random.default_rng(100 + m)
    queries = [_bbox_ecql(b) for b in _rand_boxes(rng, m)]
    serial = [ds.count("pts", q) for q in queries]
    disp = metrics.registry().counter(metrics.EXEC_DEVICE_DISPATCH)
    d0 = disp.value
    batched = ds.count_batch("pts", queries)
    assert batched is not None
    assert batched == serial
    assert disp.value - d0 == 1  # ONE dispatch for the whole batch


@pytest.mark.parametrize("m", [2, 5, 8])
def test_density_batch_bit_identical_plain(ds, m):
    rng = np.random.default_rng(200 + m)
    boxes = _rand_boxes(rng, m)
    queries = [_bbox_ecql(b) for b in boxes]
    serial = [
        ds.density("pts", q, bbox=b, width=32, height=32)
        for q, b in zip(queries, boxes)
    ]
    batched = ds.density_batch("pts", queries, bboxes=boxes,
                               width=32, height=32)
    assert batched is not None
    for s, b in zip(serial, batched):
        assert np.array_equal(s, b)


def test_stats_batch_bit_identical_plain(ds):
    rng = np.random.default_rng(17)
    queries = [_bbox_ecql(b) for b in _rand_boxes(rng, 3)]
    for spec in ("Count()", "MinMax(speed)", "Histogram(speed,12,0,100)",
                 "Enumeration(kind)"):
        serial = [ds.stats("pts", spec, q).to_json() for q in queries]
        batched = ds.stats_batch("pts", spec, queries)
        assert batched is not None, spec
        assert [s.to_json() for s in batched] == serial, spec


def test_stats_batch_descriptive_falls_back(ds):
    rng = np.random.default_rng(19)
    queries = [_bbox_ecql(b) for b in _rand_boxes(rng, 3)]
    # descriptive leaves are layout-sensitive f32 sums: never batched
    assert ds.stats_batch("pts", "DescriptiveStats(speed)", queries) is None


@pytest.mark.slow  # gated by the sharded-8dev-smoke CI job, not tier-1
@pytest.mark.parametrize("m", [2, 5, 8])
def test_batch_bit_identical_partitioned_8dev(pds, m):
    """Count + density + stats, batched vs serial, over the partitioned
    store on the 8-virtual-device mesh — one test per M so the serial
    baselines (the dominant cost) run once each. The serial per-member
    partitioned scans make this the priciest invariant in the repo, so
    it rides the dedicated 8-device CI job (ci.yml sharded-8dev-smoke)
    instead of tier-1; the plain-path M sweep above stays in tier-1."""
    import jax

    assert len(jax.devices()) == 8  # conftest's virtual mesh
    rng = np.random.default_rng(300 + m)
    boxes = _rand_boxes(rng, m)
    windows = ["2024-01-01T00:00:00Z/2024-01-12T00:00:00Z",
               "2024-01-08T00:00:00Z/2024-01-25T00:00:00Z",
               "2024-01-05T00:00:00Z/2024-01-30T00:00:00Z"]
    queries = [
        f"{_bbox_ecql(b, extra=None)} AND dtg DURING {windows[i % 3]}"
        for i, b in enumerate(boxes)
    ]
    serial = [pds.count("ppts", q) for q in queries]
    batched = pds.count_batch("ppts", queries)
    assert batched is not None
    assert batched == serial
    # the serial (mesh-off) path produces the same members too
    with config.MESH_DEVICES.scoped("off"):
        batched_off = pds.count_batch("ppts", queries)
    assert batched_off == serial
    g_serial = [
        pds.density("ppts", q, bbox=b, width=12, height=12)
        for q, b in zip(queries, boxes)
    ]
    g_batched = pds.density_batch("ppts", queries, bboxes=boxes,
                                  width=12, height=12)
    assert g_batched is not None
    for s, b in zip(g_serial, g_batched):
        assert np.array_equal(s, b)
    s_serial = [pds.stats("ppts", "MinMax(speed)", q).to_json()
                for q in queries]
    s_batched = pds.stats_batch("ppts", "MinMax(speed)", queries)
    assert s_batched is not None
    assert [s.to_json() for s in s_batched] == s_serial


def test_density_batch_weighted_bit_identical_small(ds):
    # weighted members: the batched scatter is op-for-op the serial
    # padded path (small table — compaction never engages here)
    rng = np.random.default_rng(23)
    boxes = _rand_boxes(rng, 3)
    queries = [_bbox_ecql(b) for b in boxes]
    serial = [
        ds.density("pts", q, bbox=b, width=16, height=16, weight="speed")
        for q, b in zip(queries, boxes)
    ]
    batched = ds.density_batch("pts", queries, bboxes=boxes,
                               width=16, height=16, weight="speed")
    assert batched is not None
    for s, b in zip(serial, batched):
        assert np.array_equal(s, b)


def test_empty_and_disjoint_members(ds):
    # a member whose bbox is fully outside the data (disjoint key plan)
    # must come back 0 / zero-grid, exactly like its serial run
    queries = [_bbox_ecql((-10, -10, 10, 10)),
               _bbox_ecql((160, 80, 170, 85))]
    serial = [ds.count("pts", q) for q in queries]
    batched = ds.count_batch("pts", queries)
    assert batched == serial
    assert batched[1] == 0


def test_batch_kernel_shared_across_literal_sets(ds):
    """Literals are kernel DATA: a fresh viewport set (and any batch size
    within one bucket) reuses the compiled kernel — zero recompiles."""
    rng = np.random.default_rng(29)
    reg = ds._executor(ds._store("pts")).kernel_registry()
    q1 = [_bbox_ecql(b) for b in _rand_boxes(rng, 3)]
    assert ds.count_batch("pts", q1) is not None
    t0 = reg.traces("count_batch")
    assert t0 >= 1
    # new literals, same structure, batch size in the same bucket (4 -> 4)
    q2 = [_bbox_ecql(b) for b in _rand_boxes(rng, 4)]
    assert ds.count_batch("pts", q2) is not None
    assert reg.traces("count_batch") == t0  # no retrace
    assert bucket_batch(3) == bucket_batch(4) == 4


def test_batch_audit_events_per_member(ds):
    rng = np.random.default_rng(31)
    queries = [_bbox_ecql(b) for b in _rand_boxes(rng, 3)]
    n0 = len(ds.audit.recent(500))
    out = ds.count_batch(
        "pts", queries,
        members=[{"user": f"u{i}"} for i in range(3)],
    )
    assert out is not None
    evs = ds.audit.recent(500)[n0:]
    mine = [e for e in evs if e.hints.get("distinct")]
    assert len(mine) == 3
    assert all(e.hints.get("fused") and e.hints.get("fused_batch") == 3
               for e in mine)
    assert sorted(e.user for e in mine) == ["u0", "u1", "u2"]


# ---------------------------------------------------------------------------
# structural fusion keys + the scheduler integration
# ---------------------------------------------------------------------------


def test_fuse_key_structural_equality(ds):
    k1 = fusemod.fuse_key(
        "count", "pts", {"ecql": _bbox_ecql((-10, -10, 10, 10))}, ds=ds)
    k2 = fusemod.fuse_key(
        "count", "pts", {"ecql": _bbox_ecql((5, -3, 25, 9))}, ds=ds)
    assert k1 is not None and k1 == k2
    # residual drift keys apart
    k3 = fusemod.fuse_key(
        "count", "pts",
        {"ecql": _bbox_ecql((-10, -10, 10, 10), "speed > 30")}, ds=ds)
    assert k3 != k1
    # the knob reverts to literal-text keys
    with config.SERVING_FUSION_DISTINCT.scoped(False):
        ka = fusemod.fuse_key(
            "count", "pts", {"ecql": _bbox_ecql((-10, -10, 10, 10))},
            ds=ds)
        kb = fusemod.fuse_key(
            "count", "pts", {"ecql": _bbox_ecql((5, -3, 25, 9))}, ds=ds)
    assert ka != kb
    # speculative_ok never blocks fusion
    ks = fusemod.fuse_key(
        "count", "pts",
        {"ecql": _bbox_ecql((-10, -10, 10, 10)), "speculative_ok": True},
        ds=ds)
    assert ks == k1


def test_fuse_key_density_distinct_unweighted_only(ds):
    base = {"ecql": _bbox_ecql((-10, -10, 10, 10)),
            "width": 64, "height": 64}
    k1 = fusemod.fuse_key(
        "density", "pts", {**base, "bbox": (-10, -10, 10, 10)}, ds=ds)
    k2 = fusemod.fuse_key(
        "density", "pts",
        {"ecql": _bbox_ecql((0, 0, 30, 20)), "width": 64, "height": 64,
         "bbox": (0, 0, 30, 20)}, ds=ds)
    assert k1 == k2  # distinct grid bboxes share the structural key
    # weighted grids keep the literal-identical rule
    kw1 = fusemod.fuse_key(
        "density", "pts",
        {**base, "bbox": (-10, -10, 10, 10), "weight": "speed"}, ds=ds)
    kw2 = fusemod.fuse_key(
        "density", "pts",
        {"ecql": _bbox_ecql((0, 0, 30, 20)), "width": 64, "height": 64,
         "bbox": (0, 0, 30, 20), "weight": "speed"}, ds=ds)
    assert kw1 != kw2


def _stalled_sched(ds):
    sched = ds.serving.start()
    gate = threading.Event()
    started = threading.Event()

    def stall():
        started.set()
        return gate.wait(30)

    fut = sched.submit(stall, user="stall", op="stall")
    assert started.wait(10)
    return sched, gate, fut


def test_distinct_bbox_counts_fuse_into_two_dispatches(ds):
    """THE acceptance gate shape: N=8 distinct-bbox counts through the
    scheduler execute in ≤ 2 device dispatches, every member bit-
    identical to its serial run."""
    rng = np.random.default_rng(37)
    queries = [_bbox_ecql(b) for b in _rand_boxes(rng, 8)]
    serial = [ds.count("pts", q) for q in queries]
    sched, gate, fut = _stalled_sched(ds)
    try:
        disp = metrics.registry().counter(metrics.EXEC_DEVICE_DISPATCH)
        futs = [
            sched.submit(
                (lambda q=q: ds.count("pts", q)), user=f"c{i % 3}",
                op="count",
                fuse=fusemod.make_spec(ds, "count", "pts", {"ecql": q}),
            )
            for i, q in enumerate(queries)
        ]
        d0 = disp.value
        gate.set()
        got = [f.result(60) for f in futs]
        dispatches = disp.value - d0
    finally:
        gate.set()
        fut.result(5)
        sched.stop()
    assert got == serial
    assert dispatches <= 2, f"{dispatches} dispatches for 8 distinct counts"


def test_distinct_fusion_falls_back_serially_when_ineligible(ds):
    """Members sharing a structural key whose batch cannot ride the
    megakernel still get correct per-member answers (query-at-a-time
    fallback inside the group)."""
    rng = np.random.default_rng(41)
    queries = [_bbox_ecql(b) for b in _rand_boxes(rng, 3)]
    serial = [ds.count("pts", q) for q in queries]
    # force ineligibility: the dataset-level batch entry declines, so the
    # fused group must degrade to query-at-a-time INSIDE the group
    ds.count_batch_orig = ds.count_batch
    ds.count_batch = lambda *a, **kw: None
    sched, gate, fut = _stalled_sched(ds)
    try:
        futs = [
            sched.submit(
                (lambda q=q: ds.count("pts", q)), user="u", op="count",
                fuse=fusemod.make_spec(ds, "count", "pts", {"ecql": q}),
            )
            for q in queries
        ]
        gate.set()
        got = [f.result(60) for f in futs]
    finally:
        gate.set()
        fut.result(5)
        sched.stop()
        ds.count_batch = ds.count_batch_orig
        del ds.count_batch_orig
    assert got == serial


# ---------------------------------------------------------------------------
# registry LRU pressure satellite
# ---------------------------------------------------------------------------


def test_registry_eviction_accounting():
    reg = KernelRegistry(capacity=2)
    reg.put(("siteA", 1), "k1")
    reg.put(("siteA", 2), "k2")
    reg.put(("siteB", 3), "k3")  # evicts ("siteA", 1)
    assert reg.evicts("siteA") == 1
    assert reg.evicted_recompiles() == 0
    reg.put(("siteA", 1), "k1b")  # re-trace of an evicted key
    assert reg.evicted_recompiles() == 1
    ev = metrics.registry().counter(f"{metrics.KERNEL_EVICT}.siteA")
    assert ev.value >= 1
    evr = metrics.registry().counter(metrics.KERNEL_RECOMPILE_EVICTED)
    assert evr.value >= 1


def test_registry_default_capacity_raised():
    assert (config.KERNEL_CACHE_SIZE.to_int() or 0) >= 512


# ---------------------------------------------------------------------------
# speculative counts satellite
# ---------------------------------------------------------------------------


def test_speculative_count_inline(ds):
    q = _bbox_ecql((-10, -10, 10, 10))
    exact = ds.count("pts", q)
    with resilience.deadline_scope(0.0):
        with pytest.raises(resilience.DeadlineShedError):
            ds.count("pts", q)
    n0 = len(ds.audit.recent(500))
    spec = metrics.registry().counter(metrics.SERVING_SPECULATIVE)
    s0 = spec.value
    with resilience.deadline_scope(0.0):
        est = ds.count("pts", q, speculative_ok=True)
    assert isinstance(est, int)
    assert spec.value == s0 + 1
    evs = ds.audit.recent(500)[n0:]
    marked = [e for e in evs if e.hints.get("speculative")]
    assert len(marked) == 1
    # a healthy deadline still returns the exact count
    with resilience.deadline_scope(30.0):
        assert ds.count("pts", q, speculative_ok=True) == exact


def test_speculative_count_queue_path(ds):
    """A queued count shed at dispatch resolves speculatively when the
    ticket carries the fallback."""
    q = _bbox_ecql((-10, -10, 10, 10))
    sched, gate, fut = _stalled_sched(ds)
    try:
        f = sched.submit(
            lambda: ds.count("pts", q), user="u", op="count",
            budget_s=0.001,
            speculative=lambda: ds._speculative_count("pts", q),
        )
        import time as _t

        _t.sleep(0.05)  # let the budget lapse while queued
        gate.set()
        est = f.result(30)
        assert isinstance(est, int)
    finally:
        gate.set()
        fut.result(5)
        sched.stop()


def test_speculative_count_wire():
    """Full wire contract: the x-geomesa-speculative-ok header turns an
    admission-time [GM-SHED] into the typed coarse frame."""
    fl = pytest.importorskip("pyarrow.flight")
    import json

    from geomesa_tpu.sidecar.service import GeoFlightServer

    wds = GeoDataset(n_shards=2)
    wds.create_schema("w", "a:Integer,dtg:Date,*geom:Point")
    rng = np.random.default_rng(5)
    n = 500
    wds.insert("w", {
        "geom__x": rng.uniform(-10, 10, n),
        "geom__y": rng.uniform(-10, 10, n),
        "dtg": rng.integers(0, 10**10, n).astype("datetime64[ms]"),
        "a": rng.integers(0, 5, n).astype(np.int32),
    }, fids=np.arange(n).astype(str))
    wds.flush("w")
    srv = GeoFlightServer(wds, "grpc+tcp://127.0.0.1:0")
    try:
        cli = fl.FlightClient(f"grpc+tcp://127.0.0.1:{srv.port}")
        body = json.dumps(
            {"name": "w", "ecql": "BBOX(geom, -5, -5, 5, 5)"}
        ).encode()
        out = list(cli.do_action(fl.Action("count", body),
                   fl.FlightCallOptions(headers=[
                       (b"x-geomesa-deadline-ms", b"0"),
                       (b"x-geomesa-speculative-ok", b"1"),
                   ])))
        resp = json.loads(out[0].body.to_pybytes().decode())
        assert resp.get("speculative") is True and "count" in resp
        # without the opt-in the same budget fails typed [GM-SHED]
        with pytest.raises(fl.FlightTimedOutError, match="GM-SHED"):
            list(cli.do_action(fl.Action("count", body),
                 fl.FlightCallOptions(headers=[
                     (b"x-geomesa-deadline-ms", b"0"),
                 ])))
        assert any(e.hints.get("speculative")
                   for e in wds.audit.recent(20))
    finally:
        srv.shutdown()


# ---------------------------------------------------------------------------
# pool-aware placement satellite
# ---------------------------------------------------------------------------


def test_placement_defers_to_column_hot_idle_slot(ds):
    sched = ds.serving
    import time as _t

    class _T:
        pass

    spec = fusemod.make_spec(
        ds, "count", "pts", {"ecql": _bbox_ecql((-1, -1, 1, 1))})
    t = _T()
    t.fuse = spec
    t.continuation = False
    t.defer_slot = None
    t.defer_at = 0.0
    with sched._cv:
        sched._threads = {0: threading.current_thread(),
                          1: threading.current_thread()}
        probe = sched._residency_probe
        try:
            # recency-only mode: this test exercises the defer MECHANICS
            # with a seeded heat table; the residency-ranked policy has
            # its own coverage (test_serving.py residency tests)
            sched._residency_probe = None
            sched._schema_heat["pts"] = {1: _t.perf_counter()}
            sched._idle.add(1)
            now = _t.perf_counter()
            assert sched._defer_for_placement_locked(t, 0, now)
            assert t.defer_slot == 1
            assert spec.placement["preferred"] == 1
            assert spec.placement["reason"] == "column-heat"
            # slot 0 must skip it within the grace window...
            assert not sched._defer_ok_locked(t, 0, now)
            # ...slot 1 takes it immediately...
            assert sched._defer_ok_locked(t, 1, now)
            # ...and anyone takes it after the grace window
            assert sched._defer_ok_locked(
                t, 0, now + sched._placement_grace_s() + 0.01)
            # a BUSY preferred slot never defers
            t2 = _T()
            t2.fuse = fusemod.make_spec(
                ds, "count", "pts", {"ecql": _bbox_ecql((-2, -2, 2, 2))})
            t2.continuation = False
            t2.defer_slot = None
            t2.defer_at = 0.0
            sched._idle.discard(1)
            assert not sched._defer_for_placement_locked(
                t2, 0, _t.perf_counter())
        finally:
            sched._threads = {}
            sched._schema_heat.clear()
            sched._idle.clear()
            sched._residency_probe = probe


def test_placement_surfaced_on_group_span(ds):
    """The fused group's span carries the placement decision."""
    rng = np.random.default_rng(43)
    queries = [_bbox_ecql(b) for b in _rand_boxes(rng, 2)]
    sched, gate, fut = _stalled_sched(ds)
    try:
        futs = [
            sched.submit(
                (lambda q=q: ds.count("pts", q)), user="u", op="count",
                fuse=fusemod.make_spec(ds, "count", "pts", {"ecql": q}),
            )
            for q in queries
        ]
        gate.set()
        [f.result(60) for f in futs]
        # heat recorded for the schema at dispatch
        with sched._cv:
            assert sched._schema_heat.get("pts") is not None
    finally:
        gate.set()
        fut.result(5)
        sched.stop()
