"""End-to-end GeoDataset tests — the TestGeoMesaDataStore analog
(SURVEY.md §4.2): the full planner/keyspace/executor stack vs brute-force
numpy oracles, on the 8-virtual-device CPU backend.
"""

import numpy as np
import pytest

from geomesa_tpu import GeoDataset, Query
from geomesa_tpu.filter.ecql import parse_iso_ms

SPEC = (
    "name:String:index=true,age:Integer:index=true,weight:Double,"
    "dtg:Date,*geom:Point;geomesa.z3.interval='week'"
)
N = 20_000


@pytest.fixture(scope="module")
def ds_and_data():
    rng = np.random.default_rng(123)
    ds = GeoDataset(n_shards=8)
    ds.create_schema("gdelt", SPEC)
    data = {
        "name": [f"actor{i % 50}" for i in range(N)],
        "age": rng.integers(0, 100, N).astype(np.int32),
        "weight": rng.uniform(0, 10, N),
        "dtg": rng.integers(
            parse_iso_ms("2020-01-01"), parse_iso_ms("2020-02-01"), N
        ).astype("datetime64[ms]"),
        "geom__x": rng.uniform(-120, -70, N),
        "geom__y": rng.uniform(25, 50, N),
    }
    ds.insert("gdelt", data)
    ds.flush()
    return ds, data


BBOX_TIME = (
    "BBOX(geom, -100, 30, -80, 45) AND "
    "dtg DURING 2020-01-05T00:00:00Z/2020-01-15T00:00:00Z"
)


def oracle_mask(data):
    x, y = data["geom__x"], data["geom__y"]
    t = data["dtg"].astype(np.int64)
    return (
        (x >= -100) & (x <= -80) & (y >= 30) & (y <= 45)
        & (t >= parse_iso_ms("2020-01-05")) & (t <= parse_iso_ms("2020-01-15"))
    )


def test_count_matches_oracle(ds_and_data):
    ds, data = ds_and_data
    got = ds.count("gdelt", BBOX_TIME)
    assert got == int(oracle_mask(data).sum())
    assert ds.count("gdelt") == N


def test_query_features_match_oracle(ds_and_data):
    ds, data = ds_and_data
    fc = ds.query("gdelt", BBOX_TIME)
    want = oracle_mask(data)
    assert len(fc) == int(want.sum())
    # every returned point satisfies the predicate
    xs = fc.columns["geom__x"]
    ys = fc.columns["geom__y"]
    assert ((xs >= -100) & (xs <= -80)).all()
    assert ((ys >= 30) & (ys <= 45)).all()
    ts = fc.columns["dtg"]
    assert (ts >= parse_iso_ms("2020-01-05")).all()
    assert (ts <= parse_iso_ms("2020-01-15")).all()


def test_host_and_device_paths_agree(ds_and_data):
    ds, data = ds_and_data
    ds_host = GeoDataset(n_shards=8, prefer_device=False)
    ds_host._stores = ds._stores  # share store
    assert ds.count("gdelt", BBOX_TIME) == ds_host.count("gdelt", BBOX_TIME)


def test_z3_windows_prune(ds_and_data):
    """The chosen z3 window must cover fewer rows than the table (coarse prune)."""
    ds, data = ds_and_data
    st = ds._store("gdelt")
    from geomesa_tpu.planning.planner import QueryPlanner

    plan = QueryPlanner(st).plan(BBOX_TIME)
    assert plan.index_name == "z3"
    table = st.tables["z3"]
    starts, ends = table.windows(plan.key_plan)
    window_rows = int((ends - starts).sum())
    assert 0 < window_rows < table.n


def test_density_grid(ds_and_data):
    ds, data = ds_and_data
    bbox = (-100, 30, -80, 45)
    grid = ds.density("gdelt", BBOX_TIME, bbox=bbox, width=64, height=32)
    assert grid.shape == (32, 64)
    assert int(grid.sum()) == int(oracle_mask(data).sum())
    # mass is where the points are: compare a coarse 2x2 split against numpy
    m = oracle_mask(data)
    x, y = data["geom__x"][m], data["geom__y"][m]
    left = int((x < -90).sum())
    got_left = grid[:, :32].sum()
    assert abs(got_left - left) / max(left, 1) < 0.02


def test_density_weighted(ds_and_data):
    ds, data = ds_and_data
    bbox = (-100, 30, -80, 45)
    grid = ds.density("gdelt", BBOX_TIME, bbox=bbox, width=16, height=16, weight="weight")
    m = oracle_mask(data)
    assert grid.sum() == pytest.approx(data["weight"][m].sum(), rel=1e-3)


def test_stats_scan(ds_and_data):
    ds, data = ds_and_data
    m = oracle_mask(data)
    st = ds.stats("gdelt", "Count();MinMax(age);DescriptiveStats(weight)", BBOX_TIME)
    vals = st.value()
    assert vals[0] == int(m.sum())
    assert vals[1]["min"] == data["age"][m].min()
    assert vals[1]["max"] == data["age"][m].max()
    assert vals[2]["mean"][0] == pytest.approx(data["weight"][m].mean(), rel=1e-5)


def test_stats_enumeration_and_histogram(ds_and_data):
    ds, data = ds_and_data
    m = oracle_mask(data)
    names = np.array([f"actor{i % 50}" for i in range(N)])
    st = ds.stats("gdelt", "Enumeration(name)", BBOX_TIME)
    counts = st.value()
    assert counts["actor0"] == int((names[m] == "actor0").sum())
    h = ds.stats("gdelt", "Histogram(age,10,0,100)", BBOX_TIME)
    assert int(np.sum(h.value()["counts"])) == int(m.sum())


def test_unique_and_minmax(ds_and_data):
    ds, data = ds_and_data
    u = ds.unique("gdelt", "name", "age < 5")
    names = np.array([f"actor{i % 50}" for i in range(N)])
    want = sorted(set(names[data["age"] < 5]))
    assert u == want
    mm = ds.min_max("gdelt", "weight")
    assert mm["min"] == pytest.approx(data["weight"].min())


def test_attribute_index_used_for_equality(ds_and_data):
    ds, data = ds_and_data
    exp = ds.explain("gdelt", "name = 'actor7'")
    assert "attr" in exp and "Chosen index: attr" in exp
    got = ds.count("gdelt", "name = 'actor7'")
    names = np.array([f"actor{i % 50}" for i in range(N)])
    assert got == int((names == "actor7").sum())


def test_attribute_range_query(ds_and_data):
    ds, data = ds_and_data
    got = ds.count("gdelt", "age BETWEEN 20 AND 30")
    assert got == int(((data["age"] >= 20) & (data["age"] <= 30)).sum())


def test_id_index(ds_and_data):
    ds, data = ds_and_data
    fc = ds.query("gdelt", Query(ecql="INCLUDE", max_features=3))
    fids = fc.fids[:2]
    q = "IN (" + ", ".join(f"'{f}'" for f in fids) + ")"
    exp = ds.explain("gdelt", q)
    assert "Chosen index: id" in exp
    fc2 = ds.query("gdelt", q)
    assert sorted(fc2.fids) == sorted(fids)


def test_sampling_and_limit(ds_and_data):
    ds, data = ds_and_data
    full = ds.count("gdelt", BBOX_TIME)
    sampled = ds.count("gdelt", Query(ecql=BBOX_TIME, sampling=4))
    assert sampled == pytest.approx(full / 4, abs=2)
    fc = ds.query("gdelt", Query(ecql=BBOX_TIME, max_features=7))
    assert len(fc) == 7


def test_sort_and_projection(ds_and_data):
    ds, data = ds_and_data
    fc = ds.query(
        "gdelt",
        Query(ecql="age < 10", sort_by=[("age", False)], properties=["age"],
              max_features=50),
    )
    ages = fc.columns["age"]
    assert (np.diff(ages) >= 0).all()
    assert "weight" not in fc.columns
    assert "__fid__" in fc.columns


def test_knn(ds_and_data):
    ds, data = ds_and_data
    from geomesa_tpu.utils.geometry import haversine_m

    fc = ds.knn("gdelt", -90.0, 38.0, k=15)
    assert len(fc) == 15
    d_all = haversine_m(data["geom__x"], data["geom__y"], -90.0, 38.0)
    want = np.sort(d_all)[:15]
    got = haversine_m(fc.columns["geom__x"], fc.columns["geom__y"], -90.0, 38.0)
    np.testing.assert_allclose(np.sort(got), want, rtol=1e-6)


def test_proximity(ds_and_data):
    ds, data = ds_and_data
    fc = ds.proximity("gdelt", "POINT (-90 38)", 50_000)
    from geomesa_tpu.utils.geometry import haversine_m

    d_all = haversine_m(data["geom__x"], data["geom__y"], -90.0, 38.0)
    assert len(fc) == int((d_all <= 50_000).sum())


def test_explain_tree(ds_and_data):
    ds, _ = ds_and_data
    exp = ds.explain("gdelt", BBOX_TIME)
    assert "Chosen index: z3" in exp
    assert "ranges" in exp and "Candidate indices" in exp


def test_delete_features(ds_and_data):
    ds, data = ds_and_data
    rng = np.random.default_rng(5)
    ds2 = GeoDataset(n_shards=4)
    ds2.create_schema("tmp", SPEC)
    n = 1000
    ds2.insert("tmp", {
        "name": ["a"] * n,
        "age": rng.integers(0, 100, n).astype(np.int32),
        "weight": rng.uniform(0, 1, n),
        "dtg": np.full(n, parse_iso_ms("2021-06-01")).astype("datetime64[ms]"),
        "geom__x": rng.uniform(-10, 10, n),
        "geom__y": rng.uniform(-10, 10, n),
    })
    before = ds2.count("tmp")
    removed = ds2.delete_features("tmp", "age < 50")
    assert before == n
    assert ds2.count("tmp") == n - removed
    assert ds2.count("tmp", "age < 50") == 0


def test_save_load_roundtrip(tmp_path, ds_and_data):
    ds, data = ds_and_data
    p = str(tmp_path / "ckpt")
    ds.save(p)
    ds2 = GeoDataset.load(p)
    assert ds2.count("gdelt", BBOX_TIME) == ds.count("gdelt", BBOX_TIME)
    assert ds2.bounds("gdelt") == ds.bounds("gdelt")
    st = ds2.stats("gdelt", "TopK(name,3)")
    assert len(st.value()) == 3


def test_multi_device_mesh(ds_and_data):
    """pjit path over the 8-virtual-device CPU mesh."""
    import jax

    from geomesa_tpu.parallel import shard_mesh

    assert jax.device_count() == 8
    ds, data = ds_and_data
    mesh = shard_mesh(8)
    ds_mesh = GeoDataset(mesh=mesh)
    ds_mesh._stores = ds._stores
    assert ds_mesh.count("gdelt", BBOX_TIME) == ds.count("gdelt", BBOX_TIME)
    grid = ds_mesh.density("gdelt", BBOX_TIME, bbox=(-100, 30, -80, 45), width=32, height=32)
    assert int(grid.sum()) == int(oracle_mask(data).sum())


def test_empty_and_disjoint_queries(ds_and_data):
    ds, _ = ds_and_data
    assert ds.count("gdelt", "EXCLUDE") == 0
    assert ds.count("gdelt", "BBOX(geom, 0, 0, 1, 1) AND BBOX(geom, 5, 5, 6, 6)") == 0
    assert len(ds.query("gdelt", "age > 1000")) == 0


def test_guards(ds_and_data):
    ds, _ = ds_and_data
    from geomesa_tpu import config

    with config.BLOCK_FULL_TABLE_SCANS.scoped("true"):
        with pytest.raises(ValueError, match="full-table"):
            ds.count("gdelt", "INCLUDE")
    with config.TEMPORAL_GUARD_MAX_DAYS.scoped(3):
        with pytest.raises(ValueError, match="temporal guard"):
            ds.count("gdelt", BBOX_TIME)  # 10-day span > 3
        assert ds.count(
            "gdelt",
            "dtg DURING 2020-01-05T00:00:00Z/2020-01-06T00:00:00Z",
        ) >= 0


def test_window_mask_compare_vs_cumsum():
    """The small-K broadcast-compare window mask must agree with the
    scatter+cumsum form and with the numpy twin for every K."""
    import jax.numpy as jnp

    from geomesa_tpu.kernels import masks as km

    rng = np.random.default_rng(5)
    S, L = 4, 200
    for K in (1, 2, km._COMPARE_MASK_MAX_K, km._COMPARE_MASK_MAX_K + 3):
        starts = np.zeros((S, K), np.int32)
        ends = np.zeros((S, K), np.int32)
        for s in range(S):
            # non-overlapping sorted windows, some padded (0,0)
            edges = np.sort(rng.choice(L, size=2 * K, replace=False))
            nwin = rng.integers(0, K + 1)
            for k in range(nwin):
                starts[s, k], ends[s, k] = edges[2 * k], edges[2 * k + 1]
        counts = rng.integers(1, L + 1, S).astype(np.int32)
        got = np.asarray(km.window_mask(
            jnp.asarray(starts), jnp.asarray(ends), jnp.asarray(counts), L
        ))
        want = km.window_mask_np(starts, ends, counts, L)
        np.testing.assert_array_equal(got, want, err_msg=f"K={K}")


def test_selectivity_counters_in_audit_and_explain(ds_and_data):
    ds, data = ds_and_data
    n = ds.count("gdelt", BBOX_TIME)
    ev = ds.audit.recent(1)[-1]
    assert ev.table_rows == N
    assert ev.scanned >= n > 0
    assert ev.scanned <= N
    out = ds.explain("gdelt", BBOX_TIME, analyze=True)
    assert "Window candidates (scanned)" in out
    assert f"Matched: {int(oracle_mask(data).sum())}" in out


def test_tokenless_plans_do_not_share_window_arrays(ds_and_data):
    """Two raw-IR plans with the same op but different bounds must not hit
    each other's cached device window arrays (r4 code-review finding)."""
    from geomesa_tpu.filter import ir, parse_ecql
    from geomesa_tpu.planning.planner import QueryPlanner

    ds, data = ds_and_data
    st = ds._store("gdelt")
    planner = QueryPlanner(st)
    ex = ds._executor(st)
    x, y = data["geom__x"], data["geom__y"]
    f_a = parse_ecql("BBOX(geom, -100, 30, -80, 45)")
    f_b = parse_ecql("BBOX(geom, -118, 26, -112, 34)")
    plan_a = planner.plan(f_a)   # ir.Filter input -> no cache_token
    plan_b = planner.plan(f_b)
    assert plan_a.__dict__.get("cache_token") is None
    got_a = ex.count(plan_a)
    got_b = ex.count(plan_b)
    want_a = int(((x >= -100) & (x <= -80) & (y >= 30) & (y <= 45)).sum())
    want_b = int(((x >= -118) & (x <= -112) & (y >= 26) & (y <= 34)).sum())
    assert got_a == want_a
    assert got_b == want_b


def test_disjoint_bbox_per_window_pushdown(ds_and_data):
    """Z3Filter/Z2Filter parity (r4): disjoint query boxes must scan their
    own z-windows, not the [zmin, zmax] envelope spanning the gap — the
    explain/audit candidate count stays close to the match count."""
    ds, data = ds_and_data
    x, y = data["geom__x"], data["geom__y"]
    t = data["dtg"].astype(np.int64)
    lo, hi = parse_iso_ms("2020-01-05"), parse_iso_ms("2020-01-15")
    # two far-apart small boxes
    q = (
        "(BBOX(geom, -118, 26, -114, 30) OR BBOX(geom, -76, 45, -72, 49)) "
        "AND dtg DURING 2020-01-05T00:00:00Z/2020-01-15T00:00:00Z"
    )
    in_t = (t >= lo) & (t <= hi)
    b1 = (x >= -118) & (x <= -114) & (y >= 26) & (y <= 30)
    b2 = (x >= -76) & (x <= -72) & (y >= 45) & (y <= 49)
    want = int(((b1 | b2) & in_t).sum())
    got = ds.count("gdelt", q)
    assert got == want
    ev = ds.audit.recent(1)[-1]
    assert ev.hits == want
    # envelope of the two boxes spans most of CONUS; per-window pushdown
    # must admit only a small multiple of the true matches
    assert ev.scanned <= max(60 * want, 2000), (ev.scanned, want)
    # sanity: the envelope would have admitted far more
    env = (x >= -118) & (x <= -72) & (y >= 26) & (y <= 49) & in_t
    assert ev.scanned < int(env.sum())


def test_knn_expanding_radius_prunes_scan(ds_and_data):
    """KNearestNeighborSearchProcess parity (r4): an INCLUDE kNN restricts
    the scan with an expanding bbox — the executed plan's window
    candidates stay far below the table size."""
    from geomesa_tpu.planning.executor import Executor

    ds, data = ds_and_data
    seen = []
    real = Executor.knn

    def spy(self, plan, *a, **kw):
        out = real(self, plan, *a, **kw)
        seen.append(plan)
        return out

    Executor.knn = spy
    try:
        fc = ds.knn("gdelt", -95.0, 38.0, k=5)
    finally:
        Executor.knn = real
    assert len(fc) == 5
    # exactness vs brute force
    from geomesa_tpu.utils.geometry import haversine_m

    d = haversine_m(data["geom__x"], data["geom__y"], -95.0, 38.0)
    want = np.sort(d)[:5]
    got = np.sort(haversine_m(
        fc.columns["geom__x"], fc.columns["geom__y"], -95.0, 38.0
    ))
    np.testing.assert_allclose(got, want, rtol=1e-9)
    # the final executed plan scanned a small fraction of the table
    assert seen, "knn never reached the executor"
    assert seen[-1].__dict__.get("scanned_rows", N) < N // 4


def test_knn_antimeridian_and_pole():
    """r4 review: expanding-radius kNN must wrap the antimeridian and stay
    exact at extreme latitudes (falls back to unrestricted there)."""
    from geomesa_tpu.utils.geometry import haversine_m

    rng = np.random.default_rng(44)
    n = 2_000
    ds = GeoDataset(n_shards=4)
    ds.create_schema("w", "dtg:Date,*geom:Point")
    # clusters on both sides of the dateline plus a polar cap
    x = np.concatenate([
        rng.uniform(179.0, 180.0, n // 2),
        rng.uniform(-180.0, -179.0, n // 4),
        rng.uniform(-180.0, 180.0, n // 4),
    ])
    y = np.concatenate([
        rng.uniform(-5, 5, n // 2),
        rng.uniform(-5, 5, n // 4),
        rng.uniform(85.0, 90.0, n // 4),
    ])
    ds.insert("w", {
        "dtg": np.full(n, parse_iso_ms("2022-06-01")).astype("datetime64[ms]"),
        "geom__x": x, "geom__y": y,
    }, fids=np.arange(n).astype(str))
    ds.flush()
    for qx, qy in ((-179.95, 0.0), (179.95, 1.0), (10.0, 89.5)):
        fc = ds.knn("w", qx, qy, k=8)
        d_all = np.sort(haversine_m(x, y, qx, qy))[:8]
        got = np.sort(haversine_m(
            fc.columns["geom__x"], fc.columns["geom__y"], qx, qy
        ))
        np.testing.assert_allclose(got, d_all, rtol=1e-9), (qx, qy)


def test_knn_selective_filter_fewer_than_k():
    """A base filter matching fewer than k rows must return ALL matches
    (final unrestricted pass), not a truncated bbox subset."""
    rng = np.random.default_rng(45)
    n = 5_000
    ds = GeoDataset(n_shards=4)
    ds.create_schema("s", "name:String,dtg:Date,*geom:Point")
    names = np.array(["rare" if i < 3 else f"c{i % 7}" for i in range(n)])
    ds.insert("s", {
        "name": names.tolist(),
        "dtg": np.full(n, parse_iso_ms("2022-06-01")).astype("datetime64[ms]"),
        "geom__x": rng.uniform(-170, 170, n),
        "geom__y": rng.uniform(-80, 80, n),
    }, fids=np.arange(n).astype(str))
    ds.flush()
    fc = ds.knn("s", 0.0, 0.0, k=10, query="name = 'rare'")
    assert len(fc) == 3


def test_knn_many_locations_no_stale_kernel(ds_and_data):
    """r4 review (confirmed bug): sequential kNN calls at different
    locations must never reuse a kernel with another location's search box
    baked in — every call stays exact vs brute force."""
    from geomesa_tpu.utils.geometry import haversine_m

    ds, data = ds_and_data
    x, y = data["geom__x"], data["geom__y"]
    pts = [(-95.0, 38.0), (-110.0, 45.0), (-80.0, 30.0), (-95.0, 38.0),
           (-118.0, 48.0), (-72.0, 26.0), (-100.0, 40.0), (-90.0, 35.0)]
    for qx, qy in pts:
        fc = ds.knn("gdelt", qx, qy, k=6)
        want = np.sort(haversine_m(x, y, qx, qy))[:6]
        got = np.sort(haversine_m(
            fc.columns["geom__x"], fc.columns["geom__y"], qx, qy
        ))
        np.testing.assert_allclose(got, want, rtol=1e-9,
                                   err_msg=f"stale kernel at {(qx, qy)}")
