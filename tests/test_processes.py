"""Analytic process library tests (geomesa-process parity: tube select,
track ops, route search, joins, sampling)."""

import numpy as np
import pytest

from geomesa_tpu import processes
from geomesa_tpu.api.dataset import GeoDataset, Query
from geomesa_tpu.filter.ecql import parse_iso_ms
from geomesa_tpu.utils import geometry as geo

T0 = parse_iso_ms("2024-01-01T00:00:00Z")


def _tracks_dataset(prefer_device=False):
    """Two vehicles moving east along different latitudes, 1 point/minute."""
    ds = GeoDataset(n_shards=2, prefer_device=prefer_device)
    ds.create_schema(
        "tracks", "vessel:String:index=true,heading:Float,dtg:Date,*geom:Point"
    )
    n = 60
    t = T0 + np.arange(n) * 60_000
    rows = {
        "vessel": ["a"] * n + ["b"] * n,
        "heading": [90.0] * n + [0.0] * n,
        "dtg": np.concatenate([t, t]).astype("datetime64[ms]"),
        # a: lat 10, lon 0..5.9; b: lat 20 (northbound), lon 50
        "geom": [(i * 0.1, 10.0) for i in range(n)]
        + [(50.0, 20.0 + i * 0.1) for i in range(n)],
    }
    ds.insert("tracks", rows, fids=[f"f{i}" for i in range(2 * n)])
    return ds


class TestTubeSelect:
    def test_line_gap_fill_follows_track(self):
        ds = _tracks_dataset()
        # tube follows vehicle a exactly
        tube_xy = [(0.0, 10.0), (5.9, 10.0)]
        tube_t = [T0, T0 + 59 * 60_000]
        fc = ds.tube_select("tracks", tube_xy, tube_t, buffer_m=20_000)
        assert len(fc) == 60
        d = fc.to_dict()
        assert set(d["vessel"]) == {"a"}

    def test_tube_excludes_wrong_time(self):
        ds = _tracks_dataset()
        # same corridor but time-shifted by 10 hours -> no matches
        tube_xy = [(0.0, 10.0), (5.9, 10.0)]
        shift = 36_000_000
        fc = ds.tube_select(
            "tracks", tube_xy, [T0 + shift, T0 + shift + 59 * 60_000], 20_000
        )
        assert len(fc) == 0

    def test_gap_fill_none_only_near_waypoints(self):
        ds = _tracks_dataset()
        tube_xy = [(0.0, 10.0), (5.9, 10.0)]
        tube_t = [T0, T0 + 59 * 60_000]
        fc = ds.tube_select(
            "tracks", tube_xy, tube_t, buffer_m=20_000, gap_fill="none"
        )
        # only points spatially near the two waypoints qualify
        assert 0 < len(fc) < 60

    def test_single_waypoint(self):
        ds = _tracks_dataset()
        fc = ds.tube_select(
            "tracks", [(3.0, 10.0)], [T0 + 30 * 60_000], buffer_m=30_000
        )
        assert len(fc) >= 1
        assert set(fc.to_dict()["vessel"]) == {"a"}

    def test_validation(self):
        ds = _tracks_dataset()
        with pytest.raises(ValueError):
            ds.tube_select("tracks", [(0, 0)], [T0, T0 + 1], 100)


class TestTrackProcesses:
    def test_point2point(self):
        ds = _tracks_dataset()
        lines = ds.point2point("tracks", "vessel")
        assert set(lines) == {"a", "b"}
        a = np.asarray(lines["a"].coords)
        assert len(a) == 60
        # time-ordered west -> east
        assert (np.diff(a[:, 0]) > 0).all()

    def test_point2point_break_on_day(self):
        ds = GeoDataset(n_shards=2, prefer_device=False)
        ds.create_schema("t", "v:String,dtg:Date,*geom:Point")
        t = np.array([T0, T0 + 3_600_000, T0 + 90_000_000, T0 + 93_600_000])
        ds.insert("t", {
            "v": ["a"] * 4,
            "dtg": t.astype("datetime64[ms]"),
            "geom": [(float(i), 0.0) for i in range(4)],
        })
        lines = ds.point2point("t", "v", break_on_day=True)
        assert len(lines) == 2  # split at the UTC day boundary

    def test_track_label_latest_point(self):
        ds = _tracks_dataset()
        fc = ds.track_label("tracks", "vessel")
        assert len(fc) == 2
        d = fc.to_dict()
        by_vessel = dict(zip(d["vessel"], d["geom"]))
        assert by_vessel["a"][0] == pytest.approx(5.9)
        assert by_vessel["b"][1] == pytest.approx(25.9)

    def test_date_offset(self):
        ds = _tracks_dataset()
        fc = processes.date_offset(ds, "tracks", 86_400_000, "vessel = 'a'")
        t = fc.batch.columns["dtg"].astype(np.int64)
        assert t.min() == T0 + 86_400_000

    def test_hash_attribute_stable(self):
        ds = _tracks_dataset()
        h1 = processes.hash_attribute(ds, "tracks", "vessel", 7)
        h2 = processes.hash_attribute(ds, "tracks", "vessel", 7)
        assert (h1 == h2).all()
        assert ((h1 >= 0) & (h1 < 7)).all()
        # same vessel -> same hash
        v = ds.query("tracks").to_dict()["vessel"]
        codes = {}
        for vi, hi in zip(v, h1):
            codes.setdefault(vi, set()).add(int(hi))
        assert all(len(s) == 1 for s in codes.values())


class TestRouteSearch:
    def test_route_buffer(self):
        ds = _tracks_dataset()
        fc = ds.route_search("tracks", "LINESTRING (0 10, 6 10)", 15_000)
        assert set(fc.to_dict()["vessel"]) == {"a"}
        assert len(fc) == 60

    def test_route_heading_filter(self):
        ds = _tracks_dataset()
        # vehicle a heads east (90); route bearing is east -> matches
        fc = ds.route_search(
            "tracks", "LINESTRING (0 10, 6 10)", 15_000,
            heading_attr="heading", heading_tolerance_deg=30,
        )
        assert len(fc) == 60
        # a north-south route near vessel a matches nothing with heading filter
        fc2 = ds.route_search(
            "tracks", "LINESTRING (3 9.99, 3 10.01)", 2_000,
            heading_attr="heading", heading_tolerance_deg=10,
            bidirectional=False,
        )
        assert len(fc2) == 0


class TestJoins:
    def test_attribute_join(self):
        ds = _tracks_dataset()
        ds.create_schema("meta", "vessel:String,flag:String")
        ds.insert("meta", {"vessel": ["a", "c"], "flag": ["US", "FR"]})
        out = ds.join("tracks", "meta", "vessel", "vessel")
        assert out.n == 60  # only vessel a matches
        assert (out.columns["right.flag"] == 0).all()  # dict code for 'US'

    def test_spatial_join_assign_and_counts(self):
        ds = _tracks_dataset()
        polys = [
            "POLYGON ((-1 9, 3.05 9, 3.05 11, -1 11, -1 9))",   # first 31 a-points
            "POLYGON ((49 19, 51 31, 51 19, 49 19))",            # some b-points
            "POLYGON ((100 0, 101 0, 101 1, 100 1, 100 0))",    # empty
        ]
        assign, counts = ds.spatial_join("tracks", polys)
        assert counts.shape == (3,)
        assert counts[0] == 31
        assert counts[2] == 0
        assert counts.sum() == (assign >= 0).sum()

    def test_spatial_join_device_matches_host(self):
        polys = ["POLYGON ((0.55 9, 3.05 9, 3.05 11, 0.55 11, 0.55 9))"]
        a1, c1 = _tracks_dataset(prefer_device=False).spatial_join("tracks", polys)
        a2, c2 = _tracks_dataset(prefer_device=True).spatial_join("tracks", polys)
        assert c1.tolist() == c2.tolist()
        assert (a1 == a2).all()

    def test_spatial_join_with_holes(self):
        ds = GeoDataset(n_shards=2, prefer_device=False)
        ds.create_schema("p", "*geom:Point")
        ds.insert("p", {"geom": [(0.5, 0.5), (0.05, 0.05)]})
        donut = "POLYGON ((0 0, 1 0, 1 1, 0 1, 0 0), (0.2 0.2, 0.8 0.2, 0.8 0.8, 0.2 0.8, 0.2 0.2))"
        assign, counts = ds.spatial_join("p", [donut])
        assert counts[0] == 1  # center point is in the hole
        assert assign.tolist().count(-1) == 1


class TestSampling:
    def test_one_in_n(self):
        ds = _tracks_dataset()
        fc = ds.sample("tracks", 10)
        assert len(fc) == pytest.approx(12, abs=2)
        fc2 = ds.sample("tracks", 10)
        assert len(fc) == len(fc2)  # deterministic
