"""Replica-fleet chaos suite (docs/RESILIENCE.md §7).

Cross-process-shaped (each "replica" is its own GeoFlightServer +
GeoDataset over one shared storage root — separate caches, separate
schedulers, Flight in between) chaos scenarios for the cell-affinity
router:

* affinity routing is deterministic and bit-identical to the
  single-process answer; scattered counts add exactly;
* a replica killed / drained / wedged mid-workload never hangs or
  corrupts a query: every admitted query completes bit-identical via
  failover or returns typed ``[GM-FLEET-PARTIAL]`` with EXACT survivor
  totals (re-running the skip records' sub-queries reconciles to the
  full answer);
* a mutation routed through the fleet invalidates every replica's
  covering cache entries before any replica answers from them
  (interleaved write/read schedule, restarted-replica case included).
"""

import json
import threading

import numpy as np
import pytest

from geomesa_tpu import GeoDataset, config, metrics, obs, resilience
from geomesa_tpu.fleet import FleetRouter, RendezvousRing
from geomesa_tpu.resilience import (
    AdmissionRejectedError, FleetPartialError, allow_partial, inject_faults,
)

SPEC = "name:String:index=true,speed:Float,dtg:Date,*geom:Point"
N = 900

VIEWPORTS = [
    "BBOX(geom, -30, -20, 10, 20)",
    "BBOX(geom, 0, 0, 40, 25)",
    "BBOX(geom, -45, -28, -5, 5)",
    "BBOX(geom, 5, -25, 45, 15)",
]


def _data(n=N, seed=5):
    rng = np.random.default_rng(seed)
    xs = rng.uniform(-45, 45, n)
    ys = rng.uniform(-28, 28, n)
    # pin some rows to exact routing-cell edges: the scatter's disjoint
    # half-open cells must place each edge row in exactly one sub-query
    for i, v in enumerate((-45.0, 0.0, 22.5, 45.0)):
        xs[i], ys[i] = v, 0.0
    return {
        "name": [f"n{i % 4}" for i in range(n)],
        "speed": rng.uniform(0, 30, n).astype(np.float32),
        "dtg": (np.datetime64("2024-05-01", "ms")
                + rng.integers(0, 20 * 86_400_000, n)),
        "geom": [(float(x), float(y)) for x, y in zip(xs, ys)],
    }


@pytest.fixture(autouse=True)
def _fresh_breakers():
    """The ``replica:<id>`` breakers live in the process-wide named
    registry: reset them between tests so one scenario's opened circuit
    never fences the next scenario's fresh replicas."""
    resilience.reset_breakers()
    yield
    resilience.reset_breakers()


@pytest.fixture(scope="module")
def root(tmp_path_factory):
    path = str(tmp_path_factory.mktemp("fleet_root"))
    seed = GeoDataset(n_shards=1, prefer_device=False)
    seed.create_schema("t", SPEC)
    seed.insert("t", _data(), fids=[f"f{i}" for i in range(N)])
    seed.flush("t")
    seed.save(path)
    return path


@pytest.fixture(scope="module")
def oracle(root):
    return GeoDataset.load(root, prefer_device=False)


def _replica(root, rid):
    from geomesa_tpu.sidecar import GeoFlightServer

    return GeoFlightServer(
        GeoDataset.load(root, prefer_device=False),
        replica_id=rid, fleet_root=root,
    )


def _router(servers):
    return FleetRouter({
        rid: f"grpc+tcp://127.0.0.1:{srv.port}"
        for rid, srv in servers.items()
    })


@pytest.fixture()
def fleet(root):
    servers = {rid: _replica(root, rid) for rid in ("r1", "r2", "r3")}
    router = _router(servers)
    yield servers, router
    router.close()
    for srv in servers.values():
        try:
            srv.shutdown()
        except Exception:
            pass


# ---------------------------------------------------------------------------
# ring
# ---------------------------------------------------------------------------


def test_ring_minimal_rebalance():
    """Removing a member re-homes ONLY that member's keys (the HRW
    property the warm-cache story rests on); adding it back restores the
    original assignment exactly."""
    ring3 = RendezvousRing(["a", "b", "c"])
    keys = [f"t:z3:{i}" for i in range(200)]
    before = {k: ring3.owner(k) for k in keys}
    ring2 = RendezvousRing(["a", "b"])
    moved = [k for k in keys if before[k] != ring2.owner(k)]
    assert all(before[k] == "c" for k in moved)  # only c's keys moved
    # and c's keys moved to their SECOND choice on the old ring
    for k in moved:
        assert ring2.owner(k) == ring3.owners(k)[1]
    ring3b = RendezvousRing(["b", "c", "a"])
    assert {k: ring3b.owner(k) for k in keys} == before  # order-free


def test_ring_owner_order_is_failover_path():
    ring = RendezvousRing(["a", "b", "c"])
    for k in ("x", "y", "z"):
        owners = ring.owners(k)
        assert sorted(owners) == ["a", "b", "c"]
        assert owners[0] == ring.owner(k)


# ---------------------------------------------------------------------------
# routing + scatter
# ---------------------------------------------------------------------------


def test_affinity_routing_bit_identical(fleet, oracle):
    """Every viewport routes to its stable ring owner (affinity, no
    failover on a healthy fleet) and the routed answers equal the
    single-process oracle exactly — scattered counts included."""
    servers, router = fleet
    for ecql in VIEWPORTS:
        assert router.count("t", ecql) == oracle.count("t", ecql)
    snap = router.snapshot()
    assert snap["counters"]["failover"] == 0
    assert snap["counters"]["partial"] == 0
    assert snap["counters"]["affinity"] > 0
    # repeats keep routing to the same owners: affinity grows, still no
    # failover — the warm-cache precondition
    for ecql in VIEWPORTS:
        assert router.count("t", ecql) == oracle.count("t", ecql)
    assert router.snapshot()["counters"]["failover"] == 0


def test_scatter_engages_and_adds_exactly(fleet, oracle):
    servers, router = fleet
    ecql = "BBOX(geom, -44, -27, 44, 27)"
    n0 = router.snapshot()["counters"]["scatter"]
    assert router.count("t", ecql) == oracle.count("t", ecql)
    assert router.snapshot()["counters"]["scatter"] > n0
    # scatter off routes whole — same answer
    with config.FLEET_SCATTER.scoped("false"):
        assert router.count("t", ecql) == oracle.count("t", ecql)


def test_density_and_stats_route_bit_identical(fleet, oracle):
    servers, router = fleet
    ecql = VIEWPORTS[0]
    grid = router.density("t", ecql, bbox=(-45, -28, 45, 28),
                          width=64, height=32)
    want = oracle.density("t", ecql, bbox=(-45, -28, 45, 28),
                          width=64, height=32)
    assert np.array_equal(grid, want)
    s = router.stats("t", "MinMax(speed)", ecql)
    assert s.to_json() == oracle.stats("t", "MinMax(speed)", ecql).to_json()
    g1, sn1 = router.density_curve("t", ecql, level=6,
                                   bbox=(-45, -28, 45, 28))
    g0, sn0 = oracle.density_curve("t", ecql, level=6,
                                   bbox=(-45, -28, 45, 28))
    assert sn1 == sn0 and np.array_equal(g1, g0)


# ---------------------------------------------------------------------------
# failover / kill / drain / wedge
# ---------------------------------------------------------------------------


def test_kill_one_replica_mid_workload_failover(fleet, oracle):
    """SIGKILL-shaped loss of one replica (server shutdown, no goodbye):
    every query still answers bit-identically via the next ring owner,
    within the query's own budget — zero hangs, zero partials."""
    servers, router = fleet
    expected = {e: oracle.count("t", e) for e in VIEWPORTS}
    for e in VIEWPORTS:  # warm routing
        assert router.count("t", e) == expected[e]
    servers.pop("r1").shutdown()
    results = {}
    errors = []

    def run(e):
        try:
            with resilience.deadline_scope(30.0):
                results[e] = router.count("t", e)
        except Exception as exc:  # pragma: no cover - the assert reports
            errors.append((e, exc))

    threads = [threading.Thread(target=run, args=(e,)) for e in VIEWPORTS]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
        assert not t.is_alive(), "fleet query hung after replica kill"
    assert not errors, errors
    assert results == expected
    snap = router.snapshot()
    assert snap["counters"]["partial"] == 0
    # the dead replica's breaker opened: later routing skips it outright
    assert snap["counters"]["failover"] >= 1


def test_drain_via_admin_then_undrain(fleet, oracle):
    servers, router = fleet
    ecql = VIEWPORTS[1]
    out = router.drain_replica("r2", reason="maintenance")
    assert out["draining"] is True and out["replica"] == "r2"
    assert router.registry.state("r2") == "draining"
    # a direct client to the drained replica is refused typed+retryable
    from geomesa_tpu.resilience import DeviceDrainError
    from geomesa_tpu.sidecar import GeoFlightClient
    from geomesa_tpu.sidecar.client import is_retryable

    with GeoFlightClient(
        f"grpc+tcp://127.0.0.1:{servers['r2'].port}"
    ) as c, config.RETRY_ATTEMPTS.scoped("1"):
        with pytest.raises(DeviceDrainError) as ei:
            c.count("t", ecql)
        assert is_retryable(ei.value)
    # routed traffic keeps working (owners exclude the drained replica)
    assert router.count("t", ecql) == oracle.count("t", ecql)
    status = router.probe("r2")
    assert status["draining"] is True
    router.undrain_replica("r2")
    assert router.registry.state("r2") == "ok"
    assert router.count("t", ecql) == oracle.count("t", ecql)


def test_wedged_replica_bounded_by_deadline_failover(fleet, oracle):
    """A wedged replica (accepts the call, stalls, then errors) costs
    its delay, not the query: under a live deadline the router fails
    over and completes inside the budget."""
    servers, router = fleet
    ecql = VIEWPORTS[2]
    want = oracle.count("t", ecql)
    assert router.count("t", ecql) == want  # warm the route + schema
    import time as _time

    with config.FAULT_INJECTION.scoped("true"), \
            config.RETRY_ATTEMPTS.scoped("1"), \
            config.FLEET_SCATTER.scoped("false"), \
            inject_faults(seed=3) as inj:
        inj.fail("sidecar.do_action", times=1, delay_s=0.2)
        t0 = _time.perf_counter()
        with resilience.deadline_scope(20.0):
            assert router.count("t", ecql) == want
        assert _time.perf_counter() - t0 < 20.0
    assert router.snapshot()["counters"]["failover"] >= 1


def test_all_owners_down_degrades_typed(root, oracle):
    servers = {rid: _replica(root, rid) for rid in ("ra", "rb")}
    router = _router(servers)
    try:
        ecql = VIEWPORTS[0]
        assert router.count("t", ecql) == oracle.count("t", ecql)
        for srv in servers.values():
            srv.shutdown()
        with config.RETRY_ATTEMPTS.scoped("1"), \
                config.FLEET_SCATTER.scoped("false"):
            # strict mode: typed [GM-FLEET-PARTIAL], never a hang
            with resilience.deadline_scope(30.0), \
                    pytest.raises(FleetPartialError, match="GM-FLEET"):
                router.count("t", ecql)
            # degraded mode: the survivor total (zero survivors -> 0)
            # with the skip recorded — the §3 contract over replicas
            with resilience.deadline_scope(30.0), allow_partial() as p:
                assert router.count("t", ecql) == 0
            assert len(p.skipped) == 1
            assert p.skipped[0].source == "fleet.route"
    finally:
        router.close()


def test_scatter_partial_has_exact_survivor_totals(root, oracle):
    """One owner group failing on EVERY candidate degrades the scattered
    count with EXACT survivor accounting: the returned total plus the
    oracle's answers to the skip records' sub-queries (carried verbatim
    in ``Skipped.phase``) reconstructs the full count exactly."""
    servers = {rid: _replica(root, rid) for rid in ("ra", "rb")}
    router = _router(servers)
    try:
        ecql = "BBOX(geom, -44, -27, 44, 27)"
        want = oracle.count("t", ecql)
        assert router.count("t", ecql) == want  # warm schema + routes
        with config.FAULT_INJECTION.scoped("true"), \
                config.RETRY_ATTEMPTS.scoped("1"), \
                inject_faults(seed=11) as inj:
            # fail the FIRST scattered group on its owner AND the
            # failover candidate (2 candidates in a 2-replica fleet)
            inj.fail("sidecar.do_action", times=2)
            with allow_partial() as p:
                got = router.count("t", ecql)
        assert p.skipped, "no group was skipped"
        missing = sum(
            oracle.count("t", rec.phase) for rec in p.skipped
        )
        assert got + missing == want
        assert got < want  # something really was skipped
        # strict mode raises typed instead, with the same accounting
        with config.FAULT_INJECTION.scoped("true"), \
                config.RETRY_ATTEMPTS.scoped("1"), \
                inject_faults(seed=12) as inj:
            inj.fail("sidecar.do_action", times=2)
            with pytest.raises(FleetPartialError) as ei:
                router.count("t", ecql)
        err = ei.value
        assert "[GM-FLEET-PARTIAL]" in str(err)
        missing = sum(oracle.count("t", rec.phase) for rec in err.skipped)
        assert err.value + missing == want
        assert err.ok == err.total - len(err.skipped)
    finally:
        router.close()
        for srv in servers.values():
            srv.shutdown()


# ---------------------------------------------------------------------------
# epoch propagation
# ---------------------------------------------------------------------------


def _one_row(x=0.5, y=0.5):
    tmp = GeoDataset(n_shards=1, prefer_device=False)
    tmp.create_schema("t", SPEC)
    tmp.insert("t", {
        "name": ["fresh"],
        "speed": np.array([1.0], np.float32),
        "dtg": np.array([np.datetime64("2024-05-21", "ms")]),
        "geom": [(x, y)],
    }, fids=["fresh1"])
    return tmp.to_arrow("t")


def test_epoch_interleaved_write_read_no_stale_cache(root, oracle,
                                                     monkeypatch):
    """The acceptance regression (ISSUE): an interleaved write/read
    schedule across replicas with WARM aggregate caches — a write routed
    through the fleet invalidates every replica's covering entries
    before any replica answers from them. The cache knob is set via env
    (thread-local scopes never reach the replicas' dispatch threads)."""
    monkeypatch.setenv("GEOMESA_CACHE_ENABLED", "true")
    servers = {rid: _replica(root, rid) for rid in ("ra", "rb")}
    router = _router(servers)
    try:
        ecql = "BBOX(geom, -10, -10, 10, 10)"
        with config.FLEET_SCATTER.scoped("false"):
            before = oracle.count("t", ecql)
            # warm BOTH replicas' covering caches for this viewport
            for rid in ("ra", "rb"):
                order = [rid] + [r for r in ("ra", "rb") if r != rid]
                n, _ = router._call("t", "k", "count",
                                    lambda c: c.count("t", ecql),
                                    owners=order)
                assert n == before
            # write through the router (stamped epoch)
            router.insert_arrow("t", _one_row(0.5, 0.5))
            # read from EACH replica explicitly: both must reflect the
            # write — neither may serve its warm pre-mutation cover
            for rid in ("ra", "rb"):
                order = [rid] + [r for r in ("ra", "rb") if r != rid]
                n, _ = router._call("t", "k", "count",
                                    lambda c: c.count("t", ecql),
                                    owners=order)
                assert n == before + 1, f"stale cache served by {rid}"
    finally:
        router.close()
        for srv in servers.values():
            srv.shutdown()


def test_epoch_restarted_replica_refreshes_before_serving(root, oracle):
    """A replica that MISSED a fleet write (down while it landed, then
    rejoined with pre-mutation in-memory state and fleet epoch 0) must
    refresh from the shared root — forced by the router's epoch header —
    before it may answer anything for that schema."""
    servers = {rid: _replica(root, rid) for rid in ("ra", "rb")}
    router = _router(servers)
    try:
        ecql = "BBOX(geom, -10, -10, 10, 10)"
        with config.FLEET_SCATTER.scoped("false"):
            before = router.count("t", ecql)
            # rb goes down; the write lands (on ra, persisted to root)
            servers["rb"].shutdown()
            router.insert_arrow("t", _one_row(0.2, 0.2))
            assert router.count("t", ecql) == before + 1
            # rb restarts FROM THE ROOT AS OF ITS LAST BOOT? No — a
            # restarted process loads current root, so simulate a STALE
            # replica instead: a server whose dataset predates the write
            stale = GeoDataset(n_shards=1, prefer_device=False)
            stale.create_schema("t", SPEC)
            stale.insert("t", _data(), fids=[f"f{i}" for i in range(N)])
            stale.flush("t")
            from geomesa_tpu.sidecar import GeoFlightServer

            servers["rb"] = GeoFlightServer(
                stale, replica_id="rb", fleet_root=root,
            )
            router.add_replica(
                "rb", f"grpc+tcp://127.0.0.1:{servers['rb'].port}"
            )
            # force the read onto the stale replica: the router's epoch
            # header makes it refresh from the shared root FIRST
            n, _ = router._call("t", "k", "count",
                                lambda c: c.count("t", ecql),
                                owners=["rb", "ra"])
            assert n == before + 1, "stale replica served pre-write data"
    finally:
        router.close()
        for srv in servers.values():
            try:
                srv.shutdown()
            except Exception:
                pass


def test_epoch_latch_requires_root_proof(tmp_path):
    """The write/read race regression: a read stamped epoch E that
    arrives BEFORE the write establishing E landed in the shared root
    must NOT latch E over the stale refresh — the replica latches only
    what the root's epoch marker proves, so the next request refreshes
    again and picks the write up the moment it commits."""
    import json as _json

    from geomesa_tpu.sidecar import GeoFlightClient, GeoFlightServer

    root = str(tmp_path / "race_root")
    seed = GeoDataset(n_shards=1, prefer_device=False)
    seed.create_schema("t", SPEC)
    seed.insert("t", _data(200), fids=[f"f{i}" for i in range(200)])
    seed.flush("t")
    seed.save(root)
    ecql = "BBOX(geom, -10, -10, 10, 10)"
    before = seed.count("t", ecql)
    srv = _replica(root, "ra")
    hdr = [(b"x-geomesa-fleet-epochs", _json.dumps({"t": 3}).encode())]
    try:
        with GeoFlightClient(
            f"grpc+tcp://127.0.0.1:{srv.port}",
            header_provider=lambda: hdr,
        ) as c:
            # the claimed epoch 3 has NOT committed: the replica
            # refreshes (pre-E root — same data) and must latch below 3
            assert c.count("t", ecql) == before
            assert c.replica_status()["epochs"].get("t", 0) < 3
            # the write "lands": fresh data + the root marker at 3
            seed.insert("t", {
                "name": ["late"],
                "speed": np.array([1.0], np.float32),
                "dtg": np.array([np.datetime64("2024-05-21", "ms")]),
                "geom": [(0.0, 0.0)],
            }, fids=["late1"])
            seed.flush("t")
            seed.save(root, names=["t"])
            marker = str(
                tmp_path / "race_root" / GeoFlightServer._FLEET_EPOCH_FILE
            )
            with open(marker, "w") as fh:
                _json.dump({"t": 3}, fh)
            # the un-latched replica re-refreshes and serves the write
            assert c.count("t", ecql) == before + 1
            assert c.replica_status()["epochs"]["t"] == 3
    finally:
        srv.shutdown()


def test_create_schema_propagates(fleet):
    servers, router = fleet
    router.create_schema("t2", SPEC)
    router.insert_arrow("t2", _one_row(1.0, 1.0))
    # every replica serves the new schema (refresh-on-epoch)
    for rid in servers:
        order = [rid] + [r for r in servers if r != rid]
        n, _ = router._call("t2", "k", "count",
                            lambda c: c.count("t2", "INCLUDE"),
                            owners=order)
        assert n == 1
    router.delete_schema("t2")
    assert "t2" not in router.list_schemas()


# ---------------------------------------------------------------------------
# admission + observability
# ---------------------------------------------------------------------------


def test_router_admission_bound_rejects_typed(fleet):
    servers, router = fleet
    with config.FLEET_MAX_INFLIGHT.scoped("0"):
        with pytest.raises(AdmissionRejectedError):
            router.count("t", VIEWPORTS[0])
    # the rejection landed in the shared ledger (same _UserLedger policy)
    rollups = router.serving.user_rollups()
    assert any(r["rejected"] >= 1 for r in rollups.values())


def test_debug_fleet_endpoint(fleet):
    servers, router = fleet
    router.count("t", VIEWPORTS[0])
    out = obs.handle("/debug/fleet")
    assert out is not None
    code, ctype, body = out
    assert code == 200
    payload = json.loads(body)
    snap = next(s for s in payload["routers"]
                if set(s["replicas"]) == {"r1", "r2", "r3"})
    assert snap["counters"]["affinity"] >= 1
    assert "users" in snap and "summary" in snap


def test_replica_gossip_headers_round_trip(fleet):
    """Responses carry the replica id + epoch map; the client captures
    them (the router's membership-discovery channel)."""
    servers, router = fleet
    router.insert_arrow("t", _one_row(3.0, 3.0))
    router.count("t", VIEWPORTS[0])
    seen = set()
    for rid in servers:
        c = router._client(rid)
        if c.last_replica_id is not None:
            seen.add(c.last_replica_id)
            assert c.last_epochs is not None
            assert c.last_epochs.get("t", 0) >= 1
    assert seen, "no replica gossiped its identity back"


def test_replica_breaker_fences_dead_replica(root):
    servers = {rid: _replica(root, rid) for rid in ("ra", "rb")}
    router = _router(servers)
    try:
        servers["ra"].shutdown()
        with config.FLEET_BREAKER_THRESHOLD.scoped("2"), \
                config.RETRY_ATTEMPTS.scoped("1"), \
                config.FLEET_SCATTER.scoped("false"):
            for e in VIEWPORTS:
                router.count("t", e)  # failures feed ra's breaker
            assert router.registry.state("ra") == "broken"
            assert not router.registry.usable("ra")
            # fenced: routing now skips ra entirely (pure affinity on rb)
            f0 = router.snapshot()["counters"]["failover"]
            router.count("t", VIEWPORTS[0])
            assert router.snapshot()["counters"]["failover"] == f0
    finally:
        router.close()
        servers["rb"].shutdown()


# ---------------------------------------------------------------------------
# scatter-gather for every mergeable aggregate (ISSUE 15)


def _oracle_now(root):
    """A single-process oracle over the root's CURRENT contents — the
    module-scoped ``oracle`` predates the epoch tests' fleet writes, so
    scatter bit-identity must compare against a fresh load."""
    return GeoDataset.load(root, prefer_device=False)


WIDE = "BBOX(geom, -44, -27, 44, 27)"
WIDE_BBOX = (-45.0, -28.0, 45.0, 28.0)


def test_scatter_all_kinds_bit_identical(fleet, root):
    """The tentpole contract: density grids, exact-merge stats, and
    density-curve windows SCATTER across owner groups and compose
    bit-identically to the single-process oracle; per-kind scatter
    counters and the merge histogram record each one."""
    servers, router = fleet
    oracle = _oracle_now(root)
    n0 = router.snapshot()["counters"]["scatter"]
    m0 = metrics.registry().report()

    grid = router.density("t", WIDE, bbox=WIDE_BBOX, width=64, height=32)
    want = oracle.density("t", WIDE, bbox=WIDE_BBOX, width=64, height=32)
    assert np.array_equal(grid, want)

    for spec in ("MinMax(speed)", "Histogram(speed,10,0,30)"):
        s = router.stats("t", spec, WIDE)
        assert s.to_json() == oracle.stats("t", spec, WIDE).to_json()

    g1, sn1 = router.density_curve("t", WIDE, level=6, bbox=WIDE_BBOX)
    g0, sn0 = oracle.density_curve("t", WIDE, level=6, bbox=WIDE_BBOX)
    assert sn1 == sn0
    assert np.array_equal(g1, g0)

    assert router.count("t", WIDE) == oracle.count("t", WIDE)

    snap = router.snapshot()
    assert snap["counters"]["scatter"] >= n0 + 5
    m1 = metrics.registry().report()
    for kind in ("density", "stats", "curve", "count"):
        key = f"fleet.scatter.{kind}"
        assert m1.get(key, 0) > m0.get(key, 0), key
    merge_h = m1.get("fleet.scatter.merge_ms")
    assert merge_h and merge_h["count"] >= 5
    # per-owner-group survivor rows ride /debug/fleet
    assert snap["scatter"], "no per-owner scatter rows"
    assert all(row["skipped_groups"] == 0
               for row in snap["scatter"].values())
    # non-mergeable kinds still route whole: weighted density never
    # scatters (f32 rounding is order-dependent)
    n1 = snap["counters"]["scatter"]
    gw = router.density("t", WIDE, bbox=WIDE_BBOX, width=32, height=16,
                        weight="speed")
    ww = oracle.density("t", WIDE, bbox=WIDE_BBOX, width=32, height=16,
                        weight="speed")
    assert np.array_equal(gw, ww)
    assert router.snapshot()["counters"]["scatter"] == n1


def test_scatter_groups_pinned_to_ring_order(root):
    """The merge-order regression (ISSUE 15 satellite): owner-group
    order comes from the RING (sorted member tuple), never from dict
    insertion or replica registration order — two routers built with
    the same members in different orders produce IDENTICAL group lists,
    so the fixed-order merge (and survivor group lists) is deterministic
    across router restarts."""
    from geomesa_tpu.filter.ecql import parse_ecql
    from geomesa_tpu.cache import cells as cellmod

    ft = _oracle_now(root).get_schema("t")
    decomp = cellmod.decompose(parse_ecql(WIDE), ft)
    assert decomp is not None and len(decomp.cells) > 1
    locs = {"ra": "grpc+tcp://127.0.0.1:1", "rb": "grpc+tcp://127.0.0.1:2",
            "rc": "grpc+tcp://127.0.0.1:3"}
    r1 = FleetRouter(dict(locs))
    r2 = FleetRouter({k: locs[k] for k in ("rc", "ra", "rb")})
    try:
        g1 = r1._scatter_groups("t", decomp)
        g2 = r2._scatter_groups("t", decomp)
        assert isinstance(g1, list) and g1 == g2
        owners = [o for o, _ in g1]
        ring_order = [m for m in r1.ring.members if m in set(owners)]
        assert owners == ring_order
    finally:
        r1.close()
        r2.close()


def test_density_scatter_partial_exact_survivor_groups(root):
    """The chaos gate (ISSUE 15): one owner group of a scattered density
    failing on EVERY candidate degrades typed with EXACT per-owner-group
    survivor accounting — the returned grid plus the oracle's grids for
    the skip records' sub-queries (carried verbatim in ``Skipped.phase``)
    reconstructs the full raster bit-exactly; strict mode raises
    ``[GM-FLEET-PARTIAL]`` naming the missing groups. Serial fan-out
    (fanout=1) pins which group the injected faults land on."""
    oracle = _oracle_now(root)
    servers = {rid: _replica(root, rid) for rid in ("ra", "rb")}
    router = _router(servers)
    kw = dict(bbox=WIDE_BBOX, width=48, height=24)
    try:
        want = oracle.density("t", WIDE, **kw)
        assert np.array_equal(router.density("t", WIDE, **kw), want)
        with config.FAULT_INJECTION.scoped("true"), \
                config.RETRY_ATTEMPTS.scoped("1"), \
                config.FLEET_SCATTER_FANOUT.scoped("1"), \
                inject_faults(seed=21) as inj:
            # fail the FIRST scattered group on its owner AND the only
            # failover candidate (2 candidates in a 2-replica fleet)
            inj.fail("sidecar.do_get", times=2)
            with resilience.deadline_scope(30.0), allow_partial() as p:
                got = router.density("t", WIDE, **kw)
        assert p.skipped, "no group was skipped"
        missing = np.zeros_like(want)
        for rec in p.skipped:
            assert "cells[" in rec.part or "strips[" in rec.part
            missing = missing + oracle.density("t", rec.phase, **kw)
        assert np.array_equal(got + missing, want)
        assert not np.array_equal(got, want)  # something really skipped
        # per-owner-group rows account the skip
        snap = router.snapshot()
        assert any(row["skipped_groups"] >= 1
                   for row in snap["scatter"].values())
        # strict mode raises typed instead, same accounting
        with config.FAULT_INJECTION.scoped("true"), \
                config.RETRY_ATTEMPTS.scoped("1"), \
                config.FLEET_SCATTER_FANOUT.scoped("1"), \
                inject_faults(seed=22) as inj:
            inj.fail("sidecar.do_get", times=2)
            with resilience.deadline_scope(30.0), \
                    pytest.raises(FleetPartialError) as ei:
                router.density("t", WIDE, **kw)
        err = ei.value
        assert "[GM-FLEET-PARTIAL]" in str(err)
        assert err.ok == err.total - len(err.skipped)
        missing = np.zeros_like(want)
        for rec in err.skipped:
            missing = missing + oracle.density("t", rec.phase, **kw)
        assert np.array_equal(err.value + missing, want)
    finally:
        router.close()
        for srv in servers.values():
            srv.shutdown()


def test_scatter_kill_owner_mid_workload_fails_over(fleet, root):
    """SIGKILL-shaped loss of one replica under a scattered workload:
    its owner groups fail over to surviving ring candidates — scattered
    density/stats stay bit-identical, zero partials, no hang."""
    servers, router = fleet
    oracle = _oracle_now(root)
    kw = dict(bbox=WIDE_BBOX, width=48, height=24)
    want = oracle.density("t", WIDE, **kw)
    assert np.array_equal(router.density("t", WIDE, **kw), want)
    servers.pop("r2").shutdown()
    with resilience.deadline_scope(30.0):
        got = router.density("t", WIDE, **kw)
        s = router.stats("t", "MinMax(speed)", WIDE)
    assert np.array_equal(got, want)
    assert s.to_json() == oracle.stats("t", "MinMax(speed)", WIDE).to_json()
    assert router.snapshot()["counters"]["partial"] == 0


# ---------------------------------------------------------------------------
# dynamic membership + warm handoff + auto-uncordon (ISSUE 15)
# ---------------------------------------------------------------------------


def test_register_replica_runtime_join(root):
    """A replica joining at RUNTIME (identity learned from the gossip
    headers) starts receiving its ring share without a router restart."""
    oracle = _oracle_now(root)
    servers = {rid: _replica(root, rid) for rid in ("ra", "rb")}
    router = FleetRouter({
        "ra": f"grpc+tcp://127.0.0.1:{servers['ra'].port}"
    })
    try:
        assert router.count("t", VIEWPORTS[0]) == oracle.count(
            "t", VIEWPORTS[0])
        rid = router.register_replica(
            f"grpc+tcp://127.0.0.1:{servers['rb'].port}"
        )
        assert rid == "rb"
        assert "rb" in router.ring.members
        assert "rb" in router.registry.members()
        # the joiner owns ITS HRW share of the key space immediately
        keys = [f"t:z3:{i}" for i in range(64)]
        assert any(router.ring.owner(k) == "rb" for k in keys)
        # routed traffic reaches it (route a count pinned to rb)
        n, served = router._call("t", "k", "count",
                                 lambda c: c.count("t", VIEWPORTS[0]),
                                 owners=["rb", "ra"])
        assert served == "rb"
        assert n == oracle.count("t", VIEWPORTS[0])
        assert router.snapshot()["counters"]["joined"] == 1
    finally:
        router.close()
        for srv in servers.values():
            srv.shutdown()


def test_deregister_warm_handoff_new_owner_serves_from_cache(
        root, monkeypatch):
    """The acceptance gate (ISSUE 15): a warm-handoff drain pushes the
    leaver's hottest entries to the new ring owners — the new owner
    answers the drained replica's hottest viewport FROM CACHE (zero
    scans: cache.hit increments, cache.miss does not)."""
    monkeypatch.setenv("GEOMESA_CACHE_ENABLED", "true")
    oracle = _oracle_now(root)
    servers = {rid: _replica(root, rid) for rid in ("ra", "rb", "rc")}
    router = _router(servers)
    try:
        vp = VIEWPORTS[0]
        f, ft = router._parse("t", vp)
        owner = router.ring.owner(router._affinity_key("t", f, ft))
        with config.FLEET_SCATTER.scoped("false"):
            want = router.count("t", vp)  # warms the owner's cache
            out = router.deregister_replica(owner, handoff=True)
            assert out["handoff"]["t"]["restored"] >= 1
            assert owner not in router.ring.members
            new_owner = router.ring.owner(router._affinity_key("t", f, ft))
            c = router._client(new_owner)
            # the in-process replicas share one metrics registry with
            # any LOCAL dataset: keep the oracle's own count outside the
            # measurement window
            assert want == oracle.count("t", vp)
            m0 = c.metrics()
            assert router.count("t", vp) == want
            m1 = c.metrics()
        assert m1.get("cache.hit", 0) - m0.get("cache.hit", 0) >= 1, \
            "new owner did not serve the handed-off viewport from cache"
        assert m1.get("cache.miss", 0) == m0.get("cache.miss", 0), \
            "new owner paid a scan despite the warm handoff"
        assert router.snapshot()["counters"]["left"] == 1
    finally:
        router.close()
        for srv in servers.values():
            try:
                srv.shutdown()
            except Exception:
                pass


def test_auto_uncordon_after_k_successful_probes(fleet):
    """ISSUE 15 satellite: a router-side cordon clears after K
    consecutive successful probes (geomesa.fleet.uncordon.probes), with
    the fleet.uncordon counter bumped; a failed probe resets the streak;
    config-list cordons (geomesa.fleet.cordon) never auto-clear."""
    servers, router = fleet
    m0 = metrics.registry().report().get("fleet.uncordon", 0)
    # successes BEFORE the cordon must not pre-pay the exit: only probes
    # made while cordoned count toward the streak
    for _ in range(3):
        assert router.probe("r2")["ok"]
    router.cordon("r2", reason="flapping")
    assert router.registry.state("r2") == "cordoned"
    with config.FLEET_UNCORDON_PROBES.scoped("3"):
        router.probe("r2")
        router.probe("r2")
        assert router.registry.state("r2") == "cordoned"  # streak 2 < 3
        out = router.probe("r2")
    assert out.get("uncordoned") is True
    assert router.registry.state("r2") == "ok"
    assert metrics.registry().report().get("fleet.uncordon", 0) == m0 + 1
    assert router.snapshot()["counters"]["uncordoned"] == 1
    # a failed probe resets the streak
    router.cordon("r2", reason="again")
    with config.FLEET_UNCORDON_PROBES.scoped("2"):
        router.probe("r2")
        router.registry.note_probe("r2", False)  # the reset
        router.probe("r2")
        assert router.registry.state("r2") == "cordoned"
        router.probe("r2")
    assert router.registry.state("r2") == "ok"
    # config-list cordons stay operator-owned
    with config.FLEET_CORDON.scoped("r3"), \
            config.FLEET_UNCORDON_PROBES.scoped("1"):
        assert router.registry.state("r3") == "cordoned"
        router.probe("r3")
        assert router.registry.state("r3") == "cordoned"


def test_standing_subscription_survives_join_and_drained_leave(root):
    """ISSUE 17 satellite: a fleet membership change — a runtime JOIN
    and a drained LEAVE of the subscription's owner — migrates standing
    results with ZERO missed and ZERO duplicated updates: the version
    sequence a poller observes stays contiguous across both events, and
    every polled result equals the routed from-scratch count at the same
    point in the schedule."""
    servers = {rid: _replica(root, rid) for rid in ("sa", "sb", "sc")}
    router = _router(servers)
    bbox = (-30.0, -20.0, 10.0, 20.0)
    vp = "BBOX(geom, -30, -20, 10, 20)"
    try:
        sub_id = router.subscribe("t", "count", bbox=bbox)
        from geomesa_tpu.subscribe import route_key_of

        seen = []  # every update record the poller ever observes

        def poll(cursor):
            got = router.subscription_poll(sub_id, cursor=cursor)
            seen.extend(got["updates"])
            assert got["result"]["v"] == router.count("t", vp)
            return int(got["version"])

        cursor = poll(0)
        assert [u["kind"] for u in seen] == ["snapshot"]

        # ingest through the router: the standing result advances by a
        # delta wherever the subscription lives
        router.insert_arrow("t", _one_row(0.5, 0.5))
        cursor = poll(cursor)
        assert seen[-1]["kind"] == "delta"

        # runtime JOIN: if the new member takes the route key, the group
        # must move to it (export remove=True + import) — either way the
        # poller must not observe a gap or a repeat
        extra = _replica(root, "sd")
        servers["sd"] = extra
        router.register_replica(f"grpc+tcp://127.0.0.1:{extra.port}")
        cursor = poll(cursor)
        router.insert_arrow("t", _one_row(0.6, 0.6))
        cursor = poll(cursor)

        # drained LEAVE of the CURRENT owner: subscribe-export answers
        # mid-drain (admin), the post-removal ring owner adopts the
        # group verbatim under the {count, spec} guard
        owner = router._owners(route_key_of(sub_id))[0]
        out = router.deregister_replica(owner, handoff=True)
        subs = out["handoff"].get("subscriptions") or {}
        assert subs.get("adopted", 0) + subs.get("resynced", 0) >= 1
        cursor = poll(cursor)
        router.insert_arrow("t", _one_row(0.7, 0.7))
        cursor = poll(cursor)

        # zero missed, zero duplicated: one snapshot, then a contiguous
        # version walk with no repeats
        versions = [u["version"] for u in seen]
        assert versions == sorted(set(versions))
        assert versions == list(range(1, versions[-1] + 1))
        kinds = [u["kind"] for u in seen]
        assert kinds[0] == "snapshot"
        assert kinds.count("delta") >= 3
    finally:
        router.close()
        for srv in servers.values():
            try:
                srv.shutdown()
            except Exception:
                pass
