"""Curve kernels vs brute-force oracles.

Analog of the reference's Z3Test / XZ3SFCTest / BinnedTimeTest (SURVEY.md §4.1),
but property-style against slow bit-loop oracles.
"""

import numpy as np
import pytest

from geomesa_tpu.curves.binned_time import BinnedTime, TimePeriod, WEEK_MS
from geomesa_tpu.curves.cover import zcover, ZRange
from geomesa_tpu.curves.xz import XZ2SFC, XZ3SFC
from geomesa_tpu.curves.zorder import (
    Z2SFC,
    Z3SFC,
    NormalizedDimension,
    deinterleave2,
    deinterleave3,
    device_interleave,
    interleave2,
    interleave3,
    join_u64,
    split_u64,
)


def slow_interleave(dims, bits):
    """Bit-loop oracle matching the documented layout."""
    d = len(dims)
    z = 0
    for i in range(bits):
        for k in range(d):
            z |= ((int(dims[k]) >> i) & 1) << (d * i + (d - 1 - k))
    return z


def test_interleave2_matches_oracle(rng):
    xs = rng.integers(0, 1 << 31, size=200, dtype=np.uint64)
    ys = rng.integers(0, 1 << 31, size=200, dtype=np.uint64)
    z = interleave2(xs, ys)
    for i in range(0, 200, 17):
        assert int(z[i]) == slow_interleave([xs[i], ys[i]], 31)
    xi, yi = deinterleave2(z)
    np.testing.assert_array_equal(xi, xs)
    np.testing.assert_array_equal(yi, ys)


def test_interleave3_matches_oracle(rng):
    xs = rng.integers(0, 1 << 21, size=200, dtype=np.uint64)
    ys = rng.integers(0, 1 << 21, size=200, dtype=np.uint64)
    ts = rng.integers(0, 1 << 21, size=200, dtype=np.uint64)
    z = interleave3(xs, ys, ts)
    for i in range(0, 200, 17):
        assert int(z[i]) == slow_interleave([xs[i], ys[i], ts[i]], 21)
    xi, yi, ti = deinterleave3(z)
    np.testing.assert_array_equal(xi, xs)
    np.testing.assert_array_equal(yi, ys)
    np.testing.assert_array_equal(ti, ts)


def test_device_interleave_matches_host(rng):
    import jax

    xs = rng.integers(0, 1 << 21, size=64, dtype=np.uint64)
    ys = rng.integers(0, 1 << 21, size=64, dtype=np.uint64)
    ts = rng.integers(0, 1 << 21, size=64, dtype=np.uint64)
    host_z = interleave3(xs, ys, ts)
    hi, lo = jax.jit(lambda a, b, c: device_interleave([a, b, c], 21))(
        xs.astype(np.int32), ys.astype(np.int32), ts.astype(np.int32)
    )
    dev_z = join_u64(np.asarray(hi), np.asarray(lo))
    np.testing.assert_array_equal(dev_z, host_z)
    # and the split/join helpers roundtrip
    h2, l2 = split_u64(host_z)
    np.testing.assert_array_equal(join_u64(h2, l2), host_z)


def test_normalized_dimension_roundtrip():
    dim = NormalizedDimension(-180.0, 180.0, 21)
    xs = np.linspace(-180, 180, 1000)
    idx = dim.normalize(xs)
    back = dim.denormalize(idx)
    res = 360.0 / (1 << 21)
    assert np.max(np.abs(back - xs)) <= res
    assert dim.normalize(np.array([-180.0]))[0] == 0
    assert dim.normalize(np.array([180.0]))[0] == (1 << 21) - 1
    assert dim.normalize(np.array([1e9]))[0] == (1 << 21) - 1  # clipped


def _cover_is_exact(lo, hi, bits, dims, max_ranges=10_000):
    """Oracle: every cell's z is in ranges iff the cell is in the box."""
    ranges = zcover(lo, hi, bits=bits, dims=dims, max_ranges=max_ranges)
    # Build membership set.
    covered = set()
    for r in ranges:
        covered.update(range(r.lo, r.hi + 1))
    size = 1 << bits
    for z in range(1 << (bits * dims)):
        coords = []
        for k in range(dims):
            c = 0
            for i in range(bits):
                c |= ((z >> (dims * i + (dims - 1 - k))) & 1) << i
            coords.append(c)
        inside = all(lo[k] <= coords[k] <= hi[k] for k in range(dims))
        assert (z in covered) == inside, f"z={z} coords={coords}"


def test_zcover_exact_small_2d():
    _cover_is_exact((1, 2), (5, 6), bits=3, dims=2)
    _cover_is_exact((0, 0), (7, 7), bits=3, dims=2)
    _cover_is_exact((3, 3), (3, 3), bits=3, dims=2)


def test_zcover_exact_small_3d():
    _cover_is_exact((1, 0, 2), (2, 3, 3), bits=2, dims=3)


def test_zcover_budget_overcovers_but_contains():
    lo, hi = (1, 2), (6, 5)
    exact = zcover(lo, hi, bits=3, dims=2, max_ranges=10_000)
    budget = zcover(lo, hi, bits=3, dims=2, max_ranges=4)
    assert len(budget) <= 6
    exact_set = set()
    for r in exact:
        exact_set.update(range(r.lo, r.hi + 1))
    budget_set = set()
    for r in budget:
        budget_set.update(range(r.lo, r.hi + 1))
    assert exact_set <= budget_set  # never loses a match


def test_z2_ranges_contain_points(rng):
    sfc = Z2SFC()
    bbox = (-10.0, 35.0, 5.0, 42.0)
    xs = rng.uniform(bbox[0], bbox[2], 500)
    ys = rng.uniform(bbox[1], bbox[3], 500)
    zs = sfc.index(xs, ys)
    ranges = sfc.ranges(*bbox)
    lows = np.array([r.lo for r in ranges], dtype=np.uint64)
    his = np.array([r.hi for r in ranges], dtype=np.uint64)
    for z in zs:
        i = np.searchsorted(lows, z, side="right") - 1
        assert i >= 0 and z <= his[i], f"point z {z} not covered"


def test_z3_ranges_contain_points(rng):
    sfc = Z3SFC(TimePeriod.WEEK)
    xs = rng.uniform(-74.1, -73.9, 300)
    ys = rng.uniform(40.6, 40.9, 300)
    ts = rng.uniform(1e8, 5e8, 300)  # offsets within the week
    zs = sfc.index(xs, ys, ts)
    ranges = sfc.ranges((-74.1, -73.9), (40.6, 40.9), (1e8, 5e8))
    lows = np.array([r.lo for r in ranges], dtype=np.uint64)
    his = np.array([r.hi for r in ranges], dtype=np.uint64)
    for z in zs:
        i = np.searchsorted(lows, z, side="right") - 1
        assert i >= 0 and z <= his[i]


def test_binned_time_roundtrip(rng):
    for period in TimePeriod:
        bt = BinnedTime(period)
        ts = rng.integers(0, 1_700_000_000_000, size=1000, dtype=np.int64)
        b, off = bt.to_bin_and_offset(ts)
        start = bt.bin_start_ms(b)
        np.testing.assert_array_equal(start + off, ts)
        assert np.all(off >= 0)
        assert np.all(off <= bt.max_offset_ms)


def test_binned_time_week_matches_division():
    bt = BinnedTime(TimePeriod.WEEK)
    b, off = bt.to_bin_and_offset(np.array([WEEK_MS * 100 + 1234], dtype=np.int64))
    assert b[0] == 100 and off[0] == 1234


def test_xz2_index_and_ranges(rng):
    sfc = XZ2SFC(g=8)
    # random small boxes
    n = 300
    x0 = rng.uniform(-170, 160, n)
    y0 = rng.uniform(-80, 70, n)
    w = rng.uniform(0.001, 5.0, n)
    h = rng.uniform(0.001, 5.0, n)
    codes = sfc.index(x0, y0, x0 + w, y0 + h)
    assert np.all(codes >= 0)
    query = (-20.0, -20.0, 30.0, 25.0)
    ranges = sfc.ranges(*query)
    lows = np.array([r.lo for r in ranges])
    his = np.array([r.hi for r in ranges])
    # every element that intersects the query must be covered
    inter = (x0 <= query[2]) & (x0 + w >= query[0]) & (y0 <= query[3]) & (y0 + h >= query[1])
    for c, isect in zip(codes, inter):
        i = np.searchsorted(lows, c, side="right") - 1
        covered = i >= 0 and c <= his[i]
        if isect:
            assert covered, f"intersecting element code {c} not covered"


def test_xz3_index_and_ranges(rng):
    sfc = XZ3SFC(TimePeriod.WEEK, g=6)
    n = 200
    x0 = rng.uniform(-170, 160, n)
    y0 = rng.uniform(-80, 70, n)
    t0 = rng.uniform(0, WEEK_MS * 0.9, n)
    w = rng.uniform(0.001, 2.0, n)
    dt = rng.uniform(1.0, WEEK_MS * 0.05, n)
    codes = sfc.index(x0, y0, t0, x0 + w, y0 + w, t0 + dt)
    query_x, query_y, query_t = (-20.0, 30.0), (-20.0, 25.0), (0.0, WEEK_MS * 0.5)
    ranges = sfc.ranges(query_x, query_y, query_t)
    lows = np.array([r.lo for r in ranges])
    his = np.array([r.hi for r in ranges])
    inter = (
        (x0 <= query_x[1]) & (x0 + w >= query_x[0])
        & (y0 <= query_y[1]) & (y0 + w >= query_y[0])
        & (t0 <= query_t[1]) & (t0 + dt >= query_t[0])
    )
    for c, isect in zip(codes, inter):
        i = np.searchsorted(lows, c, side="right") - 1
        covered = i >= 0 and c <= his[i]
        if isect:
            assert covered
