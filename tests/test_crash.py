"""Crash-consistency suite for the durable mutation journal
(docs/RESILIENCE.md §8).

The core contract under test: **ack = durable**. Once a mutation call
returns, a SIGKILL at ANY later point — including inside the save/
checkpoint machinery, via the injected ``journal.*`` / ``fs.*`` fault
points — must leave a root that recovers to a state containing every
acked mutation. The kill-point walk runs a fixed op script in a child
process, kills it at each recorded fault-point hit, and checks the
recovered dataset is bit-identical to a never-crashed control built from
some op prefix that covers everything the child acked (durable-but-
unacked tail ops are allowed; an acked-but-lost op is the failure).

Also here: group-commit concurrency (no acked append may vanish on
reopen), torn-tail truncation, the delete-schema tombstone, stream
offset resume, and the crc-framed fleet epoch marker's corruption
quarantine.
"""

import json
import os
import signal
import subprocess
import sys
import threading

import numpy as np
import pytest

from geomesa_tpu import GeoDataset, config, metrics
from geomesa_tpu.fs import journal as journal_mod
from geomesa_tpu.fs.journal import MutationJournal

SPEC = "name:String,weight:Double,dtg:Date,*geom:Point"


def _data(n, seed=11, tag="op"):
    rng = np.random.default_rng(seed)
    return {
        "name": [f"{tag}{seed}_{i}" for i in range(n)],
        "weight": rng.uniform(0, 10, n),
        "dtg": rng.integers(1577836800000, 1583020800000, n)
        .astype("datetime64[ms]"),
        "geom__x": rng.uniform(-120, -70, n),
        "geom__y": rng.uniform(25, 50, n),
    }


# ---------------------------------------------------------------------------
# the kill-point walk
# ---------------------------------------------------------------------------

# The op script both the child and the control run. Each mutation op acks
# by appending its index to acked.log AFTER the call returns — exactly the
# caller's view of durability.
_CHILD = r"""
import json, os, signal, sys
sys.path.insert(0, {repo!r})
os.environ.setdefault("JAX_PLATFORMS", "cpu")
import numpy as np
from geomesa_tpu import GeoDataset, resilience

root = {root!r}
mode = {mode!r}          # "record" | "kill"
kill_site = {kill_site!r}
kill_hit = {kill_hit}
hits = {{}}

_real = resilience.fault_point
def hooked(site, **ctx):
    if site.startswith("journal.") or site.startswith("fs."):
        hits[site] = hits.get(site, 0) + 1
        if mode == "kill" and site == kill_site and hits[site] == kill_hit:
            os.kill(os.getpid(), signal.SIGKILL)
    return _real(site, **ctx)
resilience.fault_point = hooked

ack_fh = open(os.path.join(root, "acked.log"), "a")
def ack(i):
    ack_fh.write(f"{{i}}\n")
    ack_fh.flush()
    os.fsync(ack_fh.fileno())

def _data(n, seed, tag="op"):
    rng = np.random.default_rng(seed)
    return {{
        "name": [f"{{tag}}{{seed}}_{{i}}" for i in range(n)],
        "weight": rng.uniform(0, 10, n),
        "dtg": rng.integers(1577836800000, 1583020800000, n)
        .astype("datetime64[ms]"),
        "geom__x": rng.uniform(-120, -70, n),
        "geom__y": rng.uniform(25, 50, n),
    }}

SPEC = {spec!r}
ds = GeoDataset(prefer_device=False)
ds.attach_journal(root)
ops = [
    lambda: ds.create_schema("t", SPEC),
    lambda: ds.insert("t", _data(8, 1), fids=[f"a{{i}}" for i in range(8)]),
    lambda: ds.insert("t", _data(8, 2), fids=[f"b{{i}}" for i in range(8)]),
    lambda: (ds.flush(), ds.save(root)),
    lambda: ds.insert("t", _data(8, 3), fids=[f"c{{i}}" for i in range(8)]),
    lambda: ds.delete_features("t", "weight > 9"),
    lambda: ds.update_schema("t", "extra:Integer"),
    lambda: ds.insert(
        "t", dict(_data(8, 4), extra=np.arange(8, dtype=np.int64)),
        fids=[f"d{{i}}" for i in range(8)]),
    lambda: (ds.flush(), ds.save(root)),
]
stop_at = {stop_at}
for i, op in enumerate(ops[: stop_at if stop_at >= 0 else len(ops)]):
    op()
    ack(i)
if mode == "record":
    with open(os.path.join(root, "hits.json"), "w") as fh:
        json.dump(hits, fh)
print("DONE")
"""


def _run_child(tmp_path, root, mode, kill_site="", kill_hit=0, stop_at=-1):
    script = _CHILD.format(
        repo=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        root=root, mode=mode, kill_site=kill_site, kill_hit=kill_hit,
        spec=SPEC, stop_at=stop_at,
    )
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    return subprocess.run(
        [sys.executable, "-c", script], env=env,
        capture_output=True, text=True, timeout=300,
    )


def _state(ds):
    """Comparable snapshot of schema 't' (absent -> None). Names go
    through to_arrow so dictionary codes decode to the REAL strings —
    comparing raw codes would mask a resurrected/lost row whose code
    happens to collide."""
    if "t" not in ds._stores:
        return None
    names = sorted(
        "" if v is None else str(v)
        for v in ds.to_arrow("t").column("name").to_pylist()
    )
    return {
        "spec": ds.get_schema("t").spec(),
        "count": int(ds.count("t")),
        "names": names,
    }


def _acked(root):
    try:
        with open(os.path.join(root, "acked.log")) as fh:
            return [int(x) for x in fh.read().split()]
    except FileNotFoundError:
        return []


def _walk_kill_points(tmp_path, points):
    """Kill the op script at each (site, hit); recovery must reproduce a
    never-crashed control covering every acked op."""
    # never-crashed controls for every op prefix (built once, in-process)
    controls = {}
    for p in range(10):
        croot = str(tmp_path / f"control{p}")
        os.makedirs(croot)
        r = _run_child(tmp_path, croot, "record", stop_at=p)
        assert r.returncode == 0, r.stderr[-2000:]
        try:
            controls[p] = _state(GeoDataset.load(croot, prefer_device=False))
        except FileNotFoundError:
            controls[p] = None

    lost = []
    for n, (site, hit) in enumerate(points):
        root = str(tmp_path / f"kill{n}")
        os.makedirs(root)
        r = _run_child(tmp_path, root, "kill", kill_site=site, kill_hit=hit)
        if r.returncode == 0:
            continue  # walk raced past the point (e.g. committer batching)
        assert r.returncode == -signal.SIGKILL
        acked = _acked(root)
        n_acked = len(acked)
        try:
            got = _state(GeoDataset.load(root, prefer_device=False))
        except FileNotFoundError:
            got = None
        # prefix consistency: recovered state == control(p) for some
        # p >= n_acked (durable-but-unacked tail allowed, acked-lost not)
        ok = any(got == controls[p] for p in range(n_acked, 10))
        if not ok:
            lost.append((site, hit, n_acked, got))
    assert not lost, f"acked mutations lost at kill points: {lost}"


def _recorded_points(tmp_path):
    root = str(tmp_path / "record")
    os.makedirs(root)
    r = _run_child(tmp_path, root, "record")
    assert r.returncode == 0, r.stderr[-2000:]
    with open(os.path.join(root, "hits.json")) as fh:
        hits = json.load(fh)
    assert any(s.startswith("journal.") for s in hits), hits
    return [(site, h) for site, n in sorted(hits.items())
            for h in range(1, n + 1)]


@pytest.mark.slow
def test_kill_point_walk_full(tmp_path):
    """SIGKILL at EVERY recorded ``journal.*`` / ``fs.*`` fault-point hit:
    zero acked mutations lost (the ISSUE's acceptance sweep)."""
    _walk_kill_points(tmp_path, _recorded_points(tmp_path))


def test_kill_point_walk_smoke(tmp_path):
    """Non-slow slice of the walk: one kill inside the journal fsync and
    one inside the checkpoint's manifest publish — the two windows where
    a naive implementation loses acked data."""
    points = _recorded_points(tmp_path)
    picked = []
    for prefer in ("journal.fsync", "fs.save.manifest"):
        got = [pt for pt in points if pt[0] == prefer]
        if got:
            picked.append(got[len(got) // 2])
    assert picked, f"no usable kill points recorded: {points}"
    _walk_kill_points(tmp_path, picked)


# ---------------------------------------------------------------------------
# group commit
# ---------------------------------------------------------------------------


def test_group_commit_concurrent_appends_all_durable(tmp_path):
    """N writer threads appending concurrently: every acked seq must be
    present after reopen, exactly once, and batches actually grouped."""
    root = str(tmp_path)
    with config.JOURNAL_GROUP_MS.scoped("5"):
        j = MutationJournal(root)
        acked = []
        lock = threading.Lock()

        def writer(t):
            for i in range(25):
                seq = j.append({"kind": "noop", "schema": "t",
                                "writer": t, "i": i})
                with lock:
                    acked.append(seq)

        threads = [threading.Thread(target=writer, args=(t,))
                   for t in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        j.close()
    assert len(acked) == 200 and len(set(acked)) == 200
    j2 = MutationJournal(root)
    seqs = [int(r["seq"]) for r in j2.records()]
    assert sorted(seqs) == sorted(acked)
    j2.close()


# ---------------------------------------------------------------------------
# torn tails
# ---------------------------------------------------------------------------


def _seg_paths(root):
    d = os.path.join(root, journal_mod.JOURNAL_DIR)
    return [os.path.join(d, f) for f in sorted(os.listdir(d))
            if f.endswith(".gmj")]


def test_torn_tail_truncates_cleanly(tmp_path):
    root = str(tmp_path)
    j = MutationJournal(root)
    for i in range(5):
        j.append({"kind": "noop", "schema": "t", "i": i})
    j.close()
    seg = _seg_paths(root)[-1]
    size = os.path.getsize(seg)
    # torn write: the last frame went down partially
    with open(seg, "r+b") as fh:
        fh.truncate(size - 7)
    before = metrics.registry().counter(metrics.JOURNAL_TORN_TAILS).value
    j2 = MutationJournal(root)
    recs = j2.records()
    assert [int(r["i"]) for r in recs] == [0, 1, 2, 3]  # valid prefix only
    assert metrics.registry().counter(
        metrics.JOURNAL_TORN_TAILS).value > before
    # the tail is REPAIRED on disk: appends sequence after the survivors
    seq = j2.append({"kind": "noop", "schema": "t", "i": 99})
    assert seq == max(int(r["seq"]) for r in recs) + 1
    j2.close()


def test_corrupt_frame_crc_stops_replay_at_last_valid(tmp_path):
    root = str(tmp_path)
    j = MutationJournal(root)
    for i in range(3):
        j.append({"kind": "noop", "schema": "t", "i": i})
    j.close()
    seg = _seg_paths(root)[-1]
    with open(seg, "r+b") as fh:
        fh.seek(-3, os.SEEK_END)  # flip a payload byte of the last frame
        b = fh.read(1)
        fh.seek(-3, os.SEEK_END)
        fh.write(bytes([b[0] ^ 0xFF]))
    j2 = MutationJournal(root)
    assert [int(r["i"]) for r in j2.records()] == [0, 1]
    j2.close()


# ---------------------------------------------------------------------------
# tombstones
# ---------------------------------------------------------------------------


def test_delete_schema_tombstone_survives_replay(tmp_path):
    """create -> insert -> drop, all journaled past the checkpoint: replay
    must NOT resurrect the dropped schema from its earlier records."""
    root = str(tmp_path)
    ds = GeoDataset(prefer_device=False)
    ds.attach_journal(root)
    ds.create_schema("t", SPEC)
    ds.insert("t", _data(8, 1), fids=[f"a{i}" for i in range(8)])
    ds.delete_schema("t")
    ds2 = GeoDataset.load(root, prefer_device=False)
    assert "t" not in ds2._stores


def test_delete_schema_tombstone_after_checkpoint(tmp_path):
    """Checkpointed schema files still on disk + a journaled tombstone:
    the drop wins over the checkpoint attach."""
    root = str(tmp_path)
    ds = GeoDataset(prefer_device=False)
    ds.attach_journal(root)
    ds.create_schema("t", SPEC)
    ds.insert("t", _data(8, 1), fids=[f"a{i}" for i in range(8)])
    ds.flush()
    ds.save(root)
    ds.delete_schema("t")  # journaled, NOT yet checkpointed
    ds2 = GeoDataset.load(root, prefer_device=False)
    assert "t" not in ds2._stores
    # and the next checkpoint makes the drop durable standalone
    ds2.save(root)
    ds3 = GeoDataset.load(root, prefer_device=False)
    assert "t" not in ds3._stores


# ---------------------------------------------------------------------------
# stream resume
# ---------------------------------------------------------------------------


def test_stream_journal_resume_exactly_once(tmp_path):
    from geomesa_tpu.stream.live import StreamingDataset
    from geomesa_tpu.stream.messages import MessageBus

    root = str(tmp_path)
    bus = MessageBus()
    sds = StreamingDataset(bus=bus, partitions=2)
    sds.attach_journal(root)
    sds.create_schema("t", SPEC)
    sds.write(
        "t",
        {"name": ["x", "y"], "weight": [1.0, 2.0],
         "dtg": [1577836800000, 1577836800001],
         "geom": [(0.0, 0.0), (1.0, 1.0)]},
        ["f1", "f2"], ts_ms=[10, 11],
    )
    assert sds.poll("t") == 2
    offsets = list(sds._offsets["t"])
    sds._journal.close()

    # restart: same broker (topic retention), fresh consumer + journal
    sds2 = StreamingDataset(bus=bus, partitions=2)
    sds2.attach_journal(root)
    assert sds2.recover() >= 2  # stream-create + stream-batch
    assert "t" in sds2._schemas
    assert len(sds2.cache("t")) == 2
    assert sds2._offsets["t"] == offsets
    # exactly-once: nothing replays twice out of the topic
    assert sds2.poll("t") == 0
    assert len(sds2.cache("t")) == 2


def test_confluent_offset_resume(tmp_path):
    from geomesa_tpu.stream.confluent import (
        SchemaRegistry, attach_confluent, confluent_resume_offset,
    )
    from geomesa_tpu.stream.live import StreamingDataset
    from geomesa_tpu.stream.messages import MessageBus

    root = str(tmp_path)
    bus = MessageBus()
    sds = StreamingDataset(bus=bus, partitions=1)
    sds.attach_journal(root)
    sds.create_schema("t", SPEC)
    reg = SchemaRegistry()
    ser, ingest = attach_confluent(sds, "t", reg)
    for off in range(3):
        payload = ser.serialize(f"f{off}", {
            "name": f"n{off}", "weight": 1.0, "dtg": 1577836800000 + off,
            "geom": "POINT (0 0)",
        })
        assert ingest(payload, ts_ms=1577836800000 + off, offset=off)
    assert confluent_resume_offset(sds, "t") == 2
    sds._journal.close()

    sds2 = StreamingDataset(bus=bus, partitions=1)
    sds2.attach_journal(root)
    sds2.recover()
    # the restarted broker consumer seeks past every acked record
    assert confluent_resume_offset(sds2, "t") == 2


# ---------------------------------------------------------------------------
# fleet epoch marker framing
# ---------------------------------------------------------------------------


def test_epoch_marker_roundtrip_and_legacy(tmp_path):
    root = str(tmp_path)
    journal_mod.write_epoch_marker(root, {"t": 3, "u": 7}, journal_seq=41)
    epochs, seq = journal_mod.read_epoch_marker(root)
    assert epochs == {"t": 3, "u": 7} and seq == 41
    # v1 legacy flat dict still reads
    with open(os.path.join(root, journal_mod.EPOCH_MARKER_FILE), "w") as fh:
        json.dump({"t": 9}, fh)
    epochs, seq = journal_mod.read_epoch_marker(root)
    assert epochs == {"t": 9} and seq == 0


def test_epoch_marker_corruption_quarantines(tmp_path):
    root = str(tmp_path)
    journal_mod.write_epoch_marker(root, {"t": 5}, journal_seq=1)
    path = os.path.join(root, journal_mod.EPOCH_MARKER_FILE)
    with open(path, "r+b") as fh:
        fh.seek(10)
        fh.write(b"\xff\xfe")
    before = metrics.registry().counter(
        metrics.FLEET_EPOCH_MARKER_QUARANTINED).value
    epochs, seq = journal_mod.read_epoch_marker(root)
    # safe direction: unreadable marker reads as empty (replicas refresh
    # redundantly, never serve stale), and the evidence is kept aside
    assert epochs == {} and seq == 0
    assert not os.path.exists(path)
    assert os.path.exists(path + ".quarantine")
    assert metrics.registry().counter(
        metrics.FLEET_EPOCH_MARKER_QUARANTINED).value > before


# ---------------------------------------------------------------------------
# recovery interop
# ---------------------------------------------------------------------------


def test_checkpoint_truncates_journal_segments(tmp_path):
    root = str(tmp_path)
    ds = GeoDataset(prefer_device=False)
    ds.attach_journal(root)
    ds.create_schema("t", SPEC)
    for s in range(4):
        ds.insert("t", _data(64, s), fids=[f"s{s}_{i}" for i in range(64)])
    ds.flush()
    before = sum(os.path.getsize(p) for p in _seg_paths(root))
    ds.save(root)
    after = sum(os.path.getsize(p) for p in _seg_paths(root))
    assert after < before  # checkpoint reclaimed covered segments
    # and nothing replays on the next load
    ds2 = GeoDataset.load(root, prefer_device=False)
    assert ds2._journal_replayed == 0
    assert ds2.count("t") == ds.count("t")


def test_replay_bit_identical_values(tmp_path):
    """Journal replay must reproduce the exact column values (the tagged
    codec round-trips dates, floats, and strings losslessly)."""
    root = str(tmp_path)
    data = _data(32, 7)
    ds = GeoDataset(prefer_device=False)
    ds.attach_journal(root)
    ds.create_schema("t", SPEC)
    ds.insert("t", data, fids=[f"f{i}" for i in range(32)])
    ds.flush()
    ds2 = GeoDataset.load(root, prefer_device=False)
    a = ds.query("t", "INCLUDE").batch
    b = ds2.query("t", "INCLUDE").batch
    assert a.n == b.n
    for k, col in a.columns.items():
        got = b.columns[k]
        if getattr(col, "dtype", None) is not None and col.dtype.kind == "f":
            np.testing.assert_array_equal(col, got)  # bit-identical, NaN-safe
        else:
            assert list(map(str, col)) == list(map(str, got)), k
