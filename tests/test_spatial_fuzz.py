"""Randomized spatial differential tests: random polygons (convex and
star-concave, some with holes, some multi) queried as INTERSECTS /
DISJOINT / DWITHIN over a point table. The device-preferring store, the
host-only store, count(), query(), and density() must all agree — this
cross-checks the window pushdown, the PIP kernels, coarse+refine, and
the aggregation paths against each other."""

pytestmark = __import__("pytest").mark.fuzz
import numpy as np
import pytest

from geomesa_tpu import GeoDataset

N = 6_000


def _ring(rng, cx, cy, r_lo, r_hi, k):
    ang = np.sort(rng.uniform(0, 2 * np.pi, k))
    rad = rng.uniform(r_lo, r_hi, k)
    xs = cx + rad * np.cos(ang)
    ys = cy + rad * np.sin(ang)
    pts = ", ".join(f"{x:.4f} {y:.4f}" for x, y in zip(xs, ys))
    first = f"{xs[0]:.4f} {ys[0]:.4f}"
    return f"({pts}, {first})"


def _rand_poly_wkt(rng):
    cx, cy = rng.uniform(-6, 6, 2)
    kind = rng.integers(0, 3)
    if kind == 0:  # convex-ish / star polygon
        return f"POLYGON ({_ring(rng, cx, cy, 1.0, 5.0, int(rng.integers(3, 9)))})"
    if kind == 1:  # with a hole
        outer = _ring(rng, cx, cy, 3.0, 5.0, int(rng.integers(4, 8)))
        hole = _ring(rng, cx, cy, 0.5, 1.5, int(rng.integers(3, 6)))
        return f"POLYGON ({outer}, {hole})"
    a = f"({_ring(rng, cx, cy, 0.5, 3.0, int(rng.integers(3, 7)))})"
    b = f"({_ring(rng, cx + 6, cy, 0.5, 3.0, int(rng.integers(3, 7)))})"
    return f"MULTIPOLYGON ({a}, {b})"


@pytest.fixture(scope="module")
def spatial_pair():
    rng = np.random.default_rng(77)
    data = {
        "geom__x": rng.uniform(-12, 12, N),
        "geom__y": rng.uniform(-12, 12, N),
    }
    stores = []
    for dev in (True, False):
        ds = GeoDataset(n_shards=2, prefer_device=dev)
        ds.create_schema("s", "*geom:Point")
        ds.insert("s", data, fids=np.arange(N).astype(str))
        ds.flush()
        stores.append(ds)
    return stores, data


def test_random_polygons_device_host_agree(spatial_pair):
    (dev, host), data = spatial_pair
    rng = np.random.default_rng(17)
    nonzero = 0
    for case in range(40):
        wkt = _rand_poly_wkt(rng)
        rel = ["INTERSECTS", "DISJOINT"][rng.integers(0, 2)]
        q = f"{rel}(geom, {wkt})"
        a = dev.count("s", q)
        b = host.count("s", q)
        assert a == b, f"case {case}: {q!r} device={a} host={b}"
        rows = len(dev.query("s", q))
        assert rows == a, f"case {case}: query rows {rows} != count {a}"
        if rel == "INTERSECTS" and a:
            nonzero += 1
            g = dev.density("s", q, bbox=(-12, -12, 12, 12),
                            width=16, height=16)
            assert abs(float(np.asarray(g).sum()) - a) < 1e-3, q
        # complements partition the table exactly
        comp = ("DISJOINT" if rel == "INTERSECTS" else "INTERSECTS")
        assert dev.count("s", f"{comp}(geom, {wkt})") == N - a, q
    assert nonzero >= 10  # the fuzz hit real geometry


def test_random_dwithin_device_host_agree(spatial_pair):
    (dev, host), data = spatial_pair
    rng = np.random.default_rng(23)
    from geomesa_tpu.utils.geometry import haversine_m

    for case in range(20):
        cx, cy = rng.uniform(-8, 8, 2)
        dist = float(rng.uniform(50_000, 500_000))
        q = f"DWITHIN(geom, POINT ({cx:.4f} {cy:.4f}), {dist:.0f}, meters)"
        a = dev.count("s", q)
        b = host.count("s", q)
        assert a == b, f"case {case}: {q!r} device={a} host={b}"
        d = haversine_m(data["geom__x"], data["geom__y"], cx, cy)
        want = int((d <= dist).sum())
        assert a == want, f"case {case}: {q!r} -> {a}, haversine oracle {want}"
