"""CLI tests (geomesa-tools command parity)."""

import json
import os

import numpy as np
import pytest

from geomesa_tpu import GeoDataset, cli

CSV = """id,name,age,date,lon,lat
a1,alice,30,2020-01-05,-100.0,40.0
a2,bob,25,2020-01-06,-99.0,41.0
a3,carol,45,2020-01-07,-98.0,42.0
"""

CONV = json.dumps({
    "type": "delimited-text",
    "format": "CSV",
    "id-field": "$1",
    "options": {"skip-lines": 1},
    "fields": [
        {"name": "name", "transform": "$2"},
        {"name": "age", "transform": "toInt($3)"},
        {"name": "dtg", "transform": "date('yyyy-MM-dd', $4)"},
        {"name": "geom", "transform": "point(toDouble($5), toDouble($6))"},
    ],
})


@pytest.fixture
def catalog(tmp_path):
    cat = str(tmp_path / "cat")
    csv_path = str(tmp_path / "data.csv")
    conv_path = str(tmp_path / "conv.conf")
    with open(csv_path, "w") as fh:
        fh.write(CSV)
    with open(conv_path, "w") as fh:
        fh.write(CONV)
    rc = cli.main([
        "create-schema", "-c", cat, "-f", "people",
        "-s", "name:String,age:Integer,dtg:Date,*geom:Point",
    ])
    assert rc == 0
    rc = cli.main(["ingest", "-c", cat, "-f", "people", "-C", conv_path, csv_path])
    assert rc == 0
    return cat, str(tmp_path)


def test_schema_commands(catalog, capsys):
    cat, _ = catalog
    assert cli.main(["get-type-names", "-c", cat]) == 0
    assert "people" in capsys.readouterr().out
    assert cli.main(["describe-schema", "-c", cat, "-f", "people"]) == 0
    out = capsys.readouterr().out
    assert "age: int32" in out and "count: 3" in out
    # duplicate create fails cleanly
    assert cli.main(["create-schema", "-c", cat, "-f", "people", "-s", "a:String"]) == 1


def test_stats_commands(catalog, capsys):
    cat, _ = catalog
    assert cli.main(["stats-count", "-c", cat, "-f", "people", "-q", "age > 26"]) == 0
    assert capsys.readouterr().out.strip() == "2"
    assert cli.main(["stats-bounds", "-c", cat, "-f", "people"]) == 0
    assert "-100" in capsys.readouterr().out
    assert cli.main(["stats-top-k", "-c", cat, "-f", "people", "-a", "name"]) == 0
    assert "alice" in capsys.readouterr().out
    assert cli.main(["stats-histogram", "-c", cat, "-f", "people", "-a", "age",
                     "--bins", "5"]) == 0
    assert "histogram" in capsys.readouterr().out
    assert cli.main(["stats-analyze", "-c", cat, "-f", "people"]) == 0
    assert "count: 3" in capsys.readouterr().out


def test_explain(catalog, capsys):
    cat, _ = catalog
    assert cli.main(["explain", "-c", cat, "-f", "people",
                     "-q", "BBOX(geom,-101,39,-98,42)"]) == 0
    out = capsys.readouterr().out
    assert "Chosen index" in out


def test_export_formats(catalog, capsys, tmp_path):
    cat, base = catalog
    # csv to stdout
    assert cli.main(["export", "-c", cat, "-f", "people", "-F", "csv",
                     "-q", "age > 26"]) == 0
    out = capsys.readouterr().out
    assert "alice" in out and "bob" not in out
    # geojson
    gj = str(tmp_path / "o.json")
    assert cli.main(["export", "-c", cat, "-f", "people", "-F", "geojson",
                     "-o", gj]) == 0
    doc = json.load(open(gj))
    assert doc["type"] == "FeatureCollection" and len(doc["features"]) == 3
    assert doc["features"][0]["geometry"]["type"] == "Point"
    # arrow + parquet + bin + leaflet
    for fmt, name in [("arrow", "o.arrow"), ("parquet", "o.parquet"),
                      ("bin", "o.bin"), ("leaflet", "o.html")]:
        path = str(tmp_path / name)
        assert cli.main(["export", "-c", cat, "-f", "people", "-F", fmt,
                         "-o", path]) == 0
        assert os.path.getsize(path) > 0
    assert os.path.getsize(str(tmp_path / "o.bin")) == 3 * 16


def test_delete_schema(catalog, capsys):
    cat, _ = catalog
    assert cli.main(["delete-schema", "-c", cat, "-f", "people"]) == 0
    capsys.readouterr()
    assert cli.main(["get-type-names", "-c", cat]) == 0
    assert "people" not in capsys.readouterr().out
    assert not os.path.exists(os.path.join(cat, "people.npz"))


def test_version(capsys):
    assert cli.main(["version"]) == 0
    assert "geomesa-tpu" in capsys.readouterr().out


def test_cli_update_schema_and_manage_partitions(tmp_path, capsys):
    from geomesa_tpu.cli import main

    cat = str(tmp_path / "cat")
    main(["create-schema", "-c", cat, "-f", "ev",
          "-s", "v:Integer,dtg:Date,*geom:Point;geomesa.partition='time'"])
    # ingest a few rows across two weeks via the dataset API + save
    import numpy as np

    from geomesa_tpu import GeoDataset
    from geomesa_tpu.filter.ecql import parse_iso_ms

    ds = GeoDataset.load(cat)
    n = 200
    rng = np.random.default_rng(1)
    lo = parse_iso_ms("2021-06-01")
    ds.insert("ev", {
        "geom__x": rng.uniform(-100, -90, n),
        "geom__y": rng.uniform(30, 40, n),
        "dtg": (lo + rng.integers(0, 14 * 86_400_000, n)).astype("datetime64[ms]"),
        "v": rng.integers(0, 9, n).astype(np.int32),
    }, fids=np.arange(n).astype(str))
    ds.flush("ev")
    ds.save(cat)
    capsys.readouterr()
    main(["manage-partitions", "-c", cat, "-f", "ev", "list"])
    out = capsys.readouterr().out
    assert "bin" in out and "rows" in out and ("resident" in out or "spilled" in out)
    main(["update-schema", "-c", cat, "-f", "ev", "--add", "tag:String"])
    out = capsys.readouterr().out
    assert "updated schema" in out and "tag" in out
    main(["manage-partitions", "-c", cat, "-f", "ev", "delete",
          "--older-than", "2021-06-08"])
    out = capsys.readouterr().out
    assert "removed" in out


def test_cli_env_convert_playback_compact(tmp_path, capsys):
    """The previously-untested CLI commands: env, convert (dry run),
    playback --fast, fs compact."""
    # env: prints every registered tunable with a source column
    cli.main(["env"])
    out = capsys.readouterr().out
    assert "geomesa.scan.ranges.target" in out
    assert "geomesa.sample.hash-buckets" in out

    # convert: dry-run a delimited config against a csv, nothing ingested
    cfg = tmp_path / "conv.conf"
    cfg.write_text(
        'type = "delimited-text"\n'
        'format = "CSV"\n'
        'id-field = "$fid"\n'
        'fields = [\n'
        '  { name = "fid", transform = "$1" }\n'
        '  { name = "name", transform = "$2" }\n'
        '  { name = "lon", transform = "toDouble($3)" }\n'
        '  { name = "lat", transform = "toDouble($4)" }\n'
        '  { name = "geom", transform = "point($lon, $lat)" }\n'
        ']\n'
    )
    csv = tmp_path / "in.csv"
    csv.write_text("a1,alpha,1.5,2.5\na2,beta,3.0,4.0\n")
    cli.main(["convert", "-f", "conv", "-s", "name:String,*geom:Point",
              "-C", str(cfg), "-i", str(csv)])
    cap = capsys.readouterr()
    assert "alpha" in cap.out and "beta" in cap.out
    assert "converted: 2 ok, 0 failed" in cap.err

    # playback --fast over a saved catalog
    cat = str(tmp_path / "cat")
    ds = GeoDataset(n_shards=1, prefer_device=False)
    ds.create_schema("pb", "v:Integer,dtg:Date,*geom:Point")
    ds.insert("pb", {
        "v": np.arange(5, dtype=np.int32),
        "dtg": (np.arange(5) * 1000 + 1577836800000).astype("datetime64[ms]"),
        "geom__x": np.arange(5.0), "geom__y": np.zeros(5),
    }, fids=np.arange(5).astype(str))
    ds.flush()
    ds.save(cat)
    cli.main(["playback", "--catalog", cat, "--feature-name", "pb", "--fast"])
    out = capsys.readouterr().out
    assert "played back 5 features" in out

    # compact over a filesystem store
    from geomesa_tpu.fs import FileSystemStorage
    from geomesa_tpu.fs.storage import DateTimeScheme

    root = str(tmp_path / "fs")
    fs = FileSystemStorage(root)
    from geomesa_tpu.schema.feature_type import FeatureType

    ft = FeatureType.from_spec("c", "v:Integer,dtg:Date,*geom:Point")
    fs.create(ft, DateTimeScheme("day"))
    for i in range(3):  # several files in one partition
        fs.write(
            "c",
            {"v": np.array([i], np.int32),
             "dtg": np.array(["2020-01-05"], "datetime64[ms]"),
             "geom__x": np.array([1.0]), "geom__y": np.array([2.0])},
            fids=np.array([f"f{i}"]),
        )
    cli.main(["compact", "--catalog", root, "--feature-name", "c"])
    out = capsys.readouterr().out
    assert "compacted" in out
