"""S2 curve and S2/S3 index tests (reference S2SFC.scala / S2Index /
S3Index; cell math validated structurally against the published S2 cell-id
layout: face tokens, hierarchy, Hilbert locality, coverer soundness)."""

import numpy as np
import pytest

from geomesa_tpu.api.dataset import GeoDataset
from geomesa_tpu.curves import s2


class TestS2CellMath:
    def test_face_cell_tokens(self):
        # face centers land on the six level-0 cells: tokens 1,3,5,7,9,b
        centers = [(0, 0), (90, 0), (0, 90), (180, 0), (-90, 0), (0, -90)]
        toks = []
        for lon, lat in centers:
            cid = s2.lnglat_to_id([lon], [lat])[0]
            toks.append(s2.token(int(s2.parent(cid, 0))))
        assert toks == ["1", "3", "5", "7", "9", "b"]

    def test_leaf_round_trip(self):
        rng = np.random.default_rng(1)
        x = rng.uniform(-180, 180, 500)
        y = rng.uniform(-89.9, 89.9, 500)
        ids = s2.lnglat_to_id(x, y)
        assert (s2.level_of(ids) == 30).all()
        x2, y2 = s2.id_to_lnglat(ids)
        dx = np.minimum(np.abs(x2 - x), 360 - np.abs(x2 - x))
        assert float(np.hypot(dx, y2 - y).max()) < 1e-5

    def test_hierarchy(self):
        ids = s2.lnglat_to_id([12.34], [56.78])
        for level in range(30):
            p = s2.parent(ids, level)
            assert s2.level_of(p)[0] == level
            assert s2.contains(p, ids)[0]
            # parent's range nests inside grandparent's
            if level:
                gp = s2.parent(ids, level - 1)
                assert int(s2.range_min(gp)[0]) <= int(s2.range_min(p)[0])
                assert int(s2.range_max(p)[0]) <= int(s2.range_max(gp)[0])

    def test_children_partition_parent(self):
        cid = int(s2.parent(s2.lnglat_to_id([10.0], [20.0]), 5)[0])
        ch = s2.children(cid)
        assert len(ch) == 4
        assert all(s2.level_of([c])[0] == 6 for c in ch)
        los = sorted(int(s2.range_min(c)) for c in ch)
        his = sorted(int(s2.range_max(c)) for c in ch)
        assert los[0] == int(s2.range_min(cid))
        assert his[-1] == int(s2.range_max(cid))
        # non-overlapping; the single id between sibling ranges is even
        # (never a leaf key — leaf ids are odd), so no leaf falls in a gap
        for a, b in zip(his[:-1], los[1:]):
            assert b == a + 2
            assert (a + 1) % 2 == 0

    def test_hilbert_locality(self):
        a = s2.lnglat_to_id([10.0], [45.0])
        b = s2.lnglat_to_id([10.0001], [45.0001])
        common = 0
        for level in range(30, -1, -1):
            if int(s2.parent(a, level)[0]) == int(s2.parent(b, level)[0]):
                common = level
                break
        assert common >= 12

    def test_token_round_trip(self):
        cid = int(s2.lnglat_to_id([5.0], [5.0])[0])
        assert s2.from_token(s2.token(cid)) == cid
        p3 = int(s2.parent(np.asarray([cid], np.uint64), 3)[0])
        assert s2.from_token(s2.token(p3)) == p3

    def test_latitude_validation(self):
        with pytest.raises(ValueError):
            s2.S2SFC().index([0.0], [91.0])


class TestS2Cover:
    def test_cover_soundness_random(self):
        rng = np.random.default_rng(2)
        sfc = s2.S2SFC(max_cells=64)
        for _ in range(10):
            x0 = rng.uniform(-180, 170)
            y0 = rng.uniform(-90, 80)
            bbox = (
                x0, y0,
                min(x0 + rng.uniform(0.5, 40), 180),
                min(y0 + rng.uniform(0.5, 40), 90),
            )
            px = rng.uniform(bbox[0], bbox[2], 200)
            py = rng.uniform(bbox[1], bbox[3], 200)
            pids = s2.lnglat_to_id(px, py)
            rs = sfc.ranges(*bbox)
            lo = np.array([r.lo for r in rs], np.uint64)
            hi = np.array([r.hi for r in rs], np.uint64)
            idx = np.searchsorted(lo, pids, side="right") - 1
            ok = (idx >= 0) & (pids <= hi[np.clip(idx, 0, len(hi) - 1)])
            assert ok.all(), f"under-cover for {bbox}"

    def test_cover_selectivity(self):
        sfc = s2.S2SFC(max_cells=64)
        rs = sfc.ranges(0, 40, 10, 50)
        span = sum(int(r.hi) - int(r.lo) + 1 for r in rs)
        assert span / float(6 << 60) < 0.05  # small fraction of the keyspace

    def test_polar_and_antimeridian(self):
        sfc = s2.S2SFC(max_cells=64)
        pole = int(s2.lnglat_to_id([3.0], [89.9])[0])
        assert any(r.lo <= pole <= r.hi for r in sfc.ranges(-10, 85, 10, 90))
        am = int(s2.lnglat_to_id([179.99], [10.0])[0])
        assert any(r.lo <= am <= r.hi for r in sfc.ranges(179, 5, 180, 15))


class TestS2S3Indices:
    def _ds(self, indices: str):
        ds = GeoDataset(n_shards=2, prefer_device=False)
        ds.create_schema(
            "t", f"name:String,dtg:Date,*geom:Point;geomesa.indices='{indices}'"
        )
        n = 500
        rng = np.random.default_rng(3)
        ds.insert("t", {
            "name": [f"n{i % 5}" for i in range(n)],
            "dtg": (np.datetime64("2024-03-01", "ms")
                    + rng.integers(0, 30 * 86_400_000, n)),
            "geom": [(float(x), float(y)) for x, y in
                     zip(rng.uniform(-60, 60, n), rng.uniform(-60, 60, n))],
        })
        return ds

    def test_s2_index_query(self):
        ds = self._ds("s2,id")
        st = ds._store("t")
        assert [k.name for k in st.keyspaces] == ["s2", "id"]
        got = ds.count("t", "BBOX(geom, -10, -10, 10, 10)")
        # oracle: host recount
        fc = ds.query("t")
        x = fc.batch.columns["geom__x"]
        y = fc.batch.columns["geom__y"]
        expect = int(((x >= -10) & (x <= 10) & (y >= -10) & (y <= 10)).sum())
        assert got == expect > 0

    def test_s3_index_query(self):
        ds = self._ds("s3,id")
        st = ds._store("t")
        assert [k.name for k in st.keyspaces] == ["s3", "id"]
        q = ("BBOX(geom, -10, -10, 10, 10) AND "
             "dtg DURING 2024-03-05T00:00:00Z/2024-03-12T00:00:00Z")
        got = ds.count("t", q)
        fc = ds.query("t")
        x = fc.batch.columns["geom__x"]
        y = fc.batch.columns["geom__y"]
        t = fc.batch.columns["dtg"].astype("datetime64[ms]")
        m = (
            (x >= -10) & (x <= 10) & (y >= -10) & (y <= 10)
            & (t >= np.datetime64("2024-03-05"))
            & (t <= np.datetime64("2024-03-12"))
        )
        assert got == int(m.sum()) > 0

    def test_s3_plan_uses_s3(self):
        ds = self._ds("s3,id")
        exp = ds.explain(
            "t",
            "BBOX(geom, -10, -10, 10, 10) AND "
            "dtg DURING 2024-03-05T00:00:00Z/2024-03-12T00:00:00Z",
        )
        assert "s3" in exp

    def test_explicit_index_list_round_trips_through_save(self, tmp_path):
        ds = self._ds("s2,id")
        ds.save(str(tmp_path / "d"))
        ds2 = GeoDataset.load(str(tmp_path / "d"))
        assert [k.name for k in ds2._store("t").keyspaces] == ["s2", "id"]
        assert ds2.count("t", "BBOX(geom, -10, -10, 10, 10)") == ds.count(
            "t", "BBOX(geom, -10, -10, 10, 10)"
        )
