"""Filter engine: ECQL parse -> IR -> compiled mask, vs numpy oracles.

Host (numpy) and device (jnp under jit) paths must agree exactly.
"""

import numpy as np
import pytest

from geomesa_tpu.filter import compile_filter, extract_geometries, extract_intervals, parse_ecql
from geomesa_tpu.filter import ir
from geomesa_tpu.filter.ecql import parse_iso_ms
from geomesa_tpu.schema import FeatureType
from geomesa_tpu.schema.columns import encode_batch

SPEC = "name:String,age:Integer,weight:Double,flag:Boolean,dtg:Date,*geom:Point"


@pytest.fixture(scope="module")
def setup():
    rng = np.random.default_rng(7)
    ft = FeatureType.from_spec("t", SPEC)
    dicts = {}
    n = 4000
    data = {
        "name": [f"n{i % 20}" for i in range(n)],
        "age": rng.integers(0, 90, n),
        "weight": rng.uniform(40, 100, n),
        "flag": rng.integers(0, 2, n).astype(bool),
        "dtg": rng.integers(
            parse_iso_ms("2020-01-01"), parse_iso_ms("2020-03-01"), n
        ).astype("datetime64[ms]"),
        "geom__x": rng.uniform(-80, -70, n),
        "geom__y": rng.uniform(35, 45, n),
    }
    batch = encode_batch(ft, data, dicts)
    return ft, dicts, batch, data


def run(ecql, setup, xp=np):
    ft, dicts, batch, data = setup
    f = parse_ecql(ecql)
    cf = compile_filter(f, ft, dicts)
    return np.asarray(cf(batch.columns, xp))


def test_bbox_and_time(setup):
    ft, dicts, batch, data = setup
    got = run(
        "BBOX(geom, -75, 39, -73, 41) AND dtg DURING 2020-01-10T00:00:00Z/2020-01-20T00:00:00Z",
        setup,
    )
    x, y = data["geom__x"], data["geom__y"]
    t = batch["dtg"]
    want = (
        (x >= -75) & (x <= -73) & (y >= 39) & (y <= 41)
        & (t >= parse_iso_ms("2020-01-10")) & (t <= parse_iso_ms("2020-01-20"))
    )
    np.testing.assert_array_equal(got, want)
    assert got.sum() > 0


def test_attribute_predicates(setup):
    ft, dicts, batch, data = setup
    got = run("age >= 18 AND age < 65 AND weight <= 80.5", setup)
    want = (data["age"] >= 18) & (data["age"] < 65) & (batch["weight"] <= 80.5)
    np.testing.assert_array_equal(got, want)

    got = run("name = 'n3' OR name IN ('n5', 'n7')", setup)
    names = np.array([f"n{i % 20}" for i in range(batch.n)])
    want = (names == "n3") | (names == "n5") | (names == "n7")
    np.testing.assert_array_equal(got, want)

    got = run("name LIKE 'n1%'", setup)
    want = np.char.startswith(names, "n1")
    np.testing.assert_array_equal(got, want)

    got = run("flag = true", setup)
    np.testing.assert_array_equal(got, data["flag"])

    got = run("age BETWEEN 30 AND 40", setup)
    want = (data["age"] >= 30) & (data["age"] <= 40)
    np.testing.assert_array_equal(got, want)

    got = run("NOT (age > 50)", setup)
    np.testing.assert_array_equal(got, ~(data["age"] > 50))


def test_intersects_polygon(setup):
    ft, dicts, batch, data = setup
    got = run(
        "INTERSECTS(geom, POLYGON ((-76 36, -72 36, -72 42, -76 42, -76 36)))", setup
    )
    x, y = data["geom__x"], data["geom__y"]
    want = (x >= -76) & (x <= -72) & (y >= 36) & (y <= 42)
    np.testing.assert_array_equal(got, want)
    # non-rectangular: triangle, compare against geometry oracle
    from geomesa_tpu.utils import geometry as geo

    tri = "POLYGON ((-78 36, -72 36, -75 44, -78 36))"
    got = run(f"WITHIN(geom, {tri})", setup)
    oracle = geo.parse_wkt(tri).contains_points(x, y)
    assert np.mean(got == oracle) > 0.999


def test_dwithin(setup):
    ft, dicts, batch, data = setup
    got = run("DWITHIN(geom, POINT (-75 40), 100000, meters)", setup)
    from geomesa_tpu.utils import geometry as geo

    d = geo.haversine_m(data["geom__x"], data["geom__y"], -75, 40)
    np.testing.assert_array_equal(got, d <= 100000)


def test_include_exclude_idin(setup):
    ft, dicts, batch, data = setup
    assert run("INCLUDE", setup).all()
    assert not run("EXCLUDE", setup).any()
    from geomesa_tpu.schema.columns import fid_strs

    fid = fid_strs(batch["__fid__"])[5]
    f = parse_ecql(f"IN ('{fid}')")
    assert isinstance(f, ir.IdIn)
    cf = compile_filter(f, ft, dicts)
    got = cf(batch.columns)
    assert got.sum() == 1 and got[5]


def test_device_mask_matches_host(setup):
    import jax
    import jax.numpy as jnp

    ft, dicts, batch, data = setup
    ecql = (
        "BBOX(geom, -75, 39, -73, 41) AND age > 21 AND name = 'n3'"
        " AND dtg AFTER 2020-01-15T00:00:00Z"
    )
    f = parse_ecql(ecql)
    cf = compile_filter(f, ft, dicts)
    host = cf(batch.columns, np)
    dev_cols = {
        k: jnp.asarray(v)
        for k, v in batch.columns.items()
        if k in cf.columns and v.dtype != object
    }
    dev = jax.jit(lambda c: cf(c, jnp))(dev_cols)
    np.testing.assert_array_equal(np.asarray(dev), host)


def test_extract_geometries_and_intervals():
    f = parse_ecql(
        "BBOX(geom, -75, 39, -73, 41) AND dtg DURING 2020-01-10T00:00:00Z/2020-01-20T00:00:00Z"
        " AND age > 21"
    )
    g = extract_geometries(f, "geom")
    assert len(g.values) == 1
    assert g.values[0].bounds() == (-75, 39, -73, 41)
    iv = extract_intervals(f, "dtg")
    assert iv.values == [(parse_iso_ms("2020-01-10"), parse_iso_ms("2020-01-20"))]
    # disjoint detection
    f2 = parse_ecql("BBOX(geom, 0, 0, 1, 1) AND BBOX(geom, 5, 5, 6, 6)")
    assert extract_geometries(f2, "geom").disjoint
    f3 = parse_ecql(
        "dtg DURING 2020-01-01T00:00:00Z/2020-01-02T00:00:00Z AND dtg AFTER 2021-01-01T00:00:00Z"
    )
    assert extract_intervals(f3, "dtg").disjoint
    # OR of two windows
    f4 = parse_ecql("BBOX(geom, 0, 0, 1, 1) OR BBOX(geom, 5, 5, 6, 6)")
    assert len(extract_geometries(f4, "geom").values) == 2


def test_parse_errors():
    with pytest.raises(ValueError):
        parse_ecql("age >")
    with pytest.raises(ValueError):
        parse_ecql("BBOX(geom, 1, 2)")
    with pytest.raises(ValueError):
        parse_ecql("age = 1 extra")


def test_unknown_attribute_raises(setup):
    ft, dicts, batch, data = setup
    with pytest.raises(KeyError) as e:
        compile_filter(parse_ecql("bogus = 1"), ft, dicts)
    assert "bogus" in str(e.value)
