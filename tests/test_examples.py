"""The examples must keep running — they are the tutorials."""

import subprocess
import sys


def test_quickstart_runs():
    proc = subprocess.run(
        [sys.executable, "examples/quickstart.py"],
        capture_output=True, text=True, timeout=600, cwd="/root/repo",
        env={"JAX_PLATFORMS": "cpu", "PYTHONPATH": "/root/repo",
             "PATH": "/usr/bin:/bin:/opt/venv/bin", "HOME": "/root"},
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "checkpoint round-trip OK" in proc.stdout
