"""Standing-query suite (geomesa_tpu/subscribe/; docs/STANDING.md).

The contract under test everywhere: the incrementally-maintained result
of a registered viewport is BIT-IDENTICAL to a from-scratch evaluation
of the same viewport at the same epoch. ``geomesa.subscribe.verify``
stays ON for the whole module, so every applied batch re-scans and
hard-asserts inside the engine — a passing test here proves the delta
algebra, not just the final numbers.
"""

import numpy as np
import pytest

from geomesa_tpu import GeoDataset, config, metrics
from geomesa_tpu.filter.ecql import parse_iso_ms
from geomesa_tpu.stream import StreamingDataset
from geomesa_tpu.subscribe import UnknownSubscription, make_spec, route_key_of

SPEC = "name:String,speed:Float,dtg:Date,*geom:Point"
VIEW = (-30.0, -20.0, 10.0, 20.0)
VIEW_ECQL = "BBOX(geom, -30, -20, 10, 20)"


@pytest.fixture(autouse=True)
def _verify_on():
    with config.SUBSCRIBE_VERIFY.scoped("true"):
        yield


def _data(n=120, seed=7, lo=-45.0, hi=45.0):
    rng = np.random.default_rng(seed)
    return {
        "name": [f"n{i % 3}" for i in range(n)],
        "speed": rng.uniform(0, 30, n).astype(np.float32),
        "dtg": (np.datetime64("2024-05-01", "ms")
                + rng.integers(0, 86_400_000, n)),
        "geom": [(float(x), float(y)) for x, y in
                 zip(rng.uniform(lo, hi, n), rng.uniform(-28, 28, n))],
    }


@pytest.fixture()
def ds():
    out = GeoDataset(n_shards=1, prefer_device=False)
    out.create_schema("t", SPEC)
    out.insert("t", _data(), fids=[f"f{i}" for i in range(120)])
    return out


def _result(ds, sub_id, cursor=0):
    from geomesa_tpu.subscribe import delta as dl

    got = ds.subscription_poll(sub_id, cursor)
    spec = ds.standing._groups[got["schema"]][
        ds.standing._subs[sub_id][1]].spec
    return got, dl.decode_result(spec, got["result"])


def test_count_delta_and_dirty_rescan_bit_identical(ds):
    sid = ds.subscribe("t", "count", bbox=VIEW)
    got, val = _result(ds, sid)
    assert val == ds.count("t", VIEW_ECQL)
    assert got["version"] == 1

    # adds apply as a delta (no rescan), still exact
    ds.insert("t", _data(60, seed=11), fids=[f"g{i}" for i in range(60)])
    got, val = _result(ds, sid)
    assert val == ds.count("t", VIEW_ECQL)
    assert [u["kind"] for u in got["updates"]] == ["snapshot", "delta"]

    # deletes re-scan only the dirty bounds (non-additive mutation)
    ds.delete_features("t", "speed > 20")
    got, val = _result(ds, sid)
    assert val == ds.count("t", VIEW_ECQL)
    assert got["updates"][-1]["kind"] == "rescan"

    # age-off is the other non-additive edge
    ds.age_off("t", "2024-05-01T12:00:00Z")
    _, val = _result(ds, sid)
    assert val == ds.count("t", VIEW_ECQL)


def test_density_grid_bit_identical(ds):
    sid = ds.subscribe("t", "density", bbox=VIEW, width=64, height=64)
    ds.insert("t", _data(80, seed=13), fids=[f"h{i}" for i in range(80)])
    _, grid = _result(ds, sid)
    ref = ds.density("t", VIEW_ECQL, bbox=VIEW, width=64, height=64)
    assert grid.dtype == ref.dtype and np.array_equal(grid, ref)


def test_pyramid_rollup_downsample_chain(ds):
    sid = ds.subscribe("t", "pyramid", bbox=VIEW, levels=5)
    ds.insert("t", _data(90, seed=17), fids=[f"p{i}" for i in range(90)])
    _, grids = _result(ds, sid)
    # leaf side 2^levels, downsampled to the 1x1 root: levels+1 grids
    assert len(grids) == 6
    assert grids[0].shape == (32, 32) and grids[-1].shape == (1, 1)
    total = ds.count("t", VIEW_ECQL)
    # every level is an exact rollup of the leaf: integer-valued f64
    for g in grids:
        assert float(g.sum()) == float(total)
    # fixed SW/SE/NW/NE downsample order: parent == 2x2 child sum
    from geomesa_tpu.cache import hierarchy

    for child, parent in zip(grids, grids[1:]):
        assert np.array_equal(hierarchy.downsample(child), parent)


def test_stats_exact_merge_only(ds):
    sid = ds.subscribe("t", "stats", bbox=VIEW, stat_spec="Enumeration(name)")
    ds.insert("t", _data(40, seed=19), fids=[f"s{i}" for i in range(40)])
    got, stat = _result(ds, sid)
    ref = ds.stats("t", "Enumeration(name)", VIEW_ECQL)
    assert stat.to_json() == ref.to_json()
    # sketches outside EXACT_MERGE_KINDS (f64-sum order sensitivity) are
    # refused at registration: a standing result must merge exactly
    with pytest.raises(ValueError, match=r"\[GM-SUB\]"):
        ds.subscribe("t", "stats", bbox=VIEW,
                     stat_spec="DescriptiveStats(speed)")


def test_fusion_one_group_one_dispatch(ds):
    sids = [ds.subscribe("t", "count", bbox=VIEW) for _ in range(10)]
    # ten subscribers, one standing group: same spec fuses
    assert len({ds.standing._subs[s][1] for s in sids}) == 1
    snap = ds.standing.snapshot()
    assert snap["subscribers"] == 10
    assert sum(g["subscribers"] for g in snap["groups"]) == 10

    before = metrics.registry().counter(metrics.SUBSCRIBE_DISPATCHES).value
    ds.insert("t", _data(30, seed=23), fids=[f"q{i}" for i in range(30)])
    after = metrics.registry().counter(metrics.SUBSCRIBE_DISPATCHES).value
    # ONE applied batch -> exactly ONE standing evaluation dispatch,
    # regardless of subscriber count (the issue's hot-viewport contract)
    assert after - before == 1
    ref = ds.count("t", VIEW_ECQL)
    for s in sids:
        _, val = _result(ds, s)
        assert val == ref


def test_dirty_scoping_leaves_disjoint_groups_untouched(ds):
    west = ds.subscribe("t", "count", bbox=(-45.0, -28.0, -1.0, 28.0))
    east = ds.subscribe("t", "count", bbox=(1.0, -28.0, 45.0, 28.0))
    ds.insert("t", _data(40, seed=29, lo=5.0, hi=40.0),
              fids=[f"e{i}" for i in range(40)])
    got_w, _ = _result(ds, west)
    v_west = got_w["version"]
    # delete only eastern rows: the dirty bounds never intersect the
    # western viewport, so its group must not re-scan (no new update)
    ds.delete_features("t", "BBOX(geom, 5, -28, 45, 28)")
    got_e, val_e = _result(ds, east)
    assert got_e["updates"][-1]["kind"] == "rescan"
    assert val_e == ds.count("t", "BBOX(geom, 1, -28, 45, 28)")
    got_w, val_w = _result(ds, west)
    assert got_w["version"] == v_west
    assert val_w == ds.count("t", "BBOX(geom, -45, -28, -1, 28)")


def test_region_polygon_viewport(ds):
    poly = "POLYGON((-20 -15, 15 -15, 15 12, -20 12, -20 -15))"
    sid = ds.subscribe("t", "count", region=poly)
    ds.insert("t", _data(50, seed=31), fids=[f"r{i}" for i in range(50)])
    _, val = _result(ds, sid)
    assert val == ds.count("t", f"INTERSECTS(geom, {poly})")


def test_updates_ring_and_cursor(ds):
    sid = ds.subscribe("t", "count", bbox=VIEW)
    with config.SUBSCRIBE_UPDATES_RING.scoped("4"):
        for i in range(6):
            ds.insert("t", {"name": ["x"], "speed": [1.0],
                            "dtg": [np.datetime64("2024-05-02", "ms")],
                            "geom": [(0.0, 0.0)]}, fids=[f"u{i}"])
    got = ds.subscription_poll(sid, cursor=0)
    assert got["version"] == 7
    # ring capped: a cursor older than the ring re-anchors on the full
    # result carried with every poll
    assert got["updates"][0]["version"] > 1
    got2 = ds.subscription_poll(sid, cursor=got["version"])
    assert got2["updates"] == []


def test_unsubscribe_and_unknown(ds):
    sid = ds.subscribe("t", "count", bbox=VIEW)
    assert ds.unsubscribe(sid) is True
    assert ds.unsubscribe(sid) is False
    with pytest.raises(UnknownSubscription):
        ds.subscription_poll(sid)


def test_route_key_embeds_ring_identity(ds):
    sid = ds.subscribe("t", "count", bbox=VIEW)
    spec = make_spec("t", "count", bbox=VIEW)
    lvl = 3
    assert route_key_of(sid) == spec.route_key(lvl)
    assert sid.startswith("t:z3:")


def test_export_import_guard_adopt_and_resync(ds):
    sid = ds.subscribe("t", "count", bbox=VIEW)
    ds.insert("t", _data(20, seed=37), fids=[f"x{i}" for i in range(20)])
    got, ref = _result(ds, sid)
    exported = ds.standing.export_groups()
    assert len(exported["groups"]) == 1
    assert "t" in exported["guards"]

    # identical window -> guard matches -> adopted verbatim (same
    # version, same update ring, zero missed / zero duplicated updates)
    twin = GeoDataset(n_shards=1, prefer_device=False)
    twin.create_schema("t", SPEC)
    twin.insert("t", _data(), fids=[f"f{i}" for i in range(120)])
    twin.insert("t", _data(20, seed=37), fids=[f"x{i}" for i in range(20)])
    out = twin._standing_engine().import_groups(exported)
    assert out == {"adopted": 1, "resynced": 0}
    got2, val2 = _result(twin, sid)
    assert val2 == ref and got2["version"] == got["version"]
    assert [u["version"] for u in got2["updates"]] == \
        [u["version"] for u in got["updates"]]

    # diverged window -> guard mismatch -> local re-scan, version stays
    # contiguous and the result reflects the LOCAL window
    other = GeoDataset(n_shards=1, prefer_device=False)
    other.create_schema("t", SPEC)
    other.insert("t", _data(80, seed=41), fids=[f"y{i}" for i in range(80)])
    out = other._standing_engine().import_groups(exported)
    assert out == {"adopted": 0, "resynced": 1}
    got3, val3 = _result(other, sid)
    assert val3 == other.count("t", VIEW_ECQL)
    assert got3["version"] == got["version"] + 1
    assert got3["updates"][-1]["kind"] == "resync"

    # export with remove=True is the leaver's half: the source forgets
    exported2 = ds.standing.export_groups(remove=True)
    assert len(exported2["groups"]) == 1
    with pytest.raises(UnknownSubscription):
        ds.subscription_poll(sid)


def test_partitioned_store_rejected():
    ds = GeoDataset(n_shards=1, prefer_device=False)
    ds.create_schema("p", "name:String,dtg:Date,*geom:Point;"
                          "geomesa.partition='time'")
    with pytest.raises(ValueError, match=r"\[GM-SUB\]"):
        ds.subscribe("p", "count", bbox=VIEW)


def test_debug_queries_exposes_subscriptions(ds):
    from geomesa_tpu import obs

    ds.subscribe("t", "count", bbox=VIEW)
    dq = obs.debug_queries(ds)
    assert dq["subscriptions"]["subscribers"] == 1
    assert dq["subscriptions"]["groups"][0]["schema"] == "t"


# ---------------------------------------------------------------------------
# streaming window: moves, expiry, epoch gauges
# ---------------------------------------------------------------------------


def _stream_ds():
    sds = StreamingDataset()
    sds.create_schema("v", SPEC)
    return sds


def _write(sds, fids, pts, t0, names=None):
    ts = [t0 + i for i in range(len(fids))]
    sds.write("v", {
        "name": names or ["m"] * len(fids),
        "speed": [1.0] * len(fids),
        "dtg": ts,
        "geom": pts,
    }, fids, ts_ms=ts)


def test_stream_moves_delta_and_epoch_gauge():
    sds = _stream_ds()
    t0 = parse_iso_ms("2024-05-01")
    _write(sds, [f"f{i}" for i in range(40)],
           [(float(i - 20), 0.0) for i in range(40)], t0)
    sid = sds.subscribe("v", "count", bbox=(-10.0, -5.0, 10.0, 5.0))
    got = sds.subscription_poll(sid)
    ref = sds.count("v", "BBOX(geom, -10, -5, 10, 5)")
    assert got["result"]["v"] == ref

    # a CHANGE on a live fid is a MOVE: -old +new, still one delta batch
    _write(sds, ["f0", "f1"], [(0.5, 0.5), (0.6, 0.6)], t0 + 10_000)
    got = sds.subscription_poll(sid, cursor=got["version"])
    assert got["result"]["v"] == sds.count("v", "BBOX(geom, -10, -5, 10, 5)")
    assert got["updates"][-1]["kind"] == "delta"

    # live deletes re-scan dirty bounds
    sds.delete("v", "f0")
    got = sds.subscription_poll(sid, cursor=got["version"])
    assert got["result"]["v"] == sds.count("v", "BBOX(geom, -10, -5, 10, 5)")

    g = metrics.registry().gauge(f"{metrics.STREAM_EPOCH}.v").value
    assert g == sds.cache("v").epoch
    assert metrics.registry().counter(
        f"{metrics.STREAM_POLL_BATCHES}.v").value >= 1


def test_stream_clear_and_fused_stream_subscribers():
    sds = _stream_ds()
    t0 = parse_iso_ms("2024-05-01")
    _write(sds, [f"f{i}" for i in range(30)],
           [(float(i % 10), float(i % 5)) for i in range(30)], t0)
    a = sds.subscribe("v", "density", bbox=(-1.0, -1.0, 11.0, 6.0),
                      width=32, height=32)
    b = sds.subscribe("v", "density", bbox=(-1.0, -1.0, 11.0, 6.0),
                      width=32, height=32)
    assert route_key_of(a) == route_key_of(b)
    eng = sds.standing
    assert len(eng._groups["v"]) == 1
    sds.clear("v")
    got = sds.subscription_poll(a)
    from geomesa_tpu.subscribe import delta as dl

    spec = eng._groups["v"][eng._subs[a][1]].spec
    grid = dl.decode_result(spec, got["result"])
    assert float(grid.sum()) == 0.0


# ---------------------------------------------------------------------------
# durability: subscriptions survive the journal and the checkpoint
# (docs/STANDING.md §7)
# ---------------------------------------------------------------------------


def test_standing_durable_across_journal_replay(tmp_path):
    """Crash before any checkpoint: journal replay rebuilds the live
    subscriptions (same ids, same results) and honors a journaled
    unsubscribe."""
    root = str(tmp_path)
    ds = GeoDataset(prefer_device=False)
    ds.attach_journal(root)
    ds.create_schema("t", SPEC)
    ds.insert("t", _data(seed=3))
    ds.flush()
    sid = ds.subscribe("t", "count", bbox=VIEW)
    gone = ds.subscribe("t", "count", bbox=(-2.0, -2.0, 2.0, 2.0))
    assert ds.unsubscribe(gone)
    want = _result(ds, sid)[1]

    ds2 = GeoDataset.load(root, prefer_device=False)
    assert _result(ds2, sid)[1] == want
    with pytest.raises(UnknownSubscription):
        ds2.subscription_poll(gone)
    # replayed registration is live, not a husk: new ingest flows
    ds2.insert("t", _data(n=40, seed=9, lo=-25.0, hi=5.0))
    ds2.flush()
    after = _result(ds2, sid)[1]
    assert after > want


def test_standing_durable_across_checkpoint(tmp_path):
    """save() truncates the journal, so the manifest must carry the
    live subscriptions: load() re-registers them under their original
    ids with a fresh snapshot anchor."""
    root = str(tmp_path)
    ds = GeoDataset(prefer_device=False)
    ds.attach_journal(root)
    ds.create_schema("t", SPEC)
    ds.insert("t", _data(seed=4))
    ds.flush()
    sid = ds.subscribe("t", "count", bbox=VIEW)
    want = _result(ds, sid)[1]
    ds.save(root)

    ds2 = GeoDataset.load(root, prefer_device=False)
    assert _result(ds2, sid)[1] == want
    ds2.insert("t", _data(n=40, seed=10, lo=-25.0, hi=5.0))
    ds2.flush()
    assert _result(ds2, sid)[1] > want
    # a second checkpoint cycle keeps carrying them
    ds2.save(root)
    ds3 = GeoDataset.load(root, prefer_device=False)
    assert _result(ds3, sid)[1] == _result(ds2, sid)[1]
