"""Spatial aggregate cache (geomesa_tpu/cache/; docs/CACHE.md).

Tier-1 correctness contract: with ``geomesa.cache.enabled``, repeated and
overlapping density/stats/count queries return BIT-IDENTICAL results to a
cold (cache-disabled) run — including after interleaved inserts/deletes
(epoch invalidation) and under partial-cover reuse — and a warm overlapping
query executes only the residual cells (asserted via the partial-hit
counter and the executor's scan accounting in the audit event).
"""

import numpy as np
import pytest

from geomesa_tpu import config, metrics
from geomesa_tpu.api.dataset import GeoDataset, Query
from geomesa_tpu.cache import AggregateCache, decompose
from geomesa_tpu.filter import parse_ecql


def _counter(name: str) -> int:
    return metrics.registry().counter(name).value


def _enabled():
    return config.CACHE_ENABLED.scoped("true")


@pytest.fixture()
def ds(rng):
    """Seeded points including rows EXACTLY on level-5 cell edges (span
    360/32 = 11.25 deg), so the half-open cell partition is exercised."""
    ds = GeoDataset(n_shards=4)
    ds.create_schema(
        "pts", "type:String:index=true,weight:Float,dtg:Date,*geom:Point"
    )
    edges = np.arange(-180.0, 180.1, 11.25)
    span = edges[(edges > -30) & (edges < 30)]
    bx, by = np.meshgrid(span, span)
    r = np.random.default_rng(7)
    n = 4000
    x = np.concatenate([bx.ravel(), r.uniform(-35, 35, n)])
    y = np.concatenate([by.ravel(), r.uniform(-35, 35, n)])
    m = len(x)
    lo = np.datetime64("2020-01-01", "ms").astype(np.int64)
    ds.insert("pts", {
        "geom__x": x, "geom__y": y,
        "weight": r.uniform(0, 2, m),
        "dtg": (lo + r.integers(0, 10**9, m)).astype("datetime64[ms]"),
        "type": r.choice(["bus", "car", "train"], m),
    }, fids=np.arange(m).astype(str))
    ds.flush("pts")
    return ds


Q1 = "BBOX(geom, -22.5, -22.5, 22.5, 22.5) AND type = 'bus'"
#: pan east: heavy cell overlap with Q1, plus a newly exposed cell column
Q2 = "BBOX(geom, -18.0, -22.5, 34.9, 22.5) AND type = 'bus'"


# -- count: identity, partial reuse, scan accounting -----------------------

def test_count_repeat_and_overlap_identical(ds):
    cold1 = ds.count("pts", Q1)
    cold2 = ds.count("pts", Q2)
    with _enabled():
        assert ds.count("pts", Q1) == cold1     # cold populate
        assert ds.count("pts", Q1) == cold1     # whole-result hit
        assert ds.count("pts", Q2) == cold2     # partial-cover reuse


def test_warm_overlap_scans_only_residual(ds):
    with _enabled():
        ds.count("pts", Q1)
        ev_cold = ds.audit.recent(1)[0]
        assert ev_cold.hints["exec_path"]["cache"] == "miss"
        cold_scanned = ev_cold.scanned
        assert cold_scanned > 0

        partial_before = _counter("cache.partial")
        ds.count("pts", Q2)
        ev_warm = ds.audit.recent(1)[0]
        # the partial-hit counter fired and the exec path names the shape
        assert _counter("cache.partial") == partial_before + 1
        path = ev_warm.hints["exec_path"]
        assert path["cache"] == "partial"
        hits, total = map(int, path["cache_cells"].split("/"))
        assert 0 < hits < total
        # executor scan accounting: the warm overlapping query scanned
        # strictly fewer candidate rows than its own cold run would have
        with config.CACHE_ENABLED.scoped("false"):
            ds.count("pts", Q2)
        assert ev_warm.scanned < ds.audit.recent(1)[0].scanned


def test_exact_repeat_scans_nothing(ds):
    with _enabled():
        ds.count("pts", Q1)
        hit_before = _counter("cache.hit")
        ds.count("pts", Q1)
        ev = ds.audit.recent(1)[0]
        assert _counter("cache.hit") == hit_before + 1
        assert ev.hints["exec_path"]["cache"] == "hit"
        assert ev.scanned == 0


def test_epoch_invalidation_insert_delete(ds):
    with _enabled():
        base = ds.count("pts", Q1)
        ds.insert("pts", {
            "geom__x": [0.0, 11.25], "geom__y": [0.0, 11.25],
            "weight": [1.0, 1.0],
            "dtg": np.array(["2020-01-02", "2020-01-03"], "datetime64[ms]"),
            "type": ["bus", "bus"],
        }, fids=["fresh1", "fresh2"])
        ds.flush("pts")
        assert ds.count("pts", Q1) == base + 2
        ds.delete_features("pts", "IN ('fresh1')")
        assert ds.count("pts", Q1) == base + 1
    # and the final state matches a cache-disabled recount
    assert ds.count("pts", Q1) == base + 1


# -- density ---------------------------------------------------------------

@pytest.mark.slow  # compile-heavy sweep: gated by the lake-smoke CI job
def test_density_unweighted_bit_identical(ds):
    bbox = (-22.5, -22.5, 22.5, 22.5)
    cold = ds.density("pts", Q1, bbox=bbox, width=96, height=64)
    with _enabled():
        g1 = ds.density("pts", Q1, bbox=bbox, width=96, height=64)
        g2 = ds.density("pts", Q1, bbox=bbox, width=96, height=64)  # hit
        g3 = ds.density("pts", Q2, bbox=bbox, width=96, height=64)  # partial
    assert np.array_equal(cold, g1)
    assert np.array_equal(cold, g2)
    assert np.array_equal(
        ds.density("pts", Q2, bbox=bbox, width=96, height=64), g3
    )


@pytest.mark.slow  # compile-heavy sweep: gated by the lake-smoke CI job
def test_density_partial_reuse_under_fixed_raster(ds):
    """A raster decoupled from the filter bbox (dashboard/WMS-overview
    shape) decomposes; overlapping filters then reuse cells."""
    bbox = (-30.0, -30.0, 30.0, 30.0)  # fixed render raster
    f1 = "BBOX(geom, -22.5, -22.5, 22.5, 22.5)"
    f2 = "BBOX(geom, -18.0, -22.5, 34.9, 22.5)"
    cold2 = ds.density("pts", f2, bbox=bbox, width=64, height=64)
    with _enabled():
        ds.density("pts", f1, bbox=bbox, width=64, height=64)
        assert "cache_cells" in ds.audit.recent(1)[0].hints["exec_path"]
        partial_before = _counter("cache.partial")
        warm2 = ds.density("pts", f2, bbox=bbox, width=64, height=64)
        assert _counter("cache.partial") == partial_before + 1
    assert np.array_equal(cold2, warm2)


def test_density_coupled_raster_whole_result_only(ds):
    """Filter bbox == render raster (pan/zoom map shape): a pan would move
    every cell key, so decomposition is skipped for density here."""
    bbox = (-22.5, -22.5, 22.5, 22.5)
    with _enabled():
        ds.density("pts", "BBOX(geom, -22.5, -22.5, 22.5, 22.5)",
                   bbox=bbox, width=32, height=32)
        assert "cache_cells" not in ds.audit.recent(1)[0].hints["exec_path"]


def test_density_cells_gated_by_budget(ds):
    """Per-cell density entries hold full rasters; when the cells alone
    would blow half the budget, decomposition is skipped so one query
    cannot evict the whole cache."""
    ds.cache = AggregateCache(budget_bytes=100_000)
    bbox = (-30.0, -30.0, 30.0, 30.0)  # decoupled raster (would decompose)
    with _enabled():
        ds.density("pts", "BBOX(geom, -22.5, -22.5, 22.5, 22.5)",
                   bbox=bbox, width=64, height=64)  # 16 KiB/cell x ~30 cells
        assert "cache_cells" not in ds.audit.recent(1)[0].hints["exec_path"]
    assert ds.cache.store.total_bytes <= 100_000


def test_density_weighted_whole_result_only(ds):
    bbox = (-22.5, -22.5, 22.5, 22.5)
    cold = ds.density("pts", Q1, bbox=bbox, width=64, height=64,
                      weight="weight")
    with _enabled():
        g1 = ds.density("pts", Q1, bbox=bbox, width=64, height=64,
                        weight="weight")
        ev = ds.audit.recent(1)[0]
        # weighted grids must not decompose (f32 rounding is order-dependent)
        assert "cache_cells" not in ev.hints["exec_path"]
        g2 = ds.density("pts", Q1, bbox=bbox, width=64, height=64,
                        weight="weight")
    assert np.array_equal(cold, g1)
    assert np.array_equal(cold, g2)


def test_cached_grid_immune_to_caller_mutation(ds):
    bbox = (-22.5, -22.5, 22.5, 22.5)
    with _enabled():
        ds.density("pts", Q1, bbox=bbox, width=32, height=32)
        g_hit = ds.density("pts", Q1, bbox=bbox, width=32, height=32)
        g_hit[:] = -1.0  # hit results are fresh copies: scribbling is safe
        g_again = ds.density("pts", Q1, bbox=bbox, width=32, height=32)
    assert g_again.min() >= 0.0


def test_density_curve_whole_result_cache(ds):
    cold, snapped = ds.density_curve("pts", Q1, level=6,
                                     bbox=(-22.5, -22.5, 22.5, 22.5))
    with _enabled():
        g1, s1 = ds.density_curve("pts", Q1, level=6,
                                  bbox=(-22.5, -22.5, 22.5, 22.5))
        hit_before = _counter("cache.hit")
        g2, s2 = ds.density_curve("pts", Q1, level=6,
                                  bbox=(-22.5, -22.5, 22.5, 22.5))
        assert _counter("cache.hit") == hit_before + 1
    assert s1 == snapped and s2 == snapped
    assert np.array_equal(cold, g1) and np.array_equal(cold, g2)


# -- stats -----------------------------------------------------------------

@pytest.mark.slow  # compile-heavy sweep: gated by the lake-smoke CI job
def test_stats_exact_merge_kinds_identical(ds):
    spec = "Count();MinMax(weight);Enumeration(type)"
    cold = ds.stats("pts", spec, Q1).value()
    with _enabled():
        assert ds.stats("pts", spec, Q1).value() == cold   # populate
        assert ds.stats("pts", spec, Q1).value() == cold   # whole hit
        warm_overlap = ds.stats("pts", spec, Q2).value()   # partial reuse
    assert warm_overlap == ds.stats("pts", spec, Q2).value()


def test_stats_inexact_merge_kind_whole_result_only(ds):
    spec = "DescriptiveStats(weight)"  # moment merge reorders f64 sums
    cold = ds.stats("pts", spec, Q1).value()
    with _enabled():
        v1 = ds.stats("pts", spec, Q1).value()
        ev = ds.audit.recent(1)[0]
        assert "cache_cells" not in ev.hints["exec_path"]
        v2 = ds.stats("pts", spec, Q1).value()
    assert v1 == cold and v2 == cold


def test_cached_stat_immune_to_caller_mutation(ds):
    spec = "Count()"
    with _enabled():
        ds.stats("pts", spec, Q1)
        hot = ds.stats("pts", spec, Q1)
        expected = hot.value()
        hot.count = -999  # entries are serialized snapshots: no aliasing
        assert ds.stats("pts", spec, Q1).value() == expected


# -- visibility / auth keying ---------------------------------------------

def test_auths_partition_the_cache(rng):
    ds = GeoDataset(n_shards=2)
    ds.create_schema("sec", "name:String,*geom:Point")
    ds.insert("sec", {"name": ["open"], "geom__x": [1.0], "geom__y": [1.0]})
    ds.insert("sec", {"name": ["secret"], "geom__x": [2.0], "geom__y": [2.0]},
              visibilities=["admin"])
    ds.flush("sec")
    q = "BBOX(geom, 0, 0, 10, 10)"
    with _enabled():
        assert ds.count("sec", Query(ecql=q, auths=["admin"])) == 2
        assert ds.count("sec", Query(ecql=q, auths=[])) == 1
        # repeat both from cache: entries must not bleed across auth sets
        assert ds.count("sec", Query(ecql=q, auths=["admin"])) == 2
        assert ds.count("sec", Query(ecql=q, auths=[])) == 1


# -- bypasses / admission ---------------------------------------------------

def test_sampling_bypasses_cache(ds):
    with _enabled():
        before = (_counter("cache.hit") + _counter("cache.miss")
                  + _counter("cache.partial"))
        ds.count("pts", Query(ecql=Q1, sampling=4))
        after = (_counter("cache.hit") + _counter("cache.miss")
                 + _counter("cache.partial"))
    assert after == before


def test_eviction_under_budget(ds):
    ds.cache = AggregateCache(budget_bytes=500)
    before = _counter("cache.evict")
    with _enabled():
        results = {}
        for dx in range(8):
            q = f"BBOX(geom, {-22.5 + dx}, -22.5, {22.5 + dx}, 22.5)"
            results[q] = ds.count("pts", q)
        # under heavy eviction every answer must still be exact
        for q, v in results.items():
            assert ds.count("pts", q) == v
    assert _counter("cache.evict") > before
    assert ds.cache.store.total_bytes <= 500


def test_delete_schema_drops_cached_entries(ds):
    with _enabled():
        ds.count("pts", Q1)
    assert ds.cache.store.total_entries > 0
    ds.delete_schema("pts")
    assert ds.cache.store.total_entries == 0
    assert ds.cache.store.total_bytes == 0


def test_disabled_cache_stores_nothing(ds):
    puts = _counter("cache.put")
    ds.count("pts", Q1)
    ds.density("pts", Q1, bbox=(-22.5, -22.5, 22.5, 22.5), width=16, height=16)
    assert _counter("cache.put") == puts
    assert ds.cache.store.total_entries == 0


# -- decomposition unit behavior -------------------------------------------

def _pt_ft():
    from geomesa_tpu.schema.feature_type import FeatureType

    return FeatureType.from_spec("t", "type:String,*geom:Point")


def test_decompose_shapes():
    f = parse_ecql(Q1)
    d = decompose(f, _pt_ft())
    assert d is not None
    assert d.cells and len(d.strips) <= 4
    assert d.residual_key == repr(parse_ecql("type = 'bus'"))
    # cell boxes are half-open realizations: max edge strictly below the
    # next cell's min edge
    (ix, iy) = d.cells[0]
    b = d.cell_boxes[(ix, iy)]
    assert b[2] < b[0] + 360.0 / (1 << d.level) + 1e-12
    # absolute identity: the same cell derived from the panned query
    d2 = decompose(parse_ecql(Q2), _pt_ft())
    shared = set(d.cells) & set(d2.cells)
    assert shared
    for c in shared:
        assert d.cell_boxes[c] == d2.cell_boxes[c]
        assert d.cell_prefix(c) == d2.cell_prefix(c)


def test_decompose_rejects_non_pan_shapes():
    ft = _pt_ft()
    # two boxes, polygon intersection, spatial under OR: all non-decomposable
    assert decompose(parse_ecql(
        "BBOX(geom, 0, 0, 10, 10) AND BBOX(geom, 5, 5, 15, 15)"), ft) is None
    assert decompose(parse_ecql(
        "INTERSECTS(geom, POLYGON((0 0, 10 0, 10 10, 0 10, 0 0)))"), ft) is None
    assert decompose(parse_ecql(
        "BBOX(geom, 0, 0, 10, 10) OR type = 'bus'"), ft) is None
    assert decompose(parse_ecql("INCLUDE"), ft) is None
    # extent geometry schemas never decompose: a polygon feature straddling
    # a cell edge would be counted once PER intersecting cell
    from geomesa_tpu.schema.feature_type import FeatureType

    poly_ft = FeatureType.from_spec("p", "type:String,*geom:Polygon")
    assert decompose(parse_ecql("BBOX(geom, 0, 0, 10, 10)"), poly_ft) is None


def test_extent_geometry_whole_result_only():
    """Reviewer repro: a polygon straddling cell edges must count ONCE with
    the cache enabled (extent schemas skip decomposition)."""
    ds = GeoDataset(n_shards=2)
    ds.create_schema("poly", "type:String,*geom:Polygon")
    ds.insert("poly", {
        "type": ["a"],
        "geom": ["POLYGON((-1 -1, 1 -1, 1 1, -1 1, -1 -1))"],
    })
    ds.flush("poly")
    q = "BBOX(geom, -22.5, -22.5, 22.5, 22.5)"
    cold = ds.count("poly", q)
    assert cold == 1
    with _enabled():
        assert ds.count("poly", q) == 1
        assert "cache_cells" not in ds.audit.recent(1)[0].hints["exec_path"]
        assert ds.count("poly", q) == 1  # whole-result hit


def test_explain_reports_cache_participation(ds):
    out = ds.explain("pts", Q1)
    assert "Aggregate cache" in out
    assert "partial-cover: level" in out
    out2 = ds.explain("pts", "type = 'bus'")
    assert "not decomposable" in out2


# -- partitioned stores -----------------------------------------------------

@pytest.mark.slow  # compile-heavy sweep: gated by the lake-smoke CI job
def test_partitioned_store_cache(rng):
    ds = GeoDataset(n_shards=2)
    ds.create_schema(
        "part", "weight:Float,dtg:Date,*geom:Point;geomesa.partition='time'"
    )
    r = np.random.default_rng(3)
    n = 2000
    lo = np.datetime64("2020-01-01", "ms").astype(np.int64)
    ds.insert("part", {
        "geom__x": r.uniform(-20, 20, n), "geom__y": r.uniform(-20, 20, n),
        "weight": r.uniform(0, 1, n),
        "dtg": (lo + r.integers(0, 40 * 86_400_000, n)).astype("datetime64[ms]"),
    }, fids=np.arange(n).astype(str))
    ds.flush("part")
    q = ("BBOX(geom, -10, -10, 12.5, 12.5) AND "
         "dtg DURING 2020-01-01T00:00:00Z/2020-02-01T00:00:00Z")
    cold = ds.count("part", q)
    with _enabled():
        assert ds.count("part", q) == cold
        assert ds.count("part", q) == cold
        assert ds.audit.recent(1)[0].hints["exec_path"]["cache"] == "hit"
