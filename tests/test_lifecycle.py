"""Incremental schema & index lifecycle + incremental flat checkpoints.

Reference parity: GeoMesaDataStore.scala:288-336 (updateSchema transition
validation), TableBasedMetadata incrementality. Round-5 asks: add/remove
an attribute index without recreating the store; update_schema without
re-flushing rows; flat-store save() writing only new data.
"""

import os

import numpy as np
import pytest

from geomesa_tpu import GeoDataset
from geomesa_tpu.filter.ecql import parse_iso_ms

SPEC = "name:String,weight:Double,dtg:Date,*geom:Point"
PSPEC = SPEC + ";geomesa.partition='time'"


def _data(n, seed=11):
    rng = np.random.default_rng(seed)
    return {
        "name": [f"actor{i % 20}" for i in range(n)],
        "weight": rng.uniform(0, 10, n),
        "dtg": rng.integers(
            parse_iso_ms("2020-01-01"), parse_iso_ms("2020-03-01"), n
        ).astype("datetime64[ms]"),
        "geom__x": rng.uniform(-120, -70, n),
        "geom__y": rng.uniform(25, 50, n),
    }


def test_update_schema_is_in_place():
    """Adding columns must not rebuild stores or re-sort indices: same
    store object, same permutation arrays, version bumped."""
    ds = GeoDataset(n_shards=2, prefer_device=False)
    ds.create_schema("t", SPEC)
    ds.insert("t", _data(2_000), fids=np.arange(2_000).astype(str))
    ds.flush()
    st = ds._store("t")
    v0 = st.version
    orders = {k: id(t.order) for k, t in st.tables.items()}
    ds.update_schema("t", "extra:Integer,score:Float")
    assert ds._store("t") is st              # no store rebuild
    for k, t in st.tables.items():
        assert id(t.order) == orders[k]      # no re-sort
    assert st.version > v0
    assert ds.count("t", "extra = 0") == 2_000
    assert np.isnan(
        ds.query("t", "INCLUDE").batch.columns["score"]).all()


def test_add_remove_attribute_index_flat():
    ds = GeoDataset(n_shards=2, prefer_device=False)
    ds.create_schema("t", SPEC)
    data = _data(5_000, seed=3)
    ds.insert("t", data, fids=np.arange(5_000).astype(str))
    ds.flush()
    oracle = int((data["weight"] > 7.5).sum())
    st = ds._store("t")
    assert "attr:weight" not in st.tables
    ds.add_attribute_index("t", "weight")
    assert "attr:weight" in st.tables
    # the planner now has the index AND its cost sketch
    ex = ds.explain("t", "weight > 7.5")
    assert "attr:weight" in ex
    assert ds.count("t", "weight > 7.5") == oracle
    # string attr index too (rank vocab path)
    ds.add_attribute_index("t", "name")
    assert ds.count("t", "name = 'actor7'") == 250
    # spec round-trips the index option
    assert "index=true" in ds.get_schema("t").spec()
    ds.remove_attribute_index("t", "weight")
    assert "attr:weight" not in st.tables
    assert ds.count("t", "weight > 7.5") == oracle  # falls back, correct
    with pytest.raises(KeyError):
        ds.remove_attribute_index("t", "weight")


def test_add_index_after_more_appends():
    """Index added mid-life stays correct across subsequent flushes."""
    ds = GeoDataset(n_shards=2, prefer_device=False)
    ds.create_schema("t", SPEC)
    d1 = _data(2_000, seed=5)
    ds.insert("t", d1, fids=np.arange(2_000).astype(str))
    ds.flush()
    ds.add_attribute_index("t", "weight")
    d2 = _data(2_000, seed=6)
    ds.insert("t", d2, fids=(np.arange(2_000) + 2_000).astype(str))
    ds.flush()
    oracle = int((d1["weight"] > 5).sum() + (d2["weight"] > 5).sum())
    assert ds.count("t", "weight > 5") == oracle


def test_add_index_partitioned_touches_only_index_arrays(tmp_path):
    """Enabling an index on a 10-partition store must not rewrite any
    partition snapshot (mtime-asserted), and spilled partitions build
    their permutation lazily on load."""
    data = _data(10_000, seed=7)
    ds = GeoDataset(n_shards=2, prefer_device=False)
    ds.create_schema("t", PSPEC)
    st = ds._store("t")
    st.max_resident = 2
    st._spill_dir = str(tmp_path / "spill")
    ds.insert("t", data, fids=np.arange(10_000).astype(str))
    ds.flush()
    p = str(tmp_path / "ckpt")
    ds.save(p)
    snap = {}
    for root, _, files in os.walk(p):
        for f in files:
            fp = os.path.join(root, f)
            snap[fp] = os.path.getmtime(fp)
    ds.add_attribute_index("t", "weight")
    ds.save(p)
    touched = []
    for fp, m in snap.items():
        if os.path.getmtime(fp) != m and not fp.endswith("manifest.json"):
            touched.append(fp)
    assert touched == [], f"data files rewritten: {touched}"
    oracle = int((data["weight"] > 7.5).sum())
    assert ds.count("t", "weight > 7.5") == oracle
    # full round trip through the checkpoint keeps the index
    ds2 = GeoDataset.load(p, prefer_device=False)
    assert "attr:weight" in [k.name for k in ds2._store("t").keyspaces]
    assert ds2.count("t", "weight > 7.5") == oracle


def test_update_schema_partitioned_lazy_upgrade(tmp_path):
    """update_schema must not rewrite partition snapshots; spilled
    partitions null-fill the new columns when next loaded."""
    data = _data(6_000, seed=9)
    ds = GeoDataset(n_shards=2, prefer_device=False)
    ds.create_schema("t", PSPEC)
    st = ds._store("t")
    st.max_resident = 1
    st._spill_dir = str(tmp_path / "spill")
    ds.insert("t", data, fids=np.arange(6_000).astype(str))
    ds.flush()
    st.evict(keep=1)
    def _snap(d):
        # lake snapshot (part.lake) since PR 13; data.npz for legacy spills
        for name in ("part.lake", "data.npz"):
            p = os.path.join(d, name)
            if os.path.exists(p):
                return p
        raise AssertionError(f"no snapshot file in {d}")

    snaps = {
        d: os.path.getmtime(_snap(d))
        for d in (os.path.join(st._spill_dir, f) for f in
                  os.listdir(st._spill_dir))
        if os.path.isdir(d)
    }
    assert len(snaps) >= 2
    ds.update_schema("t", "extra:Integer,tag:String")
    for d, m in snaps.items():
        assert os.path.getmtime(_snap(d)) == m
    assert ds.count("t", "extra = 0") == 6_000  # loads + null-fills lazily


def test_flat_incremental_checkpoint(tmp_path):
    """save -> append -> save writes only a new chunk; delete forces a
    full rewrite (mutation epoch change); loads stay correct."""
    ds = GeoDataset(n_shards=2, prefer_device=False)
    ds.create_schema("t", SPEC)
    ds.insert("t", _data(3_000, seed=1), fids=np.arange(3_000).astype(str))
    ds.flush()
    p = str(tmp_path / "ckpt")
    ds.save(p)
    cdir = os.path.join(p, "t_chunks")
    first = sorted(os.listdir(cdir))
    assert len(first) == 1
    m0 = os.path.getmtime(os.path.join(cdir, first[0]))
    # append-only growth: second save leaves chunk 0 untouched
    ds.insert("t", _data(1_000, seed=2),
              fids=(np.arange(1_000) + 3_000).astype(str))
    ds.flush()
    ds.save(p)
    now = sorted(os.listdir(cdir))
    assert len(now) == 2
    assert os.path.getmtime(os.path.join(cdir, first[0])) == m0
    ds2 = GeoDataset.load(p, prefer_device=False)
    assert ds2.count("t") == 4_000
    assert ds2.count("t", "weight > 5") == ds.count("t", "weight > 5")
    # idempotent save with no changes writes nothing new
    ds.save(p)
    assert sorted(os.listdir(cdir)) == now
    # a delete rewrites (epoch changed) and drops stale chunks
    ds.delete_features("t", "weight > 5")
    ds.save(p)
    after = sorted(os.listdir(cdir))
    assert len(after) == 1 and after[0] not in now
    ds3 = GeoDataset.load(p, prefer_device=False)
    assert ds3.count("t") == ds.count("t")
    # loaded store saves incrementally too (epoch round-trips)
    ds3.insert("t", _data(500, seed=4),
               fids=(np.arange(500) + 10_000).astype(str))
    ds3.flush()
    ds3.save(p)
    names = sorted(os.listdir(cdir))
    assert len(names) == 2


def test_add_index_with_explicit_indices_list(tmp_path):
    """Review r5: an explicit geomesa.indices list must learn the attr
    kind, or loaded/rebuilt child stores silently drop the new index
    (reproduced as KeyError on spilled-partition queries)."""
    data = _data(6_000, seed=15)
    spec = SPEC + ";geomesa.partition='time',geomesa.indices='z3,id'"
    ds = GeoDataset(n_shards=2, prefer_device=False)
    ds.create_schema("t", spec)
    st = ds._store("t")
    st.max_resident = 1
    st._spill_dir = str(tmp_path / "spill")
    ds.insert("t", data, fids=np.arange(6_000).astype(str))
    ds.flush()
    st.evict(keep=1)
    ds.add_attribute_index("t", "weight")
    assert "attr" in ds.get_schema("t").user_data["geomesa.indices"]
    oracle = int((data["weight"] > 7.5).sum())
    assert ds.count("t", "weight > 7.5") == oracle  # loads spilled parts
    # flat variant: save/load keeps the index
    p = str(tmp_path / "ckpt")
    ds.save(p)
    ds2 = GeoDataset.load(p, prefer_device=False)
    assert "attr:weight" in [k.name for k in ds2._store("t").keyspaces]
    assert ds2.count("t", "weight > 7.5") == oracle


def test_cli_index_lifecycle(tmp_path, capsys):
    from geomesa_tpu import cli

    cat = str(tmp_path / "cat")
    ds = GeoDataset(n_shards=2, prefer_device=False)
    ds.create_schema("t", SPEC)
    ds.insert("t", _data(1000), fids=np.arange(1000).astype(str))
    ds.flush()
    ds.save(cat)
    cli.main(["add-attribute-index", "--catalog", cat,
              "--feature-name", "t", "--attribute", "weight"])
    ds2 = GeoDataset.load(cat, prefer_device=False)
    assert "attr:weight" in [k.name for k in ds2._store("t").keyspaces]
    cli.main(["remove-attribute-index", "--catalog", cat,
              "--feature-name", "t", "--attribute", "weight"])
    ds3 = GeoDataset.load(cat, prefer_device=False)
    assert "attr:weight" not in [k.name for k in ds3._store("t").keyspaces]
