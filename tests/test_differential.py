"""Randomized differential testing: every generated query must agree
across (a) the device path (compaction + MXU kernels), (b) the host
fallback path, and (c) a brute-force numpy oracle. This is the
TestGeoMesaDataStore-style whole-stack exercise (SURVEY.md §4.2) with
randomized inputs instead of fixtures — seeded, so failures reproduce."""

import numpy as np
import pytest

from geomesa_tpu import GeoDataset
from geomesa_tpu.api.dataset import Query
from geomesa_tpu.filter.ecql import parse_iso_ms

N = 80_000
T0 = parse_iso_ms("2020-01-01")
T1 = parse_iso_ms("2020-03-01")


def _make(seed, prefer_device):
    rng = np.random.default_rng(seed)
    data = {
        "geom__x": rng.uniform(-120, -70, N),
        "geom__y": rng.uniform(25, 50, N),
        "dtg": rng.integers(T0, T1, N).astype("datetime64[ms]"),
        "w": rng.uniform(0, 100, N),
        "v": rng.integers(0, 1000, N).astype(np.int32),
        "cat": rng.choice(["alpha", "beta", "gamma", "delta", None], N),
    }
    ds = GeoDataset(n_shards=4, prefer_device=prefer_device)
    ds.create_schema(
        "t", "w:Double,v:Integer,cat:String:index=true,dtg:Date,*geom:Point"
    )
    ds.insert("t", data, fids=np.arange(N).astype(str))
    ds.flush("t")
    return ds, data


def _oracle(data, spec):
    x, y = data["geom__x"], data["geom__y"]
    t = data["dtg"].astype(np.int64)
    m = np.ones(N, bool)
    for kind, args in spec:
        if kind == "bbox":
            x0, y0, x1, y1 = args
            m &= (x >= x0) & (x <= x1) & (y >= y0) & (y <= y1)
        elif kind == "during":
            lo, hi = args
            m &= (t >= lo) & (t <= hi)
        elif kind == "wlt":
            m &= data["w"] < args
        elif kind == "vge":
            m &= data["v"] >= args
        elif kind == "cat":
            vals = np.asarray(
                [c if c is not None else "" for c in data["cat"]], object
            )
            m &= vals == args
    return m


def _ecql(spec):
    parts = []
    for kind, args in spec:
        if kind == "bbox":
            x0, y0, x1, y1 = args
            parts.append(f"BBOX(geom, {x0}, {y0}, {x1}, {y1})")
        elif kind == "during":
            lo, hi = args

            def iso(ms):
                import datetime as dt

                return dt.datetime.fromtimestamp(
                    ms / 1000, dt.timezone.utc
                ).strftime("%Y-%m-%dT%H:%M:%SZ")

            parts.append(f"dtg DURING {iso(lo)}/{iso(hi)}")
        elif kind == "wlt":
            parts.append(f"w < {args}")
        elif kind == "vge":
            parts.append(f"v >= {args}")
        elif kind == "cat":
            parts.append(f"cat = '{args}'")
    return " AND ".join(parts) if parts else "INCLUDE"


def _gen_queries(seed, n_queries):
    rng = np.random.default_rng(seed)
    out = []
    for _ in range(n_queries):
        spec = []
        if rng.random() < 0.85:
            cx = rng.uniform(-118, -72)
            cy = rng.uniform(27, 48)
            wx = rng.uniform(0.5, 25)
            wy = rng.uniform(0.5, 12)
            spec.append(("bbox", (round(cx - wx, 4), round(cy - wy, 4),
                                  round(cx + wx, 4), round(cy + wy, 4))))
        if rng.random() < 0.7:
            lo = int(rng.integers(T0, T1 - 86_400_000))
            hi = lo + int(rng.integers(3_600_000, 21 * 86_400_000))
            # whole-second bounds: ECQL text carries seconds, so sub-second
            # precision would diverge from the oracle
            lo -= lo % 1000
            hi -= hi % 1000
            spec.append(("during", (lo, min(hi, T1))))
        if rng.random() < 0.35:
            spec.append(("wlt", round(float(rng.uniform(1, 99)), 3)))
        if rng.random() < 0.25:
            spec.append(("vge", int(rng.integers(0, 999))))
        if rng.random() < 0.25:
            spec.append(("cat", str(rng.choice(["alpha", "beta", "gamma"]))))
        out.append(spec)
    return out


@pytest.mark.parametrize("seed", [101, 202])
def test_differential_counts(seed):
    from geomesa_tpu import config

    dev, data = _make(seed, prefer_device=True)
    host, _ = _make(seed, prefer_device=False)
    config.COMPACT_MIN_ROWS.set(1)  # engage compaction at this table size
    try:
        for spec in _gen_queries(seed * 7, 25):
            ecql = _ecql(spec)
            want = int(_oracle(data, spec).sum())
            got_dev = dev.count("t", ecql)
            got_host = host.count("t", ecql)
            assert got_dev == want, f"device path: {ecql!r}"
            assert got_host == want, f"host path: {ecql!r}"
    finally:
        config.COMPACT_MIN_ROWS.set(None)


def test_differential_density_and_stats():
    from geomesa_tpu import config

    dev, data = _make(7, prefer_device=True)
    config.COMPACT_MIN_ROWS.set(1)
    try:
        for spec in _gen_queries(99, 8):
            ecql = _ecql(spec)
            m = _oracle(data, spec)
            want = int(m.sum())
            if not want:
                continue
            bbox = (-120.0, 25.0, -70.0, 50.0)
            grid = dev.density("t", ecql, bbox=bbox, width=128, height=128)
            assert abs(float(grid.sum()) - want) < 1e-3, ecql
            s = dev.stats("t", "MinMax(w)", ecql)
            assert np.isclose(s.lo, data["w"][m].min()), ecql
            assert np.isclose(s.hi, data["w"][m].max()), ecql
    finally:
        config.COMPACT_MIN_ROWS.set(None)


def test_differential_partitioned_store():
    """The same generated queries through a time-partitioned out-of-core
    store (max_resident=1, so multi-partition queries stream)."""
    from geomesa_tpu import config

    import tempfile

    seed = 31
    rng = np.random.default_rng(seed)
    data = {
        "geom__x": rng.uniform(-120, -70, N),
        "geom__y": rng.uniform(25, 50, N),
        "dtg": rng.integers(T0, T1, N).astype("datetime64[ms]"),
        "w": rng.uniform(0, 100, N),
        "v": rng.integers(0, 1000, N).astype(np.int32),
        "cat": rng.choice(["alpha", "beta", "gamma", "delta", None], N),
    }
    with tempfile.TemporaryDirectory() as spill:
        ds = GeoDataset(n_shards=4)
        ds.create_schema(
            "t",
            "w:Double,v:Integer,cat:String:index=true,dtg:Date,*geom:Point"
            ";geomesa.partition='time'",
        )
        st = ds._store("t")
        st.max_resident = 1
        st._spill_dir = spill
        ds.insert("t", data, fids=np.arange(N).astype(str))
        ds.flush("t")
        config.COMPACT_MIN_ROWS.set(1)
        try:
            for spec in _gen_queries(seed * 3, 12):
                ecql = _ecql(spec)
                want = int(_oracle(data, spec).sum())
                assert ds.count("t", ecql) == want, ecql
            # sorted + limited through the partition stream
            spec = [("bbox", (-105.0, 30.0, -85.0, 45.0))]
            m = _oracle(data, spec)
            q = Query(ecql=_ecql(spec), sort_by=[("w", True)], max_features=9)
            out = ds.query("t", q)
            np.testing.assert_allclose(
                out.columns["w"], np.sort(data["w"][m])[::-1][:9]
            )
            # per-key sampling: the 1-in-n counter runs PER PARTITION,
            # matching the reference (SamplingIterator state lives in each
            # scan region's iterator, not globally). NB: the null sentinel
            # must not contain NUL — numpy object-array equality against a
            # string with an embedded "\0" silently matches nothing.
            got = ds.count("t", Query(ecql=_ecql(spec), sampling=8,
                                      sample_by="cat"))
            cats = np.asarray(
                [c if c is not None else "<null>" for c in data["cat"]],
                object,
            )
            bins = st.binned.to_bin_and_offset(
                data["dtg"].astype("datetime64[ms]").astype(np.int64)
            )[0]
            want_s = sum(
                -(-int((m & (cats == c) & (bins == b)).sum()) // 8)
                for b in np.unique(bins)
                for c in np.unique(cats)
                if ((m & (cats == c) & (bins == b)).sum())
            )
            assert got == want_s
        finally:
            config.COMPACT_MIN_ROWS.set(None)
