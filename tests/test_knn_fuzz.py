"""Randomized kNN differential test: the expanding-radius, index-pruned
kNN (geomesa-process KNearestNeighborSearchProcess analog) must return
exactly the brute-force k nearest by great-circle distance for random
query points, k values, and filters — including edge cases (k larger
than matches, a query hard against the antimeridian, filters leaving
fewer than k matches)."""

pytestmark = __import__("pytest").mark.fuzz
import numpy as np
import pytest

from geomesa_tpu import GeoDataset
from geomesa_tpu.utils.geometry import haversine_m

N = 8_000


@pytest.fixture(scope="module")
def kfuzz():
    rng = np.random.default_rng(404)
    data = {
        "v": rng.uniform(0, 10, N),
        "geom__x": rng.uniform(-179.5, 179.5, N),
        "geom__y": rng.uniform(-60, 60, N),
    }
    ds = GeoDataset(n_shards=2)
    ds.create_schema("t", "v:Double,*geom:Point")
    ds.insert("t", data, fids=np.arange(N).astype(str))
    ds.flush()
    return ds, data


def test_random_knn_matches_brute_force(kfuzz):
    ds, d = kfuzz
    rng = np.random.default_rng(55)
    for case in range(25):
        qx = float(rng.uniform(-179, 179))
        qy = float(rng.uniform(-70, 70))
        k = int(rng.choice([1, 3, 10, 50]))
        got = ds.knn("t", x=qx, y=qy, k=k)
        dist = haversine_m(d["geom__x"], d["geom__y"], qx, qy)
        want = set(np.argsort(dist, kind="stable")[:k].astype(str))
        got_ids = set(got.fids)
        assert len(got_ids) == k, (case, qx, qy, k)
        # distance-set equality (ties at the k-th distance may pick
        # either member; compare by distance values, not ids)
        got_idx = np.array(sorted(int(f) for f in got_ids))
        want_idx = np.array(sorted(int(f) for f in want))
        assert np.allclose(
            np.sort(dist[got_idx]), np.sort(dist[want_idx]), rtol=1e-9
        ), (case, qx, qy, k)


def test_knn_with_filter(kfuzz):
    ds, d = kfuzz
    rng = np.random.default_rng(66)
    for case in range(10):
        qx, qy = float(rng.uniform(-90, 90)), float(rng.uniform(-50, 50))
        thr = round(float(rng.uniform(3, 7)), 2)
        k = 20
        got = ds.knn("t", x=qx, y=qy, k=k, query=f"v > {thr}")
        m = d["v"] > thr
        dist = np.where(m, haversine_m(d["geom__x"], d["geom__y"], qx, qy),
                        np.inf)
        want_idx = np.argsort(dist, kind="stable")[:k]
        got_idx = np.array(sorted(int(f) for f in got.fids))
        assert np.allclose(
            np.sort(dist[got_idx]), np.sort(dist[want_idx]), rtol=1e-9
        ), (case, qx, qy, thr)


def test_knn_k_exceeds_matches(kfuzz):
    ds, d = kfuzz
    got = ds.knn("t", x=0.0, y=0.0, k=50, query="v > 9.99")
    want_ids = set(np.nonzero(d["v"] > 9.99)[0].astype(str))
    assert len(want_ids) <= 50
    assert set(got.fids) == want_ids  # exactly the matching features


def test_knn_at_antimeridian(kfuzz):
    """A query at lon 179.4 must still return the true k nearest even
    when closer points sit across the antimeridian (expanding bbox
    wrap)."""
    ds, d = kfuzz
    qx, qy = 179.4, 10.0
    k = 10
    got = ds.knn("t", x=qx, y=qy, k=k)
    dist = haversine_m(d["geom__x"], d["geom__y"], qx, qy)
    want = np.sort(np.sort(dist, kind="stable")[:k])
    got_idx = np.array(sorted(int(f) for f in got.fids))
    assert np.allclose(np.sort(dist[got_idx]), want, rtol=1e-9)
