"""Pallas grouped density kernel: exact parity with the host scatter oracle
(DensityScan.scala:29-136 semantics) in interpret mode on the CPU mesh."""

import numpy as np
import pytest

from geomesa_tpu import GeoDataset, config
from geomesa_tpu.filter.ecql import parse_iso_ms

ECQL = (
    "BBOX(geom, -100, 30, -80, 45) AND "
    "dtg DURING 2020-01-05T00:00:00Z/2020-01-15T00:00:00Z"
)
BBOX = (-100.0, 30.0, -80.0, 45.0)


@pytest.fixture
def ds_data():
    rng = np.random.default_rng(13)
    n = 40_000
    lo = parse_iso_ms("2020-01-01")
    hi = parse_iso_ms("2020-02-01")
    data = {
        "geom__x": rng.uniform(-120, -70, n),
        "geom__y": rng.uniform(25, 50, n),
        "dtg": rng.integers(lo, hi, n).astype("datetime64[ms]"),
        "weight": rng.uniform(0, 1, n).astype(np.float32),
    }
    ds = GeoDataset(n_shards=4)
    ds.create_schema("t", "weight:Float,dtg:Date,*geom:Point")
    ds.insert("t", data, fids=np.arange(n).astype(str))
    ds.flush("t")
    return ds, data


@pytest.fixture
def force_pallas(monkeypatch):
    monkeypatch.setenv("GEOMESA_PALLAS_INTERPRET", "1")
    config.COMPACT_MIN_ROWS.set(1)
    config.COMPACT_FRACTION.set(2.0)
    yield
    config.COMPACT_MIN_ROWS.set(None)
    config.COMPACT_FRACTION.set(None)


def _oracle_grid(data, width, height, weight=None):
    x, y = data["geom__x"], data["geom__y"]
    t = data["dtg"].astype(np.int64)
    m = (
        (x >= -100) & (x <= -80) & (y >= 30) & (y <= 45)
        & (t >= parse_iso_ms("2020-01-05"))
        & (t <= parse_iso_ms("2020-01-15"))
    )
    px = np.clip(((x - BBOX[0]) / (BBOX[2] - BBOX[0]) * width).astype(np.int64),
                 0, width - 1)
    py = np.clip(((y - BBOX[1]) / (BBOX[3] - BBOX[1]) * height).astype(np.int64),
                 0, height - 1)
    g = np.zeros(height * width, np.float64)
    w = m.astype(np.float64) if weight is None else np.where(m, data[weight], 0)
    np.add.at(g, py[m] * width + px[m], w[m])
    return g.reshape(height, width)


def _grouped_was_built(ds, plan, bbox, width, height):
    st = ds._store("t")
    ex = ds._executor(st)
    setup = ex._scan_setup(plan, [])
    ex._maybe_compact(plan, setup, True)
    if setup["compact"] is None:
        return False
    return ex._density_grouped(plan, setup, bbox, width, height) is not None


def test_grouped_counts_exact(ds_data, force_pallas):
    ds, data = ds_data
    st, _, plan = ds._plan("t", ECQL)
    grid = ds.density("t", ECQL, bbox=BBOX, width=256, height=256)
    assert _grouped_was_built(ds, plan, BBOX, 256, 256), (
        "pallas grouped kernel did not engage; test exercised another path"
    )
    oracle = _oracle_grid(data, 256, 256)
    assert np.array_equal(grid.astype(np.float64), oracle)


def test_grouped_ragged_grid(ds_data, force_pallas):
    """Grid not a multiple of the 128-cell tile: padded tiles are cropped."""
    ds, data = ds_data
    st, _, plan = ds._plan("t", ECQL)
    grid = ds.density("t", ECQL, bbox=BBOX, width=300, height=200)
    assert _grouped_was_built(ds, plan, BBOX, 300, 200)
    oracle = _oracle_grid(data, 300, 200)
    assert np.array_equal(grid.astype(np.float64), oracle)


def test_grouped_weighted(ds_data, force_pallas):
    ds, data = ds_data
    st, _, plan = ds._plan("t", ECQL)
    grid = ds.density("t", ECQL, bbox=BBOX, width=256, height=256,
                      weight="weight")
    assert _grouped_was_built(ds, plan, BBOX, 256, 256)
    oracle = _oracle_grid(data, 256, 256, weight="weight")
    # f32 accumulation in a different order than the oracle's f64
    assert np.allclose(grid, oracle, rtol=1e-4, atol=1e-3)
    assert abs(grid.sum() - oracle.sum()) / max(oracle.sum(), 1) < 1e-4


def test_grouped_matches_scatter_path(ds_data, force_pallas):
    """Same query through the scatter path (pallas off) must agree exactly
    on unweighted counts."""
    ds, data = ds_data
    st, _, plan = ds._plan("t", ECQL)
    g1 = ds.density("t", ECQL, bbox=BBOX, width=256, height=256)
    assert _grouped_was_built(ds, plan, BBOX, 256, 256)
    with config.DENSITY_PALLAS.scoped(False), config.DENSITY_MXU.scoped(False):
        g2 = ds.density("t", ECQL, bbox=BBOX, width=256, height=256)
    assert np.array_equal(g1, g2)
