"""Visibility security, audit log, and metrics registry tests
(geomesa-security / index/audit / geomesa-metrics parity)."""

import numpy as np
import pytest

from geomesa_tpu import config, metrics, security
from geomesa_tpu.api.dataset import GeoDataset, Query
from geomesa_tpu.security import (
    VisibilityError, allowed_lut, can_see, parse_visibility,
)


class TestVisibilityEvaluator:
    def test_empty_is_public(self):
        assert can_see("", []) is True
        assert can_see("", ["admin"]) is True

    def test_single_label(self):
        assert can_see("admin", ["admin"])
        assert not can_see("admin", ["user"])
        assert not can_see("admin", [])

    def test_and(self):
        assert can_see("admin&user", ["admin", "user"])
        assert not can_see("admin&user", ["admin"])

    def test_or(self):
        assert can_see("admin|user", ["user"])
        assert not can_see("admin|user", ["other"])

    def test_precedence_and_parens(self):
        # & binds tighter than |
        assert can_see("a&b|c", ["c"])
        assert can_see("a&b|c", ["a", "b"])
        assert not can_see("a&b|c", ["a"])
        assert not can_see("a&(b|c)", ["b", "c"])
        assert can_see("a&(b|c)", ["a", "c"])

    def test_quoted_labels(self):
        assert can_see('"label with:odd/chars"', ["label with:odd/chars"])

    def test_parse_errors(self):
        for bad in ("a&", "(a", "a)b", "a &| b", "a!!b"):
            with pytest.raises(VisibilityError):
                parse_visibility(bad)

    def test_lut(self):
        lut = allowed_lut(["", "admin", "admin&user", "user|admin"], ["admin"])
        assert lut.tolist() == [True, True, False, True]


def _vis_dataset():
    ds = GeoDataset(n_shards=2, prefer_device=False)
    ds.create_schema("t", "name:String,dtg:Date,*geom:Point")
    n = 100
    rng = np.random.default_rng(0)
    data = {
        "name": [f"n{i}" for i in range(n)],
        "dtg": np.full(n, np.datetime64("2024-06-01", "ms")),
        "geom": [(float(x), float(y)) for x, y in
                 zip(rng.uniform(-10, 10, n), rng.uniform(-10, 10, n))],
    }
    # first half admin-only, second half public
    vis = ["admin"] * 50 + [""] * 50
    ds.insert("t", data, fids=[str(i) for i in range(n)], visibilities=vis)
    return ds


class TestVisibilityEnforcement:
    def test_unrestricted_sees_all(self):
        ds = _vis_dataset()
        assert ds.count("t") == 100

    def test_no_auths_sees_public_only(self):
        ds = _vis_dataset()
        assert ds.count("t", Query(auths=[])) == 50

    def test_admin_sees_all(self):
        ds = _vis_dataset()
        assert ds.count("t", Query(auths=["admin"])) == 100

    def test_dataset_level_auths(self):
        ds = _vis_dataset()
        ds.auths = []
        assert ds.count("t") == 50
        assert len(ds.query("t")) == 50
        # per-query override wins
        assert ds.count("t", Query(auths=["admin"])) == 100

    def test_visibility_composes_with_filter(self):
        ds = _vis_dataset()
        n_all = ds.count("t", "BBOX(geom, -10, -10, 10, 0)")
        n_pub = ds.count("t", Query(ecql="BBOX(geom, -10, -10, 10, 0)", auths=[]))
        assert 0 < n_pub < n_all

    def test_density_respects_auths(self):
        ds = _vis_dataset()
        g_all = ds.density("t", bbox=(-10, -10, 10, 10), width=16, height=16)
        g_pub = ds.density("t", Query(auths=[]), bbox=(-10, -10, 10, 10),
                           width=16, height=16)
        assert g_all.sum() == pytest.approx(100)
        assert g_pub.sum() == pytest.approx(50)

    def test_proximity_respects_auths(self):
        ds = _vis_dataset()
        ds.auths = []
        fc = ds.proximity("t", "POINT (0 0)", 3_000_000)
        assert 0 < len(fc) < 100
        vis = fc.batch.columns["__vis__"]
        assert (vis == 0).all()  # only public rows

    def test_delete_respects_auths(self):
        ds = _vis_dataset()
        ds.auths = []
        removed = ds.delete_features("t", "INCLUDE")
        assert removed == 50  # only the public half
        ds.auths = None
        assert ds.count("t") == 50  # admin rows survived

    def test_mixed_none_visibilities(self):
        ds = GeoDataset(n_shards=2, prefer_device=False)
        ds.create_schema("t", "name:String,*geom:Point")
        ds.insert("t", {"name": ["a", "b"], "geom": [(0.0, 0.0), (1.0, 1.0)]},
                  visibilities=["admin", None])
        assert ds.count("t", Query(auths=[])) == 1

    def test_config_scoped_auths(self):
        ds = _vis_dataset()
        with config.SECURITY_AUTHS.scoped("admin"):
            assert ds.count("t") == 100

    def test_invalid_write_visibility_rejected(self):
        ds = GeoDataset(n_shards=2, prefer_device=False)
        ds.create_schema("t", "name:String,*geom:Point")
        with pytest.raises(VisibilityError):
            ds.insert("t", {"name": ["a"], "geom": [(0.0, 0.0)]},
                      visibilities="admin&")

    def test_device_path_visibility(self):
        # same enforcement through the jit'd device kernel
        ds = GeoDataset(n_shards=2, prefer_device=True)
        ds.create_schema("t", "name:String,*geom:Point")
        n = 64
        data = {
            "name": [f"n{i}" for i in range(n)],
            "geom": [(float(i % 10), 0.0) for i in range(n)],
        }
        ds.insert("t", data, visibilities=["secret"] * 32 + [""] * 32)
        assert ds.count("t", Query(auths=[])) == 32
        assert ds.count("t", Query(auths=["secret"])) == 64


class TestAudit:
    def test_query_events_recorded(self):
        ds = _vis_dataset()
        ds.count("t", "BBOX(geom, -10, -10, 10, 10)")
        ds.query("t")
        evs = ds.audit.recent()
        assert len(evs) == 2
        assert evs[0].hints["op"] == "count"
        assert evs[0].type_name == "t"
        assert "BBOX" in evs[0].filter
        assert evs[0].plan_time_ms >= 0
        assert evs[1].hits == 100

    def test_audit_jsonl_file(self, tmp_path):
        path = tmp_path / "audit.jsonl"
        ds = _vis_dataset()
        with config.AUDIT_PATH.scoped(str(path)):
            ds.count("t")
        import json

        lines = path.read_text().strip().splitlines()
        assert len(lines) == 1
        rec = json.loads(lines[0])
        assert rec["type_name"] == "t" and rec["hits"] == 100

    def test_audit_disabled(self):
        ds = _vis_dataset()
        with config.AUDIT_ENABLED.scoped("false"):
            ds.count("t")
        assert ds.audit.recent() == []


class TestMetrics:
    def test_counters_and_timers(self):
        reg = metrics.MetricRegistry()
        reg.counter("a").inc(3)
        reg.counter("a").inc()
        with reg.timer("t").time():
            pass
        rep = reg.report()
        assert rep["a"] == 4
        assert rep["t"]["count"] == 1

    def test_prometheus_text(self):
        reg = metrics.MetricRegistry(prefix="gm")
        reg.counter("ingest.features").inc(7)
        text = reg.prometheus()
        assert "gm_ingest_features 7" in text

    def test_dataset_wiring(self):
        before = metrics.registry().counter("ingest.features").value
        _vis_dataset()
        after = metrics.registry().counter("ingest.features").value
        assert after - before == 100
