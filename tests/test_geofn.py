"""st_* geo-function library tests (geomesa-spark-jts UDF parity)."""

import numpy as np
import pytest

from geomesa_tpu import geofn as gf
from geomesa_tpu.utils import geometry as geo

SQ = "POLYGON ((0 0, 10 0, 10 10, 0 10, 0 0))"
TRI = "POLYGON ((2 2, 8 2, 5 8, 2 2))"
LINE = "LINESTRING (0 0, 10 10)"


class TestConstructorsAndOutputs:
    def test_make_point_and_text(self):
        p = gf.st_makePoint(1.5, 2.5)
        assert (p.x, p.y) == (1.5, 2.5)
        assert gf.st_asText(p) == "POINT (1.5 2.5)"
        assert gf.st_pointFromText("POINT (3 4)").y == 4

    def test_make_line_polygon_bbox(self):
        l = gf.st_makeLine([gf.st_makePoint(0, 0), gf.st_makePoint(1, 1)])
        assert l.kind == "linestring"
        poly = gf.st_makePolygon("LINESTRING (0 0, 1 0, 1 1, 0 0)")
        assert poly.kind == "polygon"
        bb = gf.st_makeBBOX(0, 0, 2, 3)
        assert bb.bounds() == (0, 0, 2, 3)
        assert gf.st_makeBox2D("POINT (0 0)", "POINT (2 3)").bounds() == (0, 0, 2, 3)

    def test_typed_from_text_rejects(self):
        with pytest.raises(ValueError):
            gf.st_pointFromText(LINE)

    def test_multilinestring_round_trip(self):
        mls = gf.st_mLineFromText("MULTILINESTRING ((0 0, 1 1), (2 2, 3 3))")
        assert len(mls.lines) == 2
        assert gf.st_geomFromText(mls.wkt()).wkt() == mls.wkt()

    def test_geojson_round_trip(self):
        for wkt in ("POINT (1 2)", LINE, SQ,
                    "MULTIPOLYGON (((0 0, 1 0, 1 1, 0 0)))"):
            g = gf.st_geomFromText(wkt)
            back = gf.st_geomFromGeoJSON(gf.st_asGeoJSON(g))
            assert back.wkt() == g.wkt()

    def test_wkb_round_trip(self):
        for wkt in (
            "POINT (1.5 -2.25)", LINE, SQ,
            "POLYGON ((0 0, 4 0, 4 4, 0 4, 0 0), (1 1, 2 1, 2 2, 1 2, 1 1))",
            "MULTIPOINT ((0 0), (1 1))",
            "MULTILINESTRING ((0 0, 1 1), (2 2, 3 3))",
            "MULTIPOLYGON (((0 0, 1 0, 1 1, 0 0)), ((5 5, 6 5, 6 6, 5 5)))",
        ):
            g = gf.st_geomFromText(wkt)
            assert gf.st_geomFromWKB(gf.st_asBinary(g)).wkt() == g.wkt()

    def test_wkb_shapely_compat(self):
        # cross-check the wire format against a known-good WKB blob
        # (POINT(1 2) little-endian) so external readers can consume it
        import binascii

        expect = binascii.unhexlify(
            "0101000000000000000000f03f0000000000000040"
        )
        assert gf.st_asBinary("POINT (1 2)") == expect

    def test_lat_lon_text(self):
        s = gf.st_asLatLonText("POINT (-122.5 37.75)")
        assert s.startswith("37°45'") and s.endswith("W")


class TestGeoHash:
    def test_known_geohash(self):
        # canonical example: (-5.6, 42.6) -> ezs42
        h = gf.st_geoHash(gf.st_makePoint(-5.6, 42.6), 25)
        assert h == "ezs42"

    def test_round_trip_center(self):
        p = gf.st_pointFromGeoHash("ezs42")
        assert p.x == pytest.approx(-5.6, abs=0.05)
        assert p.y == pytest.approx(42.6, abs=0.05)
        box = gf.st_box2DFromGeoHash("ezs42")
        assert gf.st_contains(box, p)

    def test_array_form(self):
        hs = gf.st_geoHash((np.array([-5.6, 0.0]), np.array([42.6, 0.0])), 25)
        assert hs[0] == "ezs42"
        assert len(hs[1]) == 5


class TestAccessors:
    def test_xy(self):
        assert gf.st_x("POINT (3 4)") == 3
        assert gf.st_y("POINT (3 4)") == 4
        assert gf.st_x(LINE) is None

    def test_envelope_and_boundary(self):
        env = gf.st_envelope(TRI)
        assert env.bounds() == (2, 2, 8, 8)
        b = gf.st_boundary(SQ)
        assert b.kind == "linestring"
        assert gf.st_boundary(LINE).kind == "multipoint"

    def test_rings_and_points(self):
        donut = "POLYGON ((0 0, 4 0, 4 4, 0 4, 0 0), (1 1, 2 1, 2 2, 1 2, 1 1))"
        assert gf.st_exteriorRing(donut).kind == "linestring"
        assert gf.st_interiorRingN(donut, 0) is not None
        assert gf.st_interiorRingN(donut, 1) is None
        assert gf.st_numPoints("LINESTRING (0 0, 1 1, 2 2)") == 3
        assert gf.st_pointN(LINE, 1).x == 10
        assert gf.st_pointN(LINE, -1).x == 10

    def test_geometry_n(self):
        mp = "MULTIPOINT ((0 0), (1 1), (2 2))"
        assert gf.st_numGeometries(mp) == 3
        assert gf.st_geometryN(mp, 2).x == 2
        assert gf.st_numGeometries(SQ) == 1

    def test_type_dims_flags(self):
        assert gf.st_geometryType(SQ) == "Polygon"
        assert gf.st_dimension(SQ) == 2
        assert gf.st_dimension(LINE) == 1
        assert gf.st_dimension("POINT (0 0)") == 0
        assert gf.st_coordDim(SQ) == 2
        assert gf.st_isCollection("MULTIPOINT ((0 0))")
        assert not gf.st_isCollection(SQ)
        assert gf.st_isClosed("LINESTRING (0 0, 1 0, 1 1, 0 0)")
        assert not gf.st_isClosed(LINE)
        assert gf.st_isRing("LINESTRING (0 0, 1 0, 1 1, 0 0)")
        assert gf.st_isValid(SQ)
        # bowtie is invalid
        assert not gf.st_isValid("POLYGON ((0 0, 2 2, 2 0, 0 2, 0 0))")
        assert gf.st_isSimple(LINE)
        assert not gf.st_isSimple("LINESTRING (0 0, 2 2, 2 0, 0 2)")

    def test_casts(self):
        assert gf.st_castToPoint("POINT (0 0)").kind == "point"
        with pytest.raises(ValueError):
            gf.st_castToPolygon("POINT (0 0)")
        assert gf.st_castToGeometry(SQ).kind == "polygon"


class TestRelations:
    def test_contains_within(self):
        assert gf.st_contains(SQ, TRI)
        assert gf.st_within(TRI, SQ)
        assert not gf.st_contains(TRI, SQ)
        assert gf.st_contains(SQ, "POINT (5 5)")
        assert not gf.st_contains(SQ, "POINT (15 5)")

    def test_intersects_disjoint(self):
        assert gf.st_intersects(SQ, TRI)
        far = "POLYGON ((20 20, 30 20, 30 30, 20 30, 20 20))"
        assert gf.st_disjoint(SQ, far)
        # overlapping but no vertex containment either way
        cross1 = "POLYGON ((-1 4, 11 4, 11 6, -1 6, -1 4))"
        assert gf.st_intersects(SQ, cross1)
        assert gf.st_intersects(LINE, "LINESTRING (0 10, 10 0)")

    def test_array_fast_path(self):
        xs = np.array([5.0, 15.0, 0.0])
        ys = np.array([5.0, 5.0, 0.0])
        m = gf.st_contains(SQ, (xs, ys))
        assert m.tolist() == [True, False, True]
        assert gf.st_disjoint(SQ, (xs, ys)).tolist() == [False, True, False]

    def test_overlaps(self):
        a = "POLYGON ((0 0, 6 0, 6 6, 0 6, 0 0))"
        b = "POLYGON ((3 3, 9 3, 9 9, 3 9, 3 3))"
        assert gf.st_overlaps(a, b)
        assert not gf.st_overlaps(SQ, TRI)  # containment is not overlap
        assert not gf.st_overlaps(SQ, LINE)  # dim mismatch

    def test_touches(self):
        a = "POLYGON ((0 0, 5 0, 5 5, 0 5, 0 0))"
        b = "POLYGON ((5 0, 10 0, 10 5, 5 5, 5 0))"
        assert gf.st_touches(a, b)
        assert gf.st_touches(a, "POINT (5 2)")
        assert not gf.st_touches(a, "POINT (2 2)")

    def test_crosses(self):
        assert gf.st_crosses(LINE, "LINESTRING (0 10, 10 0)")
        assert gf.st_crosses("LINESTRING (-5 5, 15 5)", SQ)
        assert not gf.st_crosses(SQ, TRI)

    def test_equals(self):
        # same ring, rotated start + reversed direction
        a = "POLYGON ((0 0, 10 0, 10 10, 0 10, 0 0))"
        b = "POLYGON ((10 10, 10 0, 0 0, 0 10, 10 10))"
        assert gf.st_equals(a, b)
        assert not gf.st_equals(a, TRI)

    def test_covers(self):
        assert gf.st_covers(SQ, "POINT (0 0)")  # boundary point

    def test_relate(self):
        m = gf.st_relate(SQ, TRI)
        assert len(m) == 9
        assert m[0] == "2"  # interiors intersect with area
        assert gf.st_relateBool(SQ, TRI, "T*****FF*")  # contains pattern


class TestProcessing:
    def test_area(self):
        assert gf.st_area(SQ) == 100
        assert gf.st_area(TRI) == pytest.approx(18)
        donut = "POLYGON ((0 0, 4 0, 4 4, 0 4, 0 0), (1 1, 2 1, 2 2, 1 2, 1 1))"
        assert gf.st_area(donut) == pytest.approx(15)
        assert gf.st_area(LINE) == 0

    def test_length(self):
        assert gf.st_length(LINE) == pytest.approx(np.sqrt(200))
        assert gf.st_length(SQ) == 0
        assert gf.st_perimeter(SQ) == 40
        # one degree of longitude at the equator ~ 111.32 km
        m = gf.st_lengthSphere("LINESTRING (0 0, 1 0)")
        assert m == pytest.approx(111_319, rel=0.01)

    def test_centroid(self):
        c = gf.st_centroid(SQ)
        assert (c.x, c.y) == (5, 5)
        c2 = gf.st_centroid("LINESTRING (0 0, 10 0)")
        assert (c2.x, c2.y) == (5, 0)
        c3 = gf.st_centroid("MULTIPOINT ((0 0), (2 0))")
        assert c3.x == 1

    def test_distance(self):
        assert gf.st_distance("POINT (0 0)", "POINT (3 4)") == 5
        assert gf.st_distance(SQ, "POINT (13 10)") == 3
        assert gf.st_distance(SQ, "POINT (5 5)") == 0
        d = gf.st_distance(SQ, (np.array([13.0, 5.0]), np.array([10.0, 5.0])))
        assert d.tolist() == [3.0, 0.0]

    def test_distance_sphere(self):
        m = gf.st_distanceSphere("POINT (0 0)", "POINT (1 0)")
        assert m == pytest.approx(111_319, rel=0.01)

    def test_closest_point(self):
        p = gf.st_closestPoint(SQ, "POINT (15 5)")
        assert (p.x, p.y) == (10, 5)

    def test_buffer_point(self):
        b = gf.st_bufferPoint("POINT (0 45)", 10_000)
        assert b.kind == "polygon"
        # contains the center, excludes a point 20km away
        assert gf.st_contains(b, "POINT (0 45)")
        assert not gf.st_contains(b, "POINT (0 45.3)")
        # radius sanity: boundary vertex ~10km from center
        vx, vy = b.shell[0]
        assert geo.haversine_m(vx, vy, 0, 45) == pytest.approx(10_000, rel=0.01)

    def test_convexhull(self):
        h = gf.st_convexhull("MULTIPOINT ((0 0), (4 0), (4 4), (0 4), (2 2))")
        assert h.kind == "polygon"
        assert gf.st_area(h) == 16
        # aggregate over an object array of geometries
        arr = np.array(["POINT (0 0)", "POINT (1 0)", "POINT (0 1)"], dtype=object)
        h2 = gf.st_convexhull(arr)
        assert gf.st_area(h2) == pytest.approx(0.5)

    def test_translate(self):
        t = gf.st_translate(SQ, 5, -5)
        assert t.bounds() == (5, -5, 15, 5)

    def test_intersection(self):
        got = gf.st_intersection(SQ, "POLYGON ((5 5, 15 5, 15 15, 5 15, 5 5))")
        assert gf.st_area(got) == pytest.approx(25)
        assert gf.st_intersection(SQ, "POINT (5 5)").kind == "point"
        assert gf.st_intersection(SQ, "POLYGON ((20 20, 21 20, 21 21, 20 20))") is None

    def test_difference(self):
        hole = "POLYGON ((4 4, 6 4, 6 6, 4 6, 4 4))"
        d = gf.st_difference(SQ, hole)
        assert gf.st_area(d) == pytest.approx(96)
        far = "POLYGON ((20 20, 21 20, 21 21, 20 20))"
        assert gf.st_difference(SQ, far).wkt() == gf.st_geomFromText(SQ).wkt()

    def test_antimeridian_safe(self):
        g = gf.st_antimeridianSafeGeom(
            "POLYGON ((170 0, 190 0, 190 10, 170 10, 170 0))"
        )
        assert g.kind == "multipolygon"
        bs = [p.bounds() for p in g.polygons]
        assert any(b[2] <= 180 for b in bs) and any(b[0] >= -180 for b in bs)
        # in-range geometry unchanged
        same = gf.st_antimeridianSafeGeom(SQ)
        assert same.wkt() == gf.st_geomFromText(SQ).wkt()

    def test_aggregate_distance_sphere(self):
        d = gf.st_aggregateDistanceSphere(
            ["POINT (0 0)", "POINT (1 0)", "POINT (2 0)"]
        )
        assert d == pytest.approx(2 * 111_319, rel=0.01)
