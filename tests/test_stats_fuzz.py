"""Randomized differential test for the stats surface: random stat
specs over random predicate windows must match numpy oracles exactly
(counts, minmax, histogram bins, topk orders, grouped counts) — the
same sketches feed the cost model, so silent drift here skews planning
everywhere."""

pytestmark = __import__("pytest").mark.fuzz
import json

import numpy as np
import pytest

from geomesa_tpu import GeoDataset
from geomesa_tpu.filter.ecql import parse_iso_ms

N = 12_000
T0 = parse_iso_ms("2020-01-01")
T1 = parse_iso_ms("2020-02-01")


@pytest.fixture(scope="module")
def sfuzz():
    rng = np.random.default_rng(202)
    data = {
        "v": np.round(rng.uniform(0, 10, N), 3),
        "i": rng.integers(-30, 30, N).astype(np.int32),
        "k": rng.choice(np.array(["a", "b", "c", "d", "e"]), N),
        "dtg": rng.integers(T0, T1, N).astype("datetime64[ms]"),
        "geom__x": rng.uniform(-20, 20, N),
        "geom__y": rng.uniform(-20, 20, N),
    }
    ds = GeoDataset(n_shards=2)
    ds.create_schema("t", "v:Double,i:Integer,k:String,dtg:Date,*geom:Point")
    ds.insert("t", data, fids=np.arange(N).astype(str))
    ds.flush()
    return ds, data


def _rand_window(rng, d):
    kind = rng.integers(0, 3)
    if kind == 0:
        return "INCLUDE", np.ones(N, bool)
    if kind == 1:
        # round BEFORE building the oracle mask: the ECQL text carries
        # 2-decimal bounds, so the oracle must use the same values
        x0, y0 = (round(float(v), 2) for v in rng.uniform(-20, 5, 2))
        m = ((d["geom__x"] >= x0) & (d["geom__x"] <= x0 + 15)
             & (d["geom__y"] >= y0) & (d["geom__y"] <= y0 + 15))
        return f"BBOX(geom, {x0}, {y0}, {x0+15}, {y0+15})", m
    v = round(float(rng.uniform(2, 8)), 2)
    return f"v > {v}", d["v"] > v


def test_random_stats_match_oracle(sfuzz):
    ds, d = sfuzz
    rng = np.random.default_rng(303)
    for case in range(60):
        ecql, m = _rand_window(rng, d)
        kind = rng.integers(0, 5)
        if kind == 0:
            got = json.loads(ds.stats("t", "Count()", ecql).to_json())
            assert got["count"] == int(m.sum()), (case, ecql)
        elif kind == 1:
            got = json.loads(ds.stats("t", "MinMax(v)", ecql).to_json())
            if m.any():
                assert got["lo"] == pytest.approx(float(d["v"][m].min()))
                assert got["hi"] == pytest.approx(float(d["v"][m].max()))
        elif kind == 2:
            bins = int(rng.choice([4, 10, 17]))
            stat = ds.stats("t", f"Histogram(v,{bins},0,10)", ecql)
            counts = np.asarray(stat.counts).ravel()
            idx = np.clip((d["v"][m] / 10 * bins).astype(int), 0, bins - 1)
            want = np.bincount(idx, minlength=bins)
            assert np.array_equal(counts, want), (case, ecql, bins)
        elif kind == 3:
            got = json.loads(ds.stats("t", "Enumeration(k)", ecql).to_json())
            want = {k: int(c) for k, c in zip(
                *np.unique(d["k"][m], return_counts=True))}
            assert dict(got["counts"]) == want, (case, ecql)
        else:
            got = json.loads(ds.stats(
                "t", "GroupBy(k,Count())", ecql).to_json())
            # per-group exactness: group keys are dictionary codes
            vocab = ds._store("t").dicts["k"].values
            by = {vocab[int(code)]: json.loads(sub)["count"]
                  for code, sub in got["groups"]}
            keys, cnts = np.unique(d["k"][m], return_counts=True)
            want = {str(kk): int(c) for kk, c in zip(keys, cnts)}
            assert by == want, (case, ecql)


def test_stats_partial_merge_associativity(sfuzz):
    """Sketches must merge associatively: stats over A OR B == merge of
    the disjoint windows' stats (the multi-partition / multi-shard merge
    contract)."""
    ds, d = sfuzz
    left = "BBOX(geom, -20, -20, 0, 20)"
    right = "BBOX(geom, 0.000001, -20, 20, 20)"
    both = f"({left}) OR ({right})"
    for spec in ("Count()", "MinMax(v)", "Histogram(v,8,0,10)",
                 "Enumeration(k)"):
        a = json.loads(ds.stats("t", spec, left).to_json())
        b = json.loads(ds.stats("t", spec, right).to_json())
        ab = json.loads(ds.stats("t", spec, both).to_json())
        if spec == "Count()":
            assert a["count"] + b["count"] == ab["count"]
        elif spec == "MinMax(v)":
            assert ab["lo"] == pytest.approx(min(a["lo"], b["lo"]))
            assert ab["hi"] == pytest.approx(max(a["hi"], b["hi"]))
        elif spec.startswith("Histogram"):
            ca = np.asarray(ds.stats("t", spec, left).counts).ravel()
            cb = np.asarray(ds.stats("t", spec, right).counts).ravel()
            cab = np.asarray(ds.stats("t", spec, both).counts).ravel()
            assert np.array_equal(ca + cb, cab)
        else:
            da, db, dab = dict(a["counts"]), dict(b["counts"]), dict(ab["counts"])
            merged = {k: da.get(k, 0) + db.get(k, 0) for k in set(da) | set(db)}
            assert merged == dab
