"""BIN format tests (BinaryOutputEncoder / BinSorter parity)."""

import struct

import numpy as np

from geomesa_tpu import GeoDataset
from geomesa_tpu.filter.ecql import parse_iso_ms
from geomesa_tpu.io import bin_format


def test_java_string_hash():
    # oracle values from Java String.hashCode
    assert bin_format.java_string_hash("") == 0
    assert bin_format.java_string_hash("a") == 97
    assert bin_format.java_string_hash("abc") == 96354
    assert bin_format.java_string_hash("hello world") == 1794106052
    # int32 wraparound ("polygenelubricants" hashes to Integer.MIN_VALUE)
    assert bin_format.java_string_hash("polygenelubricants") == -2147483648


def test_pack_unpack_16():
    b = bin_format.pack(
        np.array([1, 2], np.int32),
        np.array([5000, 1000], np.int64),  # ms
        np.array([10.5, 20.5]),
        np.array([-100.0, -90.0]),
    )
    assert len(b) == 32
    out = bin_format.unpack(b)
    # sorted by time
    np.testing.assert_array_equal(out["track"], [2, 1])
    np.testing.assert_array_equal(out["dtg_s"], [1, 5])
    np.testing.assert_allclose(out["lat"], [20.5, 10.5])
    # wire layout: little-endian i4 i4 f4 f4
    track0, dtg0, lat0, lon0 = struct.unpack("<iiff", b[:16])
    assert (track0, dtg0) == (2, 1)
    assert abs(lat0 - 20.5) < 1e-6 and abs(lon0 + 90.0) < 1e-6


def test_pack_label_24():
    b = bin_format.pack(
        np.array([7], np.int32), np.array([1000], np.int64),
        np.array([1.0]), np.array([2.0]),
        labels=bin_format.label_to_i64(["ab"]),
    )
    assert len(b) == 24
    out = bin_format.unpack(b, label=True)
    assert out["label"][0] == int.from_bytes(b"ab".ljust(8, b"\0"), "little", signed=True)
    assert bin_format.record_size(b) == 24


def test_merge_sorted():
    def mk(ts):
        return bin_format.pack(
            np.zeros(len(ts), np.int32), np.array(ts, np.int64) * 1000,
            np.zeros(len(ts)), np.zeros(len(ts)),
        )

    merged = bin_format.merge_sorted([mk([1, 5, 9]), mk([2, 3, 8]), mk([4])])
    out = bin_format.unpack(merged)
    np.testing.assert_array_equal(out["dtg_s"], [1, 2, 3, 4, 5, 8, 9])


def test_dataset_export_bin():
    rng = np.random.default_rng(3)
    n = 500
    ds = GeoDataset(n_shards=2)
    ds.create_schema("t", "name:String,dtg:Date,*geom:Point")
    data = {
        "name": [f"trk{i % 5}" for i in range(n)],
        "dtg": rng.integers(
            parse_iso_ms("2020-01-01"), parse_iso_ms("2020-01-10"), n
        ).astype("datetime64[ms]"),
        "geom__x": rng.uniform(-120, -70, n),
        "geom__y": rng.uniform(25, 50, n),
    }
    ds.insert("t", data)
    payload = ds.export_bin("t", "BBOX(geom, -120, 25, -70, 50)", track="name")
    k = ds.count("t")
    assert len(payload) == 16 * k
    out = bin_format.unpack(payload)
    assert np.all(np.diff(out["dtg_s"]) >= 0)  # time-sorted
    assert set(out["track"]) == {
        bin_format.java_string_hash(f"trk{i}") for i in range(5)
    }
    # labeled export
    payload = ds.export_bin("t", track="name", label="name")
    assert len(payload) == 24 * k


def test_export_bin_all_null_string_attr():
    ds = GeoDataset(n_shards=2)
    ds.create_schema("t", "lab:String,dtg:Date,*geom:Point")
    ds.insert("t", {
        "lab": [None, None],
        "dtg": np.array(["2020-01-01", "2020-01-02"], "datetime64[ms]"),
        "geom__x": [1.0, 2.0], "geom__y": [3.0, 4.0],
    })
    payload = ds.export_bin("t", track="lab", label="lab")
    assert len(payload) == 2 * 24
    out = bin_format.unpack(payload, label=True)
    assert list(out["track"]) == [0, 0] and list(out["label"]) == [0, 0]


def test_java_hash_astral():
    # non-BMP char must hash as its UTF-16 surrogate pair (Java semantics):
    # for U+1D11E: h = 0xD834*31 + 0xDD1E
    assert bin_format.java_string_hash("\U0001D11E") == 0xD834 * 31 + 0xDD1E
