"""REST endpoint (geomesa-web analog), GeoJSON façade, Leaflet helper."""

import json
import urllib.request

import numpy as np
import pytest

from geomesa_tpu import GeoDataset
from geomesa_tpu.geojson_api import GeoJsonIndex


def _ds(n=200, seed=0):
    rng = np.random.default_rng(seed)
    ds = GeoDataset(n_shards=2)
    ds.create_schema("t", "name:String,v:Integer,dtg:Date,*geom:Point")
    ds.insert("t", {
        "geom__x": rng.uniform(-10, 10, n),
        "geom__y": rng.uniform(-10, 10, n),
        "dtg": rng.integers(1577836800000, 1580515200000, n).astype("datetime64[ms]"),
        "name": rng.choice(["a", "b"], n),
        "v": rng.integers(0, 100, n),
    }, fids=np.array([f"f{i}" for i in range(n)]))
    ds.flush("t")
    return ds


@pytest.fixture(scope="module")
def server():
    from geomesa_tpu import web

    ds = _ds()
    srv = web.serve(ds, "127.0.0.1", 0, background=True)
    port = srv.server_address[1]
    yield f"http://127.0.0.1:{port}", ds
    srv.shutdown()


def _get(base, path, headers=None):
    req = urllib.request.Request(base + path, headers=headers or {})
    with urllib.request.urlopen(req) as r:
        body = r.read()
        ct = r.headers.get("Content-Type", "")
    return json.loads(body), ct


def test_rest_endpoints(server):
    base, ds = server
    v, _ = _get(base, "/api/version")
    assert "version" in v
    schemas, _ = _get(base, "/api/schemas")
    assert schemas == ["t"]
    info, _ = _get(base, "/api/schemas/t")
    assert info["count"] == 200 and "z3" in info["indices"]
    cnt, _ = _get(base, "/api/schemas/t/count?cql=" +
                  urllib.parse.quote("BBOX(geom, 0, 0, 10, 10)"))
    assert cnt["count"] == ds.count("t", "BBOX(geom, 0, 0, 10, 10)")
    b, _ = _get(base, "/api/schemas/t/bounds")
    assert len(b) == 4
    st, _ = _get(base, "/api/schemas/t/stats?stat=" +
                 urllib.parse.quote("MinMax(v)"))
    assert st["kind"] == "minmax"
    h, _ = _get(base, "/api/schemas/t/histogram?attribute=v&bins=10")
    assert h["kind"] == "histogram"
    dmap, _ = _get(base, "/api/schemas/t/density?bbox=-10,-10,10,10&width=16&height=16")
    assert dmap["width"] == 16
    assert abs(sum(map(sum, dmap["grid"])) - 200) < 1e-2
    fc, ct = _get(base, "/api/schemas/t/features?max=5")
    assert ct.startswith("application/geo+json")
    assert len(fc["features"]) == 5


def test_rest_errors(server):
    base, _ = server
    with pytest.raises(urllib.request.HTTPError) as ei:
        _get(base, "/api/schemas/nope")
    assert ei.value.code == 404
    with pytest.raises(urllib.request.HTTPError) as ei:
        _get(base, "/api/schemas/t/stats")
    assert ei.value.code == 400


import urllib.parse  # noqa: E402  (used above in f-strings)


def test_geojson_index_roundtrip():
    ds = GeoDataset(n_shards=2)
    api = GeoJsonIndex(ds)
    api.create_index("pts")
    fc = {
        "type": "FeatureCollection",
        "features": [
            {"type": "Feature", "id": "a",
             "geometry": {"type": "Point", "coordinates": [1.0, 2.0]},
             "properties": {"name": "alice", "score": 10}},
            {"type": "Feature", "id": "b",
             "geometry": {"type": "Point", "coordinates": [5.0, 6.0]},
             "properties": {"name": "bob", "score": 30}},
        ],
    }
    ids = api.add("pts", fc)
    assert ids == ["a", "b"]
    # bbox query
    got = api.query("pts", {"bbox": [0, 0, 3, 3]})
    assert len(got) == 1 and got[0]["properties"]["name"] == "alice"
    # property equality
    got = api.query("pts", {"properties.name": "bob"})
    assert len(got) == 1 and got[0]["id"] == "b"
    # comparison
    got = api.query("pts", {"properties.score": {"$gt": 20}})
    assert [d["id"] for d in got] == ["b"]
    # $or
    got = api.query("pts", {"$or": [
        {"properties.name": "alice"}, {"properties.name": "bob"},
    ]})
    assert len(got) == 2
    # intersects with polygon
    got = api.query("pts", {"intersects": {
        "type": "Polygon",
        "coordinates": [[[0, 0], [2, 0], [2, 3], [0, 3], [0, 0]]],
    }})
    assert len(got) == 1 and got[0]["id"] == "a"


def test_geojson_or_is_exact():
    """$or with property predicates must not over-return (regression: prop
    clauses inside $or were dropped, matching everything)."""
    ds = GeoDataset(n_shards=2)
    api = GeoJsonIndex(ds)
    api.create_index("pts")
    api.add("pts", {"type": "FeatureCollection", "features": [
        {"type": "Feature", "id": i,
         "geometry": {"type": "Point", "coordinates": [float(i), 0.0]},
         "properties": {"name": n}}
        for i, n in enumerate(["alice", "bob", "carol", "dave"])
    ]})
    got = api.query("pts", {"$or": [
        {"properties.name": "alice"}, {"properties.name": "bob"},
    ]})
    assert sorted(d["properties"]["name"] for d in got) == ["alice", "bob"]
    # mixed spatial + property inside $or
    got = api.query("pts", {"$or": [
        {"bbox": [2.5, -1, 3.5, 1]},          # dave's point only
        {"properties.name": "alice"},
    ]})
    assert sorted(d["properties"]["name"] for d in got) == ["alice", "dave"]
    # quoting in values cannot break the filter
    got = api.query("pts", {"id": "o'brien"})
    assert got == []
    with pytest.raises(ValueError):
        api.query("pts", {"$where": "1=1"})


def test_leaflet_render():
    from geomesa_tpu import jupyter

    ds = _ds(n=20)
    html = jupyter.render_features(ds, "t")
    assert "L.geoJSON" in html and "leaflet" in html
    html = jupyter.render_density(ds, "t", bbox=(-10, -10, 10, 10),
                                  width=16, height_cells=16)
    assert "L.rectangle" in html and "fitBounds" in html


def test_web_xyz_tiles(server):
    """/tiles/z/x/y: curve-aligned tile-pyramid heatmap (the WMS
    DensityProcess surface). Sibling tiles partition the data exactly."""
    base, ds = server
    total = 0
    z = 2
    for x in range(1 << (z + 1)):
        for y in range(1 << z):
            t, _ = _get(base, f"/api/schemas/t/tiles/{z}/{x}/{y}?detail=4")
            total += sum(map(sum, t["grid"]))
            # morton blocks span 360/2^l x 180/2^l degrees, so a square-
            # degree tile is twice as tall in blocks as it is wide
            assert (t["width"], t["height"]) == (8, 16)
    assert total == ds.count("t", "INCLUDE")


def _req(base, path, method, body=None, headers=None):
    data = body.encode() if isinstance(body, str) else body
    req = urllib.request.Request(base + path, data=data, method=method,
                                 headers=headers or {})
    try:
        with urllib.request.urlopen(req) as r:
            return json.loads(r.read().decode()), r.status
    except urllib.error.HTTPError as e:
        return json.loads(e.read().decode()), e.code


def test_rest_crud_lifecycle():
    """The JVM DataStore's transport: create schema -> ingest GeoJSON ->
    query -> delete-by-filter -> drop schema, all over REST."""
    import urllib.error

    from geomesa_tpu import web

    ds = GeoDataset(n_shards=1)
    srv = web.serve(ds, "127.0.0.1", 0, background=True)
    base = f"http://127.0.0.1:{srv.server_address[1]}"
    try:
        body, code = _req(base, "/api/schemas", "POST", json.dumps(
            {"name": "crud", "spec": "name:String,v:Integer,dtg:Date,"
                                     "*geom:Point"}))
        assert code == 201 and body["name"] == "crud"
        # conflict on duplicate create
        _, code = _req(base, "/api/schemas", "POST", json.dumps(
            {"name": "crud", "spec": "x:Integer"}))
        assert code == 409
        fc = {"type": "FeatureCollection", "features": [
            {"type": "Feature", "id": f"f{i}",
             "geometry": {"type": "Point", "coordinates": [float(i), 1.0]},
             "properties": {"name": "ab"[i % 2], "v": i,
                            "dtg": "2020-01-05T00:00:00"}}
            for i in range(10)
        ]}
        body, code = _req(base, "/api/schemas/crud/features", "POST",
                          json.dumps(fc))
        assert code == 201 and body["inserted"] == 10
        got, _ = _req(base, "/api/schemas/crud/count?cql=v%20%3E%204", "GET")
        assert got["count"] == 5
        body, code = _req(
            base, "/api/schemas/crud/features?cql=name%20%3D%20%27a%27",
            "DELETE")
        assert code == 200 and body["deleted"] == 5
        got, _ = _req(base, "/api/schemas/crud/count", "GET")
        assert got["count"] == 5
        # missing cql on feature delete is a 400, not a table wipe
        _, code = _req(base, "/api/schemas/crud/features", "DELETE")
        assert code == 400
        body, code = _req(base, "/api/schemas/crud", "DELETE")
        assert code == 200
        assert "crud" not in ds.list_schemas()
        _, code = _req(base, "/api/schemas/crud", "DELETE")
        assert code == 404
    finally:
        srv.shutdown()


def test_from_geojson_extent_and_nulls():
    """from_geojson: non-point geometries become WKT; missing properties
    fill with the columnar null representation."""
    from geomesa_tpu.io import geojson as gj

    ds = GeoDataset(n_shards=1)
    ft = ds.create_schema("poly", "v:Double,*geom:Polygon")
    doc = {"type": "FeatureCollection", "features": [
        {"type": "Feature", "id": "p1",
         "geometry": {"type": "Polygon", "coordinates":
                      [[[0, 0], [4, 0], [4, 4], [0, 4], [0, 0]]]},
         "properties": {"v": 2.5}},
        {"type": "Feature", "id": "p2",
         "geometry": {"type": "Polygon", "coordinates":
                      [[[10, 10], [12, 10], [12, 12], [10, 12], [10, 10]]]},
         "properties": {}},
    ]}
    data, fids = gj.from_geojson(ft, doc)
    assert list(fids) == ["p1", "p2"]
    assert data["geom"][0].startswith("POLYGON")
    assert np.isnan(data["v"][1])
    ds.insert("poly", data, fids=fids)
    ds.flush("poly")
    assert ds.count("poly", "INTERSECTS(geom, POLYGON((1 1, 2 1, 2 2, 1 2, 1 1)))") == 1


def test_rest_write_error_mapping_and_auths():
    """Review r5: malformed GeoJSON bodies are 400s (not 404/500), and
    delete-by-filter honors X-Geomesa-Auths like every read endpoint."""
    import urllib.error

    from geomesa_tpu import web

    ds = GeoDataset(n_shards=1)
    srv = web.serve(ds, "127.0.0.1", 0, background=True)
    base = f"http://127.0.0.1:{srv.server_address[1]}"
    try:
        _req(base, "/api/schemas", "POST", json.dumps(
            {"name": "w", "spec": "v:Integer,*geom:Point"}))
        # wrong geometry type for a Point attribute -> 400
        body, code = _req(base, "/api/schemas/w/features", "POST", json.dumps(
            {"type": "FeatureCollection", "features": [
                {"type": "Feature", "id": "l1",
                 "geometry": {"type": "LineString",
                              "coordinates": [[1, 2], [3, 4]]},
                 "properties": {"v": 1}}]}))
        assert code == 400 and "Point-typed" in body["error"]
        # geometry missing 'coordinates' -> 400 naming the malformation
        body, code = _req(base, "/api/schemas/w/features", "POST", json.dumps(
            {"type": "Feature", "id": "m", "geometry": {"type": "Point"},
             "properties": {"v": 1}}))
        assert code == 400 and "malformed GeoJSON" in body["error"]
        # visibility: restricted auths cannot delete rows they cannot see
        fc = {"type": "FeatureCollection", "features": [
            {"type": "Feature", "id": f"v{i}",
             "geometry": {"type": "Point", "coordinates": [float(i), 0.0]},
             "properties": {"v": i}} for i in range(4)]}
        _req(base, "/api/schemas/w/features", "POST", json.dumps(fc))
        # mark all rows secret via the py API (the REST ingest carries no
        # visibilities yet), then delete with empty auths
        ds.delete_features("w", "INCLUDE")
        ds.insert("w", {"geom__x": np.arange(4.0), "geom__y": np.zeros(4),
                        "v": np.arange(4, dtype=np.int32)},
                  fids=np.array([f"s{i}" for i in range(4)], dtype=object),
                  visibilities="secret")
        ds.flush("w")
        req = urllib.request.Request(
            base + "/api/schemas/w/features?cql=INCLUDE", method="DELETE",
            headers={"X-Geomesa-Auths": ""})
        with urllib.request.urlopen(req) as r:
            assert json.loads(r.read().decode())["deleted"] == 0
        req = urllib.request.Request(
            base + "/api/schemas/w/features?cql=INCLUDE", method="DELETE",
            headers={"X-Geomesa-Auths": "secret"})
        with urllib.request.urlopen(req) as r:
            assert json.loads(r.read().decode())["deleted"] == 4
    finally:
        srv.shutdown()


def test_geojson_multilinestring_round_trip():
    """to_geojson/from_geojson are symmetric for MultiLineString."""
    from geomesa_tpu.io import geojson as gj

    ds = GeoDataset(n_shards=1)
    ft = ds.create_schema("mls", "*geom:MultiLineString")
    wkt = "MULTILINESTRING ((0 0, 1 1), (2 2, 3 3, 4 4))"
    ds.insert("mls", {"geom": [wkt]}, fids=["a"])
    ds.flush("mls")
    st = ds._store("mls")
    doc = gj.to_geojson(ft, st._all, st.dicts)
    g = doc["features"][0]["geometry"]
    assert g["type"] == "MultiLineString" and len(g["coordinates"]) == 2
    data, fids = gj.from_geojson(ft, doc)
    assert data["geom"][0].startswith("MULTILINESTRING")
