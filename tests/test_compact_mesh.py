"""Mesh-sharded window compaction: on a NamedSharding'd store, additive
aggregates slab-gather only their window rows per device (shard_map +
psum), matching the host oracle exactly — the multi-chip analog of the
single-chip compact path (AbstractBatchScan.scala:32: only planned ranges
are ever read)."""

import numpy as np
import pytest

from geomesa_tpu import GeoDataset, config
from geomesa_tpu.filter.ecql import parse_iso_ms
from geomesa_tpu.parallel.mesh import shard_mesh

ECQL = (
    "BBOX(geom, -100, 30, -80, 45) AND "
    "dtg DURING 2020-01-05T00:00:00Z/2020-01-15T00:00:00Z"
)
BBOX = (-100.0, 30.0, -80.0, 45.0)


@pytest.fixture
def mesh_ds():
    rng = np.random.default_rng(21)
    n = 80_000
    lo, hi = parse_iso_ms("2020-01-01"), parse_iso_ms("2020-02-01")
    data = {
        "geom__x": rng.uniform(-120, -70, n),
        "geom__y": rng.uniform(25, 50, n),
        "dtg": rng.integers(lo, hi, n).astype("datetime64[ms]"),
        "weight": rng.uniform(0, 1, n).astype(np.float32),
    }
    mesh = shard_mesh(8)
    ds = GeoDataset(n_shards=8, mesh=mesh)
    ds.create_schema("t", "weight:Float,dtg:Date,*geom:Point")
    ds.insert("t", data, fids=np.arange(n).astype(str))
    ds.flush("t")
    return ds, data


@pytest.fixture
def force_compact():
    config.COMPACT_MIN_ROWS.set(1)
    config.COMPACT_FRACTION.set(2.0)
    yield
    config.COMPACT_MIN_ROWS.set(None)
    config.COMPACT_FRACTION.set(None)


def _oracle(data):
    x, y = data["geom__x"], data["geom__y"]
    t = data["dtg"].astype(np.int64)
    return (
        (x >= -100) & (x <= -80) & (y >= 30) & (y <= 45)
        & (t >= parse_iso_ms("2020-01-05"))
        & (t <= parse_iso_ms("2020-01-15"))
    )


def _mesh_desc(ds, plan):
    st = ds._store("t")
    ex = ds._executor(st)
    setup = ex._scan_setup(plan, [])
    mesh = ex._plain_shard_mesh()
    assert mesh is not None
    return ex._mesh_compact_desc(plan, setup, mesh.shape["shard"])


def test_mesh_compact_count(mesh_ds, force_compact):
    ds, data = mesh_ds
    st, _, plan = ds._plan("t", ECQL)
    assert _mesh_desc(ds, plan) is not None, "mesh compaction did not engage"
    assert ds.count("t", ECQL) == int(_oracle(data).sum())


def test_mesh_compact_density(mesh_ds, force_compact):
    ds, data = mesh_ds
    m = _oracle(data)
    grid = ds.density("t", ECQL, bbox=BBOX, width=128, height=128)
    assert int(grid.sum()) == int(m.sum())
    # per-cell agreement with the f32-coordinate oracle
    x32 = data["geom__x"].astype(np.float32)
    y32 = data["geom__y"].astype(np.float32)
    px = np.clip(((x32 - np.float32(BBOX[0])) / np.float32(20)
                  * np.float32(128)).astype(np.int64), 0, 127)
    py = np.clip(((y32 - np.float32(BBOX[1])) / np.float32(15)
                  * np.float32(128)).astype(np.int64), 0, 127)
    ref = np.zeros(128 * 128, np.float64)
    np.add.at(ref, py[m] * 128 + px[m], 1.0)
    assert np.array_equal(grid.astype(np.float64), ref.reshape(128, 128))


def test_mesh_compact_matches_padded(mesh_ds, force_compact):
    """Same query with compaction disabled (padded GSPMD path) agrees."""
    ds, data = mesh_ds
    g1 = ds.density("t", ECQL, bbox=BBOX, width=64, height=64)
    with config.COMPACT_ENABLED.scoped(False):
        g2 = ds.density("t", ECQL + " AND weight >= 0", bbox=BBOX,
                        width=64, height=64)
    assert np.array_equal(g1, g2)


def test_mesh_compact_stats(mesh_ds, force_compact):
    ds, data = mesh_ds
    m = _oracle(data)
    s = ds.min_max("t", "weight", ECQL)
    w = data["weight"][m]
    assert s["min"] == pytest.approx(float(w.min()), rel=1e-6)
    assert s["max"] == pytest.approx(float(w.max()), rel=1e-6)
