"""Geometry substrate tests (WKT, point-in-polygon, distance)."""

import numpy as np
import pytest

from geomesa_tpu.utils import geometry as geo


def test_wkt_roundtrip_point():
    p = geo.parse_wkt("POINT (-73.98 40.75)")
    assert isinstance(p, geo.Point)
    assert p.x == -73.98 and p.y == 40.75
    assert geo.parse_wkt(p.wkt()) == p


def test_wkt_polygon_with_hole():
    wkt = "POLYGON ((0 0, 10 0, 10 10, 0 10, 0 0), (4 4, 6 4, 6 6, 4 6, 4 4))"
    p = geo.parse_wkt(wkt)
    assert isinstance(p, geo.Polygon)
    assert len(p.holes) == 1
    assert p.bounds() == (0, 0, 10, 10)
    p2 = geo.parse_wkt(p.wkt())
    assert p2.shell == p.shell and p2.holes == p.holes


def test_wkt_multipolygon():
    wkt = "MULTIPOLYGON (((0 0, 2 0, 2 2, 0 2, 0 0)), ((5 5, 7 5, 7 7, 5 7, 5 5)))"
    m = geo.parse_wkt(wkt)
    assert isinstance(m, geo.MultiPolygon)
    assert len(m.polygons) == 2
    assert geo.parse_wkt(m.wkt()).bounds() == m.bounds()


def test_wkt_linestring_and_multipoint():
    l = geo.parse_wkt("LINESTRING (0 0, 5 5, 10 0)")
    assert isinstance(l, geo.LineString)
    assert l.bounds() == (0, 0, 10, 5)
    mp = geo.parse_wkt("MULTIPOINT ((1 2), (3 4))")
    assert isinstance(mp, geo.MultiPoint)


def test_wkt_errors():
    with pytest.raises(ValueError):
        geo.parse_wkt("FROB (1 2)")
    with pytest.raises(ValueError):
        geo.parse_wkt("POLYGON ")


def test_pip_convex(rng):
    # triangle
    p = geo.parse_wkt("POLYGON ((0 0, 10 0, 5 10, 0 0))")
    xs = rng.uniform(-2, 12, 2000)
    ys = rng.uniform(-2, 12, 2000)
    got = p.contains_points(xs, ys)
    # barycentric oracle
    def inside(x, y):
        d1 = (x - 0) * (0 - 0) - (10 - 0) * (y - 0)
        s = (10 - 0) * (y - 0) - (x - 0) * (0 - 0) >= 0  # left of base
        a = (5 - 10) * (y - 0) - (x - 10) * (10 - 0) >= 0
        b = (0 - 5) * (y - 10) - (x - 5) * (0 - 10) >= 0
        return s and a and b
    oracle = np.array([inside(x, y) for x, y in zip(xs, ys)])
    assert np.mean(got == oracle) > 0.999  # allow boundary epsilon cases


def test_pip_with_hole(rng):
    p = geo.parse_wkt(
        "POLYGON ((0 0, 10 0, 10 10, 0 10, 0 0), (4 4, 6 4, 6 6, 4 6, 4 4))"
    )
    assert p.contains_points(np.array([2.0]), np.array([2.0]))[0]
    assert not p.contains_points(np.array([5.0]), np.array([5.0]))[0]  # in hole
    assert not p.contains_points(np.array([11.0]), np.array([5.0]))[0]
    # boundary of shell is inside; boundary of hole stays inside
    assert p.contains_points(np.array([0.0]), np.array([5.0]))[0]
    assert p.contains_points(np.array([4.0]), np.array([5.0]))[0]


def test_is_rectangle():
    assert geo.bbox_polygon(0, 0, 2, 3).is_rectangle()
    assert not geo.parse_wkt("POLYGON ((0 0, 10 0, 5 10, 0 0))").is_rectangle()


def test_haversine():
    # JFK -> LAX ~ 3974 km
    d = geo.haversine_m(-73.7781, 40.6413, -118.4085, 33.9416)
    assert d == pytest.approx(3.974e6, rel=0.01)
    assert geo.haversine_m(0, 0, 0, 0) == 0.0


def test_edge_buffers_padding():
    m = geo.parse_wkt(
        "MULTIPOLYGON (((0 0, 2 0, 2 2, 0 2, 0 0)), ((5 5, 7 5, 7 7, 5 7, 5 5)))"
    )
    eb = geo.polygon_edge_buffers(m, pad_to=16)
    assert len(eb["x1"]) == 16
    assert eb["n_polys"] == 2
    assert (eb["sign"][8:] == 0).all()
