"""Curve-aligned density (r4): exact per-block counts via CDF differences
over the z2-sorted scan — the index-native heatmap for tile pyramids.
Oracle: bin each point by the top bits of its normalized coordinate (the
same fixed-point mapping the z2 keys are built from).
"""

import numpy as np
import pytest

from geomesa_tpu import GeoDataset, Query
from geomesa_tpu.curves.zorder import Z2SFC
from geomesa_tpu.filter.ecql import parse_iso_ms

N = 50_000
SPEC = "weight:Float,dtg:Date,*geom:Point"


def _data(seed=21, n=N):
    rng = np.random.default_rng(seed)
    return {
        "weight": rng.uniform(0, 2, n).astype(np.float32),
        "dtg": rng.integers(
            parse_iso_ms("2020-01-01"), parse_iso_ms("2020-03-01"), n
        ).astype("datetime64[ms]"),
        "geom__x": rng.uniform(-125, -66, n),
        "geom__y": rng.uniform(24, 49, n),
    }


def _oracle(data, level, window, mask=None, weight=None):
    sfc = Z2SFC()
    ix = (sfc.lon.normalize(data["geom__x"]) >> np.uint64(31 - level)).astype(np.int64)
    iy = (sfc.lat.normalize(data["geom__y"]) >> np.uint64(31 - level)).astype(np.int64)
    ix0, iy0, ix1, iy1 = window
    m = (ix >= ix0) & (ix <= ix1) & (iy >= iy0) & (iy <= iy1)
    if mask is not None:
        m &= mask
    w = data[weight] if weight else np.ones(len(ix), np.float32)
    grid = np.zeros((iy1 - iy0 + 1, ix1 - ix0 + 1), np.float64)
    np.add.at(grid, (iy[m] - iy0, ix[m] - ix0), w[m])
    return grid.astype(np.float32)


@pytest.fixture(scope="module")
def ds():
    data = _data()
    d = GeoDataset(n_shards=8)
    d.create_schema("t", SPEC)
    d.insert("t", data, fids=np.arange(N).astype(str))
    d.flush()
    return d, data


def test_include_full_domain(ds):
    d, data = ds
    level = 6
    grid, snapped = d.density_curve("t", "INCLUDE", level=level,
                                    bbox=(-180, -90, 180, 90))
    assert snapped == (-180.0, -90.0, 180.0, 90.0)
    want = _oracle(data, level, (0, 0, 63, 63))
    np.testing.assert_array_equal(grid, want)
    assert grid.sum() == N


def test_cropped_and_filtered(ds):
    d, data = ds
    level = 8
    ecql = ("BBOX(geom, -100, 30, -80, 45) AND "
            "dtg DURING 2020-01-05T00:00:00Z/2020-01-20T00:00:00Z")
    grid, snapped = d.density_curve("t", ecql, level=level,
                                    bbox=(-100, 30, -80, 45))
    x, y = data["geom__x"], data["geom__y"]
    t = data["dtg"].astype(np.int64)
    m = ((x >= -100) & (x <= -80) & (y >= 30) & (y <= 45)
         & (t >= parse_iso_ms("2020-01-05")) & (t <= parse_iso_ms("2020-01-20")))
    nb = 1 << level
    # inclusive outward snap (floor on both edges), matching density_curve
    ix0 = int(np.floor((-100 + 180) / 360 * nb))
    ix1 = int(np.floor((-80 + 180) / 360 * nb))
    iy0 = int(np.floor((30 + 90) / 180 * nb))
    iy1 = int(np.floor((45 + 90) / 180 * nb))
    want = _oracle(data, level, (ix0, iy0, ix1, iy1), mask=m)
    np.testing.assert_array_equal(grid, want)
    # snapped bbox contains the request
    assert snapped[0] <= -100 and snapped[1] <= 30
    assert snapped[2] >= -80 and snapped[3] >= 45


def test_weighted(ds):
    d, data = ds
    grid, _ = d.density_curve("t", "INCLUDE", level=5,
                              bbox=(-180, -90, 180, 90), weight="weight")
    want = _oracle(data, 5, (0, 0, 31, 31), weight="weight")
    np.testing.assert_allclose(grid, want, rtol=1e-4)


def test_host_and_device_agree(ds):
    d, data = ds
    host = GeoDataset(n_shards=8, prefer_device=False)
    host.create_schema("t", SPEC)
    host.insert("t", data, fids=np.arange(N).astype(str))
    ga, _ = d.density_curve("t", "INCLUDE", level=7, bbox=(-130, 20, -60, 50))
    gb, _ = host.density_curve("t", "INCLUDE", level=7, bbox=(-130, 20, -60, 50))
    np.testing.assert_array_equal(ga, gb)


def test_partitioned(ds):
    d, data = ds
    p = GeoDataset(n_shards=4)
    p.create_schema("t", SPEC + ";geomesa.partition='time'")
    p._store("t").max_resident = 1
    p.insert("t", data, fids=np.arange(N).astype(str))
    p.flush()
    ga, _ = p.density_curve("t", "INCLUDE", level=6, bbox=(-180, -90, 180, 90))
    want = _oracle(data, 6, (0, 0, 63, 63))
    np.testing.assert_array_equal(ga, want)


def test_matches_scatter_density_totals(ds):
    d, data = ds
    ecql = "BBOX(geom, -110, 28, -75, 47)"
    grid, snapped = d.density_curve("t", ecql, level=9, bbox=(-110, 28, -75, 47))
    assert float(grid.sum()) == float(d.count("t", ecql))


def test_bbox_edge_on_block_boundary(ds):
    """r4 review: a bbox edge exactly ON a block boundary must include the
    block containing it (inclusive x <= xmax semantics)."""
    d, _ = ds
    n2 = 100
    d2 = GeoDataset(n_shards=2)
    d2.create_schema("e", SPEC)
    # -78.75 is a level-9 block boundary (fx(-78.75) = 144.0 exactly)
    xs = np.full(n2, -78.75)
    ys = np.linspace(30, 40, n2)
    d2.insert("e", {
        "weight": np.ones(n2, np.float32),
        "dtg": np.full(n2, parse_iso_ms("2020-01-05")).astype("datetime64[ms]"),
        "geom__x": xs, "geom__y": ys,
    }, fids=np.arange(n2).astype(str))
    d2.flush()
    q = "BBOX(geom, -100, 28, -78.75, 42)"
    grid, snapped = d2.density_curve("e", q, level=9, bbox=(-100, 28, -78.75, 42))
    assert grid.sum() == d2.count("e", q) == n2
    assert snapped[2] >= -78.75
