"""Time-partitioned out-of-core store tests (TimePartition.scala:35 +
ParquetFileSystemStorage streaming analog): routing, pruning, spill/stream
correctness vs a plain in-RAM store, deletes, incremental checkpointing.
"""

import os

import numpy as np
import pytest

from geomesa_tpu import GeoDataset, Query
from geomesa_tpu.filter.ecql import parse_iso_ms
from geomesa_tpu.index.partitioned import PartitionedFeatureStore

SPEC = "name:String:index=true,weight:Double,dtg:Date,*geom:Point"
PSPEC = SPEC + ";geomesa.partition='time'"
N = 30_000

BBOX_TIME = (
    "BBOX(geom, -100, 30, -80, 45) AND "
    "dtg DURING 2020-01-05T00:00:00Z/2020-01-15T00:00:00Z"
)


def _data(n=N, seed=11):
    rng = np.random.default_rng(seed)
    return {
        "name": [f"actor{i % 20}" for i in range(n)],
        "weight": rng.uniform(0, 10, n),
        "dtg": rng.integers(
            parse_iso_ms("2020-01-01"), parse_iso_ms("2020-03-01"), n
        ).astype("datetime64[ms]"),
        "geom__x": rng.uniform(-120, -70, n),
        "geom__y": rng.uniform(25, 50, n),
    }


@pytest.fixture(scope="module")
def pair(tmp_path_factory):
    """(partitioned ds, plain ds) over identical data; partitioned store
    runs with max_resident=1 so every multi-partition query streams."""
    data = _data()
    plain = GeoDataset(n_shards=8)
    plain.create_schema("t", SPEC)
    plain.insert("t", data, fids=np.arange(N).astype(str))
    plain.flush()

    part = GeoDataset(n_shards=8)
    part.create_schema("t", PSPEC)
    st = part._store("t")
    assert isinstance(st, PartitionedFeatureStore)
    st.max_resident = 1
    st._spill_dir = str(tmp_path_factory.mktemp("spill"))
    part.insert("t", data, fids=np.arange(N).astype(str))
    part.flush()
    return part, plain, data


def test_partitions_created_and_spilled(pair):
    part, _, data = pair
    st = part._store("t")
    bins = st.partition_bins()
    # two months of data at weekly period -> ~9 partitions
    assert len(bins) >= 8
    assert len(st.partitions) <= st.max_resident
    assert len(st.spilled) >= len(bins) - st.max_resident
    for d in st.spilled.values():
        assert os.path.isdir(d)
    assert st.count == N


def test_count_and_features_match_plain(pair):
    part, plain, _ = pair
    for q in ("INCLUDE", BBOX_TIME, "name = 'actor7'", "weight < 2.5"):
        assert part.count("t", q) == plain.count("t", q), q
    fa = part.query("t", BBOX_TIME)
    fb = plain.query("t", BBOX_TIME)
    assert len(fa) == len(fb)
    assert sorted(fa.fids) == sorted(fb.fids)


def test_density_matches_plain(pair):
    part, plain, _ = pair
    bbox = (-100, 30, -80, 45)
    ga = part.density("t", BBOX_TIME, bbox=bbox, width=64, height=64)
    gb = plain.density("t", BBOX_TIME, bbox=bbox, width=64, height=64)
    np.testing.assert_allclose(ga, gb)


def test_stats_match_plain(pair):
    part, plain, _ = pair
    for spec in ("MinMax(weight)", "Enumeration(name)",
                 "Histogram(weight,10,0,10)"):
        va = part.stats("t", spec, BBOX_TIME).value()
        vb = plain.stats("t", spec, BBOX_TIME).value()
        assert va == vb, spec


def test_partition_pruning(pair):
    part, _, _ = pair
    st, _, plan = part._plan("t", BBOX_TIME)
    pex = part._executor(st)
    pruned = pex.prune(plan)
    # a 10-day window at weekly partitioning touches at most 3 partitions
    assert 1 <= len(pruned) <= 3
    assert set(pruned) <= set(st.partition_bins())
    ev_scanned_before = part.count("t", BBOX_TIME)
    ev = part.audit.recent(1)[-1]
    # selectivity counters aggregate only over pruned partitions
    assert ev.table_rows < N
    assert ev.scanned >= ev_scanned_before


def test_knn_matches_plain(pair):
    part, plain, _ = pair
    a = part.knn("t", -90.0, 38.0, k=7)
    b = plain.knn("t", -90.0, 38.0, k=7)
    assert len(a) == 7 == len(b)
    assert sorted(a.fids) == sorted(b.fids)


def test_sort_limit_projection(pair):
    part, plain, _ = pair
    q = Query(ecql=BBOX_TIME, sort_by=[("weight", False)], max_features=25,
              properties=["weight"])
    fa, fb = part.query("t", q), plain.query("t", q)
    assert len(fa) == len(fb) == 25
    np.testing.assert_allclose(fa.columns["weight"], fb.columns["weight"])


def test_delete_across_partitions(pair):
    part, plain, data = pair
    # fresh datasets so module fixture stays intact
    p2 = GeoDataset(n_shards=4)
    p2.create_schema("t", PSPEC)
    p2._store("t").max_resident = 1
    p2.insert("t", data, fids=np.arange(N).astype(str))
    p2.flush()
    removed = p2.delete_features("t", "weight < 5")
    w = data["weight"]
    assert removed == int((w < 5).sum())
    assert p2.count("t") == N - removed
    assert p2.count("t", "weight < 5") == 0


def test_streamed_reload_is_exact(pair):
    """Force every partition through a spill+reload cycle and re-verify."""
    part, plain, _ = pair
    st = part._store("t")
    st.evict(keep=1)
    assert part.count("t", BBOX_TIME) == plain.count("t", BBOX_TIME)


def test_save_load_roundtrip(tmp_path, pair):
    part, plain, _ = pair
    p = str(tmp_path / "ckpt")
    part.save(p)
    ds2 = GeoDataset.load(p)
    st2 = ds2._store("t")
    assert isinstance(st2, PartitionedFeatureStore)
    assert ds2.count("t") == N
    assert ds2.count("t", BBOX_TIME) == plain.count("t", BBOX_TIME)
    # merged stats survive without touching column data
    assert ds2.bounds("t") is not None


def test_incremental_checkpoint_touches_only_dirty(tmp_path):
    """append → save → append-to-one-partition → save: the second save must
    rewrite only the dirty partition's snapshot (GeoMesaMetadata /
    TableBasedMetadata incremental-catalog analog)."""
    data = _data(8_000, seed=3)
    ds = GeoDataset(n_shards=4)
    ds.create_schema("t", PSPEC)
    ds.insert("t", data, fids=np.arange(8_000).astype(str))
    ds.flush()
    p = str(tmp_path / "ckpt")
    ds.save(p)
    st = ds._store("t")
    def _snap_mtime(d):
        # format-agnostic: lake snapshots write part.lake, legacy data.npz
        for f in ("part.lake", "data.npz"):
            fp = os.path.join(d, f)
            if os.path.exists(fp):
                return os.path.getmtime(fp)
        raise AssertionError(f"no snapshot file in {d}")

    snap1 = {
        b: _snap_mtime(d)
        for b, d in st.checkpoint_into(p + "/t_parts").items()
    }
    # touch exactly one partition: a single row inside one period
    one = {
        "name": ["x"], "weight": np.asarray([1.0]),
        "dtg": np.asarray([parse_iso_ms("2020-01-08")]).astype("datetime64[ms]"),
        "geom__x": np.asarray([-90.0]), "geom__y": np.asarray([40.0]),
    }
    ds.insert("t", one, fids=np.asarray(["z1"]))
    ds.save(p)
    touched = []
    for b, d in st.checkpoint_into(p + "/t_parts").items():
        m = _snap_mtime(d)
        if m != snap1.get(b):
            touched.append(b)
    target_bin = st.binned.bin_of(parse_iso_ms("2020-01-08"))
    assert touched == [target_bin]


def test_device_and_host_paths_agree(pair):
    part, plain, _ = pair
    host = GeoDataset(n_shards=8, prefer_device=False)
    host.create_schema("t", PSPEC)
    host._store("t").max_resident = 2
    d = _data(5_000, seed=9)
    host.insert("t", d, fids=np.arange(5_000).astype(str))
    dev = GeoDataset(n_shards=8, prefer_device=True)
    dev.create_schema("t", PSPEC)
    dev._store("t").max_resident = 2
    dev.insert("t", d, fids=np.arange(5_000).astype(str))
    for q in (BBOX_TIME, "INCLUDE", "name = 'actor3'"):
        assert host.count("t", q) == dev.count("t", q), q


def test_update_schema_partitioned(tmp_path):
    """Append-only schema update re-indexes every partition under the new
    schema (GeoMesaDataStore.scala:288-336 transition validation analog);
    old rows read the added column as null/zero, new rows carry values."""
    data = _data(4_000, seed=13)
    ds = GeoDataset(n_shards=4, prefer_device=False)
    ds.create_schema("t", PSPEC)
    st = ds._store("t")
    st.max_resident = 1
    st._spill_dir = str(tmp_path / "spill")
    ds.insert("t", data, fids=np.arange(4_000).astype(str))
    ds.flush()
    before = ds.count("t", BBOX_TIME)
    ds.update_schema("t", "extra:Integer,tag:String")
    assert ds.count("t", BBOX_TIME) == before
    assert ds.count("t", "extra = 0") == 4_000  # null fill for old rows
    fc = ds.query("t", "INCLUDE")
    assert "extra" in fc.columns and "tag" in fc.columns


def test_lazy_columns_on_reload(tmp_path):
    """ColumnGroups analog (r4): a reloaded cold partition materializes
    only the columns its queries touch — a projected count never loads the
    unrelated attribute columns from the snapshot."""
    from geomesa_tpu.index.partitioned import _LazyCols

    data = _data(6_000, seed=8)
    ds = GeoDataset(n_shards=4, prefer_device=False)
    ds.create_schema("t", PSPEC)
    st = ds._store("t")
    st.max_resident = 1
    st._spill_dir = str(tmp_path / "spill")
    ds.insert("t", data, fids=np.arange(6_000).astype(str))
    ds.flush()
    st.evict(keep=1)
    # touch every partition with a count (loads lazily)
    n = ds.count("t", BBOX_TIME)
    assert n == GeoDatasetOracle(data)
    loaded = []
    for child in st.partitions.values():
        m = child._all.columns
        if isinstance(m, _LazyCols):
            loaded.append(set(dict.keys(m)))
    # the count touched geometry/time columns but never the 'name' string
    # or 'weight' attribute columns
    for keys in loaded:
        assert "name" not in keys and "weight" not in keys, keys
    # a full query then materializes what it needs and stays correct
    fc = ds.query("t", BBOX_TIME)
    assert len(fc) == n


def GeoDatasetOracle(data):
    x, y = data["geom__x"], data["geom__y"]
    t = data["dtg"].astype(np.int64)
    lo, hi = parse_iso_ms("2020-01-05"), parse_iso_ms("2020-01-15")
    return int((
        (x >= -100) & (x <= -80) & (y >= 30) & (y <= 45)
        & (t >= lo) & (t <= hi)
    ).sum())


def test_update_schema_keeps_spill_ownership(tmp_path):
    """After a partitioned update_schema, GC of the OLD store must not
    remove the shared spill dir out from under the new one."""
    import gc

    data = _data(3_000, seed=21)
    ds = GeoDataset(n_shards=2, prefer_device=False)
    ds.create_schema("t", PSPEC)
    st = ds._store("t")
    st.max_resident = 1
    ds.insert("t", data, fids=np.arange(3_000).astype(str))
    ds.flush()
    spill = st._spill_dir
    assert getattr(st, "_owns_spill_dir", False) or spill is not None
    before = ds.count("t", BBOX_TIME)
    ds.update_schema("t", "extra:Integer")
    del st
    gc.collect()
    # spilled snapshots must still be readable through the new store
    assert ds.count("t", BBOX_TIME) == before
