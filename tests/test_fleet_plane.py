"""Fleet observability plane (docs/OBSERVABILITY.md §9).

Covers the four tentpole pieces end to end on an in-process replica
fleet (the test_fleet.py harness shape):

* metrics federation — ``merge_exports`` exactness (counters add,
  histograms merge bucket-wise on identical ladders, ladder skew is
  counted not re-binned), the ``/metrics/fleet`` exposition;
* cross-replica trace stitching — one scattered query produces ONE
  stitched span tree whose replica-subtree count equals the surviving
  owner-group count, visible at ``/debug/queries?trace=<id>``;
* cell-heat telemetry — the cache decomposition loop feeds the heat
  table, snapshots merge with per-replica touch splits, ``/debug/heat``;
* fleet health composition — cordon/breaker/journal-lag combos degrade
  SOFT while capacity remains, HARD (503) only at zero usable replicas;
* the replica anomaly watchdog (observation only);
* the join-pushdown row-group residency cache (docs/JOIN.md §11).
"""

import json

import numpy as np
import pytest

from geomesa_tpu import (
    GeoDataset, config, heat, metrics, obs, resilience, tracing,
)
from geomesa_tpu.fleet import FleetRouter

SPEC = "name:String:index=true,speed:Float,dtg:Date,*geom:Point"
N = 600
WIDE = "BBOX(geom, -44, -27, 44, 27)"


def _data(n=N, seed=5):
    rng = np.random.default_rng(seed)
    xs = rng.uniform(-45, 45, n)
    ys = rng.uniform(-28, 28, n)
    return {
        "name": [f"n{i % 4}" for i in range(n)],
        "speed": rng.uniform(0, 30, n).astype(np.float32),
        "dtg": (np.datetime64("2024-05-01", "ms")
                + rng.integers(0, 20 * 86_400_000, n)),
        "geom": [(float(x), float(y)) for x, y in zip(xs, ys)],
    }


@pytest.fixture(autouse=True)
def _fresh_breakers():
    resilience.reset_breakers()
    yield
    resilience.reset_breakers()


@pytest.fixture(scope="module")
def root(tmp_path_factory):
    path = str(tmp_path_factory.mktemp("fleet_obs_root"))
    seed = GeoDataset(n_shards=1, prefer_device=False)
    seed.create_schema("t", SPEC)
    seed.insert("t", _data(), fids=[f"f{i}" for i in range(N)])
    seed.flush("t")
    seed.save(path)
    return path


@pytest.fixture(scope="module")
def oracle(root):
    return GeoDataset.load(root, prefer_device=False)


def _replica(root, rid):
    from geomesa_tpu.sidecar import GeoFlightServer

    return GeoFlightServer(
        GeoDataset.load(root, prefer_device=False),
        replica_id=rid, fleet_root=root,
    )


@pytest.fixture()
def fleet(root):
    servers = {rid: _replica(root, rid) for rid in ("r1", "r2", "r3")}
    router = FleetRouter({
        rid: f"grpc+tcp://127.0.0.1:{srv.port}"
        for rid, srv in servers.items()
    })
    yield servers, router
    router.close()
    for srv in servers.values():
        try:
            srv.shutdown()
        except Exception:
            pass


# ---------------------------------------------------------------------------
# metrics federation: merge exactness
# ---------------------------------------------------------------------------


def test_merge_exports_counters_and_histograms_exact():
    a = metrics.MetricRegistry(prefix="g")
    b = metrics.MetricRegistry(prefix="g")
    a.counter("q").inc(3)
    b.counter("q").inc(4)
    b.counter("only_b").inc(2)
    for v in (0.001, 0.2):
        a.histogram("trace.count").observe(v)
    for v in (0.001, 5.0):
        b.histogram("trace.count").observe(v)
    a.gauge("load").set(1.5)
    b.gauge("load").set(2.5)
    merged = metrics.merge_exports(
        {"r1": a.export_snapshot(), "r2": b.export_snapshot()}
    )
    # counters add EXACTLY, absent names are zero-not-missing semantics
    assert merged["counters"]["q"] == 7
    assert merged["counters"]["only_b"] == 2
    # histograms add bucket-wise on the shared ladder
    ha = a.histogram("trace.count").snapshot()
    hb = b.histogram("trace.count").snapshot()
    mh = merged["histograms"]["trace.count"]
    assert list(mh["buckets"]) == list(ha["buckets"])
    assert mh["counts"] == [x + y for x, y in zip(ha["counts"],
                                                  hb["counts"])]
    assert mh["count"] == 4
    assert mh["sum_s"] == pytest.approx(ha["sum_s"] + hb["sum_s"])
    # gauges keep per-replica identity
    assert merged["gauges"]["load"] == {"r1": 1.5, "r2": 2.5}
    assert merged["bucket_skew"] == {}


def test_merge_exports_counts_ladder_skew_instead_of_rebinning():
    a = metrics.MetricRegistry(prefix="g")
    b = metrics.MetricRegistry(prefix="g")
    a.histogram("h", buckets=(1.0, 2.0)).observe(0.5)
    b.histogram("h", buckets=(1.0, 3.0)).observe(0.5)
    merged = metrics.merge_exports(
        {"r1": a.export_snapshot(), "r2": b.export_snapshot()}
    )
    assert merged["bucket_skew"] == {"h": 1}
    # the first replica's ladder survives untouched — never re-binned
    assert list(merged["histograms"]["h"]["buckets"])[:2] == [1.0, 2.0]
    assert merged["histograms"]["h"]["count"] == 1


def test_fleet_federation_and_metrics_endpoint(fleet):
    servers, router = fleet
    name = "fleet_plane_test.unique"
    metrics.registry().counter(name).inc(5)
    plane = router.observability()
    fed = plane.federate(force=True)
    assert fed["errors"] == {}
    assert fed["replicas"] == ["r1", "r2", "r3"]
    # in-process replicas share one registry, so the merged counter is
    # the exact 3x sum of three identical snapshots — federation added
    # nothing and lost nothing
    assert fed["merged"]["counters"][name] == 15
    # TTL cache: an immediate re-pull returns the same payload object
    assert plane.federate() is fed
    # /metrics/fleet renders the merged view through the live router
    code, ctype, body = obs.handle("/metrics/fleet")
    assert code == 200 and "0.0.4" in ctype
    assert b"fleet_plane_test" in body
    code, ctype, body = obs.handle(
        "/metrics/fleet", accept="application/openmetrics-text"
    )
    assert code == 200 and ctype.startswith("application/openmetrics-text")
    assert body.endswith(b"# EOF\n")


# ---------------------------------------------------------------------------
# cross-replica trace stitching
# ---------------------------------------------------------------------------


def test_scatter_stitches_one_tree_per_query(fleet, oracle, monkeypatch):
    """The acceptance gate: one scattered query -> exactly one stitched
    span tree whose replica-subtree count equals the surviving
    owner-group count, with spans from >= 2 replicas."""
    servers, router = fleet
    plane = router.observability()
    captured = []
    monkeypatch.setattr(
        plane, "note_scatter",
        lambda tid, owners: captured.append((tid, list(owners))),
    )
    with config.TRACE_ENABLED.scoped("true"), \
            config.FLEET_STITCH.scoped("true"):
        assert router.count("t", WIDE) == oracle.count("t", WIDE)
    assert len(captured) == 1, captured
    tid, owners = captured[0]
    assert tid is not None and owners
    rec = plane.stitch_now(tid, owners)
    assert rec is not None and rec["stitched"] is True
    assert rec["trace_id"] == tid
    # every surviving owner-group call produced exactly one replica
    # subtree under the router span that made it
    assert rec["subtrees"] == len(owners)
    assert len(rec["replicas"]) >= 2

    def _subtree_roots(node, out):
        for c in node.get("children") or ():
            if (c.get("attrs") or {}).get("parent_span"):
                out.append(c)
            _subtree_roots(c, out)
        return out

    roots = _subtree_roots(rec["tree"], [])
    assert len(roots) == len(owners)
    assert all((r.get("attrs") or {}).get("replica") for r in roots)
    assert not any(
        (r.get("attrs") or {}).get("stitch_orphan") for r in roots
    )
    # retained for the exact-match debug lookup
    code, _, body = obs.handle(f"/debug/queries?trace={tid}")
    assert code == 200
    got = json.loads(body)
    assert got["stitched"] is True and got["subtrees"] == len(owners)
    # stitching is idempotent: a re-stitch grafts the same subtree set
    rec2 = plane.stitch_now(tid, owners)
    assert rec2["subtrees"] == len(owners)


def test_trace_lookup_unknown_id_is_404():
    code, _, body = obs.handle("/debug/queries?trace=feedfacecafebeef")
    assert code == 404
    assert b"not retained" in body


def test_traced_local_query_lookup_falls_back_to_retention():
    ds = GeoDataset(n_shards=1)
    ds.create_schema("l", "*geom:Point")
    ds.insert("l", {"geom": [(0.0, 0.0), (1.0, 1.0)]})
    ds.flush("l")
    with config.TRACE_ENABLED.scoped("true"):
        ds.count("l", "BBOX(geom, -1, -1, 2, 2)")
        tid = tracing.last_trace().trace_id
    code, _, body = obs.handle(f"/debug/queries?trace={tid}")
    assert code == 200
    got = json.loads(body)
    assert got["trace_id"] == tid
    assert got["tree"]["name"] == "count"


# ---------------------------------------------------------------------------
# cell-heat telemetry
# ---------------------------------------------------------------------------


def test_heat_merge_adds_and_splits_by_replica():
    snaps = {
        "r1": {"t": [
            {"cell": "z5:10", "hits": 2, "misses": 1, "device_ms": 3.0,
             "touches": 3},
        ]},
        "r2": {"t": [
            {"cell": "z5:10", "hits": 0, "misses": 4, "device_ms": 8.0,
             "touches": 4},
            {"cell": "z5:11", "hits": 1, "misses": 0, "device_ms": 0.0,
             "touches": 1},
        ]},
    }
    merged = heat.merge_snapshots(snaps, top=10)
    rows = {r["cell"]: r for r in merged["t"]}
    hot = rows["z5:10"]
    assert (hot["hits"], hot["misses"], hot["touches"]) == (2, 5, 7)
    assert hot["device_ms"] == pytest.approx(11.0)
    assert hot["replicas"] == {"r1": 3, "r2": 4}
    # hottest-first ordering by touches
    assert merged["t"][0]["cell"] == "z5:10"


def test_cache_decomposition_feeds_heat_and_debug_endpoint(rng):
    heat.reset()
    ds = GeoDataset(n_shards=2)
    ds.create_schema("pts", "type:String,dtg:Date,*geom:Point")
    n = 3000
    lo = np.datetime64("2020-01-01", "ms").astype(np.int64)
    ds.insert("pts", {
        "geom__x": rng.uniform(-35, 35, n),
        "geom__y": rng.uniform(-35, 35, n),
        "dtg": (lo + rng.integers(0, 10**9, n)).astype("datetime64[ms]"),
        "type": rng.choice(["bus", "car"], n),
    }, fids=np.arange(n).astype(str))
    ds.flush("pts")
    q1 = "BBOX(geom, -22.5, -22.5, 22.5, 22.5) AND type = 'bus'"
    q2 = "BBOX(geom, -18.0, -22.5, 34.9, 22.5) AND type = 'bus'"
    with config.CACHE_ENABLED.scoped("true"):
        ds.count("pts", q1)   # cold decomposition: misses with device_ms
        ds.count("pts", q2)   # overlap: interior cells hit
    snap = heat.snapshot()
    assert snap.get("pts"), "decomposition recorded no heat"
    assert all(r["cell"].startswith("z") for r in snap["pts"])
    assert sum(r["misses"] for r in snap["pts"]) > 0
    assert sum(r["hits"] for r in snap["pts"]) > 0
    assert sum(r["device_ms"] for r in snap["pts"]) > 0
    code, _, body = obs.handle("/debug/heat?top=16")
    assert code == 200
    got = json.loads(body)
    assert got["local"]["pts"]
    assert len(got["local"]["pts"]) <= 16
    heat.reset()


def test_heat_table_bounded_evicts_coldest():
    t = heat.HeatTable(max_cells=2)
    t.record("s", 5, "1", hit=1)
    t.record("s", 5, "1", hit=1)   # touches=2: the hot row
    t.record("s", 5, "2", miss=1)  # touches=1: the cold row
    t.record("s", 5, "3", hit=1)   # insert past cap evicts z5:2
    cells = {r["cell"] for r in t.snapshot()["s"]}
    assert cells == {"z5:1", "z5:3"}


def test_fleet_heat_merges_replica_tables(fleet):
    servers, router = fleet
    heat.reset()
    heat.record("t", 6, "42", miss=1, device_ms=2.0)
    plane = router.observability()
    with config.FLEET_OBS_TTL_MS.scoped("0"):
        out = plane.fleet_heat(top=8)
    assert out["errors"] == {}
    assert out["replicas"] == ["r1", "r2", "r3"]
    rows = out["schemas"]["t"]
    row = next(r for r in rows if r["cell"] == "z6:42")
    # one shared in-process table exported by three replicas: the merge
    # adds the three identical snapshots and splits touches per replica
    assert row["misses"] == 3
    assert set(row["replicas"]) == {"r1", "r2", "r3"}
    heat.reset()


# ---------------------------------------------------------------------------
# fleet health composition (the satellite: soft/hard combos)
# ---------------------------------------------------------------------------


def test_fleet_health_soft_hard_composition(fleet, monkeypatch):
    servers, router = fleet
    plane = router.observability()
    with config.FLEET_OBS_TTL_MS.scoped("0"):
        h = plane.fleet_health()
        assert h["status"] == "ok" and h["soft"] is False
        code, _, _ = obs.handle("/healthz/fleet")
        assert code == 200

        # journal lag on the members: SOFT — acked-but-unsynced frames
        # are a durability watch item, not a capacity loss
        monkeypatch.setattr(obs, "_journal_lag", lambda: {"/data": 3})
        h = plane.fleet_health()
        assert h["status"] == "degraded" and h["soft"] is True
        assert any("journal lag" in r for r in h["reasons"])
        code, _, body = obs.handle("/healthz/fleet")
        assert code == 200 and json.loads(body)["soft"] is True
        monkeypatch.setattr(obs, "_journal_lag", lambda: {})

        # an open fs.root breaker turns each member's LOCAL health HARD
        # (503 on the replica's own /healthz) — but the fleet stays SOFT
        # while the registry says capacity remains
        fsbr = resilience.breaker("fs.root")
        for _ in range(50):
            fsbr.record_failure()
        h = plane.fleet_health()
        assert h["status"] == "degraded" and h["soft"] is True
        assert any("local health" in r for r in h["reasons"])
        code, _, _ = obs.handle("/healthz/fleet")
        assert code == 200
        resilience.reset_breakers()

        # one cordoned member: SOFT (capacity remains)
        router.registry.cordon("r2", "test")
        h = plane.fleet_health()
        assert h["status"] == "degraded" and h["soft"] is True
        assert any("cordon" in r for r in h["reasons"])
        assert not any(r.startswith("hard:") for r in h["reasons"])

        # an open replica breaker ON TOP of the cordon: still SOFT
        # while at least one member stays usable
        br = resilience.breaker("replica:r3")
        for _ in range(50):
            br.record_failure()
        with pytest.raises(resilience.CircuitOpenError):
            br.allow()
        h = plane.fleet_health()
        assert h["soft"] is True
        assert h["summary"]["usable"] >= 1
        assert any("breaker" in r or "broken" in str(h["summary"])
                   for r in h["reasons"])
        code, _, _ = obs.handle("/healthz/fleet")
        assert code == 200

        # zero usable members: HARD, 503
        router.registry.cordon("r1", "test")
        router.registry.cordon("r3", "test")
        h = plane.fleet_health()
        assert h["status"] == "degraded" and h["soft"] is False
        assert any(r.startswith("hard:") for r in h["reasons"])
        code, _, _ = obs.handle("/healthz/fleet")
        assert code == 503

        # healing restores ok
        resilience.reset_breakers()
        for rid in ("r1", "r2", "r3"):
            router.registry.uncordon(rid)
        h = plane.fleet_health()
        assert h["status"] == "ok"


def test_fleet_endpoints_404_without_router():
    # no live router in this process state: the fleet routes answer 404,
    # never 500 (the local /metrics + /healthz stay untouched)
    code, _, _ = obs.handle("/metrics/fleet")
    assert code in (200, 404)  # 200 only if another test's router leaked


# ---------------------------------------------------------------------------
# replica anomaly watchdog
# ---------------------------------------------------------------------------


def test_anomaly_report_flags_slow_replica(fleet):
    servers, router = fleet
    reg = router.registry
    with config.FLEET_ANOMALY_FACTOR.scoped("2"):
        for _ in range(16):
            reg.record_latency("r1", 0.01, "count")
            reg.record_latency("r2", 0.01, "count")
            reg.record_latency("r3", 0.08, "count")
        flagged = reg.anomaly_report()
        assert "r3" in flagged and "count" in flagged["r3"]
        assert flagged["r3"]["count"] >= 2.0
        assert "r1" not in flagged and "r2" not in flagged
        # the worst-ratio gauge published for the outlier
        g = metrics.registry().gauge(f"{metrics.FLEET_ANOMALY_PREFIX}.r3")
        assert g.value >= 2.0
        # surfaces as a SOFT health reason — observation, never a cordon
        with config.FLEET_OBS_TTL_MS.scoped("0"):
            h = router.observability().fleet_health()
        assert any("anomaly" in r for r in h["reasons"])
        assert h["soft"] is True
        assert router.registry.state("r3") not in ("cordoned", "broken")


# ---------------------------------------------------------------------------
# join pushdown residency cache (docs/JOIN.md §11)
# ---------------------------------------------------------------------------


class _FakeFile:
    def __init__(self):
        self.reads = 0

    def read_array(self, ref):
        self.reads += 1
        return np.arange(int(ref["n"]), dtype=np.int64)

    def blob_nbytes(self, ref):
        return int(ref["b"])


def test_group_residency_cache_lru_hits_and_saved_bytes():
    from geomesa_tpu.lake.residency import GroupResidencyCache

    f = _FakeFile()
    ref = {"n": 4, "b": 100}          # 32 decoded bytes per entry
    c = GroupResidencyCache(budget_bytes=96)
    a1 = c.fetch("d", "c/x", 0, ref, f)
    a2 = c.fetch("d", "c/x", 0, ref, f)
    assert a2 is a1 and f.reads == 1
    assert c.hits == 1 and c.bytes_saved == 100
    assert not a1.flags.writeable   # shared chunks fail loudly on mutate
    for gi in (1, 2, 3):            # 4 x 32 decoded bytes > 96 budget
        c.fetch("d", "c/x", gi, ref, f)
    assert c.evictions >= 1
    c.fetch("d", "c/x", 0, ref, f)  # the evicted LRU group re-decodes
    assert f.reads == 5
    snap = c.snapshot()
    assert snap["hits"] == 1 and snap["bytes_saved"] == 100
    assert snap["held_bytes"] <= 96
    # "0" disables via from_config
    with config.JOIN_PUSHDOWN_RESIDENCY_MB.scoped("0"):
        assert GroupResidencyCache.from_config() is None
    assert GroupResidencyCache.from_config() is not None


def test_join_pushdown_residency_saves_bytes_and_stays_exact(tmp_path):
    """Cross-chunk residency: with small chunks over clustered data the
    boundary row groups re-survive pruning in adjacent chunks — the
    cache serves the re-decode (hits > 0, saved bytes counted in
    stats.pushdown and the counters) and the total stays bit-identical
    to a residency-disabled run."""
    import contextlib

    from geomesa_tpu.api.dataset import Query
    from geomesa_tpu.filter.ecql import parse_iso_ms
    from geomesa_tpu.index.partitioned import PartitionedFeatureStore

    with contextlib.ExitStack() as stack:
        stack.enter_context(config.LAKE_ENABLED.scoped("true"))
        stack.enter_context(config.LAKE_ROWGROUP_ROWS.scoped("512"))
        ds = GeoDataset(n_shards=2)
        ds.create_schema(
            "t", "name:String,dtg:Date,*geom:Point;geomesa.partition='time'")
        st = ds._store("t")
        assert isinstance(st, PartitionedFeatureStore)
        st._spill_dir = str(tmp_path / "lake")
        rng = np.random.default_rng(44)
        n = 9000
        cx = rng.uniform(-110, -80, 5)
        cy = rng.uniform(30, 45, 5)
        k = rng.integers(0, 5, n)
        ds.insert("t", {
            "name": [f"r{i % 9}" for i in range(n)],
            "dtg": rng.integers(parse_iso_ms("2020-01-01"),
                                parse_iso_ms("2020-02-01"),
                                n).astype("datetime64[ms]"),
            "geom__x": np.clip(cx[k] + rng.normal(0, 0.3, n), -115, -75),
            "geom__y": np.clip(cy[k] + rng.normal(0, 0.3, n), 25, 50),
        })
        ds.flush()
        st.spill_all()
    ds.create_schema("pts", "name:String,*geom:Point")
    k = rng.integers(0, 3, 400)
    ds.insert("pts", {
        "name": ["p"] * 400,
        "geom": list(zip(
            np.clip(cx[k] + rng.normal(0, 0.2, 400), -115, -75),
            np.clip(cy[k] + rng.normal(0, 0.2, 400), 25, 50),
        )),
    })
    ds.flush()

    hits_ctr = metrics.registry().counter(
        metrics.JOIN_PUSHDOWN_RESIDENCY_HITS)
    bytes_ctr = metrics.registry().counter(
        metrics.JOIN_PUSHDOWN_RESIDENCY_BYTES)
    h0, b0 = hits_ctr.value, bytes_ctr.value
    with config.JOIN_PUSHDOWN_CELLS.scoped("4"):
        _, _, _, _, total, stats = ds._join_pushdown_count(
            "pts", "t", "dwithin", 0.1, None, None, Query(), Query(),
            None, False)
        pd = stats.pushdown
        assert pd["chunks"] > 1, pd
        assert pd["residency_hits"] > 0, pd
        assert pd["bytes_saved_residency"] > 0, pd
        assert hits_ctr.value - h0 == pd["residency_hits"]
        assert bytes_ctr.value - b0 == pd["bytes_saved_residency"]
        with config.JOIN_PUSHDOWN_RESIDENCY_MB.scoped("0"):
            _, _, _, _, total_off, stats_off = ds._join_pushdown_count(
                "pts", "t", "dwithin", 0.1, None, None, Query(), Query(),
                None, False)
        assert stats_off.pushdown["residency_hits"] == 0
        assert stats_off.pushdown["bytes_saved_residency"] == 0
    # bit-identical with the cache on, off — and against the full join
    assert total_off == total
    assert ds.join("pts", "t", predicate="dwithin",
                   distance=0.1).count == total
