"""GeoTools DataStore module (jvm/datastore) wire + SPI contract.

No JDK ships in this image, so the Java module is validated from the
other side of the wire: (1) replay the exact HTTP lifecycle Smoke.java
performs against a live server, (2) pin every endpoint template in
TpuRestClient.java to web.py's router, (3) check the SPI registration
and DataStore method surface, (4) compile with javac when available.

Reference parity: GeoMesaDataStore.scala:49 (DataStore shape), the
META-INF/services registration in geomesa-accumulo-datastore.
"""

import json
import re
import shutil
import subprocess
import urllib.error
import urllib.parse
import urllib.request
from pathlib import Path

import numpy as np
import pytest

from geomesa_tpu import GeoDataset

JVM = Path(__file__).resolve().parent.parent / "jvm" / "datastore"
SRC = JVM / "src/main/java/org/locationtech/geomesa/tpu/geotools"


def _req(base, path, method="GET", body=None):
    data = body.encode() if isinstance(body, str) else body
    headers = {"Content-Type": "application/json"} if data else {}
    req = urllib.request.Request(base + path, data=data, method=method,
                                 headers=headers)
    try:
        with urllib.request.urlopen(req) as r:
            return json.loads(r.read().decode()), r.status
    except urllib.error.HTTPError as e:
        return json.loads(e.read().decode()), e.code


@pytest.fixture()
def rest_base():
    from geomesa_tpu import web

    ds = GeoDataset(n_shards=1)
    srv = web.serve(ds, "127.0.0.1", 0, background=True)
    yield f"http://127.0.0.1:{srv.server_address[1]}"
    srv.shutdown()


def test_smoke_lifecycle_over_rest(rest_base):
    """The exact request sequence Smoke.java performs, same assertions:
    createSchema -> append 10 -> count==10 -> bounds==[0,9]x[1,1] ->
    filtered read 5 (all age>4, geometries present) -> removeSchema."""
    base = rest_base
    name = "smoke_py"
    _, code = _req(base, "/api/schemas", "POST", json.dumps({
        "name": name,
        "spec": "name:String,age:Integer,dtg:Date,*geom:Point:srid=4326",
    }))
    assert code == 201
    desc, _ = _req(base, f"/api/schemas/{name}")
    assert "age:Integer" in desc["spec"] and "*geom:Point" in desc["spec"]
    # the writer's close(): one FeatureCollection POST
    fc = {"type": "FeatureCollection", "features": [
        {"type": "Feature", "id": f"{name}-{i}",
         "geometry": {"type": "Point", "coordinates": [float(i), 1.0]},
         "properties": {"name": "even" if i % 2 == 0 else "odd",
                        "age": i, "dtg": "2020-01-05T00:00:00"}}
        for i in range(10)
    ]}
    body, code = _req(base, f"/api/schemas/{name}/features", "POST",
                      json.dumps(fc))
    assert code == 201 and body["inserted"] == 10
    got, _ = _req(base, f"/api/schemas/{name}/count?cql=INCLUDE")
    assert got["count"] == 10
    got, _ = _req(base, f"/api/schemas/{name}/bounds")
    assert got == [0.0, 1.0, 9.0, 1.0]
    cql = urllib.parse.quote("age > 4 AND BBOX(geom, -1, 0, 20, 2)")
    got, _ = _req(base, f"/api/schemas/{name}/features?cql={cql}")
    assert len(got["features"]) == 5
    for f in got["features"]:
        assert f["properties"]["age"] > 4
        assert f["geometry"]["type"] == "Point"
    _, code = _req(base, f"/api/schemas/{name}", "DELETE")
    assert code == 200
    got, _ = _req(base, "/api/schemas")
    assert name not in got


def test_rest_client_endpoints_exist_in_router():
    """Every endpoint template in TpuRestClient.java must resolve in
    web.py's router — the Java transport cannot drift silently."""
    client_src = (SRC / "TpuRestClient.java").read_text()
    web_src = Path("geomesa_tpu/web.py").read_text()
    # paths the client constructs (string literals up to the first ?)
    paths = set(re.findall(r'"(/api/[a-z/{}.]*?)[?"]', client_src))
    assert {"/api/version", "/api/schemas", "/api/schemas/"} <= paths
    # every distinct trailing operation the client uses is routed
    for op in ("count", "bounds", "features"):
        assert f'"/{op}' in client_src or f"/{op}?" in client_src
        assert f'"{op}"' in web_src, f"web.py does not route {op!r}"
    # the write surface exists in the router
    assert "def do_POST" in web_src and "def do_DELETE" in web_src \
        and "def do_PATCH" in web_src
    # methods the client sends are exactly the ones the router handles
    methods = set(re.findall(r'send\(\s*"(\w+)"', client_src))
    assert methods == {"GET", "POST", "DELETE", "PATCH"}


def test_spi_registration_and_shape():
    """META-INF/services names the factory; the factory and store declare
    the full SPI / DataStore method surface (GeoMesaDataStore.scala:49)."""
    for svc in ("org.geotools.api.data.DataStoreFactorySpi",
                "org.geotools.data.DataStoreFactorySpi"):
        reg = (JVM / "src/main/resources/META-INF/services" / svc).read_text()
        assert reg.strip() == (
            "org.locationtech.geomesa.tpu.geotools.GeoMesaTpuDataStoreFactory"
        )
    factory = (SRC / "GeoMesaTpuDataStoreFactory.java").read_text()
    assert "implements DataStoreFactorySpi" in factory
    for m in ("createDataStore", "createNewDataStore", "getDisplayName",
              "getDescription", "getParametersInfo", "canProcess",
              "isAvailable"):
        assert re.search(rf"\b{m}\s*\(", factory), f"factory missing {m}"
    store = (SRC / "GeoMesaTpuDataStore.java").read_text()
    assert "implements DataStore" in store
    for m in ("createSchema", "updateSchema", "removeSchema",
              "getTypeNames", "getNames", "getSchema", "getFeatureSource",
              "getFeatureReader", "getFeatureWriter",
              "getFeatureWriterAppend", "getLockingManager", "getInfo",
              "dispose"):
        assert re.search(rf"\b{m}\s*\(", store), f"store missing {m}"
    # the mock interface tree declares the same members the impls override
    ds_iface = (JVM / "geotools-mock/org/geotools/api/data/DataStore.java"
                ).read_text()
    for m in ("getFeatureReader", "getFeatureWriterAppend", "getTypeNames"):
        assert m in ds_iface


def test_javac_compiles_module_when_available():
    """Full compile of mock + module + smoke wherever a JDK exists."""
    javac = shutil.which("javac")
    if javac is None:
        pytest.skip("no javac in this image (validated via wire contract)")
    out = JVM / "out"
    srcs = [str(p) for p in JVM.rglob("*.java") if "out" not in p.parts]
    res = subprocess.run([javac, "-d", str(out)] + srcs,
                         capture_output=True, text=True, timeout=300)
    assert res.returncode == 0, res.stderr


def test_schema_update_and_index_over_rest(rest_base):
    """The Java DataStore's updateSchema + index lifecycle transport."""
    base = rest_base
    _req(base, "/api/schemas", "POST", json.dumps(
        {"name": "u", "spec": "v:Integer,*geom:Point"}))
    fc = {"type": "FeatureCollection", "features": [
        {"type": "Feature", "id": f"f{i}",
         "geometry": {"type": "Point", "coordinates": [float(i), 0.0]},
         "properties": {"v": i}} for i in range(6)]}
    _req(base, "/api/schemas/u/features", "POST", json.dumps(fc))
    # PATCH appends attributes in place
    body, code = _req(base, "/api/schemas/u", "PATCH",
                      json.dumps({"add_spec": "tag:String,score:Double"}))
    assert code == 200 and "score:Double" in body["spec"]
    # index lifecycle
    body, code = _req(base, "/api/schemas/u/indices", "POST",
                      json.dumps({"attribute": "v"}))
    assert code == 201 and body["index"] == "attr:v"
    desc, _ = _req(base, "/api/schemas/u")
    assert "attr:v" in desc["indices"]
    body, code = _req(base, "/api/schemas/u/indices/v", "DELETE")
    assert code == 200
    desc, _ = _req(base, "/api/schemas/u")
    assert "attr:v" not in desc["indices"]
    # errors: unknown schema 404, bad body 400
    _, code = _req(base, "/api/schemas/nope/indices", "POST",
                   json.dumps({"attribute": "v"}))
    assert code == 404
    _, code = _req(base, "/api/schemas/u/indices", "POST", "{}")
    assert code == 400
    _, code = _req(base, "/api/schemas/u/indices/nosuch", "DELETE")
    assert code == 404
