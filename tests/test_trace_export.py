"""Trace export + tail-based sampling tests (docs/OBSERVABILITY.md,
tracing_export.py).

The contract under test:

* finished traces stream to the configured sinks as OTLP-shaped JSON span
  batches (traceId/spanId/parent links, unix-nano times, attributes);
* the sampling decision happens at COMPLETION: slow, errored, degraded,
  shed, and recompile-carrying traces are ALWAYS kept; healthy traces keep
  at the seeded-deterministic geomesa.trace.sample.rate;
* the exporter NEVER blocks the query path: a wedged/failing sink plus a
  full bounded queue drops traces and counts them in trace.export.dropped
  while queries proceed at full speed;
* sink failures ride the resilience layer: retried per RetryPolicy,
  fenced by a named circuit breaker, driven deterministically through the
  geomesa.fault.injection registry.
"""

import json
import time

import numpy as np
import pytest

from geomesa_tpu import (
    GeoDataset, config, metrics, resilience, tracing, tracing_export,
)
from geomesa_tpu.filter.ecql import parse_iso_ms

BBOX = "BBOX(geom, -100, 30, -80, 45)"


def _mk_ds(n=4000, partitioned=False, seed=5):
    spec = "name:String,weight:Float,dtg:Date,*geom:Point"
    if partitioned:
        spec += ";geomesa.partition='time'"
    ds = GeoDataset(n_shards=2)
    ds.create_schema("t", spec)
    rng = np.random.default_rng(seed)
    lo, hi = parse_iso_ms("2020-01-01"), parse_iso_ms("2020-03-01")
    ds.insert("t", {
        "name": rng.choice(["a", "b"], n),
        "weight": rng.uniform(0, 1, n).astype(np.float32),
        "geom__x": rng.uniform(-120, -70, n),
        "geom__y": rng.uniform(25, 50, n),
        "dtg": rng.integers(lo, hi, n).astype("datetime64[ms]"),
    }, fids=np.arange(n).astype(str))
    ds.flush("t")
    return ds


@pytest.fixture(autouse=True)
def _isolated_exporter():
    tracing_export.reset()
    resilience.reset_breakers()
    yield
    tracing_export.reset()
    resilience.reset_breakers()


def _ctr(name):
    return metrics.registry().counter(name).value


def _batches(path):
    return [json.loads(ln) for ln in open(path).read().splitlines()]


def _spans(batch):
    return batch["resourceSpans"][0]["scopeSpans"][0]["spans"]


def _mk_trace(name="count", trace_id=None, children=("plan",)):
    """A synthetic finished trace (no dataset machinery)."""
    with config.TRACE_ENABLED.scoped("true"):
        root = tracing.start(name, trace_id=trace_id, schema="t")
        with root:
            for c in children:
                with tracing.span(c):
                    pass
        return root.trace


# ---------------------------------------------------------------------------
# OTLP shape + file sink
# ---------------------------------------------------------------------------


def test_query_exports_otlp_batch_to_file_sink(tmp_path):
    path = tmp_path / "spans.jsonl"
    ds = _mk_ds()
    with config.TRACE_ENABLED.scoped("true"), \
            config.TRACE_EXPORT_PATH.scoped(str(path)):
        n = ds.count("t", BBOX)
        tracing_export.flush()
    assert n > 0
    batches = _batches(path)
    assert batches
    spans = _spans(batches[0])
    assert spans[0]["name"] == "count"
    root_id = spans[0]["spanId"]
    tid = spans[0]["traceId"]
    assert len(tid) == 32
    # every span carries the OTLP essentials and shares the trace id
    for s in spans:
        assert len(s["spanId"]) == 16
        assert s["traceId"] == tid
        assert int(s["endTimeUnixNano"]) >= int(s["startTimeUnixNano"])
    # children link to the root
    kids = [s for s in spans if s.get("parentSpanId") == root_id]
    assert kids, spans
    names = {s["name"] for s in spans}
    assert "plan" in names


def test_root_span_carries_cost_and_keep_attrs(tmp_path):
    path = tmp_path / "spans.jsonl"
    ds = _mk_ds()
    with config.TRACE_ENABLED.scoped("true"), \
            config.TRACE_EXPORT_PATH.scoped(str(path)):
        ds.count("t", BBOX)
        tracing_export.flush()
    root = _spans(_batches(path)[0])[0]
    attrs = {a["key"]: a["value"] for a in root["attributes"]}
    assert "geomesa.keep" in attrs
    # the device kernel dispatch attributed its time to the cost ledger
    assert any(k.startswith("geomesa.cost.device_ms") for k in attrs), attrs


# ---------------------------------------------------------------------------
# tail sampling: always-keep classes + seeded determinism
# ---------------------------------------------------------------------------


def test_always_keep_classes_ignore_sample_rate(tmp_path):
    path = tmp_path / "spans.jsonl"
    with config.TRACE_EXPORT_PATH.scoped(str(path)), \
            config.TRACE_SAMPLE_RATE.scoped("0.0"):
        # healthy -> sampled out at rate 0
        healthy = _mk_trace("count")
        assert not healthy.exported
        # slow
        with config.TRACE_SLOW_MS.scoped("0"):
            slow = _mk_trace("count")
        assert slow.exported
        # errored
        err = _mk_trace("count")
        err.error = "ValueError"
        err.exported = False
        assert tracing_export.offer(err)
        # degraded
        deg = _mk_trace("count")
        deg.degraded = True
        deg.exported = False
        assert tracing_export.offer(deg)
        # shed
        shed = _mk_trace("count")
        shed.shed = True
        shed.exported = False
        assert tracing_export.offer(shed)
        # recompile-carrying
        rec = _mk_trace("count")
        rec.recompiles = 2
        rec.exported = False
        assert tracing_export.offer(rec)
        tracing_export.flush()
    reasons = []
    for b in _batches(path):
        for s in _spans(b):
            for a in s.get("attributes", []):
                if a["key"] == "geomesa.keep":
                    reasons.append(a["value"]["stringValue"])
    assert set(reasons) >= {"slow", "error", "degraded", "shed",
                            "recompile"}


def test_shed_and_error_flags_set_by_span_exit():
    from geomesa_tpu.resilience import DeadlineShedError

    with config.TRACE_ENABLED.scoped("true"):
        root = tracing.start("count", schema="t")
        with pytest.raises(DeadlineShedError):
            with root:
                raise DeadlineShedError("budget gone")
        assert root.trace.shed
        assert root.trace.error == "DeadlineShedError"

        root2 = tracing.start("count", schema="t")
        with pytest.raises(ValueError):
            with root2:
                raise ValueError("boom")
        assert root2.trace.error == "ValueError"
        assert not root2.trace.shed


def test_degraded_partition_marks_trace(tmp_path):
    ds = _mk_ds(20_000, partitioned=True)
    path = tmp_path / "spans.jsonl"
    with config.TRACE_ENABLED.scoped("true"), \
            config.TRACE_EXPORT_PATH.scoped(str(path)), \
            config.TRACE_SAMPLE_RATE.scoped("0.0"), \
            config.FAULT_INJECTION.scoped("true"), \
            resilience.allow_partial():
        with resilience.inject_faults(seed=7) as inj:
            inj.fail("exec.partition.scan", times=1)
            n = ds.count("t", BBOX)
    assert n > 0
    tr = tracing.last_trace()
    assert tr.degraded
    # degraded is an always-keep class even at rate 0
    assert tr.exported


def test_seeded_sampling_is_deterministic():
    ids = [f"{i:016x}" for i in range(200)]
    with config.TRACE_SAMPLE_RATE.scoped("0.3"), \
            config.TRACE_SAMPLE_SEED.scoped("42"):
        kept_a = {i for i in ids if tracing_export.sampled_in(i)}
        kept_b = {i for i in ids if tracing_export.sampled_in(i)}
    assert kept_a == kept_b  # same seed -> identical keep set
    assert 0 < len(kept_a) < len(ids)  # rate actually bites
    with config.TRACE_SAMPLE_RATE.scoped("0.3"), \
            config.TRACE_SAMPLE_SEED.scoped("43"):
        kept_c = {i for i in ids if tracing_export.sampled_in(i)}
    assert kept_c != kept_a  # a different seed picks a different set
    with config.TRACE_SAMPLE_RATE.scoped("1.0"):
        assert all(tracing_export.sampled_in(i) for i in ids)
    with config.TRACE_SAMPLE_RATE.scoped("0.0"):
        assert not any(tracing_export.sampled_in(i) for i in ids)


def test_sampled_out_traces_counted(tmp_path):
    path = tmp_path / "spans.jsonl"
    before = _ctr(metrics.TRACE_EXPORT_SAMPLED)
    with config.TRACE_EXPORT_PATH.scoped(str(path)), \
            config.TRACE_SAMPLE_RATE.scoped("0.0"):
        for _ in range(5):
            _mk_trace("count")
    assert _ctr(metrics.TRACE_EXPORT_SAMPLED) - before == 5
    assert not path.exists()


# ---------------------------------------------------------------------------
# non-blocking contract: wedged sink -> drops counted, queries unharmed
# ---------------------------------------------------------------------------


def test_flusher_drains_bursts_larger_than_one_batch(tmp_path):
    """A burst beyond geomesa.trace.export.batch (64) must fully drain on
    the background flusher without waiting for the next offer."""
    path = tmp_path / "spans.jsonl"
    with config.TRACE_EXPORT_PATH.scoped(str(path)):
        for i in range(70):
            _mk_trace("count", trace_id=f"{i:016x}")
        ex = tracing_export.exporter()
        for _ in range(400):
            if not ex._buf:
                break
            time.sleep(0.01)
        assert not ex._buf, f"{len(ex._buf)} traces stranded in the buffer"
        # give the in-flight write (dequeued, mid-sink) a moment to land
        ex.flush()
    batches = _batches(path)
    assert len(batches) >= 2  # 70 traces > one 64-trace batch
    roots = [s for b in batches for s in _spans(b)
             if "parentSpanId" not in s]
    assert len(roots) == 70


def _sync_exporter():
    """Install a flusher-less exporter: flush() is the only drain, so the
    sink path runs on the CALLING thread where scoped config (retry
    attempts, breaker threshold) is visible — deterministic chaos tests."""
    tracing_export.reset()
    tracing_export._exporter = tracing_export.TraceExporter(autoflush=False)
    return tracing_export._exporter


def test_wedged_sink_drops_overflow_and_never_blocks(tmp_path):
    path = tmp_path / "spans.jsonl"
    drop0 = _ctr(metrics.TRACE_EXPORT_DROPPED)
    with config.TRACE_EXPORT_PATH.scoped(str(path)), \
            config.TRACE_EXPORT_QUEUE.scoped("2"), \
            config.FAULT_INJECTION.scoped("true"):
        with resilience.inject_faults(seed=3) as inj:
            # every sink write stalls then fails: wedge the REAL
            # background flusher on one trace first...
            inj.fail(tracing_export.SINK_FAULT_POINT, times=None,
                     delay_s=0.2)
            _mk_trace("count")
            for _ in range(200):
                if inj.fired:
                    break
                time.sleep(0.005)
            assert inj.fired, "flusher never reached the wedged sink"
            # ...then hammer offers while it is stuck inside the write:
            # the 2-deep queue fills, the rest drop instantly
            t0 = time.perf_counter()
            for _ in range(12):
                _mk_trace("count")
            offered_s = time.perf_counter() - t0
            # the query/offer path never waits on the sink: 12 traces
            # offered in far less time than ONE wedged sink write
            assert offered_s < 0.2, f"offer path blocked ({offered_s:.3f}s)"
            dropped = _ctr(metrics.TRACE_EXPORT_DROPPED) - drop0
            assert dropped >= 8, f"expected overflow drops, got {dropped}"
        tracing_export.reset()  # discard the wedged queue


def test_sink_failures_retry_then_succeed(tmp_path):
    _sync_exporter()
    path = tmp_path / "spans.jsonl"
    fail0 = _ctr(metrics.TRACE_EXPORT_FAILED)
    with config.TRACE_EXPORT_PATH.scoped(str(path)), \
            config.RETRY_BASE_MS.scoped("1"), \
            config.FAULT_INJECTION.scoped("true"):
        with resilience.inject_faults(seed=3) as inj:
            # two injected failures < the default 3 attempts: the batch
            # must land after retries with nothing counted failed
            inj.fail(tracing_export.SINK_FAULT_POINT, times=2)
            _mk_trace("count")
            tracing_export.flush()
            assert len(inj.fired) == 2
    assert _ctr(metrics.TRACE_EXPORT_FAILED) == fail0
    assert _batches(path), "batch lost despite retry budget"


def test_sink_breaker_opens_after_repeated_failures(tmp_path):
    _sync_exporter()
    path = tmp_path / "spans.jsonl"
    fail0 = _ctr(metrics.TRACE_EXPORT_FAILED)
    with config.TRACE_EXPORT_PATH.scoped(str(path)), \
            config.RETRY_ATTEMPTS.scoped("1"), \
            config.RETRY_BASE_MS.scoped("1"), \
            config.BREAKER_THRESHOLD.scoped("2"), \
            config.FAULT_INJECTION.scoped("true"):
        with resilience.inject_faults(seed=3) as inj:
            inj.fail(tracing_export.SINK_FAULT_POINT, times=None)
            for _ in range(4):
                _mk_trace("count")
                tracing_export.flush()
    assert resilience.breaker("trace.export.file").state == "open"
    failed = _ctr(metrics.TRACE_EXPORT_FAILED) - fail0
    assert failed == 4
    # once open, the sink is fenced: the injector's hit count stops
    # growing (failures 3 and 4 never reached the fault point)
    assert len(inj.fired) == 2, inj.fired


def test_late_slow_trace_still_exported(tmp_path):
    """A streamed trace sampled OUT at first completion becomes slow when
    a late child stretches the root — it must then export (always-keep)."""
    path = tmp_path / "spans.jsonl"
    with config.TRACE_ENABLED.scoped("true"), \
            config.TRACE_EXPORT_PATH.scoped(str(path)), \
            config.TRACE_SAMPLE_RATE.scoped("0.0"), \
            config.TRACE_SLOW_MS.scoped("5"):
        root = tracing.start("sidecar.do_get")
        with root:
            child = tracing.span("query_batches")
            child.t0 = time.perf_counter()
        assert not root.trace.exported  # fast + rate 0 -> sampled out
        time.sleep(0.02)
        child.finish()  # stretches the root past the slow threshold
        assert root.trace.exported
        tracing_export.flush()
    reasons = [a["value"]["stringValue"]
               for b in _batches(path) for s in _spans(b)
               for a in s.get("attributes", []) if a["key"] == "geomesa.keep"]
    assert "slow" in reasons
