"""Native runtime parity: every C++ entry point must agree bit-for-bit with
the NumPy/Python fallbacks (geomesa_tpu/native.py contract)."""

import numpy as np
import pytest

from geomesa_tpu import native
from geomesa_tpu.curves import zorder
from geomesa_tpu.curves.cover import zcover
from geomesa_tpu.io.bin_format import java_string_hash

needs_native = pytest.mark.skipif(
    not native.available(), reason="native library not built"
)


def test_native_builds():
    # the toolchain is part of the supported environment: the library must
    # build here even though the framework degrades gracefully without it
    assert native.available()


@needs_native
def test_interleave2_parity():
    rng = np.random.default_rng(1)
    x = rng.integers(0, 1 << 31, 10_000).astype(np.uint64)
    y = rng.integers(0, 1 << 31, 10_000).astype(np.uint64)
    np.testing.assert_array_equal(native.interleave2(x, y), zorder.interleave2(x, y))
    z = native.interleave2(x, y)
    nx, ny = native.deinterleave2(z)
    np.testing.assert_array_equal(nx, x)
    np.testing.assert_array_equal(ny, y)


@needs_native
def test_interleave3_parity():
    rng = np.random.default_rng(2)
    x = rng.integers(0, 1 << 21, 10_000).astype(np.uint64)
    y = rng.integers(0, 1 << 21, 10_000).astype(np.uint64)
    t = rng.integers(0, 1 << 21, 10_000).astype(np.uint64)
    np.testing.assert_array_equal(
        native.interleave3(x, y, t), zorder.interleave3(x, y, t)
    )
    z = native.interleave3(x, y, t)
    nx, ny, nt = native.deinterleave3(z)
    np.testing.assert_array_equal(nx, x)
    np.testing.assert_array_equal(ny, y)
    np.testing.assert_array_equal(nt, t)


@needs_native
@pytest.mark.parametrize("dims,bits", [(2, 31), (3, 21), (2, 12), (3, 8)])
def test_zcover_parity(dims, bits):
    rng = np.random.default_rng(dims * 100 + bits)
    top = (1 << bits) - 1
    for budget in (16, 200, 2000):
        for _ in range(20):
            lo = rng.integers(0, top, dims)
            hi = [int(v + rng.integers(0, top - v + 1)) for v in lo]
            want = zcover(list(lo), hi, bits, dims, budget)
            got = native.zcover(list(lo), hi, bits, dims, budget)
            assert got == want


@needs_native
def test_zcover_point_box():
    want = zcover([5, 5], [5, 5], 8, 2, 2000)
    got = native.zcover([5, 5], [5, 5], 8, 2, 2000)
    assert got == want
    assert len(got) == 1 and got[0].lo == got[0].hi


@needs_native
def test_java_hash_parity():
    vals = ["", "a", "track-123", "ünïcødé", "🚀astral", "x" * 500]
    got = native.java_hash(vals)
    want = np.array([java_string_hash(v) for v in vals], np.int32)
    np.testing.assert_array_equal(got, want)


@needs_native
def test_windows_u64_parity():
    rng = np.random.default_rng(7)
    keys = np.sort(rng.integers(0, 1 << 60, 5000).astype(np.uint64))
    lo = rng.integers(0, 1 << 60, 64).astype(np.uint64)
    hi = lo + rng.integers(0, 1 << 40, 64).astype(np.uint64)
    s, e = native.windows_u64(keys, lo, hi)
    np.testing.assert_array_equal(s, np.searchsorted(keys, lo, side="left"))
    np.testing.assert_array_equal(e, np.searchsorted(keys, hi, side="right"))


@needs_native
def test_bin_windows_parity():
    rng = np.random.default_rng(9)
    n = 4000
    bins_col = np.sort(rng.integers(100, 120, n).astype(np.int32))
    z_col = np.empty(n, np.uint64)
    # z sorted within each bin segment (the table's (bin, z) lexsort)
    for b in np.unique(bins_col):
        seg = bins_col == b
        z_col[seg] = np.sort(rng.integers(0, 1 << 50, int(seg.sum())).astype(np.uint64))
    bins = np.array([99, 103, 107, 119, 121], np.int32)
    zlo, zhi = 1 << 10, 1 << 49

    s, e = native.bin_windows(bins_col, z_col, bins, zlo, zhi)
    # oracle: the original python loop
    ws, we = [], []
    for b in bins.tolist():
        s0 = int(np.searchsorted(bins_col, b, side="left"))
        e0 = int(np.searchsorted(bins_col, b, side="right"))
        if e0 <= s0:
            continue
        seg = z_col[s0:e0]
        s2 = s0 + int(np.searchsorted(seg, np.uint64(zlo), side="left"))
        e2 = s0 + int(np.searchsorted(seg, np.uint64(zhi), side="right"))
        if e2 > s2:
            ws.append(s2)
            we.append(e2)
    np.testing.assert_array_equal(s, np.asarray(ws, np.int64))
    np.testing.assert_array_equal(e, np.asarray(we, np.int64))


def test_fallback_when_disabled(monkeypatch):
    """GEOMESA_NATIVE=0 must route everything through the NumPy paths."""
    monkeypatch.setattr(native, "_lib", None)
    monkeypatch.setattr(native, "_tried", True)
    assert not native.available()
    x = np.array([3, 9], np.uint64)
    y = np.array([5, 2], np.uint64)
    np.testing.assert_array_equal(native.interleave2(x, y), zorder.interleave2(x, y))
    assert native.zcover([0, 0], [3, 3], 4, 2) == zcover([0, 0], [3, 3], 4, 2)
    got = native.java_hash(["abc"])
    assert got[0] == java_string_hash("abc")
