"""Randomized differential tests for the FULL predicate surface —
legacy predicates (compare/BETWEEN/IN/LIKE/IS NULL/DURING) over mixed
attribute types, boolean-combined at random, counted against a numpy
oracle; plus random sorted+limited queries against a lexsort oracle.
The device kernels, window pushdown, f32 band machinery, refine pass,
and top-k selection must compose to exact semantics for every tree."""

pytestmark = __import__("pytest").mark.fuzz
import numpy as np
import pytest

from geomesa_tpu import GeoDataset
from geomesa_tpu.api.dataset import Query
from geomesa_tpu.filter.ecql import parse_iso_ms

N = 4_000
T0 = parse_iso_ms("2020-01-01")
T1 = parse_iso_ms("2020-02-01")
WORDS = ["alpha", "beta", "betamax", "Gamma", "delta%", "e'e", ""]


@pytest.fixture(scope="module")
def pfuzz():
    rng = np.random.default_rng(123)
    data = {
        "s": np.array([WORDS[i] for i in rng.integers(0, len(WORDS), N)],
                      dtype=object),
        "i": rng.integers(-50, 50, N).astype(np.int32),
        "l": rng.integers(-2**40, 2**40, N),
        "f": np.round(rng.uniform(-10, 10, N), 2),
        "bl": rng.random(N) < 0.5,
        "dtg": rng.integers(T0, T1, N).astype("datetime64[ms]"),
        "geom__x": rng.uniform(-20, 20, N),
        "geom__y": rng.uniform(-20, 20, N),
    }
    ds = GeoDataset(n_shards=2)
    ds.create_schema(
        "p", "s:String,i:Integer,l:Long,f:Double,bl:Boolean,dtg:Date,"
             "*geom:Point")
    ds.insert("p", data, fids=np.arange(N).astype(str))
    ds.flush()
    return ds, data


def _esc(v: str) -> str:
    return v.replace("'", "''")


def _leaf(rng, d):
    kind = rng.integers(0, 8)
    if kind == 0:  # numeric compare (int/float/long)
        p = ["i", "f", "l"][rng.integers(0, 3)]
        op = ["=", "<>", "<", "<=", ">", ">="][rng.integers(0, 6)]
        # draw from the data half the time so '=' hits sometimes
        v = (d[p][rng.integers(0, N)] if rng.random() < 0.5
             else np.round(rng.uniform(-60, 60), 2))
        npop = {"=": np.equal, "<>": np.not_equal, "<": np.less,
                "<=": np.less_equal, ">": np.greater,
                ">=": np.greater_equal}[op]
        return f"{p} {op} {v}", lambda dd, p=p, v=v, o=npop: o(
            dd[p].astype(np.float64) if p != "l" else dd[p], v)
    if kind == 1:  # string equality / ordering
        op = ["=", "<>", "<", ">="][rng.integers(0, 4)]
        v = WORDS[rng.integers(0, len(WORDS))]
        text = f"s {op} '{_esc(v)}'"

        def fn(dd, v=v, op=op):
            sv = dd["s"].astype(str)
            if op == "=":
                return sv == v
            if op == "<>":
                return sv != v
            return (sv < v) if op == "<" else (sv >= v)

        return text, fn
    if kind == 2:  # BETWEEN
        p = ["i", "f"][rng.integers(0, 2)]
        lo, hi = sorted(rng.uniform(-60, 60, 2).round(2))
        return (f"{p} BETWEEN {lo} AND {hi}",
                lambda dd, p=p, lo=lo, hi=hi:
                (dd[p] >= lo) & (dd[p] <= hi))
    if kind == 3:  # IN
        p = ["i", "s"][rng.integers(0, 2)]
        if p == "i":
            vals = rng.integers(-50, 50, 3)
            return (f"i IN ({', '.join(map(str, vals))})",
                    lambda dd, vals=tuple(vals): np.isin(dd["i"], vals))
        vals = [WORDS[j] for j in rng.integers(0, len(WORDS), 2)]
        quoted = ", ".join(f"'{_esc(v)}'" for v in vals)
        return (f"s IN ({quoted})",
                lambda dd, vals=tuple(vals): np.isin(
                    dd["s"].astype(str), vals))
    if kind == 4:  # LIKE / ILIKE
        pat, pre = ("beta%", "beta") if rng.random() < 0.5 else ("%a", "a")
        ci = rng.random() < 0.5
        kw = "ILIKE" if ci else "LIKE"

        def fn(dd, pre=pre, ci=ci, pat=pat):
            sv = dd["s"].astype(str)
            if ci:
                sv = np.char.lower(sv.astype("U"))
                pre_ = pre.lower()
            else:
                pre_ = pre
            if pat.endswith("%"):
                return np.char.startswith(sv.astype("U"), pre_)
            return np.char.endswith(sv.astype("U"), pre_)

        return f"s {kw} '{pat}'", fn
    if kind == 5:  # IS NULL / IS NOT NULL (empty string is NOT null)
        neg = rng.random() < 0.5
        text = f"s IS {'NOT ' if neg else ''}NULL"
        # this dataset has no null strings, only empties
        return text, lambda dd, neg=neg: np.full(N, neg)
    if kind == 6:  # temporal
        a, b = sorted(rng.integers(T0, T1, 2))
        ai = np.datetime64(int(a), "ms")
        bi = np.datetime64(int(b), "ms")
        form = rng.integers(0, 3)
        t = lambda dd: dd["dtg"].astype(np.int64)  # noqa: E731
        if form == 0:
            return (f"dtg DURING {ai}Z/{bi}Z",
                    lambda dd, a=a, b=b, t=t: (t(dd) >= a) & (t(dd) <= b))
        if form == 1:
            return (f"dtg BEFORE {ai}Z",
                    lambda dd, a=a, t=t: t(dd) < a)
        return (f"dtg AFTER {bi}Z",
                lambda dd, b=b, t=t: t(dd) > b)
    # boolean
    v = rng.random() < 0.5
    return (f"bl = {str(v).lower()}",
            lambda dd, v=v: dd["bl"] == v)


def _tree(rng, d, depth):
    if depth == 0 or rng.random() < 0.45:
        return _leaf(rng, d)
    k = rng.integers(0, 3)
    lt, lf = _tree(rng, d, depth - 1)
    if k == 2:
        return f"NOT ({lt})", lambda dd, lf=lf: ~lf(dd)
    rt, rf = _tree(rng, d, depth - 1)
    j = "AND" if k == 0 else "OR"
    op = np.logical_and if k == 0 else np.logical_or
    return (f"({lt}) {j} ({rt})",
            lambda dd, lf=lf, rf=rf, op=op: op(lf(dd), rf(dd)))


def test_random_predicate_trees_match_oracle(pfuzz):
    ds, data = pfuzz
    rng = np.random.default_rng(31)
    for case in range(150):
        text, fn = _tree(rng, data, 2)
        want = int(fn(data).sum())
        got = ds.count("p", text)
        assert got == want, f"case {case}: {text!r} -> {got}, oracle {want}"


def test_random_predicates_with_spatial_window(pfuzz):
    ds, data = pfuzz
    rng = np.random.default_rng(41)
    box = ((data["geom__x"] >= -10) & (data["geom__x"] <= 10)
           & (data["geom__y"] >= -10) & (data["geom__y"] <= 10))
    for case in range(60):
        text, fn = _tree(rng, data, 1)
        q = f"BBOX(geom, -10, -10, 10, 10) AND ({text})"
        want = int((box & fn(data)).sum())
        got = ds.count("p", q)
        assert got == want, f"case {case}: {q!r} -> {got}, oracle {want}"


def test_random_sorted_limited_queries(pfuzz):
    """Random sort specs (1-2 keys, numeric, both directions, assorted
    k) against a stable-lexsort oracle on values."""
    ds, data = pfuzz
    rng = np.random.default_rng(51)
    box = ((data["geom__x"] >= -10) & (data["geom__x"] <= 10)
           & (data["geom__y"] >= -10) & (data["geom__y"] <= 10))
    idx0 = np.nonzero(box)[0]
    for case in range(25):
        nkeys = int(rng.integers(1, 3))
        keys = list(rng.choice(["i", "f", "l"], nkeys, replace=False))
        descs = [bool(rng.random() < 0.5) for _ in keys]
        k = int(rng.choice([1, 3, 40, 500, 2500]))
        q = Query("BBOX(geom, -10, -10, 10, 10)",
                  sort_by=list(zip(keys, descs)), max_features=k)
        got = ds.query("p", q).batch
        cols = []
        for kk, dd in reversed(list(zip(keys, descs))):
            c = data[kk][idx0].astype(np.float64)
            cols.append(-c if dd else c)
        order = np.lexsort(tuple(cols))
        want_rows = idx0[order][:k]
        assert got.n == min(k, len(idx0))
        for kk in keys:
            assert np.array_equal(
                np.asarray(got.columns[kk], np.float64),
                data[kk][want_rows].astype(np.float64),
            ), f"case {case}: sort {list(zip(keys, descs))} k={k} on {kk}"


def test_float_literals_on_int_columns_exact(pfuzz):
    """Fuzz-found (r5): int(val) truncation corrupted =, <>, >= and
    negative bounds for non-integral literals on int columns."""
    ds, data = pfuzz
    i = data["i"]
    cases = {
        "i = 5.5": (i == 5.5), "i <> 5.5": (i != 5.5),
        "i >= 9.07": (i >= 9.07), "i > -9.07": (i > -9.07),
        "i <= -34.8": (i <= -34.8), "i < -0.5": (i < -0.5),
        "i BETWEEN -34.8 AND -9.07": ((i >= -34.8) & (i <= -9.07)),
        "i IN (5.5, 3)": np.isin(i, [3]),
        "NOT (i >= 9.07)": ~(i >= 9.07),
    }
    for q, m in cases.items():
        assert ds.count("p", q) == int(m.sum()), q


def test_out_of_range_int_literal_in_IN(pfuzz):
    """Review r5: a literal beyond int64 in IN must match nothing, not
    raise OverflowError."""
    ds, data = pfuzz
    assert ds.count("p", "l IN (100000000000000000000, 7)") == int(
        (data["l"] == 7).sum())
    assert ds.count("p", "l IN (100000000000000000000)") == 0
