"""Direct property tests for the radix pack-sort engine (index/packsort.py)
and its native counterparts (VERDICT r2 #10).

Oracles: numpy argsort/lexsort on the raw keys. Covered branches:
* ``to_ordered_u64`` order preservation for every supported dtype, including
  negative floats, NaN-free extremes, and int64 limits.
* quantized windows remain supersets under forced (coarse) shifts.
* ``fid_hash64`` width-independence and collision resolution via the IdIn
  exact-equality mask.
* LSM append with ``force_shift`` mismatch falls back to a full rebuild.
* native pack/unpack == pure-numpy pack path, bit for bit.
"""

import numpy as np
import pytest

from geomesa_tpu.index import packsort


RNG = np.random.default_rng(42)


# ---------------------------------------------------------------------------
# to_ordered_u64: order preservation per dtype
# ---------------------------------------------------------------------------

@pytest.mark.parametrize(
    "arr",
    [
        np.array([-(2**31), -1, 0, 1, 2**31 - 1], np.int32),
        np.array([0, 1, 2**32 - 1], np.uint32),
        np.array(
            [-(2**63), -(2**53) - 1, -1, 0, 1, 2**53 + 1, 2**63 - 1], np.int64
        ),
        np.array([0, 1, 2**63, 2**64 - 1], np.uint64),
        np.array(
            [-np.inf, -3.3e38, -1.0, -1e-45, 0.0, 1e-45, 1.0, 3.3e38, np.inf],
            np.float32,
        ),
        np.array(
            [-np.inf, -1.7e308, -1.0, -5e-324, -0.0, 0.0, 5e-324, 1.0, np.inf],
            np.float64,
        ),
        np.array([False, True]),
        np.array([-(2**15), -1, 0, 2**15 - 1], np.int16),
    ],
    ids=["i32", "u32", "i64", "u64", "f32", "f64", "bool", "i16"],
)
def test_to_ordered_u64_order_preserving(arr):
    u, bits = packsort.to_ordered_u64(arr)
    assert u.dtype == np.uint64
    # strictly increasing input -> strictly increasing mapped output, except
    # -0.0/0.0 which compare equal as floats and may map equal or ordered
    lt_in = arr[:-1] < arr[1:]
    le_out = u[:-1] <= u[1:]
    assert le_out.all()
    assert (u[:-1][lt_in] < u[1:][lt_in]).all()
    if bits < 64:
        assert int(u.max()) < (1 << bits)


@pytest.mark.parametrize("dtype", [np.int32, np.int64, np.float32, np.float64])
def test_to_ordered_u64_random_order_matches_argsort(dtype):
    if np.dtype(dtype).kind == "f":
        a = RNG.normal(scale=1e6, size=5000).astype(dtype)
    else:
        info = np.iinfo(dtype)
        a = RNG.integers(info.min, info.max, 5000, dtype=dtype)
    u, _ = packsort.to_ordered_u64(a)
    assert np.array_equal(np.argsort(a, kind="stable"), np.argsort(u, kind="stable"))


def test_ordered_u64_scalar_matches_vector():
    for dtype, vals in [
        (np.int64, [-(2**62), -5, 0, 7, 2**62]),
        (np.float64, [-1e300, -1.5, 0.0, 2.5, 1e300]),
        (np.int32, [-100, 0, 100]),
    ]:
        vec, _ = packsort.to_ordered_u64(np.asarray(vals, dtype))
        for v, expect in zip(vals, vec):
            assert packsort.ordered_u64_scalar(v, dtype) == int(expect)


def test_ordered_u64_scalar_clamps_out_of_range_int():
    # query bound beyond the dtype range clamps (still a superset)
    hi = packsort.ordered_u64_scalar(2**40, np.int32)
    assert hi == packsort.ordered_u64_scalar(2**31 - 1, np.int32)
    lo = packsort.ordered_u64_scalar(-(2**40), np.int32)
    assert lo == packsort.ordered_u64_scalar(-(2**31), np.int32)


# ---------------------------------------------------------------------------
# pack_sort core invariants
# ---------------------------------------------------------------------------

def _check_pack(key, bits, prefix=None, force_shift=None):
    out = packsort.pack_sort(key, bits, prefix=prefix, force_shift=force_shift)
    if out is None:
        return None
    perm, kq, pfx_sorted, shift = out
    # permutation is a bijection
    assert len(perm) == len(key)
    assert np.array_equal(np.sort(perm), np.arange(len(key)))
    # stored key = quantized key gathered through perm
    assert np.array_equal(kq, key[perm] >> np.uint64(shift))
    # stored key column is sorted (within prefix groups when present)
    if prefix is None:
        assert np.all(kq[:-1] <= kq[1:])
    else:
        assert np.array_equal(pfx_sorted, prefix[perm])
        assert np.all(pfx_sorted[:-1] <= pfx_sorted[1:])
        same = pfx_sorted[:-1] == pfx_sorted[1:]
        assert np.all(kq[:-1][same] <= kq[1:][same])
    return out


def test_pack_sort_matches_lexsort_oracle():
    n = 50_000
    key = RNG.integers(0, 2**63, n, dtype=np.uint64)
    pfx = RNG.integers(-3, 9, n, dtype=np.int32)
    out = _check_pack(key, 63, prefix=pfx)
    assert out is not None
    perm, kq, pfx_sorted, shift = out
    oracle = np.lexsort((key >> np.uint64(shift), pfx))
    # equal quantized keys permit any within-group order: compare sorted keys
    assert np.array_equal(pfx[oracle], pfx_sorted)
    assert np.array_equal(key[oracle] >> np.uint64(shift), kq)


def test_pack_sort_empty_and_tiny():
    assert packsort.pack_sort(np.zeros(0, np.uint64), 32) is None
    out = _check_pack(np.array([5, 3, 3, 1], np.uint64), 32)
    assert out is not None
    assert np.array_equal(out[1], np.array([1, 3, 3, 5], np.uint64))


def test_pack_sort_refuses_too_coarse():
    # huge index space leaves < MIN_KEY_BITS for the key -> None
    key = RNG.integers(0, 2**63, 8, dtype=np.uint64)
    assert packsort.pack_sort(key, 63, force_shift=62) is None


def test_pack_sort_near_int32_perm_boundary():
    # the perm dtype switches at 2**31 rows; can't allocate that, but verify
    # the idx_bits math at a large-but-allocatable n keeps the perm exact
    n = 1_500_000
    key = RNG.integers(0, 2**63, n, dtype=np.uint64)
    perm, kq, _, shift = packsort.pack_sort(key, 63)
    assert perm.dtype == np.int32
    assert np.array_equal(kq, key[perm] >> np.uint64(shift))


def test_quantized_windows_superset_under_forced_shift():
    """Windows resolved against quantized keys must be supersets of exact
    matches, for every shift the engine might pick."""
    n = 20_000
    key = RNG.integers(0, 2**40, n, dtype=np.uint64)
    for shift in (0, 4, 9, 17):
        out = packsort.pack_sort(key, 40, force_shift=shift)
        assert out is not None
        perm, kq, _, sh = out
        assert sh == shift
        for lo, hi in [(0, 2**39), (2**33, 2**35), (12345, 12345 + 2**20)]:
            exact = ((key >= lo) & (key <= hi)).sum()
            s = np.searchsorted(kq, np.uint64(lo >> sh), side="left")
            e = np.searchsorted(kq, np.uint64(hi >> sh), side="right")
            assert e - s >= exact  # superset
            # and the window rows really contain every exact match
            rows = key[perm[s:e]]
            assert ((rows >= lo) & (rows <= hi)).sum() == exact


def test_pack_sort_tiebreak_orders_equal_keys():
    n = 10_000
    key = RNG.integers(0, 16, n, dtype=np.uint64)  # heavy duplication
    tb = RNG.integers(0, 2**63, n, dtype=np.uint64) << np.uint64(1)
    perm, kq, _, shift = packsort.pack_sort(key, 40, tiebreak=tb, tiebreak_bits=16)
    assert shift == 0
    same = kq[:-1] == kq[1:]
    tb_sorted = tb[perm]
    # within equal keys, the USED tiebreak bits are non-decreasing (the
    # engine spends only the spare bits: 64 - idx_bits - key_bits here)
    used = min(16, 64 - packsort.bits_for(n) - 40)
    assert used > 0
    top = tb_sorted >> np.uint64(64 - used)
    assert np.all(top[:-1][same] <= top[1:][same])


def test_native_vs_numpy_pack_sort_equivalence(monkeypatch):
    """The native pack/unpack path and the pure-numpy path must agree."""
    from geomesa_tpu import native

    if native.lib() is None:
        pytest.skip("native library unavailable")
    n = 30_000
    key = RNG.integers(0, 2**63, n, dtype=np.uint64)
    pfx = RNG.integers(0, 7, n, dtype=np.int32)
    got = packsort.pack_sort(key, 63, prefix=pfx)
    monkeypatch.setattr(native, "_lib", None)
    monkeypatch.setattr(native, "_tried", True)
    want = packsort.pack_sort(key, 63, prefix=pfx)
    assert got is not None and want is not None
    for g, w in zip(got[:3], want[:3]):
        if g is not None:
            assert np.array_equal(np.asarray(g), np.asarray(w))
    assert got[3] == want[3]


# ---------------------------------------------------------------------------
# fid hashing
# ---------------------------------------------------------------------------

def test_fid_hash64_width_independent():
    fids = ["a", "abcdefg", "abcdefgh", "abcdefghi", "x" * 31]
    h_u7 = packsort.fid_hash64(np.asarray(fids, dtype="U7")[:2])
    h_u32 = packsort.fid_hash64(np.asarray(fids, dtype="U32")[:2])
    assert np.array_equal(h_u7, h_u32)
    # bytes vs unicode columns agree for pure-ASCII fids (S stores UTF-8
    # bytes, U stores UCS4 codepoints; hashes differ across those layouts,
    # so the engine must hash a consistent layout -- verify S==S, U==U)
    h_s = packsort.fid_hash64(np.asarray(fids, dtype="S32"))
    h_s2 = packsort.fid_hash64(np.asarray(fids, dtype="S40"))
    assert np.array_equal(h_s, h_s2)


def test_fid_hash64_scalar_matches_vector():
    fids = np.asarray(["f0", "f1", "some-longer-feature-id-string"])
    h = packsort.fid_hash64(fids)
    for i, f in enumerate(fids):
        assert packsort.fid_hash64_one(str(f)) == int(h[i])


def test_fid_hash_collision_resolved_by_idin(monkeypatch):
    """Force EVERY fid into the same hash bucket: an IdIn query must return
    exactly the requested fids, not their bucket-mates. (With the real hash,
    collisions at test scale are ~impossible, so this pins the hash to a
    constant — the lookup window then spans all rows and only the exact
    fid-equality mask separates matches.)"""
    from geomesa_tpu.api.dataset import GeoDataset
    from geomesa_tpu.index import keyspace as ks_mod

    monkeypatch.setattr(
        ks_mod.packsort, "fid_hash64",
        lambda fids: np.full(len(np.asarray(fids)), 12345, np.uint64),
    )
    monkeypatch.setattr(
        ks_mod.packsort, "fid_hash64_one", lambda fid: 12345
    )
    ds = GeoDataset(n_shards=2)
    ds.create_schema("t", "name:String,dtg:Date,*geom:Point")
    n = 512
    ds.insert(
        "t",
        {
            "geom__x": np.linspace(-120, -60, n),
            "geom__y": np.linspace(25, 45, n),
            "dtg": np.full(n, np.datetime64("2024-01-02", "ms")),
            "name": [f"n{i}" for i in range(n)],
        },
        fids=[f"fid{i}" for i in range(n)],
    )
    ds.flush("t")
    got = ds.query("t", "IN ('fid7')").to_dict()
    assert got["__fid__"] == ["fid7"]
    got = ds.query("t", "IN ('fid7', 'fid300', 'missing')").to_dict()
    assert sorted(got["__fid__"]) == ["fid300", "fid7"]


# ---------------------------------------------------------------------------
# force_shift mismatch -> rebuild path (store-level)
# ---------------------------------------------------------------------------

def test_force_shift_mismatch_triggers_rebuild():
    """Append a batch whose keys cannot be quantized with the existing
    table's shift: the table must rebuild, stay sorted, and stay correct."""
    from geomesa_tpu.index.store import FeatureStore
    from geomesa_tpu.schema.feature_type import FeatureType

    ft = FeatureType.from_spec("t", "dtg:Date,*geom:Point")
    fs = FeatureStore(ft, n_shards=2)
    n = 4096
    fs.append(
        {
            "geom__x": RNG.uniform(-170, 170, n),
            "geom__y": RNG.uniform(-80, 80, n),
            "dtg": np.full(n, np.datetime64("2024-01-02", "ms")),
        }
    )
    fs.flush()
    t = fs.tables["z3"]
    shifts_before = dict(t.key_shifts or {})
    # second, much larger batch forces more idx bits -> different shift
    m = 70_000
    fs.append(
        {
            "geom__x": RNG.uniform(-170, 170, m),
            "geom__y": RNG.uniform(-80, 80, m),
            "dtg": np.full(m, np.datetime64("2024-06-02", "ms")),
        }
    )
    fs.flush()
    assert t.n == n + m
    # the append CANNOT merge here: fresh keys forced to the old shift don't
    # fit the fresh batch's bit budget, so the table must rebuild with a new
    # (coarser) quantization — assert the shift really changed
    assert t.key_shifts is not None and shifts_before
    assert t.key_shifts["__z3"] != shifts_before["__z3"]
    # sorted invariant holds after the rebuild
    b, z = t.key_columns["__z3_bin"], t.key_columns["__z3"]
    assert np.all(b[:-1] <= b[1:])
    same = b[:-1] == b[1:]
    assert np.all(z[:-1][same] <= z[1:][same])


def test_append_with_matching_shift_merges_in_order():
    from geomesa_tpu.index.store import FeatureStore
    from geomesa_tpu.schema.feature_type import FeatureType

    ft = FeatureType.from_spec("t", "dtg:Date,*geom:Point")
    fs = FeatureStore(ft, n_shards=2)
    for day in (2, 9, 5):  # out-of-order time bins across appends
        n = 3000
        fs.append(
            {
                "geom__x": RNG.uniform(-120, -60, n),
                "geom__y": RNG.uniform(25, 45, n),
                "dtg": np.full(n, np.datetime64(f"2024-01-0{day}", "ms")),
            }
        )
        fs.flush()
    t = fs.tables["z3"]
    assert t.n == 9000
    b, z = t.key_columns["__z3_bin"], t.key_columns["__z3"]
    assert np.all(b[:-1] <= b[1:])
    same = b[:-1] == b[1:]
    assert np.all(z[:-1][same] <= z[1:][same])
