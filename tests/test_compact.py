"""Window-compacted scan layout: results must be identical to the padded
device path and the host oracle (reference parity: range scans only read
planned ranges, AbstractBatchScan.scala:32, with unchanged semantics)."""

import numpy as np
import pytest

from geomesa_tpu import GeoDataset
from geomesa_tpu.filter.ecql import parse_iso_ms
from geomesa_tpu.planning import executor as exmod


@pytest.fixture
def ds_data():
    rng = np.random.default_rng(11)
    n = 60_000
    lo = parse_iso_ms("2020-01-01")
    hi = parse_iso_ms("2020-02-01")
    data = {
        "geom__x": rng.uniform(-120, -70, n),
        "geom__y": rng.uniform(25, 50, n),
        "dtg": rng.integers(lo, hi, n).astype("datetime64[ms]"),
        "weight": rng.uniform(0, 1, n).astype(np.float32),
    }
    ds = GeoDataset(n_shards=4)
    ds.create_schema("t", "weight:Float,dtg:Date,*geom:Point")
    ds.insert("t", data, fids=np.arange(n).astype(str))
    ds.flush("t")
    return ds, data


ECQL = (
    "BBOX(geom, -100, 30, -80, 45) AND "
    "dtg DURING 2020-01-05T00:00:00Z/2020-01-15T00:00:00Z"
)


def _oracle_mask(data):
    x, y = data["geom__x"], data["geom__y"]
    t = data["dtg"].astype(np.int64)
    return (
        (x >= -100) & (x <= -80) & (y >= 30) & (y <= 45)
        & (t >= parse_iso_ms("2020-01-05"))
        & (t <= parse_iso_ms("2020-01-15"))
    )


@pytest.fixture
def force_compact():
    from geomesa_tpu import config

    config.COMPACT_MIN_ROWS.set(1)
    config.COMPACT_FRACTION.set(2.0)
    yield
    config.COMPACT_MIN_ROWS.set(None)
    config.COMPACT_FRACTION.set(None)


def _compact_was_used(ds, plan):
    st = ds._store("t")
    return any(k[0] == "compact_win" for k in st.__dict__.get("_win_cache", {}))


def test_compact_count_density_match_oracle(ds_data, force_compact):
    ds, data = ds_data
    want = int(_oracle_mask(data).sum())
    st, _, plan = ds._plan("t", ECQL)
    ex = ds._executor(st)
    assert ex.count(plan) == want
    assert _compact_was_used(ds, plan), "compact path did not engage"
    bbox = (-100.0, 30.0, -80.0, 45.0)
    grid = ex.density(plan, bbox, 64, 64)
    assert abs(float(grid.sum()) - want) < 1e-3
    # per-cell equality against the padded device path
    ds2 = GeoDataset(n_shards=4)
    ds2.create_schema("t", "weight:Float,dtg:Date,*geom:Point")
    ds2.insert("t", data, fids=np.arange(len(data["dtg"])).astype(str))
    ds2.flush("t")
    grid2 = ds2.density("t", ECQL, bbox=bbox, width=64, height=64)
    np.testing.assert_allclose(grid, grid2)


def test_compact_features_mask(ds_data, force_compact):
    ds, data = ds_data
    out = ds.query("t", ECQL)
    want = _oracle_mask(data)
    assert len(out) == int(want.sum())
    assert set(out.fids) == set(np.nonzero(want)[0].astype(str))


def test_compact_sampling_parity(ds_data, force_compact, monkeypatch):
    from geomesa_tpu.api.dataset import Query

    ds, data = ds_data
    q = Query(ecql=ECQL, sampling=10)
    n_compact = ds.count("t", q)
    st, _, plan = ds._plan("t", q)
    assert _compact_was_used(ds, plan)
    # same query, compaction off: the deterministic 1-in-n counter must
    # select the identical sample
    monkeypatch.setenv("GEOMESA_COMPACT_ENABLED", "false")
    n_full = ds.count("t", Query(ecql=ECQL, sampling=10))
    want = int(_oracle_mask(data).sum())
    assert n_compact == n_full == -(-want // 10)


def test_compact_stats(ds_data, force_compact):
    ds, data = ds_data
    got = ds.stats("t", "MinMax(weight)", ECQL)
    m = _oracle_mask(data)
    w = data["weight"][m]
    assert np.isclose(got.lo, w.min(), atol=1e-6)
    assert np.isclose(got.hi, w.max(), atol=1e-6)


def _f32_hist(x, y, bbox, W, H):
    """Host oracle replicating the device's f32 cell binning (the device
    computes px/py from f32 coordinates; a row on a cell boundary may bin
    one cell off vs f64 — established device-path semantics)."""
    x32, y32 = x.astype(np.float32), y.astype(np.float32)
    b = [np.float32(v) for v in bbox]
    px = np.clip(((x32 - b[0]) / (b[2] - b[0]) * np.float32(W)).astype(np.int64), 0, W - 1)
    py = np.clip(((y32 - b[1]) / (b[3] - b[1]) * np.float32(H)).astype(np.int64), 0, H - 1)
    out = np.zeros(H * W, np.float32)
    np.add.at(out, py * W + px, 1.0)
    return out.reshape(H, W)


def test_mxu_density_per_cell(ds_data, force_compact):
    """The MXU pair kernel must be per-cell exact vs the host histogram."""
    ds, data = ds_data
    bbox = (-100.0, 30.0, -80.0, 45.0)
    W = H = 96
    st, _, plan = ds._plan("t", ECQL)
    ex = ds._executor(st)
    grid = ex.density(plan, bbox, W, H)
    # pair cache must hold a real pair list (proves the MXU path ran)
    pc = st.__dict__.get("_pair_cache", {})
    assert any(v for v in pc.values()), "MXU pair path did not engage"
    m = _oracle_mask(data)
    want = _f32_hist(data["geom__x"][m], data["geom__y"][m], bbox, W, H)
    np.testing.assert_allclose(grid, want)


def test_mxu_density_unclipped_rows(ds_data, force_compact):
    """Rows outside the density bbox clamp into edge cells on both paths
    (RenderingGrid convention) — the pair boxes must cover the clip."""
    ds, data = ds_data
    # filter wider than the density bbox: many matched rows fall outside
    ecql = "BBOX(geom, -110, 27, -75, 48)"
    bbox = (-100.0, 33.0, -90.0, 42.0)
    W = H = 64
    grid = ds.density("t", ecql, bbox=bbox, width=W, height=H)
    x, y = data["geom__x"], data["geom__y"]
    m = (x >= -110) & (x <= -75) & (y >= 27) & (y <= 48)
    want = _f32_hist(x[m], y[m], bbox, W, H)
    np.testing.assert_allclose(grid, want)


def test_compact_weighted_density(ds_data, force_compact):
    ds, data = ds_data
    bbox = (-100.0, 30.0, -80.0, 45.0)
    grid = ds.density("t", ECQL, bbox=bbox, width=32, height=32,
                      weight="weight")
    m = _oracle_mask(data)
    assert np.isclose(
        float(grid.sum()), float(data["weight"][m].sum()), rtol=1e-4
    )
