"""Randomized differential test: a time-PARTITIONED store (with spilled
partitions) must answer every random predicate tree exactly like a flat
store over the same rows. This hammers partition pruning (time-bound
extraction feeding bin selection) composed with window pushdown, lazy
snapshot reload, and per-partition merge."""

pytestmark = __import__("pytest").mark.fuzz
import numpy as np
import pytest

from geomesa_tpu import GeoDataset
from geomesa_tpu.filter.ecql import parse_iso_ms

N = 20_000
T0 = parse_iso_ms("2020-01-01")
T1 = parse_iso_ms("2020-04-01")  # ~13 weekly partitions
SPEC = "v:Double,k:Integer,dtg:Date,*geom:Point"


@pytest.fixture(scope="module")
def pair(tmp_path_factory):
    rng = np.random.default_rng(55)
    data = {
        "v": rng.uniform(0, 10, N),
        "k": rng.integers(0, 20, N).astype(np.int32),
        "dtg": rng.integers(T0, T1, N).astype("datetime64[ms]"),
        "geom__x": rng.uniform(-20, 20, N),
        "geom__y": rng.uniform(-20, 20, N),
    }
    flat = GeoDataset(n_shards=2)
    flat.create_schema("t", SPEC)
    flat.insert("t", data, fids=np.arange(N).astype(str))
    flat.flush()
    part = GeoDataset(n_shards=2)
    part.create_schema("t", SPEC + ";geomesa.partition='time'")
    st = part._store("t")
    st.max_resident = 2  # constant spill/reload churn
    st._spill_dir = str(tmp_path_factory.mktemp("spill"))
    part.insert("t", data, fids=np.arange(N).astype(str))
    part.flush()
    st.evict(keep=1)
    return flat, part


def _rand_time(rng):
    a, b = sorted(rng.integers(T0 - 10**9, T1 + 10**9, 2))
    ai, bi = np.datetime64(int(a), "ms"), np.datetime64(int(b), "ms")
    form = rng.integers(0, 4)
    if form == 0:
        return f"dtg DURING {ai}Z/{bi}Z"
    if form == 1:
        return f"dtg BEFORE {ai}Z"
    if form == 2:
        return f"dtg AFTER {bi}Z"
    return f"dtg TEQUALS {ai}Z"


def _rand_pred(rng, depth):
    if depth == 0 or rng.random() < 0.4:
        kind = rng.integers(0, 3)
        if kind == 0:
            return _rand_time(rng)
        if kind == 1:
            op = ["<", ">", "<=", ">="][rng.integers(0, 4)]
            return f"v {op} {rng.uniform(0, 10):.2f}"
        x0, y0 = rng.uniform(-20, 10, 2)
        return f"BBOX(geom, {x0:.2f}, {y0:.2f}, {x0+10:.2f}, {y0+10:.2f})"
    k = rng.integers(0, 3)
    lt = _rand_pred(rng, depth - 1)
    if k == 2:
        return f"NOT ({lt})"
    rt = _rand_pred(rng, depth - 1)
    return f"({lt}) {'AND' if k == 0 else 'OR'} ({rt})"


def test_partitioned_matches_flat_on_random_trees(pair):
    flat, part = pair
    rng = np.random.default_rng(67)
    nonzero = 0
    for case in range(80):
        q = _rand_pred(rng, 2)
        a, b = flat.count("t", q), part.count("t", q)
        assert a == b, f"case {case}: {q!r} flat={a} partitioned={b}"
        nonzero += a > 0
    assert nonzero >= 40


def test_partitioned_matches_flat_stats_and_density(pair):
    flat, part = pair
    rng = np.random.default_rng(71)
    for case in range(15):
        q = _rand_pred(rng, 1)
        sf = flat.stats("t", "MinMax(v);Count()", q).to_json()
        sp = part.stats("t", "MinMax(v);Count()", q).to_json()
        assert sf == sp, f"case {case}: {q!r}\n{sf}\n{sp}"
        g1 = flat.density("t", q, bbox=(-20, -20, 20, 20), width=8, height=8)
        g2 = part.density("t", q, bbox=(-20, -20, 20, 20), width=8, height=8)
        assert np.allclose(np.asarray(g1), np.asarray(g2)), f"{case}: {q!r}"
