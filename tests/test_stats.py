"""Stats sketches vs numpy oracles (reference analog: geomesa-utils stats tests)."""

import numpy as np
import pytest

from geomesa_tpu.stats import (
    CountStat, DescriptiveStats, EnumerationStat, Frequency, GroupBy,
    Histogram, MinMax, SeqStat, Stat, TopK, Z3HistogramStat, parse_stat,
)


@pytest.fixture
def cols(rng):
    n = 5000
    return {
        "v": rng.normal(10, 5, n),
        "cat": rng.integers(0, 7, n),
        "geom__x": rng.uniform(-75, -73, n),
        "geom__y": rng.uniform(40, 42, n),
        "dtg": rng.integers(1_600_000_000_000, 1_601_000_000_000, n).astype(np.int64),
    }


def roundtrip(s: Stat) -> Stat:
    return Stat.from_json(s.to_json())


def test_count_observe_merge_unobserve(cols):
    a, b = CountStat(), CountStat()
    a.observe(cols)
    mask = cols["cat"] == 3
    b.observe(cols, mask)
    assert a.value() == 5000
    assert b.value() == int(mask.sum())
    a.merge(b)
    assert a.value() == 5000 + int(mask.sum())
    a.unobserve(cols, mask)
    assert a.value() == 5000
    assert roundtrip(a).value() == a.value()


def test_minmax_numeric_and_geom(cols):
    m = MinMax("v")
    m.observe(cols)
    assert m.value()["min"] == pytest.approx(cols["v"].min())
    assert m.value()["max"] == pytest.approx(cols["v"].max())
    g = MinMax("geom")
    g.observe(cols)
    assert g.value()["min"][0] == pytest.approx(cols["geom__x"].min())
    assert g.value()["max"][1] == pytest.approx(cols["geom__y"].max())
    # split-merge == whole
    h1, h2 = MinMax("v"), MinMax("v")
    h1.observe({"v": cols["v"][:2000]})
    h2.observe({"v": cols["v"][2000:]})
    h1.merge(h2)
    assert h1.value() == m.value()
    assert roundtrip(h1).value() == m.value()


def test_enumeration_and_topk(cols):
    e = EnumerationStat("cat")
    e.observe(cols)
    vals, counts = np.unique(cols["cat"], return_counts=True)
    for v, c in zip(vals.tolist(), counts.tolist()):
        assert e.counts[v] == c
    t = TopK("cat", 3)
    t.observe(cols)
    top = t.value()
    assert len(top) == 3
    assert top[0][1] == counts.max()
    assert roundtrip(t).value() == top


def test_histogram_merge_and_selectivity(cols):
    h = Histogram("v", 50, -10.0, 30.0)
    h.observe(cols)
    assert int(h.counts.sum()) == 5000
    # split-merge equivalence
    h1, h2 = Histogram("v", 50, -10.0, 30.0), Histogram("v", 50, -10.0, 30.0)
    h1.observe({"v": cols["v"][:1000]})
    h2.observe({"v": cols["v"][1000:]})
    h1.merge(h2)
    np.testing.assert_array_equal(h1.counts, h.counts)
    # selectivity estimate close to truth for an aligned range
    est = h.count_between(0.0, 20.0)
    truth = int(((cols["v"] >= 0) & (cols["v"] <= 20)).sum())
    assert abs(est - truth) / truth < 0.1
    assert roundtrip(h).value() == h.value()


def test_frequency_overestimates_bounded(cols):
    f = Frequency("cat", width=256)
    f.observe(cols)
    vals, counts = np.unique(cols["cat"], return_counts=True)
    for v, c in zip(vals.tolist(), counts.tolist()):
        assert f.count(v) >= c  # count-min never underestimates
        assert f.count(v) <= c + 5000 // 256 * 4  # loose CM bound
    f2 = Frequency("cat", width=256)
    f2.observe(cols)
    f.merge(f2)
    assert f.count(int(vals[0])) >= 2 * int(counts[0])
    assert roundtrip(f).count(int(vals[0])) == f.count(int(vals[0]))


def test_descriptive_stats(cols):
    d = DescriptiveStats(["v"])
    d.observe(cols)
    v = d.value()
    assert v["mean"][0] == pytest.approx(cols["v"].mean())
    assert v["stddev"][0] == pytest.approx(cols["v"].std(), rel=1e-6)
    d1, d2 = DescriptiveStats(["v"]), DescriptiveStats(["v"])
    d1.observe({"v": cols["v"][:777]})
    d2.observe({"v": cols["v"][777:]})
    d1.merge(d2)
    assert d1.value()["mean"][0] == pytest.approx(v["mean"][0])


def test_groupby(cols):
    g = GroupBy("cat", "MinMax(v)")
    g.observe(cols)
    for k, sub in g.value().items():
        sel = cols["cat"] == k
        assert sub["min"] == pytest.approx(cols["v"][sel].min())
    assert roundtrip(g).value().keys() == g.value().keys()


def test_z3histogram_estimate(cols):
    z = Z3HistogramStat("geom", "dtg", "week", 1024)
    z.observe(cols)
    assert sum(z.value().values()) == 5000
    # estimate over the full window ~ total count
    from geomesa_tpu.curves.zorder import Z3SFC

    sfc = Z3SFC("week")
    bins = np.array(sorted(z.bins.keys()))
    # Whole-space cover -> estimate must equal the exact total.
    from geomesa_tpu.curves.cover import ZRange

    whole = [ZRange(0, (1 << 63) - 1)]
    est = z.estimate_count(bins, whole)
    assert est == pytest.approx(5000, rel=0.01)
    # A small-bbox cover must be monotonically smaller, never negative.
    ranges = sfc.ranges((-75, -73), (40, 42), (0, float(sfc.binned.max_offset_ms)))
    sub = z.estimate_count(bins, ranges)
    assert 0 <= sub <= est
    rt = roundtrip(z)
    assert rt.estimate_count(bins, whole) == pytest.approx(est)


def test_parser_roundtrip(cols):
    s = parse_stat(
        "Count();MinMax(v);Histogram(v,20,-10,30);TopK(cat,5);"
        "GroupBy(cat,DescriptiveStats(v));Z3Histogram(geom,dtg,week,512)"
    )
    assert isinstance(s, SeqStat)
    s.observe(cols)
    vals = s.value()
    assert vals[0] == 5000
    rt = roundtrip(s)
    assert rt.value()[0] == 5000


def test_parser_errors():
    with pytest.raises(ValueError):
        parse_stat("Bogus(x)")
    with pytest.raises(ValueError):
        parse_stat("MinMax(")
    with pytest.raises(ValueError):
        parse_stat("")
