"""Streaming layer tests (Kafka datastore / live cache / Lambda parity)."""

import numpy as np
import pytest

from geomesa_tpu import GeoDataset
from geomesa_tpu.filter.ecql import parse_iso_ms
from geomesa_tpu.stream import (
    GeoMessage, LambdaDataset, LiveFeatureCache, MessageBus, StreamingDataset,
)
from geomesa_tpu.stream.live import playback
from geomesa_tpu.stream.messages import CHANGE, CLEAR, DELETE
from geomesa_tpu.schema.feature_type import FeatureType

SPEC = "name:String,speed:Double,dtg:Date,*geom:Point"


def test_geomessage_wire_round_trip():
    m = GeoMessage.change("fid-1", {"name": "x", "speed": 4.5, "geom": [1.0, 2.0]}, 123456)
    m2 = GeoMessage.deserialize(m.serialize())
    assert m2 == m
    d = GeoMessage.delete("fid-2", 99)
    assert GeoMessage.deserialize(d.serialize()) == d
    c = GeoMessage.clear(5)
    assert GeoMessage.deserialize(c.serialize()) == c


def test_topic_partitioning_and_offsets():
    bus = MessageBus()
    t = bus.create("x", partitions=4)
    for i in range(20):
        t.send(GeoMessage.change(f"f{i}", {}, i))
    msgs, offs = t.poll([0, 0, 0, 0])
    assert len(msgs) == 20
    assert sum(offs) == 20
    # same fid -> same partition (ordering per feature)
    t2 = bus.create("y", partitions=4)
    t2.send(GeoMessage.change("abc", {}, 1))
    t2.send(GeoMessage.change("abc", {}, 2))
    ends = t2.end_offsets()
    assert sorted(ends) == [0, 0, 0, 2]
    # incremental poll
    msgs2, offs2 = t.poll(offs)
    assert msgs2 == [] and offs2 == offs


def _write_points(ds, name, n=50, t0="2020-01-01", seed=0):
    rng = np.random.default_rng(seed)
    ts = parse_iso_ms(t0) + np.arange(n) * 1000
    data = {
        "name": [f"n{i % 3}" for i in range(n)],
        "speed": rng.uniform(0, 30, n),
        "dtg": ts,
        "geom": [(float(x), float(y)) for x, y in
                 zip(rng.uniform(-120, -70, n), rng.uniform(25, 50, n))],
    }
    ds.write(name, data, [f"f{i}" for i in range(n)], ts_ms=ts)
    return data


def test_streaming_dataset_query_count_density():
    ds = StreamingDataset()
    ds.create_schema("track", SPEC)
    data = _write_points(ds, "track", 100)
    assert ds.count("track") == 100
    xs = np.array([p[0] for p in data["geom"]])
    ys = np.array([p[1] for p in data["geom"]])
    expect = int(((xs >= -100) & (xs <= -80) & (ys >= 30) & (ys <= 45)).sum())
    assert ds.count("track", "BBOX(geom, -100, 30, -80, 45)") == expect
    grid = ds.density("track", "BBOX(geom, -100, 30, -80, 45)",
                      bbox=(-100, 30, -80, 45), width=32, height=32)
    assert abs(float(grid.sum()) - expect) < 1e-3
    # attribute predicate over live window
    assert ds.count("track", "name = 'n0'") == sum(
        1 for i in range(100) if i % 3 == 0
    )
    st = ds.stats("track", "Enumeration(name)")
    assert set(st.value()) == {"n0", "n1", "n2"}


def test_live_update_delete_clear_and_events():
    ds = StreamingDataset()
    ds.create_schema("t", SPEC)
    events = []
    ds.add_listener("t", lambda m: events.append(m.kind))
    ts = parse_iso_ms("2020-01-01")
    ds.write("t", {"name": ["a"], "speed": [1.0], "dtg": [ts], "geom": [(0.0, 0.0)]},
             ["f1"], ts_ms=[ts])
    assert ds.count("t") == 1
    # update same fid (newer ts) replaces
    ds.write("t", {"name": ["b"], "speed": [2.0], "dtg": [ts + 1000], "geom": [(1.0, 1.0)]},
             ["f1"], ts_ms=[ts + 1000])
    assert ds.count("t") == 1
    batch = ds.query("t")
    assert ds.cache("t").dicts["name"].decode(batch.columns["name"]) == ["b"]
    # stale update (older ts) is dropped (event-time ordering)
    ds.write("t", {"name": ["zzz"], "speed": [0.0], "dtg": [ts], "geom": [(9.0, 9.0)]},
             ["f1"], ts_ms=[ts])
    batch = ds.query("t")
    assert ds.cache("t").dicts["name"].decode(batch.columns["name"]) == ["b"]
    ds.delete("t", "f1")
    assert ds.count("t") == 0
    ds.write("t", {"name": ["c"], "speed": [1.0], "dtg": [ts], "geom": [(0.0, 0.0)]},
             ["f2"], ts_ms=[ts])
    ds.clear("t")
    assert ds.count("t") == 0
    assert CHANGE in events and DELETE in events and CLEAR in events


def test_clear_delivered_once():
    ds = StreamingDataset()
    ds.create_schema("t", SPEC)
    events = []
    ds.add_listener("t", lambda m: events.append(m.kind))
    ds.clear("t")
    ds.poll()
    assert events.count(CLEAR) == 1


def test_null_geometry_tolerated():
    ds = StreamingDataset()
    ds.create_schema("t", SPEC)
    ts = parse_iso_ms("2020-01-01")
    ds.write("t", {"name": ["a", "b"], "speed": [1.0, 2.0], "dtg": [ts, ts],
                   "geom": [(1.0, 2.0), None]}, ["f1", "f2"], ts_ms=[ts, ts])
    # feature with null geometry is invisible to queries, no crash
    assert ds.count("t") == 1
    assert ds.count("t", "speed > 0") == 1
    batch = ds.query("t")
    from geomesa_tpu.schema.columns import fid_strs

    assert fid_strs(batch.columns["__fid__"]).tolist() == ["f1"]


def test_event_time_expiry():
    cache = LiveFeatureCache(FeatureType.from_spec("t", SPEC), expiry_ms=10_000)
    cache.put("a", {"geom": [0.0, 0.0]}, 0)
    cache.put("b", {"geom": [0.0, 0.0]}, 95_000)
    dropped = cache.expire(now_ms=100_000)
    assert dropped == 1 and len(cache) == 1


def test_grid_index_pruning_matches_full_scan():
    ds = StreamingDataset()
    ds.create_schema("t", SPEC)
    _write_points(ds, "t", 300, seed=5)
    ds.poll()
    cache = ds.cache("t")
    from geomesa_tpu.filter import parse_ecql

    f = parse_ecql("BBOX(geom, -95, 30, -85, 40)")
    cand = cache.candidate_rows(f)
    assert cand is not None and 0 < len(cand) < 300
    # pruned path returns identical results to an unpruned evaluation
    n_pruned = ds.count("t", "BBOX(geom, -95, 30, -85, 40)")
    batch = cache.batch()
    xs, ys = batch.columns["geom__x"], batch.columns["geom__y"]
    expect = int(((xs >= -95) & (xs <= -85) & (ys >= 30) & (ys <= 40)).sum())
    assert n_pruned == expect


def test_playback():
    ds = StreamingDataset()
    ds.create_schema("t", SPEC)
    n = 30
    ts = parse_iso_ms("2020-01-01") + np.arange(n) * 500
    rng = np.random.default_rng(0)
    data = {
        "name": ["a"] * n,
        "speed": rng.uniform(0, 1, n),
        "dtg": ts,
        "geom": [(0.0, 0.0)] * n,
    }
    playback(ds, "t", data, [f"f{i}" for i in range(n)], ts, sleep=False)
    assert ds.count("t") == n


def test_lambda_tiering():
    lam = LambdaDataset(GeoDataset(n_shards=2), persist_age_ms=60_000)
    lam.create_schema("t", SPEC)
    t0 = parse_iso_ms("2020-01-01")
    # old features (will persist) + recent (stay hot)
    rng = np.random.default_rng(1)
    for start, base in ((0, t0), (50, t0 + 10_000_000)):
        ts = base + np.arange(50) * 1000
        lam.write("t", {
            "name": [f"n{i % 3}" for i in range(50)],
            "speed": rng.uniform(0, 30, 50),
            "dtg": ts,
            "geom": [(float(x), float(y)) for x, y in
                     zip(rng.uniform(-120, -70, 50), rng.uniform(25, 50, 50))],
        }, [f"f{start + i}" for i in range(50)], ts_ms=ts)
    now = t0 + 10_000_000 + 49_000 + 1
    moved = lam.run_persistence(now_ms=now)
    assert moved == 50  # the old batch migrated
    assert len(lam.transient.cache("t")) == 50
    assert lam.persistent.count("t") == 50
    # merged query sees both tiers
    assert lam.count("t") == 100
    # merged stats decode strings across tiers
    st = lam.stats("t", "Enumeration(name)")
    assert set(st.value()) == {"n0", "n1", "n2"}
    assert sum(st.value().values()) == 100
    # density merges without double counting
    grid = lam.density("t", bbox=(-120, 25, -70, 50), width=16, height=16)
    assert abs(float(grid.sum()) - 100) < 1e-3
    # second persistence run is a no-op at same cutoff
    assert lam.run_persistence(now_ms=now) == 0


def test_lambda_repersist_update_no_duplicate():
    # a feature updated between persistence runs must be replaced in the
    # cold tier, not duplicated
    lam = LambdaDataset(GeoDataset(n_shards=2), persist_age_ms=1_000)
    lam.create_schema("t", SPEC)
    t0 = parse_iso_ms("2020-01-01")
    row = {"name": ["a"], "speed": [1.0], "dtg": [t0], "geom": [(0.0, 0.0)]}
    lam.write("t", row, ["f1"], ts_ms=[t0])
    assert lam.run_persistence(now_ms=t0 + 2_000) == 1
    # update arrives later with a new position, then ages out too
    row2 = {"name": ["a"], "speed": [2.0], "dtg": [t0 + 5_000], "geom": [(1.0, 1.0)]}
    lam.write("t", row2, ["f1"], ts_ms=[t0 + 5_000])
    assert lam.run_persistence(now_ms=t0 + 10_000) == 1
    assert lam.persistent.count("t") == 1  # replaced, not appended
    assert lam.count("t") == 1
    got = lam.persistent.query("t").to_dict()
    assert got["speed"][0] == pytest.approx(2.0)


def test_lambda_persist_null_geometry():
    lam = LambdaDataset(GeoDataset(n_shards=2), persist_age_ms=1_000)
    lam.create_schema("t", SPEC)
    t0 = parse_iso_ms("2020-01-01")
    lam.write("t", {"name": ["a", "b"], "speed": [1.0, 2.0],
                    "dtg": [t0, t0], "geom": [None, (3.0, 4.0)]},
              ["f1", "f2"], ts_ms=[t0, t0])
    assert lam.run_persistence(now_ms=t0 + 2_000) == 2
    assert lam.persistent.count("t", "BBOX(geom, 0, 0, 10, 10)") == 1
