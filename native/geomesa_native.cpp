// geomesa_tpu native runtime — host-side hot-path kernels.
//
// The TPU compute path is JAX/XLA/Pallas; this library covers the *host*
// runtime work that sits around it (the role the reference delegates to the
// JVM/sfcurve: geomesa-z3/pom.xml:21 bit-interleave, Z3SFC.scala:54 zranges,
// BinaryOutputEncoder.scala:36 track hashing, and the searchsorted window
// resolution of the scan path). Exposed with a C ABI and loaded from Python
// via ctypes (geomesa_tpu/native.py); every entry point has a NumPy fallback
// so the framework runs without a toolchain.
//
// Semantics are bit-exact mirrors of the Python implementations in
// geomesa_tpu/curves/zorder.py, curves/cover.py, io/bin_format.py — parity is
// enforced by tests/test_native.py.

#include <cstdint>
#include <cstring>
#include <cmath>
#include <algorithm>
#include <deque>
#include <vector>
#if defined(_OPENMP)
#include <omp.h>
#include <parallel/algorithm>
#endif

// Parallelize a loop body over [0, n) when OpenMP is available and the
// problem is large enough to amortize thread startup.
#define GM_PAR_FOR(n) _Pragma("omp parallel for if ((n) > 1000000)")
#if !defined(_OPENMP)
#undef GM_PAR_FOR
#define GM_PAR_FOR(n)
#endif

namespace {

// ---------------------------------------------------------------------------
// Morton bit spread / gather (zorder.py:_split2/_combine2/_split3/_combine3)
// ---------------------------------------------------------------------------

inline uint64_t split2(uint64_t x) {
  x &= 0x7FFFFFFFull;
  x = (x | (x << 16)) & 0x0000FFFF0000FFFFull;
  x = (x | (x << 8)) & 0x00FF00FF00FF00FFull;
  x = (x | (x << 4)) & 0x0F0F0F0F0F0F0F0Full;
  x = (x | (x << 2)) & 0x3333333333333333ull;
  x = (x | (x << 1)) & 0x5555555555555555ull;
  return x;
}

inline uint64_t combine2(uint64_t z) {
  z &= 0x5555555555555555ull;
  z = (z | (z >> 1)) & 0x3333333333333333ull;
  z = (z | (z >> 2)) & 0x0F0F0F0F0F0F0F0Full;
  z = (z | (z >> 4)) & 0x00FF00FF00FF00FFull;
  z = (z | (z >> 8)) & 0x0000FFFF0000FFFFull;
  z = (z | (z >> 16)) & 0x00000000FFFFFFFFull;
  return z;
}

inline uint64_t split3(uint64_t x) {
  x &= 0x1FFFFFull;
  x = (x | (x << 32)) & 0x1F00000000FFFFull;
  x = (x | (x << 16)) & 0x1F0000FF0000FFull;
  x = (x | (x << 8)) & 0x100F00F00F00F00Full;
  x = (x | (x << 4)) & 0x10C30C30C30C30C3ull;
  x = (x | (x << 2)) & 0x1249249249249249ull;
  return x;
}

inline uint64_t combine3(uint64_t z) {
  z &= 0x1249249249249249ull;
  z = (z | (z >> 2)) & 0x10C30C30C30C30C3ull;
  z = (z | (z >> 4)) & 0x100F00F00F00F00Full;
  z = (z | (z >> 8)) & 0x1F0000FF0000FFull;
  z = (z | (z >> 16)) & 0x1F00000000FFFFull;
  z = (z | (z >> 32)) & 0x1FFFFFull;
  return z;
}

}  // namespace

extern "C" {

void gm_interleave2(const uint64_t* x, const uint64_t* y, uint64_t* out,
                    int64_t n) {
  GM_PAR_FOR(n)
  for (int64_t i = 0; i < n; ++i)
    out[i] = (split2(x[i]) << 1) | split2(y[i]);
}

void gm_deinterleave2(const uint64_t* z, uint64_t* x, uint64_t* y, int64_t n) {
  for (int64_t i = 0; i < n; ++i) {
    x[i] = combine2(z[i] >> 1);
    y[i] = combine2(z[i]);
  }
}

void gm_interleave3(const uint64_t* x, const uint64_t* y, const uint64_t* t,
                    uint64_t* out, int64_t n) {
  GM_PAR_FOR(n)
  for (int64_t i = 0; i < n; ++i)
    out[i] = (split3(x[i]) << 2) | (split3(y[i]) << 1) | split3(t[i]);
}

void gm_deinterleave3(const uint64_t* z, uint64_t* x, uint64_t* y, uint64_t* t,
                      int64_t n) {
  for (int64_t i = 0; i < n; ++i) {
    x[i] = combine3(z[i] >> 2);
    y[i] = combine3(z[i] >> 1);
    t[i] = combine3(z[i]);
  }
}

// ---------------------------------------------------------------------------
// Z-range cover (curves/cover.py:zcover — identical BFS + budget + merge)
// ---------------------------------------------------------------------------

int64_t gm_zcover(const uint64_t* qlo, const uint64_t* qhi, int32_t bits,
                  int32_t dims, int64_t max_ranges, uint64_t* out_lo,
                  uint64_t* out_hi, int64_t cap) {
  if (dims < 1 || dims > 3 || bits < 1 || bits * dims > 63) return -2;
  const int d = dims;
  for (int k = 0; k < d; ++k)
    if (qlo[k] > qhi[k]) return -2;

  struct Cell {
    uint64_t zmin;
    int32_t level;
    uint64_t mins[3];
    uint64_t maxs[3];
  };

  const uint64_t full = (1ull << bits) - 1;
  std::deque<Cell> frontier;
  {
    Cell root{};
    root.zmin = 0;
    root.level = 0;
    for (int k = 0; k < d; ++k) {
      root.mins[k] = 0;
      root.maxs[k] = full;
    }
    frontier.push_back(root);
  }
  std::vector<std::pair<uint64_t, uint64_t>> out;

  auto cell_span = [&](int32_t level) -> uint64_t {
    return (1ull << (uint64_t)(d * (bits - level))) - 1;
  };
  auto disjoint = [&](const uint64_t* mins, const uint64_t* maxs) {
    for (int k = 0; k < d; ++k)
      if (maxs[k] < qlo[k] || mins[k] > qhi[k]) return true;
    return false;
  };

  while (!frontier.empty()) {
    Cell c = frontier.front();
    frontier.pop_front();
    if (disjoint(c.mins, c.maxs)) continue;
    bool contained = true;
    for (int k = 0; k < d; ++k)
      if (!(qlo[k] <= c.mins[k] && c.maxs[k] <= qhi[k])) {
        contained = false;
        break;
      }
    if (contained) {
      out.emplace_back(c.zmin, c.zmin + cell_span(c.level));
      continue;
    }
    if (c.level == bits) {
      out.emplace_back(c.zmin, c.zmin);
      continue;
    }
    if ((int64_t)(out.size() + frontier.size() + (1u << d)) > max_ranges) {
      out.emplace_back(c.zmin, c.zmin + cell_span(c.level));
      while (!frontier.empty()) {
        Cell f = frontier.front();
        frontier.pop_front();
        if (disjoint(f.mins, f.maxs)) continue;
        out.emplace_back(f.zmin, f.zmin + cell_span(f.level));
      }
      break;
    }
    const int b = bits - 1 - c.level;
    const uint64_t half = 1ull << b;
    const int group_shift = d * b;
    for (uint32_t combo = 0; combo < (1u << d); ++combo) {
      Cell child{};
      child.level = c.level + 1;
      uint64_t zadd = 0;
      for (int k = 0; k < d; ++k) {
        const uint32_t bit = (combo >> (d - 1 - k)) & 1u;
        if (bit) {
          child.mins[k] = c.mins[k] + half;
          child.maxs[k] = c.maxs[k];
          zadd |= 1ull << (group_shift + (d - 1 - k));
        } else {
          child.mins[k] = c.mins[k];
          child.maxs[k] = c.maxs[k] - half;
        }
      }
      child.zmin = c.zmin + zadd;
      frontier.push_back(child);
    }
  }

  // merge adjacent/overlapping (cover.py:_merge)
  std::sort(out.begin(), out.end());
  int64_t m = 0;
  for (size_t i = 0; i < out.size(); ++i) {
    if (m > 0 && out[i].first <= out_hi[m - 1] + 1) {
      if (out[i].second > out_hi[m - 1]) out_hi[m - 1] = out[i].second;
    } else {
      if (m >= cap) return -1;
      out_lo[m] = out[i].first;
      out_hi[m] = out[i].second;
      ++m;
    }
  }
  return m;
}

// ---------------------------------------------------------------------------
// Java String.hashCode over UTF-16 code units (io/bin_format.py)
// ---------------------------------------------------------------------------

void gm_java_hash_utf16(const uint16_t* units, const int64_t* offsets,
                        int64_t n, int32_t* out) {
  for (int64_t i = 0; i < n; ++i) {
    uint32_t h = 0;
    for (int64_t j = offsets[i]; j < offsets[i + 1]; ++j)
      h = h * 31u + units[j];
    out[i] = (int32_t)h;
  }
}

// ---------------------------------------------------------------------------
// Batched searchsorted window resolution (the "scan" of the slice model)
// ---------------------------------------------------------------------------

// Per-range [lo, hi] windows over one sorted u64 key column:
// start = lower_bound(lo), end = upper_bound(hi).
void gm_windows_u64(const uint64_t* keys, int64_t n, const uint64_t* lo,
                    const uint64_t* hi, int64_t k, int64_t* starts,
                    int64_t* ends) {
  for (int64_t i = 0; i < k; ++i) {
    starts[i] = std::lower_bound(keys, keys + n, lo[i]) - keys;
    ends[i] = std::upper_bound(keys, keys + n, hi[i]) - keys;
  }
}

// Z3-style windows: rows sorted by (bin, z); for each requested bin emit the
// [zlo, zhi] window inside that bin's segment. Returns number of non-empty
// windows (mirrors Z3KeySpace.resolve_windows inner loop).
int64_t gm_bin_windows(const int32_t* bins_col, const uint64_t* z_col,
                       int64_t n, const int32_t* bins, int64_t nbins,
                       uint64_t zlo, uint64_t zhi, int64_t* starts,
                       int64_t* ends) {
  int64_t m = 0;
  for (int64_t i = 0; i < nbins; ++i) {
    const int32_t b = bins[i];
    const int64_t s = std::lower_bound(bins_col, bins_col + n, b) - bins_col;
    const int64_t e = std::upper_bound(bins_col, bins_col + n, b) - bins_col;
    if (e <= s) continue;
    const int64_t s2 =
        s + (std::lower_bound(z_col + s, z_col + e, zlo) - (z_col + s));
    const int64_t e2 =
        s + (std::upper_bound(z_col + s, z_col + e, zhi) - (z_col + s));
    if (e2 > s2) {
      starts[m] = s2;
      ends[m] = e2;
      ++m;
    }
  }
  return m;
}

}  // extern "C"

// ---------------------------------------------------------------------------
// Fused Z-curve encode (normalize + interleave in one pass; bit-exact mirror
// of zorder.py NormalizedDimension.normalize + interleave2/3)
// ---------------------------------------------------------------------------

namespace {

inline uint64_t norm_dim(double v, double lo, double hi, int bits) {
  const double scaled = (v - lo) / (hi - lo) * (double)(1ull << bits);
  double f = std::floor(scaled);
  const double maxi = (double)((1ull << bits) - 1);
  if (!(f > 0.0)) f = 0.0;  // NaN and negatives clamp to 0 (np.clip parity)
  if (f > maxi) f = maxi;
  return (uint64_t)f;
}

const uint64_t kHashPrimes[8] = {
    0x9E3779B97F4A7C15ull, 0xC2B2AE3D27D4EB4Full, 0x165667B19E3779F9ull,
    0x27D4EB2F165667C5ull, 0x85EBCA77C2B2AE63ull, 0xFF51AFD7ED558CCDull,
    0xC4CEB9FE1A85EC53ull, 0x2545F4914F6CDD1Dull};

template <int64_t P>
void time_split_fixed(const int64_t* t, int64_t n, int32_t scale, int32_t* bin,
                      int64_t* off_ms, int32_t* off_scaled) {
  // scale==1 branch keeps the inner loop free of a runtime-divisor division
  if (off_scaled && scale == 1) {
    GM_PAR_FOR(n)
    for (int64_t i = 0; i < n; ++i) {
      int64_t b = t[i] / P;
      if (t[i] % P < 0) --b;
      const int64_t off = t[i] - b * P;
      bin[i] = (int32_t)b;
      if (off_ms) off_ms[i] = off;
      off_scaled[i] = (int32_t)off;
    }
    return;
  }
  GM_PAR_FOR(n)
  for (int64_t i = 0; i < n; ++i) {
    int64_t b = t[i] / P;
    if (t[i] % P < 0) --b;  // floor division
    const int64_t off = t[i] - b * P;
    bin[i] = (int32_t)b;
    if (off_ms) off_ms[i] = off;
    if (off_scaled) off_scaled[i] = (int32_t)(off / scale);
  }
}

}  // namespace

extern "C" {

void gm_z2_encode(const double* x, const double* y, int64_t n, uint64_t* out) {
  GM_PAR_FOR(n)
  for (int64_t i = 0; i < n; ++i) {
    const uint64_t xi = norm_dim(x[i], -180.0, 180.0, 31);
    const uint64_t yi = norm_dim(y[i], -90.0, 90.0, 31);
    out[i] = (split2(xi) << 1) | split2(yi);
  }
}

void gm_z3_encode(const double* x, const double* y, const int64_t* off_ms,
                  double off_max, int64_t n, uint64_t* out) {
  GM_PAR_FOR(n)
  for (int64_t i = 0; i < n; ++i) {
    const uint64_t xi = norm_dim(x[i], -180.0, 180.0, 21);
    const uint64_t yi = norm_dim(y[i], -90.0, 90.0, 21);
    const uint64_t ti = norm_dim((double)off_ms[i], 0.0, off_max, 21);
    out[i] = (split3(xi) << 2) | (split3(yi) << 1) | split3(ti);
  }
}

// ---------------------------------------------------------------------------
// Feature-id hash (bit-exact mirror of packsort.fid_hash64: NUL-padded
// 8-byte little-endian chunks, XOR of chunk*prime, murmur-style avalanche)
// ---------------------------------------------------------------------------

void gm_fid_hash64(const uint8_t* data, int64_t n, int64_t itemsize,
                   uint64_t* out) {
  const int64_t k = (itemsize + 7) / 8;
  GM_PAR_FOR(n)
  for (int64_t i = 0; i < n; ++i) {
    const uint8_t* row = data + i * itemsize;
    uint64_t h = 0;
    for (int64_t j = 0; j < k; ++j) {
      uint64_t chunk = 0;
      const int64_t off = j * 8;
      const int64_t len = std::min<int64_t>(8, itemsize - off);
      std::memcpy(&chunk, row + off, (size_t)len);  // little-endian hosts
      h ^= chunk * kHashPrimes[j & 7];
    }
    h ^= h >> 33;
    h *= 0xFF51AFD7ED558CCDull;
    h ^= h >> 29;
    out[i] = h;
  }
}

// ---------------------------------------------------------------------------
// Time split: epoch_ms -> (bin, offset_ms, offset_ms/scale) in one pass for
// the fixed-width periods (binned_time.to_bin_and_offset / to_scaled).
// Constant divisors per branch keep the integer division fast.
// ---------------------------------------------------------------------------

void gm_time_split(const int64_t* t, int64_t n, int64_t period_ms,
                   int32_t scale, int32_t* bin, int64_t* off_ms,
                   int32_t* off_scaled) {
  const int64_t kDay = 86400000ll;
  if (period_ms == kDay)
    time_split_fixed<86400000ll>(t, n, scale, bin, off_ms, off_scaled);
  else if (period_ms == 7 * kDay)
    time_split_fixed<604800000ll>(t, n, scale, bin, off_ms, off_scaled);
  else
    GM_PAR_FOR(n)
    for (int64_t i = 0; i < n; ++i) {
      int64_t b = t[i] / period_ms;
      if (t[i] % period_ms < 0) --b;
      const int64_t off = t[i] - b * period_ms;
      bin[i] = (int32_t)b;
      if (off_ms) off_ms[i] = off;
      if (off_scaled) off_scaled[i] = (int32_t)(off / scale);
    }
}

// ---------------------------------------------------------------------------
// Fused pack/unpack for the radix pack-sort (packsort.pack_sort): one pass
// to assemble [prefix | key_q | tiebreak | idx] u64 rows, and one pass to
// split the sorted array back into (perm, key_q, prefix). The sort itself
// stays numpy's vectorized introsort.
// ---------------------------------------------------------------------------

void gm_pack_idx(const uint64_t* key, int64_t n, int32_t key_shift,
                 int32_t idx_bits, int32_t tb_bits, const uint64_t* tiebreak,
                 const int32_t* prefix, int32_t prefix_bits, int64_t pmin,
                 uint64_t* out) {
  GM_PAR_FOR(n)
  for (int64_t i = 0; i < n; ++i) {
    uint64_t v = (key[i] >> key_shift) << (idx_bits + tb_bits);
    if (tiebreak) v |= (tiebreak[i] >> (64 - tb_bits)) << idx_bits;
    if (prefix) v |= (uint64_t)((int64_t)prefix[i] - pmin) << (64 - prefix_bits);
    out[i] = v | (uint64_t)i;
  }
}

void gm_unpack_idx(const uint64_t* packed, int64_t n, int32_t kq_bits,
                   int32_t idx_bits, int32_t tb_bits, int32_t prefix_bits,
                   int64_t pmin, int32_t* perm32, int64_t* perm64,
                   uint64_t* key_out, int32_t* prefix_out) {
  const uint64_t idx_mask = ((uint64_t)1 << idx_bits) - 1;
  const uint64_t key_mask =
      kq_bits >= 64 ? ~0ull : (((uint64_t)1 << kq_bits) - 1);
  GM_PAR_FOR(n)
  for (int64_t i = 0; i < n; ++i) {
    const uint64_t v = packed[i];
    if (perm32)
      perm32[i] = (int32_t)(v & idx_mask);
    else
      perm64[i] = (int64_t)(v & idx_mask);
    key_out[i] = (v >> (idx_bits + tb_bits)) & key_mask;
    if (prefix_out)
      prefix_out[i] = (int32_t)((int64_t)(v >> (64 - prefix_bits)) + pmin);
  }
}

// offset_ms = t - bin*period in one fused pass (ingest reuses the bin
// column encode_batch computed; a numpy multiply+subtract is two temps).
void gm_off_from_bin(const int64_t* t, const int32_t* bin, int64_t period_ms,
                     int64_t n, int64_t* out) {
  GM_PAR_FOR(n)
  for (int64_t i = 0; i < n; ++i)
    out[i] = t[i] - (int64_t)bin[i] * period_ms;
}

// Sort a u64 array in place — parallel when OpenMP is enabled and worth it.
// (Single-threaded callers should prefer numpy's AVX-vectorized introsort,
// which beats scalar std::sort; see packsort.pack_sort's dispatch.)
void gm_sort_u64(uint64_t* a, int64_t n) {
#if defined(_OPENMP)
  if (n > 2000000 && omp_get_max_threads() > 1) {
    __gnu_parallel::sort(a, a + n);
    return;
  }
#endif
  std::sort(a, a + n);
}

int32_t gm_num_threads() {
#if defined(_OPENMP)
  return omp_get_max_threads();
#else
  return 1;
#endif
}

// Fused UCS4 -> bytes narrowing with ASCII validation, one pass (numpy
// needs separate compare + cast passes over the 4x-wide source; at 20M
// 21-char fids that is ~2.5 s of the ingest hot path). Returns 1 when all
// code points were ASCII (dst valid), 0 otherwise (dst undefined).
int32_t gm_u32_to_s(const uint32_t* src, uint8_t* dst, int64_t n) {
  // blockwise so a non-ASCII input bails after ~64Ki elements instead of
  // finishing a full wasted pass (the caller redoes the work in unicode)
  const int64_t blk = 1 << 16;
  for (int64_t lo = 0; lo < n; lo += blk) {
    int64_t hi = lo + blk < n ? lo + blk : n;
    uint32_t acc = 0;
    for (int64_t i = lo; i < hi; ++i) {
      uint32_t v = src[i];
      acc |= v;
      dst[i] = (uint8_t)v;
    }
    if (acc >= 128u) return 0;
  }
  return 1;
}

// Mirror widening for exports (bytes -> UCS4), ASCII-validated.
int32_t gm_s_to_u32(const uint8_t* src, uint32_t* dst, int64_t n) {
  const int64_t blk = 1 << 16;
  for (int64_t lo = 0; lo < n; lo += blk) {
    int64_t hi = lo + blk < n ? lo + blk : n;
    uint8_t acc = 0;
    for (int64_t i = lo; i < hi; ++i) {
      uint8_t v = src[i];
      acc |= v;
      dst[i] = v;
    }
    if (acc >= 128u) return 0;
  }
  return 1;
}

int32_t gm_abi_version() { return 4; }

}  // extern "C"
