package org.geotools.api.feature.type;

/** Mock of GeoTools' {@code org.geotools.api.feature.type.Name} — the
 * subset the geomesa-tpu DataStore uses. Replace this source tree with
 * the real gt-api jar to compile against GeoTools proper. */
public interface Name {
    String getLocalPart();
    String getNamespaceURI();
    String getURI();
}
