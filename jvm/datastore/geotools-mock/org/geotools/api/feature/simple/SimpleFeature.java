package org.geotools.api.feature.simple;

/** Mock subset of {@code org.geotools.api.feature.simple.SimpleFeature}. */
public interface SimpleFeature {
    String getID();
    SimpleFeatureType getFeatureType();
    Object getAttribute(String name);
    Object getAttribute(int index);
    void setAttribute(String name, Object value);
    Object getDefaultGeometry();
}
