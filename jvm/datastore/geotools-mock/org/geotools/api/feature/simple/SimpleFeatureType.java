package org.geotools.api.feature.simple;

import java.util.List;
import org.geotools.api.feature.type.Name;

/** Mock subset of {@code org.geotools.api.feature.simple.SimpleFeatureType}. */
public interface SimpleFeatureType {
    String getTypeName();
    Name getName();
    int getAttributeCount();
    List<String> getAttributeNames();
    Class<?> getType(String name);
    String getGeometryAttribute();
}
