package org.geotools.api.filter;

/** Mock subset of {@code org.geotools.api.filter.Filter}. */
public interface Filter {
    Filter INCLUDE = new Filter() {
        @Override public String toString() { return "INCLUDE"; }
    };
}
