package org.geotools.api.data;

import java.io.Closeable;
import java.io.IOException;
import java.util.NoSuchElementException;

/** Mock subset of {@code org.geotools.api.data.FeatureReader}. */
public interface FeatureReader<T, F> extends Closeable {
    T getFeatureType();
    F next() throws IOException, NoSuchElementException;
    boolean hasNext() throws IOException;
    @Override void close() throws IOException;
}
