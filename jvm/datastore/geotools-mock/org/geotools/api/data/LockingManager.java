package org.geotools.api.data;

/** Mock marker for {@code org.geotools.api.data.LockingManager}. */
public interface LockingManager {
}
