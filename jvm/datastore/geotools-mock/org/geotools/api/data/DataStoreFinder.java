package org.geotools.api.data;

import java.io.IOException;
import java.util.Iterator;
import java.util.Map;
import java.util.ServiceLoader;

/** Mock of {@code org.geotools.api.data.DataStoreFinder}: resolves
 * factories from META-INF/services exactly as the real finder does. */
public final class DataStoreFinder {
    private DataStoreFinder() {}

    public static DataStore getDataStore(Map<String, ?> params)
            throws IOException {
        Iterator<DataStoreFactorySpi> it =
                ServiceLoader.load(DataStoreFactorySpi.class).iterator();
        while (it.hasNext()) {
            DataStoreFactorySpi f = it.next();
            if (f.isAvailable() && f.canProcess(params)) {
                return f.createDataStore(params);
            }
        }
        return null;
    }
}
