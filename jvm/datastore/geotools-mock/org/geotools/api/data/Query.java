package org.geotools.api.data;

import org.geotools.api.filter.Filter;

/** Mock subset of {@code org.geotools.api.data.Query}: type name,
 * filter, max features, property projection. */
public class Query {
    public static final int DEFAULT_MAX = Integer.MAX_VALUE;

    private String typeName;
    private Filter filter = Filter.INCLUDE;
    private int maxFeatures = DEFAULT_MAX;
    private String[] propertyNames;

    public Query() {}
    public Query(String typeName) { this.typeName = typeName; }
    public Query(String typeName, Filter filter) {
        this.typeName = typeName;
        this.filter = filter;
    }

    public String getTypeName() { return typeName; }
    public void setTypeName(String typeName) { this.typeName = typeName; }
    public Filter getFilter() { return filter; }
    public void setFilter(Filter filter) { this.filter = filter; }
    public int getMaxFeatures() { return maxFeatures; }
    public void setMaxFeatures(int maxFeatures) { this.maxFeatures = maxFeatures; }
    public String[] getPropertyNames() { return propertyNames; }
    public void setPropertyNames(String[] propertyNames) { this.propertyNames = propertyNames; }
}
