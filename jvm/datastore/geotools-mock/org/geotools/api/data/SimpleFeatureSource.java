package org.geotools.api.data;

import java.io.IOException;
import org.geotools.api.feature.simple.SimpleFeature;
import org.geotools.api.feature.simple.SimpleFeatureType;

/** Mock subset of {@code org.geotools.api.data.SimpleFeatureSource}. */
public interface SimpleFeatureSource
        extends FeatureSource<SimpleFeatureType, SimpleFeature> {
    FeatureReader<SimpleFeatureType, SimpleFeature> getFeatures(Query query)
            throws IOException;
}
